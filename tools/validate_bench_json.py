"""Validate bench artifacts: summary JSON, JSONL logs, driver wrappers.

Usage:
    python tools/validate_bench_json.py FILE [FILE ...]

Checks, per file (type auto-detected from content):

* bench_summary.json (bench.py's write-ahead atomic summary): the file
  json.load-s, kind == "bench_summary", status is one of
  running/complete/killed, models is a list of names, and every entry
  in results carries the metric/value/unit/vs_baseline contract the
  driver greps for.
* *.jsonl (monitor export / bench log / flight recorder): EVERY
  non-empty line parses as a JSON object; lines with kind ==
  "serving_loadgen" (tools/serving_loadgen.py) additionally carry the
  mode/requests/duration_s/throughput_rps/latency_ms{p50,p95,p99}
  contract the serving report section reads; lines with kind ==
  "generation_loadgen" (tools/serving_loadgen.py --generate) carry
  that plus tokens/tokens_per_s and ttft_ms/inter_token_ms percentile
  objects (the generation report section's contract); lines with
  kind == "chaos_loadgen" (tools/serving_loadgen.py --chaos) carry the
  loadgen contract plus fault_spec and the chaos verdict
  (wrong_answers/worker_deaths, both required to be ZERO, and the
  baseline/chaos p99 pair with its inflation bound); lines with
  kind == "spec_loadgen" (tools/serving_loadgen.py --generate
  --spec-decode) carry the speculative-decoding A/B contract: spec and
  baseline side objects with tokens/tokens_per_s/gen_steps, the spec
  side's draft accounting (acceptance_rate in [0,1]), the on/off
  speedup, and wrong_answers required to be ZERO; lines with
  kind == "router_loadgen" (tools/serving_loadgen.py --router N) carry
  the loadgen contract plus replicas/redispatches/shed, the 1->N
  scaling block, and zero-gated preempt / hot_swap / chaos drill
  verdicts; lines with
  kind == "disagg_loadgen" (tools/serving_loadgen.py --router N
  --disagg) carry the disaggregated prefill/decode fleet contract:
  replicas.prefill/decode split, zero-gated wrong_answers and
  post_warmup_compiles, disagg vs baseline TTFT distributions, and
  the KV-transfer accounting; lines with
  kind == "program_lint" (tools/program_lint.py) carry the
  model/ok/counts/findings contract the lint report section reads;
  lines with kind == "graph_opt" (tools/program_lint.py --optimize)
  carry the model/opt_level/ops_before/ops_after/vars_eliminated/
  passes contract the graph-optimization report section reads; lines
  with kind == "sharding_report" (tools/program_lint.py --sharding,
  also emitted by the FLAGS_sharding_verify gate's to_record) carry
  the mesh shape/axes, the predicted collective/reshard/grad-sync
  bytes per step, the priced-collective rows and the PTV06x findings
  the sharding analysis report section reads; lines
  with kind == "trace_report" (tools/trace_report.py --out) carry the
  span/trace/request counts, the per-component breakdown_ms, the
  slowest-N rows and the consistency-audit verdict the tracing report
  section reads; lines with kind == "perf_gate" (tools/perf_gate.py)
  carry the ledger path, the per-(config, metric) verdict rows
  (status regression/improvement/ok/too_few_samples/new_config with
  the median +- k*MAD band that produced them) and regression /
  improvement counts that must agree with the rows; lines with
  kind == "goodput_report" (tools/goodput_report.py --out) carry the
  exclusive category ledger (every goodput category present,
  non-negative, summing to wall_s within 5%), the goodput fraction in
  [0,1], the step/compile/starvation counters and the worst-N step
  waterfall rows.
* incident_*.json (paddle_tpu/monitor_alerts.py bundles, also accepted
  as a JSONL line): kind == "incident_bundle" with the fired rule, the
  full stats snapshot, breaching-bucket exemplar trace ids, the kept
  span list and the flight-recorder ring — the correlation contract a
  post-mortem reads.
* driver BENCH_rNN.json wrappers ({"n", "cmd", "rc", "tail",
  "parsed"}): parsed must be non-null — the exact invariant the r05
  rc=124 artifact violated.

Exits 0 when every file passes, 1 otherwise, listing each failure on
stderr. Importable: validate_file(path) -> list of error strings.
"""
from __future__ import annotations

import json
import sys

_RESULT_KEYS = ("metric", "value", "unit", "vs_baseline")
_STATUSES = ("running", "complete", "killed")


def validate_summary(obj, where="summary"):
    errs = []
    if obj.get("kind") != "bench_summary":
        errs.append(f"{where}: kind != 'bench_summary' "
                    f"(got {obj.get('kind')!r})")
    if obj.get("status") not in _STATUSES:
        errs.append(f"{where}: status {obj.get('status')!r} not in "
                    f"{_STATUSES}")
    models = obj.get("models")
    if not isinstance(models, list) or not all(
            isinstance(m, str) for m in models):
        errs.append(f"{where}: models must be a list of names")
    results = obj.get("results")
    if not isinstance(results, list):
        errs.append(f"{where}: results must be a list")
        results = []
    for i, r in enumerate(results):
        if not isinstance(r, dict):
            errs.append(f"{where}: results[{i}] is not an object")
            continue
        missing = [k for k in _RESULT_KEYS if k not in r]
        if missing:
            errs.append(f"{where}: results[{i}] missing {missing}")
    if obj.get("status") != "running" and "ts_end" not in obj:
        errs.append(f"{where}: finished summary lacks ts_end")
    return errs


def validate_wrapper(obj, where="wrapper"):
    errs = []
    missing = [k for k in ("cmd", "rc", "parsed") if k not in obj]
    if missing:
        errs.append(f"{where}: driver wrapper missing {missing}")
    if obj.get("parsed") is None:
        errs.append(f"{where}: parsed is null (rc={obj.get('rc')}) — "
                    f"run left no parseable result")
    return errs


_LOADGEN_PCTS = ("p50", "p95", "p99")


def validate_loadgen(obj, where="loadgen"):
    """Schema of one tools/serving_loadgen.py record."""
    errs = []
    if not isinstance(obj.get("mode"), str):
        errs.append(f"{where}: mode must be a string "
                    f"(got {obj.get('mode')!r})")
    for key in ("requests", "errors", "duration_s", "throughput_rps"):
        if not isinstance(obj.get(key), (int, float)) \
                or isinstance(obj.get(key), bool):
            errs.append(f"{where}: {key} must be numeric "
                        f"(got {obj.get(key)!r})")
    lat = obj.get("latency_ms")
    if not isinstance(lat, dict):
        errs.append(f"{where}: latency_ms must be an object")
    else:
        for q in _LOADGEN_PCTS:
            v = lat.get(q)
            # None is legal only for a run that completed zero requests
            if v is None and obj.get("requests"):
                errs.append(f"{where}: latency_ms.{q} missing with "
                            f"requests > 0")
            elif v is not None and (not isinstance(v, (int, float))
                                    or isinstance(v, bool)):
                errs.append(f"{where}: latency_ms.{q} must be numeric "
                            f"(got {v!r})")
    if not isinstance(obj.get("config"), dict):
        errs.append(f"{where}: config must be an object")
    return errs


def validate_generation_loadgen(obj, where="generation_loadgen"):
    """Schema of one tools/serving_loadgen.py --generate record."""
    errs = []
    if not isinstance(obj.get("mode"), str):
        errs.append(f"{where}: mode must be a string "
                    f"(got {obj.get('mode')!r})")
    for key in ("requests", "errors", "duration_s", "throughput_rps",
                "tokens", "tokens_per_s"):
        if not isinstance(obj.get(key), (int, float)) \
                or isinstance(obj.get(key), bool):
            errs.append(f"{where}: {key} must be numeric "
                        f"(got {obj.get(key)!r})")
    # latency_ms needs its percentiles whenever requests completed;
    # ttft_ms whenever tokens were generated; inter_token_ms may be
    # all-null even on a successful run (requests of one token have no
    # inter-token gap), so only its TYPE is enforced
    for field, need in (("latency_ms", bool(obj.get("requests"))),
                        ("ttft_ms", bool(obj.get("tokens"))),
                        ("inter_token_ms", False)):
        hist = obj.get(field)
        if not isinstance(hist, dict):
            errs.append(f"{where}: {field} must be an object")
            continue
        for q in _LOADGEN_PCTS:
            v = hist.get(q)
            if v is None and need:
                errs.append(f"{where}: {field}.{q} missing on a run "
                            f"with completed work")
            elif v is not None and (not isinstance(v, (int, float))
                                    or isinstance(v, bool)):
                errs.append(f"{where}: {field}.{q} must be numeric "
                            f"(got {v!r})")
    if not isinstance(obj.get("config"), dict):
        errs.append(f"{where}: config must be an object")
    # optional prefix-cache probe block (--shared-prefix-frac runs)
    pre = obj.get("prefix")
    if pre is not None:
        if not isinstance(pre, dict):
            errs.append(f"{where}: prefix must be an object")
        else:
            for key in ("hit_requests", "miss_requests"):
                v = pre.get(key)
                if not isinstance(v, int) or isinstance(v, bool):
                    errs.append(f"{where}: prefix.{key} must be an int "
                                f"(got {v!r})")
            hr = pre.get("hit_rate")
            if hr is not None and (not isinstance(hr, (int, float))
                                   or isinstance(hr, bool)):
                errs.append(f"{where}: prefix.hit_rate must be numeric "
                            f"or null (got {hr!r})")
            for field in ("ttft_hit_ms", "ttft_miss_ms"):
                hist = pre.get(field)
                if not isinstance(hist, dict):
                    errs.append(f"{where}: prefix.{field} must be an "
                                f"object")
                    continue
                for q in _LOADGEN_PCTS:
                    v = hist.get(q)
                    if v is not None and (not isinstance(v, (int, float))
                                          or isinstance(v, bool)):
                        errs.append(f"{where}: prefix.{field}.{q} must "
                                    f"be numeric (got {v!r})")
    return errs


def validate_spec_loadgen(obj, where="spec_loadgen"):
    """Schema of one tools/serving_loadgen.py --generate --spec-decode
    record: the speculative-decoding A/B. Both sides ("spec" and
    "baseline") carry tokens / tokens_per_s / gen_steps; the spec side
    adds the drafter accounting (draft_proposed / draft_accepted /
    acceptance_rate in [0,1]); wrong_answers must be ZERO — the record
    documents bit-exact parity with the serial reference, not a
    best-effort tally."""
    errs = []
    if not isinstance(obj.get("mode"), str):
        errs.append(f"{where}: mode must be a string "
                    f"(got {obj.get('mode')!r})")
    for key in ("requests", "compared_requests"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be an int (got {v!r})")
    wrong = obj.get("wrong_answers")
    if not isinstance(wrong, int) or isinstance(wrong, bool):
        errs.append(f"{where}: wrong_answers must be an int "
                    f"(got {wrong!r})")
    elif wrong != 0:
        errs.append(f"{where}: wrong_answers={wrong} violates the "
                    f"bit-exact speculative-decoding contract")
    sp = obj.get("speedup")
    if sp is not None and (not isinstance(sp, (int, float))
                           or isinstance(sp, bool)):
        errs.append(f"{where}: speedup must be numeric or null "
                    f"(got {sp!r})")
    for side in ("spec", "baseline"):
        s = obj.get(side)
        if not isinstance(s, dict):
            errs.append(f"{where}: {side} must be an object")
            continue
        for key in ("duration_s", "errors", "tokens", "tokens_per_s",
                    "gen_steps", "post_warmup_compiles"):
            v = s.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errs.append(f"{where}: {side}.{key} must be numeric "
                            f"(got {v!r})")
    s = obj.get("spec")
    if isinstance(s, dict):
        for key in ("spec_steps", "draft_proposed", "draft_accepted"):
            v = s.get(key)
            if not isinstance(v, int) or isinstance(v, bool):
                errs.append(f"{where}: spec.{key} must be an int "
                            f"(got {v!r})")
        ar = s.get("acceptance_rate")
        if ar is not None and (not isinstance(ar, (int, float))
                               or isinstance(ar, bool)
                               or not 0.0 <= ar <= 1.0):
            errs.append(f"{where}: spec.acceptance_rate must be in "
                        f"[0, 1] or null (got {ar!r})")
    if not isinstance(obj.get("config"), dict):
        errs.append(f"{where}: config must be an object")
    return errs


def validate_chaos_loadgen(obj, where="chaos_loadgen"):
    """Schema of one tools/serving_loadgen.py --chaos record: the base
    loadgen contract plus the chaos verdict fields. wrong_answers and
    worker_deaths must be zero — the record documents the
    graceful-degradation guarantee, not a best-effort tally."""
    errs = validate_loadgen(obj, where=where)
    if not isinstance(obj.get("fault_spec"), str):
        errs.append(f"{where}: fault_spec must be a string "
                    f"(got {obj.get('fault_spec')!r})")
    for key in ("wrong_answers", "worker_deaths"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be an int (got {v!r})")
        elif v != 0:
            errs.append(f"{where}: {key}={v} violates the zero-"
                        f"incorrect-responses chaos contract")
    for key in ("baseline_p99_ms", "chaos_p99_ms", "p99_inflation",
                "p99_bound"):
        v = obj.get(key)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool)):
            errs.append(f"{where}: {key} must be numeric (got {v!r})")
    if isinstance(obj.get("p99_inflation"), (int, float)) \
            and isinstance(obj.get("p99_bound"), (int, float)) \
            and obj["p99_inflation"] > obj["p99_bound"]:
        errs.append(f"{where}: p99_inflation={obj['p99_inflation']} "
                    f"exceeds p99_bound={obj['p99_bound']}")
    return errs


def validate_router_loadgen(obj, where="router_loadgen"):
    """Schema of one tools/serving_loadgen.py --router record: the base
    loadgen contract plus replica count, failover accounting, the 1->N
    scaling block, and the optional preempt / hot-swap / chaos drill
    verdicts. Wherever a drill block is present its zero-regression
    fields (wrong answers, dropped requests, standby compiles) must
    actually be zero — the record documents the fleet guarantee."""
    errs = validate_loadgen(obj, where=where)
    reps = obj.get("replicas")
    if not isinstance(reps, int) or isinstance(reps, bool) or reps < 1:
        errs.append(f"{where}: replicas must be a positive int "
                    f"(got {reps!r})")
    for key in ("redispatches", "shed", "wrong_answers"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{where}: {key} must be a non-negative int "
                        f"(got {v!r})")
    if obj.get("wrong_answers"):
        errs.append(f"{where}: wrong_answers="
                    f"{obj['wrong_answers']} violates the exactly-"
                    f"once, zero-incorrect-responses router contract")
    scaling = obj.get("scaling")
    if not isinstance(scaling, dict):
        errs.append(f"{where}: scaling must be an object")
    else:
        for key in ("rps_1", "rps_n"):
            v = scaling.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errs.append(f"{where}: scaling.{key} must be numeric "
                            f"(got {v!r})")
        ratio = scaling.get("ratio")
        if ratio is not None and (not isinstance(ratio, (int, float))
                                  or isinstance(ratio, bool)):
            errs.append(f"{where}: scaling.ratio must be numeric or "
                        f"null (got {ratio!r})")
        mr = scaling.get("min_ratio")
        if isinstance(ratio, (int, float)) \
                and isinstance(mr, (int, float)) and mr > 0 \
                and ratio < mr:
            errs.append(f"{where}: scaling.ratio={ratio} below "
                        f"min_ratio={mr}")
    pre = obj.get("preempt")
    if pre is not None:
        if not isinstance(pre, dict):
            errs.append(f"{where}: preempt must be an object")
        else:
            for key in ("client_errors", "wrong_answers"):
                v = pre.get(key)
                if not isinstance(v, int) or isinstance(v, bool):
                    errs.append(f"{where}: preempt.{key} must be an "
                                f"int (got {v!r})")
                elif v != 0:
                    errs.append(f"{where}: preempt.{key}={v} — a "
                                f"deregistered replica must not cost "
                                f"clients anything while others are "
                                f"healthy")
    hot = obj.get("hot_swap")
    if hot is not None:
        if not isinstance(hot, dict):
            errs.append(f"{where}: hot_swap must be an object")
        else:
            if hot.get("swapped") is not True:
                errs.append(f"{where}: hot_swap.swapped must be true")
            if hot.get("drained") is not True:
                errs.append(f"{where}: hot_swap.drained must be true "
                            f"(old replica stopped undrained)")
            for key in ("dropped_requests",
                        "standby_post_warmup_compiles"):
                v = hot.get(key)
                if not isinstance(v, int) or isinstance(v, bool):
                    errs.append(f"{where}: hot_swap.{key} must be an "
                                f"int (got {v!r})")
                elif v != 0:
                    errs.append(f"{where}: hot_swap.{key}={v} violates "
                                f"the zero-downtime swap contract")
    chaos = obj.get("chaos")
    if chaos is not None:
        if not isinstance(chaos, dict):
            errs.append(f"{where}: chaos must be an object")
        else:
            for key in ("wrong_answers", "worker_deaths"):
                v = chaos.get(key)
                if not isinstance(v, int) or isinstance(v, bool):
                    errs.append(f"{where}: chaos.{key} must be an int "
                                f"(got {v!r})")
                elif v != 0:
                    errs.append(f"{where}: chaos.{key}={v} violates "
                                f"the replica-kill failover contract")
            for key in ("redispatches", "baseline_p99_ms",
                        "chaos_p99_ms", "p99_inflation", "p99_bound"):
                v = chaos.get(key)
                if v is not None and (not isinstance(v, (int, float))
                                      or isinstance(v, bool)):
                    errs.append(f"{where}: chaos.{key} must be "
                                f"numeric (got {v!r})")
            if isinstance(chaos.get("p99_inflation"), (int, float)) \
                    and isinstance(chaos.get("p99_bound"),
                                   (int, float)) \
                    and chaos["p99_inflation"] > chaos["p99_bound"]:
                errs.append(f"{where}: chaos.p99_inflation="
                            f"{chaos['p99_inflation']} exceeds "
                            f"p99_bound={chaos['p99_bound']}")
    return errs


def validate_disagg_loadgen(obj, where="disagg_loadgen"):
    """Schema of one tools/serving_loadgen.py --router --disagg record:
    the base loadgen contract plus the prefill/decode fleet split, the
    zero-gated correctness fields (wrong_answers and post-warmup
    compiles must BOTH be zero — the record documents the
    disaggregation guarantee), the disagg TTFT distributions with their
    symmetric-baseline counterparts, and the KV-transfer accounting."""
    errs = validate_loadgen(obj, where=where)
    reps = obj.get("replicas")
    if not isinstance(reps, dict):
        errs.append(f"{where}: replicas must be an object "
                    f"(got {reps!r})")
    else:
        for key, floor in (("prefill", 1), ("decode", 1)):
            v = reps.get(key)
            if not isinstance(v, int) or isinstance(v, bool) \
                    or v < floor:
                errs.append(f"{where}: replicas.{key} must be an int "
                            f">= {floor} (got {v!r})")
    for key in ("wrong_answers", "post_warmup_compiles"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{where}: {key} must be a non-negative int "
                        f"(got {v!r})")
        elif v != 0:
            errs.append(f"{where}: {key}={v} violates the "
                        f"zero-wrong-answers / zero-recompile "
                        f"disaggregation contract")
    for side, label in ((obj, where),
                        (obj.get("baseline"), f"{where}.baseline")):
        if not isinstance(side, dict):
            errs.append(f"{where}: baseline must be an object "
                        f"(got {side!r})")
            continue
        for key in ("ttft_ms", "ttft_shared_ms"):
            d = side.get(key)
            if not isinstance(d, dict):
                errs.append(f"{label}: {key} must be an object "
                            f"(got {d!r})")
                continue
            for q in _LOADGEN_PCTS:
                v = d.get(q)
                if v is not None and (not isinstance(v, (int, float))
                                      or isinstance(v, bool)):
                    errs.append(f"{label}: {key}.{q} must be numeric "
                                f"or null (got {v!r})")
    ratio = obj.get("ttft_shared_p99_ratio")
    if ratio is not None and (not isinstance(ratio, (int, float))
                              or isinstance(ratio, bool)):
        errs.append(f"{where}: ttft_shared_p99_ratio must be numeric "
                    f"or null (got {ratio!r})")
    xfer = obj.get("transfer")
    if xfer is not None:
        if not isinstance(xfer, dict):
            errs.append(f"{where}: transfer must be an object")
        else:
            for key in ("requests", "blocks", "bytes", "fallbacks",
                        "prefix_reuse"):
                v = xfer.get(key)
                if v is not None and (not isinstance(v, int)
                                      or isinstance(v, bool) or v < 0):
                    errs.append(f"{where}: transfer.{key} must be a "
                                f"non-negative int (got {v!r})")
    return errs


_LINT_SEVERITIES = ("error", "warn")


def validate_program_lint(obj, where="program_lint"):
    """Schema of one tools/program_lint.py record."""
    errs = []
    if not isinstance(obj.get("model"), str):
        errs.append(f"{where}: model must be a string "
                    f"(got {obj.get('model')!r})")
    if not isinstance(obj.get("ok"), bool):
        errs.append(f"{where}: ok must be a bool")
    counts = obj.get("counts")
    if not isinstance(counts, dict):
        errs.append(f"{where}: counts must be an object")
        counts = {}
    for key in ("error", "warn"):
        v = counts.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: counts.{key} must be an int "
                        f"(got {v!r})")
    findings = obj.get("findings")
    if not isinstance(findings, list):
        errs.append(f"{where}: findings must be a list")
        findings = []
    for i, f in enumerate(findings):
        if not isinstance(f, dict):
            errs.append(f"{where}: findings[{i}] is not an object")
            continue
        missing = [k for k in ("rule", "severity", "where", "message")
                   if not isinstance(f.get(k), str)]
        if missing:
            errs.append(f"{where}: findings[{i}] missing/non-string "
                        f"{missing}")
        sev = f.get("severity")
        if isinstance(sev, str) and sev not in _LINT_SEVERITIES:
            errs.append(f"{where}: findings[{i}].severity {sev!r} not "
                        f"in {_LINT_SEVERITIES}")
    # ok must agree with the error count the driver gates on
    if isinstance(obj.get("ok"), bool) and isinstance(
            counts.get("error"), int):
        if obj["ok"] != (counts["error"] == 0):
            errs.append(f"{where}: ok={obj['ok']} contradicts "
                        f"counts.error={counts['error']}")
    return errs


def validate_graph_opt(obj, where="graph_opt"):
    """Schema of one tools/program_lint.py --optimize record (the
    analysis/passes PassManager report)."""
    errs = []
    if not isinstance(obj.get("model"), str):
        errs.append(f"{where}: model must be a string "
                    f"(got {obj.get('model')!r})")
    for key in ("opt_level", "ops_before", "ops_after",
                "vars_eliminated"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be an int (got {v!r})")
    passes = obj.get("passes")
    if not isinstance(passes, list):
        errs.append(f"{where}: passes must be a list")
        passes = []
    for i, p in enumerate(passes):
        if not isinstance(p, dict):
            errs.append(f"{where}: passes[{i}] is not an object")
            continue
        if not isinstance(p.get("name"), str):
            errs.append(f"{where}: passes[{i}].name must be a string")
        for key in ("ops_before", "ops_after"):
            v = p.get(key)
            if not isinstance(v, int) or isinstance(v, bool):
                errs.append(f"{where}: passes[{i}].{key} must be an "
                            f"int (got {v!r})")
        if not isinstance(p.get("seconds"), (int, float)) \
                or isinstance(p.get("seconds"), bool):
            errs.append(f"{where}: passes[{i}].seconds must be "
                        f"numeric")
    # passes only shrink the op list — a growing program means a bug
    if isinstance(obj.get("ops_before"), int) \
            and isinstance(obj.get("ops_after"), int) \
            and obj["ops_after"] > obj["ops_before"]:
        errs.append(f"{where}: ops_after={obj['ops_after']} exceeds "
                    f"ops_before={obj['ops_before']}")
    return errs


def validate_memory_plan(obj, where="memory_plan"):
    """Schema of one tools/program_lint.py --memory record
    (analysis/memory.MemoryPlan.to_record)."""
    errs = []
    if not isinstance(obj.get("model"), str):
        errs.append(f"{where}: model must be a string "
                    f"(got {obj.get('model')!r})")
    if not isinstance(obj.get("fingerprint"), str):
        errs.append(f"{where}: fingerprint must be a string")
    for key in ("ops", "vars", "est_peak_bytes", "pinned_bytes",
                "peak_op_idx", "unsized_vars", "budget_bytes",
                "reuse_bytes_available"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be an int (got {v!r})")
    for key in ("est_peak_bytes", "pinned_bytes", "budget_bytes",
                "reuse_bytes_available"):
        v = obj.get(key)
        if isinstance(v, int) and not isinstance(v, bool) and v < 0:
            errs.append(f"{where}: {key} must be >= 0 (got {v})")
    if not isinstance(obj.get("peak_op"), str):
        errs.append(f"{where}: peak_op must be a string")
    if not isinstance(obj.get("dynamic"), bool):
        errs.append(f"{where}: dynamic must be a bool")
    # the peak counts the pinned set, so it can never undercut it
    if isinstance(obj.get("est_peak_bytes"), int) \
            and isinstance(obj.get("pinned_bytes"), int) \
            and obj["est_peak_bytes"] < obj["pinned_bytes"]:
        errs.append(f"{where}: est_peak_bytes={obj['est_peak_bytes']} "
                    f"below pinned_bytes={obj['pinned_bytes']}")
    residents = obj.get("top_residents")
    if not isinstance(residents, list):
        errs.append(f"{where}: top_residents must be a list")
        residents = []
    for i, iv in enumerate(residents):
        if not isinstance(iv, dict):
            errs.append(f"{where}: top_residents[{i}] is not an object")
            continue
        if not isinstance(iv.get("name"), str):
            errs.append(f"{where}: top_residents[{i}].name must be a "
                        f"string")
        for key in ("nbytes", "def", "last_use"):
            v = iv.get(key)
            if not isinstance(v, int) or isinstance(v, bool):
                errs.append(f"{where}: top_residents[{i}].{key} must "
                            f"be an int (got {v!r})")
        for key in ("pinned", "dynamic"):
            if not isinstance(iv.get(key), bool):
                errs.append(f"{where}: top_residents[{i}].{key} must "
                            f"be a bool")
    findings = obj.get("findings")
    if not isinstance(findings, list):
        errs.append(f"{where}: findings must be a list")
        findings = []
    for i, f in enumerate(findings):
        if not isinstance(f, dict) or not isinstance(
                f.get("rule"), str) or not f.get("rule", "").startswith(
                "PTV"):
            errs.append(f"{where}: findings[{i}] must be an object "
                        f"with a PTVnnn rule")
    return errs


def validate_sharding_report(obj, where="sharding_report"):
    """kind="sharding_report" (tools/program_lint.py --sharding /
    analysis/sharding.ShardingReport.to_record): the static layout-
    propagation verdict — mesh, predicted collective/reshard/grad-sync
    bytes per step, the priced-collective rows, and PTV06x findings."""
    errs = []
    if not isinstance(obj.get("fingerprint"), str):
        errs.append(f"{where}: fingerprint must be a string")
    shape = obj.get("mesh_shape")
    if not isinstance(shape, list) or not shape or not all(
            isinstance(d, int) and not isinstance(d, bool) and d >= 1
            for d in shape):
        errs.append(f"{where}: mesh_shape must be a non-empty list of "
                    f"positive ints (got {shape!r})")
    axes = obj.get("mesh_axes")
    if not isinstance(axes, list) or not all(
            isinstance(a, str) for a in axes):
        errs.append(f"{where}: mesh_axes must be a list of strings")
    elif isinstance(shape, list) and len(axes) != len(shape):
        errs.append(f"{where}: mesh_axes {axes} and mesh_shape "
                    f"{shape} disagree on rank")
    for key in ("mesh_devices", "ops", "collective_bytes_per_step",
                "reshard_bytes_per_step", "grad_sync_bytes"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{where}: {key} must be a non-negative int "
                        f"(got {v!r})")
    if not isinstance(obj.get("dynamic"), bool):
        errs.append(f"{where}: dynamic must be a bool")
    if not isinstance(obj.get("uncovered_op_types"), list):
        errs.append(f"{where}: uncovered_op_types must be a list")
    colls = obj.get("collectives")
    if not isinstance(colls, list):
        errs.append(f"{where}: collectives must be a list")
        colls = []
    total = 0
    for i, c in enumerate(colls):
        if not isinstance(c, dict):
            errs.append(f"{where}: collectives[{i}] is not an object")
            continue
        for key in ("kind", "where"):
            if not isinstance(c.get(key), str):
                errs.append(f"{where}: collectives[{i}].{key} must be "
                            f"a string")
        v = c.get("bytes")
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{where}: collectives[{i}].bytes must be a "
                        f"non-negative int (got {v!r})")
        else:
            total += v
    # the rows are the TOP collectives of the total, never more than it
    cb = obj.get("collective_bytes_per_step")
    if isinstance(cb, int) and not isinstance(cb, bool) and total > cb:
        errs.append(f"{where}: collectives rows sum {total} exceeds "
                    f"collective_bytes_per_step={cb}")
    # grad-sync and reshard components can never exceed the total
    for key in ("reshard_bytes_per_step", "grad_sync_bytes"):
        v = obj.get(key)
        if isinstance(cb, int) and isinstance(v, int) \
                and not isinstance(v, bool) and v > cb:
            errs.append(f"{where}: {key}={v} exceeds "
                        f"collective_bytes_per_step={cb}")
    findings = obj.get("findings")
    if not isinstance(findings, list):
        errs.append(f"{where}: findings must be a list")
        findings = []
    for i, f in enumerate(findings):
        if not isinstance(f, dict) or not isinstance(
                f.get("rule"), str) or not f.get("rule", "").startswith(
                "PTV06"):
            errs.append(f"{where}: findings[{i}] must be an object "
                        f"with a PTV06x rule")
    return errs


def validate_sharded_bench(obj, where):
    """kind="sharded_bench" (bench.py BENCH_MESH runs): the scaling
    facts a dp x tp ledger row must carry — mesh shape, per-chip
    throughput, and the static collective-traffic estimate."""
    errs = []
    if not isinstance(obj.get("metric"), str):
        errs.append(f"{where}: metric must be a string")
    shape = obj.get("mesh_shape")
    if not isinstance(shape, list) or not shape or not all(
            isinstance(d, int) and not isinstance(d, bool) and d >= 1
            for d in shape):
        errs.append(f"{where}: mesh_shape must be a non-empty list of "
                    f"positive ints (got {shape!r})")
    axes = obj.get("mesh_axes")
    if axes is not None:
        if not isinstance(axes, list) or not all(
                isinstance(a, str) for a in axes):
            errs.append(f"{where}: mesh_axes must be a list of strings")
        elif isinstance(shape, list) and len(axes) != len(shape):
            errs.append(f"{where}: mesh_axes {axes} and mesh_shape "
                        f"{shape} disagree on rank")
    nd = obj.get("mesh_devices")
    if not isinstance(nd, int) or isinstance(nd, bool) or nd < 1:
        errs.append(f"{where}: mesh_devices must be a positive int "
                    f"(got {nd!r})")
    elif isinstance(shape, list) and shape and all(
            isinstance(d, int) and not isinstance(d, bool)
            for d in shape):
        prod = 1
        for d in shape:
            prod *= d
        if prod != nd:
            errs.append(f"{where}: mesh_devices={nd} != "
                        f"prod(mesh_shape)={prod}")
    v = obj.get("per_chip_throughput")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
        errs.append(f"{where}: per_chip_throughput must be a "
                    f"non-negative number (got {v!r})")
    cb = obj.get("collective_bytes_per_step")
    if not isinstance(cb, int) or isinstance(cb, bool) or cb < 0:
        errs.append(f"{where}: collective_bytes_per_step must be a "
                    f"non-negative int (got {cb!r})")
    # optional closed-form gradient-sync reference (bench.py): when
    # present it is a component of the per-op total above
    gs = obj.get("grad_sync_bytes_per_step")
    if gs is not None:
        if not isinstance(gs, int) or isinstance(gs, bool) or gs < 0:
            errs.append(f"{where}: grad_sync_bytes_per_step must be a "
                        f"non-negative int (got {gs!r})")
        elif isinstance(cb, int) and not isinstance(cb, bool) \
                and gs > cb:
            errs.append(f"{where}: grad_sync_bytes_per_step={gs} "
                        f"exceeds collective_bytes_per_step={cb}")
    return errs


def validate_trace_report(obj, where="trace_report"):
    """kind="trace_report" (tools/trace_report.py --out): the
    critical-path summary over a span dump — counts, per-component
    breakdown, slowest-N rows, and the consistency audit verdict."""
    errs = []
    for key in ("n_spans", "n_traces", "n_requests"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{where}: {key} must be a non-negative int "
                        f"(got {v!r})")
    if not isinstance(obj.get("keep"), dict):
        errs.append(f"{where}: keep must be an object "
                    f"(reason -> count)")
    bd = obj.get("breakdown_ms")
    if not isinstance(bd, dict):
        errs.append(f"{where}: breakdown_ms must be an object")
        bd = {}
    for comp in ("queue", "prefill", "decode", "fetch", "e2e",
                 "critical_path"):
        ent = bd.get(comp)
        if not isinstance(ent, dict):
            errs.append(f"{where}: breakdown_ms.{comp} must be an "
                        f"object")
            continue
        for key in ("mean_ms", "p95_ms"):
            v = ent.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or isinstance(v, bool)):
                errs.append(f"{where}: breakdown_ms.{comp}.{key} must "
                            f"be numeric or null (got {v!r})")
    slowest = obj.get("slowest")
    if not isinstance(slowest, list):
        errs.append(f"{where}: slowest must be a list")
        slowest = []
    for i, r in enumerate(slowest):
        if not isinstance(r, dict):
            errs.append(f"{where}: slowest[{i}] is not an object")
            continue
        if not isinstance(r.get("trace_id"), str):
            errs.append(f"{where}: slowest[{i}].trace_id must be a "
                        f"string")
        for key in ("e2e_ms", "critical_path_ms"):
            v = r.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errs.append(f"{where}: slowest[{i}].{key} must be "
                            f"numeric (got {v!r})")
    cons = obj.get("consistency")
    if not isinstance(cons, dict):
        errs.append(f"{where}: consistency must be an object")
    else:
        for key in ("checked", "violations"):
            v = cons.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{where}: consistency.{key} must be a "
                            f"non-negative int (got {v!r})")
    return errs


def validate_incident_bundle(obj, where="incident_bundle"):
    """kind="incident_bundle" (paddle_tpu/monitor_alerts.py): one
    atomic correlation artifact per pending->firing transition — the
    rule that fired, the stats snapshot it fired on, the breaching-
    bucket trace exemplars, the kept-span ring and the flight ring."""
    errs = []
    for key in ("ts",):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be numeric (got {v!r})")
    if not isinstance(obj.get("pid"), int) \
            or isinstance(obj.get("pid"), bool):
        errs.append(f"{where}: pid must be an int")
    rule = obj.get("rule")
    if not isinstance(rule, dict):
        errs.append(f"{where}: rule must be an object")
    else:
        for key in ("name", "kind", "expr"):
            if not isinstance(rule.get(key), str):
                errs.append(f"{where}: rule.{key} must be a string "
                            f"(got {rule.get(key)!r})")
        if rule.get("kind") not in ("threshold", "ratio", "burn"):
            errs.append(f"{where}: rule.kind {rule.get('kind')!r} not "
                        f"a known rule kind")
        t = rule.get("threshold")
        if not isinstance(t, (int, float)) or isinstance(t, bool):
            errs.append(f"{where}: rule.threshold must be numeric")
    snap = obj.get("snapshot")
    if not isinstance(snap, dict):
        errs.append(f"{where}: snapshot must be an object")
    else:
        for key in ("counters", "gauges", "histograms"):
            if not isinstance(snap.get(key), dict):
                errs.append(f"{where}: snapshot.{key} must be an "
                            f"object")
    ids = obj.get("exemplar_trace_ids")
    if not isinstance(ids, list) or not all(
            isinstance(i, str) for i in ids):
        errs.append(f"{where}: exemplar_trace_ids must be a list of "
                    f"strings")
        ids = []
    spans = obj.get("spans")
    if not isinstance(spans, list):
        errs.append(f"{where}: spans must be a list")
        spans = []
    span_traces = set()
    for i, s in enumerate(spans):
        if not isinstance(s, dict) or not isinstance(
                s.get("trace_id"), str):
            errs.append(f"{where}: spans[{i}] must be an object with "
                        f"a trace_id")
            continue
        span_traces.add(s["trace_id"])
    # the correlation contract: every exemplar id that has any span in
    # the bundle comes first-class; an exemplar with NO span at all is
    # legal (the trace may have been sampled out or evicted), but when
    # spans exist the bundle must lead with the exemplar-linked ones
    if ids and spans and span_traces:
        lead = spans[0].get("trace_id")
        if ids[0] in span_traces and lead not in ids:
            errs.append(f"{where}: spans do not lead with the "
                        f"breaching exemplar traces")
    if not isinstance(obj.get("flight_records"), list):
        errs.append(f"{where}: flight_records must be a list")
    nd = obj.get("n_spans_dropped")
    if not isinstance(nd, int) or isinstance(nd, bool) or nd < 0:
        errs.append(f"{where}: n_spans_dropped must be a non-negative "
                    f"int (got {nd!r})")
    return errs


_GATE_STATUSES = ("ok", "regression", "improvement", "too_few_samples",
                  "new_config")


_GOODPUT_CATEGORIES = (
    "device_compute", "compile", "input_wait", "feed_stage",
    "fetch_sync", "checkpoint_save", "checkpoint_restore",
    "retry_backoff", "nan_rollback", "preempt_drain", "probe_wait",
    "other")


def validate_goodput_report(obj, where="goodput_report"):
    """kind="goodput_report" (tools/goodput_report.py --out): the
    exclusive category ledger of one run — every category present and
    non-negative, the fraction in [0,1], and the sum≈wall invariant
    the ledger promises (categories within 5% of wall-clock)."""
    errs = []
    if not isinstance(obj.get("config"), str):
        errs.append(f"{where}: config must be a string "
                    f"(got {obj.get('config')!r})")
    for key in ("ts", "wall_s", "goodput_frac", "sum_frac_err"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be numeric (got {v!r})")
    frac = obj.get("goodput_frac")
    if isinstance(frac, (int, float)) and not isinstance(frac, bool) \
            and not 0.0 <= frac <= 1.0:
        errs.append(f"{where}: goodput_frac must be in [0,1] "
                    f"(got {frac})")
    cats = obj.get("categories")
    if not isinstance(cats, dict):
        errs.append(f"{where}: categories must be an object")
        cats = {}
    for c in _GOODPUT_CATEGORIES:
        v = cats.get(c)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"{where}: categories.{c} must be numeric "
                        f"(got {v!r})")
        elif v < 0:
            errs.append(f"{where}: categories.{c} must be >= 0 "
                        f"(got {v})")
    for c in cats:
        if c not in _GOODPUT_CATEGORIES:
            errs.append(f"{where}: unknown category {c!r}")
    # the ledger's core contract: category seconds sum to wall-clock
    wall = obj.get("wall_s")
    if isinstance(wall, (int, float)) and not isinstance(wall, bool) \
            and wall > 0 and not errs:
        total = sum(float(cats[c]) for c in _GOODPUT_CATEGORIES)
        if abs(total - wall) / wall > 0.05:
            errs.append(f"{where}: categories sum {total:.4f}s drifts "
                        f">5% from wall_s={wall:.4f}")
    for key in ("steps", "compile_steps", "post_warmup_compiles",
                "starved_steps"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be an int (got {v!r})")
        elif v < 0:
            errs.append(f"{where}: {key} must be >= 0 (got {v})")
    steps = obj.get("worst_steps")
    if not isinstance(steps, list):
        errs.append(f"{where}: worst_steps must be a list")
        steps = []
    for i, s in enumerate(steps):
        if not isinstance(s, dict):
            errs.append(f"{where}: worst_steps[{i}] is not an object")
            continue
        if not isinstance(s.get("step"), int) \
                or isinstance(s.get("step"), bool):
            errs.append(f"{where}: worst_steps[{i}].step must be an "
                        f"int")
        for key in ("input_wait_s", "feed_s", "compile_s", "compute_s",
                    "fetch_s", "other_s", "total_s"):
            v = s.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errs.append(f"{where}: worst_steps[{i}].{key} must be "
                            f"numeric (got {v!r})")
    return errs


def validate_perf_gate(obj, where="perf_gate"):
    """kind="perf_gate" (tools/perf_gate.py): the noise-aware verdict
    of one gated run against the ledger baseline."""
    errs = []
    v = obj.get("ts")
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        errs.append(f"{where}: ts must be numeric (got {v!r})")
    if not isinstance(obj.get("ledger"), str):
        errs.append(f"{where}: ledger must be a string (path)")
    for key in ("k_mad", "min_samples", "baseline_n"):
        v = obj.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be numeric (got {v!r})")
    rows = obj.get("results")
    if not isinstance(rows, list):
        errs.append(f"{where}: results must be a list")
        rows = []
    n_reg = n_imp = 0
    for i, r in enumerate(rows):
        if not isinstance(r, dict):
            errs.append(f"{where}: results[{i}] is not an object")
            continue
        for key in ("config", "metric"):
            if not isinstance(r.get(key), str):
                errs.append(f"{where}: results[{i}].{key} must be a "
                            f"string")
        st = r.get("status")
        if st not in _GATE_STATUSES:
            errs.append(f"{where}: results[{i}].status {st!r} not in "
                        f"{_GATE_STATUSES}")
        v = r.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"{where}: results[{i}].value must be numeric")
        # the band fields must exist whenever the row was actually
        # compared against a baseline
        if st in ("ok", "regression", "improvement"):
            for key in ("baseline_median", "baseline_mad", "band",
                        "n_baseline"):
                bv = r.get(key)
                if not isinstance(bv, (int, float)) \
                        or isinstance(bv, bool):
                    errs.append(f"{where}: results[{i}].{key} must be "
                                f"numeric on a compared row")
        n_reg += st == "regression"
        n_imp += st == "improvement"
    for key, n in (("regressions", n_reg), ("improvements", n_imp)):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errs.append(f"{where}: {key} must be an int (got {v!r})")
        elif v != n:
            errs.append(f"{where}: {key}={v} disagrees with the "
                        f"result rows ({n})")
    return errs


def validate_jsonl(path):
    errs = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"{path}:{ln}: unparseable line ({e})")
                continue
            if not isinstance(rec, dict):
                errs.append(f"{path}:{ln}: line is not a JSON object")
            elif rec.get("kind") == "serving_loadgen":
                errs.extend(validate_loadgen(rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "generation_loadgen":
                errs.extend(validate_generation_loadgen(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "chaos_loadgen":
                errs.extend(validate_chaos_loadgen(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "spec_loadgen":
                errs.extend(validate_spec_loadgen(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "router_loadgen":
                errs.extend(validate_router_loadgen(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "disagg_loadgen":
                errs.extend(validate_disagg_loadgen(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "program_lint":
                errs.extend(validate_program_lint(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "graph_opt":
                errs.extend(validate_graph_opt(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "memory_plan":
                errs.extend(validate_memory_plan(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "sharding_report":
                errs.extend(validate_sharding_report(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "sharded_bench":
                errs.extend(validate_sharded_bench(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "trace_report":
                errs.extend(validate_trace_report(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "incident_bundle":
                errs.extend(validate_incident_bundle(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "perf_gate":
                errs.extend(validate_perf_gate(
                    rec, where=f"{path}:{ln}"))
            elif rec.get("kind") == "goodput_report":
                errs.extend(validate_goodput_report(
                    rec, where=f"{path}:{ln}"))
    return errs


def validate_file(path):
    """Auto-detect the artifact type and return a list of error
    strings (empty = valid)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [f"{path}: unreadable ({e})"]
    if not text.strip():
        return [f"{path}: empty"]
    # whole-file JSON first; fall back to line-by-line JSONL
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return validate_jsonl(path)
    if not isinstance(obj, dict):
        return [f"{path}: top-level JSON is not an object"]
    if obj.get("kind") == "bench_summary":
        return validate_summary(obj, where=path)
    if obj.get("kind") == "incident_bundle":
        return validate_incident_bundle(obj, where=path)
    if obj.get("kind") == "perf_gate":
        return validate_perf_gate(obj, where=path)
    if obj.get("kind") == "goodput_report":
        return validate_goodput_report(obj, where=path)
    if "parsed" in obj and "cmd" in obj:
        return validate_wrapper(obj, where=path)
    # a single-record JSONL (e.g. one snapshot) is also fine
    return []


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    errs = []
    for path in argv:
        errs.extend(validate_file(path))
    for e in errs:
        print(f"INVALID: {e}", file=sys.stderr)
    if not errs:
        print(f"ok: {len(argv)} artifact(s) valid")
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Goodput report: category table, goodput fraction, step waterfall.

Renders the run-level wall-clock ledger (paddle_tpu/goodput.py): every
second of a run attributed to one exclusive category, the goodput
fraction (device_compute / wall), and a per-step waterfall for the
worst-N steps.  Two modes:

  # render the last goodput_snapshot record found in run logs
  python tools/goodput_report.py RUN.jsonl [RUN2.jsonl ...] \
      [--worst 5] [--out report.jsonl]

  # self-contained CPU smoke: tiny SGD training loop, in-process
  python tools/goodput_report.py --smoke --cpu --steps 40 \
      [--starve] [--config goodput_smoke] [--out report.jsonl] [--check]

``--starve`` arms ``slow_step:ms=<starve-ms>:site=reader`` so the run
demonstrates input starvation (input_wait becomes the top category).
``--out`` appends one ``kind="goodput_report"`` JSONL record that
tools/perf_ledger.py ingests (metrics ``goodput_frac`` and
``input_wait_s``) so tools/perf_gate.py flags goodput regressions like
throughput regressions.  ``--check`` exits 1 when the category sum
drifts more than 5% from wall-clock (the ledger's invariant).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# category render order matches paddle_tpu.goodput.CATEGORIES; the bar
# glyph per category keys the waterfall
_BAR_GLYPHS = {
    "input_wait": "i",
    "feed_s": "f",
    "compile_s": "c",
    "compute_s": "#",
    "fetch_s": "s",
    "other_s": ".",
}


def load_snapshot(paths):
    """Last kind=goodput_snapshot record across the given JSONL logs."""
    snap = None
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and \
                            rec.get("kind") == "goodput_snapshot":
                        snap = rec
        except OSError as e:
            print(f"warning: cannot read {path}: {e}", file=sys.stderr)
    return snap


def run_smoke(steps=40, batch=8, starve=False, starve_ms=80.0,
              label="smoke"):
    """Self-contained tiny CPU training run under the goodput ledger."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import goodput, layers
    from paddle_tpu.core.flags import FLAGS
    from paddle_tpu.resilience import faults

    prev = {k: getattr(FLAGS, k)
            for k in ("enable_monitor", "enable_goodput", "fault_spec")}
    FLAGS.enable_monitor = True
    FLAGS.enable_goodput = True
    if starve:
        FLAGS.fault_spec = "slow_step:ms=%g:site=reader" % starve_ms
        faults.reset_injector()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), \
                fluid.unique_name.guard("gp_"):
            x = layers.data("x", shape=[-1, 16], dtype="float32",
                            append_batch_size=False)
            y = layers.data("y", shape=[-1, 1], dtype="float32",
                            append_batch_size=False)
            h = layers.fc(x, size=32, act="relu")
            pred = layers.fc(h, size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)

        rng = np.random.RandomState(0)

        def gen():
            for _ in range(steps):
                yield {"x": rng.randn(batch, 16).astype(np.float32),
                       "y": rng.randn(batch, 1).astype(np.float32)}

        loader = fluid.io.DataLoader.from_generator(capacity=2)
        loader.set_batch_generator(lambda: gen())

        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            # startup runs OUTSIDE the ledger window so its one-off
            # build doesn't count against the training run's warmup
            exe.run(startup)
            goodput.start_run(label)
            for feed in loader():
                exe.run(main, feed=feed, fetch_list=[loss])
            snap = goodput.end_run()
    finally:
        goodput.reset()
        for k, v in prev.items():
            setattr(FLAGS, k, v)
        faults.reset_injector()
    return snap


def worst_steps(snap, n):
    """Worst-N step records by wall time including the preceding wait."""
    steps = list(snap.get("step_records") or [])
    steps.sort(key=lambda s: float(s.get("total_s") or 0.0),
               reverse=True)
    return steps[:max(0, n)]


def _bar(step, width=36):
    total = float(step.get("total_s") or 0.0)
    if total <= 0:
        return " " * width
    parts = [("input_wait", float(step.get("input_wait_s") or 0.0))]
    for k in ("feed_s", "compile_s", "compute_s", "fetch_s", "other_s"):
        parts.append((k, float(step.get(k) or 0.0)))
    out = []
    for key, sec in parts:
        out.append(_BAR_GLYPHS[key] * int(round(width * sec / total)))
    return ("".join(out))[:width].ljust(width)


def render(snap, worst=5):
    lines = []
    wall = float(snap.get("wall_s") or 0.0)
    cats = snap.get("categories") or {}
    lines.append("== goodput report: %s ==" % (snap.get("label")
                                               or snap.get("config")
                                               or "run"))
    lines.append("wall-clock            %10.3f s" % wall)
    lines.append("goodput fraction      %10.3f   "
                 "(device_compute / wall)" % float(
                     snap.get("goodput_frac") or 0.0))
    lines.append("sum-invariant error   %9.1f %%" % (
        100.0 * float(snap.get("sum_frac_err") or 0.0)))
    lines.append("steps %d  compile-steps %d  post-warmup compiles %d  "
                 "starved steps %d" % (
                     int(snap.get("steps") or 0),
                     int(snap.get("compile_steps") or 0),
                     int(snap.get("post_warmup_compiles") or 0),
                     int(snap.get("starved_steps") or 0)))
    lines.append("")
    lines.append("%-20s %12s %8s" % ("category", "seconds", "% wall"))
    order = sorted(cats.items(), key=lambda kv: -float(kv[1] or 0.0))
    for name, sec in order:
        sec = float(sec or 0.0)
        pct = 100.0 * sec / wall if wall > 0 else 0.0
        lines.append("%-20s %12.4f %7.1f%%" % (name, sec, pct))
    top = worst_steps(snap, worst)
    if top:
        lines.append("")
        lines.append("-- worst %d steps (i=input f=feed c=compile "
                     "#=compute s=sync .=other) --" % len(top))
        lines.append("%5s %10s %10s  %s" % ("step", "total_ms",
                                            "input_ms", "waterfall"))
        for s in top:
            lines.append("%5d %10.2f %10.2f  |%s|" % (
                int(s.get("step") or 0),
                1e3 * float(s.get("total_s") or 0.0),
                1e3 * float(s.get("input_wait_s") or 0.0),
                _bar(s)))
    return "\n".join(lines)


def report_record(snap, config, worst=5):
    """The kind="goodput_report" JSONL record perf_ledger ingests."""
    return {
        "kind": "goodput_report",
        "ts": time.time(),
        "config": config,
        "wall_s": snap.get("wall_s"),
        "goodput_frac": snap.get("goodput_frac"),
        "sum_frac_err": snap.get("sum_frac_err"),
        "categories": snap.get("categories") or {},
        "steps": snap.get("steps"),
        "compile_steps": snap.get("compile_steps"),
        "post_warmup_compiles": snap.get("post_warmup_compiles"),
        "input_batches": snap.get("input_batches"),
        "starved_steps": snap.get("starved_steps"),
        "worst_steps": worst_steps(snap, worst),
    }


def _emit(path, rec):
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Goodput category table, fraction, and step-time "
                    "waterfall")
    ap.add_argument("logs", nargs="*",
                    help="JSONL logs holding goodput_snapshot records")
    ap.add_argument("--smoke", action="store_true",
                    help="run a self-contained tiny CPU training loop")
    ap.add_argument("--cpu", action="store_true",
                    help="force the jax CPU backend")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--starve", action="store_true",
                    help="arm slow_step:site=reader during --smoke")
    ap.add_argument("--starve-ms", type=float, default=80.0)
    ap.add_argument("--config", default=None,
                    help="config label stamped into the --out record")
    ap.add_argument("--worst", type=int, default=5)
    ap.add_argument("--out", default=None,
                    help="append a goodput_report JSONL record here")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the category sum drifts >5%% "
                         "from wall-clock")
    args = ap.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.smoke:
        label = args.config or (
            "smoke_starved" if args.starve else "smoke_clean")
        snap = run_smoke(steps=args.steps, batch=args.batch,
                         starve=args.starve, starve_ms=args.starve_ms,
                         label=label)
    else:
        if not args.logs:
            ap.error("give JSONL logs or --smoke")
        snap = load_snapshot(args.logs)
        if snap is None:
            print("no goodput_snapshot record found", file=sys.stderr)
            return 2

    print(render(snap, worst=args.worst))
    config = args.config or snap.get("label") or "goodput"
    if args.out:
        _emit(args.out, report_record(snap, config, worst=args.worst))
        print("\nwrote goodput_report record -> %s" % args.out)
    if args.check:
        from paddle_tpu.goodput import check_invariant
        if not check_invariant(snap, tol=0.05):
            print("INVARIANT FAILED: category sum vs wall-clock "
                  "err=%.1f%%" % (
                      100.0 * float(snap.get("sum_frac_err") or 1.0)),
                  file=sys.stderr)
            return 1
        print("invariant OK: category seconds sum to wall-clock "
              "within 5%")
    return 0


if __name__ == "__main__":
    sys.exit(main())

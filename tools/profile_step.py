"""Profile one benchmark training step on the attached device and print
a device-time breakdown.

Usage (on TPU; also runs on CPU for plumbing checks):
    python tools/profile_step.py [bert|resnet50]

Uses bench.py's model builders, so the profiled program is EXACTLY the
benchmarked one (same BENCH_BATCH/BENCH_SEQ/BENCH_AMP/BENCH_FLASH env
config). Captures a jax.profiler trace around a handful of steps
(enqueued async, single end sync — see bench.py on tunnel RTT) and
aggregates the XPlane device events by category via
fluid.profiler.summarize_xplane: the per-op cost discipline of the
reference's operators/benchmark/op_tester.cc applied to the whole step.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def main():
    args = [a for a in sys.argv[1:] if a != "--cpu"]
    if "--cpu" in sys.argv:
        import jax
        jax.config.update("jax_platforms", "cpu")
    model = args[0] if args else "bert"
    import bench
    import paddle_tpu as fluid
    from paddle_tpu import monitor, profiler

    # a profile run IS a metrics run: turn the monitor on (unless the
    # user explicitly set the flag) so the same command yields both the
    # device trace and a JSONL stats snapshot next to it
    if "FLAGS_enable_monitor" not in os.environ:
        fluid.set_flags({"FLAGS_enable_monitor": True})

    build = bench.build_resnet50_bench if model == "resnet50" \
        else bench.build_bert_bench
    exe, prog, scope, feed, loss, _ = build()
    trace_dir = "/tmp/paddle_tpu_profile_step"
    with fluid.scope_guard(scope):
        _profile(exe, prog, feed, loss, trace_dir, profiler)
    if monitor.enabled():
        log = monitor.snapshot_to_jsonl(
            os.path.join(trace_dir, "monitor.jsonl"))
        print(f"# monitor snapshot: {log} "
              f"(report: python tools/metrics_report.py {log})")


def _profile(exe, prog, feed, loss, trace_dir, profiler, steps=5):
    # warm up + compile outside the trace
    exe.run(prog, feed=feed, fetch_list=[loss])
    x, = exe.run(prog, feed=feed, fetch_list=[loss], return_numpy=False)
    np.asarray(x)
    profiler.start_profiler(output_dir=trace_dir)
    for _ in range(steps):
        x, = exe.run(prog, feed=feed, fetch_list=[loss],
                     return_numpy=False)
    np.asarray(x)  # drain before stopping the trace
    profiler.stop_profiler()
    summary = profiler.summarize_xplane(trace_dir)
    summary["per_step_us"] = summary["total_us"] / steps
    print(json.dumps({
        "per_step_us": round(summary["per_step_us"], 1),
        "by_category_us": {k: round(v, 1)
                           for k, v in summary["by_category"].items()},
        "top_ops_us": [(n, round(v, 1))
                       for n, v in summary["top_ops"][:15]],
    }, indent=1))


if __name__ == "__main__":
    main()

"""Profile one benchmark training step on the attached device and print
a device-time breakdown.

Usage (on TPU; also runs on CPU for plumbing checks):
    python tools/profile_step.py [bert|resnet50]

Captures a jax.profiler trace around a handful of steps (enqueued
async, single end sync — see bench.py on tunnel RTT) and aggregates the
XPlane device events by category via fluid.profiler.summarize_xplane:
the per-op cost discipline of the reference's
operators/benchmark/op_tester.cc applied to the whole step.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "bert"
    import paddle_tpu as fluid
    from paddle_tpu import profiler

    trace_dir = "/tmp/paddle_tpu_profile_step"
    if model == "resnet50":
        from paddle_tpu.models import resnet
        batch = int(os.environ.get("BENCH_BATCH", "64"))
        main_prog, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main_prog, startup), \
                fluid.scope_guard(scope):
            loss, acc, _ = resnet.build_train(amp=True)
            exe = fluid.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {"image": rng.randn(batch, 3, 224, 224)
                    .astype(np.float32),
                    "label": rng.randint(0, 1000, (batch, 1))
                    .astype(np.int64)}
            _profile(exe, main_prog, feed, loss, trace_dir, profiler)
    else:
        from paddle_tpu.models import transformer
        batch = int(os.environ.get("BENCH_BATCH", "32"))
        seq = int(os.environ.get("BENCH_SEQ", "512"))
        cfg = transformer.bert_base(
            dropout=0.1, attn_dropout=0.0,
            use_flash=os.environ.get("BENCH_FLASH", "1") == "1")
        main_prog, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main_prog, startup), \
                fluid.scope_guard(scope):
            loss, _ = transformer.build_train(cfg, batch, seq, lr=1e-4,
                                              amp=True)
            exe = fluid.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            toks = rng.randint(0, cfg.vocab_size, (batch, seq)) \
                .astype(np.int64)
            feed = {"tokens": toks, "labels": toks}
            _profile(exe, main_prog, feed, loss, trace_dir, profiler)


def _profile(exe, prog, feed, loss, trace_dir, profiler, steps=5):
    # warm up + compile outside the trace
    exe.run(prog, feed=feed, fetch_list=[loss])
    x, = exe.run(prog, feed=feed, fetch_list=[loss], return_numpy=False)
    np.asarray(x)
    profiler.start_profiler(output_dir=trace_dir)
    for _ in range(steps):
        x, = exe.run(prog, feed=feed, fetch_list=[loss],
                     return_numpy=False)
    np.asarray(x)  # drain before stopping the trace
    profiler.stop_profiler()
    summary = profiler.summarize_xplane(trace_dir)
    summary["per_step_us"] = summary["total_us"] / steps
    print(json.dumps({
        "per_step_us": round(summary["per_step_us"], 1),
        "by_category_us": {k: round(v, 1)
                           for k, v in summary["by_category"].items()},
        "top_ops_us": [(n, round(v, 1))
                       for n, v in summary["top_ops"][:15]],
    }, indent=1))


if __name__ == "__main__":
    main()

"""Standalone subprocess replica: one engine + HTTP front end per
process.

The missing piece between the in-process router drills and a real
fleet: `serving_loadgen --router --disagg` (and anything else that
wants genuine process isolation) launches N of these, each binding an
ephemeral port and writing it to --port-file, then registers them with
the Router as ``Replica(url=...)``. Two backends:

* --model-dir DIR: a saved inference model behind a warmed
  ServingEngine (/v1/predict).
* --weights FILE.npz: a tiny-GPT GenerationEngine (/v1/generate,
  /v1/kv/export, /v1/kv/adopt). The npz holds the trained (or scratch)
  parameter tensors under their training-graph names; the engine's
  startup program is never run, so the loaded weights survive and
  every replica process decodes from IDENTICAL parameters — the
  property the disagg wrong-answers gate leans on.

Lifecycle: build -> warm (all compiles) -> bind -> write --port-file
(atomically, AFTER readiness) -> print one ``{"kind":
"replica_ready"}`` line -> serve until SIGTERM/SIGINT -> drain and
exit 0. SIGTERM-clean by construction: the handler only sets an
event; draining happens on the main thread.

Usage (normally spawned by tools/serving_loadgen.py):
    python tools/serving_replica.py --weights w.npz --vocab 64 \
        --max-seq 96 --block-size 8 --port-file /tmp/r0.port
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_gen_engine(args):
    import paddle_tpu as fluid
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import GenerationEngine

    cfg = gpt.gpt_small(vocab_size=args.vocab, d_model=args.d_model,
                        n_heads=args.n_heads, n_layers=args.n_layers,
                        d_ff=args.d_ff, max_seq_len=args.max_seq,
                        dropout=0.0, use_flash=False)
    scope = fluid.Scope()
    data = np.load(args.weights)
    for name in data.files:
        scope.var(name)
        scope.set(name, np.array(data[name]))
    engine = GenerationEngine(
        cfg, scope, max_slots=args.slots, max_seq=args.max_seq,
        default_timeout_ms=args.timeout_ms, paged=True,
        block_size=args.block_size or None,
        kv_pool_blocks=args.kv_pool_blocks or None,
        spec_decode=args.spec_decode or None,
        spec_k=args.spec_k or None)
    # start() seeds only the decode state ("gen." names) and warms the
    # executables; the loaded weights are untouched
    return engine


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="standalone subprocess serving replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 (default) binds an ephemeral port")
    ap.add_argument("--port-file",
                    help="write the bound port here once READY "
                         "(written atomically after warmup + bind)")
    ap.add_argument("--model-dir",
                    help="saved inference model -> ServingEngine "
                         "(/v1/predict)")
    ap.add_argument("--weights",
                    help="npz of tiny-GPT parameters -> "
                         "GenerationEngine (/v1/generate + /v1/kv/*)")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=32)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=0)
    ap.add_argument("--kv-pool-blocks", type=int, default=0)
    ap.add_argument("--timeout-ms", type=float, default=10000.0)
    ap.add_argument("--max-batch-size", type=int, default=8)
    ap.add_argument("--seq-buckets", default="8,16,32")
    ap.add_argument("--spec-decode", action="store_true")
    ap.add_argument("--spec-k", type=int, default=0)
    args = ap.parse_args(argv)

    if not args.model_dir and not args.weights:
        print("need --model-dir and/or --weights", file=sys.stderr)
        return 2

    from paddle_tpu.serving import serve

    engine = None
    gen = None
    if args.model_dir:
        from paddle_tpu.serving import EngineConfig, ServingEngine
        engine = ServingEngine(EngineConfig(
            args.model_dir, max_batch_size=args.max_batch_size,
            default_timeout_ms=args.timeout_ms,
            seq_buckets=tuple(int(s) for s in
                              args.seq_buckets.split(",")),
            warmup=True))
    if args.weights:
        gen = build_gen_engine(args)

    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # serve() warms the engines (every compile of the process's
    # lifetime) before binding, so the port's appearance IS readiness
    srv = serve(engine, port=args.port, gen_engine=gen)
    port = srv.port
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, args.port_file)  # atomic: readers never see ""
    print(json.dumps({"kind": "replica_ready", "pid": os.getpid(),
                      "port": port, "url": f"http://{args.host}:{port}",
                      "predict": engine is not None,
                      "generate": gen is not None}), flush=True)

    while not stop_evt.wait(0.2):
        pass

    # SIGTERM-clean: finish in-flight work, then release everything
    srv.close(drain=True)
    if gen is not None:
        gen.stop(drain=True)
    if engine is not None:
        engine.stop(drain=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Load generator for the serving engine: throughput + latency JSONL.

Two workloads:

* **encoder** (default): fixed-shape predict requests through the
  dynamic batcher (`kind="serving_loadgen"` records).
* **generation** (--generate): autoregressive decode requests with
  mixed prompt lengths and staggered admission through the
  continuous-batching `GenerationEngine`
  (`kind="generation_loadgen"` records carrying tokens/s, TTFT and
  inter-token latency percentiles). --compare-serial replays the same
  request set through serial per-request `gpt.kv_generate` — the
  throughput floor continuous batching must beat AND the exact-answer
  reference every engine output is verified against (exit 4 on
  mismatch). --shared-prefix-frac makes that fraction of requests open
  with one fixed whole-block prefix: the record gains a "prefix"
  object splitting TTFT hit-vs-miss and snapshotting the paged KV
  pool; --block-size / --slab pick the KV layout for A/B runs;
  --temperature applies one sampling temperature to every request
  (engine, HTTP and serial paths alike — parity holds at any value).
  --spec-decode switches to the speculative-decoding A/B
  (`kind="spec_loadgen"`): a spec-on and a spec-off engine run the
  same repetitive cyclic-successor traffic over briefly-trained
  weights, the record carries acceptance rate, effective tokens/step
  and the on/off tokens-per-second speedup, and every spec-on output
  is verified against serial kv_generate (exit 4 on divergence).

Two targets:

* **in-process** (default): builds a tiny CPU model (or loads
  --model-dir), starts a warmed ServingEngine, and drives it directly —
  the CPU smoke bench behind the acceptance criteria (zero post-warmup
  compiles; batched > serial throughput).
* **HTTP** (--url): POSTs /v1/predict (or /v1/generate with
  --generate) at an already-running front end.

Two arrival disciplines:

* **closed loop** (default): --concurrency workers each keep exactly one
  request in flight (classic closed-loop load; throughput is
  concurrency / mean latency).
* **open loop** (--rate R): requests are launched on a fixed-rate
  schedule regardless of completions, the discipline that actually
  exposes queueing collapse (rejections surface as `errors`).

Each run appends one `{"kind": "serving_loadgen", ...}` record to --out
(JSONL, schema enforced by tools/validate_bench_json.py) and prints it;
tools/metrics_report.py renders these records as a serving section.
--compare-serial additionally runs the same request set through a bare
single-request predictor and emits a second record (mode
"serial_baseline") plus a speedup line. --check-compiles asserts the
executor cache-miss counter stayed flat after warmup (exit 3 when it
moved).

--trace (generation, in-process only) arms FLAGS_enable_trace at 100%
sampling, wraps every request in a root span, dumps the kept spans to
--trace-out (JSONL) and ASSERTS the trace trees are complete: every
request must carry queue/prefill/decode/fetch child spans, the
critical-path components must sum to within 10% of the measured e2e,
and the parent/child consistency audit must be clean — exit 6 on any
violation. The record gains a "trace" object and the span dump feeds
tools/trace_report.py.

--chaos is the resilience acceptance run (`kind="chaos_loadgen"`
records): a fault-free baseline pass pins per-request expected outputs
and the fault-free p99, then the same traffic replays with
FLAGS_fault_spec armed (--fault-spec). Every 200 is numerically
verified against the baseline; exit 4 on any wrong answer or engine
worker death, exit 5 when the chaos p99 exceeds --chaos-p99-bound
times the fault-free p99.

Usage:
    python tools/serving_loadgen.py --requests 200 --concurrency 8 \
        --compare-serial --check-compiles --out loadgen.jsonl
    python tools/serving_loadgen.py --url http://127.0.0.1:8000 \
        --rate 50 --duration 10
    python tools/serving_loadgen.py --generate --requests 24 \
        --slots 4 --max-new-tokens 8 --compare-serial --check-compiles
    python tools/serving_loadgen.py --generate --spec-decode \
        --spec-k 8 --requests 64 --slots 4 --vocab 8 --max-seq 128 \
        --max-prompt 8 --max-new-tokens 96 --check-compiles \
        --out spec.jsonl
    python tools/serving_loadgen.py --chaos --requests 100 \
        --fault-spec "transient_fail:p=0.05,step_nan:p=0.01"
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _percentile(sorted_ms, q):
    if not sorted_ms:
        return None
    i = min(len(sorted_ms) - 1, max(0, int(q * len(sorted_ms)) - 1))
    return round(sorted_ms[i], 3)


def summarize(kind_mode, latencies_s, errors, duration_s, config):
    lat = sorted(v * 1e3 for v in latencies_s)
    n = len(lat)
    return {
        "kind": "serving_loadgen",
        "mode": kind_mode,
        "requests": n,
        "errors": errors,
        "duration_s": round(duration_s, 4),
        "throughput_rps": round(n / duration_s, 2) if duration_s else 0.0,
        "latency_ms": {
            "mean": round(sum(lat) / n, 3) if n else None,
            "p50": _percentile(lat, 0.50),
            "p95": _percentile(lat, 0.95),
            "p99": _percentile(lat, 0.99),
            "max": round(lat[-1], 3) if n else None,
        },
        "config": config,
    }


def build_tiny_model(tmpdir, feat=6):
    """Save the classifier the serving tests use: x[b, t, feat] ->
    reduce_sum over t -> fc -> softmax (seq-pad invariant, so bucket
    padding is checkable against unpadded references)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, -1, feat], dtype="float32",
                        append_batch_size=False)
        s = layers.reduce_sum(x, dim=1)
        h = layers.fc(s, size=16, act="relu")
        pred = layers.fc(h, size=4, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(tmpdir, ["x"], [pred], exe,
                                      main_program=main)
    return tmpdir


def make_requests(n, seq_buckets, feat, seed=0):
    """Mixed-shape single-row requests with lengths drawn from the
    bucket ladder's covered range."""
    rng = np.random.RandomState(seed)
    hi = max(seq_buckets)
    return [{"x": rng.randn(1, int(rng.randint(1, hi + 1)),
                            feat).astype(np.float32)}
            for _ in range(n)]


class _EngineTarget:
    def __init__(self, engine):
        self.engine = engine

    def call(self, feed, timeout_ms):
        self.engine.predict(feed, timeout_ms=timeout_ms)


class _HTTPTarget:
    def __init__(self, url):
        self.url = url.rstrip("/")

    def call(self, feed, timeout_ms):
        import urllib.request
        body = json.dumps(
            {"inputs": {k: v.tolist() for k, v in feed.items()},
             "timeout_ms": timeout_ms}).encode()
        req = urllib.request.Request(
            self.url + "/v1/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()


def run_closed(target, requests, concurrency, timeout_ms):
    latencies, errors = [], [0]
    lock = threading.Lock()
    it = iter(requests)

    def worker():
        while True:
            with lock:
                feed = next(it, None)
            if feed is None:
                return
            t0 = time.perf_counter()
            try:
                target.call(feed, timeout_ms)
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
            except Exception:  # noqa: BLE001 — rejected/timed-out
                with lock:     # requests are the load signal, not a bug
                    errors[0] += 1

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return latencies, errors[0], time.perf_counter() - t0


def run_open(target, requests, rate, timeout_ms):
    """Fixed-rate arrivals: every 1/rate seconds a new request launches
    on its own thread whether or not earlier ones finished."""
    latencies, errors = [], [0]
    lock = threading.Lock()
    threads = []

    def one(feed):
        t0 = time.perf_counter()
        try:
            target.call(feed, timeout_ms)
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
        except Exception:  # noqa: BLE001
            with lock:
                errors[0] += 1

    interval = 1.0 / rate
    t_start = time.perf_counter()
    for i, feed in enumerate(requests):
        due = t_start + i * interval
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=one, args=(feed,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    return latencies, errors[0], time.perf_counter() - t_start


def run_serial_baseline(predictor, requests):
    """Single-request dispatch, no batching — the throughput floor the
    batched engine must beat."""
    latencies = []
    t0 = time.perf_counter()
    for feed in requests:
        t1 = time.perf_counter()
        predictor.run_dict(feed)
        latencies.append(time.perf_counter() - t1)
    return latencies, 0, time.perf_counter() - t0


def _lat_summary(values_s):
    """{"mean", "p50", "p95", "p99", "max"} in ms (None when empty)."""
    lat = sorted(v * 1e3 for v in values_s)
    n = len(lat)
    return {
        "mean": round(sum(lat) / n, 3) if n else None,
        "p50": _percentile(lat, 0.50),
        "p95": _percentile(lat, 0.95),
        "p99": _percentile(lat, 0.99),
        "max": round(lat[-1], 3) if n else None,
    }


def summarize_generation(mode, latencies_s, ttfts_s, inter_s, tokens,
                         errors, duration_s, config):
    """One kind="generation_loadgen" record (schema enforced by
    tools/validate_bench_json.py)."""
    n = len(latencies_s)
    return {
        "kind": "generation_loadgen",
        "mode": mode,
        "requests": n,
        "errors": errors,
        "duration_s": round(duration_s, 4),
        "throughput_rps": round(n / duration_s, 2) if duration_s
        else 0.0,
        "tokens": int(tokens),
        "tokens_per_s": round(tokens / duration_s, 2) if duration_s
        else 0.0,
        "latency_ms": _lat_summary(latencies_s),
        "ttft_ms": _lat_summary(ttfts_s),
        "inter_token_ms": _lat_summary(inter_s),
        "config": config,
    }


def make_gen_requests(n, vocab, max_prompt, max_new_tokens, seed=0,
                      shared_prefix_frac=0.0, shared_prefix_len=0,
                      temperature=0.0):
    """Mixed prompt lengths in [1, max_prompt] — with staggered
    admission this is exactly the traffic that would recompile a
    shape-naive decode path.

    `shared_prefix_frac` of the requests open with one fixed
    `shared_prefix_len`-token prefix (the shared-system-prompt shape of
    real LLM traffic): the prefix-cache workload. Each request carries
    `"shared": bool` so the report can split TTFT by cohort even when
    the engine under test has no cache to report hits from.

    `temperature` rides on every request (engine, HTTP and serial-
    reference paths all honor it): with the per-request seed, sampled
    runs stay reproducible AND --compare-serial stays meaningful at
    temperature > 0 — both paths draw through the same
    models/sampling.py rng discipline."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, vocab, size=max(int(shared_prefix_len),
                                            0)).tolist()
    out = []
    for i in range(n):
        shared = bool(prefix) and shared_prefix_frac > 0 \
            and rng.random_sample() < shared_prefix_frac
        if shared:
            tail = rng.randint(0, vocab, size=rng.randint(
                1, max(2, max_prompt - len(prefix) + 1))).tolist()
            prompt = prefix + tail
        else:
            prompt = rng.randint(0, vocab, size=rng.randint(
                1, max_prompt + 1)).tolist()
        out.append({"prompt": prompt,
                    "max_new_tokens": int(max_new_tokens),
                    "seed": int(seed + i), "idx": i, "shared": shared,
                    "temperature": float(temperature)})
    return out


def make_spec_requests(n, vocab, max_prompt, max_new_tokens, seed=0,
                       temperature=0.0):
    """Repetitive generation traffic for the --spec-decode A/B: every
    prompt is a run of the cyclic-successor sequence ((t+1) % vocab
    follows t — the task the spec mode trains its tiny model on), so
    greedy continuations are deterministic and, once the generation
    wraps the vocab cycle, the n-gram drafter's suffix lookup starts
    hitting — the repetition-heavy regime speculative decoding exists
    for. Requests still vary in start token, length and seed so slots
    join/leave the batch staggered."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        s = int(rng.randint(vocab))
        plen = int(rng.randint(2, max_prompt + 1))
        prompt = [(s + j) % vocab for j in range(plen)]
        out.append({"prompt": prompt,
                    "max_new_tokens": int(max_new_tokens),
                    "seed": int(seed + i), "idx": i, "shared": False,
                    "temperature": float(temperature)})
    return out


class _GenStats:
    """Thread-safe TTFT / inter-token / token-count accumulators shared
    by the per-request calls of one run."""

    def __init__(self):
        self.lock = threading.Lock()
        self.ttfts = []
        self.inter = []
        self.tokens = 0
        # prefix-cache probe: TTFT split by whether the engine reported
        # cached prompt tokens, plus per-request outputs keyed by the
        # request's idx for the wrong-answers check vs the serial ref
        self.ttft_hit = []
        self.ttft_miss = []
        self.hits = 0
        self.misses = 0
        self.outputs = {}

    def record(self, t_submit, token_times, n_tokens):
        with self.lock:
            if token_times:
                self.ttfts.append(token_times[0] - t_submit)
                self.inter.extend(b - a for a, b in
                                  zip(token_times, token_times[1:]))
            self.tokens += n_tokens

    def record_prefix(self, t_submit, token_times, cached_tokens,
                      idx=None, tokens=None):
        with self.lock:
            if cached_tokens:
                self.hits += 1
            else:
                self.misses += 1
            if token_times:
                (self.ttft_hit if cached_tokens
                 else self.ttft_miss).append(token_times[0] - t_submit)
            if idx is not None:
                self.outputs[idx] = list(tokens or ())


class _GenEngineTarget:
    """Drives an in-process GenerationEngine; per-token timestamps come
    from the engine's stream_cb. With `traced` each call opens a root
    "request" span (the loadgen stands in for the HTTP front end), so
    the engine's gen.request/queue/prefill/decode spans nest under it
    and the loadgen-measured e2e is the trace's tail-sampling input."""

    def __init__(self, engine, stats, traced=False):
        self.engine = engine
        self.stats = stats
        self.traced = traced

    def call(self, req, timeout_ms):
        from paddle_tpu.serving import GenerationRequest
        times = []
        root = None
        if self.traced:
            from paddle_tpu import trace
            root = trace.start_span("request",
                                    attrs={"idx": req.get("idx")})
        t0 = time.perf_counter()
        try:
            greq = GenerationRequest(
                req["prompt"], req["max_new_tokens"],
                temperature=req.get("temperature", 0.0),
                seed=req["seed"], timeout_ms=timeout_ms,
                spec_decode=req.get("spec_decode"),
                stream_cb=lambda tok: times.append(
                    time.perf_counter()))
            if root is not None:
                from paddle_tpu import trace
                with trace.use_span(root):
                    resp = self.engine.submit(greq)
            else:
                resp = self.engine.submit(greq)
            out = resp.result(
                timeout=(timeout_ms or 30000.0) / 1e3 + 30.0)
        except Exception as e:
            if root is not None:
                from paddle_tpu import trace
                trace.finish_trace(
                    root, error=f"{type(e).__name__}: {e}",
                    e2e_ms=(time.perf_counter() - t0) * 1e3)
            raise
        if root is not None:
            from paddle_tpu import trace
            trace.finish_trace(
                root, e2e_ms=(time.perf_counter() - t0) * 1e3)
        self.stats.record(t0, times, len(out["tokens"]))
        self.stats.record_prefix(t0, times, out.get("cached_tokens", 0),
                                 idx=req.get("idx"),
                                 tokens=out["tokens"])


class _GenHTTPTarget:
    """POSTs /v1/generate; no token stream over plain HTTP, so TTFT
    comes from the engine-reported ttft_ms in the response."""

    def __init__(self, url, stats):
        self.url = url.rstrip("/")
        self.stats = stats

    def call(self, req, timeout_ms):
        import urllib.request
        body = json.dumps({"prompt": req["prompt"],
                           "max_new_tokens": req["max_new_tokens"],
                           "temperature": req.get("temperature", 0.0),
                           "seed": req["seed"],
                           "spec_decode": req.get("spec_decode"),
                           "timeout_ms": timeout_ms}).encode()
        r = urllib.request.Request(
            self.url + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=60) as resp:
            out = json.load(resp)
        with self.stats.lock:
            if out.get("ttft_ms") is not None:
                self.stats.ttfts.append(out["ttft_ms"] / 1e3)
            self.stats.tokens += len(out.get("tokens", ()))


def run_serial_generation(exe, scope, prog, step, reqs):
    """Serial per-request kv_generate over a batch=1 decode graph
    sharing the engine's scope — the no-continuous-batching floor AND
    the exact-answer reference (outputs keyed by request idx)."""
    from paddle_tpu.models import gpt
    stats = _GenStats()
    latencies = []
    outputs = {}
    t0 = time.perf_counter()
    for req in reqs:
        times = []
        t1 = time.perf_counter()
        out = gpt.kv_generate(
            exe, scope, prog, step.token_var, step.logits_var,
            step.cache_names, req["prompt"], req["max_new_tokens"],
            temperature=req.get("temperature", 0.0), seed=req["seed"],
            stream_cb=lambda tok: times.append(time.perf_counter()))
        latencies.append(time.perf_counter() - t1)
        stats.record(t1, times, len(out))
        if "idx" in req:
            outputs[req["idx"]] = list(out)
    return stats, latencies, time.perf_counter() - t0, outputs


_TRACE_PHASES = ("queue", "prefill", "decode", "fetch")


def _check_traces(args, tr_mod):
    """--trace post-run audit: drain the kept-span ring, dump it to
    --trace-out, and verify (a) every ok request trace is COMPLETE
    (queue/prefill/decode/fetch spans all present), (b) the
    critical-path component sum lands within 10% of the measured e2e,
    (c) the parent/child consistency audit is clean. Returns
    (failed, summary-dict for the loadgen record)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report as trp

    spans = tr_mod.drain_spans()
    out = args.trace_out
    if not out:
        base = args.out or os.path.join(tempfile.gettempdir(),
                                        "serving_loadgen.jsonl")
        out = os.path.splitext(os.path.abspath(base))[0] \
            + ".spans.jsonl"
    try:  # fresh dump per run: trace_report reads whole files
        os.remove(out)
    except OSError:
        pass
    tr_mod.export_jsonl(out, spans)

    by_id, children = trp.build_index(spans)
    roots = [r for r in trp.trace_roots(spans, by_id)
             if r["name"] in trp.REQUEST_ROOTS]
    rows = [trp.analyze_request(r, children) for r in roots]
    checked, violations = trp.check_consistency(spans, children)

    incomplete, crit_bad = [], []
    n_err = 0
    for root, row in zip(roots, rows):
        if row["status"] != "ok":
            n_err += 1  # rejected/timed-out requests legitimately
            continue    # carry partial trees
        names = {s["name"] for s in trp._walk(root, children)}
        missing = [p for p in _TRACE_PHASES if p not in names]
        if missing:
            incomplete.append((row["trace_id"], missing))
            continue
        e2e, crit = row["e2e_ms"], row["critical_path_ms"]
        # The phase spans tile the ENGINE-side request span. A loadgen
        # or HTTP root above it additionally measures the client
        # waiter-thread wakeup delay between engine completion and the
        # caller observing it — time no span can cover — so check the
        # identity against the innermost request-boundary span.
        for s in trp._walk(root, children):
            if s["name"] in trp.REQUEST_ROOTS:
                a = s.get("attrs", {}).get("e2e_ms")
                e2e = float(a) if isinstance(a, (int, float)) \
                    else float(s.get("dur_ms") or e2e)
        # 10% of e2e plus 2ms absolute slack for thread-wakeup jitter
        # on sub-10ms CPU requests
        if abs(e2e - crit) > 0.10 * e2e + 2.0:
            crit_bad.append((row["trace_id"], e2e, crit))

    failed = False
    if not rows:
        print("FAIL: --trace run kept no request traces", file=sys.stderr)
        failed = True
    for tid, missing in incomplete[:10]:
        print(f"FAIL: trace {tid[:8]} incomplete: missing "
              f"{','.join(missing)} span(s)", file=sys.stderr)
    for tid, e2e, crit in crit_bad[:10]:
        print(f"FAIL: trace {tid[:8]} critical path {crit}ms vs e2e "
              f"{e2e}ms (>10% apart)", file=sys.stderr)
    for v in violations[:10]:
        print(f"FAIL: trace consistency: {v}", file=sys.stderr)
    failed = failed or bool(incomplete or crit_bad or violations)

    return failed, {
        "out": out, "spans": len(spans), "requests": len(rows),
        "error_requests": n_err, "incomplete": len(incomplete),
        "crit_path_violations": len(crit_bad),
        "consistency_checked": checked,
        "consistency_violations": len(violations),
    }


def run_generation(args):
    """The --generate workload: continuous-batching engine (or HTTP
    front end) under closed/open-loop generation traffic, optional
    serial kv_generate baseline, optional compile-count gate."""
    prefix_frac = getattr(args, "shared_prefix_frac", 0.0) or 0.0
    prefix_len = getattr(args, "shared_prefix_len", 0) or 0
    block_size = getattr(args, "block_size", 0) or 0
    if prefix_frac > 0 and prefix_len <= 0:
        # auto: largest whole-block prefix that still leaves >= 1
        # uncached prompt token (only FULL blocks are shareable, so the
        # block size itself must fit under max_prompt too)
        if block_size <= 0:
            block_size = min(16, max(args.max_prompt - 1, 1))
        prefix_len = (max(args.max_prompt - 1, 1)
                      // block_size) * block_size
        prefix_len = max(prefix_len, 0)
    temperature = getattr(args, "temperature", 0.0) or 0.0
    reqs = make_gen_requests(args.requests, args.vocab, args.max_prompt,
                             args.max_new_tokens, args.seed,
                             shared_prefix_frac=prefix_frac,
                             shared_prefix_len=prefix_len,
                             temperature=temperature)
    common = {"concurrency": args.concurrency, "rate": args.rate,
              "slots": args.slots, "max_prompt": args.max_prompt,
              "max_new_tokens": args.max_new_tokens,
              "max_seq": args.max_seq, "vocab": args.vocab,
              "temperature": temperature,
              "shared_prefix_frac": prefix_frac,
              "shared_prefix_len": prefix_len}
    if args.trace and args.url:
        print("--trace inspects the in-process span ring; --url is not "
              "supported", file=sys.stderr)
        return 2

    if args.url:
        stats = _GenStats()
        target = _GenHTTPTarget(args.url, stats)
        if args.rate > 0:
            if args.duration > 0:
                reqs = reqs[:max(1, int(args.rate * args.duration))]
            lat, errs, dur = run_open(target, reqs, args.rate,
                                      args.timeout_ms)
            mode = "open"
        else:
            lat, errs, dur = run_closed(target, reqs, args.concurrency,
                                        args.timeout_ms)
            mode = "closed"
        emit(summarize_generation(mode, lat, stats.ttfts, stats.inter,
                                  stats.tokens, errs, dur, common),
             args.out)
        return 0

    import paddle_tpu as fluid
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import GenerationEngine

    if args.trace:
        from paddle_tpu import trace as _tr
        # 100% head sampling by default: the completeness assertion
        # must see EVERY request's tree, not just the tail-kept ones.
        fluid.set_flags({"FLAGS_enable_trace": True,
                         "FLAGS_trace_sample": args.trace_sample})

    cfg = gpt.gpt_small(vocab_size=args.vocab, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=args.max_seq,
                        dropout=0.0, use_flash=False)
    scope = fluid.Scope()
    engine = GenerationEngine(cfg, scope, max_slots=args.slots,
                              max_seq=args.max_seq,
                              default_timeout_ms=args.timeout_ms,
                              paged=(False if getattr(args, "slab", False)
                                     else None),
                              block_size=block_size or None)
    engine.init_scope()   # scratch weights: loadgen measures the
    engine.start()        # serving path, not model quality
    misses_after_warmup = engine.cache_stats()["misses"]
    if args.trace:
        _tr.reset()  # drop any warmup-era spans: the dump must hold
        # exactly the measured run's traces

    stats = _GenStats()
    target = _GenEngineTarget(engine, stats, traced=args.trace)
    if args.rate > 0:
        if args.duration > 0:
            reqs = reqs[:max(1, int(args.rate * args.duration))]
        lat, errs, dur = run_open(target, reqs, args.rate,
                                  args.timeout_ms)
        mode = "open"
    else:
        lat, errs, dur = run_closed(target, reqs, args.concurrency,
                                    args.timeout_ms)
        mode = "closed"
    rec = summarize_generation(mode, lat, stats.ttfts, stats.inter,
                               stats.tokens, errs, dur, common)
    post = engine.post_warmup_compiles()
    rec["cache"] = {"misses_after_warmup": misses_after_warmup,
                    "misses_total": engine.cache_stats()["misses"],
                    "post_warmup_compiles": post}
    total = stats.hits + stats.misses
    rec["prefix"] = {
        "shared_prefix_frac": prefix_frac,
        "shared_prefix_len": prefix_len,
        "hit_requests": stats.hits,
        "miss_requests": stats.misses,
        "hit_rate": round(stats.hits / total, 4) if total else None,
        "ttft_hit_ms": _lat_summary(stats.ttft_hit),
        "ttft_miss_ms": _lat_summary(stats.ttft_miss),
        "kv": engine.kv_block_stats(),
    }
    trace_fail = False
    if args.trace:
        trace_fail, rec["trace"] = _check_traces(args, _tr)
    emit(rec, args.out)

    if args.compare_serial:
        # batch=1 decode graph, default (unprefixed) state names: no
        # collision with the engine's "gen." state, weights shared
        dec_main, dec_start = fluid.Program(), fluid.Program()
        with fluid.program_guard(dec_main, dec_start):
            step1 = gpt.build_decode_step(cfg, batch=1,
                                          max_seq=args.max_seq)
        sstats, slat, sdur, souts = run_serial_generation(
            engine.exe, scope, dec_main, step1, reqs)
        srec = summarize_generation(
            "serial_baseline", slat, sstats.ttfts, sstats.inter,
            sstats.tokens, 0, sdur, common)
        wrong = sum(
            1 for i, toks in souts.items()
            if i in stats.outputs
            and [int(t) for t in stats.outputs[i]]
            != [int(t) for t in toks])
        srec["wrong_answers"] = wrong
        srec["compared_requests"] = sum(
            1 for i in souts if i in stats.outputs)
        emit(srec, args.out)
        if wrong:
            print(f"FAIL: {wrong} engine outputs diverge from the "
                  f"serial reference", file=sys.stderr)
            engine.stop()
            return 4
        if srec["tokens_per_s"]:
            speedup = rec["tokens_per_s"] / srec["tokens_per_s"]
            print(f"# continuous/serial tokens-per-second speedup: "
                  f"{speedup:.2f}x")

    engine.stop()
    if args.check_compiles and post > 0:
        print(f"FAIL: {post} compiles after generation warmup",
              file=sys.stderr)
        return 3
    if trace_fail:
        return 6
    return 0


def run_spec_generation(args):
    """--generate --spec-decode: the speculative-decoding A/B.

    Trains the tiny GPT on the cyclic-successor task first (seconds on
    CPU; greedy continuations become deterministic), then drives the
    SAME repetitive closed-loop traffic (make_spec_requests) through a
    spec-ON and a spec-OFF paged engine sharing the trained weights,
    and finally replays every request through the serial kv_generate
    reference for the exact-answer check. Emits one
    kind="spec_loadgen" record (schema: tools/validate_bench_json.py)
    carrying acceptance rate, effective tokens/step and the on/off
    tokens-per-second speedup. Exit 4 when any spec-on output diverges
    from the serial reference; 3 (--check-compiles) when either engine
    compiled anything post-warmup."""
    import paddle_tpu as fluid
    from paddle_tpu import monitor
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import GenerationEngine

    if args.url or args.rate > 0 or args.trace:
        print("--spec-decode is an in-process closed-loop A/B; "
              "--url/--rate/--trace are not supported", file=sys.stderr)
        return 2
    temperature = getattr(args, "temperature", 0.0) or 0.0
    vocab = args.vocab
    spec_k = args.spec_k if args.spec_k > 0 \
        else int(fluid.FLAGS.spec_decode_k)
    spec_ngram = int(fluid.FLAGS.spec_decode_ngram)
    cfg = gpt.gpt_small(vocab_size=vocab, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=args.max_seq,
                        dropout=0.0, use_flash=False)
    scope = fluid.Scope()
    train_main, train_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(train_main, train_start), \
            fluid.scope_guard(scope):
        # train at the FULL decode length: every positional-embedding
        # row a generation can reach must learn the task, or greedy
        # continuations drift off the cycle past the trained horizon
        # (tanking draft acceptance for long requests)
        t_seq = int(args.max_seq)
        loss, _, _ = gpt.build_train(cfg, batch=8, seq_len=t_seq,
                                     lr=5e-3)
        exe = fluid.Executor()
        exe.run(train_start)
        base = np.arange(t_seq) % vocab
        toks = np.stack([(base + i) % vocab
                         for i in range(8)]).astype(np.int64)
        for _ in range(40):
            exe.run(train_main, feed={"tokens": toks},
                    fetch_list=[loss])

    reqs = make_spec_requests(args.requests, vocab, args.max_prompt,
                              args.max_new_tokens, args.seed,
                              temperature=temperature)
    fluid.set_flags({"FLAGS_enable_monitor": True})

    def one_run(spec_on):
        monitor.STAT_RESET()
        eng = GenerationEngine(
            cfg, scope, exe=fluid.Executor(), max_slots=args.slots,
            max_seq=args.max_seq, default_timeout_ms=args.timeout_ms,
            block_size=(getattr(args, "block_size", 0) or None),
            spec_decode=spec_on, spec_k=spec_k)
        eng.start()
        stats = _GenStats()
        target = _GenEngineTarget(eng, stats)
        lat, errs, dur = run_closed(target, reqs, args.concurrency,
                                    args.timeout_ms)
        c = monitor.get_stats_snapshot()["counters"]
        post = eng.post_warmup_compiles()
        eng.stop()
        steps = int(c.get("serving.gen_steps", 0))
        side = {
            "duration_s": round(dur, 4),
            "errors": errs,
            "tokens": int(stats.tokens),
            "tokens_per_s": round(stats.tokens / dur, 2) if dur
            else 0.0,
            "gen_steps": steps,
            # batch-level: generated tokens per decode dispatch (> 1
            # needs either multi-slot occupancy or accepted drafts)
            "tokens_per_step": round(stats.tokens / steps, 3)
            if steps else None,
            "latency_ms": _lat_summary(lat),
            "post_warmup_compiles": post,
        }
        if spec_on:
            prop = int(c.get("serving.gen_spec_draft_proposed", 0))
            acc = int(c.get("serving.gen_spec_draft_accepted", 0))
            side.update({
                "spec_steps": int(c.get("serving.gen_spec_steps", 0)),
                "draft_proposed": prop,
                "draft_accepted": acc,
                "acceptance_rate": round(acc / prop, 4) if prop
                else None,
            })
        return side, stats

    base_side, _ = one_run(False)
    spec_side, spec_stats = one_run(True)

    # exact-answer reference: serial kv_generate over the same trained
    # weights (unprefixed batch=1 graph, no collision with gen. state)
    dec_main, dec_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(dec_main, dec_start):
        step1 = gpt.build_decode_step(cfg, batch=1,
                                      max_seq=args.max_seq)
    _, _, _, souts = run_serial_generation(
        fluid.Executor(), scope, dec_main, step1, reqs)
    wrong = sum(1 for i, toks in souts.items()
                if i in spec_stats.outputs
                and [int(t) for t in spec_stats.outputs[i]]
                != [int(t) for t in toks])
    compared = sum(1 for i in souts if i in spec_stats.outputs)

    off_tps = base_side["tokens_per_s"]
    rec = {
        "kind": "spec_loadgen",
        "mode": "closed",
        "requests": len(reqs),
        "wrong_answers": wrong,
        "compared_requests": compared,
        "speedup": round(spec_side["tokens_per_s"] / off_tps, 3)
        if off_tps else None,
        "spec": spec_side,
        "baseline": base_side,
        "config": {"concurrency": args.concurrency,
                   "slots": args.slots,
                   "max_prompt": args.max_prompt,
                   "max_new_tokens": args.max_new_tokens,
                   "max_seq": args.max_seq, "vocab": vocab,
                   "temperature": temperature,
                   "spec_k": spec_k, "spec_ngram": spec_ngram},
    }
    emit(rec, args.out)
    if wrong:
        print(f"FAIL: {wrong} spec-on outputs diverge from the serial "
              f"reference", file=sys.stderr)
        return 4
    post = (spec_side["post_warmup_compiles"]
            + base_side["post_warmup_compiles"])
    if args.check_compiles and post > 0:
        print(f"FAIL: {post} compiles after spec A/B warmup",
              file=sys.stderr)
        return 3
    return 0


def _chaos_retryable(e):
    from paddle_tpu.serving import OverloadedError, QueueFullError
    return isinstance(e, (OverloadedError, QueueFullError,
                          ConnectionError))


def run_chaos_closed(engine, requests, expected, concurrency,
                     timeout_ms, retries=0, call=None):
    """Closed-loop pass that also VERIFIES every successful response
    against the fault-free expected outputs: under chaos a request may
    fail (shed, timed out — that is degradation, allowed and counted)
    but a 200 carrying wrong numbers is a correctness bug (counted
    separately, never allowed).

    Accounting is by VERDICT, exactly one per request index: a request
    that sheds on one attempt and answers on a later one (client retry
    here, or router failover behind `call`) counts once, with its final
    outcome — never as both an error and an answer.

    `call(feed, timeout_ms) -> [arrays]` overrides the engine dispatch
    (the router mode routes through Router.predict); `retries` bounds
    client-side re-submissions after a retryable rejection."""
    verdicts = {}          # idx -> ("ok"|"wrong", latency_s) | ("error", None)
    lock = threading.Lock()
    it = iter(list(enumerate(requests)))
    if call is None:
        def call(feed, t):  # noqa: E306
            return engine.predict(feed, timeout_ms=t)

    def worker():
        while True:
            with lock:
                item = next(it, None)
            if item is None:
                return
            idx, feed = item
            t0 = time.perf_counter()
            outs = None
            attempt = 0
            while True:
                try:
                    outs = call(feed, timeout_ms)
                    break
                except Exception as e:  # noqa: BLE001 — shed/timeout
                    if attempt < retries and _chaos_retryable(e):
                        attempt += 1
                        time.sleep(0.01 * attempt)
                        continue
                    break
            if outs is None:
                with lock:
                    verdicts[idx] = ("error", None)
                continue
            dt = time.perf_counter() - t0
            ok = len(outs) == len(expected[idx]) and all(
                np.allclose(o, e, rtol=1e-4, atol=1e-5)
                for o, e in zip(outs, expected[idx]))
            with lock:
                verdicts[idx] = ("ok" if ok else "wrong", dt)

    threads = [threading.Thread(target=worker)
               for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dur = time.perf_counter() - t0
    latencies = [v[1] for v in verdicts.values() if v[1] is not None]
    errors = sum(1 for v in verdicts.values() if v[0] == "error")
    wrong = sum(1 for v in verdicts.values() if v[0] == "wrong")
    return latencies, errors, wrong, dur


def run_chaos(args):
    """--chaos: the graceful-degradation acceptance run. Baseline pass
    (faults off) for expected outputs + fault-free p99, then the same
    traffic with FLAGS_fault_spec armed. Exit 4 on any wrong answer or
    worker death, 5 when chaos p99 exceeds --chaos-p99-bound x the
    fault-free p99."""
    import paddle_tpu as fluid
    from paddle_tpu.resilience import reset_injector
    from paddle_tpu.serving import EngineConfig, ServingEngine

    if args.url:
        print("--chaos drives an in-process engine; --url is not "
              "supported", file=sys.stderr)
        return 2

    seq_buckets = tuple(int(s) for s in args.seq_buckets.split(","))
    feat = 6
    reqs = make_requests(args.requests, seq_buckets, feat, args.seed)

    fluid.set_flags({"FLAGS_fault_spec": ""})
    reset_injector()
    model_dir = args.model_dir or build_tiny_model(
        tempfile.mkdtemp(prefix="serving_chaos_"), feat)
    cfg = EngineConfig(model_dir,
                       max_batch_size=args.max_batch_size,
                       max_wait_us=args.max_wait_us,
                       queue_capacity=max(64, args.concurrency * 8),
                       default_timeout_ms=args.timeout_ms,
                       seq_buckets=seq_buckets,
                       warmup=True)
    engine = ServingEngine(cfg)
    engine.start()

    # fault-free ground truth, one request at a time (no batching
    # effects), through a predictor clone sharing the weights
    ref = engine.predictor.clone()
    expected = [ref.run_dict(feed) for feed in reqs]

    base_lat, base_errs, base_wrong, base_dur = run_chaos_closed(
        engine, reqs, expected, args.concurrency, args.timeout_ms)
    base_p99 = _percentile(sorted(v * 1e3 for v in base_lat), 0.99)

    fluid.set_flags({"FLAGS_fault_spec": args.fault_spec,
                     "FLAGS_fault_seed": args.seed})
    reset_injector()
    lat, errs, wrong, dur = run_chaos_closed(
        engine, reqs, expected, args.concurrency, args.timeout_ms)
    worker_deaths = sum(1 for w in engine._workers if not w.is_alive())
    fluid.set_flags({"FLAGS_fault_spec": ""})
    reset_injector()
    engine.stop()

    chaos_p99 = _percentile(sorted(v * 1e3 for v in lat), 0.99)
    inflation = (round(chaos_p99 / base_p99, 3)
                 if base_p99 and chaos_p99 else None)
    n = len(lat)
    rec = {
        "kind": "chaos_loadgen",
        "mode": "chaos",
        "requests": n,
        "errors": errs,
        "duration_s": round(dur, 4),
        "throughput_rps": round(n / dur, 2) if dur else 0.0,
        "latency_ms": _lat_summary(lat),
        "fault_spec": args.fault_spec,
        "wrong_answers": wrong + base_wrong,
        "worker_deaths": worker_deaths,
        "baseline_p99_ms": base_p99,
        "chaos_p99_ms": chaos_p99,
        "p99_inflation": inflation,
        "p99_bound": args.chaos_p99_bound,
        "config": {"concurrency": args.concurrency,
                   "max_batch_size": args.max_batch_size,
                   "max_wait_us": args.max_wait_us,
                   "seq_buckets": list(seq_buckets),
                   "baseline_errors": base_errs,
                   "seed": args.seed},
    }
    emit(rec, args.out)

    if rec["wrong_answers"] or worker_deaths:
        print(f"FAIL: {rec['wrong_answers']} wrong answers, "
              f"{worker_deaths} worker deaths under chaos",
              file=sys.stderr)
        return 4
    if inflation is not None and inflation > args.chaos_p99_bound:
        print(f"FAIL: chaos p99 {chaos_p99}ms is {inflation}x the "
              f"fault-free p99 {base_p99}ms (bound "
              f"{args.chaos_p99_bound}x)", file=sys.stderr)
        return 5
    return 0


def run_router(args):
    """--router N: the multi-replica acceptance run
    (`kind="router_loadgen"` records). N warmed in-process replicas go
    behind the serving Router; the run measures closed-loop throughput
    with 1 registered replica then with all N (the ~linear-scaling
    smoke — a deterministic per-batch service time injected via
    `slow_step` makes the ratio machine-independent), and optionally:

    * --preempt-drill: deregister+resume one replica mid-load; any
      client-visible error while another replica is healthy fails the
      run (exit 4).
    * --hot-swap: warm a v2 standby under load, flip, drain v1 —
      zero dropped requests and zero standby post-warmup compiles or
      exit 4.
    * --chaos: hard-kill one replica mid-pass (stop(drain=False), no
      drain) and rely on failover; wrong answers or non-victim worker
      deaths exit 4, p99 over --chaos-p99-bound x the fault-free p99
      exits 5.

    Every response in every pass is verified against fault-free
    expected outputs with exactly-once per-request verdicts. Exit 7
    when the 1->N throughput ratio lands below --scaling-min (> 0)."""
    import paddle_tpu as fluid
    from paddle_tpu.resilience import reset_injector
    from paddle_tpu.serving import (EngineConfig, Replica, Router,
                                    ServingEngine)

    if args.url or args.generate:
        print("--router drives in-process predict replicas; --url and "
              "--generate are not supported", file=sys.stderr)
        return 2
    n_rep = args.router
    seq_buckets = tuple(int(s) for s in args.seq_buckets.split(","))
    feat = 6
    reqs = make_requests(args.requests, seq_buckets, feat, args.seed)
    # closed-loop scaling needs every replica's queue deep enough to
    # fill batches in EACH of the ~3 shape-signature groups the mixed
    # seq lengths land in, even after the load splits N ways
    conc = max(args.concurrency,
               4 * n_rep * args.max_batch_size + n_rep)

    fluid.set_flags({"FLAGS_fault_spec": ""})
    reset_injector()
    model_dir = args.model_dir or build_tiny_model(
        tempfile.mkdtemp(prefix="serving_router_"), feat)
    all_engines = []

    def make_engine(start=True):
        cfg = EngineConfig(model_dir,
                           max_batch_size=args.max_batch_size,
                           max_wait_us=args.max_wait_us,
                           queue_capacity=max(64, conc * 8),
                           default_timeout_ms=args.timeout_ms,
                           seq_buckets=seq_buckets,
                           warmup=True)
        e = ServingEngine(cfg)
        if start:
            e.start()
        all_engines.append(e)
        return e

    engines = [make_engine() for _ in range(n_rep)]
    names = engines[0].output_names()
    # fault-free ground truth: every replica loads the same saved
    # weights, so one clone references them all
    ref = engines[0].predictor.clone()
    expected = [ref.run_dict(feed) for feed in reqs]

    if args.service_ms > 0:
        # deterministic per-batch service time: slow_step with no p=
        # fires on EVERY batch at the "serving" fault site, sleeping
        # inside each engine's infer lock — so service parallelizes
        # across replicas and the 1->N ratio is machine-independent
        fluid.set_flags(
            {"FLAGS_fault_spec":
             f"slow_step:ms={args.service_ms}:site=serving"})
        reset_injector()

    replicas = [Replica(f"r{i}", engine=e, version="v1")
                for i, e in enumerate(engines)]

    def router_call(router):
        def call(feed, t):
            outs = router.predict(feed, timeout_ms=t)
            return [outs[n] for n in names]
        return call

    # -- pass 1: one registered replica (the scaling denominator) ------
    r1 = Router([replicas[0]], start_probe=False)
    lat1, err1, wrong1, dur1 = run_chaos_closed(
        None, reqs, expected, conc, args.timeout_ms,
        retries=2, call=router_call(r1))
    r1.close()
    rps_1 = round(len(lat1) / dur1, 2) if dur1 else 0.0

    # -- pass 2: all N replicas (the main record + chaos baseline) -----
    router = Router(replicas, probe_interval_s=0.2)
    call_n = router_call(router)
    lat_n, err_n, wrong_n, dur_n = run_chaos_closed(
        None, reqs, expected, conc, args.timeout_ms,
        retries=2, call=call_n)
    rps_n = round(len(lat_n) / dur_n, 2) if dur_n else 0.0
    ratio = round(rps_n / rps_1, 3) if rps_1 else None

    wrong_total = wrong1 + wrong_n
    hard_fail = []

    # -- preemption drill ----------------------------------------------
    preempt_rec = None
    if args.preempt_drill and n_rep >= 2:
        res = {}

        def _pload():
            res["r"] = run_chaos_closed(
                None, reqs, expected, conc, args.timeout_ms,
                retries=2, call=call_n)

        th = threading.Thread(target=_pload)
        th.start()
        time.sleep(max(0.05, dur_n * 0.25))
        router.preempt("r1")
        time.sleep(max(0.05, dur_n * 0.25))
        router.resume("r1")
        th.join()
        _, errs_p, wrong_p, _ = res["r"]
        wrong_total += wrong_p
        preempt_rec = {"replica": "r1", "client_errors": errs_p,
                       "wrong_answers": wrong_p, "resumed": True}
        if errs_p or wrong_p:
            hard_fail.append(
                f"preempt drill: {errs_p} client errors / {wrong_p} "
                f"wrong answers while other replicas were healthy")

    # -- hot-swap drill ------------------------------------------------
    hot_rec = None
    if args.hot_swap:
        stop_evt = threading.Event()
        lock = threading.Lock()
        counter, totals, bad = [0], [0], [0]

        def _hs_worker():
            while not stop_evt.is_set():
                with lock:
                    idx = counter[0] % len(reqs)
                    counter[0] += 1
                try:
                    outs = call_n(reqs[idx], args.timeout_ms)
                    ok = len(outs) == len(expected[idx]) and all(
                        np.allclose(o, e, rtol=1e-4, atol=1e-5)
                        for o, e in zip(outs, expected[idx]))
                except Exception:  # noqa: BLE001
                    ok = False
                with lock:
                    totals[0] += 1
                    if not ok:
                        bad[0] += 1

        workers = [threading.Thread(target=_hs_worker)
                   for _ in range(conc)]
        for w in workers:
            w.start()
        # standby warms its full ladder here, WHILE v1 keeps serving
        standby = Replica("r0v2", engine=make_engine(start=False),
                          version="v2")
        swap = router.hot_swap("r0", standby)
        time.sleep(max(0.1, dur_n * 0.25))  # post-flip load on v2
        stop_evt.set()
        for w in workers:
            w.join()
        standby_compiles = standby.post_warmup_compiles()
        hot_rec = {"swapped": bool(swap["swapped"]),
                   "old": swap["old"], "new": swap["new"],
                   "requests": totals[0],
                   "dropped_requests": bad[0],
                   "drained": bool(swap["drained"]),
                   "standby_post_warmup_compiles": standby_compiles}
        if bad[0]:
            hard_fail.append(f"hot-swap drill dropped {bad[0]} of "
                             f"{totals[0]} requests")
        if standby_compiles:
            hard_fail.append(f"standby compiled {standby_compiles} "
                             f"time(s) after warmup")
        if not swap["drained"]:
            hard_fail.append("old replica not drained before stop")

    # -- chaos: hard-kill one replica mid-run --------------------------
    chaos_rec = None
    p99_over = False
    if args.chaos:
        base_p99 = _percentile(sorted(v * 1e3 for v in lat_n), 0.99)
        victim = router.replicas()[-1]
        red0 = router.redispatches

        def _killer():
            time.sleep(max(0.05, dur_n * 0.3))
            victim.engine.stop(drain=False)

        kth = threading.Thread(target=_killer)
        kth.start()
        lat_c, err_c, wrong_c, dur_c = run_chaos_closed(
            None, reqs, expected, conc, args.timeout_ms,
            retries=3, call=call_n)
        kth.join()
        wrong_total += wrong_c
        chaos_p99 = _percentile(sorted(v * 1e3 for v in lat_c), 0.99)
        inflation = (round(chaos_p99 / base_p99, 3)
                     if base_p99 and chaos_p99 else None)
        deaths = sum(1 for r in router.replicas() if r is not victim
                     for w in r.engine._workers if not w.is_alive())
        chaos_rec = {"killed_replica": victim.name,
                     "requests": len(lat_c),
                     "client_errors": err_c,
                     "wrong_answers": wrong_c,
                     "worker_deaths": deaths,
                     "redispatches": router.redispatches - red0,
                     "baseline_p99_ms": base_p99,
                     "chaos_p99_ms": chaos_p99,
                     "p99_inflation": inflation,
                     "p99_bound": args.chaos_p99_bound}
        if wrong_c or deaths:
            hard_fail.append(f"chaos: {wrong_c} wrong answers, "
                             f"{deaths} non-victim worker deaths")
        p99_over = inflation is not None \
            and inflation > args.chaos_p99_bound

    fluid.set_flags({"FLAGS_fault_spec": ""})
    reset_injector()
    router.close()
    for e in all_engines:
        try:
            e.stop(drain=False, timeout=5.0)
        except Exception:  # noqa: BLE001 — chaos victims already down
            pass

    rec = {
        "kind": "router_loadgen",
        "mode": "closed",
        "replicas": n_rep,
        "requests": len(lat_n),
        "errors": err_n,
        "wrong_answers": wrong_total,
        "duration_s": round(dur_n, 4),
        "throughput_rps": rps_n,
        "latency_ms": _lat_summary(lat_n),
        "redispatches": router.redispatches,
        "shed": router.shed,
        "scaling": {"rps_1": rps_1, "rps_n": rps_n, "ratio": ratio,
                    "min_ratio": args.scaling_min,
                    "pass1_errors": err1},
        "config": {"concurrency": conc,
                   "max_batch_size": args.max_batch_size,
                   "max_wait_us": args.max_wait_us,
                   "seq_buckets": list(seq_buckets),
                   "service_ms": args.service_ms,
                   "seed": args.seed},
    }
    if preempt_rec:
        rec["preempt"] = preempt_rec
    if hot_rec:
        rec["hot_swap"] = hot_rec
    if chaos_rec:
        rec["chaos"] = chaos_rec
    emit(rec, args.out)

    if wrong_total or hard_fail:
        for msg in hard_fail or [f"{wrong_total} wrong answers"]:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 4
    if p99_over:
        print(f"FAIL: chaos p99 {chaos_rec['chaos_p99_ms']}ms is "
              f"{chaos_rec['p99_inflation']}x the fault-free p99 "
              f"{chaos_rec['baseline_p99_ms']}ms (bound "
              f"{args.chaos_p99_bound}x)", file=sys.stderr)
        return 5
    if args.scaling_min > 0 and (ratio is None
                                 or ratio < args.scaling_min):
        print(f"FAIL: 1->{n_rep} replica throughput ratio {ratio} "
              f"below --scaling-min {args.scaling_min}",
              file=sys.stderr)
        return 7
    return 0


def run_disagg(args):
    """--router N --disagg: the disaggregated prefill/decode fleet
    acceptance run (`kind="disagg_loadgen"` records).

    Two passes over IDENTICAL shared-prefix generation traffic, each
    against a FRESH fleet of N real subprocess replicas
    (tools/serving_replica.py — separate processes, HTTP wire,
    loaded-from-npz identical weights):

    * baseline: N symmetric (role=unified) workers behind a plain
      Router — every worker re-prefills every prefix it meets.
    * disagg: --disagg-prefill prefill workers + the rest decode
      workers behind Router(disagg=True) — prefixes are prefilled
      once, shipped over /v1/kv/export -> /v1/kv/adopt, and reused via
      the fleet prefix store.

    --service-ms injects a deterministic per-prefill-chunk delay
    (slow_step at the gen_prefill fault site, armed via FLAGS env in
    the worker processes) so the TTFT comparison is
    machine-independent, exactly like the router scaling run. Gates:
    any wrong answer vs the in-process serial reference exits 4; any
    worker post-warmup compile exits 3 (--check-compiles); disagg
    shared-cohort TTFT p99 not beating baseline exits 5 (active at
    shared-prefix-frac >= 0.6); the one-tree trace audit
    (request -> prefill/fetch/decode spans, trace_report consistency)
    exits 6."""
    import shutil
    import signal as _signal
    import subprocess
    import urllib.request

    import paddle_tpu as fluid
    from paddle_tpu import monitor as _mon
    from paddle_tpu import trace as _tr
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import GenerationEngine, Replica, Router

    if args.url or args.chaos:
        print("--disagg races local subprocess replicas; --url and "
              "--chaos are not supported", file=sys.stderr)
        return 2
    n_rep = args.router
    n_p = max(1, args.disagg_prefill)
    n_d = n_rep - n_p
    if n_d < 1:
        print(f"--disagg needs >= 1 decode worker (--router {n_rep} "
              f"--disagg-prefill {n_p})", file=sys.stderr)
        return 2

    block_size = args.block_size or 8
    prefix_frac = args.shared_prefix_frac \
        if args.shared_prefix_frac > 0 else 0.75
    prefix_len = args.shared_prefix_len or (
        (max(args.max_prompt - 1, 1) // block_size) * block_size)
    if prefix_len < block_size:
        print(f"--disagg needs at least one full shared block "
              f"(prefix_len {prefix_len} < block_size {block_size}; "
              f"raise --max-prompt)", file=sys.stderr)
        return 2
    reqs = make_gen_requests(args.requests, args.vocab,
                             args.max_prompt, args.max_new_tokens,
                             args.seed, shared_prefix_frac=prefix_frac,
                             shared_prefix_len=prefix_len,
                             temperature=args.temperature)

    tmpdir = tempfile.mkdtemp(prefix="serving_disagg_")

    # -- the weights every process shares (npz under training-graph
    # names; each replica loads them, so all fleets decode identically)
    cfg = gpt.gpt_small(vocab_size=args.vocab, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=args.max_seq,
                        dropout=0.0, use_flash=False)
    scope = fluid.Scope()
    seed_engine = GenerationEngine(cfg, scope, max_slots=args.slots,
                                   max_seq=args.max_seq, paged=True,
                                   block_size=block_size)
    seed_engine.init_scope()  # scratch weights; never start()ed
    weights = {}
    for name in scope.names():
        if name.startswith("gen."):
            continue  # decode state is per-process, not a weight
        v = scope.get(name)
        if v is not None:
            weights[name] = np.asarray(v)
    npz = os.path.join(tmpdir, "weights.npz")
    np.savez(npz, **weights)

    # -- serial exact-answer reference (in-process, batch=1 graph on
    # the same scope — the wrong-answers oracle for BOTH passes)
    dec_main, dec_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(dec_main, dec_start):
        step1 = gpt.build_decode_step(cfg, batch=1,
                                      max_seq=args.max_seq)
    _, _, _, souts = run_serial_generation(
        seed_engine.exe, scope, dec_main, step1, reqs)

    replica_py = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "serving_replica.py")
    worker_env = dict(os.environ)
    worker_env.setdefault("JAX_PLATFORMS", "cpu")
    if args.service_ms > 0:
        # deterministic per-prefill-chunk service time in EVERY worker
        # of BOTH fleets: prefill cost dominates and is identical
        # across machines, so where prefill *runs* (the thing disagg
        # changes) decides the TTFT comparison
        worker_env["FLAGS_fault_spec"] = \
            f"slow_step:ms={args.service_ms}:site=gen_prefill"

    def spawn_fleet(tag, n):
        procs = []
        for i in range(n):
            name = f"{tag}{i}"
            pf = os.path.join(tmpdir, f"{name}.port")
            log = open(os.path.join(tmpdir, f"{name}.log"), "w")
            cmd = [sys.executable, replica_py, "--weights", npz,
                   "--vocab", str(args.vocab),
                   "--max-seq", str(args.max_seq),
                   "--slots", str(args.slots),
                   "--block-size", str(block_size),
                   "--timeout-ms", str(args.timeout_ms),
                   "--port-file", pf]
            p = subprocess.Popen(cmd, stdout=log,
                                 stderr=subprocess.STDOUT,
                                 env=worker_env)
            procs.append({"proc": p, "port_file": pf, "log": log,
                          "name": name})
        deadline = time.monotonic() + 300.0
        for w in procs:
            while not os.path.exists(w["port_file"]):
                if w["proc"].poll() is not None:
                    w["log"].flush()
                    with open(w["log"].name) as lf:
                        tail = "".join(lf.readlines()[-15:])
                    raise RuntimeError(
                        f"replica {w['name']} died during warmup "
                        f"(rc={w['proc'].returncode}):\n{tail}")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"replica {w['name']} not ready in 300s")
                time.sleep(0.1)
            with open(w["port_file"]) as f:
                w["url"] = f"http://127.0.0.1:{int(f.read().strip())}"
        return procs

    def worker_compiles(url):
        with urllib.request.urlopen(url + "/healthz",
                                    timeout=5.0) as r:
            body = json.loads(r.read() or b"{}")
        return int(body.get("engines", {}).get("generate", {})
                   .get("post_warmup_compiles") or 0)

    def stop_fleet(procs):
        clean = 0
        for w in procs:
            if w["proc"].poll() is None:
                w["proc"].send_signal(_signal.SIGTERM)
        for w in procs:
            try:
                rc = w["proc"].wait(timeout=30)
            except subprocess.TimeoutExpired:
                w["proc"].kill()
                rc = w["proc"].wait()
            if rc == 0:
                clean += 1
            w["log"].close()
        return clean

    def drive(router, traced):
        """Closed loop: --concurrency threads, each one request in
        flight, straight into Router.generate. Client-side TTFT proxy:
        measured e2e minus the engine-reported decode tail, so router
        + transfer overhead lands in TTFT (where it belongs)."""
        pending = list(reqs)
        results = {}
        errors = [0]
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    if not pending:
                        return
                    req = pending.pop(0)
                payload = {"prompt": req["prompt"],
                           "max_new_tokens": req["max_new_tokens"],
                           "temperature": req.get("temperature", 0.0),
                           "seed": req["seed"],
                           "timeout_ms": args.timeout_ms}
                root = None
                t0 = time.perf_counter()
                try:
                    if traced:
                        root = _tr.start_span(
                            "request", attrs={"idx": req["idx"]})
                        with _tr.use_span(root):
                            out = router.generate(payload)
                    else:
                        out = router.generate(payload)
                except Exception as e:  # noqa: BLE001
                    if root is not None:
                        _tr.finish_trace(
                            root, error=f"{type(e).__name__}: {e}",
                            e2e_ms=(time.perf_counter() - t0) * 1e3)
                    with lock:
                        errors[0] += 1
                    continue
                e2e = time.perf_counter() - t0
                if root is not None:
                    _tr.finish_trace(root, e2e_ms=e2e * 1e3)
                eng_e2e = (out.get("e2e_ms") or 0.0) / 1e3
                eng_ttft = (out.get("ttft_ms") or 0.0) / 1e3
                ttft = max(0.0, e2e - max(0.0, eng_e2e - eng_ttft))
                with lock:
                    results[req["idx"]] = {
                        "e2e": e2e, "ttft": ttft,
                        "tokens": list(out.get("tokens", ())),
                        "shared": bool(req["shared"])}

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results, errors[0], time.perf_counter() - t0

    def wrong_count(results):
        return sum(1 for i, r in results.items()
                   if i in souts and [int(t) for t in r["tokens"]]
                   != [int(t) for t in souts[i]])

    def pass_summary(results, errors, dur):
        vals = list(results.values())
        lat = [r["e2e"] for r in vals]
        tokens = sum(len(r["tokens"]) for r in vals)
        return {
            "requests": len(vals), "errors": errors,
            "duration_s": round(dur, 4),
            "throughput_rps": round(len(vals) / dur, 2) if dur else 0.0,
            "tokens": tokens,
            "tokens_per_s": round(tokens / dur, 2) if dur else 0.0,
            "latency_ms": _lat_summary(lat),
            "ttft_ms": _lat_summary([r["ttft"] for r in vals]),
            "ttft_shared_ms": _lat_summary(
                [r["ttft"] for r in vals if r["shared"]]),
            "ttft_miss_ms": _lat_summary(
                [r["ttft"] for r in vals if not r["shared"]]),
        }

    fleet = []
    try:
        # ---- pass A: symmetric baseline (N unified workers) ----------
        fleet = spawn_fleet("u", n_rep)
        router_a = Router(
            [Replica(w["name"], url=w["url"], role="unified")
             for w in fleet],
            probe_interval_s=0.2, disagg=False)
        res_a, err_a, dur_a = drive(router_a, traced=False)
        compiles_a = sum(worker_compiles(w["url"]) for w in fleet)
        router_a.close()
        clean_a = stop_fleet(fleet)
        wrong_a = wrong_count(res_a)
        base = pass_summary(res_a, err_a, dur_a)
        base["post_warmup_compiles"] = compiles_a
        base["clean_exits"] = clean_a

        # ---- pass B: disaggregated fleet (fresh processes) -----------
        fluid.set_flags({"FLAGS_enable_trace": True,
                         "FLAGS_trace_sample": 1.0,
                         "FLAGS_enable_monitor": True})
        _mon.STAT_RESET()
        _tr.reset()
        fleet = spawn_fleet("p", n_p) + spawn_fleet("d", n_d)
        reps_b = [Replica(w["name"], url=w["url"],
                          role=("prefill" if w["name"].startswith("p")
                                else "decode"))
                  for w in fleet]
        router_b = Router(reps_b, probe_interval_s=0.2, disagg=True)
        res_b, err_b, dur_b = drive(router_b, traced=True)
        counters = _mon.get_stats_snapshot().get("counters", {})
        store_stats = router_b.prefix_store.stats()
        compiles_b = sum(worker_compiles(w["url"]) for w in fleet)
        router_b.close()
        clean_b = stop_fleet(fleet)
        fleet = []
        wrong_b = wrong_count(res_b)
    finally:
        for w in fleet:
            if w["proc"].poll() is None:
                w["proc"].kill()
        shutil.rmtree(tmpdir, ignore_errors=True)

    # ---- trace audit: one tree per request, router->prefill->fetch->
    # decode spans, trace_report consistency clean --------------------
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report as trp
    spans = _tr.drain_spans()
    trace_out = args.trace_out
    if not trace_out:
        base_p = args.out or os.path.join(tempfile.gettempdir(),
                                          "disagg_loadgen.jsonl")
        trace_out = os.path.splitext(os.path.abspath(base_p))[0] \
            + ".spans.jsonl"
    try:
        os.remove(trace_out)
    except OSError:
        pass
    _tr.export_jsonl(trace_out, spans)
    by_id, children = trp.build_index(spans)
    roots = [r for r in trp.trace_roots(spans, by_id)
             if r["name"] in trp.REQUEST_ROOTS]
    n_no_decode = 0
    n_with_transfer = 0
    for root in roots:
        if root.get("status") != "ok":
            continue
        names = {s["name"] for s in trp._walk(root, children)}
        if "decode" not in names:
            n_no_decode += 1
        if "prefill" in names and "fetch" in names:
            n_with_transfer += 1
    _, violations = trp.check_consistency(spans, children)
    trace_fail = (not roots) or n_no_decode or violations \
        or n_with_transfer == 0
    if not roots:
        print("FAIL: disagg pass kept no request traces",
              file=sys.stderr)
    if n_no_decode:
        print(f"FAIL: {n_no_decode} request trace(s) missing the "
              f"decode span", file=sys.stderr)
    if n_with_transfer == 0 and roots:
        print("FAIL: no request trace carries the prefill+fetch "
              "transfer spans", file=sys.stderr)
    for v in violations[:10]:
        print(f"FAIL: trace consistency: {v}", file=sys.stderr)

    dis = pass_summary(res_b, err_b, dur_b)
    dis["post_warmup_compiles"] = compiles_b
    dis["clean_exits"] = clean_b
    b99 = base["ttft_shared_ms"]["p99"] \
        if base["ttft_shared_ms"] else None
    d99 = dis["ttft_shared_ms"]["p99"] \
        if dis["ttft_shared_ms"] else None
    ratio = round(d99 / b99, 3) if b99 and d99 is not None else None

    rec = {
        "kind": "disagg_loadgen",
        "mode": "closed",
        "replicas": {"prefill": n_p, "decode": n_d,
                     "baseline_unified": n_rep},
        "requests": dis["requests"],
        "errors": err_a + err_b,
        "wrong_answers": wrong_a + wrong_b,
        "duration_s": dis["duration_s"],
        "throughput_rps": dis["throughput_rps"],
        "tokens": dis["tokens"],
        "tokens_per_s": dis["tokens_per_s"],
        "latency_ms": dis["latency_ms"],
        "ttft_ms": dis["ttft_ms"],
        "ttft_shared_ms": dis["ttft_shared_ms"],
        "ttft_miss_ms": dis["ttft_miss_ms"],
        "ttft_shared_p99_ratio": ratio,
        "post_warmup_compiles": compiles_a + compiles_b,
        "baseline": base,
        "transfer": {
            "requests": int(counters.get(
                "serving.disagg_requests", 0)),
            "prefix_reuse": int(counters.get(
                "serving.disagg_prefix_reuse", 0)),
            "fallbacks": int(counters.get(
                "serving.disagg_fallbacks", 0)),
            "blocks": int(counters.get("serving.kv_xfer_blocks", 0)),
            "bytes": int(counters.get("serving.kv_xfer_bytes", 0)),
            "fleet_store": store_stats,
        },
        "trace": {"out": trace_out, "spans": len(spans),
                  "requests": len(roots),
                  "with_transfer": n_with_transfer,
                  "missing_decode": n_no_decode,
                  "consistency_violations": len(violations)},
        "config": {"concurrency": args.concurrency,
                   "slots": args.slots,
                   "max_prompt": args.max_prompt,
                   "max_new_tokens": args.max_new_tokens,
                   "max_seq": args.max_seq, "vocab": args.vocab,
                   "block_size": block_size,
                   "shared_prefix_frac": prefix_frac,
                   "shared_prefix_len": prefix_len,
                   "service_ms": args.service_ms,
                   "seed": args.seed},
    }
    emit(rec, args.out)

    if rec["wrong_answers"]:
        print(f"FAIL: {rec['wrong_answers']} outputs diverge from the "
              f"serial reference", file=sys.stderr)
        return 4
    if args.check_compiles and rec["post_warmup_compiles"]:
        print(f"FAIL: {rec['post_warmup_compiles']} post-warmup "
              f"compiles across the fleets", file=sys.stderr)
        return 3
    if prefix_frac >= 0.6 and b99 and d99 is not None and d99 > b99:
        print(f"FAIL: disagg shared-cohort TTFT p99 {d99}ms does not "
              f"beat the symmetric baseline {b99}ms", file=sys.stderr)
        return 5
    if trace_fail:
        return 6
    return 0


def emit(rec, out_path):
    print(json.dumps(rec))
    if out_path:
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="drive a running HTTP front end "
                                  "instead of an in-process engine")
    ap.add_argument("--model-dir", help="saved inference model for the "
                                        "in-process engine (default: "
                                        "build a tiny classifier)")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="open-loop only: cap the run; 0 = run the "
                         "request count")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrivals per second (0 = closed "
                         "loop)")
    ap.add_argument("--max-batch-size", type=int, default=8)
    ap.add_argument("--max-wait-us", type=int, default=2000)
    ap.add_argument("--seq-buckets", default="8,16,32",
                    help="comma-separated seq bucket ladder")
    ap.add_argument("--timeout-ms", type=float, default=10000.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the bucket-ladder warmup pass (baseline "
                         "for the compile-count comparison)")
    ap.add_argument("--compare-serial", action="store_true")
    ap.add_argument("--check-compiles", action="store_true",
                    help="exit 3 if the engine executor compiled "
                         "anything after warmup")
    ap.add_argument("--out", help="append JSONL records here")
    ap.add_argument("--generate", action="store_true",
                    help="generation workload through the "
                         "continuous-batching GenerationEngine")
    ap.add_argument("--slots", type=int, default=4,
                    help="generation decode slots (the fixed batch of "
                         "the one compiled decode step)")
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--max-prompt", type=int, default=8,
                    help="prompts are drawn with mixed lengths in "
                         "[1, max-prompt]")
    ap.add_argument("--max-seq", type=int, default=32,
                    help="generation KV-cache length")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="generation sampling temperature, honored by "
                         "the engine, HTTP and serial-reference paths "
                         "alike (0 = greedy); with per-request seeds "
                         "--compare-serial stays exact at any value")
    ap.add_argument("--spec-decode", action="store_true",
                    help="generation only: speculative-decoding A/B — "
                         "spec-on vs spec-off engines over the same "
                         "repetitive traffic plus the serial exact-"
                         "answer reference (kind=spec_loadgen; exit 4 "
                         "on divergence)")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="draft tokens per slot per verify step for "
                         "--spec-decode (0 = FLAGS_spec_decode_k)")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.0,
                    help="fraction of generation requests opening with "
                         "one fixed shared prefix (the prefix-cache "
                         "workload); report splits TTFT hit vs miss")
    ap.add_argument("--shared-prefix-len", type=int, default=0,
                    help="shared prefix length in tokens (0 = auto: "
                         "largest whole-block prefix < max-prompt)")
    ap.add_argument("--block-size", type=int, default=0,
                    help="KV block size for the paged engine "
                         "(0 = FLAGS_gen_kv_block_size)")
    ap.add_argument("--slab", action="store_true",
                    help="force the contiguous slab KV layout "
                         "(paged=False) regardless of FLAGS_gen_paged_kv")
    ap.add_argument("--trace", action="store_true",
                    help="generation only: arm FLAGS_enable_trace, dump "
                         "kept spans to --trace-out and assert complete "
                         "span trees + critical-path consistency "
                         "(exit 6 on violation)")
    ap.add_argument("--trace-out",
                    help="span dump path (default: <out>.spans.jsonl)")
    ap.add_argument("--trace-sample", type=float, default=1.0,
                    help="FLAGS_trace_sample for the --trace run "
                         "(default 1.0 so every tree is auditable)")
    ap.add_argument("--chaos", action="store_true",
                    help="fault-injection acceptance run: baseline "
                         "pass, then the same traffic under "
                         "--fault-spec; exit 4 on wrong answers or "
                         "worker deaths, 5 on p99 over bound")
    ap.add_argument("--fault-spec",
                    default="transient_fail:p=0.05,step_nan:p=0.01",
                    help="FLAGS_fault_spec armed for the chaos pass")
    ap.add_argument("--chaos-p99-bound", type=float, default=50.0,
                    help="max allowed chaos-p99 / fault-free-p99 ratio")
    ap.add_argument("--router", type=int, default=0,
                    help="multi-replica mode: N in-process replicas "
                         "behind the serving Router; records 1->N "
                         "throughput scaling (kind=router_loadgen). "
                         "Combine with --chaos for the replica-kill "
                         "failover run, --hot-swap / --preempt-drill "
                         "for the elasticity drills")
    ap.add_argument("--service-ms", type=float, default=20.0,
                    help="router mode: deterministic per-batch service "
                         "time injected at the serving fault site so "
                         "the scaling ratio is machine-independent "
                         "(0 = none)")
    ap.add_argument("--scaling-min", type=float, default=0.0,
                    help="router mode: minimum required rps_N/rps_1 "
                         "ratio; exit 7 below it (0 = record only)")
    ap.add_argument("--hot-swap", action="store_true",
                    help="router mode: v1->v2 hot-swap drill under "
                         "load (exit 4 on any dropped request or "
                         "standby post-warmup compile)")
    ap.add_argument("--preempt-drill", action="store_true",
                    help="router mode: preempt+resume one replica "
                         "under load; exit 4 on any client-visible "
                         "error")
    ap.add_argument("--disagg", action="store_true",
                    help="router mode: disaggregated prefill/decode "
                         "fleet acceptance run across real subprocess "
                         "replicas — --disagg-prefill prefill workers "
                         "+ rest decode, KV blocks shipped over "
                         "/v1/kv/export->adopt, vs a symmetric "
                         "baseline (kind=disagg_loadgen)")
    ap.add_argument("--disagg-prefill", type=int, default=1,
                    help="disagg mode: prefill workers out of "
                         "--router N (rest are decode workers)")
    args = ap.parse_args(argv)

    if args.router:
        if args.disagg:
            return run_disagg(args)
        return run_router(args)
    if args.chaos:
        return run_chaos(args)
    if args.generate:
        if args.spec_decode:
            return run_spec_generation(args)
        return run_generation(args)

    seq_buckets = tuple(int(s) for s in args.seq_buckets.split(","))
    feat = 6
    reqs = make_requests(args.requests, seq_buckets, feat, args.seed)
    common = {"concurrency": args.concurrency, "rate": args.rate,
              "max_batch_size": args.max_batch_size,
              "max_wait_us": args.max_wait_us,
              "seq_buckets": list(seq_buckets),
              "warmup": not args.no_warmup}

    rc = 0
    if args.url:
        target = _HTTPTarget(args.url)
        if args.rate > 0:
            if args.duration > 0:
                reqs = reqs[:max(1, int(args.rate * args.duration))]
            lat, errs, dur = run_open(target, reqs, args.rate,
                                      args.timeout_ms)
            rec = summarize("open", lat, errs, dur, common)
        else:
            lat, errs, dur = run_closed(target, reqs, args.concurrency,
                                        args.timeout_ms)
            rec = summarize("closed", lat, errs, dur, common)
        emit(rec, args.out)
        return rc

    from paddle_tpu.serving import EngineConfig, ServingEngine

    model_dir = args.model_dir or build_tiny_model(
        tempfile.mkdtemp(prefix="serving_loadgen_"), feat)
    cfg = EngineConfig(model_dir,
                       max_batch_size=args.max_batch_size,
                       max_wait_us=args.max_wait_us,
                       queue_capacity=max(64, args.concurrency * 8),
                       default_timeout_ms=args.timeout_ms,
                       seq_buckets=seq_buckets,
                       warmup=not args.no_warmup)
    engine = ServingEngine(cfg)
    engine.start()
    misses_after_warmup = engine.cache_stats()["misses"]

    target = _EngineTarget(engine)
    if args.rate > 0:
        if args.duration > 0:
            reqs = reqs[:max(1, int(args.rate * args.duration))]
        lat, errs, dur = run_open(target, reqs, args.rate,
                                  args.timeout_ms)
        rec = summarize("open", lat, errs, dur, common)
    else:
        lat, errs, dur = run_closed(target, reqs, args.concurrency,
                                    args.timeout_ms)
        rec = summarize("closed", lat, errs, dur, common)
    stats = engine.cache_stats()
    rec["cache"] = {"misses_after_warmup": misses_after_warmup,
                    "misses_total": stats["misses"],
                    "post_warmup_compiles":
                        stats["misses"] - misses_after_warmup}
    emit(rec, args.out)

    if args.compare_serial:
        ref = engine.predictor.clone()  # shares weights + compile cache
        misses_before_serial = engine.cache_stats()["misses"]
        slat, serrs, sdur = run_serial_baseline(ref, reqs)
        srec = summarize("serial_baseline", slat, serrs, sdur, common)
        # the batcher-off baseline feeds RAW shapes, so every novel
        # (1, seq) pair is a fresh XLA specialization — the recompile
        # pathology the bucket ladder exists to prevent
        srec["cache"] = {"serial_compiles":
                         engine.cache_stats()["misses"]
                         - misses_before_serial}
        emit(srec, args.out)
        if srec["throughput_rps"]:
            speedup = rec["throughput_rps"] / srec["throughput_rps"]
            print(f"# batched/serial speedup: {speedup:.2f}x")

    engine.stop()
    if args.check_compiles and rec["cache"]["post_warmup_compiles"] > 0:
        print(f"FAIL: {rec['cache']['post_warmup_compiles']} compiles "
              f"after warmup (warmup={not args.no_warmup})",
              file=sys.stderr)
        rc = 3
    return rc


if __name__ == "__main__":
    sys.exit(main())

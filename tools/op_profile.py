"""Per-op profile table: framework op types, not raw HLO names.

Usage:
    python tools/op_profile.py [bert|resnet50|gpt|transformer|deeplab]
                               [--cpu] [--tiny] [--steps N] [--top N]
                               [--by-op] [--log PATH]

Builds the selected bench model (same BENCH_* env config as bench.py),
captures a jax.profiler trace around a few steps PLUS the step's
compiled HLO — whose per-instruction op_name metadata carries the
FLAGS_op_trace_scopes annotations '{op.type}:{block}/{idx}' emitted by
core/lowering — then joins trace events back to framework ops via
profiler.summarize_xplane(hlo_text=...) and prints the reference
print_profiler-style op table: calls, total/avg/min/max ms split
device/host, % of step, sorted by total. `--by-op` keeps one row per op
instance (block/idx) instead of aggregating per type. With --log (or
FLAGS_monitor_export_path set) the rows are also appended as an
{"kind": "op_profile"} JSONL record, which tools/metrics_report.py
renders as its own section.

This is the capability match for the reference's platform/profiler.cc
per-op RecordEvent + print_profiler table: fused-HLO profiles are
unreadable without source-level annotation carried into the trace
("Operator Fusion in XLA", PAPERS.md).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def op_table_rows(summary, by_op=False):
    """Flatten summarize_xplane's "by_framework_op" dict into table rows
    (dicts, JSON-serializable), aggregated per op TYPE unless by_op.
    Rows sorted by total time descending; pct is share of the summed
    attributed time."""
    fw = summary.get("by_framework_op") or {}
    agg = {}
    for key, r in fw.items():
        k = key if by_op else r["op_type"]
        a = agg.get(k)
        if a is None:
            a = agg[k] = {"op": k, "calls": 0, "device_us": 0.0,
                          "host_us": 0.0, "total_us": 0.0,
                          "min_us": float("inf"), "max_us": 0.0}
        a["calls"] += r["calls"]
        a["device_us"] += r["device_us"]
        a["host_us"] += r["host_us"]
        a["total_us"] += r["total_us"]
        a["min_us"] = min(a["min_us"], r["min_us"])
        a["max_us"] = max(a["max_us"], r["max_us"])
    total = sum(a["total_us"] for a in agg.values()) or 1.0
    rows = []
    for a in sorted(agg.values(), key=lambda a: -a["total_us"]):
        rows.append({
            "op": a["op"],
            "calls": a["calls"],
            "total_ms": round(a["total_us"] / 1e3, 4),
            "avg_ms": round(a["total_us"] / a["calls"] / 1e3, 4),
            "min_ms": round(a["min_us"] / 1e3, 4),
            "max_ms": round(a["max_us"] / 1e3, 4),
            "device_ms": round(a["device_us"] / 1e3, 4),
            "host_ms": round(a["host_us"] / 1e3, 4),
            "pct": round(100.0 * a["total_us"] / total, 2),
        })
    return rows


def render_table(rows, top=40):
    """The reference print_profiler layout for the rows above."""
    lines = [f"{'op':32s} {'calls':>6s} {'total ms':>10s} {'avg ms':>9s} "
             f"{'min ms':>9s} {'max ms':>9s} {'device ms':>10s} "
             f"{'host ms':>9s} {'%':>6s}"]
    lines.append("-" * len(lines[0]))
    for r in rows[:top]:
        lines.append(
            f"{r['op'][:32]:32s} {r['calls']:>6d} {r['total_ms']:>10.3f} "
            f"{r['avg_ms']:>9.3f} {r['min_ms']:>9.3f} {r['max_ms']:>9.3f} "
            f"{r['device_ms']:>10.3f} {r['host_ms']:>9.3f} "
            f"{r['pct']:>5.1f}%")
    if len(rows) > top:
        lines.append(f"... {len(rows) - top} more row(s)")
    return "\n".join(lines)


def profile_model(model="bert", steps=5, tiny=False,
                  trace_dir="/tmp/paddle_tpu_op_profile"):
    """Build + warm the model, capture compiled HLO and an XPlane trace
    of `steps` async steps, and return summarize_xplane's dict with
    "by_framework_op". Same build path as bench.py so the profiled
    program is exactly the benchmarked one."""
    import numpy as np

    import bench
    import paddle_tpu as fluid
    from paddle_tpu import profiler

    if tiny:
        build = bench._CPU_TINY_BUILDS[model]
    else:
        build = {"bert": bench.build_bert_bench,
                 "resnet50": bench.build_resnet50_bench,
                 "gpt": bench.build_gpt_bench,
                 "transformer": bench.build_transformer_bench,
                 "deeplab": bench.build_deeplab_bench}[model]
    exe, prog, scope, feed, loss, _ = build()
    with fluid.scope_guard(scope):
        # warm up + compile outside the trace
        exe.run(prog, feed=feed, fetch_list=[loss])
        hlo = exe.compiled_hlo(prog, feed=feed, fetch_list=[loss])
        profiler.start_profiler(output_dir=trace_dir)
        x = None
        for _ in range(steps):
            x, = exe.run(prog, feed=feed, fetch_list=[loss],
                         return_numpy=False)
        np.asarray(x)  # drain before stopping the trace
        profiler.stop_profiler()
    summary = profiler.summarize_xplane(trace_dir, hlo_text=hlo)
    summary["steps"] = steps
    return summary


def _log_rows(path, model, rows):
    rec = {"kind": "op_profile", "model": model, "rows": rows}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return path


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-framework-op profile table")
    ap.add_argument("model", nargs="?", default="bert",
                    choices=["bert", "resnet50", "gpt", "transformer",
                             "deeplab"])
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (plumbing checks)")
    ap.add_argument("--tiny", action="store_true",
                    help="use bench.py's 2-layer tiny-shape builder")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--by-op", action="store_true",
                    help="one row per op instance (block/idx), not per "
                         "op type")
    ap.add_argument("--log", default="",
                    help="append rows as an op_profile JSONL record "
                         "(default: FLAGS_monitor_export_path if set)")
    args = ap.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    summary = profile_model(args.model, steps=args.steps,
                            tiny=args.tiny)
    rows = op_table_rows(summary, by_op=args.by_op)
    if not rows:
        print("no framework-op attribution found — is "
              "FLAGS_op_trace_scopes on?", file=sys.stderr)
        return 1
    attributed = [r for r in rows if r["op"] != "(unattributed)"]
    print(f"op profile — {args.model}, {summary['steps']} steps, "
          f"{summary['total_us'] / 1e3:.2f} ms total, "
          f"{len(attributed)} framework op "
          f"{'instances' if args.by_op else 'types'} attributed")
    print(render_table(rows, top=args.top))

    log = args.log
    if not log:
        try:
            from paddle_tpu.core.flags import FLAGS
            log = FLAGS.monitor_export_path
        except Exception:  # noqa: BLE001 — logging is best-effort
            log = ""
    if log:
        _log_rows(log, args.model, rows)
        print(f"# rows appended to {log} "
              f"(report: python tools/metrics_report.py {log})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

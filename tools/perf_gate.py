"""Noise-aware perf regression gate over the perf ledger.

Usage:
    python tools/perf_gate.py --ledger LEDGER.jsonl [options] \
        FILE [FILE ...]               # gate the rows in these artifacts
    python tools/perf_gate.py --ledger LEDGER.jsonl \
        --config C --metric M --value V [--unit U]   # gate one value
    python tools/perf_gate.py --self-check            # replay fixtures

For every candidate (config, metric) row the gate builds a baseline
from the last --last same-key ledger rows: the band is
``median +- max(k * 1.4826 * MAD, min_rel * |median|)`` — the median /
MAD pair shrugs off the occasional outlier round that would wreck a
mean/stddev gate, and the relative floor keeps a near-zero-MAD
baseline (three identical runs) from flagging measurement jitter.
Verdicts per row:

* ``regression``      — outside the band in the BAD direction (lower
  for throughput-like metrics, higher for latency/bytes-like ones;
  direction is inferred from the metric name + unit)
* ``improvement``     — outside the band in the good direction
* ``ok``              — inside the band
* ``too_few_samples`` — baseline smaller than --min-samples (never
  gates: a thin history must not fail CI)
* ``new_config``      — no history at all for the key

The run appends one ``kind="perf_gate"`` JSONL record to --out
(schema enforced by tools/validate_bench_json.py; rendered by
tools/metrics_report.py) and prints it. Exit 1 when any row regressed
— the CI/sweep contract — else 0. --ingest additionally appends the
candidate rows to the ledger AFTER gating (so a gated run becomes
tomorrow's baseline). --self-check replays the bundled golden
fixtures (regression / improvement / too-few-samples / new-config /
latency-direction / outlier-robustness) through the same code path
and exits nonzero on any unexpected verdict.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import perf_ledger  # noqa: E402

_LOWER_BETTER_UNITS = ("ms", "s", "seconds", "bytes", "ops", "vars")
_LOWER_BETTER_HINTS = ("latency", "_ms", "ttft", "wait", "seconds",
                       "bytes", "peak", "ops_after")


def lower_is_better(metric: str, unit: str = "") -> bool:
    """Direction inference: throughput-like metrics regress DOWN,
    latency/footprint-like metrics regress UP."""
    m = (metric or "").lower()
    u = (unit or "").lower()
    if any(t in m for t in ("per_sec", "per_s", "tokens_per",
                            "throughput", "rps", "qps", "mfu",
                            "eliminated")):
        return False
    if u in _LOWER_BETTER_UNITS or u.endswith("ms"):
        return True
    return any(h in m for h in _LOWER_BETTER_HINTS)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def gate_value(value: float, baseline: List[float], metric: str,
               unit: str = "", k: float = 4.0, min_rel: float = 0.02,
               min_samples: int = 3) -> Dict:
    """Verdict for one candidate value against its baseline history
    (oldest first). Pure function — the fixtures and tests drive it
    directly."""
    out = {"metric": metric, "unit": unit, "value": value,
           "direction": "lower" if lower_is_better(metric, unit)
           else "higher"}
    if not baseline:
        out["status"] = "new_config"
        return out
    if len(baseline) < min_samples:
        out["status"] = "too_few_samples"
        out["n_baseline"] = len(baseline)
        return out
    med = _median(baseline)
    mad = _median([abs(x - med) for x in baseline])
    band = max(k * 1.4826 * mad, min_rel * abs(med))
    delta = value - med
    out.update({"baseline_median": med, "baseline_mad": mad,
                "band": band, "n_baseline": len(baseline),
                "delta": delta,
                "delta_frac": delta / med if med else None})
    bad_up = lower_is_better(metric, unit)
    if delta > band:
        out["status"] = "regression" if bad_up else "improvement"
    elif delta < -band:
        out["status"] = "improvement" if bad_up else "regression"
    else:
        out["status"] = "ok"
    return out


def gate_rows(candidates: List[dict], ledger_rows: List[dict],
              k: float = 4.0, min_rel: float = 0.02,
              min_samples: int = 3, last: int = 20) -> List[dict]:
    """Gate candidate ledger rows against history grouped by
    (config, metric). Baseline = the last `last` same-key rows."""
    history: Dict[Tuple[str, str], List[float]] = {}
    for r in ledger_rows:
        key = (r.get("config"), r.get("metric"))
        history.setdefault(key, []).append(r.get("value"))
    results = []
    for c in candidates:
        key = (c.get("config"), c.get("metric"))
        base = [v for v in history.get(key, [])
                if isinstance(v, (int, float))][-last:]
        res = gate_value(c.get("value"), base, c.get("metric"),
                         c.get("unit", ""), k=k, min_rel=min_rel,
                         min_samples=min_samples)
        res["config"] = c.get("config")
        results.append(res)
    return results


def gate_report(results: List[dict], ledger: str, k: float,
                min_samples: int, last: int) -> dict:
    return {"kind": "perf_gate", "ts": time.time(), "ledger": ledger,
            "k_mad": k, "min_samples": min_samples, "baseline_n": last,
            "results": results,
            "regressions": sum(r["status"] == "regression"
                               for r in results),
            "improvements": sum(r["status"] == "improvement"
                                for r in results)}


# ---------------------------------------------------------------------------
# Golden fixtures (--self-check)
# ---------------------------------------------------------------------------

# (name, metric, unit, baseline, candidate, expected status)
FIXTURES = [
    ("throughput_regression", "bert_tokens_per_sec", "tokens/s",
     [35000.0, 35400.0, 35200.0], 27000.0, "regression"),
    ("throughput_improvement", "bert_tokens_per_sec", "tokens/s",
     [35000.0, 35400.0, 35200.0], 42000.0, "improvement"),
    ("within_noise", "bert_tokens_per_sec", "tokens/s",
     [35000.0, 35400.0, 35200.0, 34900.0, 35600.0], 35100.0, "ok"),
    ("too_few_samples", "bert_tokens_per_sec", "tokens/s",
     [35000.0], 20000.0, "too_few_samples"),
    ("new_config", "gpt_tokens_per_sec", "tokens/s",
     [], 1000.0, "new_config"),
    ("latency_regression", "latency_ms_p99", "ms",
     [10.0, 10.5, 9.8], 20.0, "regression"),
    ("latency_improvement", "latency_ms_p99", "ms",
     [10.0, 10.5, 9.8], 5.0, "improvement"),
    # one wild outlier round must not widen the band enough to pass a
    # real 20% regression (median/MAD robustness)
    ("outlier_robust_regression", "bert_tokens_per_sec", "tokens/s",
     [35000.0, 35400.0, 35200.0, 12000.0, 35100.0], 28000.0,
     "regression"),
    # ...nor flag honest jitter on a flat baseline (relative floor)
    ("flat_baseline_jitter_ok", "bert_tokens_per_sec", "tokens/s",
     [35000.0, 35000.0, 35000.0], 34650.0, "ok"),
]


def self_check() -> int:
    failures = []
    for name, metric, unit, baseline, value, want in FIXTURES:
        got = gate_value(value, baseline, metric, unit)["status"]
        if got != want:
            failures.append(f"{name}: expected {want}, got {got}")
    if failures:
        for f in failures:
            print(f"SELF-CHECK FAIL: {f}", file=sys.stderr)
        return 1
    print(f"self-check ok: {len(FIXTURES)} fixtures")
    return 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="candidate artifacts (any shape "
                         "validate_bench_json.py knows)")
    ap.add_argument("--ledger", default=None)
    ap.add_argument("--out", default=None,
                    help="append the perf_gate record here (JSONL)")
    ap.add_argument("--config", default=None)
    ap.add_argument("--metric", default=None)
    ap.add_argument("--value", type=float, default=None)
    ap.add_argument("--unit", default="")
    ap.add_argument("--k", type=float, default=4.0,
                    help="MAD multiplier of the noise band")
    ap.add_argument("--min-rel", type=float, default=0.02,
                    help="relative floor of the band (fraction of the "
                         "baseline median)")
    ap.add_argument("--min-samples", type=int, default=3)
    ap.add_argument("--last", type=int, default=20,
                    help="baseline window: last N same-key rows")
    ap.add_argument("--ingest", action="store_true",
                    help="append the candidate rows to the ledger "
                         "after gating")
    ap.add_argument("--self-check", action="store_true")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.ledger:
        ap.error("--ledger is required (unless --self-check)")

    candidates: List[dict] = []
    if args.value is not None:
        if not (args.config and args.metric):
            ap.error("--value needs --config and --metric")
        candidates.append({"config": args.config,
                           "metric": args.metric,
                           "value": args.value, "unit": args.unit})
    skipped = 0
    for path in args.files:
        rows, sk = perf_ledger.rows_from_file(path)
        candidates.extend(rows)
        skipped += sk
    if not candidates:
        print("perf_gate: no candidate rows found", file=sys.stderr)
        return 2

    ledger_rows = perf_ledger.load_rows(args.ledger)
    results = gate_rows(candidates, ledger_rows, k=args.k,
                        min_rel=args.min_rel,
                        min_samples=args.min_samples, last=args.last)
    report = gate_report(results, args.ledger, args.k,
                         args.min_samples, args.last)
    if skipped:
        report["skipped_inputs"] = skipped
    perf_ledger._stat_add("ledger.gate_runs")
    if report["regressions"]:
        perf_ledger._stat_add("ledger.gate_regressions",
                              report["regressions"])

    if args.out:
        d = os.path.dirname(os.path.abspath(args.out))
        os.makedirs(d, exist_ok=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(report) + "\n")
            f.flush()
            os.fsync(f.fileno())
    for r in results:
        med = r.get("baseline_median")
        band = r.get("band")
        detail = "" if med is None else \
            f" vs {med:.6g} +- {band:.6g} (n={r.get('n_baseline')})"
        print(f"perf_gate: {r['status']:>15}  {r['config']} "
              f"{r['metric']} = {r['value']:.6g}{detail}")
    print(json.dumps(report))

    if args.ingest:
        perf_ledger.append_rows(args.ledger, candidates)
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# TPU recovery sweep: the full bench matrix + flash A/B + a one-step
# XPlane profile, run once when tools/probe_and_sweep.sh sees the
# tunnel answer (or by hand after `python -c "import jax; jax.devices()"`
# succeeds). Mirrors results into the repo so an end-of-round snapshot
# always captures them. Never timeout-kills a bench mid-claim (wedge
# hygiene — see PERF.md).
#
# Reference analogue: the committed CI driver paddle/scripts/paddle_build.sh
# and the benchmark runner paddle/fluid/operators/benchmark/op_tester.cc.
#
# Env: ROUND (default r05) controls the mirrored filename.
cd "$(dirname "$0")/.."
ROUND=${ROUND:-r05}
R=${SWEEP_OUT:-/tmp/sweep_results.jsonl}
# Fresh results file: a stale /tmp file from an earlier round (or an
# aborted sweep) must not be mirrored into this round's committed log.
: > "$R"
# One sweep at a time — the probe loop and a manual invocation must not
# interleave lines in $R.
exec 9> /tmp/ptn_sweep.lock
flock -n 9 || { echo "another sweep is already running" >&2; exit 1; }
run() {
  echo "=== $* ===" >> "$R"
  env "$@" BENCH_STEPS=30 BENCH_WAIT_TPU_S=60 python bench.py \
      2>>/tmp/sweep_err.log >> "$R"
  cp "$R" "PERF_SWEEP_${ROUND}.log" 2>/dev/null || true
}
run BENCH_FLASH=1 BENCH_BATCH=32
run BENCH_FLASH=0 BENCH_BATCH=32
run BENCH_FLASH=1 BENCH_BATCH=64
run BENCH_FLASH=0 BENCH_BATCH=64
run BENCH_FLASH=1 BENCH_BATCH=16 BENCH_SEQ=1024
run BENCH_FLASH=0 BENCH_BATCH=16 BENCH_SEQ=1024
run BENCH_MODEL=gpt BENCH_BATCH=32
run BENCH_MODEL=resnet50 BENCH_BATCH=64
run BENCH_MODEL=resnet50 BENCH_BATCH=128
run BENCH_MODEL=transformer BENCH_BATCH=32
run BENCH_MODEL=deeplab BENCH_BATCH=8
echo "=== attention microbench ===" >> "$R"
python tools/attn_micro.py >> "$R" 2>&1
echo "=== profile ===" >> "$R"
python tools/profile_step.py > /tmp/profile_step.out 2>&1
tail -40 /tmp/profile_step.out >> "$R"
echo DONE >> "$R"
cp "$R" "PERF_SWEEP_${ROUND}.log"

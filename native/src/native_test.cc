// Native-layer unit tests (assert-based, mirroring the reference's
// colocated *_test.cc pattern, e.g. memory/allocation/
// best_fit_allocator_test.cc and framework/blocking_queue tests).
#include <cassert>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <thread>
#include <vector>

#include "allocator.h"
#include "blocking_queue.h"
#include "data_feed.h"
#include "profiler.h"
#include "threadpool.h"

using namespace ptn;

static void TestBlockingQueue() {
  BlockingQueue<int> q(4);
  std::thread prod([&] {
    for (int i = 0; i < 100; ++i) assert(q.Push(i));
    q.Close();
  });
  int sum = 0, v;
  while (q.Pop(&v)) sum += v;
  prod.join();
  assert(sum == 4950);
  std::puts("TestBlockingQueue OK");
}

static void TestThreadPool() {
  ThreadPool pool(4);
  std::atomic<int> n{0};
  for (int i = 0; i < 64; ++i) pool.Submit([&] { n.fetch_add(1); });
  pool.Wait();
  assert(n == 64);
  std::puts("TestThreadPool OK");
}

static void TestBufferPool() {
  BufferPool pool(1 << 20);
  void* a = pool.Alloc(1000);
  void* b = pool.Alloc(5000);
  assert(a && b && a != b);
  std::memset(a, 1, 1000);
  std::memset(b, 2, 5000);
  pool.Free(a);
  void* c = pool.Alloc(512);  // should reuse a's block
  assert(c != nullptr);
  auto s = pool.GetStats();
  assert(s.bytes_reserved == (1u << 20));
  assert(s.n_allocs == 3);
  pool.Free(b);
  pool.Free(c);
  assert(pool.GetStats().bytes_in_use == 0);
  std::puts("TestBufferPool OK");
}

static void TestDataFeed() {
  // 2 slots: float dim 3, int64 dim 2; 7 samples across 2 files.
  const char* f1 = "/tmp/ptn_test_1.txt";
  const char* f2 = "/tmp/ptn_test_2.txt";
  {
    std::ofstream o(f1);
    for (int i = 0; i < 4; ++i)
      o << "3 " << i << ".5 1.0 2.0 2 " << i << " " << i + 1 << "\n";
  }
  {
    std::ofstream o(f2);
    for (int i = 4; i < 7; ++i)
      o << "1 " << i << ".5 2 " << i << " " << i + 1 << "\n";
  }
  std::vector<SlotDesc> slots = {{"x", SlotType::kFloat32, 3, false},
                                 {"y", SlotType::kInt64, 2, false}};
  DataFeed feed(slots, /*batch=*/2, /*cap=*/4, /*drop_last=*/false);
  feed.AddFile(f1);
  feed.AddFile(f2);
  feed.Start(2);
  int64_t total = 0;
  int n_batches = 0;
  Batch b;
  while (feed.Next(&b)) {
    total += b.batch_size;
    ++n_batches;
    // int slot: second value == first + 1 in every row
    auto* iv = static_cast<int64_t*>(b.buffers[1]);
    for (int64_t i = 0; i < b.batch_size; ++i) {
      assert(iv[i * 2 + 1] == iv[i * 2] + 1);
    }
    feed.ReleaseBatch(&b);
  }
  assert(total == 7);
  assert(n_batches == 4);  // 2+2+2+1
  assert(feed.samples_parsed() == 7);
  assert(feed.parse_errors() == 0);
  feed.Stop();
  std::puts("TestDataFeed OK");
}

static void TestProfiler() {
  ProfilerReset();
  ProfilerEnable();
  ProfilerPush("step");
  ProfilerPush("lower");
  ProfilerPop("lower");
  ProfilerPop("step");
  ProfilerDisable();
  int n = ProfilerDumpChromeTrace("/tmp/ptn_trace.json");
  assert(n == 4);
  std::puts("TestProfiler OK");
}

int main() {
  TestBlockingQueue();
  TestThreadPool();
  TestBufferPool();
  TestDataFeed();
  TestProfiler();
  std::puts("ALL NATIVE TESTS OK");
  return 0;
}

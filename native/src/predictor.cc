// C-ABI inference entry points: load a saved inference model and run
// predictions from pure C/C++ — the counterpart of the reference's
// inference/capi/ (PD_NewAnalysisConfig, PD_PredictorRun,
// PD_GetOutputTensor). Same embedding strategy as trainer.cc: the XLA
// compute path is driven through an embedded (or hosted) CPython via
// paddle_tpu.native_predictor; buffers cross the ABI raw.
#include "py_embed.h"

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

using ptn_embed::Gil;
using ptn_embed::capture_py_error;

struct Predictor {
  PyObject* obj;  // paddle_tpu.native_predictor.NativePredictor
};

constexpr int kMaxRank = 8;  // dims_out contract in output_meta

}  // namespace

extern "C" {

const char* ptn_predictor_last_error() {
  return ptn_embed::last_error().c_str();
}

// Interpreter bootstrap. Identical contract to ptn_trainer_init.
int ptn_predictor_init(const char* repo_root) {
  return ptn_embed::bootstrap(repo_root, "paddle_tpu.native_predictor");
}

// Load a model dir written by fluid.io.save_inference_model. Returns a
// handle or NULL (see ptn_predictor_last_error).
void* ptn_predictor_load(const char* model_dir) {
  Gil gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.native_predictor");
  if (!mod) {
    capture_py_error("import");
    return nullptr;
  }
  PyObject* obj = PyObject_CallMethod(mod, "load_predictor", "s", model_dir);
  Py_DECREF(mod);
  if (!obj) {
    capture_py_error("load_predictor");
    return nullptr;
  }
  return new Predictor{obj};
}

// Run one prediction. Feed ABI matches ptn_trainer_run_step. Returns
// the number of outputs (cached on the handle), or -1 on failure.
int ptn_predictor_run(void* handle, int n, const char** names,
                      const void** bufs, const uint64_t* nbytes,
                      const char** dtypes, const int64_t* shapes,
                      const int* ranks) {
  if (!handle || n < 0 ||
      (n > 0 && (!names || !bufs || !nbytes || !dtypes || !shapes ||
                 !ranks))) {
    ptn_embed::last_error() =
        "run: NULL handle/feed arrays or negative feed count";
    return -1;
  }
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* feed = PyList_New(n);
  const int64_t* sp = shapes;
  for (int i = 0; i < n; ++i) {
    if (ranks[i] < 0 || !bufs[i] || !names[i] || !dtypes[i]) {
      ptn_embed::last_error() = "run: malformed feed entry";
      Py_DECREF(feed);
      return -1;
    }
    PyObject* shape = PyTuple_New(ranks[i]);
    for (int d = 0; d < ranks[i]; ++d)
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(sp[d]));
    sp += ranks[i];
    PyObject* entry = Py_BuildValue(
        "(sy#sO)", names[i], static_cast<const char*>(bufs[i]),
        static_cast<Py_ssize_t>(nbytes[i]), dtypes[i], shape);
    Py_DECREF(shape);
    if (!entry) {
      capture_py_error("build feed entry");
      Py_DECREF(feed);
      return -1;
    }
    PyList_SET_ITEM(feed, i, entry);
  }
  PyObject* r = PyObject_CallMethod(p->obj, "run_raw", "O", feed);
  Py_DECREF(feed);
  if (!r) {
    capture_py_error("run_raw");
    return -1;
  }
  long count = PyLong_AsLong(r);
  Py_DECREF(r);
  return static_cast<int>(count);
}

// Metadata of output i from the last run: dtype string (copied into
// dtype_buf, NUL-terminated), rank + dims (dims_out must hold >= 8),
// and total byte size. Returns 0 / -1 (rank > 8 is an error — the
// caller's dims buffer contract is 8).
int ptn_predictor_output_meta(void* handle, int i, char* dtype_buf,
                              int dtype_cap, int* rank_out,
                              int64_t* dims_out, uint64_t* nbytes_out) {
  if (!handle) {
    ptn_embed::last_error() = "output_meta: NULL handle";
    return -1;
  }
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* r = PyObject_CallMethod(p->obj, "output_meta", "i", i);
  if (!r) {
    capture_py_error("output_meta");
    return -1;
  }
  const char* dt = nullptr;
  PyObject* shape = nullptr;
  long long nb = 0;
  if (!PyArg_ParseTuple(r, "sOL", &dt, &shape, &nb)) {
    capture_py_error("parse output_meta");
    Py_DECREF(r);
    return -1;
  }
  Py_ssize_t rank = PyList_Size(shape);
  if (rank > kMaxRank) {
    ptn_embed::last_error() = "output_meta: rank exceeds the 8-dim ABI";
    Py_DECREF(r);
    return -1;
  }
  std::snprintf(dtype_buf, dtype_cap, "%s", dt);
  *rank_out = static_cast<int>(rank);
  for (Py_ssize_t d = 0; d < rank; ++d)
    dims_out[d] = PyLong_AsLongLong(PyList_GetItem(shape, d));
  *nbytes_out = static_cast<uint64_t>(nb);
  Py_DECREF(r);
  return 0;
}

// Copy output i's bytes into dst (cap bytes). Returns bytes written or
// -1.
int64_t ptn_predictor_output_data(void* handle, int i, void* dst,
                                  uint64_t cap) {
  if (!handle || !dst) {
    ptn_embed::last_error() = "output_data: NULL handle or dst";
    return -1;
  }
  Gil gil;
  Predictor* p = static_cast<Predictor*>(handle);
  PyObject* r = PyObject_CallMethod(p->obj, "output_bytes", "i", i);
  if (!r) {
    capture_py_error("output_bytes");
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    capture_py_error("output bytes access");
    Py_DECREF(r);
    return -1;
  }
  if (static_cast<uint64_t>(len) > cap) len = static_cast<Py_ssize_t>(cap);
  std::memcpy(dst, buf, len);
  Py_DECREF(r);
  return static_cast<int64_t>(len);
}

void ptn_predictor_destroy(void* handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (p) {
    Gil gil;
    Py_XDECREF(p->obj);
    delete p;
  }
}

}  // extern "C"

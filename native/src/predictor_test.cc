// Pure-C++ inference entry test: build+save an inference model via
// embedded setup, then LOAD and PREDICT entirely through the C ABI —
// the counterpart of the reference's inference/capi tests
// (pd_config/pd_predict test suite).
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "test_deadline.h"

extern "C" {
int ptn_predictor_init(const char* repo_root);
void* ptn_predictor_load(const char* model_dir);
int ptn_predictor_run(void* handle, int n, const char** names,
                      const void** bufs, const uint64_t* nbytes,
                      const char** dtypes, const int64_t* shapes,
                      const int* ranks);
int ptn_predictor_output_meta(void* handle, int i, char* dtype_buf,
                              int dtype_cap, int* rank_out,
                              int64_t* dims_out, uint64_t* nbytes_out);
int64_t ptn_predictor_output_data(void* handle, int i, void* dst,
                                  uint64_t cap);
void ptn_predictor_destroy(void* handle);
const char* ptn_predictor_last_error();
// from trainer.cc (linked together): arbitrary setup python
int ptn_trainer_exec(const char* code);
}

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAILED: %s (line %d): %s\n", #cond,       \
                   __LINE__, ptn_predictor_last_error());             \
      std::exit(1);                                                   \
    }                                                                 \
  } while (0)

int main(int argc, char** argv) {
  ptn_test::install_deadline("predictor_test");
  const char* repo = argc > 1 ? argv[1] : "..";
  CHECK(ptn_predictor_init(repo) == 0);

  const char* setup = R"PY(
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers

main, startup = fluid.Program(), fluid.Program()
scope = fluid.Scope()
with fluid.program_guard(main, startup), fluid.scope_guard(scope):
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.fc(x, size=4, act="relu")
    z = layers.fc(y, size=2)
    exe = fluid.Executor()
    exe.run(startup)
    fluid.io.save_inference_model("/tmp/ptn_pred_model", ["x"], [z], exe,
                                  main_program=main)
)PY";
  CHECK(ptn_trainer_exec(setup) == 0);

  void* pred = ptn_predictor_load("/tmp/ptn_pred_model");
  CHECK(pred != nullptr);

  std::vector<float> x(6 * 8);
  for (size_t i = 0; i < x.size(); ++i) x[i] = 0.01f * (float)i - 0.2f;
  const char* names[] = {"x"};
  const void* bufs[] = {x.data()};
  const uint64_t nbytes[] = {x.size() * sizeof(float)};
  const char* dtypes[] = {"float32"};
  const int64_t shapes[] = {6, 8};
  const int ranks[] = {2};

  int n_out = ptn_predictor_run(pred, 1, names, bufs, nbytes, dtypes,
                                shapes, ranks);
  CHECK(n_out == 1);

  char dtype[16];
  int rank = 0;
  int64_t dims[8];
  uint64_t out_bytes = 0;
  CHECK(ptn_predictor_output_meta(pred, 0, dtype, sizeof(dtype), &rank,
                                  dims, &out_bytes) == 0);
  CHECK(std::strcmp(dtype, "float32") == 0);
  CHECK(rank == 2 && dims[0] == 6 && dims[1] == 2);
  CHECK(out_bytes == 6 * 2 * sizeof(float));

  std::vector<float> out(6 * 2);
  CHECK(ptn_predictor_output_data(pred, 0, out.data(),
                                  out_bytes) == (int64_t)out_bytes);
  for (float v : out) CHECK(std::isfinite(v));

  // run twice: same input -> identical output (deterministic inference)
  std::vector<float> out2(6 * 2);
  CHECK(ptn_predictor_run(pred, 1, names, bufs, nbytes, dtypes, shapes,
                          ranks) == 1);
  CHECK(ptn_predictor_output_data(pred, 0, out2.data(),
                                  out_bytes) == (int64_t)out_bytes);
  for (size_t i = 0; i < out.size(); ++i) CHECK(out[i] == out2[i]);

  // malformed input must surface as error codes, never crash the
  // embedded interpreter:
  // 1. NULL handle
  CHECK(ptn_predictor_run(nullptr, 1, names, bufs, nbytes, dtypes,
                          shapes, ranks) == -1);
  CHECK(std::strlen(ptn_predictor_last_error()) > 0);
  // 2. wrong feature width (8 -> 5): byte count and shape disagree
  //    with the saved program's declared input
  const int64_t bad_shapes[] = {6, 5};
  const uint64_t bad_nbytes[] = {6 * 5 * sizeof(float)};
  CHECK(ptn_predictor_run(pred, 1, names, bufs, bad_nbytes, dtypes,
                          bad_shapes, ranks) == -1);
  // 3. byte buffer inconsistent with the declared shape
  const uint64_t short_nbytes[] = {7};
  CHECK(ptn_predictor_run(pred, 1, names, bufs, short_nbytes, dtypes,
                          shapes, ranks) == -1);
  // 4. unknown feed name
  const char* bad_names[] = {"not_a_var"};
  CHECK(ptn_predictor_run(pred, 1, bad_names, bufs, nbytes, dtypes,
                          shapes, ranks) == -1);
  // 5. negative rank in the feed meta
  const int bad_ranks[] = {-1};
  CHECK(ptn_predictor_run(pred, 1, names, bufs, nbytes, dtypes, shapes,
                          bad_ranks) == -1);
  // ...and the predictor still works after every rejected call
  CHECK(ptn_predictor_run(pred, 1, names, bufs, nbytes, dtypes, shapes,
                          ranks) == 1);

  ptn_predictor_destroy(pred);
  std::printf("predictor_test OK\n");
  return 0;
}

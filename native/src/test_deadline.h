// Shared wall-clock deadline for the C-ABI test binaries: a wedged
// backend (e.g. a dead TPU tunnel the CPU pin could not sidestep)
// degrades to a reported skip (exit 77, the automake convention)
// instead of hanging the build forever.
#pragma once

#include <signal.h>
#include <unistd.h>

#include <cstdlib>

namespace ptn_test {

inline const char*& deadline_name() {
  static const char* name = "test";
  return name;
}

// Async-signal-safe: write() + _exit() only.
inline void deadline_handler(int) {
  const char pre[] = "SKIP: ";
  const char post[] =
      " exceeded its wall-clock deadline (wedged backend?)\n";
  ssize_t ignored = write(2, pre, sizeof(pre) - 1);
  const char* n = deadline_name();
  size_t len = 0;
  while (n[len]) ++len;
  ignored = write(2, n, len);
  ignored = write(2, post, sizeof(post) - 1);
  (void)ignored;
  _exit(77);
}

// Default 540 s; override via PTN_TEST_DEADLINE_S. Non-numeric or
// non-positive values fall back to the default (alarm(0) would silently
// disable the guard).
inline void install_deadline(const char* test_name) {
  deadline_name() = test_name;
  signal(SIGALRM, deadline_handler);
  unsigned secs = 540;
  if (const char* env = std::getenv("PTN_TEST_DEADLINE_S")) {
    int v = std::atoi(env);
    if (v > 0) secs = (unsigned)v;
  }
  alarm(secs);
}

}  // namespace ptn_test

// C ABI for the native runtime — the boundary Python binds via ctypes.
//
// Counterpart of the reference's pybind layer (paddle/fluid/pybind/
// pybind.cc) and its stable C APIs (framework/c/c_api.cc, inference/capi/):
// everything the Python frontend needs from the native runtime crosses
// here as plain C. No Python.h dependency — keeps the .so usable from any
// host language (the reference's C++ trainer demo is the precedent,
// train/demo_trainer.cc).
#include <cstdint>
#include <cstring>
#include <vector>

#include "allocator.h"
#include "data_feed.h"
#include "profiler.h"

using ptn::Batch;
using ptn::BufferPool;
using ptn::DataFeed;
using ptn::SlotDesc;
using ptn::SlotType;

extern "C" {

// ---------------- buffer pool ----------------

void* ptn_pool_create(uint64_t chunk_bytes) {
  return new BufferPool(chunk_bytes ? chunk_bytes : (16u << 20));
}
void ptn_pool_destroy(void* pool) { delete static_cast<BufferPool*>(pool); }
void* ptn_pool_alloc(void* pool, uint64_t size) {
  return static_cast<BufferPool*>(pool)->Alloc(size);
}
void ptn_pool_free(void* pool, void* p) {
  static_cast<BufferPool*>(pool)->Free(p);
}
void ptn_pool_stats(void* pool, uint64_t* in_use, uint64_t* reserved,
                    uint64_t* peak, uint64_t* n_allocs) {
  auto s = static_cast<BufferPool*>(pool)->GetStats();
  *in_use = s.bytes_in_use;
  *reserved = s.bytes_reserved;
  *peak = s.peak_in_use;
  *n_allocs = s.n_allocs;
}

// ---------------- data feed ----------------

// slot_types: 0=float32, 1=int64; slot_dims: values per sample (pad/trunc).
void* ptn_feed_create(int32_t n_slots, const char** slot_names,
                      const int32_t* slot_types, const int64_t* slot_dims,
                      int64_t batch_size, int32_t queue_capacity,
                      int32_t drop_last) {
  std::vector<SlotDesc> slots;
  slots.reserve(static_cast<size_t>(n_slots));
  for (int32_t i = 0; i < n_slots; ++i) {
    slots.push_back({slot_names[i],
                     static_cast<SlotType>(slot_types[i]), slot_dims[i],
                     /*dense=*/false});
  }
  return new DataFeed(std::move(slots), batch_size,
                      static_cast<size_t>(queue_capacity), drop_last != 0);
}

void ptn_feed_destroy(void* feed) { delete static_cast<DataFeed*>(feed); }

void ptn_feed_add_file(void* feed, const char* path) {
  static_cast<DataFeed*>(feed)->AddFile(path);
}

void ptn_feed_set_shuffle(void* feed, int32_t on, uint64_t seed) {
  static_cast<DataFeed*>(feed)->SetShuffle(on != 0, seed);
}

void ptn_feed_start(void* feed, int32_t n_threads) {
  static_cast<DataFeed*>(feed)->Start(n_threads);
}

void ptn_feed_stop(void* feed) { static_cast<DataFeed*>(feed)->Stop(); }

// Pops the next batch and copies each slot into caller-provided buffers
// (shaped [batch_size, dim]; short final batches zero-pad the tail rows and
// report the true size). lengths_out: concatenated per-slot [batch] arrays.
// Returns batch_size (>0), or 0 at end of data.
int64_t ptn_feed_next(void* feed, void** slot_buffers, int64_t* lengths_out) {
  auto* df = static_cast<DataFeed*>(feed);
  Batch b;
  if (!df->Next(&b)) return 0;
  // Copy out then release pool buffers (caller side keeps a stable ABI:
  // plain memcpy into numpy arrays it allocated).
  int64_t bs = b.batch_size;
  int64_t off = 0;
  for (size_t si = 0; si < b.buffers.size(); ++si) {
    const auto& lens = b.lengths[si];
    size_t row = df->SlotRowBytes(si);
    std::memcpy(slot_buffers[si], b.buffers[si],
                static_cast<size_t>(bs) * row);
    for (int64_t i = 0; i < bs; ++i) {
      lengths_out[off + i] = lens[static_cast<size_t>(i)];
    }
    off += df->MaxBatch();
  }
  df->ReleaseBatch(&b);
  return bs;
}

uint64_t ptn_feed_samples_parsed(void* feed) {
  return static_cast<DataFeed*>(feed)->samples_parsed();
}
uint64_t ptn_feed_parse_errors(void* feed) {
  return static_cast<DataFeed*>(feed)->parse_errors();
}

// ---------------- profiler ----------------

void ptn_profiler_enable() { ptn::ProfilerEnable(); }
void ptn_profiler_disable() { ptn::ProfilerDisable(); }
void ptn_profiler_reset() { ptn::ProfilerReset(); }
void ptn_profiler_push(const char* name) { ptn::ProfilerPush(name); }
void ptn_profiler_pop(const char* name) { ptn::ProfilerPop(name); }
int ptn_profiler_dump(const char* path) {
  return ptn::ProfilerDumpChromeTrace(path);
}

// ---------------- version ----------------

const char* ptn_version() { return "paddle-tpu-native 0.1"; }

}  // extern "C"

// C-ABI training entry points: load a saved program and run training
// steps from pure C/C++ — the counterpart of the reference's
// train/demo/demo_trainer.cc (load ProgramDesc + persistables, run the
// Executor in a loop) and train/test_train_recognize_digits.cc.
//
// On TPU the compute path IS the XLA runtime driven through JAX, so the
// native trainer embeds CPython (the inverse of the usual ctypes
// direction; the CPython C API is the sanctioned binding layer here) and
// drives paddle_tpu.native_trainer. C callers never touch Python types:
// feeds cross the ABI as raw buffers + shape/dtype strings.
#include "py_embed.h"

#include <cstdint>
#include <cstring>
#include <string>

namespace {

using ptn_embed::Gil;
using ptn_embed::capture_py_error;

struct Trainer {
  PyObject* obj;  // paddle_tpu.native_trainer.NativeTrainer
};

}  // namespace

extern "C" {

const char* ptn_trainer_last_error() {
  return ptn_embed::last_error().c_str();
}

// Initialize the embedded interpreter (no-op when already hosted inside
// Python); see py_embed.h bootstrap for the JAX_PLATFORMS pinning.
int ptn_trainer_init(const char* repo_root) {
  return ptn_embed::bootstrap(repo_root, "paddle_tpu.native_trainer");
}

// Load a model directory saved by
// paddle_tpu.native_trainer.save_trainer_model (program JSON +
// persistables) — the analogue of demo_trainer.cc reading the
// __model__ ProgramDesc + params. Returns a handle or NULL.
void* ptn_trainer_load(const char* model_dir) {
  Gil gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.native_trainer");
  if (!mod) {
    capture_py_error("import");
    return nullptr;
  }
  PyObject* obj =
      PyObject_CallMethod(mod, "load_trainer", "s", model_dir);
  Py_DECREF(mod);
  if (!obj) {
    capture_py_error("load_trainer");
    return nullptr;
  }
  return new Trainer{obj};
}

// One training step. feeds cross as n parallel arrays:
//   names[i]        var name
//   bufs[i]/nbytes  raw little-endian buffer
//   dtypes[i]       numpy dtype string ("float32", "int64", ...)
//   shapes[i]/ranks flattened dims
// Returns the scalar loss; NaN on failure (see ptn_trainer_last_error).
double ptn_trainer_run_step(void* handle, int n, const char** names,
                            const void** bufs, const uint64_t* nbytes,
                            const char** dtypes, const int64_t* shapes,
                            const int* ranks) {
  if (!handle || n < 0 ||
      (n > 0 && (!names || !bufs || !nbytes || !dtypes || !shapes ||
                 !ranks))) {
    ptn_embed::last_error() =
        "run_step: NULL handle/feed arrays or negative feed count";
    return NAN;
  }
  Gil gil;
  Trainer* t = static_cast<Trainer*>(handle);
  PyObject* feed = PyList_New(n);
  const int64_t* sp = shapes;
  for (int i = 0; i < n; ++i) {
    if (ranks[i] < 0 || !bufs[i] || !names[i] || !dtypes[i]) {
      ptn_embed::last_error() = "run_step: malformed feed entry";
      Py_DECREF(feed);
      return NAN;
    }
    PyObject* shape = PyTuple_New(ranks[i]);
    for (int d = 0; d < ranks[i]; ++d)
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(sp[d]));
    sp += ranks[i];
    PyObject* entry = Py_BuildValue(
        "(sy#sO)", names[i], static_cast<const char*>(bufs[i]),
        static_cast<Py_ssize_t>(nbytes[i]), dtypes[i], shape);
    Py_DECREF(shape);
    if (!entry) {
      capture_py_error("build feed entry");
      Py_DECREF(feed);
      return NAN;
    }
    PyList_SET_ITEM(feed, i, entry);
  }
  PyObject* r = PyObject_CallMethod(t->obj, "run_step_raw", "O", feed);
  Py_DECREF(feed);
  if (!r) {
    capture_py_error("run_step");
    return NAN;
  }
  double loss = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return loss;
}

// Persist the trainer's current state back into the model dir.
int ptn_trainer_save(void* handle, const char* model_dir) {
  if (!handle) {
    ptn_embed::last_error() = "save: NULL handle";
    return -1;
  }
  Gil gil;
  Trainer* t = static_cast<Trainer*>(handle);
  PyObject* r = PyObject_CallMethod(t->obj, "save", "s", model_dir);
  if (!r) {
    capture_py_error("save");
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

void ptn_trainer_destroy(void* handle) {
  Trainer* t = static_cast<Trainer*>(handle);
  if (t) {
    Gil gil;
    Py_XDECREF(t->obj);
    delete t;
  }
}

// Convenience for the native test: run arbitrary setup Python (e.g.
// build + save the model being trained).
int ptn_trainer_exec(const char* code) {
  Gil gil;
  if (PyRun_SimpleString(code) != 0) {
    ptn_embed::last_error() = "ptn_trainer_exec: python raised";
    return -1;
  }
  return 0;
}

}  // extern "C"

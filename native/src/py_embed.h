// Shared CPython-embedding plumbing for the C-ABI entry points
// (trainer.cc, predictor.cc): GIL RAII, python-error capture, and the
// interpreter bootstrap. The embedding direction mirrors the
// reference's train/demo + inference/capi split over one runtime.
#pragma once

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdlib>
#include <string>

namespace ptn_embed {

// GIL helper working both embedded (we own the interpreter) and hosted
// (the .so was ctypes-loaded inside a running Python).
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Per-thread last-error string; each entry-point family exposes its own
// *_last_error() that reads this.
inline std::string& last_error() {
  thread_local std::string err;
  return err;
}

inline void capture_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyObject* s = value ? PyObject_Str(value) : nullptr;
  const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!msg) {
    // PyUnicode_AsUTF8 can itself fail (non-UTF-8 surrogates); never
    // concatenate NULL into std::string
    PyErr_Clear();
    msg = "unknown python error";
  }
  last_error() = std::string(where) + ": " + msg;
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Interpreter bootstrap: no-op when hosted inside a running Python;
// when embedding, pins JAX to the CPU backend unless
// PTN_TRAINER_KEEP_PLATFORM is set (the TPU-tunnel backend must not be
// claimed by a side process). Prepends repo_root to sys.path and
// imports `module` as a smoke check. Returns 0 / -1.
inline int bootstrap(const char* repo_root, const char* module) {
  bool embedded = false;
  if (!Py_IsInitialized()) {
    if (!std::getenv("PTN_TRAINER_KEEP_PLATFORM"))
      setenv("JAX_PLATFORMS", "cpu", 1);
    Py_InitializeEx(0);
    embedded = true;
  }
  int rc = 0;
  {
    Gil gil;
    if (embedded && !std::getenv("PTN_TRAINER_KEEP_PLATFORM")) {
      // The env var alone is not enough: site images that register a
      // tunnel PJRT backend from sitecustomize re-pin JAX_PLATFORMS at
      // interpreter start, so a backend resolve here would claim (or
      // block on) the tunnel from a side process. jax.config.update
      // still wins post-import because no XLA client exists yet — the
      // same pattern tests/conftest.py uses for suite hermeticity.
      if (PyRun_SimpleString(
              "import jax\n"
              "jax.config.update('jax_platforms', 'cpu')\n") != 0) {
        last_error() = "bootstrap: failed to pin jax to the cpu backend";
        rc = -1;
      }
    }
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    if (repo_root && *repo_root) {
      PyObject* p = PyUnicode_FromString(repo_root);
      PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
    if (rc == 0) {
      PyObject* mod = PyImport_ImportModule(module);
      if (!mod) {
        capture_py_error(module);
        rc = -1;
      } else {
        Py_DECREF(mod);
      }
    }
  }
  if (embedded) {
    // Release the GIL the init thread acquired with Py_InitializeEx so
    // other C threads can enter via PyGILState_Ensure.
    PyEval_SaveThread();
  }
  return rc;
}

}  // namespace ptn_embed

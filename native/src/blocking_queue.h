// Bounded MPMC blocking queue.
//
// TPU-native counterpart of the reference's concurrency primitives
// (framework/blocking_queue.h:26 `BlockingQueue`, reader/
// lod_tensor_blocking_queue.h `LoDTensorBlockingQueue`): a capacity-bounded
// queue with close semantics used between parse workers and the consumer
// that stages batches for host→device infeed.
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>
#include <utility>

namespace ptn {

template <typename T>
class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity) : capacity_(capacity) {}

  // Returns false iff the queue was closed before space became available.
  bool Push(T item) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return closed_ || q_.size() < capacity_; });
    if (closed_) return false;
    q_.push_back(std::move(item));
    not_empty_.notify_one();
    return true;
  }

  // Returns false iff the queue is closed AND drained.
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  // After Close: pushes fail, pops drain then fail.
  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  void Reopen() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = false;
  }

  size_t Size() const {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

  bool Closed() const {
    std::lock_guard<std::mutex> lk(mu_);
    return closed_;
  }

 private:
  mutable std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<T> q_;
  size_t capacity_;
  bool closed_ = false;
};

}  // namespace ptn

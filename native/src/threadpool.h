// Fixed-size worker pool.
//
// Counterpart of the reference's framework/threadpool.{h,cc} (used by its
// threaded SSA executors and async data feeders). Here it drives parse
// workers in the data feed and async host-side work.
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace ptn {

class ThreadPool {
 public:
  explicit ThreadPool(int n_threads) {
    for (int i = 0; i < n_threads; ++i) {
      workers_.emplace_back([this] { Loop(); });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      tasks_.push(std::move(fn));
    }
    cv_.notify_one();
  }

  // Blocks until every submitted task has finished.
  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    idle_cv_.wait(lk, [&] { return tasks_.empty() && active_ == 0; });
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        task = std::move(tasks_.front());
        tasks_.pop();
        ++active_;
      }
      task();
      {
        std::lock_guard<std::mutex> lk(mu_);
        --active_;
        if (tasks_.empty() && active_ == 0) idle_cv_.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_, idle_cv_;
  int active_ = 0;
  bool stop_ = false;
};

}  // namespace ptn

// Host buffer pool: auto-growth best-fit allocator.
//
// Counterpart of the reference's memory facade (memory/malloc.h,
// allocation/allocator_facade.cc:48 choosing `auto_growth` /
// `naive_best_fit` strategies, allocation/auto_growth_best_fit_allocator.cc).
// On TPU the device heap belongs to XLA; what the framework still owns is
// HOST staging memory for the input pipeline — parse buffers and batch
// staging areas reused across steps. This allocator keeps a best-fit free
// list over large malloc'd regions so steady-state batch assembly does no
// system allocation.
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace ptn {

class BufferPool {
 public:
  // chunk_size: granularity of growth mallocs (default 16 MiB).
  explicit BufferPool(size_t chunk_size = 16u << 20)
      : chunk_size_(chunk_size) {}

  ~BufferPool() {
    for (void* r : regions_) std::free(r);
  }

  void* Alloc(size_t size) {
    if (size == 0) size = 1;
    size = Align(size);
    std::lock_guard<std::mutex> lk(mu_);
    // Best fit: smallest free block >= size.
    auto it = free_by_size_.lower_bound(size);
    if (it == free_by_size_.end()) {
      Grow(size);
      it = free_by_size_.lower_bound(size);
    }
    char* base = it->second;
    size_t block = it->first;
    EraseFree(it);
    if (block - size >= kMinSplit) {
      InsertFree(block - size, base + size);
      block = size;
    }
    allocated_[base] = block;
    bytes_in_use_ += block;
    peak_in_use_ = bytes_in_use_ > peak_in_use_ ? bytes_in_use_ : peak_in_use_;
    ++n_allocs_;
    return base;
  }

  void Free(void* p) {
    if (p == nullptr) return;
    std::lock_guard<std::mutex> lk(mu_);
    auto it = allocated_.find(static_cast<char*>(p));
    if (it == allocated_.end()) return;
    size_t block = it->second;
    bytes_in_use_ -= block;
    char* base = it->first;
    allocated_.erase(it);
    // Coalesce with a free right-neighbour if adjacent.
    auto nb = free_by_addr_.find(base + block);
    if (nb != free_by_addr_.end()) {
      size_t nb_size = nb->second;
      EraseFreeAddr(nb);
      block += nb_size;
    }
    InsertFree(block, base);
  }

  struct Stats {
    uint64_t bytes_in_use, bytes_reserved, peak_in_use, n_allocs;
  };
  Stats GetStats() const {
    std::lock_guard<std::mutex> lk(mu_);
    return {bytes_in_use_, bytes_reserved_, peak_in_use_, n_allocs_};
  }

 private:
  static constexpr size_t kAlign = 64;  // cache line; SIMD-friendly
  static constexpr size_t kMinSplit = 256;

  static size_t Align(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

  void Grow(size_t at_least) {
    size_t n = at_least > chunk_size_ ? Align(at_least) : chunk_size_;
    void* r = nullptr;
    if (posix_memalign(&r, kAlign, n) != 0 || r == nullptr) return;
    regions_.push_back(r);
    bytes_reserved_ += n;
    InsertFree(n, static_cast<char*>(r));
  }

  void InsertFree(size_t size, char* base) {
    auto it = free_by_size_.emplace(size, base);
    free_by_addr_[base] = size;
    (void)it;
  }
  void EraseFree(std::multimap<size_t, char*>::iterator it) {
    free_by_addr_.erase(it->second);
    free_by_size_.erase(it);
  }
  void EraseFreeAddr(std::map<char*, size_t>::iterator it) {
    auto range = free_by_size_.equal_range(it->second);
    for (auto i = range.first; i != range.second; ++i) {
      if (i->second == it->first) {
        free_by_size_.erase(i);
        break;
      }
    }
    free_by_addr_.erase(it);
  }

  size_t chunk_size_;
  mutable std::mutex mu_;
  std::multimap<size_t, char*> free_by_size_;
  std::map<char*, size_t> free_by_addr_;
  std::unordered_map<char*, size_t> allocated_;
  std::vector<void*> regions_;
  uint64_t bytes_in_use_ = 0, bytes_reserved_ = 0, peak_in_use_ = 0,
           n_allocs_ = 0;
};

}  // namespace ptn

// Host-side event profiler with chrome://tracing export.
//
// Counterpart of the reference's platform/profiler.{h,cc} (`RecordEvent`
// RAII :81, Enable/DisableProfiler state machine :166) + tools/timeline.py
// (proto → chrome trace). Host phases (program build, lowering, infeed,
// step dispatch) are recorded here; device-side events come from the jax
// profiler — paddle_tpu/profiler.py merges both, mirroring the reference's
// host+CUPTI merged timeline (platform/device_tracer.cc:58).
#include "profiler.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <mutex>
#include <thread>
#include <vector>

namespace ptn {

namespace {

struct Event {
  const char* phase;  // "B" or "E" (begin/end)
  std::string name;
  uint64_t ts_us;
  uint64_t tid;
};

struct State {
  std::mutex mu;
  std::vector<Event> events;
  std::atomic<bool> enabled{false};
  std::chrono::steady_clock::time_point origin =
      std::chrono::steady_clock::now();
};

State* state() {
  static State s;
  return &s;
}

uint64_t NowUs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::microseconds>(
          std::chrono::steady_clock::now() - state()->origin)
          .count());
}

uint64_t Tid() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id()) & 0xffff;
}

}  // namespace

void ProfilerEnable() { state()->enabled = true; }

void ProfilerDisable() { state()->enabled = false; }

void ProfilerReset() {
  std::lock_guard<std::mutex> lk(state()->mu);
  state()->events.clear();
  state()->origin = std::chrono::steady_clock::now();
}

void ProfilerPush(const char* name) {
  State* s = state();
  if (!s->enabled.load(std::memory_order_relaxed)) return;
  uint64_t ts = NowUs(), tid = Tid();
  std::lock_guard<std::mutex> lk(s->mu);
  s->events.push_back({"B", name, ts, tid});
}

void ProfilerPop(const char* name) {
  State* s = state();
  if (!s->enabled.load(std::memory_order_relaxed)) return;
  uint64_t ts = NowUs(), tid = Tid();
  std::lock_guard<std::mutex> lk(s->mu);
  s->events.push_back({"E", name, ts, tid});
}

int ProfilerDumpChromeTrace(const char* path) {
  State* s = state();
  std::lock_guard<std::mutex> lk(s->mu);
  FILE* f = std::fopen(path, "w");
  if (f == nullptr) return -1;
  std::fprintf(f, "{\"traceEvents\":[\n");
  for (size_t i = 0; i < s->events.size(); ++i) {
    const Event& e = s->events[i];
    std::fprintf(f,
                 "{\"name\":\"%s\",\"ph\":\"%s\",\"pid\":0,\"tid\":%llu,"
                 "\"ts\":%llu}%s\n",
                 e.name.c_str(), e.phase,
                 static_cast<unsigned long long>(e.tid),
                 static_cast<unsigned long long>(e.ts_us),
                 i + 1 < s->events.size() ? "," : "");
  }
  std::fprintf(f, "]}\n");
  std::fclose(f);
  return static_cast<int>(s->events.size());
}

}  // namespace ptn

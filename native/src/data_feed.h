// Multi-threaded training-data feed.
//
// TPU-native counterpart of the reference's DataFeed/Dataset stack
// (framework/data_feed.h:61 `DataFeed`, :222 `MultiSlotDataFeed`,
// framework/data_set.h:92 `Dataset::LoadIntoMemory`, :102 shuffle): parse
// worker threads read MultiSlot-format text files, assemble samples, and a
// batcher packs fixed-shape dense batches (TPU needs static shapes — ragged
// slots are padded/truncated to `dim` and the true lengths are emitted
// alongside, replacing LoD metadata). Batches flow through a bounded
// BlockingQueue to the Python host-infeed loop.
//
// MultiSlot text format (data_feed.cc parser in the reference): each line is
// one sample; for each slot in config order: `<n> <v1> ... <vn>`.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "allocator.h"
#include "blocking_queue.h"

namespace ptn {

enum class SlotType : int32_t { kFloat32 = 0, kInt64 = 1 };

struct SlotDesc {
  std::string name;
  SlotType type;
  int64_t dim;   // values per sample; shorter rows padded, longer truncated
  bool dense;    // dense: exactly dim values expected (no length output)
};

// One parsed sample: per-slot raw values.
struct Sample {
  // flat storage: per slot, the parsed values (float or int64 view)
  std::vector<std::vector<float>> fvals;
  std::vector<std::vector<int64_t>> ivals;
};

// A packed batch: per slot one contiguous buffer [batch, dim] plus a
// lengths vector [batch] holding the pre-pad value counts.
struct Batch {
  int64_t batch_size = 0;
  std::vector<void*> buffers;          // slot-ordered, BufferPool-owned
  std::vector<std::vector<int64_t>> lengths;
};

class DataFeed {
 public:
  DataFeed(std::vector<SlotDesc> slots, int64_t batch_size,
           size_t queue_capacity, bool drop_last)
      : slots_(std::move(slots)),
        batch_size_(batch_size),
        drop_last_(drop_last),
        queue_(queue_capacity) {}

  ~DataFeed() { Stop(); }

  void AddFile(const std::string& path) { files_.push_back(path); }

  void SetShuffle(bool on, uint64_t seed) {
    shuffle_ = on;
    seed_ = seed;
  }

  // Launch n parse workers + 1 batcher. Each worker takes whole files off a
  // shared index; parsed samples flow to the batcher through sample_q_.
  void Start(int n_threads);

  // Pops the next batch; false at end of epoch. Caller owns the buffers and
  // must return them via ReleaseBatch.
  bool Next(Batch* out) { return queue_.Pop(out); }

  void ReleaseBatch(Batch* b) {
    for (void* p : b->buffers) pool_.Free(p);
    b->buffers.clear();
  }

  void Stop();

  BufferPool::Stats PoolStats() const { return pool_.GetStats(); }
  uint64_t samples_parsed() const { return samples_parsed_.load(); }
  uint64_t parse_errors() const { return parse_errors_.load(); }
  int64_t MaxBatch() const { return batch_size_; }
  size_t SlotRowBytes(size_t si) const {
    const auto& s = slots_[si];
    return static_cast<size_t>(s.dim) *
           (s.type == SlotType::kFloat32 ? 4 : 8);
  }

 private:
  void ParseWorker();
  void BatchWorker();
  bool ParseLine(const char* line, size_t len, Sample* s);
  void PackBatch(std::vector<Sample>& buf, Batch* b);

  std::vector<SlotDesc> slots_;
  int64_t batch_size_;
  bool drop_last_;
  bool shuffle_ = false;
  uint64_t seed_ = 0;

  std::vector<std::string> files_;
  std::atomic<size_t> next_file_{0};
  std::atomic<int> live_parsers_{0};
  std::atomic<uint64_t> samples_parsed_{0};
  std::atomic<uint64_t> parse_errors_{0};

  BlockingQueue<Batch> queue_;
  std::unique_ptr<BlockingQueue<Sample>> sample_q_;
  std::vector<std::thread> parse_threads_;
  std::thread batch_thread_;
  BufferPool pool_;
  bool running_ = false;
};

}  // namespace ptn

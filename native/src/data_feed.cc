#include "data_feed.h"

#include <cstdio>
#include <cstring>
#include <algorithm>

namespace ptn {

void DataFeed::Start(int n_threads) {
  Stop();
  queue_.Reopen();
  next_file_ = 0;
  running_ = true;
  // sample queue sized to keep parsers ahead of the batcher without
  // unbounded memory
  sample_q_.reset(new BlockingQueue<Sample>(
      static_cast<size_t>(batch_size_) * 4 + 64));
  if (n_threads < 1) n_threads = 1;
  live_parsers_ = n_threads;
  for (int i = 0; i < n_threads; ++i) {
    parse_threads_.emplace_back([this] { ParseWorker(); });
  }
  batch_thread_ = std::thread([this] { BatchWorker(); });
}

void DataFeed::Stop() {
  if (!running_) return;
  running_ = false;
  if (sample_q_) sample_q_->Close();
  queue_.Close();
  for (auto& t : parse_threads_) t.join();
  parse_threads_.clear();
  if (batch_thread_.joinable()) batch_thread_.join();
  // drain unreturned batches
  Batch b;
  while (queue_.Pop(&b)) ReleaseBatch(&b);
}

void DataFeed::ParseWorker() {
  std::string content;
  for (;;) {
    size_t idx = next_file_.fetch_add(1);
    if (idx >= files_.size()) break;
    FILE* f = std::fopen(files_[idx].c_str(), "rb");
    if (f == nullptr) {
      parse_errors_.fetch_add(1);
      continue;
    }
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    content.resize(static_cast<size_t>(sz));
    size_t got = sz > 0 ? std::fread(&content[0], 1, sz, f) : 0;
    std::fclose(f);
    content.resize(got);

    const char* p = content.data();
    const char* end = p + content.size();
    while (p < end) {
      const char* nl = static_cast<const char*>(
          memchr(p, '\n', static_cast<size_t>(end - p)));
      size_t len = nl ? static_cast<size_t>(nl - p)
                      : static_cast<size_t>(end - p);
      if (len > 0) {
        Sample s;
        if (ParseLine(p, len, &s)) {
          samples_parsed_.fetch_add(1);
          if (!sample_q_->Push(std::move(s))) return;  // closed
        } else {
          parse_errors_.fetch_add(1);
        }
      }
      p = nl ? nl + 1 : end;
    }
  }
  // Last parser out closes the sample queue so the batcher can flush.
  if (live_parsers_.fetch_sub(1) == 1) sample_q_->Close();
}

bool DataFeed::ParseLine(const char* line, size_t len, Sample* s) {
  const char* p = line;
  const char* end = line + len;
  s->fvals.resize(slots_.size());
  s->ivals.resize(slots_.size());

  auto skip_ws = [&] {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  };
  auto read_i64 = [&](int64_t* out) -> bool {
    skip_ws();
    if (p >= end) return false;
    char* q = nullptr;
    long long v = strtoll(p, &q, 10);
    if (q == p) return false;
    p = q;
    *out = v;
    return true;
  };
  auto read_f32 = [&](float* out) -> bool {
    skip_ws();
    if (p >= end) return false;
    char* q = nullptr;
    float v = strtof(p, &q);
    if (q == p) return false;
    p = q;
    *out = v;
    return true;
  };

  for (size_t si = 0; si < slots_.size(); ++si) {
    int64_t n = 0;
    if (!read_i64(&n) || n < 0) return false;
    if (slots_[si].type == SlotType::kFloat32) {
      auto& v = s->fvals[si];
      v.resize(static_cast<size_t>(n));
      for (int64_t j = 0; j < n; ++j) {
        if (!read_f32(&v[static_cast<size_t>(j)])) return false;
      }
    } else {
      auto& v = s->ivals[si];
      v.resize(static_cast<size_t>(n));
      for (int64_t j = 0; j < n; ++j) {
        if (!read_i64(&v[static_cast<size_t>(j)])) return false;
      }
    }
  }
  return true;
}

void DataFeed::BatchWorker() {
  std::vector<Sample> buf;
  buf.reserve(static_cast<size_t>(batch_size_));
  std::mt19937_64 rng(seed_);
  std::vector<Sample> shuffle_buf;
  const size_t shuffle_window =
      shuffle_ ? static_cast<size_t>(batch_size_) * 64 : 0;

  Sample s;
  while (sample_q_->Pop(&s)) {
    if (shuffle_) {
      // reservoir-window shuffle (the reference's LocalShuffle analogue:
      // data_set.h:99) — bounded memory, decorrelates file order
      shuffle_buf.push_back(std::move(s));
      if (shuffle_buf.size() < shuffle_window) continue;
      size_t pick = rng() % shuffle_buf.size();
      std::swap(shuffle_buf[pick], shuffle_buf.back());
      s = std::move(shuffle_buf.back());
      shuffle_buf.pop_back();
    }
    buf.push_back(std::move(s));
    if (static_cast<int64_t>(buf.size()) == batch_size_) {
      Batch b;
      PackBatch(buf, &b);
      buf.clear();
      if (!queue_.Push(std::move(b))) return;
    }
  }
  // drain the shuffle window
  while (!shuffle_buf.empty()) {
    buf.push_back(std::move(shuffle_buf.back()));
    shuffle_buf.pop_back();
    if (static_cast<int64_t>(buf.size()) == batch_size_) {
      Batch b;
      PackBatch(buf, &b);
      buf.clear();
      if (!queue_.Push(std::move(b))) return;
    }
  }
  if (!buf.empty() && !drop_last_) {
    Batch b;
    PackBatch(buf, &b);
    if (!queue_.Push(std::move(b))) return;
  }
  queue_.Close();
}

void DataFeed::PackBatch(std::vector<Sample>& buf, Batch* b) {
  const int64_t bs = static_cast<int64_t>(buf.size());
  b->batch_size = bs;
  b->buffers.resize(slots_.size());
  b->lengths.resize(slots_.size());
  for (size_t si = 0; si < slots_.size(); ++si) {
    const auto& slot = slots_[si];
    const size_t elem = slot.type == SlotType::kFloat32 ? 4 : 8;
    const size_t row = static_cast<size_t>(slot.dim) * elem;
    char* dst = static_cast<char*>(
        pool_.Alloc(static_cast<size_t>(bs) * row));
    std::memset(dst, 0, static_cast<size_t>(bs) * row);
    auto& lens = b->lengths[si];
    lens.resize(static_cast<size_t>(bs));
    for (int64_t i = 0; i < bs; ++i) {
      char* out = dst + static_cast<size_t>(i) * row;
      if (slot.type == SlotType::kFloat32) {
        const auto& v = buf[static_cast<size_t>(i)].fvals[si];
        size_t n = std::min<size_t>(v.size(),
                                    static_cast<size_t>(slot.dim));
        std::memcpy(out, v.data(), n * 4);
        lens[static_cast<size_t>(i)] = static_cast<int64_t>(v.size());
      } else {
        const auto& v = buf[static_cast<size_t>(i)].ivals[si];
        size_t n = std::min<size_t>(v.size(),
                                    static_cast<size_t>(slot.dim));
        std::memcpy(out, v.data(), n * 8);
        lens[static_cast<size_t>(i)] = static_cast<int64_t>(v.size());
      }
    }
    b->buffers[si] = dst;
  }
}

}  // namespace ptn

// Pure-C++ training entry test: build+save a model (via embedded
// setup), then LOAD and TRAIN it entirely through the C ABI — the
// counterpart of the reference's train/demo/demo_trainer.cc +
// train/test_train_recognize_digits.cc.
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "test_deadline.h"

extern "C" {
int ptn_trainer_init(const char* repo_root);
void* ptn_trainer_load(const char* model_dir);
double ptn_trainer_run_step(void* handle, int n, const char** names,
                            const void** bufs, const uint64_t* nbytes,
                            const char** dtypes, const int64_t* shapes,
                            const int* ranks);
int ptn_trainer_save(void* handle, const char* model_dir);
void ptn_trainer_destroy(void* handle);
int ptn_trainer_exec(const char* code);
const char* ptn_trainer_last_error();
}

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAILED: %s (line %d): %s\n", #cond,       \
                   __LINE__, ptn_trainer_last_error());               \
      std::exit(1);                                                   \
    }                                                                 \
  } while (0)

int main(int argc, char** argv) {
  ptn_test::install_deadline("trainer_test");
  const char* repo = argc > 1 ? argv[1] : "..";
  CHECK(ptn_trainer_init(repo) == 0);

  // Build + save a digit-classifier program (the reference demo trains
  // recognize_digits; same shape of model at toy scale).
  const char* setup = R"PY(
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.native_trainer import save_trainer_model

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    img = layers.data("img", shape=[16, 64], dtype="float32",
                      append_batch_size=False)
    label = layers.data("label", shape=[16, 1], dtype="int64",
                        append_batch_size=False)
    h = layers.fc(img, size=32, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
save_trainer_model("/tmp/ptn_trainer_model", main, startup, loss.name)
)PY";
  CHECK(ptn_trainer_exec(setup) == 0);

  void* tr = ptn_trainer_load("/tmp/ptn_trainer_model");
  CHECK(tr != nullptr);

  // Synthetic separable data generated in C: class = argmax-ish of a
  // linear map, so the model can actually learn it.
  std::mt19937 rng(7);
  std::normal_distribution<float> nd(0.f, 1.f);
  const int B = 16, D = 64;
  std::vector<float> img(B * D);
  std::vector<int32_t> label(B);

  const char* names[2] = {"img", "label"};
  const char* dtypes[2] = {"float32", "int32"};
  const int64_t shapes[4] = {B, D, B, 1};
  const int ranks[2] = {2, 2};

  double first = 0, last = 0;
  for (int step = 0; step < 40; ++step) {
    for (int i = 0; i < B; ++i) {
      float best = -1e30f;
      int cls = 0;
      for (int d = 0; d < D; ++d) {
        img[i * D + d] = nd(rng);
        if (d < 10 && img[i * D + d] > best) {
          best = img[i * D + d];
          cls = d;
        }
      }
      label[i] = cls;
    }
    const void* bufs[2] = {img.data(), label.data()};
    const uint64_t nbytes[2] = {img.size() * sizeof(float),
                                label.size() * sizeof(int32_t)};
    double loss = ptn_trainer_run_step(tr, 2, names, bufs, nbytes,
                                       dtypes, shapes, ranks);
    CHECK(!std::isnan(loss));
    if (step == 0) first = loss;
    last = loss;
  }
  std::printf("c-trainer: loss %.4f -> %.4f over 40 steps\n", first, last);
  CHECK(last < first * 0.8);

  CHECK(ptn_trainer_save(tr, "/tmp/ptn_trainer_model_out") == 0);
  ptn_trainer_destroy(tr);
  std::printf("trainer_test OK\n");
  return 0;
}

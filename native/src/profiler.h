#pragma once

namespace ptn {

void ProfilerEnable();
void ProfilerDisable();
void ProfilerReset();
void ProfilerPush(const char* name);
void ProfilerPop(const char* name);
// Writes chrome://tracing JSON; returns event count or -1.
int ProfilerDumpChromeTrace(const char* path);

}  // namespace ptn

"""OpTest harness: single-op output + numeric-gradient checks.

Reference analogue: python/paddle/fluid/tests/unittests/op_test.py — the
workhorse of the reference's test strategy (SURVEY.md §4). A subclass
declares `op_type`, `inputs`, `outputs`, `attrs`; `check_output()` runs the
single op through a scratch Scope+Executor (so the whole Program-IR →
XLA lowering path is exercised, not the jnp functions directly);
`check_grad()` compares the analytic gradient produced by
`append_backward` (generic-vjp grad ops) against central finite
differences (reference get_numeric_gradient, op_test.py:47).

Keep test tensors tiny: the numeric pass runs 2*numel forward executions
(each hits the executor's executable cache after the first).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.backward import append_backward
from paddle_tpu.framework import grad_var_name


def _as_entries(slot_val, slot):
    """Normalise a slot declaration to [(var_name, np.ndarray), ...]."""
    if isinstance(slot_val, (list, tuple)) and slot_val and \
            isinstance(slot_val[0], (list, tuple)):
        return [(n, np.asarray(a)) for n, a in slot_val]
    return [(slot, np.asarray(slot_val))]


class OpTest:
    """Subclass per op; call self.setup() from the test, then check_*()."""

    op_type: str = None
    inputs: dict = None
    outputs: dict = None
    attrs: dict = None

    def setup(self):  # subclasses override
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _ensure(self):
        if self.inputs is None:
            self.setup()
        self.attrs = self.attrs or {}

    def _build_program(self, grad_inputs=()):
        """Fresh program with one op; returns (main, in_map, out_names)."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            blk = main.global_block()
            in_map = {}   # slot -> [names]
            feeds = {}    # name -> array
            for slot, val in self.inputs.items():
                names = []
                for name, arr in _as_entries(val, slot):
                    blk.create_var(
                        name=name, shape=list(arr.shape),
                        dtype=str(arr.dtype),
                        stop_gradient=name not in grad_inputs,
                        is_data=True)
                    feeds[name] = arr
                    names.append(name)
                in_map[slot] = names
            out_map = {}
            for slot, val in self.outputs.items():
                names = []
                for name, _ in _as_entries(val, slot):
                    blk.create_var(name=name, stop_gradient=False)
                    names.append(name)
                out_map[slot] = names
            blk.append_op(self.op_type, inputs=in_map, outputs=out_map,
                          attrs=dict(self.attrs))
        return main, feeds, out_map

    # ------------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=()):
        self._ensure()
        main, feeds, out_map = self._build_program()
        fetch, expect = [], []
        for slot, val in self.outputs.items():
            for name, arr in _as_entries(val, slot):
                if name in no_check_set or slot in no_check_set:
                    continue
                fetch.append(name)
                expect.append(arr)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            got = exe.run(main, feed=feeds, fetch_list=fetch)
        for name, e, g in zip(fetch, expect, got):
            np.testing.assert_allclose(
                g, e, atol=atol, rtol=rtol,
                err_msg=f"{self.op_type}: output {name!r} mismatch")

    # ------------------------------------------------------------------
    def _loss_program(self, grad_inputs, output_names):
        """One-op program + mean/sum reduction to a scalar loss var."""
        main, feeds, out_map = self._build_program(grad_inputs)
        blk = main.global_block()
        with fluid.program_guard(main):
            means = []
            for slot, names in out_map.items():
                for n in names:
                    if output_names and n not in output_names and \
                            slot not in output_names:
                        continue
                    m = blk.create_var(name=f"{n}__mean",
                                       stop_gradient=False)
                    blk.append_op("mean", inputs={"X": [n]},
                                  outputs={"Out": [m.name]})
                    means.append(m.name)
            assert means, "no outputs selected for gradient check"
            loss = blk.create_var(name="loss__", stop_gradient=False)
            blk.append_op("sum", inputs={"X": means},
                          outputs={"Out": [loss.name]})
        return main, feeds, blk.var("loss__")

    def check_grad(self, inputs_to_check, output_names=None,
                   max_relative_error=0.005, numeric_grad_delta=5e-3,
                   atol=1e-4):
        self._ensure()
        inputs_to_check = list(inputs_to_check)
        # map slot names to var names
        grad_vars = []
        for slot in inputs_to_check:
            for name, _ in _as_entries(self.inputs[slot], slot):
                grad_vars.append(name)
        if isinstance(output_names, str):
            output_names = [output_names]

        main, feeds, loss = self._loss_program(grad_vars, output_names)
        with fluid.program_guard(main):
            append_backward(loss)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            analytic = exe.run(
                main, feed=feeds,
                fetch_list=[grad_var_name(n) for n in grad_vars])

        # numeric: central differences of the scalar loss
        fwd, ffeeds, floss = self._loss_program((), output_names)
        fexe = fluid.Executor()
        scope = fluid.Scope()

        def run_loss():
            with fluid.scope_guard(scope):
                return float(fexe.run(fwd, feed=ffeeds,
                                      fetch_list=[loss.name])[0])

        for name, a_grad in zip(grad_vars, analytic):
            x = ffeeds[name]
            num = np.zeros_like(x, dtype=np.float64).reshape(-1)
            flat = x.reshape(-1)
            delta = numeric_grad_delta
            for i in range(flat.size):
                orig = flat[i]
                flat[i] = orig + delta
                hi = run_loss()
                flat[i] = orig - delta
                lo = run_loss()
                flat[i] = orig
                num[i] = (hi - lo) / (2.0 * delta)
            num = num.reshape(x.shape)
            abs_a = np.abs(a_grad)
            denom = np.maximum(np.maximum(abs_a, np.abs(num)), 1e-3)
            rel = np.abs(a_grad - num) / denom
            bad = rel > max_relative_error
            close = np.abs(a_grad - num) < atol
            if np.any(bad & ~close):
                i = np.unravel_index(np.argmax(rel * ~close), rel.shape)
                raise AssertionError(
                    f"{self.op_type}: grad of {name!r} mismatch at {i}: "
                    f"analytic={a_grad[i]} numeric={num[i]} "
                    f"rel={rel[i]:.4g}")


def make_op_test(op_type, inputs, outputs, attrs=None):
    """Inline OpTest without subclassing."""
    t = OpTest()
    t.op_type = op_type
    t.inputs = inputs
    t.outputs = outputs
    t.attrs = attrs or {}
    return t

"""Appendix-B API surface parity test: every public name the
reference exports (SURVEY.md App. B, extracted from fluid's __all__)
must resolve on this package. Guards against regressions as modules
are reorganized."""
import paddle_tpu as fluid

SURFACE = {
 "layers": """fc center_loss embedding dynamic_lstm dynamic_lstmp dynamic_gru
 gru_unit linear_chain_crf crf_decoding cos_sim cross_entropy bpr_loss
 square_error_cost chunk_eval sequence_conv conv2d conv3d sequence_pool
 sequence_softmax softmax pool2d pool3d adaptive_pool2d adaptive_pool3d
 batch_norm instance_norm data_norm beam_search_decode conv2d_transpose
 conv3d_transpose sequence_expand sequence_expand_as sequence_pad
 sequence_unpad lstm_unit reduce_sum reduce_mean reduce_max reduce_min
 reduce_prod reduce_all reduce_any sequence_first_step sequence_last_step
 sequence_slice dropout split ctc_greedy_decoder edit_distance l2_normalize
 matmul topk warpctc sequence_reshape transpose im2sequence nce
 sampled_softmax_with_cross_entropy hsigmoid beam_search row_conv multiplex
 layer_norm group_norm spectral_norm softmax_with_cross_entropy smooth_l1
 one_hot autoincreased_step_counter reshape squeeze unsqueeze lod_reset
 lod_append lrn pad pad_constant_like label_smooth roi_pool roi_align
 dice_loss image_resize image_resize_short resize_bilinear resize_trilinear
 resize_nearest gather gather_nd scatter scatter_nd_add scatter_nd
 sequence_scatter random_crop mean_iou relu selu log crop crop_tensor
 rank_loss margin_rank_loss elu relu6 pow stanh hard_sigmoid swish prelu
 brelu leaky_relu soft_relu flatten sequence_mask stack pad2d unstack
 sequence_enumerate unique unique_with_counts expand expand_as
 sequence_concat scale elementwise_add elementwise_div elementwise_sub
 elementwise_mul elementwise_max elementwise_min elementwise_pow
 elementwise_mod elementwise_floordiv uniform_random_batch_size_like
 gaussian_random sampling_id gaussian_random_batch_size_like sum slice
 strided_slice shape rank size logical_and logical_or logical_xor
 logical_not clip clip_by_norm mean mul sigmoid_cross_entropy_with_logits
 maxout space_to_depth affine_grid sequence_reverse affine_channel
 similarity_focus hash grid_sampler log_loss add_position_encoding
 bilinear_tensor_product merge_selected_rows get_tensor_from_selected_rows
 lstm shuffle_channel temporal_shift py_func psroi_pool prroi_pool
 teacher_student_sigmoid_loss huber_loss kldiv_loss npair_loss pixel_shuffle
 fsp_matrix continuous_value_model where sign deformable_conv unfold
 deformable_roi_pooling filter_by_instag shard_index hard_swish gather_tree
 mse_loss uniform_random
 create_tensor create_parameter create_global_var cast
 tensor_array_to_tensor concat sums assign fill_constant_batch_size_like
 fill_constant argmin argmax argsort ones zeros reverse has_inf has_nan
 isfinite range linspace zeros_like ones_like diag eye
 While Switch increment array_write create_array less_than less_equal
 greater_than greater_equal equal not_equal array_read array_length IfElse
 DynamicRNN StaticRNN reorder_lod_tensor_by_rank Print is_empty
 data read_file double_buffer py_reader create_py_reader_by_data load
 prior_box density_prior_box multi_box_head bipartite_match target_assign
 detection_output ssd_loss rpn_target_assign retinanet_target_assign
 sigmoid_focal_loss anchor_generator roi_perspective_transform
 generate_proposal_labels generate_proposals generate_mask_labels
 iou_similarity box_coder polygon_box_transform yolov3_loss yolo_box
 box_clip multiclass_nms retinanet_detection_output
 distribute_fpn_proposals box_decoder_and_assign collect_fpn_proposals
 exponential_decay natural_exp_decay inverse_time_decay polynomial_decay
 piecewise_decay noam_decay cosine_decay linear_lr_warmup
 accuracy auc
 Uniform Normal Categorical MultivariateNormalDiag
 RNNCell GRUCell LSTMCell Decoder BeamSearchDecoder rnn dynamic_decode""",
 "metrics": "MetricBase CompositeMetric Precision Recall Accuracy "
            "ChunkEvaluator EditDistance DetectionMAP Auc",
 "initializer": "Constant Uniform Normal TruncatedNormal Xavier Bilinear "
                "MSRA NumpyArrayInitializer force_init_on_cpu "
                "init_on_cpu",
 "optimizer": "SGD Momentum Adagrad Adam Adamax Dpsgd DecayedAdagrad Ftrl "
              "RMSProp Adadelta LarsMomentum DGCMomentum Lamb ModelAverage "
              "ExponentialMovingAverage PipelineOptimizer "
              "LookaheadOptimizer RecomputeOptimizer",
 "regularizer": "L1Decay L2Decay",
 "clip": "set_gradient_clip ErrorClipByValue GradientClipByValue "
         "GradientClipByNorm GradientClipByGlobalNorm",
 "io": "save_vars save_params save_persistables load_vars load_params "
       "load_persistables save_inference_model load_inference_model batch "
       "save load",
 "dygraph": "Conv2D Conv3D Pool2D FC BatchNorm Embedding GRUUnit LayerNorm "
            "NCE PRelu BilinearTensorProduct Conv2DTranspose "
            "Conv3DTranspose GroupNorm SpectralNorm TreeConv",
 "": "Program program_guard default_main_program default_startup_program "
     "Executor ParallelExecutor CompiledProgram BuildStrategy "
     "ExecutionStrategy CPUPlace Scope global_scope scope_guard LoDTensor "
     "LoDTensorArray DataFeeder WeightNormParamAttr ParamAttr name_scope "
     "unique_name gradients profiler install_check data embedding one_hot "
     "average",
}


def test_api_surface_complete():
    missing = {}
    for modname, names in SURFACE.items():
        mod = fluid if modname == "" else getattr(fluid, modname, None)
        if modname == "dygraph":
            from paddle_tpu.dygraph import nn as mod
        assert mod is not None, f"module {modname} missing"
        gaps = [n for n in names.split() if not hasattr(mod, n)]
        if gaps:
            missing[modname or "fluid"] = gaps
    assert not missing, missing

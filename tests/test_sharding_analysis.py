"""Static sharding analyzer tests (analysis/sharding.py,
docs/static_analysis.md): propagation-rule units, the PTV06x findings,
the FLAGS_sharding_verify pre-compile gate in Executor._resolve_step
and ServingEngine.warmup (rejection BEFORE any compile), and the
one-oracle reconciliation between the per-op communication-cost model
and SpecLayout's closed-form gradient_sync_bytes."""
import contextlib
import io as pyio
import json
import os
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from jax.sharding import PartitionSpec as P
from paddle_tpu import layers
from paddle_tpu.analysis import (ProgramVerificationError,
                                 analyze_program_sharding)
from paddle_tpu.analysis.sharding import (_remap_reshape, reset_memo,
                                          sharding_gate)
from paddle_tpu.parallel.layout import MeshDims, SpecLayout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F32 = 4  # itemsize used by the byte assertions below


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


@contextlib.contextmanager
def _gate_flags(mode, mesh):
    """Flip the gate flags and clear the memo, restoring on exit."""
    prev = (fluid.FLAGS.sharding_verify, fluid.FLAGS.sharded_mesh)
    fluid.set_flags({"FLAGS_sharding_verify": mode,
                     "FLAGS_sharded_mesh": mesh})
    reset_memo()
    try:
        yield
    finally:
        fluid.set_flags({"FLAGS_sharding_verify": prev[0],
                         "FLAGS_sharded_mesh": prev[1]})
        reset_memo()


def _conflict_program():
    """Two shard_hints place the dp axis on different (batch-free)
    dims of the same tensor; the elementwise_add merge is the PTV060
    layout inconsistency."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8, 8], dtype="float32",
                        append_batch_size=False)
        a = layers.shard_hint(x, [None, "dp", None])
        b = layers.shard_hint(x, [None, None, "dp"])
        out = layers.elementwise_add(a, b)
    return main, startup, out


def _nondivisible_program():
    """shard_hint over a dim the mesh axis does not divide: PTV062."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6, 16], dtype="float32",
                        append_batch_size=False)
        out = layers.shard_hint(x, ["dp", None])
    return main, startup, out


# ---------------------------------------------------------------------------
# propagation-rule units
# ---------------------------------------------------------------------------

def _axis_size(p, sizes={"dp": 4, "tp": 2}):
    if isinstance(p, (tuple, list)):
        n = 1
        for a in p:
            n *= sizes.get(str(a), 1)
        return n
    return sizes.get(str(p), 1)


def test_remap_reshape_rules():
    # 1:1 dims carry their axis through
    parts, lost = _remap_reshape((8, 16), ("dp", None), (8, 16),
                                 _axis_size)
    assert parts == ("dp", None) and lost == []
    # merge: the leading in-dim's axis rides onto the merged out dim
    parts, lost = _remap_reshape((8, 16), ("dp", None), (128,),
                                 _axis_size)
    assert parts == ("dp",) and lost == []
    # merge: a non-leading sharded in-dim is lost (-> reshard)
    parts, lost = _remap_reshape((8, 16), (None, "dp"), (128,),
                                 _axis_size)
    assert parts == (None,) and lost == [1]
    # split: the axis lands on the leading out dim when it divides
    parts, lost = _remap_reshape((128,), ("dp",), (8, 16), _axis_size)
    assert parts == ("dp", None) and lost == []
    # split where the leading out dim does not divide: lost
    parts, lost = _remap_reshape((6,), ("dp",), (2, 3), _axis_size)
    assert parts == (None, None) and lost == [0]


def test_elementwise_conflict_is_ptv060():
    main, _, _ = _conflict_program()
    layout = SpecLayout(MeshDims((8,)))
    rep = analyze_program_sharding(main, layout)
    errs = rep.result.errors()
    assert errs and all(d.rule == "PTV060" for d in errs)
    assert rep.to_record()["counts"]["error"] == len(errs)


def test_shard_hint_nondivisible_is_ptv062():
    main, _, _ = _nondivisible_program()
    rep = analyze_program_sharding(main, SpecLayout(MeshDims((8,))))
    assert not rep.result.errors()
    assert any(d.rule == "PTV062" for d in rep.result.findings)
    # the hint was declined, so nothing is sharded and nothing moves
    assert rep.collective_bytes_per_step == 0


def test_matmul_contraction_costs():
    """Both-sides-sharded contraction prices a 2x partial-sum
    all-reduce; one-sided prices a gather of that operand. Mesh
    (1, 2) also covers the 1-sized-dp-axis edge case: feeds replicate
    (dp=1), only tp is live."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[16, 4], dtype="float32",
                        append_batch_size=False)
        a = layers.shard_hint(x, [None, "tp"])
        b = layers.shard_hint(y, ["tp", None])
        both = layers.matmul(a, b)     # contraction sharded both sides
        one = layers.matmul(a, y)      # ... and on one side only
    layout = SpecLayout(MeshDims((1, 2)))
    rep = analyze_program_sharding(main, layout)
    assert not rep.result.errors()
    kinds = {}
    for c in rep.costs:
        kinds.setdefault(c.kind, 0)
        kinds[c.kind] += c.bytes
    # partial sum: 2 x full out bytes (out [8,4] is replicated)
    assert kinds.get("all_reduce") == 2 * 8 * 4 * F32
    # one-sided: gather a's [8,16] out of its 2-way tp split
    a_bytes = 8 * 16 * F32
    assert kinds.get("reshard") == a_bytes - a_bytes // 2
    assert rep.reshard_bytes_per_step == kinds["reshard"]
    assert rep.collective_bytes_per_step == sum(kinds.values())
    assert both is not None and one is not None


def test_reduce_over_sharded_dim_prices_allreduce():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], dtype="float32",
                        append_batch_size=False)
        a = layers.shard_hint(x, [None, "tp"])
        s = layers.reduce_sum(a, dim=1)
    rep = analyze_program_sharding(main, SpecLayout(MeshDims((1, 2))))
    costs = [c for c in rep.costs if c.kind == "all_reduce"]
    assert len(costs) == 1
    # out [8] replicated: 2 x its full payload
    assert costs[0].bytes == 2 * 8 * F32
    assert s is not None


# ---------------------------------------------------------------------------
# mesh / layout edge cases
# ---------------------------------------------------------------------------

def test_mesh_dims_edge_cases():
    for bad in ((0,), (4, -1), (2, 2, 2, 2)):
        with pytest.raises(ValueError):
            MeshDims(bad)
    lay = SpecLayout(MeshDims((8, 1)))  # 1-sized model axis
    assert (lay.dp, lay.tp, lay.fsdp) == (8, 1, 1)
    assert lay.param_spec("w", (16, 16)) == P()  # no tp split at tp=1
    assert lay.feed_spec("x", (16, 4)) == P("dp")
    lay3 = SpecLayout(MeshDims((2, 2, 2)))
    assert lay3.fsdp_axis == "fsdp" and lay3.fsdp == 2
    # fsdp leading-dim weight shard composes with the tp column split
    assert lay3.param_spec("w", (8, 4)) == P("fsdp", "tp")
    assert lay3.shard_count("w", (8, 4)) == 4


def test_layout_fallbacks_become_ptv062():
    """A declined shard (non-divisible dim) recorded by the layout
    surfaces as a PTV062 finding on the report, not a silent drop."""
    main, _, _ = _nondivisible_program()
    layout = SpecLayout(MeshDims((8,)))
    rep = analyze_program_sharding(main, layout)
    assert layout.fallbacks  # feed_spec declined x's batch dim (6 % 8)
    wants = {d.var for d in rep.result.findings if d.rule == "PTV062"}
    assert "x" in wants


# ---------------------------------------------------------------------------
# the FLAGS_sharding_verify gate
# ---------------------------------------------------------------------------

def test_gate_modes_off_and_invalid():
    main, _, out = _nondivisible_program()
    with _gate_flags("off", "8"):
        assert sharding_gate(main) is None
    with _gate_flags("warn", ""):  # no layout in scope -> no-op
        assert sharding_gate(main) is None
    with _gate_flags("bogus", "8"):
        with pytest.raises(ValueError):
            sharding_gate(main)
    assert out is not None


def test_gate_warns_once_then_memoizes():
    main, _, out = _nondivisible_program()
    shapes = {"x": ((6, 16), "float32")}
    with _gate_flags("warn", "8"):
        with pytest.warns(UserWarning, match="sharding analysis"):
            rep1 = sharding_gate(main, feed_shapes=shapes,
                                 fetch_names=[out.name], where="t")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            rep2 = sharding_gate(main, feed_shapes=shapes,
                                 fetch_names=[out.name], where="t")
        assert rep2 is rep1  # memo hit, no re-analysis
        assert not [w for w in caught
                    if "sharding analysis" in str(w.message)]


def test_executor_gate_rejects_with_zero_compiles():
    """error mode: a layout-inconsistent program raises from
    _resolve_step BEFORE the executable-cache key — cache_stats()
    still shows zero compiles attempted — and keeps raising on every
    call (memoized analysis)."""
    main, _, out = _conflict_program()
    feed = {"x": np.zeros((2, 8, 8), np.float32)}
    with _gate_flags("error", "8"):
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            for _ in range(2):
                with pytest.raises(ProgramVerificationError,
                                   match="PTV060"):
                    exe.run(main, feed=feed, fetch_list=[out])
        stats = exe.cache_stats()
        assert stats["misses"] == 0 and stats["hits"] == 0, stats


def test_warmup_gate_rejects_before_ladder(tmp_path):
    """ServingEngine.warmup: the per-cell sharding gate rejects the
    saved layout-inconsistent model before the first ladder compile."""
    from paddle_tpu.serving import EngineConfig, ServingEngine

    main, startup, out = _conflict_program()
    d = str(tmp_path / "model")
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [out], exe,
                                      main_program=main)
    with _gate_flags("error", "8"):
        eng = ServingEngine(EngineConfig(d, max_batch_size=2,
                                         warmup=False))
        with pytest.raises(ProgramVerificationError, match="PTV060"):
            eng.warmup()
        assert eng.cache_stats()["misses"] == 0


# ---------------------------------------------------------------------------
# reconciliation: per-op cost model vs the closed form, one oracle
# ---------------------------------------------------------------------------

def test_cost_model_reconciles_with_closed_form(monkeypatch):
    """Over every bench builder x {dp8, dp4xtp2}: the per-op grad_sync
    component must agree with SpecLayout.gradient_sync_bytes within
    10%, and collective_bytes_estimate must BE the analyzer total (the
    delegation makes them one oracle). Startup compiles are stubbed —
    the analysis only reads the Program."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(fluid.Executor, "run",
                        lambda self, *a, **kw: [])
    for dims in ((8,), (4, 2)):
        for name, build in sorted(bench._CPU_TINY_BUILDS.items()):
            _, prog, _, _, _, _ = build()
            layout = SpecLayout(MeshDims(dims)).add_program(prog)
            rep = analyze_program_sharding(prog, layout)
            closed = layout.gradient_sync_bytes(prog)
            assert closed > 0, (name, dims)  # train programs sync grads
            drift = abs(rep.grad_sync_bytes - closed) / closed
            assert drift <= 0.10, (name, dims, rep.grad_sync_bytes,
                                   closed)
            assert rep.collective_bytes_per_step >= rep.grad_sync_bytes
        # one-oracle check once per mesh (it re-runs the analysis)
        assert layout.collective_bytes_estimate(prog) == \
            rep.collective_bytes_per_step, (name, dims)


def test_layout_total_under_fsdp_mesh(monkeypatch):
    """Resolution stays total on a 3-axis dp x tp x fsdp mesh: every
    persistable of every bench builder gets a spec whose shard count
    divides the mesh."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(fluid.Executor, "run",
                        lambda self, *a, **kw: [])
    mesh = MeshDims((2, 2, 2))
    for name, build in sorted(bench._CPU_TINY_BUILDS.items()):
        _, prog, _, _, _, _ = build()
        layout = SpecLayout(mesh).add_program(prog)
        persist = [v for v in prog.list_vars()
                   if getattr(v, "persistable", False)]
        assert len(layout) == len(persist), name
        for v in persist:
            n = layout.shard_count(v.name, v.shape)
            assert n >= 1 and mesh.size % n == 0, (name, v.name, n)


# ---------------------------------------------------------------------------
# artifact schema, report section, ledger rows
# ---------------------------------------------------------------------------

def test_sharding_report_schema_and_render(tmp_path):
    main, _, _ = _conflict_program()
    rep = analyze_program_sharding(main, SpecLayout(MeshDims((8,))))
    rec = rep.to_record(model="conflict")
    v = _tools("validate_bench_json")
    assert v.validate_sharding_report(rec, "r0") == []
    assert any("mesh_shape" in e for e in v.validate_sharding_report(
        dict(rec, mesh_shape=[]), "r0"))
    assert any("collective" in e for e in v.validate_sharding_report(
        dict(rec, collective_bytes_per_step=-1), "r0"))
    log = tmp_path / "shard.jsonl"
    log.write_text(json.dumps(rec) + "\n")
    assert v.validate_file(str(log)) == []
    buf = pyio.StringIO()
    rc = _tools("metrics_report").report(str(log), out=buf)
    text = buf.getvalue()
    assert rc == 0
    assert "-- sharding analysis" in text
    assert "conflict" in text and "PTV060" in text


def test_perf_ledger_sharding_rows():
    pl = _tools("perf_ledger")
    rows, skipped = pl.rows_from_record(
        {"kind": "sharded_bench", "metric": "gpt_tok_s", "ts": 0.0,
         "mesh_shape": [8], "per_chip_throughput": 10.0,
         "collective_bytes_per_step": 4096,
         "grad_sync_bytes_per_step": 2048})
    assert skipped == 0
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["collective_bytes_per_step"]["value"] == 4096.0
    assert by_metric["collective_vs_grad_sync_ratio"]["value"] == 2.0
    # sharding_report records land as predicted-bytes rows too
    main, _, _ = _conflict_program()
    rep = analyze_program_sharding(main, SpecLayout(MeshDims((8,))))
    rows2, _ = pl.rows_from_record(rep.to_record(model="m"))
    metrics = {r["metric"] for r in rows2}
    assert {"collective_bytes_per_step", "reshard_bytes_per_step",
            "grad_sync_bytes"} <= metrics

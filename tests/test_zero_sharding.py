"""Sharded-executor tests: the GSPMD dp x tp path of docs/sharding.md
on the 8-device virtual CPU mesh (conftest forces
--xla_force_host_platform_device_count=8), checked for numerical parity
against single-device training — the test_dist_base.py loss-equivalence
pattern, extended to final params and compile-cache behaviour."""
import contextlib
import io as pyio
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from jax.sharding import PartitionSpec as P
from paddle_tpu.parallel.layout import (DATA_AXIS, FSDP_AXIS, MODEL_AXIS,
                                        MeshDims, SpecLayout,
                                        mesh_from_spec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


@contextlib.contextmanager
def _sharded_flags(mesh_spec):
    """Flip the (traced) gate flags, restoring on exit — they key the
    executable cache, so leaking them would poison later tests."""
    prev = (fluid.FLAGS.sharded_exec, fluid.FLAGS.sharded_mesh)
    fluid.set_flags({"FLAGS_sharded_exec": True,
                     "FLAGS_sharded_mesh": mesh_spec})
    try:
        yield
    finally:
        fluid.set_flags({"FLAGS_sharded_exec": prev[0],
                         "FLAGS_sharded_mesh": prev[1]})


# ---------------------------------------------------------------------------
# dp=8 / dp=4 x tp=2 training parity vs single device (tiny gpt builder)
# ---------------------------------------------------------------------------

_BATCH, _SEQ = 8, 16  # batch divides dp=8 and dp=4; d_model divides tp=2


def _tiny_gpt(optimizer="adamw"):
    from paddle_tpu.models import gpt
    cfg = gpt.gpt_small(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, max_seq_len=_SEQ, dropout=0.0,
                        attn_dropout=0.0, use_flash=False)
    opt_cls = None  # build_train default: AdamW (moment1/moment2 ZeRO)
    if optimizer == "momentum":
        from paddle_tpu import optimizer as opt
        opt_cls = lambda learning_rate: opt.MomentumOptimizer(  # noqa: E731
            learning_rate, momentum=0.9)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, _, _ = gpt.build_train(cfg, _BATCH, _SEQ, lr=1e-3,
                                     optimizer_cls=opt_cls)
    main.random_seed = 7
    startup.random_seed = 7
    return main, startup, loss, cfg


def _train_tiny_gpt(sharded, steps=5, optimizer="adamw"):
    """5 optimizer steps; returns (losses, final params, cache_stats())."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup, loss, cfg = _tiny_gpt(optimizer)
        toks = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (_BATCH, _SEQ)).astype(np.int64)
        exe = fluid.Executor()
        exe.run(startup)
        prog = main
        if sharded:
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
        vals = []
        for _ in range(steps):
            lv, = exe.run(prog, feed={"tokens": toks}, fetch_list=[loss])
            vals.append(float(np.asarray(lv)))
        params = {v.name: scope.get_numpy(v.name)
                  for v in main.list_vars()
                  if getattr(v, "is_parameter", False)}
    return vals, params, exe.cache_stats()


_baseline_cache = {}


def _baseline(optimizer):
    if optimizer not in _baseline_cache:
        _baseline_cache[optimizer] = _train_tiny_gpt(
            sharded=False, optimizer=optimizer)
    return _baseline_cache[optimizer]


# dp=8 splits only the batch — bitwise-stable reduction, AdamW stays
# tight. dp=4 x tp=2 re-orders the float32 matmul reductions across the
# tp partials; AdamW's normalized update turns that dust into ~lr-sized
# param drift, so the tp case trains with Momentum (still a ZeRO-sharded
# accumulator — `velocity`) where drift stays proportional to the noise.
@pytest.mark.parametrize("mesh_spec,optimizer,tol", [
    ("8", "adamw", 1e-4),
    ("4,2", "momentum", 1e-3),
])
def test_sharded_training_matches_single_device(mesh_spec, optimizer, tol):
    base_vals, base_params, _ = _baseline(optimizer)
    with _sharded_flags(mesh_spec):
        vals, params, stats = _train_tiny_gpt(sharded=True,
                                              optimizer=optimizer)
    np.testing.assert_allclose(base_vals, vals, rtol=tol, atol=tol / 10)
    assert base_params.keys() == params.keys()
    for name in base_params:
        np.testing.assert_allclose(base_params[name], params[name],
                                   rtol=tol, atol=tol, err_msg=name)
    # one compile for startup, one for the train signature, zero
    # recompiles after step 1 (the ISSUE acceptance bar)
    assert stats["misses"] == 2, stats
    assert stats["hits"] == 4, stats


def test_sharded_stats_and_presharded_feed():
    """exec.feed_presharded ticks when a feed arrives already placed on
    its target NamedSharding; parallel.* gauges come from the layout."""
    import jax
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    try:
        with _sharded_flags("8"):
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                main, startup, loss, cfg = _tiny_gpt()
                toks = np.random.RandomState(0).randint(
                    0, cfg.vocab_size, (_BATCH, _SEQ)).astype(np.int64)
                exe = fluid.Executor()
                exe.run(startup)
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name)
                exe.run(prog, feed={"tokens": toks}, fetch_list=[loss])
                placed = jax.device_put(toks,
                                        prog.feed_sharding(toks.shape))
                exe.run(prog, feed={"tokens": placed}, fetch_list=[loss])
                exe.run(prog, feed={"tokens": placed}, fetch_list=[loss])
        snap = monitor.get_stats_snapshot()
        assert snap["counters"].get("parallel.sharded_steps", 0) >= 3
        assert snap["counters"].get("exec.feed_presharded", 0) >= 1
        assert snap["gauges"].get("parallel.mesh_devices") == 8
        assert snap["gauges"].get("parallel.sharded_vars", 0) >= 1
        assert snap["gauges"].get("parallel.replicated_vars", 0) >= 1
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})


# ---------------------------------------------------------------------------
# layout-table unit tests
# ---------------------------------------------------------------------------

def test_mesh_from_spec_parsing():
    m = mesh_from_spec("8")
    assert m.axis_names == (DATA_AXIS,) and m.shape[DATA_AXIS] == 8
    m2 = mesh_from_spec("4,2")
    assert m2.axis_names == (DATA_AXIS, MODEL_AXIS)
    assert (m2.shape[DATA_AXIS], m2.shape[MODEL_AXIS]) == (4, 2)
    m3 = mesh_from_spec("4x2")  # sweep-config spelling
    assert dict(m3.shape) == dict(m2.shape)
    m4 = mesh_from_spec("2,2,2")  # third positional axis: fsdp
    assert m4.axis_names == (DATA_AXIS, MODEL_AXIS, FSDP_AXIS)
    assert (m4.shape[DATA_AXIS], m4.shape[MODEL_AXIS],
            m4.shape[FSDP_AXIS]) == (2, 2, 2)
    for bad in ("0", "", "-4,2", "2,2,2,2"):
        with pytest.raises(ValueError):
            mesh_from_spec(bad)


def test_layout_resolves_every_var_in_every_bench_builder(monkeypatch):
    """Resolution must be total: each persistable var of each bench
    builder gets a PartitionSpec (fallback = replication, never an
    error) under the dp=4 x tp=2 layout.

    The layout only reads the Program, so the builders' startup
    compiles are stubbed out — 5 XLA compiles would dominate tier-1."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(fluid.Executor, "run",
                        lambda self, *a, **kw: [])
    mesh = MeshDims((4, 2))
    for name, build in sorted(bench._CPU_TINY_BUILDS.items()):
        _, prog, _, _, _, _ = build()
        layout = SpecLayout(mesh).add_program(prog)
        persist = [v for v in prog.list_vars()
                   if getattr(v, "persistable", False)]
        assert persist, name
        assert len(layout) == len(persist), name
        for v in persist:
            spec = layout._table[v.name]
            assert isinstance(spec, P), (name, v.name)
            n = layout.shard_count(v.name, v.shape)
            assert n >= 1 and mesh.size % n == 0, (name, v.name, n)


def test_layout_divisibility_fallback_replicates():
    lay = SpecLayout(MeshDims((8,)))
    assert lay.feed_spec("x", (12, 16)) == P()       # 12 % 8 != 0
    assert lay.feed_spec("x", (16, 4)) == P(DATA_AXIS)
    assert lay.zero_spec("w_moment1_0", (12, 4)) == P()
    assert lay.zero_spec("w_moment1_0", (16, 4)) == P(DATA_AXIS, None)

    lay2 = SpecLayout(MeshDims((4, 3)))
    assert lay2.param_spec("w", (8, 10)) == P()      # 10 % 3 != 0
    assert lay2.param_spec("w", (8, 9)) == P(None, MODEL_AXIS)
    # ZeRO accumulator: dim 0 over dp, last dim over tp
    assert lay2.spec_for("fc_0.w_0_moment1_0", (8, 9)) == \
        P(DATA_AXIS, MODEL_AXIS)
    # scalar schedule state and 1-D non-accumulators replicate
    assert lay2.spec_for("learning_rate_0", (1,)) == P()
    assert lay2.spec_for("fc_0.w_0_beta1_pow_acc_0", (1,)) == P()
    assert lay2.spec_for("fc_0.b_0", (64,)) == P()


def test_layout_state_spec_fn_contract():
    """__call__ is the CompiledProgram.with_distributed state_spec_fn:
    sharded names return their spec, everything else None (replicated),
    including names never seen by add_program."""
    lay = SpecLayout(MeshDims((8,)))
    lay._table["w_moment1_0"] = lay.zero_spec("w_moment1_0", (16, 4))
    lay._table["b_0"] = P()
    assert lay("w_moment1_0") == P(DATA_AXIS, None)
    assert lay("b_0") is None
    assert lay("never_seen") is None


# ---------------------------------------------------------------------------
# artifact schema + report + lint tooling
# ---------------------------------------------------------------------------

_REC = {"kind": "sharded_bench", "ts": 0.0,
        "metric": "gpt_small_pretrain_tokens_per_sec_per_chip",
        "unit": "tokens/s", "mesh_shape": [4, 2],
        "mesh_axes": ["dp", "tp"], "mesh_devices": 8,
        "per_chip_throughput": 123.4,
        "collective_bytes_per_step": 4096}


def test_validate_sharded_bench_schema():
    v = _tools("validate_bench_json")
    assert v.validate_sharded_bench(_REC, "r0") == []
    assert any("mesh_devices" in e for e in v.validate_sharded_bench(
        dict(_REC, mesh_devices=6), "r0"))
    assert any("mesh_shape" in e for e in v.validate_sharded_bench(
        dict(_REC, mesh_shape=[]), "r0"))
    assert any("per_chip_throughput" in e
               for e in v.validate_sharded_bench(
                   dict(_REC, per_chip_throughput=-1), "r0"))
    assert any("collective_bytes" in e for e in v.validate_sharded_bench(
        dict(_REC, collective_bytes_per_step=1.5), "r0"))


def test_metrics_report_sharding_section(tmp_path):
    log = tmp_path / "bench.jsonl"
    log.write_text(json.dumps(_REC) + "\n")
    assert _tools("validate_bench_json").validate_file(str(log)) == []
    buf = pyio.StringIO()
    rc = _tools("metrics_report").report(str(log), out=buf)
    text = buf.getvalue()
    assert rc == 0
    assert "-- sharding" in text and "4x2" in text and "dp,tp" in text
    assert "123.4" in text


def test_program_lint_mesh_divides_peak(tmp_path):
    """--memory --mesh dp,tp: per-chip peak must not exceed the
    unsharded peak, and the record must carry the mesh shape."""
    from paddle_tpu import io, layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[64], dtype="float32")
        h = layers.fc(x, size=128, act="relu")
        out = layers.fc(h, size=64)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        model = str(tmp_path / "model")
        io.save_inference_model(model, ["x"], [out], exe,
                                main_program=main)

    def run(*extra):
        # in-process (subprocess CLI start-up is covered by
        # test_analysis) — still goes through main()'s argv parsing
        pl = _tools("program_lint")
        buf = pyio.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = pl.main([model, "--memory", "--jsonl", *extra])
        assert rc == 0, buf.getvalue()
        recs = [json.loads(l) for l in buf.getvalue().splitlines()
                if l.strip()]
        return next(x for x in recs if x.get("kind") == "memory_plan")

    plain = run()
    sharded = run("--mesh", "4,2")
    assert sharded.get("mesh_shape") == [4, 2]
    assert 0 < sharded["est_peak_bytes"] <= plain["est_peak_bytes"]

"""Longitudinal perf ledger + noise-aware regression gate
(tools/perf_ledger.py, tools/perf_gate.py): row extraction for every
artifact shape the validator knows, provenance stamping, the
median/MAD gate verdicts, and the end-to-end acceptance path — ingest
the checked-in BENCH_rNN.json history, build a synthetic 3-run
baseline, and prove a seeded >=20% throughput drop exits nonzero while
an unchanged run exits 0."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


perf_ledger = _tools("perf_ledger")
perf_gate = _tools("perf_gate")


# ---------------------------------------------------------------------------
# Row extraction per record shape
# ---------------------------------------------------------------------------

def test_rows_from_bench_summary_and_wrapper():
    summary = {"kind": "bench_summary", "status": "complete",
               "results": [
                   {"metric": "bert_tokens_per_sec", "value": 35440.8,
                    "unit": "tokens/s", "model": "bert"},
                   {"metric": "resnet_img_per_sec", "value": 0.0,
                    "unit": "img/s", "error": "backend unavailable"},
               ]}
    rows, skipped = perf_ledger.rows_from_record(summary)
    # the errored 0.0 result is SKIPPED, never a baseline sample
    assert len(rows) == 1 and skipped == 1
    assert rows[0]["config"] == "bert" \
        and rows[0]["metric"] == "bert_tokens_per_sec" \
        and rows[0]["value"] == 35440.8

    # driver wrapper: a parseable payload recurses...
    rows, skipped = perf_ledger.rows_from_record(
        {"cmd": "python bench.py", "parsed": summary})
    assert len(rows) == 1 and skipped == 1
    # ...a null payload (the r03/r05 timeout shape) is one skip
    rows, skipped = perf_ledger.rows_from_record(
        {"cmd": "python bench.py", "parsed": None})
    assert rows == [] and skipped == 1
    # ...and an errored payload likewise
    rows, skipped = perf_ledger.rows_from_record(
        {"cmd": "x", "parsed": {"error": "timeout"}})
    assert rows == [] and skipped == 1


def test_rows_from_loadgen_sharded_graphopt_memplan():
    gen = {"kind": "generation_loadgen", "mode": "closed",
           "tokens_per_s": 512.5, "throughput_rps": 20.0,
           "latency_ms": {"p50": 10.0, "p99": 30.0},
           "ttft_ms": {"p95": 12.0},
           "config": {"slots": 4, "max_prompt": 8}}
    rows, skipped = perf_ledger.rows_from_record(gen)
    assert skipped == 0
    by_metric = {r["metric"]: r for r in rows}
    assert set(by_metric) == {"tokens_per_s", "throughput_rps",
                              "latency_ms_p50", "latency_ms_p99",
                              "ttft_ms_p95"}
    # config key = mode + stable digest of the config object, so the
    # same invocation lines up across rounds...
    cfg = by_metric["tokens_per_s"]["config"]
    assert cfg.startswith("closed:")
    again, _ = perf_ledger.rows_from_record(gen)
    assert again[0]["config"] == cfg
    # ...and a different config object gets a different key
    other, _ = perf_ledger.rows_from_record(
        dict(gen, config={"slots": 8, "max_prompt": 8}))
    assert other[0]["config"] != cfg

    rows, _ = perf_ledger.rows_from_record(
        {"kind": "sharded_bench", "mesh_shape": [2, 1],
         "metric": "tok_s", "per_chip_throughput": 123.0})
    assert rows[0]["config"] == "mesh2x1" \
        and rows[0]["metric"] == "tok_s_per_chip"

    rows, _ = perf_ledger.rows_from_record(
        {"kind": "graph_opt", "model": "gpt", "opt_level": 2,
         "ops_after": 120, "vars_eliminated": 30})
    assert {r["metric"] for r in rows} == {"ops_after",
                                           "vars_eliminated"}
    assert all(r["config"] == "gpt:O2" for r in rows)

    rows, _ = perf_ledger.rows_from_record(
        {"kind": "memory_plan", "model": "bert",
         "est_peak_bytes": 1 << 30})
    assert rows[0]["metric"] == "est_peak_bytes" \
        and rows[0]["value"] == float(1 << 30)

    # unrelated kinds pass through silently (mixed monitor logs)
    assert perf_ledger.rows_from_record(
        {"kind": "stats_snapshot", "counters": {}}) == ([], 0)
    # non-numeric values never become rows
    rows, skipped = perf_ledger.rows_from_record(
        {"metric": "m", "value": "fast"})
    assert rows == [] and skipped == 1


def test_ingest_stamps_provenance_and_appends(tmp_path):
    art = tmp_path / "a.jsonl"
    with open(art, "w") as f:
        f.write(json.dumps({"metric": "tok_s", "value": 100.0,
                            "unit": "tok/s", "model": "gpt"}) + "\n")
        f.write("not json\n")   # tolerated: counted, not fatal
    ledger = tmp_path / "ledger.jsonl"
    n, skipped = perf_ledger.ingest(
        [str(art)], str(ledger),
        perf_ledger.provenance("abc1234", "tpu", "2x1"))
    assert n == 1 and skipped == 1
    rows = perf_ledger.load_rows(str(ledger))
    assert len(rows) == 1
    r = rows[0]
    assert r["git_rev"] == "abc1234" and r["platform"] == "tpu" \
        and r["mesh_shape"] == "2x1" and r["source"] == "a.jsonl"
    assert r["ingested_ts"] > 0
    # append-only: a second ingest adds, never rewrites
    perf_ledger.ingest([str(art)], str(ledger))
    assert len(perf_ledger.load_rows(str(ledger))) == 2


# ---------------------------------------------------------------------------
# Gate verdicts
# ---------------------------------------------------------------------------

def test_gate_direction_inference():
    assert not perf_gate.lower_is_better("bert_tokens_per_sec",
                                         "tokens/s")
    assert not perf_gate.lower_is_better("throughput_rps", "req/s")
    assert perf_gate.lower_is_better("latency_ms_p99", "ms")
    assert perf_gate.lower_is_better("ttft_ms_p95", "ms")
    assert perf_gate.lower_is_better("est_peak_bytes", "bytes")
    assert perf_gate.lower_is_better("ops_after", "ops")
    # vars_eliminated counts eliminations: more is better even though
    # the unit says "vars"
    assert not perf_gate.lower_is_better("vars_eliminated", "vars")


def test_gate_golden_fixtures_inline_and_cli():
    assert perf_gate.self_check() == 0
    p = subprocess.run([sys.executable, "tools/perf_gate.py",
                        "--self-check"], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr


def test_gate_rows_groups_by_config_and_metric(tmp_path):
    ledger_rows = [
        {"kind": "ledger_row", "config": "bench", "metric": "tok_s",
         "value": v} for v in (100.0, 101.0, 99.0)
    ] + [
        {"kind": "ledger_row", "config": "other", "metric": "tok_s",
         "value": 5.0},
    ]
    res = perf_gate.gate_rows(
        [{"config": "bench", "metric": "tok_s", "value": 70.0,
          "unit": "tok/s"},
         {"config": "other", "metric": "tok_s", "value": 5.0},
         {"config": "fresh", "metric": "tok_s", "value": 5.0}],
        ledger_rows)
    by_cfg = {r["config"]: r for r in res}
    assert by_cfg["bench"]["status"] == "regression"
    assert by_cfg["other"]["status"] == "too_few_samples"
    assert by_cfg["fresh"]["status"] == "new_config"


# ---------------------------------------------------------------------------
# End-to-end acceptance: checked-in history -> ledger -> gate
# ---------------------------------------------------------------------------

def _run_gate(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "tools/perf_gate.py"] + args, cwd=cwd,
        capture_output=True, text=True, timeout=120)


def test_e2e_checked_in_history_gate(tmp_path):
    """Ingest BENCH_r01..r05 (only r02 carries a real number — the
    null/errored wrappers are skipped, not averaged), add two synthetic
    same-config runs to reach min-samples, then: a 25% lower candidate
    exits nonzero with a validated kind="perf_gate" report; the
    unchanged value exits 0; and metrics_report renders the section."""
    ledger = tmp_path / "perf_ledger.jsonl"
    paths = [os.path.join(REPO, f"BENCH_r0{i}.json")
             for i in range(1, 6)]
    n, skipped = perf_ledger.ingest(
        paths, str(ledger), perf_ledger.provenance("seed", "tpu", ""))
    assert n == 1 and skipped == 4
    row = perf_ledger.load_rows(str(ledger))[0]
    base_val = row["value"]
    assert base_val > 0 and row["platform"] == "tpu"

    # two more rounds of the same config (honest jitter) -> 3 samples
    for i, v in enumerate((base_val * 1.004, base_val * 0.997)):
        art = tmp_path / f"round{i}.json"
        art.write_text(json.dumps(
            {"metric": row["metric"], "value": v, "unit": row["unit"],
             "model": row["config"]}))
        perf_ledger.ingest([str(art)], str(ledger))
    assert len(perf_ledger.load_rows(str(ledger))) == 3

    gate_out = tmp_path / "gate.jsonl"
    # seeded regression: 25% below the median MUST fail the gate
    p = _run_gate(["--ledger", str(ledger), "--out", str(gate_out),
                   "--config", row["config"], "--metric", row["metric"],
                   "--value", str(base_val * 0.75), "--unit",
                   row["unit"]])
    assert p.returncode == 1, p.stdout + p.stderr
    assert "regression" in p.stdout

    # unchanged run: exits 0, verdict ok
    p = _run_gate(["--ledger", str(ledger), "--out", str(gate_out),
                   "--config", row["config"], "--metric", row["metric"],
                   "--value", str(base_val), "--unit", row["unit"]])
    assert p.returncode == 0, p.stdout + p.stderr
    assert " ok" in p.stdout

    # both reports validate against the schema
    validate = _tools("validate_bench_json")
    assert validate.validate_file(str(gate_out)) == []
    reports = [json.loads(ln) for ln in gate_out.read_text()
               .splitlines() if ln.strip()]
    assert len(reports) == 2
    assert reports[0]["regressions"] == 1 \
        and reports[1]["regressions"] == 0
    for rep in reports:
        assert validate.validate_perf_gate(rep, "gate.jsonl") == []

    # metrics_report renders the perf-gate section from the same log
    p = subprocess.run(
        [sys.executable, "tools/metrics_report.py", str(gate_out)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "perf gate" in p.stdout and "regression" in p.stdout


def test_gate_ingest_makes_todays_run_tomorrows_baseline(tmp_path):
    ledger = tmp_path / "ledger.jsonl"
    for v in (100.0, 101.0, 99.5):
        perf_ledger.append_rows(
            str(ledger),
            [{"kind": "ledger_row", "record_kind": "bench_result",
              "config": "bench", "metric": "tok_s", "value": v,
              "unit": "tok/s"}],
            perf_ledger.provenance("r0", "tpu", ""))
    art = tmp_path / "new.json"
    art.write_text(json.dumps({"metric": "tok_s", "value": 100.5,
                               "unit": "tok/s", "model": "bench"}))
    p = _run_gate(["--ledger", str(ledger), "--ingest", str(art)])
    assert p.returncode == 0, p.stdout + p.stderr
    # --ingest appended the candidate AFTER gating
    assert len(perf_ledger.load_rows(str(ledger))) == 4


def test_incident_bundle_whole_file_validates(tmp_path):
    """validate_file auto-detects a whole-file incident bundle (the
    shape monitor_alerts writes)."""
    validate = _tools("validate_bench_json")
    bundle = {"kind": "incident_bundle", "ts": 123.0, "pid": 1,
              "rule": {"name": "slo", "kind": "burn",
                       "expr": "x", "op": ">", "threshold": 100.0},
              "state": "firing", "value": 400.0,
              "windows": {"10s": {"p": 400.0, "covered": True,
                                  "breach": True}},
              "snapshot": {"counters": {}, "gauges": {},
                           "histograms": {}},
              "exemplar_trace_ids": ["aabb"],
              "spans": [{"trace_id": "aabb", "span_id": "cc",
                         "name": "request"}],
              "n_spans_dropped": 0,
              "flight_records": []}
    f = tmp_path / "incident_slo_123.json"
    f.write_text(json.dumps(bundle))
    assert validate.validate_file(str(f)) == []
    # a mangled one (missing snapshot) is rejected
    bad = dict(bundle)
    del bad["snapshot"]
    f2 = tmp_path / "incident_bad.json"
    f2.write_text(json.dumps(bad))
    assert validate.validate_file(str(f2)) != []

"""End-to-end smoke: y=Wx+b lowering, autodiff, optimizer step, save/load.

Mirrors the reference's install_check + book/test_fit_a_line."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup


def test_forward_fc():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], dtype="float32")
        y = layers.fc(x, size=2, bias_attr=True)
    assert y.shape == (-1, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out, = exe.run(main, feed={"x": np.ones((4, 3), np.float32)},
                   fetch_list=[y])
    assert out.shape == (4, 2)


def test_fit_a_line_converges():
    rng = np.random.RandomState(0)
    w_true = np.array([[2.0], [-3.4]], np.float32)
    b_true = 4.2

    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32")
        label = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for _ in range(150):
        xs = rng.randn(32, 2).astype(np.float32)
        ys = xs @ w_true + b_true + 0.01 * rng.randn(32, 1).astype(
            np.float32)
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < 0.05, f"did not converge: {losses[::30]}"


def test_program_serialization_roundtrip():
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], dtype="float32")
        y = layers.fc(x, size=2)
    blob = main.to_json()
    restored = fluid.Program.from_json(blob)
    assert restored.fingerprint() == main.fingerprint()


def test_gradients_numeric_vs_analytic():
    """OpTest-style check (reference op_test.py get_numeric_gradient)."""
    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        x.stop_gradient = False
        y = layers.tanh(x)
        loss = layers.mean(y)
        fluid.append_backward(loss)

    exe = fluid.Executor()
    exe.run(startup)
    xv = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    gname = "x@GRAD"
    g, = exe.run(main, feed={"x": xv}, fetch_list=[gname])
    # numeric gradient (eps large enough to dominate fp32 eval noise)
    eps = 1e-2
    num = np.zeros_like(xv)
    main2 = main.clone()

    def f(v):
        out, = exe.run(main2, feed={"x": v}, fetch_list=[loss.name])
        return float(out)

    for i in range(xv.size):
        pert = xv.copy().reshape(-1)
        pert[i] += eps
        up = f(pert.reshape(xv.shape))
        pert[i] -= 2 * eps
        down = f(pert.reshape(xv.shape))
        num.reshape(-1)[i] = (up - down) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=1e-2, atol=1e-3)


def test_device_array_feed_passthrough():
    """jax.Array feeds skip the host round trip (executor._prepare_feed
    passthrough): same numerics as numpy feeds, dtype mismatches cast
    on device, and the executable cache is shared between both forms."""
    import jax

    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], dtype="float32")
        y = layers.fc(x, size=2, bias_attr=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    out_np, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    n_cached = len(exe._cache)
    out_dev, = exe.run(main, feed={"x": jax.device_put(xv)},
                       fetch_list=[y])
    np.testing.assert_allclose(out_dev, out_np, rtol=1e-6)
    assert len(exe._cache) == n_cached, "device feed must hit the cache"
    # wrong-dtype device feed is cast on device, not rejected
    out_cast, = exe.run(main, feed={"x": jax.device_put(
        xv.astype(np.float64))}, fetch_list=[y])
    np.testing.assert_allclose(out_cast, out_np, rtol=1e-6)


def test_int64_feed_dtype_canonicalized_shares_cache():
    """With x64 off, jax.device_put narrows int64->int32; the numpy feed
    path must canonicalize to the same dtype so both forms share one
    executable instead of compiling twice (_canon_feed_dtype)."""
    import jax

    main, startup = _fresh_programs()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[4], dtype="int64",
                          append_batch_size=False)
        y = layers.scale(layers.cast(ids, "float32"), scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    iv = np.arange(4, dtype=np.int64)
    out_np, = exe.run(main, feed={"ids": iv}, fetch_list=[y])
    n_cached = len(exe._cache)
    out_dev, = exe.run(main, feed={"ids": jax.device_put(iv)},
                       fetch_list=[y])
    np.testing.assert_allclose(out_dev, out_np)
    assert len(exe._cache) == n_cached, (
        "int64 numpy feed and its device_put form must key the same "
        "executable (dtype canonicalization in _prepare_feed)")


def test_scope_pool_clear():
    """App-D scope pool: leaked scopes can be bulk-released
    (framework/scope_pool.h semantics) without breaking live ones."""
    from paddle_tpu.core import scope as S

    s = S.Scope()
    s.set("leak", np.ones(4))
    n = S.scope_pool_size()
    assert n >= 1
    S.clear_scope_pool()
    assert s.find_var("leak") is None
    # the global scope survives cleared-but-usable
    S.global_scope().set("x", np.zeros(2))
    assert S.global_scope().find_var("x") is not None

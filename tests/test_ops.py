"""Per-op unit tests via the OpTest harness (reference unittests/test_*_op.py
pattern: declared inputs/expected outputs + numeric gradient checks)."""
import numpy as np
import pytest

from op_test import make_op_test

RS = np.random.RandomState


def test_elementwise_add_broadcast():
    x = RS(0).rand(2, 3, 4).astype("float32")
    y = RS(1).rand(3, 4).astype("float32")
    t = make_op_test("elementwise_add", {"X": x, "Y": y}, {"Out": x + y},
                     {"axis": -1})
    t.check_output()
    t.check_grad(["X", "Y"])


def test_mul_op():
    x = RS(0).rand(3, 4).astype("float32")
    y = RS(1).rand(4, 5).astype("float32")
    t = make_op_test("mul", {"X": x, "Y": y}, {"Out": x @ y})
    t.check_output(atol=1e-4)
    t.check_grad(["X", "Y"], max_relative_error=0.01)


def test_softmax_op():
    x = RS(0).rand(3, 7).astype("float32")
    e = np.exp(x - x.max(-1, keepdims=True))
    t = make_op_test("softmax", {"X": x}, {"Out": e / e.sum(-1, keepdims=True)})
    t.check_output()
    t.check_grad(["X"], max_relative_error=0.02)


def test_relu_and_tanh_grad():
    x = (RS(0).rand(3, 4).astype("float32") - 0.5) * 4
    # keep away from the relu kink where numeric diff is ill-defined
    x[np.abs(x) < 0.05] = 0.5
    make_op_test("relu", {"X": x}, {"Out": np.maximum(x, 0)}).check_output()
    make_op_test("relu", {"X": x}, {"Out": np.maximum(x, 0)}).check_grad(["X"])
    make_op_test("tanh", {"X": x}, {"Out": np.tanh(x)}).check_grad(["X"])


def test_reduce_mean_keepdim():
    x = RS(0).rand(2, 3, 4).astype("float32")
    t = make_op_test("reduce_mean", {"X": x},
                     {"Out": x.mean(axis=1, keepdims=True)},
                     {"dim": [1], "keep_dim": True})
    t.check_output()
    t.check_grad(["X"])


def test_concat_multi_input():
    a = RS(0).rand(2, 3).astype("float32")
    b = RS(1).rand(2, 5).astype("float32")
    t = make_op_test("concat", {"X": [("a", a), ("b", b)]},
                     {"Out": np.concatenate([a, b], axis=1)}, {"axis": 1})
    t.check_output()
    t.check_grad(["X"])


def test_layer_norm_op():
    x = RS(0).rand(4, 6).astype("float32")
    scale = RS(1).rand(6).astype("float32")
    bias = RS(2).rand(6).astype("float32")
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
    t = make_op_test(
        "layer_norm", {"X": x, "Scale": scale, "Bias": bias},
        {"Y": y, "Mean": mu.reshape(-1), "Variance": var.reshape(-1)},
        {"epsilon": 1e-5, "begin_norm_axis": 1})
    t.check_output(atol=1e-4)
    t.check_grad(["X", "Scale", "Bias"], max_relative_error=0.02,
                 output_names="Y")


def test_conv2d_op():
    x = RS(0).rand(1, 2, 5, 5).astype("float32")
    w = RS(1).rand(3, 2, 3, 3).astype("float32")
    t = make_op_test("conv2d", {"Input": x, "Filter": w}, {"Output": None},
                     {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1})
    # output checked against jax itself elsewhere; here check grads only
    t.check_grad(["Input", "Filter"], max_relative_error=0.02,
                 output_names="Output")


def test_pool2d_max_grad():
    x = RS(0).rand(1, 2, 6, 6).astype("float32")
    t = make_op_test("pool2d", {"X": x}, {"Out": None},
                     {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]})
    t.check_grad(["X"], max_relative_error=0.02)


def test_cross_entropy_op():
    p = np.full((4, 5), 0.1, "float32")
    p[np.arange(4), [0, 1, 2, 3]] = 0.6
    lab = np.array([[0], [2], [1], [4]], dtype="int64")
    exp = -np.log(p[np.arange(4), lab.ravel()]).reshape(-1, 1)
    t = make_op_test("cross_entropy", {"X": p, "Label": lab}, {"Y": exp})
    t.check_output(atol=1e-5)
    t.check_grad(["X"], output_names="Y", max_relative_error=0.02)


def test_sigmoid_cross_entropy_with_logits():
    x = RS(0).randn(4, 3).astype("float32")
    lab = RS(1).rand(4, 3).astype("float32")
    exp = np.maximum(x, 0) - x * lab + np.log1p(np.exp(-np.abs(x)))
    t = make_op_test("sigmoid_cross_entropy_with_logits",
                     {"X": x, "Label": lab}, {"Out": exp})
    t.check_output(atol=1e-5)
    t.check_grad(["X"], max_relative_error=0.02)


def test_transpose_reshape_grad():
    x = RS(0).rand(2, 3, 4).astype("float32")
    t = make_op_test("transpose2", {"X": x},
                     {"Out": x.transpose(2, 0, 1), "XShape": None},
                     {"axis": [2, 0, 1]})
    t.check_output(no_check_set=("XShape",))
    t.check_grad(["X"], output_names="Out")


def test_batch_norm_infer():
    x = RS(0).rand(2, 3, 4, 4).astype("float32")
    scale = np.ones(3, "float32")
    bias = np.zeros(3, "float32")
    mean = np.full(3, 0.5, "float32")
    var = np.full(3, 2.0, "float32")
    y = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5)
    t = make_op_test(
        "batch_norm",
        {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
         "Variance": var},
        {"Y": y, "MeanOut": None, "VarianceOut": None, "SavedMean": None,
         "SavedVariance": None},
        {"epsilon": 1e-5, "is_test": True, "momentum": 0.9,
         "data_layout": "NCHW"})
    t.check_output(no_check_set=("MeanOut", "VarianceOut", "SavedMean",
                                 "SavedVariance"), atol=1e-4)


def test_lookup_table_grad():
    w = RS(0).rand(7, 4).astype("float32")
    ids = np.array([[1], [3], [1], [6]], dtype="int64")
    t = make_op_test("lookup_table_v2", {"W": w, "Ids": ids.reshape(-1)},
                     {"Out": w[ids.ravel()]})
    t.check_output()
    t.check_grad(["W"], max_relative_error=0.02)


def test_gather_scatter_grad():
    x = RS(0).rand(5, 3).astype("float32")
    idx = np.array([0, 2, 4], dtype="int64")
    t = make_op_test("gather", {"X": x, "Index": idx}, {"Out": x[idx]})
    t.check_output()
    t.check_grad(["X"])


def test_huber_kldiv_losses():
    x = RS(0).randn(3, 4).astype("float32")
    y = RS(1).randn(3, 4).astype("float32")
    d = 1.0
    r = x - y
    hub = np.where(np.abs(r) <= d, 0.5 * r * r, d * (np.abs(r) - 0.5 * d))
    t = make_op_test("huber_loss", {"X": x, "Y": y},
                     {"Out": hub, "Residual": r}, {"delta": d})
    t.check_output(no_check_set=("Residual",))


def test_polynomial_decay_cycle():
    """cycle=True polynomial decay: horizon stretches to
    decay_steps * ceil(step/decay_steps), so the rate saw-tooths
    (reference learning_rate_scheduler.py). Step counter ticks once
    per run (module convention: first run sees step=1)."""
    import math

    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        lr = layers.polynomial_decay(0.1, decay_steps=4,
                                     end_learning_rate=0.01,
                                     power=1.0, cycle=True)
        x = layers.data("pcx", shape=[1], dtype="float32")
        loss = layers.mean(x)
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        vals = []
        for _ in range(8):
            out, = exe.run(main,
                           feed={"pcx": np.ones((1, 1), np.float32)},
                           fetch_list=[lr])
            vals.append(round(np.asarray(out).item(), 5))

    def ref(step):
        mult = max(1.0, math.ceil(step / 4))
        frac = step / (4 * mult)
        return round((0.1 - 0.01) * (1 - frac) + 0.01, 5)

    assert vals == [ref(s) for s in range(1, 9)], vals

"""2-process multi-host bootstrap rehearsal.

Reference analogue: test_dist_base.py:533-770 — multi-process localhost
training with loss-equivalence against single-process. Here each worker
process carries 4 virtual CPU devices; `init_parallel_env()` performs
the REAL `jax.distributed.initialize` coordinator handshake (trainer 0's
endpoint, the PADDLE_TRAINER_* env contract), then:

1. a global-mesh allreduce across both processes' devices, and
2. three dp train steps of the shared MLP through Executor +
   CompiledProgram.with_distributed, whose losses must match a
   single-process run of the same seeded program.

The single-process 8-device mesh in test_parallel.py covers the SPMD
math; this covers the process-bootstrap path those tests bypass.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MLP_SOURCE = '''
def build_and_run(fluid, layers, mesh=None, steps=3):
    import numpy as np
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = rng.randn(32, 1).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        prog = main
        if mesh is not None:
            prog = fluid.CompiledProgram(main).with_distributed(mesh)
        vals = []
        for _ in range(steps):
            lv, = exe.run(prog, feed={"x": xs, "y": ys},
                          fetch_list=[loss])
            vals.append(float(np.asarray(lv)))
    return vals
'''

_WORKER = f'''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {ROOT!r})
import paddle_tpu as fluid
import paddle_tpu.distributed as dist
from paddle_tpu import layers

dist.init_parallel_env()   # PADDLE_TRAINER_* -> jax.distributed.initialize
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert dist.parallel_env_world_size() == 2
rank = dist.parallel_env_rank()

# 1. global-mesh allreduce: every device contributes its global index
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
mesh = dist.global_mesh({{"dp": -1}})
sh = NamedSharding(mesh, P("dp"))
local = np.arange(4, dtype=np.float32) + 4 * jax.process_index()
g = jax.make_array_from_process_local_data(sh, local, (8,))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(g)
total = float(np.asarray(total))
assert total == 28.0, f"allreduce over the global mesh got {{total}}"

# 2. dp train steps through the framework over the 2-process mesh
{_MLP_SOURCE}
vals = build_and_run(fluid, layers, mesh=mesh)
print("LOSSES", json.dumps(vals))
'''

_SINGLE = f'''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {ROOT!r})
import paddle_tpu as fluid
from paddle_tpu import layers
{_MLP_SOURCE}
vals = build_and_run(fluid, layers, mesh=None)
print("LOSSES", json.dumps(vals))
'''


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_worker(code, env, timeout=420):
    e = dict(os.environ)
    e.pop("XLA_FLAGS", None)
    # drop the axon sitecustomize so workers start on a clean backend
    e["PYTHONPATH"] = ROOT
    e.update(env)
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=timeout,
                          env=e)


def _losses(proc, who):
    assert proc.returncode == 0, \
        f"{who} failed rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
    for line in proc.stdout.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError(f"{who}: no LOSSES line\n{proc.stdout}")


def _spawn_pair(code, extra_env=None):
    """Run `code` in 2 coordinated worker processes; returns both procs."""
    import concurrent.futures as cf

    port = _free_port()
    eps = f"127.0.0.1:{port},127.0.0.1:{port + 1}"
    with cf.ThreadPoolExecutor(2) as pool:
        futs = [
            pool.submit(_run_worker, code,
                        {"PADDLE_TRAINERS_NUM": "2",
                         "PADDLE_TRAINER_ID": str(i),
                         "PADDLE_TRAINER_ENDPOINTS": eps,
                         **(extra_env or {})})
            for i in range(2)
        ]
        return [f.result() for f in futs]


def test_two_process_bootstrap_and_loss_parity():
    procs = _spawn_pair(_WORKER)
    l0 = _losses(procs[0], "worker 0")
    l1 = _losses(procs[1], "worker 1")
    np.testing.assert_allclose(l0, l1, rtol=1e-6,
                               err_msg="ranks disagree on the loss")

    single = _losses(_run_worker(_SINGLE, {}), "single-process")
    np.testing.assert_allclose(
        l0, single, rtol=1e-4, atol=1e-5,
        err_msg="2-process dp loss must match single-process")
    assert single[0] > single[-1], "loss must decrease over steps"


# ---------------------------------------------------------------------------
# scenario 2: dp x tp mesh whose TP groups SPAN the process boundary +
# an all-to-all-bearing (Ulysses) step across processes
# (test_dist_base.py:533-770 grinds the same matrix with NCCL rings)
# ---------------------------------------------------------------------------

_TRANSFORMER_SOURCE = '''
def build_and_run_transformer(fluid, layers, mesh=None, spec_fn=None,
                              steps=3):
    import numpy as np
    from paddle_tpu.models import transformer
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        dropout=0.0, use_flash=False, tp=mesh is not None)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 64, (4, 16)).astype(np.int64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, feeds = transformer.build_train(cfg, 4, 16, lr=1e-2)
        exe = fluid.Executor()
        exe.run(startup)
        prog = main
        if mesh is not None:
            prog = fluid.CompiledProgram(main).with_distributed(
                mesh, state_spec_fn=spec_fn, batch_axes=("dp",))
        vals = []
        for _ in range(steps):
            lv, = exe.run(prog, feed={"tokens": toks, "labels": toks},
                          fetch_list=[loss])
            vals.append(float(np.asarray(lv)))
    return vals


def tp_spec_fn(name):
    from jax.sharding import PartitionSpec as P
    if name.endswith((".q.w", ".k.w", ".v.w", ".fc1.w")):
        return P(None, "tp")
    if name.endswith((".q.b", ".k.b", ".v.b", ".fc1.b")):
        return P("tp")
    if name.endswith((".proj.w", ".fc2.w")):
        return P("tp", None)
    return None
'''

_WORKER_TP = f'''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {ROOT!r})
import paddle_tpu as fluid
import paddle_tpu.distributed as dist
from paddle_tpu import layers

dist.init_parallel_env()
rank = dist.parallel_env_rank()
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# TP groups that CROSS the process boundary: device grid transposed so
# each tp pair is (process0_dev_i, process1_dev_i) — every q/k/v matmul
# psum rides the inter-process link, not just intra-host
devs = np.array(jax.devices()).reshape(2, 4).T      # [dp=4, tp=2]
mesh = Mesh(devs, axis_names=("dp", "tp"))
{_TRANSFORMER_SOURCE}
vals = build_and_run_transformer(fluid, layers, mesh=mesh,
                                 spec_fn=tp_spec_fn)
print("LOSSES", json.dumps(vals))

# Ulysses all-to-all attention across both processes: sp=8 spans the
# job; the two all-to-alls cross the process boundary
from paddle_tpu.parallel.ulysses import ulysses_attention_sharded
mesh_sp = Mesh(np.array(jax.devices()), axis_names=("sp",))
rng = np.random.RandomState(1)
b, h, t, d = 2, 8, 32, 8
qg = rng.randn(b, h, t, d).astype(np.float32)
kg = rng.randn(b, h, t, d).astype(np.float32)
vg = rng.randn(b, h, t, d).astype(np.float32)
sh = NamedSharding(mesh_sp, P(None, None, "sp", None))
half = slice(rank * t // 2, (rank + 1) * t // 2)
mk = lambda a: jax.make_array_from_process_local_data(
    sh, np.ascontiguousarray(a[:, :, half]), (b, h, t, d))
q, k, v = mk(qg), mk(kg), mk(vg)
out = ulysses_attention_sharded(q, k, v, mesh_sp, seq_axis="sp",
                                causal=True)
rep = jax.jit(lambda x: x,
              out_shardings=NamedSharding(mesh_sp, P()))(out)
got = np.asarray(rep)

# dense causal reference on the replicated host copies
s = np.einsum("bhqd,bhkd->bhqk", qg, kg) / np.sqrt(d)
mask = np.tril(np.ones((t, t), bool))
s = np.where(mask, s, -1e30)
w = np.exp(s - s.max(-1, keepdims=True))
w /= w.sum(-1, keepdims=True)
ref = np.einsum("bhqk,bhkd->bhqd", w, vg)
err = float(np.abs(got - ref).max())
assert err < 1e-4, f"ulysses cross-process mismatch {{err}}"
print("ULYSSES_OK", err)
'''


def test_cross_process_tp_and_alltoall():
    procs = _spawn_pair(_WORKER_TP)
    l0 = _losses(procs[0], "worker 0")
    l1 = _losses(procs[1], "worker 1")
    np.testing.assert_allclose(l0, l1, rtol=1e-6,
                               err_msg="ranks disagree on the loss")
    for i, p in enumerate(procs):
        assert "ULYSSES_OK" in p.stdout, \
            f"worker {i}: no ULYSSES_OK\n{p.stdout}\n{p.stderr}"

    # single-process reference of the same seeded transformer program
    single_code = f'''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {ROOT!r})
import paddle_tpu as fluid
from paddle_tpu import layers
{_TRANSFORMER_SOURCE}
vals = build_and_run_transformer(fluid, layers, mesh=None)
print("LOSSES", json.dumps(vals))
'''
    single = _losses(_run_worker(single_code, {}), "single-process")
    np.testing.assert_allclose(
        l0, single, rtol=1e-4, atol=1e-5,
        err_msg="cross-process dp x tp loss must match single-process")


# ---------------------------------------------------------------------------
# scenario 3: sharded checkpoint written by 2 processes, loaded and
# resumed by 1 process (and vice-versa parity on the continued losses)
# ---------------------------------------------------------------------------

_WORKER_CKPT_TMPL = '''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {root!r})
import paddle_tpu as fluid
import paddle_tpu.distributed as dist
from paddle_tpu import layers
from paddle_tpu.io_sharded import save_sharded_persistables

dist.init_parallel_env()
mesh = dist.global_mesh({{"dp": -1}})
{mlp_source}
import numpy as np
rng = np.random.RandomState(0)
xs = rng.randn(32, 16).astype(np.float32)
ys = rng.randn(32, 1).astype(np.float32)
main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 7
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[16], dtype="float32")
    label = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, size=32, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    exe.run(startup)
    prog = fluid.CompiledProgram(main).with_distributed(mesh)
    pre, post = [], []
    for _ in range(3):
        lv, = exe.run(prog, feed={{"x": xs, "y": ys}}, fetch_list=[loss])
        pre.append(float(np.asarray(lv)))
    save_sharded_persistables(exe, {ckpt!r}, main_program=main,
                              scope=scope)
    for _ in range(3):
        lv, = exe.run(prog, feed={{"x": xs, "y": ys}}, fetch_list=[loss])
        post.append(float(np.asarray(lv)))
print("LOSSES", json.dumps(pre + post))
'''

_SINGLE_RESUME_TMPL = '''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {root!r})
import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.io_sharded import load_sharded_persistables

rng = np.random.RandomState(0)
xs = rng.randn(32, 16).astype(np.float32)
ys = rng.randn(32, 1).astype(np.float32)
main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 99   # different init on purpose
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[16], dtype="float32")
    label = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, size=32, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    exe.run(startup)
    # resume from the 2-process sharded checkpoint in ONE process
    load_sharded_persistables(exe, {ckpt!r}, main_program=main,
                              scope=scope)
    vals = []
    for _ in range(3):
        lv, = exe.run(main, feed={{"x": xs, "y": ys}}, fetch_list=[loss])
        vals.append(float(np.asarray(lv)))
print("LOSSES", json.dumps(vals))
'''


def test_checkpoint_across_process_counts(tmp_path):
    ckpt = str(tmp_path / "ckpt_2proc")
    code = _WORKER_CKPT_TMPL.format(root=ROOT, mlp_source="",
                                    ckpt=ckpt)
    procs = _spawn_pair(code)
    l0 = _losses(procs[0], "worker 0")
    l1 = _losses(procs[1], "worker 1")
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    assert os.path.exists(os.path.join(ckpt, "manifest.json")), \
        "process 0 must write the primary manifest"

    resumed = _losses(
        _run_worker(_SINGLE_RESUME_TMPL.format(root=ROOT, ckpt=ckpt), {}),
        "single-process resume")
    # the single process resumed from the 2-process shards must continue
    # exactly where the 2-process run went (post-checkpoint losses)
    np.testing.assert_allclose(
        resumed, l0[3:], rtol=1e-4, atol=1e-6,
        err_msg="single-process resume diverges from the 2-process run")

"""2-process multi-host bootstrap rehearsal.

Reference analogue: test_dist_base.py:533-770 — multi-process localhost
training with loss-equivalence against single-process. Here each worker
process carries 4 virtual CPU devices; `init_parallel_env()` performs
the REAL `jax.distributed.initialize` coordinator handshake (trainer 0's
endpoint, the PADDLE_TRAINER_* env contract), then:

1. a global-mesh allreduce across both processes' devices, and
2. three dp train steps of the shared MLP through Executor +
   CompiledProgram.with_distributed, whose losses must match a
   single-process run of the same seeded program.

The single-process 8-device mesh in test_parallel.py covers the SPMD
math; this covers the process-bootstrap path those tests bypass.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MLP_SOURCE = '''
def build_and_run(fluid, layers, mesh=None, steps=3):
    import numpy as np
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = rng.randn(32, 1).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        prog = main
        if mesh is not None:
            prog = fluid.CompiledProgram(main).with_distributed(mesh)
        vals = []
        for _ in range(steps):
            lv, = exe.run(prog, feed={"x": xs, "y": ys},
                          fetch_list=[loss])
            vals.append(float(np.asarray(lv)))
    return vals
'''

_WORKER = f'''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {ROOT!r})
import paddle_tpu as fluid
import paddle_tpu.distributed as dist
from paddle_tpu import layers

dist.init_parallel_env()   # PADDLE_TRAINER_* -> jax.distributed.initialize
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert dist.parallel_env_world_size() == 2
rank = dist.parallel_env_rank()

# 1. global-mesh allreduce: every device contributes its global index
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
mesh = dist.global_mesh({{"dp": -1}})
sh = NamedSharding(mesh, P("dp"))
local = np.arange(4, dtype=np.float32) + 4 * jax.process_index()
g = jax.make_array_from_process_local_data(sh, local, (8,))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(g)
total = float(np.asarray(total))
assert total == 28.0, f"allreduce over the global mesh got {{total}}"

# 2. dp train steps through the framework over the 2-process mesh
{_MLP_SOURCE}
vals = build_and_run(fluid, layers, mesh=mesh)
print("LOSSES", json.dumps(vals))
'''

_SINGLE = f'''
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {ROOT!r})
import paddle_tpu as fluid
from paddle_tpu import layers
{_MLP_SOURCE}
vals = build_and_run(fluid, layers, mesh=None)
print("LOSSES", json.dumps(vals))
'''


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_worker(code, env, timeout=420):
    e = dict(os.environ)
    e.pop("XLA_FLAGS", None)
    # drop the axon sitecustomize so workers start on a clean backend
    e["PYTHONPATH"] = ROOT
    e.update(env)
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=timeout,
                          env=e)


def _losses(proc, who):
    assert proc.returncode == 0, \
        f"{who} failed rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}"
    for line in proc.stdout.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError(f"{who}: no LOSSES line\n{proc.stdout}")


def test_two_process_bootstrap_and_loss_parity():
    import concurrent.futures as cf

    port = _free_port()
    eps = f"127.0.0.1:{port},127.0.0.1:{port + 1}"
    with cf.ThreadPoolExecutor(2) as pool:
        futs = [
            pool.submit(_run_worker, _WORKER,
                        {"PADDLE_TRAINERS_NUM": "2",
                         "PADDLE_TRAINER_ID": str(i),
                         "PADDLE_TRAINER_ENDPOINTS": eps})
            for i in range(2)
        ]
        procs = [f.result() for f in futs]
    l0 = _losses(procs[0], "worker 0")
    l1 = _losses(procs[1], "worker 1")
    np.testing.assert_allclose(l0, l1, rtol=1e-6,
                               err_msg="ranks disagree on the loss")

    single = _losses(_run_worker(_SINGLE, {}), "single-process")
    np.testing.assert_allclose(
        l0, single, rtol=1e-4, atol=1e-5,
        err_msg="2-process dp loss must match single-process")
    assert single[0] > single[-1], "loss must decrease over steps"

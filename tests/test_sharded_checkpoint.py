"""Sharded checkpointing + op-version gating.

Round-trips a dp x tp-sharded training state on the 8-device CPU mesh:
every process writes only its addressable shards (no host-0 gather) and
load rebuilds the exact NamedShardings (SURVEY.md §5 orbax-style bullet;
reference op_compatible_info.h for the version gate).
"""
import json
import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import ParamAttr, check_op_versions


def _spec_fn(name):
    if name == "w_col":
        return P(None, "tp")
    if name == "w_row":
        return P("tp", None)
    return None


def _build(batch=8):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[batch, 8], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[batch, 1], dtype="float32",
                        append_batch_size=False)
        h = layers.fc(x, size=16, act="relu",
                      param_attr=ParamAttr(name="w_col"),
                      bias_attr=ParamAttr(name="b1"))
        pred = layers.fc(h, size=1, param_attr=ParamAttr(name="w_row"),
                         bias_attr=ParamAttr(name="b2"))
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, axis_names=("dp", "tp"))


def test_sharded_roundtrip_restores_shardings(tmp_path):
    mesh = _mesh()
    main, startup, loss = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 8).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    compiled = fluid.CompiledProgram(main).with_distributed(
        mesh, state_spec_fn=_spec_fn, batch_axes=("dp",))
    with fluid.scope_guard(scope):
        exe.run(startup)
        l1, = exe.run(compiled, feed=feed, fetch_list=[loss])
        fluid.save_sharded_persistables(exe, str(tmp_path), main,
                                        scope=scope)

    # the checkpoint is sharded on disk: w_col split over tp -> 2 files
    files = os.listdir(tmp_path)
    wcol_files = [f for f in files if f.startswith("w_col__")]
    assert len(wcol_files) == 2, files
    man = json.load(open(tmp_path / "manifest.json"))
    assert man["vars"]["w_col"]["spec"] == [None, "tp"]
    assert "adam" in man["op_versions"] or "sgd" in man["op_versions"] \
        or len(man["op_versions"]) > 0

    # fresh scope: restore and verify shardings + values + resumability
    scope2 = fluid.Scope()
    exe2 = fluid.Executor()
    with fluid.scope_guard(scope2):
        fluid.load_sharded_persistables(exe2, str(tmp_path), main,
                                        mesh=mesh, scope=scope2)
    w = scope2.get("w_col")
    assert isinstance(w, jax.Array)
    assert w.sharding == NamedSharding(mesh, P(None, "tp"))
    np.testing.assert_allclose(np.asarray(w),
                               np.asarray(scope.get("w_col")))
    for n in ("w_row", "b1", "b2"):
        np.testing.assert_allclose(np.asarray(scope2.get(n)),
                                   np.asarray(scope.get(n)))
    with fluid.scope_guard(scope2):
        l2, = exe2.run(compiled, feed=feed, fetch_list=[loss])
    assert np.isfinite(l2).all()


def test_sharded_load_onto_fresh_host(tmp_path):
    """mesh=None load gives plain host arrays (single-host serving)."""
    mesh = _mesh()
    main, startup, loss = _build()
    exe = fluid.Executor()
    scope = fluid.Scope()
    feed = {"x": np.zeros((8, 8), np.float32),
            "y": np.zeros((8, 1), np.float32)}
    compiled = fluid.CompiledProgram(main).with_distributed(
        mesh, state_spec_fn=_spec_fn)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled, feed=feed, fetch_list=[loss])
        fluid.save_sharded_persistables(exe, str(tmp_path), main,
                                        scope=scope)
    scope2 = fluid.Scope()
    fluid.load_sharded_persistables(exe, str(tmp_path), main,
                                    mesh=None, scope=scope2)
    w = scope2.get("w_col")
    assert isinstance(w, np.ndarray) and w.shape == (8, 16)
    np.testing.assert_allclose(w, np.asarray(scope.get("w_col")))


def test_op_version_gate_refuses_newer_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 2], dtype="float32",
                        append_batch_size=False)
        layers.relu(x)
    d = main.to_dict()
    assert d["op_versions"]["relu"] == 1
    # a future build bumped relu to v9: this build must refuse
    d["op_versions"]["relu"] = 9
    with pytest.raises(RuntimeError, match="relu"):
        fluid.Program.from_dict(d)
    with pytest.raises(RuntimeError, match="not registered"):
        check_op_versions({"op_from_the_future": 1})

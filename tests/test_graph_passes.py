"""Graph optimization pass pipeline (paddle_tpu/analysis/passes):
golden per-pass fixtures, the clone/re-verify/fail-open protocol, the
FLAGS_graph_opt_level gate in Executor/ServingEngine, and the bit-exact
parity contract across optimization levels on the bench model builders.

Pass catalog: docs/graph_passes.md.
"""
from __future__ import annotations

import io
import json
import os
import re
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis.passes import (CommonSubexprElimination,
                                        ConstantFolding,
                                        DeadOpElimination, FOLDABLE_OPS,
                                        Pass, PassManager,
                                        optimize_gate, optimize_program,
                                        reset_memo)
from paddle_tpu.framework import Operator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


def _op_types(program):
    return [op.type for op in program.global_block().ops]


def _raw_program(var_specs, op_specs):
    prog = fluid.Program()
    blk = prog.global_block()
    for name, kw in var_specs:
        blk.create_var(name=name, **kw)
    for op_type, ins, outs, attrs in op_specs:
        blk.ops.append(Operator(blk, op_type, ins, outs, attrs))
    return prog


def _run(prog, feed, fetch, startup=None, level=None):
    """Execute `prog` under FLAGS_graph_opt_level=level -> numpy list."""
    prev = fluid.FLAGS.graph_opt_level
    if level is not None:
        fluid.set_flags({"FLAGS_graph_opt_level": level})
    try:
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            if startup is not None:
                exe.run(startup)
            return exe.run(prog, feed=feed, fetch_list=fetch)
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})


# ---------------------------------------------------------------------------
# level semantics
# ---------------------------------------------------------------------------

def test_level0_returns_program_untouched():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.relu(x)
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[y.name], level=0)
    assert opt is main
    assert report["passes"] == []
    assert report["ops_before"] == report["ops_after"]


def test_level1_never_tags_fusion_or_plans_donation():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.relu(layers.scale(layers.relu(x), scale=2.0))
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[y.name], level=1)
    assert {p["name"] for p in report["passes"]} == \
        {"dead_op_elim", "constant_fold", "cse"}
    assert not any(getattr(op, "_fusion_group", None)
                   for op in opt.global_block().ops)
    assert getattr(opt, "_donation_plan", None) is None


def test_pipeline_never_mutates_the_original_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        c = layers.fill_constant(shape=[4], dtype="float32", value=2.0)
        y = layers.elementwise_add(x, layers.scale(c, scale=3.0))
        _dead = layers.scale(y, scale=9.0)
    before = _op_types(main)
    fp = main.fingerprint()
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[y.name], level=2)
    assert opt is not main
    assert _op_types(main) == before
    assert main.fingerprint() == fp
    assert report["ops_after"] < report["ops_before"]


# ---------------------------------------------------------------------------
# dead-op elimination
# ---------------------------------------------------------------------------

def test_dce_removes_dead_op():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.relu(x)
        dead = layers.scale(y, scale=9.0)
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[y.name], level=1)
    dce = next(p for p in report["passes"] if p["name"] == "dead_op_elim")
    assert dce["removed"] == 1
    assert not any(dead.name in op.outputs.get("Out", ())
                   for op in opt.global_block().ops)
    # the dead op's result var no longer appears anywhere
    assert report["vars_eliminated"] >= 1


def test_dce_anchors_side_effect_ops_and_their_grads():
    """A host-RPC pull and the grad::generic that performs its sparse
    PUSH must stay live even though nothing downstream reads them —
    the regression mode of test_distributed's PS-mode training."""
    from paddle_tpu.analysis.graph_utils import live_op_mask
    prog = _raw_program(
        [("ids", dict(is_data=True, shape=[6], dtype="int64")),
         ("w", dict(shape=[1], dtype="float32")),
         ("rows", dict(shape=[6, 3], dtype="float32")),
         ("loss", dict(shape=[1], dtype="float32")),
         ("w_g", dict(shape=[1], dtype="float32"))],
        [("distributed_lookup_table", {"Ids": ["ids"], "W": ["w"]},
          {"Outputs": ["rows"]},
          {"endpoints": ["h:1"], "emb_dim": 3, "table_name": "t"}),
         ("mean", {"X": ["rows"]}, {"Out": ["loss"]}, {}),
         ("grad::generic", {"Ids": ["ids"], "W": ["w"]},
          {"W@GRAD": ["w_g"]},
          {"fwd_type": "distributed_lookup_table", "fwd_attrs": {},
           "fwd_in_slots": {}, "fwd_out_slots": {},
           "fwd_out_grad_mask": {}, "fwd_id": 0})])
    # nothing fetches w_g, yet every op must stay live
    assert all(live_op_mask(prog, ["loss"]))


def test_dce_declines_without_a_fetch_list():
    """No fetch list means 'run for side effects' (startup programs):
    reachability is undefined, so DCE must keep everything."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        layers.relu(x)
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[], level=1)
    dce = next(p for p in report["passes"] if p["name"] == "dead_op_elim")
    assert dce["removed"] == 0
    assert len(opt.global_block().ops) == len(main.global_block().ops)


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

def test_constant_fold_fill_scale_chain():
    """fill_constant(2.0) -> scale(x3) collapses to one assign_value
    carrying 6.0 — evaluated through the registered lowerings, so the
    folded value is the bit pattern the device would have produced."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        c = layers.fill_constant(shape=[4], dtype="float32", value=2.0)
        c2 = layers.scale(c, scale=3.0)
        y = layers.elementwise_add(x, c2)
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[y.name], level=1)
    fold = next(p for p in report["passes"]
                if p["name"] == "constant_fold")
    assert fold["folded"] == 2 and fold["materialized"] == 1
    types = _op_types(opt)
    assert "fill_constant" not in types and "scale" not in types
    av = [op for op in opt.global_block().ops
          if op.type == "assign_value"]
    assert len(av) == 1
    np.testing.assert_array_equal(av[0].attrs["values"],
                                  np.full((4,), 6.0, np.float32))
    # executed results agree bit-exactly with the unoptimized program
    feed = {"x": np.arange(8, dtype=np.float32).reshape(2, 4)}
    r0, = _run(main, feed, [y.name], level=0)
    r1, = _run(main, feed, [y.name], level=1)
    assert np.array_equal(r0, r1)


def test_constant_fold_double_write_keeps_each_definition():
    """A var written twice by folded ops must materialize each
    definition's OWN value at its def site — readers of the first def
    see the first value, the final fetch sees the last."""
    f32_4 = dict(shape=[4], dtype="float32")
    prog = _raw_program(
        [("c", dict(**f32_4)), ("u", dict(**f32_4)),
         ("v", dict(**f32_4))],
        [("fill_constant",
          {}, {"Out": ["c"]},
          {"shape": [4], "dtype": "float32", "value": 1.0}),
         ("scale", {"X": ["c"]}, {"Out": ["u"]}, {"scale": 2.0}),
         ("fill_constant",
          {}, {"Out": ["c"]},
          {"shape": [4], "dtype": "float32", "value": 5.0}),
         ("scale", {"X": ["c"]}, {"Out": ["v"]}, {"scale": 2.0})])
    opt, report = optimize_program(prog, feed_names=[],
                                   fetch_names=["u", "v", "c"], level=1)
    assert not report.get("rejected")
    u, v, c = _run(opt, {}, ["u", "v", "c"], level=0)
    np.testing.assert_array_equal(u, np.full((4,), 2.0, np.float32))
    np.testing.assert_array_equal(v, np.full((4,), 10.0, np.float32))
    np.testing.assert_array_equal(c, np.full((4,), 5.0, np.float32))


def test_constant_fold_whitelist_excludes_reductions():
    """Bit-exactness gate: accumulation-order-sensitive ops must never
    be in the fold whitelist."""
    for banned in ("reduce_sum", "reduce_mean", "matmul", "mul",
                   "softmax", "mean", "sum"):
        assert banned not in FOLDABLE_OPS


# ---------------------------------------------------------------------------
# common-subexpression elimination
# ---------------------------------------------------------------------------

def test_cse_dedupes_identical_pure_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        a = layers.relu(x)
        b = layers.relu(x)  # identical computation
        z = layers.elementwise_add(a, b)
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[z.name], level=1)
    cse = next(p for p in report["passes"] if p["name"] == "cse")
    assert cse["deduped"] == 1
    assert _op_types(opt).count("relu") == 1
    # the survivor's add now reads the SAME var twice
    add = next(op for op in opt.global_block().ops
               if op.type == "elementwise_add")
    assert add.inputs["X"] == add.inputs["Y"]
    feed = {"x": np.arange(-4, 4, dtype=np.float32).reshape(2, 4)}
    r0, = _run(main, feed, [z.name], level=0)
    r1, = _run(main, feed, [z.name], level=1)
    assert np.array_equal(r0, r1)


def test_cse_never_touches_stateful_ops():
    """Two uniform_random ops are two independent draws — deduping
    them would change the numerics."""
    f32_4 = dict(shape=[4], dtype="float32")
    attrs = {"shape": [4], "dtype": "float32", "min": 0.0, "max": 1.0}
    prog = _raw_program(
        [("a", dict(**f32_4)), ("b", dict(**f32_4)),
         ("z", dict(**f32_4))],
        [("uniform_random", {}, {"Out": ["a"]}, dict(attrs)),
         ("uniform_random", {}, {"Out": ["b"]}, dict(attrs)),
         ("elementwise_add", {"X": ["a"], "Y": ["b"]},
          {"Out": ["z"]}, {})])
    opt, report = optimize_program(prog, feed_names=[],
                                   fetch_names=["z"], level=1)
    cse = next(p for p in report["passes"] if p["name"] == "cse")
    assert cse["deduped"] == 0
    assert _op_types(opt).count("uniform_random") == 2


def test_cse_redefinition_cannot_redirect_reads():
    """An op whose output is later redefined must never become a CSE
    source: renaming a duplicate's readers to it would make them read
    the REDEFINED value."""
    f32_4 = dict(shape=[4], dtype="float32")
    prog = _raw_program(
        [("x", dict(is_data=True, **f32_4)), ("a", dict(**f32_4)),
         ("b", dict(**f32_4))],
        [("relu", {"X": ["x"]}, {"Out": ["a"]}, {}),
         ("relu", {"X": ["x"]}, {"Out": ["b"]}, {}),   # dup of op0
         ("tanh", {"X": ["x"]}, {"Out": ["a"]}, {})])  # redefines a
    opt, report = optimize_program(prog, feed_names=["x"],
                                   fetch_names=["a", "b"], level=1)
    assert not report.get("rejected")
    feed = {"x": np.array([-1.0, 0.5, 2.0, -3.0], np.float32)}
    a, b = _run(opt, feed, ["a", "b"], level=0)
    np.testing.assert_array_equal(b, np.maximum(feed["x"], 0.0))
    np.testing.assert_allclose(a, np.tanh(feed["x"]), rtol=1e-6)


# ---------------------------------------------------------------------------
# elementwise fusion scopes
# ---------------------------------------------------------------------------

def _chain_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        t = layers.scale(x, scale=2.0)
        u = layers.relu(t)
        v = layers.elementwise_add(u, u)
        loss = layers.reduce_sum(v)
    return main, startup, loss


def test_fusion_merges_maximal_elementwise_chains():
    main, startup, loss = _chain_program()
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[loss.name], level=2)
    fus = next(p for p in report["passes"]
               if p["name"] == "fusion_scopes")
    assert fus["groups"] == 1 and fus["fused_ops"] == 3
    assert fus["merged"] == 1
    assert report["ops_after"] == report["ops_before"] - 2
    fused = [op for op in opt.global_block().ops
             if op.type == "fused_elementwise"]
    assert len(fused) == 1
    fop = fused[0]
    assert [s["type"] for s in fop.attrs["sub_ops"]] == \
        ["scale", "relu", "elementwise_add"]
    # every chain intermediate stays materialized (backward reads them)
    assert len(fop.outputs["Out"]) == 3
    assert getattr(fop, "_fusion_group", None) == "ewfuse0"
    # the reduction is NOT elementwise and stays out of the fused op
    red = next(op for op in opt.global_block().ops
               if op.type == "reduce_sum")
    assert getattr(red, "_fusion_group", None) is None
    # the scope label is an annotation, not a serialized attr
    assert "ewfuse" not in opt.to_json()
    # and the replayed chain is bit-exact against the unfused program
    feed = {"x": np.array([[-1.0, 0.5, 2.0, -3.0]], np.float32)}
    base, = _run(main, feed, [loss.name], startup=startup, level=0)
    opt_v, = _run(main, feed, [loss.name], startup=startup, level=2)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(opt_v))


def test_fusion_falls_back_to_tags_when_a_merge_gate_fails():
    """A run whose attrs can't round-trip through JSON (np scalar) must
    not merge — it degrades to the shared _fusion_group annotation."""
    prog = _raw_program(
        [("x", dict(is_data=True, shape=[4], dtype="float32")),
         ("a", dict(shape=[4], dtype="float32")),
         ("b", dict(shape=[4], dtype="float32"))],
        [("scale", {"X": ["x"]}, {"Out": ["a"]},
          {"scale": np.float32(2.0), "bias": 0.0,
           "bias_after_scale": True}),
         ("relu", {"X": ["a"]}, {"Out": ["b"]}, {})])
    opt, report = optimize_program(prog, feed_names=["x"],
                                   fetch_names=["b"], level=2)
    assert not report.get("rejected")
    fus = next(p for p in report["passes"]
               if p["name"] == "fusion_scopes")
    assert fus["groups"] == 1 and fus["merged"] == 0
    ops = opt.global_block().ops
    assert [op.type for op in ops] == ["scale", "relu"]
    assert [getattr(op, "_fusion_group", None) for op in ops] == \
        ["ewfuse0", "ewfuse0"]


def test_fusion_scope_lands_in_compiled_hlo():
    """At level 2 the compiled executable's op_name metadata carries
    the ewfuse<N>/ scope prefix — the chain presents to XLA (and to
    profiles) as one named unit."""
    main, startup, loss = _chain_program()
    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": 2})
    try:
        scope = fluid.Scope()
        exe = fluid.Executor()
        feed = {"x": np.ones((2, 4), np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            hlo = exe.compiled_hlo(main, feed=feed,
                                   fetch_list=[loss.name])
        assert re.search(r'op_name="[^"]*ewfuse0/', hlo), hlo[:2000]
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})


# ---------------------------------------------------------------------------
# donation planner
# ---------------------------------------------------------------------------

def _sgd_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=1)
        loss = layers.reduce_mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_donation_planner_targets_inplace_state():
    main, _, loss = _sgd_program()
    opt, report = optimize_program(main, feed_names=["x"],
                                   fetch_names=[loss.name], level=2)
    don = next(p for p in report["passes"]
               if p["name"] == "donation_plan")
    plan = getattr(opt, "_donation_plan", frozenset())
    assert don["donated_vars"] == len(plan) >= 2  # fc w + b at least
    assert don["donated_bytes"] > 0
    block = opt.global_block()
    for name in plan:
        assert block.var(name).persistable


def test_executor_compiles_with_planned_donation():
    """End-to-end at level 2: the training executable splits donated
    vs pinned state and records which buffers it donates."""
    main, startup, loss = _sgd_program()
    weight = next(n for n, v in main.global_block().vars.items()
                  if v.persistable and ".w_" in n)
    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": 2})
    try:
        scope = fluid.Scope()
        exe = fluid.Executor()
        feed = {"x": np.ones((2, 4), np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(3):  # donation must survive repeated steps
                lv, = exe.run(main, feed=feed, fetch_list=[loss.name])
        donating = [s for s in exe._cache.values()
                    if getattr(s, "donate_names", None)]
        assert donating, "no cached executable has a donation plan"
        assert any(weight in s.donate_names for s in donating)
        assert np.isfinite(np.asarray(lv)).all()
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})


# ---------------------------------------------------------------------------
# the re-verify fail-open protocol and the memoized gate
# ---------------------------------------------------------------------------

class _BreakingPass(Pass):
    """Deliberately corrupts dataflow: re-verification must catch it
    and the pipeline must fall back to the original program."""

    name = "break_dataflow"
    min_level = 1

    def run(self, program, ctx):
        blk = program.global_block()
        blk.ops.append(Operator(blk, "relu", {"X": ["__ghost__"]},
                                {"Out": [blk.ops[0].outputs["Out"][0]]}))
        program._fp_cache = None
        return {}


def test_reverify_rejects_broken_rewrite_and_fails_open():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.relu(x)
    pm = PassManager([_BreakingPass()])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out, report = pm.run(main, feed_names=["x"],
                             fetch_names=[y.name], level=1)
    assert out is main  # fail-open: original survives
    assert report.get("rejected") is True
    assert report["ops_after"] == report["ops_before"]
    assert any("re-verification" in str(w.message) for w in caught)


def test_optimize_gate_memoizes_per_fingerprint_and_level():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.relu(layers.relu(x))
    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": 1})
    reset_memo()
    try:
        p1, r1 = optimize_gate(main, feed_names=["x"],
                               fetch_names=[y.name])
        p2, r2 = optimize_gate(main, feed_names=["x"],
                               fetch_names=[y.name])
        assert p1 is p2 and r1 is r2  # served from the memo
        reset_memo()
        p3, _ = optimize_gate(main, feed_names=["x"],
                              fetch_names=[y.name])
        assert p3 is not p1  # fresh pipeline run after reset
        fluid.set_flags({"FLAGS_graph_opt_level": 0})
        p0, rep0 = optimize_gate(main, feed_names=["x"],
                                 fetch_names=[y.name])
        assert p0 is main and rep0 is None
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})
        reset_memo()


# ---------------------------------------------------------------------------
# bit-exact parity + op-count reduction on the bench builders
# ---------------------------------------------------------------------------

def _builder_losses(build, level, steps=2):
    """Fresh build + executor at the given opt level -> loss sequence
    (np arrays). Builders are deterministic (seeded init, per-op-id
    PRNG), so cross-level runs are comparable bit-for-bit."""
    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": level})
    try:
        exe, prog, scope, feed, loss, _cfg = build()
        out = []
        with fluid.scope_guard(scope):
            for _ in range(steps):
                lv, = exe.run(prog, feed=feed, fetch_list=[loss])
                out.append(np.asarray(lv))
        exe.close()
        return out
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})


def _tiny_builds():
    sys.path.insert(0, REPO)
    os.environ.setdefault("BENCH_FLASH", "0")
    import bench
    return bench._CPU_TINY_BUILDS


@pytest.mark.parametrize("model", ["gpt", "transformer"])
def test_headline_builders_bit_exact_and_smaller(model):
    """Acceptance: on the GPT and transformer bench programs the full
    pipeline (level 2) is bit-exact vs level 0 AND measurably reduces
    the op count."""
    build = _tiny_builds()[model]
    l0 = _builder_losses(build, 0)
    l2 = _builder_losses(build, 2)
    for a, b in zip(l0, l2):
        assert np.array_equal(a, b), (model, l0, l2)
    # measured op-count reduction on the real training program
    exe, prog, scope, feed, loss, _cfg = build()
    exe.close()
    _, report = optimize_program(prog, feed_names=list(feed),
                                 fetch_names=[loss.name], level=2)
    assert not report.get("rejected")
    assert report["ops_after"] < report["ops_before"], report


@pytest.mark.slow
@pytest.mark.parametrize("model", ["bert", "resnet50", "gpt",
                                   "transformer", "deeplab"])
def test_all_builders_bit_exact_across_all_levels(model):
    build = _tiny_builds()[model]
    base = _builder_losses(build, 0)
    for level in (1, 2):
        got = _builder_losses(build, level)
        for a, b in zip(base, got):
            assert np.array_equal(a, b), (model, level, base, got)


@pytest.mark.slow
def test_registry_wide_pipeline_reverifies_clean():
    """Every op OP_TEST_MATRIX certifies as passing goes through the
    full pipeline without tripping the re-verification gate."""
    from op_specs import SKIPS, SPECS
    import test_op_sweep as sweep

    matrix = json.load(open(os.path.join(REPO, "OP_TEST_MATRIX.json")))
    ops = [op for op, rec in matrix["ops"].items()
           if rec.get("status") == "pass"
           and op in SPECS and op not in SKIPS]
    assert len(ops) > 250
    bad = {}
    for op in ops:
        main, feeds, out_map, _direct, _ = sweep._build_program(
            op, SPECS[op])
        fetch = [nm for names in out_map.values() for nm in names]
        _, report = optimize_program(main, feed_names=list(feeds),
                                     fetch_names=fetch, level=2)
        if report.get("rejected"):
            bad[op] = report
    assert not bad, f"{len(bad)} op(s) rejected by re-verify: " \
                    f"{sorted(bad)[:10]}"


# ---------------------------------------------------------------------------
# serving gate
# ---------------------------------------------------------------------------

def test_serving_warmup_primes_the_gate_once(tmp_path):
    from paddle_tpu.analysis.passes import base as base_mod
    from paddle_tpu.serving import EngineConfig, ServingEngine

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = layers.softmax(layers.fc(x, size=3))
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp_path), ["x"], [out], exe,
                                      main_program=main)
    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": 1})
    reset_memo()
    try:
        cfg = EngineConfig(model_dir=str(tmp_path), max_batch_size=4,
                           warmup=True)
        engine = ServingEngine(cfg).start()
        try:
            # one memo entry covers the WHOLE warmup ladder
            assert len(base_mod._OPT_MEMO) == 1
            r = engine.predict({"x": np.ones((2, 4), np.float32)})
            assert r[0].shape == (2, 3)
        finally:
            engine.stop()
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})
        reset_memo()


# ---------------------------------------------------------------------------
# CLI + artifact schema
# ---------------------------------------------------------------------------

def test_program_lint_optimize_cli_end_to_end(tmp_path):
    """--optimize emits a kind="graph_opt" record that the artifact
    validator accepts and metrics_report renders."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        c = layers.fill_constant(shape=[4], dtype="float32", value=2.0)
        y = layers.elementwise_add(x, layers.scale(c, scale=3.0))
        out = layers.softmax(y)
    scope = fluid.Scope()
    exe = fluid.Executor()
    model_dir = str(tmp_path / "model")
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                      main_program=main)
    log = str(tmp_path / "lint.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"),
         model_dir, "--optimize", "--jsonl", "--out", log],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    recs = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    kinds = [rec["kind"] for rec in recs]
    assert kinds == ["program_lint", "graph_opt"]
    opt = recs[1]
    assert opt["opt_level"] == 2
    assert opt["ops_after"] < opt["ops_before"]
    assert any(p["name"] == "constant_fold" and p["folded"] >= 2
               for p in opt["passes"])
    # schema + rendering
    assert _tools("validate_bench_json").validate_file(log) == []
    buf = io.StringIO()
    rc = _tools("metrics_report").report(log, out=buf)
    text = buf.getvalue()
    assert rc == 0 and "graph optimization" in text \
        and "constant_fold" in text


def test_validate_graph_opt_schema():
    validate = _tools("validate_bench_json").validate_graph_opt
    good = {"kind": "graph_opt", "model": "m", "opt_level": 2,
            "ops_before": 10, "ops_after": 8, "vars_eliminated": 1,
            "passes": [{"name": "cse", "ops_before": 10,
                        "ops_after": 8, "seconds": 0.01,
                        "deduped": 2}]}
    assert validate(good) == []
    assert validate({"kind": "graph_opt"})  # everything missing
    grew = dict(good, ops_after=12)
    assert any("exceeds" in e for e in validate(grew))
    bad_pass = dict(good, passes=[{"name": 3}])
    assert validate(bad_pass)

"""Smoke tests for the App-B layer wrappers added in round 3: each
builds a tiny graph through the public layers API and executes it on
the CPU mesh, verifying the wrapper's op wiring (slot names, attr
plumbing, output vars) end to end."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(build, feeds):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        fetches = build()
        exe = fluid.Executor()
        exe.run(startup)
        if not isinstance(fetches, (list, tuple)):
            fetches = [fetches]
        return exe.run(main, feed=feeds, fetch_list=list(fetches))


def test_multiclass_nms_layer():
    def build():
        b = layers.data("bx", shape=[8, 4], dtype="float32")
        s = layers.data("sc", shape=[3, 8], dtype="float32")
        return layers.detection.multiclass_nms(
            b, s, score_threshold=0.1, nms_top_k=4, keep_top_k=4)
    rng = np.random.RandomState(0)
    boxes = np.abs(rng.randn(2, 8, 4)).astype(np.float32)
    scores = rng.rand(2, 3, 8).astype(np.float32)
    out, = _run(build, {"bx": boxes, "sc": scores})
    assert out.shape == (2, 4, 6)


def test_anchor_generator_layer():
    def build():
        x = layers.data("fm", shape=[16, 4, 4], dtype="float32")
        a, v = layers.detection.anchor_generator(
            x, anchor_sizes=[32.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0])
        return [a, v]
    fm = np.zeros((2, 16, 4, 4), np.float32)
    a, v = _run(build, {"fm": fm})
    assert a.shape[-1] == 4 and v.shape == a.shape


def test_bipartite_match_and_target_assign():
    def build():
        d = layers.data("dist", shape=[3, 5], dtype="float32",
                        append_batch_size=False)
        mi, md = layers.detection.bipartite_match(d)
        return [mi, md]
    dist = np.random.RandomState(0).rand(3, 5).astype(np.float32)
    mi, md = _run(build, {"dist": dist})
    assert mi.shape[-1] == 5


def test_detection_output_composition():
    def build():
        loc = layers.data("loc", shape=[8, 4], dtype="float32")
        conf = layers.data("conf", shape=[8, 3], dtype="float32")
        pb = layers.data("pb", shape=[8, 4], dtype="float32",
                         append_batch_size=False)
        pbv = layers.data("pbv", shape=[8, 4], dtype="float32",
                          append_batch_size=False)
        return layers.detection.detection_output(loc, conf, pb, pbv,
                                                 keep_top_k=4,
                                                 nms_top_k=4)
    rng = np.random.RandomState(0)
    out, = _run(build, {
        "loc": rng.randn(2, 8, 4).astype(np.float32),
        "conf": rng.randn(2, 8, 3).astype(np.float32),
        "pb": np.abs(rng.randn(8, 4)).astype(np.float32),
        "pbv": np.full((8, 4), 0.1, np.float32)})
    assert out.shape[1] == 4 and out.shape[2] == 6


def test_yolov3_loss_layer():
    def build():
        x = layers.data("yx", shape=[18, 4, 4], dtype="float32")
        gt = layers.data("ygt", shape=[2, 4], dtype="float32")
        lb = layers.data("ylb", shape=[2], dtype="int32")
        return layers.detection.yolov3_loss(
            x, gt, lb, anchors=[10, 13, 16, 30, 33, 23],
            anchor_mask=[0, 1, 2], class_num=1, ignore_thresh=0.7,
            downsample_ratio=32)
    rng = np.random.RandomState(0)
    out, = _run(build, {
        "yx": rng.randn(1, 18, 4, 4).astype(np.float32),
        "ygt": np.abs(rng.rand(1, 2, 4)).astype(np.float32) * 0.5,
        "ylb": np.zeros((1, 2), np.int32)})
    assert np.isfinite(out).all()


def test_sigmoid_focal_loss_layer():
    def build():
        x = layers.data("fx", shape=[4], dtype="float32")
        lb = layers.data("flb", shape=[1], dtype="int32")
        fg = layers.data("ffg", shape=[1], dtype="int32",
                         append_batch_size=False)
        return layers.detection.sigmoid_focal_loss(x, lb, fg)
    rng = np.random.RandomState(0)
    out, = _run(build, {"fx": rng.randn(6, 4).astype(np.float32),
                        "flb": rng.randint(0, 4, (6, 1)).astype(np.int32),
                        "ffg": np.array([3], np.int32)})
    assert np.isfinite(out).all()


def test_sequence_wrapper_family():
    def build():
        x = layers.data("sq", shape=[6, 4], dtype="float32")
        first = layers.sequence_first_step(x)
        last = layers.sequence_last_step(x)
        rev = layers.sequence_reverse(x)
        return [first, last, rev]
    rng = np.random.RandomState(0)
    xv = rng.randn(3, 6, 4).astype(np.float32)
    first, last, rev = _run(build, {"sq": xv})
    np.testing.assert_allclose(first, xv[:, 0], rtol=1e-6)
    np.testing.assert_allclose(last, xv[:, -1], rtol=1e-6)
    np.testing.assert_allclose(rev, xv[:, ::-1], rtol=1e-6)


def test_hsigmoid_and_nce_layers():
    def build():
        x = layers.data("hx", shape=[8], dtype="float32")
        lb = layers.data("hl", shape=[1], dtype="int64")
        h = layers.hsigmoid(x, lb, num_classes=6)
        n = layers.nce(x, lb, num_total_classes=6, num_neg_samples=3)
        return [h, n]
    rng = np.random.RandomState(0)
    h, n = _run(build, {"hx": rng.randn(4, 8).astype(np.float32),
                        "hl": rng.randint(0, 6, (4, 1)).astype(np.int64)})
    assert np.isfinite(h).all() and np.isfinite(n).all()


def test_ctc_greedy_decoder_layer():
    def build():
        x = layers.data("cx", shape=[5, 4], dtype="float32")
        return layers.ctc_greedy_decoder(x, blank=3)
    logits = np.zeros((2, 5, 4), np.float32)
    logits[0, :, 1] = 5.0          # all 1s -> collapses to one 1
    logits[1, :, 3] = 5.0          # all blanks -> empty (padded)
    out, = _run(build, {"cx": logits})
    assert out.shape[0] == 2
    assert out[0][0] == 1


def test_scatter_nd_and_resize_layers():
    def build():
        idx = layers.data("si", shape=[4, 1], dtype="int64",
                          append_batch_size=False)
        upd = layers.data("su", shape=[4], dtype="float32",
                          append_batch_size=False)
        s = layers.scatter_nd(idx, upd, shape=[8])
        img = layers.data("im", shape=[2, 4, 4], dtype="float32")
        r = layers.resize_trilinear(
            layers.reshape(img, [-1, 1, 2, 4, 4]), out_shape=[2, 8, 8])
        return [s, r]
    rng = np.random.RandomState(0)
    s, r = _run(build, {
        "si": np.array([[0], [2], [2], [5]], np.int64),
        "su": np.ones(4, np.float32),
        "im": rng.randn(1, 2, 4, 4).astype(np.float32)})
    np.testing.assert_allclose(s, [1, 0, 2, 0, 0, 1, 0, 0], rtol=1e-6)
    assert r.shape == (1, 1, 2, 8, 8)


def test_mean_iou_and_multiplex_layers():
    def build():
        p = layers.data("mp", shape=[4], dtype="int32")
        lb = layers.data("ml", shape=[4], dtype="int32")
        miou, wrong, correct = layers.mean_iou(p, lb, num_classes=3)
        return [miou]
    pred = np.array([[0, 1, 2, 1]], np.int32)
    lab = np.array([[0, 1, 2, 2]], np.int32)
    miou, = _run(build, {"mp": pred, "ml": lab})
    assert 0.0 < float(miou) <= 1.0


def test_dygraph_new_layers():
    import paddle_tpu.dygraph as dg
    rng = np.random.RandomState(0)
    with dg.guard():
        c3 = dg.Conv3D(num_channels=2, num_filters=3, filter_size=3,
                       padding=1)
        x = dg.to_variable(rng.randn(1, 2, 4, 4, 4).astype(np.float32))
        assert c3(x).numpy().shape == (1, 3, 4, 4, 4)

        ct = dg.Conv2DTranspose(num_channels=2, num_filters=3,
                                filter_size=3, padding=1)
        x2 = dg.to_variable(rng.randn(1, 2, 5, 5).astype(np.float32))
        assert ct(x2).numpy().shape == (1, 3, 5, 5)

        # output_size anywhere in [natural, natural + stride) is valid
        # (reference conv_transpose semantics); natural here is
        # (5-1)*2 + 3 = 11
        for osz, ok in ((11, True), (12, True), (13, False), (10, False)):
            ct2 = dg.Conv2DTranspose(num_channels=2, num_filters=3,
                                     filter_size=3, stride=2,
                                     output_size=[osz, osz])
            if ok:
                assert ct2(x2).numpy().shape == (1, 3, osz, osz)
            else:
                try:
                    ct2(x2)
                    raise AssertionError("output_size %d accepted" % osz)
                except ValueError:
                    pass

        gu = dg.GRUUnit(size=12)
        inp = dg.to_variable(rng.randn(2, 12).astype(np.float32))
        hid = dg.to_variable(rng.randn(2, 4).astype(np.float32))
        h, _, _ = gu(inp, hid)
        assert h.numpy().shape == (2, 4)

        btp = dg.BilinearTensorProduct(size=5, x_dim=3, y_dim=4)
        xa = dg.to_variable(rng.randn(2, 3).astype(np.float32))
        ya = dg.to_variable(rng.randn(2, 4).astype(np.float32))
        assert btp(xa, ya).numpy().shape == (2, 5)

        nce_l = dg.NCE(num_total_classes=7, dim=3)
        lb = dg.to_variable(rng.randint(0, 7, (2, 1)).astype(np.int64))
        assert np.isfinite(nce_l(xa, lb).numpy()).all()


def test_ssd_loss_mining_and_normalize():
    """ssd_loss: positives drive loc loss, max_negative mining keeps
    ~neg_pos_ratio negatives, and normalize divides by num_pos."""
    def build(normalize):
        def inner():
            loc = layers.data("sl_loc", shape=[6, 4], dtype="float32",
                              append_batch_size=False)
            conf = layers.data("sl_conf", shape=[6, 3], dtype="float32",
                               append_batch_size=False)
            gt = layers.data("sl_gt", shape=[2, 4], dtype="float32",
                             append_batch_size=False)
            lb = layers.data("sl_lb", shape=[2, 1], dtype="int64",
                             append_batch_size=False)
            pb = layers.data("sl_pb", shape=[6, 4], dtype="float32",
                             append_batch_size=False)
            pbv = layers.data("sl_pbv", shape=[6, 4], dtype="float32",
                              append_batch_size=False)
            loss = layers.detection.ssd_loss(
                loc, conf, gt, lb, pb, pbv, background_label=0,
                normalize=normalize)
            return loss
        return inner
    rng = np.random.RandomState(0)
    priors = np.array([[0, 0, .2, .2], [.2, .2, .4, .4], [.4, .4, .6, .6],
                       [.6, .6, .8, .8], [0, .5, .2, .7],
                       [.5, 0, .7, .2]], np.float32)
    gt = np.array([[0, 0, .2, .2], [.6, .6, .8, .8]], np.float32)
    feeds = {"sl_loc": rng.randn(6, 4).astype(np.float32) * 0.1,
             "sl_conf": rng.randn(6, 3).astype(np.float32),
             "sl_gt": gt,
             "sl_lb": np.array([[1], [2]], np.int64),
             "sl_pb": priors,
             "sl_pbv": np.full((6, 4), 0.1, np.float32)}
    out_norm, = _run(build(True), feeds)
    out_raw, = _run(build(False), feeds)
    assert out_norm.shape == (6, 1)
    assert np.all(np.isfinite(out_norm))
    # two gt boxes match two priors exactly -> num_pos = 2
    np.testing.assert_allclose(out_norm * 2.0, out_raw, rtol=1e-5)
    # unmatched, un-mined priors contribute zero loss rows
    assert (np.abs(out_raw) > 0).sum() < 6 * 1 + 1


def test_crf_layers():
    def build():
        emission = layers.data("crf_e", shape=[5, 3], dtype="float32",
                               append_batch_size=False)
        label = layers.data("crf_l", shape=[5, 1], dtype="int64",
                            append_batch_size=False)
        ll = layers.linear_chain_crf(
            layers.reshape(emission, [1, 5, 3]),
            layers.reshape(label, [1, 5]),
            param_attr=fluid.ParamAttr(name="crfw_t"))
        path = layers.crf_decoding(
            layers.reshape(emission, [1, 5, 3]),
            param_attr=fluid.ParamAttr(name="crfw_t"))
        return [ll, path]
    rng = np.random.RandomState(0)
    ll, path = _run(build, {
        "crf_e": rng.randn(5, 3).astype(np.float32),
        "crf_l": rng.randint(0, 3, (5, 1)).astype(np.int64)})
    assert np.isfinite(ll).all()
    assert path.shape[-1] == 5


def test_edit_distance_and_gather_tree_layers():
    def build():
        h = layers.data("ed_h", shape=[4], dtype="int64")
        r = layers.data("ed_r", shape=[4], dtype="int64")
        dist, seq_num = layers.edit_distance(h, r, normalized=False)
        # gather_tree takes [max_time, batch, beam]
        ids = layers.data("gt_i", shape=[3, 1, 2], dtype="int64",
                          append_batch_size=False)
        parents = layers.data("gt_p", shape=[3, 1, 2], dtype="int64",
                              append_batch_size=False)
        tree = layers.gather_tree(ids, parents)
        return [dist, tree]
    dist, tree = _run(build, {
        "ed_h": np.array([[1, 2, 3, 4]], np.int64),
        "ed_r": np.array([[1, 2, 4, 4]], np.int64),
        "gt_i": np.array([[[2, 2]], [[3, 4]], [[5, 6]]], np.int64),
        "gt_p": np.array([[[0, 0]], [[0, 1]], [[1, 0]]], np.int64)})
    assert float(dist.reshape(-1)[0]) >= 1.0
    assert tree.shape == (3, 1, 2)


def test_spectral_norm_and_row_conv_layers():
    def build():
        w = layers.data("sn_w", shape=[6, 4], dtype="float32",
                        append_batch_size=False)
        sn = layers.spectral_norm(w, power_iters=2)
        x = layers.data("rc_x", shape=[5, 4], dtype="float32")
        rc = layers.row_conv(x, future_context_size=2)
        return [sn, rc]
    rng = np.random.RandomState(0)
    sn, rc = _run(build, {
        "sn_w": rng.randn(6, 4).astype(np.float32),
        "rc_x": rng.randn(2, 5, 4).astype(np.float32)})
    assert sn.shape == (6, 4) and rc.shape == (2, 5, 4)


def test_crop_pool3d_affine_grid_layers():
    def build():
        x = layers.data("cr_x", shape=[6, 6], dtype="float32")
        c = layers.crop_tensor(x, shape=[2, 4, 4], offsets=[0, 1, 1])
        v = layers.data("p3_x", shape=[2, 4, 4, 4], dtype="float32")
        p3 = layers.pool3d(v, pool_size=2, pool_stride=2)
        ap3 = layers.adaptive_pool3d(v, pool_size=2)
        theta = layers.data("ag_t", shape=[2, 3], dtype="float32")
        grid = layers.affine_grid(theta, out_shape=[2, 1, 4, 4])
        return [c, p3, ap3, grid]
    rng = np.random.RandomState(0)
    c, p3, ap3, grid = _run(build, {
        "cr_x": rng.randn(2, 6, 6).astype(np.float32),
        "p3_x": rng.randn(2, 2, 4, 4, 4).astype(np.float32),
        "ag_t": rng.randn(2, 2, 3).astype(np.float32)})
    assert c.shape == (2, 4, 4)
    assert p3.shape == (2, 2, 2, 2, 2)
    assert ap3.shape == (2, 2, 2, 2, 2)
    assert grid.shape == (2, 4, 4, 2)


def test_im2sequence_and_similarity_focus_layers():
    def build():
        x = layers.data("i2s_x", shape=[1, 4, 4], dtype="float32")
        seq = layers.im2sequence(x, filter_size=2, stride=2)
        y = layers.data("sf_x", shape=[3, 2, 2], dtype="float32")
        sf = layers.similarity_focus(y, axis=1, indexes=[0])
        return [seq, sf]
    rng = np.random.RandomState(0)
    seq, sf = _run(build, {
        "i2s_x": rng.randn(2, 1, 4, 4).astype(np.float32),
        "sf_x": rng.randn(2, 3, 2, 2).astype(np.float32)})
    assert seq.shape[-1] == 4
    assert sf.shape == (2, 3, 2, 2)


def test_random_ops_and_selected_rows_layers():
    def build():
        x = layers.data("rnd_x", shape=[4], dtype="float32")
        u = layers.uniform_random_batch_size_like(x, shape=[-1, 6])
        g = layers.gaussian_random_batch_size_like(x, shape=[-1, 3])
        m = layers.merge_selected_rows(x)
        t = layers.get_tensor_from_selected_rows(m)
        s = layers.sum([x, x])
        return [u, g, t, s]
    xv = np.ones((5, 4), np.float32)
    u, g, t, s = _run(build, {"rnd_x": xv})
    assert u.shape == (5, 6) and g.shape == (5, 3)
    np.testing.assert_allclose(t, xv)
    np.testing.assert_allclose(s, 2 * xv)


def test_retinanet_detection_output_layer_multilevel():
    """the layer must hand per-FPN-level lists to the op unconcatenated:
    score_threshold applies to all but the LAST level (which keeps
    everything), so a high threshold with a two-level call still yields
    level-2 detections — a level-concatenating wrapper would drop them."""
    def build():
        b0 = layers.data("rdl_b0", shape=[2, 4], dtype="float32")
        b1 = layers.data("rdl_b1", shape=[1, 4], dtype="float32")
        s0 = layers.data("rdl_s0", shape=[2, 3], dtype="float32")
        s1 = layers.data("rdl_s1", shape=[1, 3], dtype="float32")
        a0 = layers.create_tensor(dtype="float32", name="rdl_a0")
        a1 = layers.create_tensor(dtype="float32", name="rdl_a1")
        layers.assign(np.array([[0, 0, 9, 9], [10, 10, 19, 19]],
                               np.float32), a0)
        layers.assign(np.array([[0, 0, 19, 19]], np.float32), a1)
        info = layers.data("rdl_info", shape=[3], dtype="float32")
        return layers.detection.retinanet_detection_output(
            [b0, b1], [s0, s1], [a0, a1], info,
            score_threshold=0.9, nms_top_k=3, keep_top_k=5)
    out, = _run(build, {
        "rdl_b0": np.zeros((1, 2, 4), np.float32),
        "rdl_b1": np.zeros((1, 1, 4), np.float32),
        "rdl_s0": np.full((1, 2, 3), 0.5, np.float32),
        "rdl_s1": np.full((1, 1, 3), 0.5, np.float32),
        "rdl_info": np.array([[32.0, 32.0, 1.0]], np.float32)})
    kept = out[0][out[0][:, 0] > 0]
    # level-0 scores (0.5 < 0.9) are filtered; the last level keeps all
    assert kept.shape[0] >= 1
    assert np.allclose(kept[:, 1], 0.5)
    # all survivors decode from the level-1 anchor (exp(0)*20-wide box)
    np.testing.assert_allclose(kept[:, 4] - kept[:, 2], 19.0, atol=1e-4)


def test_where_and_unique_layers_padded():
    """layers.where / layers.unique wrap the padded static-shape ops
    instead of raising (reference where_index_op / unique_op)."""
    def build():
        c = layers.data("wuc", shape=[6], dtype="float32",
                        append_batch_size=False)
        cond = layers.cast(layers.less_than(
            layers.fill_constant([6], "float32", 2.0), c), "bool")
        idx = layers.where(cond)
        x = layers.data("wux", shape=[5], dtype="int64",
                        append_batch_size=False)
        u, inv = layers.unique(x, dtype="int64")
        return [idx, u, inv]
    idx, u, inv = _run(build, {
        "wuc": np.array([1.0, 3.0, 0.0, 5.0, 2.0, 9.0], np.float32),
        "wux": np.array([7, 2, 7, 4, 2], np.int64)})
    real = idx[idx[:, 0] >= 0, 0] if idx.ndim == 2 else idx[idx >= 0]
    np.testing.assert_array_equal(np.sort(real), [1, 3, 5])
    # first 3 slots are the real uniques; padding is int-max sentinel
    assert set(int(v) for v in u[:3]) == {2, 4, 7}
    assert (u[3:] == np.iinfo(u.dtype).max).all()  # sentinel padding
    np.testing.assert_array_equal(u[inv], [7, 2, 7, 4, 2])

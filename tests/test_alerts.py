"""SLO alerting engine (paddle_tpu/monitor_alerts.py): rule grammar,
threshold/ratio/burn evaluation with a fake clock, multi-window
burn-rate semantics (a transient spike must NOT fire; a sustained
breach must), exactly-once atomic incident bundles with trace-exemplar
correlation, and the /alertz + /healthz + /metrics exposure on the
serving HTTP front end. Everything runs on a fake clock — no sleeps in
the evaluation paths."""
import contextlib
import json
import os
import sys
import time
import urllib.request

import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, monitor_alerts, trace
from paddle_tpu.monitor_alerts import (AlertEngine, parse_duration,
                                       parse_rules)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ms-oriented buckets for the synthetic latency histograms: good
# requests land in <=5, the injected-slow ones in (250, 500]
MS_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0)


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


@contextlib.contextmanager
def _monitor_on(**flag_over):
    prev = {k: getattr(fluid.FLAGS, k)
            for k in list(flag_over) + ["enable_monitor"]}
    fluid.set_flags({"FLAGS_enable_monitor": True,
                     **{f"FLAGS_{k}": v for k, v in flag_over.items()}})
    monitor.reset_stats()
    monitor.reset_flight_recorder()
    try:
        yield monitor
    finally:
        monitor_alerts.stop_alerts()
        monitor.reset_stats()
        monitor.reset_flight_recorder()
        fluid.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Rule grammar
# ---------------------------------------------------------------------------

def test_parse_duration():
    assert parse_duration("30s") == 30.0
    assert parse_duration("5m") == 300.0
    assert parse_duration("1h") == 3600.0
    assert parse_duration("2.5") == 2.5
    with pytest.raises(ValueError):
        parse_duration("")


def test_parse_rules_all_kinds():
    rules = parse_rules(
        "deep:threshold:serving.queue_depth > 100:for=30s;"
        "shed:ratio:serving.rejected/serving.requests >= 0.05;"
        "burny:burn:serving.e2e_ms:p99 > 250:windows=1m,10m")
    assert [r.kind for r in rules] == ["threshold", "ratio", "burn"]
    t, r, b = rules
    assert t.stat == "serving.queue_depth" and t.op == ">" \
        and t.value == 100.0 and t.for_s == 30.0
    # >= must not parse as > (longest-op-first)
    assert r.num == "serving.rejected" and r.den == "serving.requests" \
        and r.op == ">=" and r.value == 0.05
    assert b.stat == "serving.e2e_ms" and b.pct == 0.99 \
        and b.windows_s == (60.0, 600.0)
    d = b.to_dict()
    assert d["histogram"] == "serving.e2e_ms" \
        and d["windows_s"] == [60.0, 600.0]
    # empty spec -> no rules (the disabled default)
    assert parse_rules("") == [] and parse_rules(None) == []


@pytest.mark.parametrize("bad", [
    "noexpr:threshold",                        # too few fields
    "x:threshold:serving.queue_depth 100",     # no operator
    "x:ratio:serving.rejected > 0.05",         # ratio without NUM/DEN
    "x:burn:h:p99 > 1",                        # burn without windows=
    "x:burn:h:q99 > 1:windows=1m",             # bad percentile syntax
    "x:burn:h:p150 > 1:windows=1m",            # percentile out of range
    "x:frobnicate:a > 1",                      # unknown kind
    "x:threshold:a > 1:unknown=2",             # unknown option
    "a:threshold:x > 1;a:threshold:y > 2",     # duplicate name
])
def test_parse_rules_rejects_malformed(bad):
    with pytest.raises(ValueError, match="bad alert rule"):
        parse_rules(bad)


# ---------------------------------------------------------------------------
# Threshold + ratio state machine (fake clock)
# ---------------------------------------------------------------------------

def test_threshold_for_pending_then_firing_then_resolved():
    with _monitor_on():
        clock = _Clock()
        eng = AlertEngine(parse_rules(
            "deep:threshold:t.depth > 10:for=30s"), clock=clock)
        # missing stat: no breach, stays inactive
        out = eng.evaluate_once()
        assert out["rules"][0]["state"] == "inactive"

        monitor.STAT_SET("t.depth", 50)
        out = eng.evaluate_once()
        assert out["rules"][0]["state"] == "pending"  # for= hold-down
        assert out["pending"] == 1 and out["firing"] == 0

        clock.t += 29
        assert eng.evaluate_once()["rules"][0]["state"] == "pending"
        clock.t += 1
        out = eng.evaluate_once()
        assert out["rules"][0]["state"] == "firing"
        assert out["firing"] == 1 and eng.firing() == ["deep"]
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["alerts.fired"] == 1
        assert snap["gauges"]["alerts.firing"] == 1

        # a breach that clears mid-hold-down resets the episode
        monitor.STAT_SET("t.depth", 3)
        out = eng.evaluate_once()
        assert out["rules"][0]["state"] == "inactive"
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["alerts.resolved"] == 1
        assert snap["gauges"]["alerts.firing"] == 0
        # re-breach starts a fresh for= window
        monitor.STAT_SET("t.depth", 50)
        assert eng.evaluate_once()["rules"][0]["state"] == "pending"


def test_ratio_rule_and_zero_denominator():
    with _monitor_on():
        clock = _Clock()
        eng = AlertEngine(parse_rules(
            "shed:ratio:t.rej/t.req > 0.05"), clock=clock)
        # no traffic at all: denominator 0 never breaches
        assert eng.evaluate_once()["rules"][0]["state"] == "inactive"
        monitor.STAT_ADD("t.req", 100)
        monitor.STAT_ADD("t.rej", 3)
        out = eng.evaluate_once()
        assert out["rules"][0]["state"] == "inactive"
        assert out["rules"][0]["value"] == pytest.approx(0.03)
        monitor.STAT_ADD("t.rej", 7)   # 10/100
        out = eng.evaluate_once()      # for_s=0 -> fires immediately
        assert out["rules"][0]["state"] == "firing"
        assert out["rules"][0]["value"] == pytest.approx(0.10)


# ---------------------------------------------------------------------------
# Multi-window burn rate (fake clock)
# ---------------------------------------------------------------------------

def _observe(n, ms, exemplar=None):
    for _ in range(n):
        monitor.STAT_OBSERVE("t.req_ms", ms, buckets=MS_BUCKETS,
                             exemplar=exemplar)


def test_burn_rate_spike_vs_sustained():
    """The canonical multi-window property: a one-tick latency spike
    trips the short window but is diluted out of the long one (no
    fire); only a sustained breach fires; recovery resolves."""
    with _monitor_on():
        clock = _Clock()
        eng = AlertEngine(parse_rules(
            "slo:burn:t.req_ms:p99 > 100:windows=10s,60s"), clock=clock)

        # cold start: even an immediately-terrible percentile must not
        # fire while no window has full history coverage
        _observe(50, 400.0)
        out = eng.evaluate_once()
        r = out["rules"][0]
        assert r["state"] == "inactive"
        assert not any(w["covered"]
                       for w in r["window_detail"].values())

        monitor.STAT_RESET("t.req_ms")
        eng = AlertEngine(parse_rules(
            "slo:burn:t.req_ms:p99 > 100:windows=10s,60s"), clock=clock)
        # warm both windows with healthy traffic: 50 good obs / 5s tick
        for _ in range(14):            # 70s of history
            _observe(50, 4.0)
            eng.evaluate_once()
            clock.t += 5
        r = eng.evaluate_once()["rules"][0]
        assert r["state"] == "inactive"
        assert all(w["covered"] for w in r["window_detail"].values())

        # transient spike: 5 bad among ~600 good in the 60s window
        # (0.8% < 1%) -> short window breaches, long one does not
        _observe(50, 4.0)
        _observe(5, 400.0)
        clock.t += 5
        r = eng.evaluate_once()["rules"][0]
        assert r["state"] == "inactive", r
        det = r["window_detail"]
        assert det["10s"]["breach"] and not det["60s"]["breach"]

        # sustained breach: every request slow for two ticks
        for _ in range(2):
            _observe(50, 400.0)
            clock.t += 5
            r = eng.evaluate_once()["rules"][0]
        assert r["state"] == "firing", r
        assert all(w["breach"] for w in r["window_detail"].values())

        # recovery: healthy traffic until the bad obs age out of both
        # windows
        for _ in range(14):
            _observe(50, 4.0)
            clock.t += 5
            r = eng.evaluate_once()["rules"][0]
        assert r["state"] == "inactive"
        c = monitor.get_stats_snapshot()["counters"]
        assert c["alerts.fired"] == 1 and c["alerts.resolved"] == 1


def test_burn_rate_survives_stat_reset():
    with _monitor_on():
        clock = _Clock()
        eng = AlertEngine(parse_rules(
            "slo:burn:t.req_ms:p99 > 100:windows=10s"), clock=clock)
        for _ in range(4):
            _observe(20, 4.0)
            eng.evaluate_once()
            clock.t += 5
        monitor.STAT_RESET("t.req_ms")   # counts go backwards
        _observe(5, 400.0)
        clock.t += 5
        # stale history was cleared: the window is uncovered again, so
        # the reset cannot fabricate a negative-delta breach
        r = eng.evaluate_once()["rules"][0]
        assert r["state"] == "inactive"


# ---------------------------------------------------------------------------
# Incident bundles
# ---------------------------------------------------------------------------

def test_bundle_written_exactly_once_per_episode(tmp_path):
    with _monitor_on(alert_bundle_dir=str(tmp_path)):
        clock = _Clock()
        eng = AlertEngine(parse_rules(
            "deep:threshold:t.depth > 10"), clock=clock)
        monitor.STAT_SET("t.depth", 50)
        eng.evaluate_once()
        files = sorted(tmp_path.glob("incident_deep_*.json"))
        assert len(files) == 1
        # staying in firing across further ticks must not rewrite
        for _ in range(3):
            clock.t += 5
            eng.evaluate_once()
        assert len(sorted(tmp_path.glob("incident_deep_*.json"))) == 1
        assert monitor.get_stats_snapshot()["counters"][
            "alerts.bundles_written"] == 1
        # resolve + re-fire = a new episode = a second bundle
        monitor.STAT_SET("t.depth", 0)
        clock.t += 5
        eng.evaluate_once()
        monitor.STAT_SET("t.depth", 99)
        clock.t += 5
        eng.evaluate_once()
        files = sorted(tmp_path.glob("incident_deep_*.json"))
        assert len(files) == 2
        # atomic write: no tmp droppings, every file parses + validates
        assert not list(tmp_path.glob("*.tmp.*"))
        validate = _tools("validate_bench_json").validate_incident_bundle
        for f in files:
            with open(f) as fh:
                bundle = json.load(fh)
            assert validate(bundle, f.name) == []
            assert bundle["rule"]["name"] == "deep"
            assert bundle["snapshot"]["gauges"]["t.depth"] >= 50


def test_bundle_failure_never_unwinds_evaluation(tmp_path):
    blocked = tmp_path / "not_a_dir"
    blocked.write_text("a file where the bundle dir should be")
    with _monitor_on(alert_bundle_dir=str(blocked / "sub")):
        eng = AlertEngine(parse_rules(
            "deep:threshold:t.depth > 10"), clock=_Clock())
        monitor.STAT_SET("t.depth", 50)
        out = eng.evaluate_once()      # must not raise
        assert out["rules"][0]["state"] == "firing"
        c = monitor.get_stats_snapshot()["counters"]
        assert c["alerts.bundle_errors"] == 1
        assert "alerts.bundles_written" not in c


# ---------------------------------------------------------------------------
# End-to-end demo: injected latency fault -> burn alert -> one bundle
# whose exemplars/spans identify the breaching requests
# ---------------------------------------------------------------------------

def test_e2e_fault_trips_burn_alert_with_correlated_bundle(tmp_path):
    """The acceptance demo: a synthetic request loop under a
    deterministic slow_step fault trips the burn-rate alert, and the
    single incident bundle leads with the trace ids of the requests
    that actually breached the SLO — with zero compiles involved."""
    from paddle_tpu.resilience import faults
    prev_trace = {k: getattr(fluid.FLAGS, k)
                  for k in ("enable_trace", "trace_sample",
                            "fault_spec")}
    fluid.set_flags({"FLAGS_enable_trace": True,
                     "FLAGS_trace_sample": 1.0,
                     "FLAGS_fault_spec": ""})
    trace.reset()
    faults.reset_injector()
    try:
        with _monitor_on(alert_bundle_dir=str(tmp_path),
                         alert_bundle_max_spans=512):
            clock = _Clock()
            eng = AlertEngine(parse_rules(
                "slo:burn:t.req_ms:p99 > 100:windows=10s,60s"),
                clock=clock)
            compiles_before = monitor.get_stats_snapshot()[
                "counters"].get("executor.compile_cache_miss", 0)

            slow_ids = []

            def request(slow):
                span = trace.start_span("request")
                t0 = time.perf_counter()
                inj = faults.injector()
                if inj is not None:
                    inj.pre_step("serving")
                wall_ms = (time.perf_counter() - t0) * 1000.0
                # healthy requests measure ~0ms of injected stall; use
                # a 4ms floor so they land in a deterministic bucket
                lat = max(wall_ms, 400.0 if slow else 4.0)
                tid = span.trace_id
                monitor.STAT_OBSERVE("t.req_ms", lat,
                                     buckets=MS_BUCKETS, exemplar=tid)
                trace.finish_trace(span)
                if slow:
                    slow_ids.append(tid)

            # healthy warmup covering both windows
            for _ in range(14):
                for _ in range(50):
                    request(slow=False)
                eng.evaluate_once()
                clock.t += 5
            assert eng.evaluate_once()["firing"] == 0

            # arm the fault: every serving-site step now stalls 20ms
            fluid.set_flags(
                {"FLAGS_fault_spec": "slow_step:ms=20:site=serving"})
            faults.reset_injector()
            for _ in range(2):
                for _ in range(50):
                    request(slow=True)
                clock.t += 5
                out = eng.evaluate_once()
            assert out["firing"] == 1, out
            inj_snap = monitor.get_stats_snapshot()["counters"]
            assert inj_snap["resilience.fault_slow"] >= 100

            bundles = sorted(tmp_path.glob("incident_slo_*.json"))
            assert len(bundles) == 1   # exactly one per firing episode
            with open(bundles[0]) as f:
                bundle = json.load(f)
            validate = _tools(
                "validate_bench_json").validate_incident_bundle
            assert validate(bundle, bundles[0].name) == []

            # breaching-bucket exemplars lead, and they are traces of
            # genuinely slow requests
            ids = bundle["exemplar_trace_ids"]
            assert ids and ids[0] in slow_ids
            slow_set = set(slow_ids)
            breaching = [i for i in ids if i in slow_set]
            assert breaching, ids
            # the bundle's spans cover the breaching exemplar traces
            span_tids = {s["trace_id"] for s in bundle["spans"]}
            assert ids[0] in span_tids
            assert bundle["rule"]["histogram"] == "t.req_ms"
            assert all(w["breach"]
                       for w in bundle["windows"].values())

            # alert evaluation is pure host-side bookkeeping: nothing
            # compiled anywhere in the loop
            compiles_after = monitor.get_stats_snapshot()[
                "counters"].get("executor.compile_cache_miss", 0)
            assert compiles_after == compiles_before
    finally:
        faults.reset_injector()
        trace.reset()
        fluid.set_flags(
            {f"FLAGS_{k}": v for k, v in prev_trace.items()})
        faults.reset_injector()


# ---------------------------------------------------------------------------
# HTTP exposure: /alertz, /healthz alerts_firing, /metrics ALERTS
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def test_http_alertz_healthz_and_metrics_exposure():
    from paddle_tpu.serving.http import ServingHTTPServer

    class _StubEngine:
        ready = True

    prev = {k: getattr(fluid.FLAGS, k)
            for k in ("alert_rules", "alert_eval_interval_s")}
    # interval 0: the engine exists, but only explicit evaluate_once()
    # ticks it — the test controls exactly when state changes
    fluid.set_flags({
        "FLAGS_alert_rules": "deep:threshold:t.depth > 10",
        "FLAGS_alert_eval_interval_s": 0.0})
    monitor_alerts.stop_alerts()
    srv = None
    try:
        with _monitor_on():
            srv = ServingHTTPServer(engine=_StubEngine(), port=0)
            eng = monitor_alerts.active_engine()
            assert eng is not None   # maybe_start created it from FLAGS

            code, raw = _get(srv.url + "/alertz")
            assert code == 200
            body = json.loads(raw)
            assert body["firing"] == 0 \
                and body["rules"][0]["state"] == "inactive"
            # inactive rules emit no ALERTS series
            assert "ALERTS{" not in monitor.prometheus_text()

            monitor.STAT_SET("t.depth", 42)
            eng.evaluate_once()

            code, raw = _get(srv.url + "/alertz")
            body = json.loads(raw)
            assert code == 200 and body["firing"] == 1
            assert body["rules"][0]["state"] == "firing"
            assert body["rules"][0]["value"] == 42

            code, raw = _get(srv.url + "/healthz")
            body = json.loads(raw)
            # a firing alert informs but never flips health
            assert code == 200 and body["state"] == "ok"
            assert body["alerts_firing"] == 1

            code, raw = _get(srv.url + "/metrics")
            text = raw.decode()
            assert code == 200
            assert 'ALERTS{alertname="deep",alertstate="firing"} 1' \
                in text
    finally:
        if srv is not None:
            srv.close()
        monitor_alerts.stop_alerts()
        fluid.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})


def test_background_evaluator_thread_lifecycle():
    prev = {k: getattr(fluid.FLAGS, k)
            for k in ("alert_rules", "alert_eval_interval_s")}
    fluid.set_flags({
        "FLAGS_alert_rules": "deep:threshold:t.depth > 10",
        "FLAGS_alert_eval_interval_s": 0.02})
    monitor_alerts.stop_alerts()
    try:
        with _monitor_on():
            monitor.STAT_SET("t.depth", 42)
            eng = monitor_alerts.maybe_start()
            assert eng is not None
            deadline = time.time() + 5.0
            while time.time() < deadline \
                    and monitor_alerts.firing_count() == 0:
                time.sleep(0.01)
            assert monitor_alerts.firing_count() == 1
            # maybe_start is idempotent: no second thread, same engine
            assert monitor_alerts.maybe_start() is eng
    finally:
        monitor_alerts.stop_alerts()
        fluid.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})
    # after stop the module answers empty, engine-lessly
    assert monitor_alerts.firing_count() == 0
    assert monitor_alerts.alertz_dict()["rules"] == []

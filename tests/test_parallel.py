"""Distributed-path tests on the 8-device virtual CPU mesh
(reference pattern: test_dist_base.py loss-equivalence on localhost)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _build_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_data_parallel_matches_single_device():
    """2-trainer run ≈ single-process run (test_dist_base.py:22-27)."""
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = rng.randn(32, 1).astype(np.float32)

    losses = {}
    for mode in ("single", "dp"):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss = _build_mlp()
            main.random_seed = 7
            startup.random_seed = 7
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mode == "dp":
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name)
            vals = []
            for _ in range(5):
                lv, = exe.run(prog, feed={"x": xs, "y": ys},
                              fetch_list=[loss])
                vals.append(float(np.asarray(lv)))
            losses[mode] = vals
    np.testing.assert_allclose(losses["single"], losses["dp"],
                               rtol=1e-4, atol=1e-5)


def test_collective_grad_flows():
    """Regression: collectives must not sever gradient flow."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        h = layers.fc(x, size=4, bias_attr=False)
        h2 = layers.c_allreduce_sum(h)
        loss = layers.mean(h2)
        pg = fluid.optimizer.SGD(0.1).backward(loss)
    assert len(pg) == 1, "fc weight must receive a gradient through the " \
        "collective"


def test_transformer_tp_sp_dryrun():
    """dp x tp mesh with Megatron TP/SP shardings compiles + runs."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_shard_hint_compiles():
    from jax.sharding import Mesh
    import jax
    import numpy as np_
    mesh = Mesh(np_.asarray(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    from paddle_tpu.parallel.mesh import mesh_context
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            mesh_context(mesh):
        x = layers.data("x", shape=[8, 16], dtype="float32",
                        append_batch_size=False)
        h = layers.fc(x, size=32)
        h = layers.shard_hint(h, ["dp", "tp"])
        loss = layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        compiled = fluid.CompiledProgram(main).with_distributed(
            mesh, batch_axes=("dp",))
        lv, = exe.run(compiled,
                      feed={"x": np.ones((8, 16), np.float32)},
                      fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()


def test_unknown_batch_axis_raises():
    from jax.sharding import Mesh
    import jax
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("dp",))
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[8, 4], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.fc(x, size=4))
        exe = fluid.Executor()
        exe.run(startup)
        compiled = fluid.CompiledProgram(main).with_distributed(
            mesh, batch_axes=("data",))
        with pytest.raises(ValueError, match="batch_axes"):
            exe.run(compiled, feed={"x": np.ones((8, 4), np.float32)},
                    fetch_list=[loss])

"""Distributed-path tests on the 8-device virtual CPU mesh
(reference pattern: test_dist_base.py loss-equivalence on localhost)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _build_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        label = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_data_parallel_matches_single_device():
    """2-trainer run ≈ single-process run (test_dist_base.py:22-27)."""
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = rng.randn(32, 1).astype(np.float32)

    losses = {}
    for mode in ("single", "dp"):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss = _build_mlp()
            main.random_seed = 7
            startup.random_seed = 7
            exe = fluid.Executor()
            exe.run(startup)
            prog = main
            if mode == "dp":
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name)
            vals = []
            for _ in range(5):
                lv, = exe.run(prog, feed={"x": xs, "y": ys},
                              fetch_list=[loss])
                vals.append(float(np.asarray(lv)))
            losses[mode] = vals
    np.testing.assert_allclose(losses["single"], losses["dp"],
                               rtol=1e-4, atol=1e-5)


def test_collective_grad_flows():
    """Regression: collectives must not sever gradient flow."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        h = layers.fc(x, size=4, bias_attr=False)
        h2 = layers.c_allreduce_sum(h)
        loss = layers.mean(h2)
        pg = fluid.optimizer.SGD(0.1).backward(loss)
    assert len(pg) == 1, "fc weight must receive a gradient through the " \
        "collective"


def test_transformer_tp_sp_dryrun():
    """dp x tp mesh with Megatron TP/SP shardings compiles + runs."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_shard_hint_compiles():
    from jax.sharding import Mesh
    import jax
    import numpy as np_
    mesh = Mesh(np_.asarray(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    from paddle_tpu.parallel.mesh import mesh_context
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            mesh_context(mesh):
        x = layers.data("x", shape=[8, 16], dtype="float32",
                        append_batch_size=False)
        h = layers.fc(x, size=32)
        h = layers.shard_hint(h, ["dp", "tp"])
        loss = layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        compiled = fluid.CompiledProgram(main).with_distributed(
            mesh, batch_axes=("dp",))
        lv, = exe.run(compiled,
                      feed={"x": np.ones((8, 16), np.float32)},
                      fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()


def test_unknown_batch_axis_raises():
    from jax.sharding import Mesh
    import jax
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("dp",))
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[8, 4], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.fc(x, size=4))
        exe = fluid.Executor()
        exe.run(startup)
        compiled = fluid.CompiledProgram(main).with_distributed(
            mesh, batch_axes=("data",))
        with pytest.raises(ValueError, match="batch_axes"):
            exe.run(compiled, feed={"x": np.ones((8, 4), np.float32)},
                    fetch_list=[loss])


# ---------------------------------------------------------------------------
# Pipeline parallelism (parallel/pipeline.py)
# ---------------------------------------------------------------------------

def test_gpipe_matches_sequential():
    """GPipe over pp=4 must equal running the 4 stages sequentially."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import gpipe, stack_stage_params
    from paddle_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(0)
    d = 16
    n_stages = 4
    params = [{"w": jnp.asarray(rng.randn(d, d).astype(np.float32) / 4),
               "b": jnp.asarray(rng.randn(d).astype(np.float32) / 10)}
              for _ in range(n_stages)]

    def stage(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    x = jnp.asarray(rng.randn(8, d).astype(np.float32))
    want = x
    for p in params:
        want = stage(p, want)

    mesh = make_mesh((2, 4), ("dp", "pp"))
    stacked = stack_stage_params(params)
    got = gpipe(stage, stacked, x, n_microbatches=4, mesh=mesh, axis="pp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gpipe_backward_trains():
    """jax.grad through the pipeline gives the same grads as sequential."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import gpipe, stack_stage_params
    from paddle_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(1)
    d, n_stages = 8, 4
    params = [{"w": jnp.asarray(rng.randn(d, d).astype(np.float32) / 3)}
              for _ in range(n_stages)]
    stacked = stack_stage_params(params)
    x = jnp.asarray(rng.randn(8, d).astype(np.float32))
    mesh = make_mesh((4,), ("pp",), devices=jax.devices()[:4])

    def stage(p, h):
        return jnp.tanh(h @ p["w"])

    def loss_pipe(sp):
        return jnp.mean(gpipe(stage, sp, x, n_microbatches=4,
                              mesh=mesh, axis="pp") ** 2)

    def loss_seq(sp):
        h = x
        for i in range(n_stages):
            h = stage(jax.tree.map(lambda a: a[i], sp), h)
        return jnp.mean(h ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]),
                               np.asarray(g_seq["w"]), rtol=1e-4, atol=1e-5)


def test_section_pipeline_grad_accumulation():
    import jax.numpy as jnp
    from paddle_tpu.parallel import SectionPipeline

    rng = np.random.RandomState(2)
    d = 8
    p1 = {"w": jnp.asarray(rng.randn(d, d).astype(np.float32))}
    p2 = {"w": jnp.asarray(rng.randn(d, 1).astype(np.float32))}
    x = jnp.asarray(rng.randn(16, d).astype(np.float32))
    y = jnp.asarray(rng.randn(16, 1).astype(np.float32))

    def s1(p, h):
        return jnp.tanh(h @ p["w"])

    def s2(p, h):
        return h @ p["w"]

    def loss_fn(pred, yb):
        return jnp.mean((pred - yb) ** 2)

    pipe = SectionPipeline([s1, s2], n_microbatches=4)
    loss, grads = pipe.grad(loss_fn, [p1, p2], x, y)

    import jax

    def full(ps):
        return loss_fn(s2(ps[1], s1(ps[0], x)), y)

    want_loss, want_grads = jax.value_and_grad(full)([p1, p2])
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads[0]["w"]),
                               np.asarray(want_grads[0]["w"]),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Recompute + gradient merge (IR-level)
# ---------------------------------------------------------------------------

def _train_mlp_losses(opt_factory, steps=6, seed=3, batch=16):
    rng = np.random.RandomState(seed)
    xs = rng.randn(batch, 16).astype(np.float32)
    ys = rng.randn(batch, 1).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[16], dtype="float32")
            label = layers.data("y", shape=[1], dtype="float32")
            h1 = layers.fc(x, size=32, act="relu")
            h2 = layers.fc(h1, size=32, act="relu")
            pred = layers.fc(h2, size=1)
            loss = layers.mean(layers.square_error_cost(pred, label))
            opt_factory(loss, [h1, h2])
        main.random_seed = startup.random_seed = 11
        exe = fluid.Executor()
        exe.run(startup)
        out = []
        for _ in range(steps):
            lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            out.append(float(np.asarray(lv)))
    return out


def test_recompute_matches_plain():
    def plain(loss, cps):
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    def recompute(loss, cps):
        opt = fluid.optimizer.RecomputeOptimizer(
            fluid.optimizer.SGD(learning_rate=0.1))
        opt._set_checkpoints(cps)
        opt.minimize(loss)

    np.testing.assert_allclose(_train_mlp_losses(plain),
                               _train_mlp_losses(recompute),
                               rtol=1e-5, atol=1e-6)


def test_gradient_merge_matches_big_batch():
    """k=2 merge over half-batches == plain SGD on the full batch."""
    rng = np.random.RandomState(4)
    xs = rng.randn(16, 16).astype(np.float32)
    ys = rng.randn(16, 1).astype(np.float32)

    def build(opt_factory):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[16], dtype="float32")
            label = layers.data("y", shape=[1], dtype="float32")
            h = layers.fc(x, size=8, act="relu")
            pred = layers.fc(h, size=1)
            loss = layers.mean(layers.square_error_cost(pred, label))
            opt_factory(loss)
        main.random_seed = startup.random_seed = 13
        return main, startup, loss

    # merged: two half-batch steps per apply, averaging grads
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup, loss = build(lambda l: fluid.optimizer
                                    .GradientMergeOptimizer(
                                        fluid.optimizer.SGD(0.1), k_steps=2)
                                    .minimize(l))
        w_name = main.global_block().all_parameters()[0].name
        exe = fluid.Executor()
        exe.run(startup)
        for i in range(4):  # 2 applies
            half = slice(0, 8) if i % 2 == 0 else slice(8, 16)
            exe.run(main, feed={"x": xs[half], "y": ys[half]},
                    fetch_list=[loss])
        w_merged = np.asarray(scope.get(w_name))

    # plain: one full-batch step per apply
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        main, startup, loss = build(
            lambda l: fluid.optimizer.SGD(0.1).minimize(l))
        w_name = main.global_block().all_parameters()[0].name
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        w_plain = np.asarray(scope.get(w_name))

    np.testing.assert_allclose(w_merged, w_plain, rtol=1e-4, atol=1e-5)


def test_moe_expert_parallel_matches_dense_reference():
    """ep axis: expert-sharded MoE FFN over the 8-device mesh must match
    a single-device dense evaluation of the same top-1 routing, and its
    gradients must be finite through a train step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.parallel.moe import (init_moe_params, moe_ffn_sharded)

    E, d, f = 8, 16, 32
    params = init_moe_params(0, E, d, f)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 6, d).astype(np.float32))

    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("ep",))
    y, load = moe_ffn_sharded(x, params, mesh, ep_axis="ep")

    # dense single-device reference with identical routing math
    logits = jnp.einsum("btd,de->bte", x, params["gate_w"])
    probs = jax.nn.softmax(logits, -1)
    mask = jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=probs.dtype)
    coef = probs * mask
    h = jax.nn.gelu(jnp.einsum("btd,edf->betf", x, params["w1"])
                    + params["b1"][None, :, None, :])
    out = jnp.einsum("betf,efd->betd", h, params["w2"]) \
        + params["b2"][None, :, None, :]
    ref = jnp.einsum("betd,bte->btd", out, coef)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=2e-5)
    assert 0.0 < float(load) <= 1.0

    def loss_fn(p):
        yy, _ = moe_ffn_sharded(x, p, mesh, ep_axis="ep")
        return jnp.mean(yy ** 2)

    g = jax.jit(jax.grad(loss_fn))(params)
    assert all(bool(np.isfinite(np.asarray(v)).all())
               for v in jax.tree.leaves(g))
    # the router (gate) must receive gradient through the prob factor
    assert float(np.abs(np.asarray(g["gate_w"])).sum()) > 0


def test_c_alltoall_op_exchanges_shards():
    """c_alltoall over a mesh axis: the Ulysses/MoE exchange primitive
    (XLA AllToAll over ICI)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.core.registry import REGISTRY

    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("sp",))
    x = jnp.arange(8 * 8 * 4, dtype=jnp.float32).reshape(8, 8, 4)

    opdef = REGISTRY.get("c_alltoall")

    def local(xl):
        out = opdef.lower(None, {"X": [xl]},
                          {"axis_name": "sp", "split_axis": 1,
                           "concat_axis": 0})
        return out["Out"][0]

    sm = shard_map(local, mesh=mesh, in_specs=(P("sp", None, None),),
                   out_specs=P("sp", None, None), check_rep=False)
    y = np.asarray(sm(x))
    # all_to_all(split=1, concat=0) == a global [dim0 <-> dim1-block]
    # transpose: reconstruct via the jax primitive as reference
    def ref_local(xl):
        return jax.lax.all_to_all(xl, "sp", split_axis=1, concat_axis=0,
                                  tiled=True)
    ref = np.asarray(shard_map(ref_local, mesh=mesh,
                               in_specs=(P("sp", None, None),),
                               out_specs=P("sp", None, None),
                               check_rep=False)(x))
    np.testing.assert_array_equal(y, ref)


def test_seq_parallel_attention_ops_on_mesh():
    """The registered ring/ulysses Program-IR ops run on a real mesh
    context and match each other (same exact attention math); a mesh
    WITHOUT the seq axis falls back to the single-device path."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.core.registry import REGISTRY

    class Ctx:
        def __init__(self, mesh):
            self.mesh = mesh

    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 8, 32, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 8, 32, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 8, 32, 8).astype(np.float32))
    ins = {"Q": [q], "K": [k], "V": [v]}
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("sp",))

    outs = {}
    for op in ("ring_attention", "ulysses_attention"):
        outs[op] = np.asarray(
            REGISTRY.get(op).lower(Ctx(mesh), ins, {"causal": True})
            ["Out"][0])
    np.testing.assert_allclose(outs["ring_attention"],
                               outs["ulysses_attention"], atol=2e-5)

    # mesh without 'sp': graceful exact fallback, same numbers
    mesh2 = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("dp", "tp"))
    fb = np.asarray(
        REGISTRY.get("ulysses_attention").lower(Ctx(mesh2), ins,
                                                {"causal": True})
        ["Out"][0])
    np.testing.assert_allclose(fb, outs["ulysses_attention"], atol=2e-5)


def test_moe_sparse_dispatch_matches_dense():
    """Capacity-based a2a dispatch == dense formulation when nothing is
    dropped; small capacity drops overflow tokens to exactly zero."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.parallel.moe import (init_moe_params, moe_ffn_sharded,
                                         moe_ffn_sparse_sharded)

    E, d, f = 8, 16, 32
    params = init_moe_params(1, E, d, f)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 6, d).astype(np.float32))
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("ep",))

    dense, _ = moe_ffn_sharded(x, params, mesh, ep_axis="ep")
    sparse, load = moe_ffn_sparse_sharded(x, params, mesh, ep_axis="ep",
                                          capacity=12)  # >= N: no drops
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               atol=2e-5)
    assert 0.0 < float(load) <= 1.0

    # capacity 1: at most one token per expert survives; dropped rows
    # are exactly zero and survivors still match the dense math
    tiny, _ = moe_ffn_sparse_sharded(x, params, mesh, ep_axis="ep",
                                     capacity=1)
    tiny = np.asarray(tiny).reshape(-1, d)
    ref = np.asarray(dense).reshape(-1, d)
    zero_rows = np.all(tiny == 0.0, axis=-1)
    assert zero_rows.any()  # something overflowed
    keep_rows = ~zero_rows
    np.testing.assert_allclose(tiny[keep_rows], ref[keep_rows], atol=2e-5)

    # gradients flow (router + experts) through the sparse path
    def loss_fn(p):
        y, _ = moe_ffn_sparse_sharded(x, p, mesh, ep_axis="ep",
                                      capacity=12)
        return jnp.mean(y ** 2)

    g = jax.jit(jax.grad(loss_fn))(params)
    assert all(bool(np.isfinite(np.asarray(v)).all())
               for v in jax.tree.leaves(g))
    assert float(np.abs(np.asarray(g["gate_w"])).sum()) > 0


def test_moe_layer_trains_in_static_graph():
    """fluid.layers.moe_ffn end to end: a static program with an MoE
    FFN trains (single-device dense path here; with_distributed + an
    'ep' mesh axis runs the sharded formulations)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = fluid.layers.data("moe_x", shape=[6, 16], dtype="float32")
        y, load = layers.moe_ffn(x, num_experts=4, d_ff=32)
        tgt = fluid.layers.data("moe_t", shape=[6, 16], dtype="float32")
        loss = layers.mean(layers.square(
            layers.elementwise_sub(y, tgt)))
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        xv = rng.randn(4, 6, 16).astype(np.float32)
        tv = np.tanh(xv)
        losses = []
        for _ in range(25):
            lv, ld = exe.run(main, feed={"moe_x": xv, "moe_t": tv},
                             fetch_list=[loss, load])
            losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
        assert 0.0 < float(ld) <= 1.0


def test_long_context_example_trains():
    """examples/long_context.py: ring attention through the fluid API
    over the sp=8 mesh — the user-facing long-context walkthrough."""
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "examples/long_context.py"),
         "--cpu", "--steps", "8", "--seq", "128"],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ring attention over sp=8" in r.stdout

"""Registry-wide op sweep driven by tests/op_specs.py.

For every spec'd op: execute through the full Program-IR -> Executor ->
XLA path and compare against a direct call of the registered lowering
(IR-path integrity), check finiteness, compare optional numpy references,
and run analytic-vs-numeric gradient checks (reference op_test.py:47
get_numeric_gradient discipline) on the declared slots.

`python tests/test_op_sweep.py --matrix` regenerates OP_TEST_MATRIX.json,
the committed per-op pass/skip matrix for the whole registry.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax
import paddle_tpu as fluid
from paddle_tpu.backward import append_backward
from paddle_tpu.core.registry import REGISTRY
from paddle_tpu.framework import grad_var_name

from op_specs import SKIPS, SPECS


class _DirectCtx:
    """Minimal LowerCtx stand-in for direct lowering calls."""
    mesh = None
    block = None
    attrs = {}

    def __init__(self, is_test=False):
        self.is_test = is_test

    @property
    def rng(self):
        return jax.random.PRNGKey(0)

    def sub_block(self, idx):
        raise NotImplementedError

    def lower_sub_block(self, block, env):
        raise NotImplementedError


def _entries(slot, val):
    """Normalise spec input value -> [(var_name, array), ...]."""
    if isinstance(val, list):
        return [(n, np.asarray(a)) for n, a in val]
    return [(f"{slot}__in", np.asarray(val))]


def _direct_lower(op, spec):
    opdef = REGISTRY.get(op)
    ins = {}
    for slot, val in spec["ins"].items():
        ins[slot] = [jax.numpy.asarray(a) for _, a in _entries(slot, val)]
    ctx = _DirectCtx(is_test=spec["is_test"])
    outs = opdef.lower(ctx, ins, dict(spec["attrs"]))
    return {s: [np.asarray(a) for a in arrs] for s, arrs in outs.items()}


def _build_program(op, spec, grad_slots=()):
    main, startup = fluid.Program(), fluid.Program()
    direct = _direct_lower(op, spec)
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        in_map, feeds = {}, {}
        grad_names = []
        for slot, val in spec["ins"].items():
            names = []
            for name, arr in _entries(slot, val):
                blk.create_var(name=name, shape=list(arr.shape),
                               dtype=str(arr.dtype),
                               stop_gradient=slot not in grad_slots,
                               is_data=True)
                feeds[name] = arr
                names.append(name)
                if slot in grad_slots:
                    grad_names.append(name)
            in_map[slot] = names
        out_map = {}
        for slot, arrs in direct.items():
            names = []
            for i in range(len(arrs)):
                nm = f"{slot}__out" if len(arrs) == 1 else f"{slot}__o{i}"
                blk.create_var(name=nm, stop_gradient=False)
                names.append(nm)
            out_map[slot] = names
        attrs = dict(spec["attrs"])
        if spec["is_test"]:
            # the executor traces with is_test=False; the op-level attr
            # keeps both paths (direct ctx + executor) in the same mode
            attrs["is_test"] = True
        blk.append_op(op, inputs=in_map, outputs=out_map, attrs=attrs)
    return main, feeds, out_map, direct, grad_names


def _run_output_checks(op, spec):
    main, feeds, out_map, direct, _ = _build_program(op, spec)
    fetch, ref = [], []
    for slot, names in out_map.items():
        for nm, arr in zip(names, direct[slot]):
            fetch.append(nm)
            ref.append(arr)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        got = exe.run(main, feed=feeds, fetch_list=fetch)
    for nm, r, g in zip(fetch, ref, got):
        assert tuple(g.shape) == tuple(r.shape), \
            f"{op}: {nm} shape {g.shape} != direct {r.shape}"
        assert g.dtype == r.dtype, \
            f"{op}: {nm} dtype {g.dtype} != direct {r.dtype}"
        if spec["finite"] and np.issubdtype(g.dtype, np.floating):
            assert np.isfinite(g).all(), f"{op}: {nm} non-finite"
        if spec["exact"]:
            np.testing.assert_allclose(
                g, r, atol=spec["atol"], rtol=spec["atol"] * 10,
                err_msg=f"{op}: executor vs direct lowering for {nm}")
    # independent numpy reference
    if spec["expect"] is not None:
        flat_ins = {}
        for slot, val in spec["ins"].items():
            ent = _entries(slot, val)
            for n, a in ent:
                flat_ins[n] = a
            if len(ent) == 1:   # expose single-entry slots by slot name
                flat_ins[slot] = ent[0][1]
        want = spec["expect"](flat_ins, spec["attrs"])
        for slot, arrs in want.items():
            for nm, w in zip(out_map[slot], arrs):
                g = got[fetch.index(nm)]
                np.testing.assert_allclose(
                    g, np.asarray(w), atol=1e-4, rtol=1e-4,
                    err_msg=f"{op}: numpy reference mismatch for {nm}")


def _float_out_names(out_map, direct):
    names = []
    for slot, arrs in direct.items():
        for nm, arr in zip(out_map[slot], arrs):
            if np.issubdtype(arr.dtype, np.floating):
                names.append((slot, nm))
    return names


def _run_grad_check(op, spec):
    grad_slots = spec["grad"]
    main, feeds, out_map, direct, grad_names = _build_program(
        op, spec, grad_slots)
    opdef = REGISTRY.get(op)
    blk = main.global_block()
    with fluid.program_guard(main):
        means = []
        for slot, nm in _float_out_names(out_map, direct):
            if slot in opdef.nondiff_outputs:
                continue
            m = blk.create_var(name=f"{nm}__mean", stop_gradient=False)
            blk.append_op("mean", inputs={"X": [nm]},
                          outputs={"Out": [m.name]})
            means.append(m.name)
        assert means, f"{op}: no differentiable outputs for grad check"
        loss = blk.create_var(name="loss__", stop_gradient=False)
        blk.append_op("sum", inputs={"X": means},
                      outputs={"Out": [loss.name]})
        append_backward(blk.var("loss__"))

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        analytic = exe.run(main, feed=feeds,
                           fetch_list=[grad_var_name(n)
                                       for n in grad_names])

    # numeric central differences on a fresh forward-only program
    fmain, ffeeds, fout_map, fdirect, _ = _build_program(op, spec)
    fblk = fmain.global_block()
    with fluid.program_guard(fmain):
        means = []
        for slot, nm in _float_out_names(fout_map, fdirect):
            if slot in opdef.nondiff_outputs:
                continue
            m = fblk.create_var(name=f"{nm}__mean", stop_gradient=False)
            fblk.append_op("mean", inputs={"X": [nm]},
                           outputs={"Out": [m.name]})
            means.append(m.name)
        floss = fblk.create_var(name="loss__", stop_gradient=False)
        fblk.append_op("sum", inputs={"X": means},
                       outputs={"Out": [floss.name]})
    fexe = fluid.Executor()
    scope = fluid.Scope()

    def run_loss():
        with fluid.scope_guard(scope):
            return float(fexe.run(fmain, feed=ffeeds,
                                  fetch_list=["loss__"])[0])

    delta = 5e-3
    for name, a_grad in zip(grad_names, analytic):
        x = ffeeds[name]
        if not np.issubdtype(x.dtype, np.floating):
            continue
        flat = x.reshape(-1)
        num = np.zeros(flat.size, np.float64)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            hi = run_loss()
            flat[i] = orig - delta
            lo = run_loss()
            flat[i] = orig
            num[i] = (hi - lo) / (2 * delta)
        num = num.reshape(x.shape)
        a = np.asarray(a_grad, np.float64)
        denom = np.maximum(np.maximum(np.abs(a), np.abs(num)), 1e-2)
        rel = np.abs(a - num) / denom
        bad = (rel > spec["grad_tol"]) & (np.abs(a - num) > 1e-4)
        if np.any(bad):
            i = np.unravel_index(np.argmax(rel), rel.shape)
            raise AssertionError(
                f"{op}: grad mismatch for {name} at {i}: "
                f"analytic={a[i]:.6g} numeric={num[i]:.6g}")


def run_spec(op):
    spec = SPECS[op]
    _run_output_checks(op, spec)
    if spec["grad"]:
        _run_grad_check(op, spec)


# ---------------------------------------------------------------------------


def test_registry_fully_covered():
    """Every registered op is either spec'd or skipped with a reason.
    Ops registered dynamically by other tests (load_op_library plugins
    outside the package) are not part of the parity surface."""
    missing = [t for t in REGISTRY.types()
               if t not in SPECS and t not in SKIPS
               and getattr(REGISTRY.get(t).lower, "__module__",
                           "").startswith(("paddle_tpu.", "tests"))]
    assert not missing, f"ops without sweep spec or skip: {missing}"
    stale = [t for t in list(SPECS) + list(SKIPS)
             if not REGISTRY.has(t)]
    assert not stale, f"spec entries for unregistered ops: {stale}"


def test_sweep_scale():
    """The sweep directly tests a substantial fraction of the registry."""
    assert len(SPECS) >= 250, \
        f"only {len(SPECS)} ops spec'd; target >= 250"


@pytest.mark.parametrize("op", sorted(SPECS))
def test_op(op):
    run_spec(op)


# ---------------------------------------------------------------------------
# matrix generation: python tests/test_op_sweep.py --matrix
# ---------------------------------------------------------------------------

def write_matrix(path="OP_TEST_MATRIX.json"):
    import json
    import traceback
    matrix = {}
    for t in REGISTRY.types():
        if t in SKIPS:
            matrix[t] = {"status": "skip", "reason": SKIPS[t]}
        elif t in SPECS:
            try:
                run_spec(t)
                s = SPECS[t]
                matrix[t] = {"status": "pass",
                             "grad_checked": sorted(s["grad"]),
                             "exact": s["exact"],
                             "numpy_ref": s["expect"] is not None}
                if s["expect"] is None:
                    from op_expects import NOREF_REASONS
                    if t in NOREF_REASONS:
                        matrix[t]["noref_reason"] = NOREF_REASONS[t]
                if not s["grad"]:
                    from op_expects import NOGRAD_REASONS
                    if t in NOGRAD_REASONS:
                        matrix[t]["nograd_reason"] = NOGRAD_REASONS[t]
            except Exception as e:  # pragma: no cover
                matrix[t] = {"status": "fail",
                             "error": traceback.format_exception_only(
                                 type(e), e)[0].strip()}
        else:
            matrix[t] = {"status": "uncovered"}
    counts = {}
    for v in matrix.values():
        counts[v["status"]] = counts.get(v["status"], 0) + 1
    out = {"counts": counts, "total": len(matrix), "ops": matrix}
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps(counts), "->", path)


if __name__ == "__main__":
    import sys
    if "--matrix" in sys.argv:
        # standalone run: force the CPU backend the same way conftest does
        jax.config.update("jax_platforms", "cpu")
        write_matrix()

"""RNN stack tests: cells/rnn() (scan-based recurrent op), dynamic
gru/lstm full-sequence ops, beam-search decode (reference
test_rnn_cell_api.py / test_rnn_decode_api.py pattern)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.layers import rnn as rnn_mod


def _run(main, startup, feed, fetch):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_gru_cell_rnn_matches_numpy():
    b, t, din, d = 3, 5, 4, 6
    rs = np.random.RandomState(0)
    x = rs.randn(b, t, din).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data("x", [b, t, din])
        cell = rnn_mod.GRUCell(d)
        out, final = rnn_mod.rnn(cell, xv)
        outs = _run(main, startup, {"x": x}, [out, final])
    o, f = outs
    assert o.shape == (b, t, d)
    assert f.shape == (b, d)
    # final state equals last output
    np.testing.assert_allclose(o[:, -1], f, atol=1e-5)
    # outputs change over time (non-degenerate)
    assert np.abs(o[:, 0] - o[:, -1]).max() > 1e-6


def test_lstm_cell_rnn_shapes_and_grad():
    b, t, din, d = 2, 4, 3, 5
    rs = np.random.RandomState(1)
    x = rs.randn(b, t, din).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data("x", [b, t, din])
        cell = rnn_mod.LSTMCell(d)
        out, (h, c) = rnn_mod.rnn(cell, xv)
        loss = layers.mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
        l0, = _run(main, startup, {"x": x}, [loss])
    assert np.isfinite(l0)


def test_dynamic_gru_op_sequence_mask():
    """Steps past each row's length must carry state through unchanged."""
    b, t, d = 2, 6, 4
    rs = np.random.RandomState(2)
    x3 = rs.randn(b, t, 3 * d).astype("float32")
    lens = np.array([6, 3], dtype="int64")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        xv = fluid.data("x", [b, t, 3 * d])
        lv = fluid.data("lens", [b], dtype="int64")
        w = layers.create_parameter([d, 3 * d], "float32", name="gru_w")
        hid = blk.create_var(name="gru_hid")
        blk.append_op("gru", inputs={"Input": [xv.name],
                                     "Weight": [w.name],
                                     "Lengths": [lv.name]},
                      outputs={"Hidden": [hid.name]}, infer_shape=False)
        h, = _run(main, startup, {"x": x3, "lens": lens}, [hid])
    assert h.shape == (b, t, d)
    # row 1 frozen after step 3
    np.testing.assert_allclose(h[1, 3], h[1, 5], atol=1e-6)
    assert np.abs(h[0, 3] - h[0, 5]).max() > 1e-7


def test_dynamic_lstm_op():
    b, t, d = 2, 5, 3
    rs = np.random.RandomState(3)
    x4 = rs.randn(b, t, 4 * d).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        xv = fluid.data("x", [b, t, 4 * d])
        w = layers.create_parameter([d, 4 * d], "float32", name="lstm_w")
        bias = layers.create_parameter([1, 7 * d], "float32", name="lstm_b")
        hid = blk.create_var(name="lstm_hid")
        cell = blk.create_var(name="lstm_cell")
        blk.append_op("lstm", inputs={"Input": [xv.name], "Weight": [w.name],
                                      "Bias": [bias.name]},
                      outputs={"Hidden": [hid.name], "Cell": [cell.name]},
                      infer_shape=False)
        h, c = _run(main, startup, {"x": x4}, [hid, cell])
    assert h.shape == (b, t, d) and c.shape == (b, t, d)
    # |h| <= 1 (tanh-bounded), cell unbounded
    assert np.abs(h).max() <= 1.0 + 1e-6


def test_beam_search_decode_greedy_path():
    """Beam decode over a fixed transition table: beam search with size 1+
    must reproduce the greedy argmax chain of a deterministic LM."""
    vocab, d, beam, steps, b = 7, 8, 3, 5, 2
    rs = np.random.RandomState(4)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cell = rnn_mod.GRUCell(d)
        emb_w = layers.create_parameter([vocab, d], "float32", name="emb_w")

        def embed(ids):
            return layers.gather(emb_w, ids)

        def output_fn(h):
            return layers.fc(h, size=vocab, name="out_proj",
                             bias_attr=False)

        dec = rnn_mod.BeamSearchDecoder(
            cell, start_token=1, end_token=0, beam_size=beam,
            embedding_fn=embed, output_fn=output_fn)
        init = layers.fill_constant([b, d], "float32", 0.0)
        ids, scores = rnn_mod.dynamic_decode(dec, inits=init,
                                             max_step_num=steps)
        out_ids, out_scores = _run(main, startup, {}, [ids, scores])
    assert out_ids.shape == (b, steps, beam)
    assert out_scores.shape == (b, steps, beam)
    # top beam scores are non-increasing over beams at the last step
    last = out_scores[:, -1, :]
    assert (np.diff(last, axis=1) <= 1e-5).all()


def test_gather_tree_backtrack():
    # T=3, B=1, beam=2: hand-built parents
    ids = np.array([[[2, 3]], [[4, 5]], [[6, 7]]], dtype="int64")
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], dtype="int64")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        iv = fluid.data("ids", [3, 1, 2], dtype="int64")
        pv = fluid.data("par", [3, 1, 2], dtype="int64")
        out = blk.create_var(name="gt_out")
        blk.append_op("gather_tree", inputs={"Ids": [iv.name],
                                             "Parents": [pv.name]},
                      outputs={"Out": [out.name]}, infer_shape=False)
        res, = _run(main, startup, {"ids": ids, "par": parents}, [out])
    # beam 0 at t=2: id 6, parent chain: parents[2][0]=0 -> ids[1][0]=4,
    # parents[1][0]=1 -> ids[0][1]=3
    np.testing.assert_array_equal(res[:, 0, 0], [3, 4, 6])
    # beam 1 at t=2: id 7, parent 1 -> ids[1][1]=5, parents[1][1]=0 -> ids[0][0]=2
    np.testing.assert_array_equal(res[:, 0, 1], [2, 5, 7])


def test_dynamic_gru_lstm_layers():
    b, t, d = 2, 4, 3
    rs = np.random.RandomState(5)
    x3 = rs.randn(b, t, 3 * d).astype("float32")
    x4 = rs.randn(b, t, 4 * d).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        g_in = fluid.data("g", [b, t, 3 * d])
        l_in = fluid.data("l", [b, t, 4 * d])
        h_gru = layers.dynamic_gru(g_in, d)
        h_lstm, c_lstm = layers.dynamic_lstm(l_in, 4 * d)
        hp, cp = layers.dynamic_lstmp(l_in, 4 * d, proj_size=2)
        res = _run(main, startup, {"g": x3, "l": x4},
                   [h_gru, h_lstm, c_lstm, hp, cp])
    assert res[0].shape == (b, t, d)
    assert res[1].shape == (b, t, d)
    assert res[3].shape == (b, t, 2)


def test_static_rnn():
    t, b, d = 4, 2, 3
    rs = np.random.RandomState(6)
    x = rs.randn(t, b, d).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data("x", [t, b, d])
        srnn = layers.StaticRNN()
        with srnn.step():
            x_t = srnn.step_input(xv)
            h_prev = srnn.memory(shape=[-1, d], batch_ref=xv)
            h = layers.fc([x_t, h_prev], size=d, act="tanh",
                          name="srnn_fc")
            srnn.update_memory(h_prev, h)
            srnn.step_output(h)
        out = srnn()
        res, = _run(main, startup, {"x": x}, [out])
    assert res.shape == (t, b, d)
    assert np.abs(res).max() <= 1.0


def test_ifelse_and_switch():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [4, 1])
        zero = layers.fill_constant([4, 1], "float32", 0.0)
        cond = layers.greater_than(x, zero)
        ie = layers.IfElse(cond)
        with ie.true_block():
            ie.output(layers.scale(ie.input(x), 2.0))
        with ie.false_block():
            ie.output(layers.scale(ie.input(x), -1.0))
        merged, = ie()

        # Switch over a scalar step counter
        step = layers.fill_constant([1], "float32", 5.0)
        lr = layers.create_global_var([1], 0.0, "float32",
                                      persistable=True, name="sw_lr")
        bound = layers.fill_constant([1], "float32", 10.0)
        sw = layers.Switch()
        with sw.case(layers.less_than(step, bound)):
            layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
        with sw.default():
            layers.assign(layers.fill_constant([1], "float32", 0.01), lr)

        xin = np.array([[1.0], [-2.0], [3.0], [-4.0]], dtype="float32")
        m, lrv = _run(main, startup, {"x": xin}, [merged, lr])
    np.testing.assert_allclose(m.ravel(), [2.0, 2.0, 6.0, 4.0])
    np.testing.assert_allclose(lrv, [0.1])


def test_rnn_sequence_length_masking():
    b, t, din, d = 2, 6, 3, 4
    rs = np.random.RandomState(7)
    x = rs.randn(b, t, din).astype("float32")
    lens = np.array([6, 3], dtype="int64")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data("x", [b, t, din])
        lv = fluid.data("lens", [b], dtype="int64")
        cell = rnn_mod.GRUCell(d)
        out, final = rnn_mod.rnn(cell, xv, sequence_length=lv)
        o, f = _run(main, startup, {"x": x, "lens": lens}, [out, final])
    # final state of short row == state at its last valid step
    np.testing.assert_allclose(f[1], o[1, 2], atol=1e-6)
    np.testing.assert_allclose(f[0], o[0, 5], atol=1e-6)


def test_lstm_layer_wrapper():
    b, t, din, d = 2, 5, 4, 6
    rs = np.random.RandomState(8)
    x = rs.randn(b, t, din).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data("x", [b, t, din])
        out, lh, lc = layers.lstm(xv, None, None, t, d, num_layers=2,
                                  is_bidirec=True)
        o, h, c = _run(main, startup, {"x": x}, [out, lh, lc])
    assert o.shape == (b, t, 2 * d)
    assert h.shape == (4, b, d) and c.shape == (4, b, d)
    # forward-direction final state of last layer: matches out last step
    np.testing.assert_allclose(h[2], o[:, -1, :d], atol=1e-5)
    np.testing.assert_allclose(h[3], o[:, 0, d:], atol=1e-5)


def test_attention_dropout_off_in_clone_for_test():
    from paddle_tpu.models import transformer
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cfg = transformer.TransformerConfig(
            vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            dropout=0.5, use_flash=False)
        x = fluid.data("tokens", [2, 8], dtype="int64")
        hid = transformer.encoder(x, cfg)
    test_prog = main.clone(for_test=True)
    toks = np.random.RandomState(0).randint(0, 32, (2, 8)).astype("int64")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        a, = exe.run(test_prog, feed={"tokens": toks}, fetch_list=[hid])
        bvals, = exe.run(test_prog, feed={"tokens": toks},
                         fetch_list=[hid])
    # inference must be deterministic (dropout off)
    np.testing.assert_allclose(a, bvals, atol=0)

"""Disaggregated prefill/decode serving tests: the KV wire format
(byte-exact fp32/bf16 round-trips), cross-engine export -> adopt with
refcount/parity checks, graph-opt-level invariance of a decode worker
continuing on adopted blocks under eviction pressure, the fleet-level
content-addressed prefix store, router role restriction, and the
in-process two-phase prefill->decode dispatch end to end.

Same exactness discipline as tests/test_generation.py: the model is
trained on the cyclic-successor task, so any divergence between a
decode worker running on shipped KV and the unified engine shows up as
a wrong token, never a tolerance failure.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import gpt
from paddle_tpu.serving import (FleetPrefixStore, GenerationEngine,
                                PrefixCache, Replica, Router,
                                adopt_prefix, export_prefix)
from paddle_tpu.serving.kv_wire import (pack_blocks, payload_bytes,
                                        unpack_blocks)

VOCAB, SEQ, BLOCK = 16, 12, 4


@pytest.fixture(scope="module")
def trained():
    """Tiny GPT trained on the cyclic-successor task; returns
    (cfg, scope).  Greedy continuation of [a, b, c] is
    [(c+1) % VOCAB, (c+2) % VOCAB, ...]."""
    cfg = gpt.gpt_small(vocab_size=VOCAB, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=SEQ,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, _, _ = gpt.build_train(cfg, batch=8, seq_len=SEQ,
                                     lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        base = np.arange(SEQ) % VOCAB
        toks = np.stack([(base + i) % VOCAB for i in range(8)]) \
            .astype(np.int64)
        for _ in range(40):
            exe.run(main, feed={"tokens": toks}, fetch_list=[loss])
    return cfg, scope


def _clone_scope(scope):
    """Fresh scope holding only the parameter tensors (no gen.* decode
    state), so two engines can coexist without name collisions — the
    in-test stand-in for two replica processes loading one npz."""
    dst = fluid.Scope()
    for name in scope.names():
        if name.startswith("gen."):
            continue
        v = scope.get(name)
        if v is not None:
            dst.var(name)
            dst.set(name, np.array(np.asarray(v)))
    return dst


def _serial_tokens(cfg, scope, prompt, max_new):
    dec_main, dec_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(dec_main, dec_start):
        step = gpt.build_decode_step(cfg, batch=1, max_seq=SEQ)
    return gpt.kv_generate(fluid.Executor(), scope, dec_main,
                           step.token_var, step.logits_var,
                           step.cache_names, prompt=prompt,
                           max_new_tokens=max_new)


# ---------------------------------------------------------------------------
# kv_wire: serialize -> deserialize parity
# ---------------------------------------------------------------------------

class _FakeScope:
    def __init__(self, pools):
        self._pools = pools

    def get(self, name):
        return self._pools[name]


def _fake_pools(dtype, n_blocks=6, h=2, hd=3):
    rng = np.random.RandomState(0)
    names = ["k0", "v0", "k1", "v1"]
    pools = {n: rng.randn(n_blocks, BLOCK, h, hd).astype(dtype)
             for n in names}
    return _FakeScope(pools), names, pools


def test_kv_wire_roundtrip_fp32_byte_exact():
    scope, names, pools = _fake_pools(np.float32)
    ids, hashes = [2, 4], ["aa", "bb"]
    payload = pack_blocks(scope, names, ids, hashes, BLOCK)
    assert payload["kind"] == "kv_shipment"
    assert payload["n_blocks"] == 2 and payload["n_tokens"] == 2 * BLOCK
    assert payload["shape"] == [2, BLOCK, 2, 3]
    # raw-bytes accounting: 2 pools/layer x 2 layers x rows x fp32
    assert payload_bytes(payload) == 2 * 2 * (2 * BLOCK * 2 * 3) * 4

    ship = unpack_blocks(payload)
    assert ship.chain_hashes == hashes
    assert ship.dtype == np.float32 and len(ship.layers) == 2
    for li, (kn, vn) in enumerate((("k0", "v0"), ("k1", "v1"))):
        k, v = ship.layers[li]
        assert k.tobytes() == pools[kn][ids].tobytes()
        assert v.tobytes() == pools[vn][ids].tobytes()


def test_kv_wire_roundtrip_bf16_byte_exact():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    scope, names, pools = _fake_pools(ml_dtypes.bfloat16)
    payload = pack_blocks(scope, names, [1, 3, 5], ["a", "b", "c"],
                          BLOCK)
    assert payload["dtype"] == "bfloat16"
    ship = unpack_blocks(payload)
    assert ship.dtype == np.dtype(ml_dtypes.bfloat16)
    assert ship.layers[0][0].tobytes() == \
        pools["k0"][[1, 3, 5]].tobytes()


def test_kv_wire_rejects_malformed():
    scope, names, _ = _fake_pools(np.float32)
    with pytest.raises(ValueError):
        pack_blocks(scope, names[:3], [1], ["a"], BLOCK)  # odd pools
    with pytest.raises(ValueError):
        pack_blocks(scope, names, [1, 2], ["a"], BLOCK)  # id/hash skew
    good = pack_blocks(scope, names, [1], ["a"], BLOCK)
    with pytest.raises(ValueError):
        unpack_blocks({**good, "kind": "nope"})
    with pytest.raises(ValueError):
        unpack_blocks({**good, "version": 99})
    with pytest.raises(ValueError):
        unpack_blocks({**good, "chain_hashes": ["a", "b"]})
    bad = {**good,
           "layers": [{"k": good["layers"][0]["k"][:8],
                       "v": good["layers"][0]["v"]},
                      good["layers"][1]]}
    with pytest.raises(ValueError):
        unpack_blocks(bad)


def test_kv_wire_empty_shipment():
    scope, names, _ = _fake_pools(np.float32)
    payload = pack_blocks(scope, names, [], [], BLOCK)
    ship = unpack_blocks(payload)
    assert ship.n_blocks == 0 and ship.n_tokens == 0
    assert payload_bytes(payload) == 0


# ---------------------------------------------------------------------------
# export_prefix -> adopt_prefix across two engines (the tentpole)
# ---------------------------------------------------------------------------

def test_export_adopt_cross_engine_parity(trained):
    """A prefill engine exports a prompt's full-block KV; a separate
    decode engine (own scope = own process stand-in) adopts it, ends up
    with cache-held refcounts and byte-identical pool rows, and then
    decodes EXACTLY the serial-reference tokens with the prefix counted
    as cached and zero post-warmup compiles."""
    cfg, scope = trained
    prompt = [i % VOCAB for i in range(2 * BLOCK + 1)]  # 2 full blocks
    want = _serial_tokens(cfg, _clone_scope(scope), prompt, 3)

    eng_a = GenerationEngine(cfg, _clone_scope(scope),
                             exe=fluid.Executor(), max_slots=2,
                             max_seq=SEQ, block_size=BLOCK)
    eng_b = GenerationEngine(cfg, _clone_scope(scope),
                             exe=fluid.Executor(), max_slots=2,
                             max_seq=SEQ, block_size=BLOCK)
    eng_a.start()
    eng_b.start()
    try:
        payload = export_prefix(eng_a, prompt)
        assert payload["n_blocks"] == 2
        res = adopt_prefix(eng_b, payload)
        assert res["adopted"] == 2 and res["duplicate"] == 0
        assert res["resident"] == 2

        # adopted blocks are cache-held (refcount 1 -> evictable) and
        # byte-identical to the exporting engine's rows
        ship = unpack_blocks(payload)
        names = eng_b.step.cache_names
        for j, h in enumerate(ship.chain_hashes):
            bid = eng_b._prefix._entries[h]
            assert eng_b._pool.refcount(bid) == 1
            for li in range(len(ship.layers)):
                pool_k = np.asarray(eng_b.scope.get(names[2 * li]))
                pool_v = np.asarray(eng_b.scope.get(names[2 * li + 1]))
                assert pool_k[bid].tobytes() == \
                    ship.layers[li][0][j].tobytes()
                assert pool_v[bid].tobytes() == \
                    ship.layers[li][1][j].tobytes()

        # re-adoption is a pure dup (move-to-end, no new blocks)
        res2 = adopt_prefix(eng_b, payload)
        assert res2["adopted"] == 0 and res2["duplicate"] == 2

        out = eng_b.generate(prompt, 3)
        assert out["tokens"] == want
        assert out["cached_tokens"] == 2 * BLOCK
        assert eng_b.post_warmup_compiles() == 0

        # shipment validation against a live engine (shares eng_b
        # rather than paying another warmup ladder)
        scope_f, names = _fake_pools(np.float32)[:2]
        with pytest.raises(ValueError):
            adopt_prefix(eng_b, pack_blocks(scope_f, names, [1], ["a"],
                                            BLOCK + 1))  # block size
        with pytest.raises(ValueError):
            adopt_prefix(eng_b, pack_blocks(scope_f, names[:2], [1],
                                            ["a"], BLOCK))  # layers
        with pytest.raises(ValueError):
            # 2x3 heads != engine pools
            adopt_prefix(eng_b, pack_blocks(scope_f, names, [1], ["a"],
                                            BLOCK))
    finally:
        eng_a.stop()
        eng_b.stop()


@pytest.fixture(scope="module")
def shipped(trained):
    """The eviction tests' shared-prefix KV payload, exported ONCE from
    a short-lived prefill engine — a shipment is plain data, so one
    export serves every graph-opt-level variant."""
    cfg, scope = trained
    prefix = [i % VOCAB for i in range(2 * BLOCK)]
    eng_p = GenerationEngine(cfg, _clone_scope(scope),
                             exe=fluid.Executor(), max_slots=2,
                             max_seq=SEQ, block_size=BLOCK)
    eng_p.start()
    try:
        payload = export_prefix(eng_p, prefix + [8])
        assert eng_p.post_warmup_compiles() == 0
    finally:
        eng_p.stop()
    return prefix, payload


@pytest.mark.parametrize("opt_level", [0, 2])
def test_adopted_decode_parity_under_eviction(trained, shipped,
                                              opt_level):
    """Decode-worker-on-adopted-KV vs unified engine, token for token,
    at graph opt levels 0 and 2, with a pool tight enough that finished
    requests' blocks (and eventually the adopted prefix itself) face
    eviction pressure."""
    cfg, scope = trained
    prefix, payload = shipped
    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": opt_level})
    try:
        prompts = [prefix + [8], prefix + [9], [5, 6, 7]]
        ref_scope = _clone_scope(scope)
        want = [_serial_tokens(cfg, ref_scope, p, 3) for p in prompts]

        # 8 blocks total, block 0 reserved: 2 slots x 3 blocks of live
        # decode state + the 2 adopted blocks only fit via eviction
        eng_d = GenerationEngine(cfg, _clone_scope(scope),
                                 exe=fluid.Executor(), max_slots=2,
                                 max_seq=SEQ, block_size=BLOCK,
                                 kv_pool_blocks=8)
        eng_d.start()
        try:
            adopt_prefix(eng_d, payload)
            outs = [eng_d.generate(p, 3) for p in prompts]
            assert [o["tokens"] for o in outs] == want
            assert outs[0]["cached_tokens"] == 2 * BLOCK
            assert eng_d.post_warmup_compiles() == 0
        finally:
            eng_d.stop()
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})


# ---------------------------------------------------------------------------
# FleetPrefixStore
# ---------------------------------------------------------------------------

def test_fleet_prefix_store_depth_owner_lru():
    store = FleetPrefixStore(max_entries=3)
    assert store.block_size is None and len(store) == 0
    store.learn_block_size(8)
    assert store.block_size == 8

    store.register(["h1", "h2"], "d0")
    assert store.owned_depth(["h1", "h2"], "d0") == 2
    assert store.owned_depth(["h1", "h2", "h3"], "d0") == 2
    assert store.owned_depth(["h1", "h2"], "d1") == 0
    # chain_owner needs the WHOLE chain; exclusion respected
    assert store.chain_owner(["h1", "h2"]) == "d0"
    assert store.chain_owner(["h1", "h2", "h3"]) is None
    assert store.chain_owner(["h1"], exclude=("d0",)) is None
    store.register(["h1"], "d1")
    assert store.chain_owner(["h1"], exclude=("d0",)) == "d1"

    store.drop_owner("d0")
    assert store.owned_depth(["h1"], "d1") == 1  # d1's claim survives
    assert store.owned_depth(["h2"], "d0") == 0
    assert len(store) == 1

    # LRU bound: oldest untouched hash falls off
    store.register(["a", "b", "c"], "d0")  # h1 evicted (4 > max 3)
    assert store.owned_depth(["h1"], "d1") == 0
    assert len(store) == 3
    st = store.stats()
    assert st["entries"] == 3 and st["block_size"] == 8


# ---------------------------------------------------------------------------
# Router role restriction + in-process two-phase dispatch
# ---------------------------------------------------------------------------

class _FakeGen:
    """Minimal gen-engine stand-in for routing tests: health + queue
    gauges only."""

    def health(self):
        return {"state": "ok", "retry_after_s": 0.0}

    def load(self):
        return 0.0


def test_router_role_restriction():
    with pytest.raises(ValueError):
        Replica("x", gen_engine=_FakeGen(), role="wat")
    rp = Replica("p0", gen_engine=_FakeGen(), role="prefill")
    rd = Replica("d0", gen_engine=_FakeGen(), role="decode")
    router = Router([rp, rd], start_probe=False)
    try:
        # decode traffic never routes to a prefill-only replica…
        for _ in range(8):
            assert router._pick("generate", set(), None).name == "d0"
        # …prefill traffic never to a decode-only one…
        for _ in range(8):
            assert router._pick("prefill", set(), None).name == "p0"
        # …and predict needs a unified replica: none here
        assert router._pick("predict", set(), None) is None
        status, body, _ = router.healthz()
        assert status == 200
        roles = {n: d["role"] for n, d in body["replicas"].items()}
        assert roles == {"p0": "prefill", "d0": "decode"}
    finally:
        router.close()


def test_router_disagg_end_to_end_in_process(trained):
    """Two-phase dispatch against real engines in one process: a
    prefill-role engine and a decode-role engine behind
    Router(disagg=True). Outputs match the serial reference exactly,
    the fleet store learns the prefix, and the second request with the
    same prefix skips the transfer (prefix reuse)."""
    from paddle_tpu import monitor
    cfg, scope = trained
    prefix = [i % VOCAB for i in range(2 * BLOCK)]
    p_a, p_b = prefix + [8], prefix + [9]
    ref_scope = _clone_scope(scope)
    want_a = _serial_tokens(cfg, ref_scope, p_a, 3)
    want_b = _serial_tokens(cfg, ref_scope, p_b, 3)

    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    eng_p = GenerationEngine(cfg, _clone_scope(scope),
                             exe=fluid.Executor(), max_slots=2,
                             max_seq=SEQ, block_size=BLOCK)
    eng_d = GenerationEngine(cfg, _clone_scope(scope),
                             exe=fluid.Executor(), max_slots=2,
                             max_seq=SEQ, block_size=BLOCK)
    eng_p.start()
    eng_d.start()
    router = Router([Replica("p0", gen_engine=eng_p, role="prefill"),
                     Replica("d0", gen_engine=eng_d, role="decode")],
                    start_probe=False, disagg=True)
    try:
        out_a = router.generate({"prompt": p_a, "max_new_tokens": 3})
        out_b = router.generate({"prompt": p_b, "max_new_tokens": 3})
        assert out_a["tokens"] == want_a
        assert out_b["tokens"] == want_b
        # decode worker served both from the adopted prefix
        assert out_b["cached_tokens"] == 2 * BLOCK
        assert eng_d.post_warmup_compiles() == 0
        assert eng_p.post_warmup_compiles() == 0
        assert router.prefix_store.owned_depth(
            PrefixCache.chunk_hashes(prefix, BLOCK), "d0") == 2
        c = monitor.get_stats_snapshot()["counters"]
        assert c.get("serving.disagg_requests") == 2
        assert c.get("serving.kv_xfer_blocks", 0) >= 2
        # request B found the chain already owned by d0: no 2nd hop
        assert c.get("serving.disagg_prefix_reuse") == 1
        assert not c.get("serving.disagg_fallbacks")
    finally:
        router.close()
        eng_p.stop()
        eng_d.stop()
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})


def test_router_disagg_falls_back_without_prefill_replica(trained):
    """Prefill worker dead mid-fleet: dispatch must fall back to plain
    decode (local re-prefill) with the SAME answer, counting a
    fallback."""
    from paddle_tpu import monitor
    cfg, scope = trained
    prompt = [i % VOCAB for i in range(2 * BLOCK + 1)]
    want = _serial_tokens(cfg, _clone_scope(scope), prompt, 3)

    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    eng_d = GenerationEngine(cfg, _clone_scope(scope),
                             exe=fluid.Executor(), max_slots=2,
                             max_seq=SEQ, block_size=BLOCK)
    eng_d.start()
    router = Router([Replica("d0", gen_engine=eng_d, role="decode")],
                    start_probe=False, disagg=True)
    try:
        out = router.generate({"prompt": prompt, "max_new_tokens": 3})
        assert out["tokens"] == want
        c = monitor.get_stats_snapshot()["counters"]
        assert c.get("serving.disagg_fallbacks") == 1
    finally:
        router.close()
        eng_d.stop()
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})

"""Inference-stack tests (reference: inference/api/
analysis_predictor_tester.cc + tests/book save/load+predict pattern)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                  create_paddle_predictor)


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("infer_model"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[8], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=4, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["img"], [pred], exe,
                                      main_program=main)
        xb = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        want, = exe.run(main, feed={"img": xb}, fetch_list=[pred])
    return d, xb, np.asarray(want)


def test_predictor_run_matches_executor(saved_model):
    d, xb, want = saved_model
    config = AnalysisConfig(d)
    predictor = create_paddle_predictor(config)
    out, = predictor.run([PaddleTensor(xb, "img")])
    np.testing.assert_allclose(out.as_ndarray(), want, rtol=1e-5,
                               atol=1e-6)
    assert out.shape == [4, 4]


def test_zero_copy_api(saved_model):
    d, xb, want = saved_model
    predictor = create_paddle_predictor(AnalysisConfig(d))
    names = predictor.get_input_names()
    assert names == ["img"]
    predictor.get_input_tensor("img").copy_from_cpu(xb)
    predictor.zero_copy_run()
    out_name = predictor.get_output_names()[0]
    got = predictor.get_output_tensor(out_name).copy_to_cpu()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_clone_is_independent(saved_model):
    d, xb, want = saved_model
    p1 = create_paddle_predictor(AnalysisConfig(d))
    p2 = p1.clone()
    out, = p2.run([PaddleTensor(xb, "img")])
    np.testing.assert_allclose(out.as_ndarray(), want, rtol=1e-5,
                               atol=1e-6)


def test_stablehlo_export_roundtrip(saved_model, tmp_path):
    from jax import export as jexport

    d, xb, want = saved_model
    predictor = create_paddle_predictor(AnalysisConfig(d))
    path = str(tmp_path / "model.stablehlo")
    meta = predictor.export_stablehlo(path, {"img": xb})
    assert meta["bytes"] > 0 and os.path.getsize(path) == meta["bytes"]

    with open(path, "rb") as f:
        exported = jexport.deserialize(f.read())
    got = exported.call(xb)[0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-6)


def test_config_tensorrt_gated(saved_model):
    config = AnalysisConfig(saved_model[0])
    with pytest.raises(NotImplementedError):
        config.enable_tensorrt_engine(workspace_size=1 << 20)


def test_clone_shares_weights_and_compile_cache(saved_model):
    """clone() must NOT re-read the model from disk: it shares the
    loaded program, the weight scope, and the executor — so a shape the
    parent already served is a cache hit for the clone."""
    d, xb, want = saved_model
    p1 = create_paddle_predictor(AnalysisConfig(d))
    p1.run_dict({"img": xb})
    assert p1.clone()._scope is p1._scope
    assert p1.clone()._exe is p1._exe
    assert p1.clone()._program is p1._program

    p2 = p1.clone()
    before = p1._exe.cache_stats()
    out, = p2.run_dict({"img": xb})
    after = p1._exe.cache_stats()
    assert after["misses"] == before["misses"], \
        "clone re-compiled a shape its parent already served"
    assert after["hits"] == before["hits"] + 1
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    # per-clone ZeroCopy staging stays independent
    p2.get_input_tensor("img").copy_from_cpu(xb)
    assert "img" not in p1._inputs


def test_stablehlo_export_feed_order(saved_model, tmp_path):
    """Regression: export_stablehlo must order positional args by the
    model's declared feed order, not sorted(example_feed). The inputs
    here are named so sorted order REVERSES declaration order, and the
    computation is asymmetric (2*z + 3*a), so a swap changes values."""
    from jax import export as jexport

    d = str(tmp_path / "two_input_model")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        z = layers.data("z_first", shape=[8], dtype="float32")
        a = layers.data("a_second", shape=[8], dtype="float32")
        out = layers.elementwise_add(layers.scale(z, scale=2.0),
                                     layers.scale(a, scale=3.0))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["z_first", "a_second"],
                                      [out], exe, main_program=main)

    predictor = create_paddle_predictor(AnalysisConfig(d))
    assert predictor.get_input_names() == ["z_first", "a_second"]
    rng = np.random.RandomState(1)
    zb = rng.randn(2, 8).astype(np.float32)
    ab = rng.randn(2, 8).astype(np.float32)

    path = str(tmp_path / "two_input.stablehlo")
    predictor.export_stablehlo(path, {"a_second": ab, "z_first": zb})
    with open(path, "rb") as f:
        exported = jexport.deserialize(f.read())
    # positional call order == declared feed order, NOT sorted order
    got, = exported.call(zb, ab)
    np.testing.assert_allclose(np.asarray(got), 2.0 * zb + 3.0 * ab,
                               rtol=1e-5, atol=1e-5)

    # the feed must cover the declared inputs exactly
    with pytest.raises(ValueError, match="z_first"):
        predictor.export_stablehlo(path, {"a_second": ab})
    with pytest.raises(ValueError, match="bogus"):
        predictor.export_stablehlo(
            path, {"a_second": ab, "z_first": zb, "bogus": ab})

"""Inference-stack tests (reference: inference/api/
analysis_predictor_tester.cc + tests/book save/load+predict pattern)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                  create_paddle_predictor)


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("infer_model"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[8], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=4, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["img"], [pred], exe,
                                      main_program=main)
        xb = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        want, = exe.run(main, feed={"img": xb}, fetch_list=[pred])
    return d, xb, np.asarray(want)


def test_predictor_run_matches_executor(saved_model):
    d, xb, want = saved_model
    config = AnalysisConfig(d)
    predictor = create_paddle_predictor(config)
    out, = predictor.run([PaddleTensor(xb, "img")])
    np.testing.assert_allclose(out.as_ndarray(), want, rtol=1e-5,
                               atol=1e-6)
    assert out.shape == [4, 4]


def test_zero_copy_api(saved_model):
    d, xb, want = saved_model
    predictor = create_paddle_predictor(AnalysisConfig(d))
    names = predictor.get_input_names()
    assert names == ["img"]
    predictor.get_input_tensor("img").copy_from_cpu(xb)
    predictor.zero_copy_run()
    out_name = predictor.get_output_names()[0]
    got = predictor.get_output_tensor(out_name).copy_to_cpu()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_clone_is_independent(saved_model):
    d, xb, want = saved_model
    p1 = create_paddle_predictor(AnalysisConfig(d))
    p2 = p1.clone()
    out, = p2.run([PaddleTensor(xb, "img")])
    np.testing.assert_allclose(out.as_ndarray(), want, rtol=1e-5,
                               atol=1e-6)


def test_stablehlo_export_roundtrip(saved_model, tmp_path):
    from jax import export as jexport

    d, xb, want = saved_model
    predictor = create_paddle_predictor(AnalysisConfig(d))
    path = str(tmp_path / "model.stablehlo")
    meta = predictor.export_stablehlo(path, {"img": xb})
    assert meta["bytes"] > 0 and os.path.getsize(path) == meta["bytes"]

    with open(path, "rb") as f:
        exported = jexport.deserialize(f.read())
    got = exported.call(xb)[0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-6)


def test_config_tensorrt_gated(saved_model):
    config = AnalysisConfig(saved_model[0])
    with pytest.raises(NotImplementedError):
        config.enable_tensorrt_engine(workspace_size=1 << 20)

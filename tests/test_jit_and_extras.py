"""TracedLayer (dygraph→static), custom-op loading, profiler timeline."""
import json
import os

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.dygraph as dg
from paddle_tpu import layers


def test_traced_layer_matches_eager_and_reloads(tmp_path):
    rng = np.random.RandomState(0)
    xb = rng.randn(4, 8).astype(np.float32)

    with dg.guard():
        net = dg.Linear(8, 3)
        x = dg.to_variable(xb)
        eager_out, traced = dg.TracedLayer.trace(lambda v: net(v), [x])
        want = eager_out.numpy()

        got, = traced([dg.to_variable(xb)])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    # the captured program serves through save/load_inference_model
    d = str(tmp_path / "traced")
    traced.save_inference_model(d)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        out2, = exe.run(prog, feed={feeds[0]: xb}, fetch_list=fetches)
    np.testing.assert_allclose(np.asarray(out2), want, rtol=1e-5,
                               atol=1e-6)


def test_load_op_library_and_use(tmp_path):
    op_py = tmp_path / "my_ops.py"
    op_py.write_text(
        "import jax.numpy as jnp\n"
        "from paddle_tpu.core.registry import register_op\n"
        "\n"
        "@register_op('my_squareplus')\n"
        "def _sp(ctx, ins, attrs):\n"
        "    x = ins['X'][0]\n"
        "    b = attrs.get('b', 4.0)\n"
        "    return {'Out': [0.5 * (x + jnp.sqrt(x * x + b))]}\n")
    new_ops = fluid.load_op_library(str(op_py))
    assert new_ops == ["my_squareplus"]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        out = main.global_block().create_var(name="sp_out",
                                             shape=(-1, 4),
                                             dtype="float32")
        main.global_block().append_op(
            "my_squareplus", inputs={"X": [x.name]},
            outputs={"Out": [out.name]}, attrs={"b": 4.0},
            infer_shape=False)
        # the generic vjp grad applies to custom ops too
        loss = layers.mean(out)
        grads = fluid.gradients(loss, [x])

    xb = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        got, g = exe.run(main, feed={"x": xb},
                         fetch_list=[out, grads[0]])
    want = 0.5 * (xb + np.sqrt(xb * xb + 4.0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)
    want_g = (0.5 * (1 + xb / np.sqrt(xb * xb + 4.0))) / xb.size
    np.testing.assert_allclose(np.asarray(g), want_g, rtol=1e-4)


def test_load_op_library_rejects_so(tmp_path):
    import pytest
    with pytest.raises(ValueError, match="pallas"):
        fluid.load_op_library(str(tmp_path / "libfoo.so"))


def test_chrome_trace_export(tmp_path):
    from paddle_tpu import native, profiler

    if not native.AVAILABLE:
        import pytest
        pytest.skip("native runtime not built")
    profiler.enable_host_profiler()
    with profiler.record_event("unit_test_phase"):
        pass
    path = str(tmp_path / "trace.json")
    assert profiler.export_chrome_tracing(path)
    with open(path) as f:
        trace = json.load(f)
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "unit_test_phase" in names


def test_dygraph_optimizer_minimize():
    """Reference dygraph training loop: loss.backward();
    optimizer.minimize(loss, parameter_list=...); clear_gradients —
    eager update rules for SGD/Momentum/Adagrad/Adam/AdamW, with the
    dygraph LR-decay objects advancing per step."""
    import numpy as np

    import paddle_tpu as fluid

    rng = np.random.RandomState(0)
    xv = rng.randn(32, 8).astype(np.float32)
    yv = (xv[:, :1] * 1.5 - 0.5).astype(np.float32)

    for make_opt in [
        lambda: fluid.optimizer.SGD(learning_rate=0.05),
        lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
        lambda: fluid.optimizer.Adagrad(learning_rate=0.2),
        lambda: fluid.optimizer.Adam(learning_rate=0.05),
        lambda: fluid.optimizer.AdamW(learning_rate=0.05,
                                      weight_decay=0.01),
    ]:
        with dg.guard():
            lin = dg.Linear(8, 1)
            opt = make_opt()
            first = last = None
            for _ in range(25):
                pred = lin(dg.to_variable(xv))
                loss = ((pred - dg.to_variable(yv)) ** 2).mean()
                loss.backward()
                opt.minimize(loss, parameter_list=lin.parameters())
                lin.clear_gradients()
                v = float(loss.numpy())
                first = v if first is None else first
                last = v
            assert last < first * 0.5, (type(opt).__name__, first, last)


def test_dygraph_lr_decay_objects():
    import paddle_tpu.dygraph as dgm

    pw = dgm.PiecewiseDecay([10, 20], [0.1, 0.01, 0.001])
    lrs = [pw.step() for _ in range(25)]
    assert lrs[0] == 0.1 and lrs[15] == 0.01 and lrs[24] == 0.001

    noam = dgm.NoamDecay(d_model=512, warmup_steps=10)
    ns = [noam.step() for _ in range(30)]
    assert ns.index(max(ns)) in (8, 9, 10)  # peak at warmup

    cos = dgm.CosineDecay(0.1, step_each_epoch=5, epochs=10)
    cs = [cos.step() for _ in range(50)]
    assert cs[0] == 0.1 and cs[-1] < cs[0]

    with dg.guard():
        lin = dg.Linear(4, 1)
        opt = fluid.optimizer.SGD(
            learning_rate=dgm.PiecewiseDecay([2], [0.5, 0.0]))
        import numpy as np
        x = dg.to_variable(np.ones((2, 4), np.float32))
        w0 = lin.weight.numpy().copy()
        for i in range(4):
            loss = lin(x).mean()
            loss.backward()
            opt.minimize(loss, parameter_list=lin.parameters())
            lin.clear_gradients()
        # steps 2+ use lr 0.0: weights frozen after the schedule drops
        w2 = lin.weight.numpy().copy()
        loss = lin(x).mean()
        loss.backward()
        opt.minimize(loss, parameter_list=lin.parameters())
        np.testing.assert_array_equal(w2, lin.weight.numpy())
        assert not np.allclose(w0, w2)


def test_dygraph_minimize_pipeline_matches_static_semantics():
    """Regularization, clip, no_grad_set, dtype preservation, and
    per-name state all flow through the eager minimize pipeline."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.clip import set_gradient_clip
    from paddle_tpu.regularizer import L2Decay

    with dg.guard():
        lin = dg.Linear(4, 1)
        opt = fluid.optimizer.SGD(learning_rate=1.0,
                                  regularization=L2Decay(0.1))
        x = dg.to_variable(np.zeros((2, 4), np.float32))
        loss = lin(x).mean()
        loss.backward()
        w0 = lin.weight.numpy().copy()
        opt.minimize(loss, parameter_list=[lin.weight],
                     no_grad_set={lin.bias.name})
        # zero input -> dL/dw = 0, so the only update is the L2 term
        np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * w0,
                                   rtol=1e-6)
        assert lin.weight.numpy().dtype == np.float32

    with dg.guard():
        lin = dg.Linear(4, 1)
        set_gradient_clip(fluid.clip.GradientClipByGlobalNorm(1e-3))
        try:
            opt = fluid.optimizer.SGD(learning_rate=1.0)
            x = dg.to_variable(np.ones((2, 4), np.float32))
            loss = lin(x).mean()
            loss.backward()
            w0 = lin.weight.numpy().copy()
            opt.minimize(loss, parameter_list=lin.parameters())
            delta = np.linalg.norm(lin.weight.numpy() - w0)
            assert delta <= 1.1e-3  # clipped global norm bounds the step
        finally:
            set_gradient_clip(None)


def test_lr_decay_object_in_static_mode_raises_clearly():
    import paddle_tpu as fluid
    import paddle_tpu.dygraph as dgm

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("lrx", shape=[4], dtype="float32")
        loss = layers.mean(layers.fc(x, size=2))
        opt = fluid.optimizer.SGD(
            learning_rate=dgm.PiecewiseDecay([2], [0.1, 0.01]))
        try:
            opt.minimize(loss)
            assert False, "expected TypeError"
        except TypeError as e:
            assert "dygraph" in str(e)


def test_py_func_backward_func():
    """Differentiable py_func (reference backward_func contract): the
    host backward receives (inputs, outputs, out_grads) and its
    returned gradients flow into upstream parameters."""
    import paddle_tpu as fluid

    calls = {"bwd": 0}

    def fwd(x):
        return x * x

    def bwd(x, y, dy):
        calls["bwd"] += 1
        return 2.0 * x * dy

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("pfx", shape=[4], dtype="float32")
        h = layers.fc(x, size=4, bias_attr=False,
                      param_attr=fluid.ParamAttr(name="pf_w"))
        out_var = main.current_block().create_var(
            name="pf_out", dtype="float32", shape=[-1, 4])
        y = layers.py_func(fwd, h, out_var, backward_func=bwd)
        loss = layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.ones((2, 4), np.float32)
        w0 = np.asarray(sc.find_var("pf_w")).copy()
        exe.run(main, feed={"pfx": xv}, fetch_list=[loss])
        w1 = np.asarray(sc.find_var("pf_w"))
        assert calls["bwd"] >= 1
        assert not np.allclose(w0, w1)  # gradient reached the weight
        # analytic check: d(mean(h^2))/dW = x^T * (2h/8)
        h0 = xv @ w0
        expect = w0 - 0.5 * (xv.T @ (2.0 * h0 / h0.size))
        np.testing.assert_allclose(w1, expect, rtol=1e-4)


def test_py_func_skip_vars_in_backward_input():
    """skip_vars_in_backward_input removes the listed vars from the
    backward host call's argument list (reference contract)."""
    import paddle_tpu as fluid

    seen = {}

    def fwd(x):
        return x * 3.0

    def bwd(y, dy):  # input x skipped: receives (out, out_grad) only
        seen["n_args"] = 2
        return 3.0 * dy

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("psx", shape=[4], dtype="float32")
        h = layers.fc(x, size=4, bias_attr=False)
        out_var = main.current_block().create_var(
            name="ps_out", dtype="float32", shape=[-1, 4])
        y = layers.py_func(fwd, h, out_var, backward_func=bwd,
                           skip_vars_in_backward_input=[h])
        loss = layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed={"psx": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        assert seen.get("n_args") == 2

"""TracedLayer (dygraph→static), custom-op loading, profiler timeline."""
import json
import os

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.dygraph as dg
from paddle_tpu import layers


def test_traced_layer_matches_eager_and_reloads(tmp_path):
    rng = np.random.RandomState(0)
    xb = rng.randn(4, 8).astype(np.float32)

    with dg.guard():
        net = dg.Linear(8, 3)
        x = dg.to_variable(xb)
        eager_out, traced = dg.TracedLayer.trace(lambda v: net(v), [x])
        want = eager_out.numpy()

        got, = traced([dg.to_variable(xb)])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    # the captured program serves through save/load_inference_model
    d = str(tmp_path / "traced")
    traced.save_inference_model(d)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        out2, = exe.run(prog, feed={feeds[0]: xb}, fetch_list=fetches)
    np.testing.assert_allclose(np.asarray(out2), want, rtol=1e-5,
                               atol=1e-6)


def test_load_op_library_and_use(tmp_path):
    op_py = tmp_path / "my_ops.py"
    op_py.write_text(
        "import jax.numpy as jnp\n"
        "from paddle_tpu.core.registry import register_op\n"
        "\n"
        "@register_op('my_squareplus')\n"
        "def _sp(ctx, ins, attrs):\n"
        "    x = ins['X'][0]\n"
        "    b = attrs.get('b', 4.0)\n"
        "    return {'Out': [0.5 * (x + jnp.sqrt(x * x + b))]}\n")
    new_ops = fluid.load_op_library(str(op_py))
    assert new_ops == ["my_squareplus"]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        out = main.global_block().create_var(name="sp_out",
                                             shape=(-1, 4),
                                             dtype="float32")
        main.global_block().append_op(
            "my_squareplus", inputs={"X": [x.name]},
            outputs={"Out": [out.name]}, attrs={"b": 4.0},
            infer_shape=False)
        # the generic vjp grad applies to custom ops too
        loss = layers.mean(out)
        grads = fluid.gradients(loss, [x])

    xb = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        got, g = exe.run(main, feed={"x": xb},
                         fetch_list=[out, grads[0]])
    want = 0.5 * (xb + np.sqrt(xb * xb + 4.0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)
    want_g = (0.5 * (1 + xb / np.sqrt(xb * xb + 4.0))) / xb.size
    np.testing.assert_allclose(np.asarray(g), want_g, rtol=1e-4)


def test_load_op_library_rejects_so(tmp_path):
    import pytest
    with pytest.raises(ValueError, match="pallas"):
        fluid.load_op_library(str(tmp_path / "libfoo.so"))


def test_chrome_trace_export(tmp_path):
    from paddle_tpu import native, profiler

    if not native.AVAILABLE:
        import pytest
        pytest.skip("native runtime not built")
    profiler.enable_host_profiler()
    with profiler.record_event("unit_test_phase"):
        pass
    path = str(tmp_path / "trace.json")
    assert profiler.export_chrome_tracing(path)
    with open(path) as f:
        trace = json.load(f)
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "unit_test_phase" in names

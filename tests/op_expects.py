"""Independent numpy references + extra grad slots for the op sweep.

VERDICT r3 #3: ~180 swept ops were verified only as self-consistent
(IR path vs the same lowering) — no independent witness. Each entry
here computes the REFERENCE-defined output in pure numpy, written from
the reference op kernels (cited per family as
/root/reference/paddle/fluid/operators/<file>), independent of the jax
lowerings. op_specs.py merges EXPECTS into SPECS at import; a lowering
bug now fails against this witness, not against itself.

EXTRA_GRADS adds numeric-gradient slots to every differentiable op the
sweep previously left unchecked (op_test.py:47 discipline).

For ops where this framework's contract deliberately diverges from the
reference (padded sequence/detection outputs instead of LoD), the
reference MATH is reproduced on the padded layout the SURVEY sanctions.
"""
from __future__ import annotations

import numpy as np

EXPECTS = {}
EXTRA_GRADS = {}


def exp_(op, fn):
    assert op not in EXPECTS, op
    EXPECTS[op] = fn


def grads(op, *slots):
    EXTRA_GRADS.setdefault(op, []).extend(slots)


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _erf(x):
    from scipy.special import erf as _e  # scipy ships with the image
    return _e(x)


# ---------------------------------------------------------------------------
# activations (activation_op.cc — formulas from each OpMaker's AddComment,
# defaults from SetDefault calls at activation_op.cc:360-620)
# ---------------------------------------------------------------------------
_ACT = {
    "exp": lambda x, a: np.exp(x),
    "tanh": lambda x, a: np.tanh(x),
    "sigmoid": lambda x, a: _sig(x),
    "sin": lambda x, a: np.sin(x),
    "cos": lambda x, a: np.cos(x),
    "atan": lambda x, a: np.arctan(x),
    "erf": lambda x, a: _erf(x),
    "softplus": lambda x, a: np.log1p(np.exp(x)),
    "softsign": lambda x, a: x / (1 + np.abs(x)),
    "gelu": lambda x, a: 0.5 * x * (1 + _erf(x / np.sqrt(2.0))),
    "logsigmoid": lambda x, a: np.log(_sig(x)),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * np.tanh(
        a.get("scale_a", 0.67) * x),
    "square": lambda x, a: x * x,
    "swish": lambda x, a: x * _sig(a.get("beta", 1.0) * x),
    "hard_sigmoid": lambda x, a: np.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
    "hard_swish": lambda x, a: x * np.clip(
        x + a.get("offset", 3.0), 0, a.get("threshold", 6.0)
    ) / a.get("scale", 6.0),
    "elu": lambda x, a: np.where(
        x > 0, x, a.get("alpha", 1.0) * (np.exp(np.minimum(x, 0)) - 1)),
    "selu": lambda x, a: a.get("scale", 1.0507009873554805) * np.where(
        x > 0, x, a.get("alpha", 1.6732632423543772)
        * (np.exp(np.minimum(x, 0)) - 1)),
    "soft_relu": lambda x, a: np.log1p(np.exp(np.clip(
        x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))),
    "tanh_shrink": lambda x, a: x - np.tanh(x),
    "log": lambda x, a: np.log(x),
    "sqrt": lambda x, a: np.sqrt(x),
    "rsqrt": lambda x, a: 1.0 / np.sqrt(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "asin": lambda x, a: np.arcsin(x),
    "acos": lambda x, a: np.arccos(x),
    "abs": lambda x, a: np.abs(x),
    "relu": lambda x, a: np.maximum(x, 0),
    "relu6": lambda x, a: np.clip(x, 0, a.get("threshold", 6.0)),
    "leaky_relu": lambda x, a: np.maximum(x, a.get("alpha", 0.02) * x),
    "brelu": lambda x, a: np.clip(x, a.get("t_min", 0.0),
                                  a.get("t_max", 24.0)),
    "hard_shrink": lambda x, a: np.where(
        np.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "softshrink": lambda x, a: np.where(
        x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
        np.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)),
    "thresholded_relu": lambda x, a: np.where(
        x > a.get("threshold", 1.0), x, 0.0),
    "ceil": lambda x, a: np.ceil(x),
    "floor": lambda x, a: np.floor(x),
    "round": lambda x, a: np.round(x),
    "sign": lambda x, a: np.sign(x),
    "pow": lambda x, a: np.power(x, a.get("factor", 1.0)),
}
for _op, _fn in _ACT.items():
    exp_(_op, (lambda f: lambda i, a: {"Out": [f(i["X"], a)]})(_fn))
exp_("prelu", lambda i, a: {"Out": [np.where(i["X"] > 0, i["X"],
                                             i["Alpha"][0] * i["X"])]})
grads("prelu", "Alpha")
for _op in ["ceil", "floor", "round", "sign"]:
    grads(_op, "X")        # zero-gradient contract, witnessed numerically

# ---------------------------------------------------------------------------
# binary elementwise / comparisons / logical (elementwise_*_op.h)
# ---------------------------------------------------------------------------
_BIN = {
    "elementwise_add": np.add, "elementwise_sub": np.subtract,
    "elementwise_mul": np.multiply, "elementwise_div": np.divide,
    "elementwise_max": np.maximum, "elementwise_min": np.minimum,
    "elementwise_pow": np.power,
    "elementwise_mod": np.mod, "elementwise_floordiv": np.floor_divide,
}
for _op, _fn in _BIN.items():
    exp_(_op, (lambda f: lambda i, a: {"Out": [f(i["X"], i["Y"])]})(_fn))
grads("elementwise_pow", "Y")
_CMP = {"equal": np.equal, "not_equal": np.not_equal,
        "less_than": np.less, "less_equal": np.less_equal,
        "greater_than": np.greater, "greater_equal": np.greater_equal,
        "logical_and": np.logical_and, "logical_or": np.logical_or,
        "logical_xor": np.logical_xor}
for _op, _fn in _CMP.items():
    exp_(_op, (lambda f: lambda i, a: {"Out": [f(i["X"], i["Y"])]})(_fn))
exp_("logical_not", lambda i, a: {"Out": [np.logical_not(i["X"])]})

# ---------------------------------------------------------------------------
# reductions (reduce_op.h)
# ---------------------------------------------------------------------------
def _red(fn):
    def r(i, a):
        dim = tuple(a["dim"])
        return {"Out": [fn(i["X"], axis=dim,
                           keepdims=a.get("keep_dim", False))]}
    return r


exp_("reduce_sum", _red(np.sum))
exp_("reduce_mean", _red(np.mean))
exp_("reduce_max", _red(np.max))
exp_("reduce_min", _red(np.min))
exp_("reduce_prod", _red(np.prod))
exp_("reduce_all", _red(np.all))
exp_("reduce_any", _red(np.any))
exp_("l2_normalize", lambda i, a: {"Out": [i["X"] / np.sqrt(
    np.sum(i["X"] ** 2, axis=a.get("axis", 1), keepdims=True)
    + a.get("epsilon", 1e-10))]})
exp_("clip_by_norm", lambda i, a: {"Out": [
    i["X"] * np.minimum(1.0, a["max_norm"]
                        / max(np.sqrt((i["X"] ** 2).sum()), 1e-12))]})
exp_("norm", lambda i, a: {"Out": [i["X"] / np.sqrt(
    np.sum(i["X"] ** 2, axis=a.get("axis", 1), keepdims=True)
    + a.get("epsilon", 1e-10))]})

# ---------------------------------------------------------------------------
# matmul family (mul_op.h, fc_op.cc, bilinear_tensor_product_op.h,
# cos_sim_op.h, fsp_op.h)
# ---------------------------------------------------------------------------
exp_("matmul_v2", lambda i, a: {"Out": [i["X"] @ i["Y"]]})
exp_("fc", lambda i, a: {"Out": [i["Input"] @ i["W"] + i["Bias"]]})
grads("fc", "Bias")


def _btp(i, a):
    # out[b, k] = x[b] @ W[k] @ y[b] + bias[k]
    x, y, w = i["X"], i["Y"], i["Weight"]
    out = np.einsum("bi,kij,bj->bk", x, w, y) + i["Bias"]
    return {"Out": [out]}


exp_("bilinear_tensor_product", _btp)
grads("bilinear_tensor_product", "Weight")


def _cos_sim(i, a):
    x, y = i["X"], i["Y"]
    xn = np.sqrt((x * x).sum(1, keepdims=True))
    yn = np.sqrt((y * y).sum(1, keepdims=True))
    return {"Out": [(x * y).sum(1, keepdims=True) / (xn * yn)]}


exp_("cos_sim", _cos_sim)


def _fsp(i, a):
    x, y = i["X"], i["Y"]  # (b, c1, h, w), (b, c2, h, w)
    b, c1, h, w = x.shape
    out = np.einsum("bihw,bjhw->bij", x, y) / (h * w)
    return {"Out": [out]}


exp_("fsp", _fsp)


def _conv_shift(i, a):
    # conv_shift_op.h: out[b, j] = sum_k x[b, (j + k - m/2) % n] * y[b, k]
    x, y = i["X"], i["Y"]
    b, n = x.shape
    m = y.shape[1]
    out = np.zeros_like(x)
    for bi in range(b):
        for j in range(n):
            for k in range(m):
                out[bi, j] += x[bi, (j + k - m // 2) % n] * y[bi, k]
    return {"Out": [out]}


exp_("conv_shift", _conv_shift)

# ---------------------------------------------------------------------------
# shape / tensor manipulation
# ---------------------------------------------------------------------------
exp_("reshape", lambda i, a: {"Out": [i["X"].reshape(a["shape"])]})
exp_("reshape2", lambda i, a: {"Out": [i["X"].reshape(a["shape"])]})
exp_("flatten", lambda i, a: {"Out": [i["X"].reshape(
    int(np.prod(i["X"].shape[:a["axis"]])), -1)]})
exp_("flatten2", lambda i, a: {"Out": [i["X"].reshape(
    int(np.prod(i["X"].shape[:a["axis"]])), -1)]})
exp_("squeeze", lambda i, a: {"Out": [np.squeeze(i["X"],
                                                 tuple(a["axes"]))]})
exp_("squeeze2", lambda i, a: {"Out": [np.squeeze(i["X"],
                                                  tuple(a["axes"]))]})
exp_("unsqueeze", lambda i, a: {"Out": [np.expand_dims(i["X"],
                                                       a["axes"][0])]})
exp_("unsqueeze2", lambda i, a: {"Out": [np.expand_dims(i["X"],
                                                        a["axes"][0])]})
for _op in ["flatten", "flatten2", "squeeze", "squeeze2", "unsqueeze",
            "unsqueeze2", "unstack", "expand_as", "multiplex"]:
    grads(_op, "X")
exp_("stack", lambda i, a: {"Y": [np.stack([i["stk_a"], i["stk_b"]],
                                           axis=a.get("axis", 0))]})
exp_("transpose", lambda i, a: {"Out": [np.transpose(i["X"], a["axis"])]})
exp_("transpose2", lambda i, a: {"Out": [np.transpose(i["X"],
                                                      a["axis"])]})


def _slice(i, a):
    x = i["Input"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(a["axes"], a["starts"], a["ends"]):
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


exp_("slice", _slice)


def _strided_slice(i, a):
    x = i["Input"]
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(a["axes"], a["starts"], a["ends"],
                              a["strides"]):
        idx[ax] = slice(st, en, sd)
    return {"Out": [x[tuple(idx)]]}


exp_("strided_slice", _strided_slice)
exp_("expand", lambda i, a: {"Out": [np.tile(i["X"],
                                             a["expand_times"])]})
exp_("expand_as", lambda i, a: {"Out": [np.tile(
    i["X"], [t // s for t, s in zip(i["target_tensor"].shape,
                                    i["X"].shape)])]})


def _pad(i, a):
    x = i["X"]
    p = a["paddings"]
    pads = [(p[2 * d], p[2 * d + 1]) for d in range(x.ndim)]
    return {"Out": [np.pad(x, pads, constant_values=a.get("pad_value",
                                                          0.0))]}


exp_("pad", _pad)


def _pad2d(i, a):
    x = i["X"]  # NCHW
    p = a["paddings"]  # [top, bottom, left, right]
    mode = a.get("mode", "constant")
    pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": [np.pad(x, pads,
                               constant_values=a.get("pad_value", 0.0))]}
    np_mode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": [np.pad(x, pads, mode=np_mode)]}


exp_("pad2d", _pad2d)


def _pad_constant_like(i, a):
    x, y = i["X"], i["Y"]
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [np.pad(y, pads,
                           constant_values=a.get("pad_value", 0.0))]}


exp_("pad_constant_like", _pad_constant_like)
exp_("reverse", lambda i, a: {"Out": [np.flip(i["X"],
                                              tuple(a["axis"]))]})
exp_("gather", lambda i, a: {"Out": [i["X"][i["Index"]]]})


def _gather_nd(i, a):
    x, idx = i["X"], i["Index"]
    return {"Out": [x[tuple(idx[..., k] for k in range(idx.shape[-1]))]]}


exp_("gather_nd", _gather_nd)


def _scatter(i, a):
    out = i["X"].copy()
    if a.get("overwrite", True):
        out[i["Ids"]] = i["Updates"]
    else:
        out[i["Ids"]] = 0
        np.add.at(out, i["Ids"], i["Updates"])
    return {"Out": [out]}


exp_("scatter", _scatter)
grads("scatter", "Updates")


def _scatter_nd_add(i, a):
    out = i["X"].copy()
    idx = i["Index"]
    np.add.at(out, tuple(idx[..., k] for k in range(idx.shape[-1])),
              i["Updates"])
    return {"Out": [out]}


exp_("scatter_nd_add", _scatter_nd_add)
exp_("cast", lambda i, a: {"Out": [i["X"].astype(a["out_dtype"])]})
exp_("assign", lambda i, a: {"Out": [i["X"]]})
exp_("shape", lambda i, a: {"Out": [np.array(i["Input"].shape,
                                             np.int32)]})
exp_("size", lambda i, a: {"Out": [np.array(i["Input"].size)]})
exp_("diag", lambda i, a: {"Out": [np.diag(i["Diagonal"])]})
exp_("eye", lambda i, a: {"Out": [np.eye(a["num_rows"],
                                         a["num_columns"],
                                         dtype=np.float32)]})
exp_("linspace", lambda i, a: {"Out": [np.linspace(
    i["Start"][0], i["Stop"][0], a["num"], dtype=np.float32)]})
exp_("range", lambda i, a: {"Out": [np.arange(
    i["Start"][0], i["End"][0], i["Step"][0], dtype=np.float32)]})
exp_("fill_any_like", lambda i, a: {"Out": [np.full_like(i["X"],
                                                         a["value"])]})
exp_("fill", lambda i, a: {"Out": [np.array(a["value"], np.float32)
                                   .reshape(a["shape"])]})
exp_("fill_constant_batch_size_like", lambda i, a: {"Out": [np.full(
    [i["Input"].shape[0] if s == -1 else s for s in a["shape"]],
    a["value"], np.float32)]})


def _one_hot(i, a):
    ids = i["X"].reshape(-1).astype(np.int64)
    out = np.zeros((ids.size, a["depth"]), np.float32)
    out[np.arange(ids.size), ids] = 1.0
    return {"Out": [out]}


exp_("one_hot", _one_hot)
exp_("one_hot_v2", _one_hot)


def _shard_index(i, a):
    # shard_index_op.h: shard_size = index_num / nshards;
    # out = id/shard_size == shard_id ? id % shard_size : ignore_value
    ids = i["X"]
    shard_size = a["index_num"] // a["nshards"]
    return {"Out": [np.where(ids // shard_size == a["shard_id"],
                             ids % shard_size, a["ignore_value"])]}


exp_("shard_index", _shard_index)


def _top_k(i, a):
    x, k = i["X"], a["k"]
    idx = np.argsort(-x, axis=-1, kind="stable")[..., :k]
    return {"Out": [np.take_along_axis(x, idx, -1)],
            "Indices": [idx.astype(np.int64)]}


exp_("top_k", _top_k)
exp_("arg_max", lambda i, a: {"Out": [np.argmax(i["X"],
                                                a.get("axis", -1))]})
exp_("arg_min", lambda i, a: {"Out": [np.argmin(i["X"],
                                                a.get("axis", -1))]})
exp_("argsort", lambda i, a: {"Out": [np.sort(i["X"],
                                              axis=a.get("axis", -1))],
                              "Indices": [np.argsort(
                                  i["X"], axis=a.get("axis", -1),
                                  kind="stable").astype(np.int64)]})
exp_("isfinite", lambda i, a: {"Out": [np.array(
    np.isfinite(i["X"]).all())]})
exp_("has_inf", lambda i, a: {"Out": [np.array(np.isinf(i["X"]).any())]})
exp_("has_nan", lambda i, a: {"Out": [np.array(np.isnan(i["X"]).any())]})
exp_("is_empty", lambda i, a: {"Out": [np.array(i["X"].size == 0)]})


def _multiplex(i, a):
    rows = [i["mpx_a"], i["mpx_b"]]
    ids = i["Ids"].reshape(-1)
    out = np.stack([rows[ids[r]][r] for r in range(len(ids))])
    return {"Out": [out]}


exp_("multiplex", _multiplex)
exp_("assign_value", lambda i, a: {"Out": [np.array(
    a["values"], np.float32).reshape(a["shape"])]})


def _sequence_mask(i, a):
    lens = i["X"]
    m = a["maxlen"]
    return {"Y": [(np.arange(m)[None, :] < lens[:, None])]}


exp_("sequence_mask", _sequence_mask)


def _space_to_depth(i, a):
    x, bs = i["X"], a["blocksize"]
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // bs, bs, w // bs, bs)
    y = y.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * bs * bs, h // bs,
                                              w // bs)
    return {"Out": [y]}


exp_("space_to_depth", _space_to_depth)


def _pixel_shuffle(i, a):
    x, r = i["X"], a["upscale_factor"]
    n, c, h, w = x.shape
    y = x.reshape(n, c // (r * r), r, r, h, w)
    y = y.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r,
                                              w * r)
    return {"Out": [y]}


exp_("pixel_shuffle", _pixel_shuffle)


def _shuffle_channel(i, a):
    x, g = i["X"], a["group"]
    n, c, h, w = x.shape
    y = x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
    return {"Out": [y.reshape(n, c, h, w)]}


exp_("shuffle_channel", _shuffle_channel)

# ---------------------------------------------------------------------------
# embedding (lookup_table_op.h)
# ---------------------------------------------------------------------------
exp_("lookup_table", lambda i, a: {"Out": [
    i["W"][i["Ids"].reshape(-1)].reshape(
        i["Ids"].shape[:-1] + (i["W"].shape[1],))]})
exp_("lookup_table_v2", lambda i, a: {"Out": [i["W"][i["Ids"]]]})

# ---------------------------------------------------------------------------
# losses (cross_entropy_op.h, bpr_loss_op.h:62-77, hinge_loss_op.h:36-39,
# rank_loss_op.h:39-40, huber_loss_op.h:29-41, smooth_l1_loss_op.h:32-45,
# modified_huber_loss_op.h:40-51, log_loss_op.h:43-45, kldiv_loss_op.h:29-38,
# teacher_student_sigmoid_loss_op.h:34-55)
# ---------------------------------------------------------------------------
def _xent(i, a):
    x, lbl = i["X"], i["Label"].reshape(-1)
    return {"Y": [-np.log(x[np.arange(x.shape[0]), lbl])
                  .reshape(-1, 1)]}


exp_("cross_entropy", _xent)
exp_("cross_entropy2",
     lambda i, a: {"Y": [-np.log(i["X"][np.arange(i["X"].shape[0]),
                                        i["Label"].reshape(-1)])
                         .reshape(-1, 1)]})


def _bpr(i, a):
    x, lbl = i["X"], i["Label"].reshape(-1)
    n, c = x.shape
    out = np.zeros((n, 1), np.float64)
    for r in range(n):
        p = x[r, lbl[r]]
        s = sum(-np.log(1.0 + np.exp(x[r, j] - p))
                for j in range(c) if j != lbl[r])
        out[r, 0] = -s / (c - 1)
    return {"Y": [out.astype(np.float32)]}


exp_("bpr_loss", _bpr)


def _softmax_xent(i, a):
    sm = _softmax(i["Logits"], -1)
    lbl = i["Label"].reshape(-1)
    loss = -np.log(sm[np.arange(sm.shape[0]), lbl]).reshape(-1, 1)
    return {"Softmax": [sm], "Loss": [loss]}


exp_("softmax_with_cross_entropy", _softmax_xent)
exp_("sigmoid_cross_entropy_with_logits", lambda i, a: {"Out": [
    np.maximum(i["X"], 0) - i["X"] * i["Label"]
    + np.log1p(np.exp(-np.abs(i["X"])))]})
exp_("hinge_loss", lambda i, a: {"Loss": [np.maximum(
    0.0, 1.0 - i["Logits"] * (2.0 * i["Labels"] - 1.0))]})


def _huber(i, a):
    d = a["delta"]
    r = i["Y"] - i["X"]
    ab = np.abs(r)
    return {"Out": [np.where(ab <= d, 0.5 * r * r,
                             d * (ab - 0.5 * d))]}


exp_("huber_loss", _huber)
grads("huber_loss", "Y")


def _kldiv(i, a):
    t, x = i["Target"], i["X"]
    ele = np.where(t > 0, t * (np.log(np.maximum(t, 1e-30)) - x), 0.0)
    red = a.get("reduction", "mean")
    if red == "none":
        return {"Loss": [ele]}
    if red == "batchmean":
        return {"Loss": [np.array(ele.sum() / x.shape[0], np.float32)]}
    if red == "sum":
        return {"Loss": [np.array(ele.sum(), np.float32)]}
    return {"Loss": [np.array(ele.mean(), np.float32)]}


exp_("kldiv_loss", _kldiv)
exp_("log_loss", lambda i, a: {"Loss": [
    -i["Labels"] * np.log(i["Predicted"] + a["epsilon"])
    - (1 - i["Labels"]) * np.log(1 - i["Predicted"] + a["epsilon"])]})
exp_("mse_loss", lambda i, a: {"Out": [np.array(
    ((i["X"] - i["Y"]) ** 2).mean(), np.float32)]})
grads("mse_loss", "Y")
exp_("rank_loss", lambda i, a: {"Out": [
    np.log1p(np.exp(i["Left"] - i["Right"]))
    - i["Label"] * (i["Left"] - i["Right"])]})
exp_("margin_rank_loss", lambda i, a: {"Out": [np.maximum(
    0.0, -i["Label"] * (i["X1"] - i["X2"]) + a.get("margin", 0.0))]})


def _smooth_l1(i, a):
    sigma2 = a.get("sigma", 1.0) ** 2
    d = i["X"] - i["Y"]
    ab = np.abs(d)
    ele = np.where(ab < 1.0 / sigma2, 0.5 * d * d * sigma2,
                   ab - 0.5 / sigma2)
    return {"Out": [ele.sum(axis=tuple(range(1, d.ndim)))
                    .reshape(-1, 1)]}


exp_("smooth_l1_loss", _smooth_l1)
grads("smooth_l1_loss", "Y")


def _mod_huber(i, a):
    # modified_huber_loss_op.h:40-51 on val = y_hat * x, y_hat = 2y - 1
    val = (2.0 * i["Y"] - 1.0) * i["X"]
    out = np.where(val < -1, -4.0 * val,
                   np.where(val < 1, (1 - val) ** 2, 0.0))
    return {"Out": [out]}


exp_("modified_huber_loss", _mod_huber)
exp_("squared_l2_distance", lambda i, a: {"Out": [
    ((i["X"] - i["Y"]) ** 2).sum(1, keepdims=True)]})
grads("squared_l2_distance", "Y")


def _ts_sigmoid(i, a):
    # teacher_student_sigmoid_loss_op.h:43-62; both label>=0 branches
    # reduce to 2·softplus(x) − x·label
    x, lbl = i["X"], i["Label"]
    base = np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))
    out = np.where(lbl < -1.0, base,
                   np.where(lbl < 0.0, base - x,
                            2.0 * base - x * lbl))
    return {"Y": [out]}


exp_("teacher_student_sigmoid_loss", _ts_sigmoid)
exp_("label_smooth", lambda i, a: {"Out": [
    (1 - a["epsilon"]) * i["X"] + a["epsilon"] / i["X"].shape[-1]]})
exp_("log_softmax", lambda i, a: {"Out": [np.log(_softmax(i["X"]))]})
exp_("softmax", lambda i, a: {"Out": [_softmax(i["X"])]})
grads("dice_loss", "X")
grads("dropout", "X")

# ---------------------------------------------------------------------------
# optimizer update rules (sgd_op.h, momentum_op.h, adam_op.h, ...)
# ---------------------------------------------------------------------------
def _momentum(i, a):
    v = a["mu"] * i["Velocity"] + i["Grad"]
    return {"ParamOut": [i["Param"] - i["LearningRate"][0] * v],
            "VelocityOut": [v]}


exp_("momentum", _momentum)


def _adam(i, a):
    lr = i["LearningRate"][0]
    b1, b2, eps = a["beta1"], a["beta2"], a["epsilon"]
    m = b1 * i["Moment1"] + (1 - b1) * i["Grad"]
    v = b2 * i["Moment2"] + (1 - b2) * i["Grad"] ** 2
    lr_t = lr * np.sqrt(1 - i["Beta2Pow"][0]) / (1 - i["Beta1Pow"][0])
    p = i["Param"] - lr_t * m / (np.sqrt(v) + eps)
    return {"ParamOut": [p], "Moment1Out": [m], "Moment2Out": [v]}


exp_("adam", _adam)


def _adamw(i, a):
    base = _adam(i, a)
    lr = i["LearningRate"][0]
    p = base["ParamOut"][0] - lr * a.get("coeff", 0.01) * i["Param"]
    return {"ParamOut": [p], "Moment1Out": base["Moment1Out"],
            "Moment2Out": base["Moment2Out"]}


exp_("adamw", _adamw)


def _adagrad(i, a):
    mom = i["Moment"] + i["Grad"] ** 2
    p = i["Param"] - i["LearningRate"][0] * i["Grad"] / (
        np.sqrt(mom) + a["epsilon"])
    return {"ParamOut": [p], "MomentOut": [mom]}


exp_("adagrad", _adagrad)


def _adamax(i, a):
    lr = i["LearningRate"][0]
    b1, b2, eps = a["beta1"], a["beta2"], a["epsilon"]
    m = b1 * i["Moment"] + (1 - b1) * i["Grad"]
    inf = np.maximum(b2 * i["InfNorm"], np.abs(i["Grad"]))
    lr_t = lr / (1 - i["Beta1Pow"][0])
    p = i["Param"] - lr_t * m / (inf + eps)
    return {"ParamOut": [p], "MomentOut": [m], "InfNormOut": [inf]}


exp_("adamax", _adamax)


def _adadelta(i, a):
    rho, eps = a["rho"], a["epsilon"]
    g2 = rho * i["AvgSquaredGrad"] + (1 - rho) * i["Grad"] ** 2
    upd = -np.sqrt((i["AvgSquaredUpdate"] + eps) / (g2 + eps)) * i["Grad"]
    u2 = rho * i["AvgSquaredUpdate"] + (1 - rho) * upd ** 2
    return {"ParamOut": [i["Param"] + upd], "AvgSquaredGradOut": [g2],
            "AvgSquaredUpdateOut": [u2]}


exp_("adadelta", _adadelta)


def _decayed_adagrad(i, a):
    mom = a["decay"] * i["Moment"] + (1 - a["decay"]) * i["Grad"] ** 2
    p = i["Param"] - i["LearningRate"][0] * i["Grad"] / (
        np.sqrt(mom) + a["epsilon"])
    return {"ParamOut": [p], "MomentOut": [mom]}


exp_("decayed_adagrad", _decayed_adagrad)


def _rmsprop(i, a):
    rho, eps, mu = a["decay"], a["epsilon"], a["momentum"]
    lr = i["LearningRate"][0]
    ms = rho * i["MeanSquare"] + (1 - rho) * i["Grad"] ** 2
    mom = mu * i["Moment"] + lr * i["Grad"] / np.sqrt(ms + eps)
    return {"ParamOut": [i["Param"] - mom], "MomentOut": [mom],
            "MeanSquareOut": [ms]}


exp_("rmsprop", _rmsprop)


def _proximal_gd(i, a):
    lr = i["LearningRate"][0]
    l1, l2 = a["l1"], a["l2"]
    prox = i["Param"] - lr * i["Grad"]
    p = (np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0)
         / (1.0 + lr * l2))
    return {"ParamOut": [p]}


exp_("proximal_gd", _proximal_gd)


def _proximal_adagrad(i, a):
    mom = i["Moment"] + i["Grad"] ** 2
    lr = i["LearningRate"][0] / (np.sqrt(mom) + a["epsilon"])
    prox = i["Param"] - lr * i["Grad"]
    p = (np.sign(prox) * np.maximum(np.abs(prox) - lr * a["l1"], 0)
         / (1.0 + lr * a["l2"]))
    return {"ParamOut": [p], "MomentOut": [mom]}


exp_("proximal_adagrad", _proximal_adagrad)


def _lars(i, a):
    lr = i["LearningRate"][0]
    pn = np.sqrt((i["Param"] ** 2).sum())
    gn = np.sqrt((i["Grad"] ** 2).sum())
    local_lr = (lr * a["lars_coeff"] * pn
                / (gn + a["lars_weight_decay"] * pn))
    v = a["mu"] * i["Velocity"] + local_lr * (
        i["Grad"] + a["lars_weight_decay"] * i["Param"])
    return {"ParamOut": [i["Param"] - v], "VelocityOut": [v]}


exp_("lars_momentum", _lars)


def _ftrl(i, a):
    # ftrl_op.h:58-100, lr_power = -0.5 path
    g, p = i["Grad"], i["Param"]
    sq, lin = i["SquaredAccumulator"], i["LinearAccumulator"]
    lr = i["LearningRate"][0]
    l1, l2 = a["l1"], a["l2"]
    new_acc = sq + g * g
    lin_out = lin + g - ((np.sqrt(new_acc) - np.sqrt(sq)) / lr) * p
    x = l1 * np.sign(lin_out) - lin_out
    y = np.sqrt(new_acc) / lr + 2 * l2
    p_out = np.where(np.abs(lin_out) > l1, x / y, 0.0)
    return {"ParamOut": [p_out], "SquaredAccumOut": [new_acc],
            "LinearAccumOut": [lin_out]}


exp_("ftrl", _ftrl)


def _lamb(i, a):
    # lamb_op.h:65-73 moment update + :280-300 trust-ratio param update
    g, p = i["Grad"], i["Param"]
    b1, b2 = a["beta1"], a["beta2"]
    eps, wd = a["epsilon"], a["weight_decay"]
    lr = i["LearningRate"][0]
    m1 = b1 * i["Moment1"] + (1 - b1) * g
    m2 = b2 * i["Moment2"] + (1 - b2) * g * g
    trd = m1 / (np.sqrt(m2) + eps) + wd * p
    pn = np.sqrt((p ** 2).sum())
    tn = np.sqrt((trd ** 2).sum())
    p_out = p - lr * (pn / tn) * trd
    return {"ParamOut": [p_out], "Moment1Out": [m1], "Moment2Out": [m2]}


exp_("lamb", _lamb)


def _dgc_momentum(i, a):
    # dgc_momentum_op.h: plain momentum while current_step <
    # rampup_begin_step (the spec drives step 0 < 100)
    assert float(i["current_step"][0]) < a["rampup_begin_step"]
    return _momentum(i, a)


exp_("dgc_momentum", _dgc_momentum)

# ---------------------------------------------------------------------------
# norms (batch_norm_op.cc, layer_norm_op.h, group_norm_op.h,
# instance_norm via batch-norm-per-instance, affine_channel_op.cc)
# ---------------------------------------------------------------------------
def _bn_infer(i, a):
    x = i["X"]
    eps = a["epsilon"]
    mean = i["Mean"].reshape(1, -1, 1, 1)
    var = i["Variance"].reshape(1, -1, 1, 1)
    s = i["Scale"].reshape(1, -1, 1, 1)
    b = i["Bias"].reshape(1, -1, 1, 1)
    return {"Y": [(x - mean) / np.sqrt(var + eps) * s + b]}


exp_("batch_norm", _bn_infer)
grads("batch_norm", "X", "Scale", "Bias")


def _layer_norm(i, a):
    x = i["X"]
    ax = tuple(range(a["begin_norm_axis"], x.ndim))
    mu = x.mean(ax, keepdims=True)
    var = x.var(ax, keepdims=True)
    y = (x - mu) / np.sqrt(var + a["epsilon"])
    return {"Y": [y * i["Scale"] + i["Bias"]]}


exp_("layer_norm", _layer_norm)


def _instance_norm(i, a):
    x = i["X"]
    mu = x.mean((2, 3), keepdims=True)
    var = x.var((2, 3), keepdims=True)
    y = (x - mu) / np.sqrt(var + a["epsilon"])
    return {"Y": [y * i["Scale"].reshape(1, -1, 1, 1)
                  + i["Bias"].reshape(1, -1, 1, 1)]}


exp_("instance_norm", _instance_norm)
grads("instance_norm", "Scale", "Bias")


def _group_norm(i, a):
    x, g = i["X"], a["groups"]
    n, c, h, w = x.shape
    xg = x.reshape(n, g, c // g, h, w)
    mu = xg.mean((2, 3, 4), keepdims=True)
    var = xg.var((2, 3, 4), keepdims=True)
    y = ((xg - mu) / np.sqrt(var + a["epsilon"])).reshape(n, c, h, w)
    return {"Y": [y * i["Scale"].reshape(1, -1, 1, 1)
                  + i["Bias"].reshape(1, -1, 1, 1)]}


exp_("group_norm", _group_norm)
grads("group_norm", "Scale", "Bias")


def _lrn(i, a):
    # lrn_op.cc: out = x / (k + alpha * sum_local(x^2))^beta
    x = i["X"]
    n_, c, h, w = x.shape
    nsz, k, al, be = a["n"], a["k"], a["alpha"], a["beta"]
    sq = np.zeros_like(x)
    for ci in range(c):
        lo = max(0, ci - (nsz - 1) // 2)
        hi = min(c, ci + (nsz - 1) // 2 + 1)
        sq[:, ci] = (x[:, lo:hi] ** 2).sum(1)
    return {"Out": [x / (k + al * sq) ** be]}


exp_("lrn", _lrn)
exp_("affine_channel", lambda i, a: {"Out": [
    i["X"] * i["Scale"].reshape(1, -1, 1, 1)
    + i["Bias"].reshape(1, -1, 1, 1)]})
grads("affine_channel", "Scale", "Bias")


def _add_pos_enc(i, a):
    x = i["X"]
    b, t, d = x.shape
    half = d // 2
    pos = np.arange(t, dtype=np.float64)[:, None]
    div = np.power(10000.0, np.arange(half, dtype=np.float64) / half)
    enc = np.zeros((t, d))
    enc[:, :half] = np.sin(pos / div)
    enc[:, half:] = np.cos(pos / div)
    return {"Out": [(a["alpha"] * x + a["beta"]
                     * enc[None]).astype(np.float32)]}


exp_("add_position_encoding", _add_pos_enc)


def _temporal_shift(i, a):
    x = i["X"]
    seg, ratio = a["seg_num"], a["shift_ratio"]
    nt, c, h, w = x.shape
    n = nt // seg
    xr = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    out = np.zeros_like(xr)
    out[:, :-1, :c1] = xr[:, 1:, :c1]            # shift left
    out[:, 1:, c1:c2] = xr[:, :-1, c1:c2]        # shift right
    out[:, :, c2:] = xr[:, :, c2:]
    return {"Out": [out.reshape(nt, c, h, w)]}


exp_("temporal_shift", _temporal_shift)
grads("data_norm", "X")

# ---------------------------------------------------------------------------
# quantization (fake_quantize_op.cc:31-80, fake_dequantize_op.cc)
# ---------------------------------------------------------------------------
def _fq_absmax(i, a):
    x = i["X"]
    bins = (1 << (a["bit_length"] - 1)) - 1
    s = np.abs(x).max()
    return {"Out": [np.round(np.clip(x, -s, s) * bins / s)],
            "OutScale": [np.array([s], np.float32)]}


exp_("fake_quantize_abs_max", _fq_absmax)


def _fq_ch_absmax(i, a):
    x = i["X"]
    bins = (1 << (a["bit_length"] - 1)) - 1
    s = np.abs(x).max(axis=tuple(range(1, x.ndim)))
    out = np.round(x * (bins / s.reshape(-1, *([1] * (x.ndim - 1)))))
    return {"Out": [out], "OutScale": [s.astype(np.float32)]}


exp_("fake_channel_wise_quantize_abs_max", _fq_ch_absmax)
exp_("fake_dequantize_max_abs", lambda i, a: {"Out": [
    i["X"] * i["Scale"][0] / a["max_range"]]})


def _fq_dq_moving(i, a):
    # is_test: scale = InScale; quantize then dequantize
    x, s = i["X"], i["InScale"][0]
    bins = (1 << (a["bit_length"] - 1)) - 1
    return {"Out": [np.round(np.clip(x, -s, s) * bins / s) * s / bins]}


exp_("fake_quantize_dequantize_moving_average_abs_max", _fq_dq_moving)


def _fq_moving(i, a):
    x, s = i["X"], i["InScale"][0]
    bins = (1 << (a["bit_length"] - 1)) - 1
    return {"Out": [np.round(np.clip(x, -s, s) * bins / s)]}


exp_("fake_quantize_moving_average_abs_max", _fq_moving)
exp_("fake_quantize_range_abs_max", _fq_moving)
exp_("moving_average_abs_max_scale", lambda i, a: {"Out": [i["X"]]})

# ---------------------------------------------------------------------------
# metrics (accuracy_op.h, edit_distance_op.h, ctc_align_op.h, mean_iou_op.h)
# ---------------------------------------------------------------------------
def _accuracy(i, a):
    idx, lbl = i["Indices"], i["Label"]
    correct = (idx[:, :1] == lbl).sum()
    n = lbl.shape[0]
    return {"Accuracy": [np.array(correct / n, np.float32)]}


exp_("accuracy", _accuracy)


def _edit_distance(i, a):
    def lev(h, r):
        h = [v for v in h if v >= 0]
        r = [v for v in r if v >= 0]
        d = np.zeros((len(h) + 1, len(r) + 1))
        d[:, 0] = np.arange(len(h) + 1)
        d[0, :] = np.arange(len(r) + 1)
        for x in range(1, len(h) + 1):
            for y in range(1, len(r) + 1):
                d[x, y] = min(d[x - 1, y] + 1, d[x, y - 1] + 1,
                              d[x - 1, y - 1] + (h[x - 1] != r[y - 1]))
        return d[len(h), len(r)]

    out = np.array([[lev(hh, rr)] for hh, rr in zip(i["Hyps"],
                                                    i["Refs"])],
                   np.float32)
    return {"Out": [out]}


exp_("edit_distance", _edit_distance)


def _ctc_align(i, a):
    # ctc_align_op.h merge-repeated + drop-blank; padded contract keeps
    # the static input width, -1 past the kept tokens
    blank = a["blank"]
    x = i["Input"]
    out = np.full_like(x, -1)
    for r, row in enumerate(x):
        prev = None
        n = 0
        for v in row:
            if v != prev and v != blank:
                out[r, n] = v
                n += 1
            prev = v
    return {"Output": [out]}


exp_("ctc_align", _ctc_align)


def _mean_iou(i, a):
    p, l_ = i["Predictions"].reshape(-1), i["Labels"].reshape(-1)
    n = a["num_classes"]
    ious = []
    for c in range(n):
        inter = ((p == c) & (l_ == c)).sum()
        union = ((p == c) | (l_ == c)).sum()
        if union > 0:
            ious.append(inter / union)
    return {"OutMeanIou": [np.array(np.mean(ious), np.float32)]}


exp_("mean_iou", _mean_iou)

# ---------------------------------------------------------------------------
# conv / pool (conv_op.h im2col+gemm semantics, pool_op.h)
# ---------------------------------------------------------------------------
def _conv2d_np(x, w, strides, pads, dilations=(1, 1), groups=1):
    n, cin, h, wid = x.shape
    cout, cing, kh, kw = w.shape
    sh, sw = strides
    ph, pw = pads
    dh, dw = dilations
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wid + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    xp = np.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    out = np.zeros((n, cout, oh, ow), np.float64)
    cpg = cin // groups
    opg = cout // groups
    for b in range(n):
        for oc in range(cout):
            g = oc // opg
            for i_ in range(oh):
                for j in range(ow):
                    acc = 0.0
                    for ic in range(cpg):
                        for r in range(kh):
                            for c in range(kw):
                                acc += (xp[b, g * cpg + ic,
                                           i_ * sh + r * dh,
                                           j * sw + c * dw]
                                        * w[oc, ic, r, c])
                    out[b, oc, i_, j] = acc
    return out.astype(np.float32)


exp_("conv2d", lambda i, a: {"Output": [_conv2d_np(
    i["Input"], i["Filter"], a["strides"], a["paddings"],
    a.get("dilations", [1, 1]), a.get("groups", 1))]})
exp_("depthwise_conv2d", lambda i, a: {"Output": [_conv2d_np(
    i["Input"], i["Filter"], a["strides"], a["paddings"],
    a.get("dilations", [1, 1]), a.get("groups", 1))]})


def _conv2d_transpose_np(x, w, strides, pads, groups=1):
    n, cin, h, wid = x.shape
    cing, copg, kh, kw = w.shape
    sh, sw = strides
    ph, pw = pads
    cout = copg * groups
    oh = (h - 1) * sh - 2 * ph + kh
    ow = (wid - 1) * sw - 2 * pw + kw
    out = np.zeros((n, cout, oh + 2 * ph, ow + 2 * pw), np.float64)
    cpg = cin // groups
    for b in range(n):
        for g in range(groups):
            for ic in range(cpg):
                for oc in range(copg):
                    for i_ in range(h):
                        for j in range(wid):
                            out[b, g * copg + oc,
                                i_ * sh:i_ * sh + kh,
                                j * sw:j * sw + kw] += (
                                x[b, g * cpg + ic, i_, j]
                                * w[g * cpg + ic, oc])
    out = out[:, :, ph:ph + oh, pw:pw + ow]
    return out.astype(np.float32)


exp_("conv2d_transpose", lambda i, a: {"Output": [_conv2d_transpose_np(
    i["Input"], i["Filter"], a["strides"], a["paddings"],
    a.get("groups", 1))]})
exp_("depthwise_conv2d_transpose",
     lambda i, a: {"Output": [_conv2d_transpose_np(
         i["Input"], i["Filter"], a["strides"], a["paddings"],
         a.get("groups", 1))]})


def _pool2d(i, a):
    x = i["X"]
    kh, kw = a["ksize"]
    sh, sw = a["strides"]
    ph, pw = a["paddings"]
    n, c, h, w = x.shape
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    fill = -np.inf if a["pooling_type"] == "max" else 0.0
    xp = np.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)],
                constant_values=fill)
    out = np.zeros((n, c, oh, ow), np.float32)
    for i_ in range(oh):
        for j in range(ow):
            win = xp[:, :, i_ * sh:i_ * sh + kh, j * sw:j * sw + kw]
            out[:, :, i_, j] = (win.max((2, 3))
                                if a["pooling_type"] == "max"
                                else win.mean((2, 3)))
    return {"Out": [out]}


exp_("pool2d", _pool2d)


def _maxout(i, a):
    x, g = i["X"], a["groups"]
    n, c, h, w = x.shape
    return {"Out": [x.reshape(n, c // g, g, h, w).max(2)]}


exp_("maxout", _maxout)


def _unfold(i, a):
    x = i["X"]
    kh, kw = a["kernel_sizes"]
    sh, sw = a["strides"]
    p = a["paddings"]
    dh, dw = a["dilations"]
    n, c, h, w = x.shape
    xp = np.pad(x, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
    oh = (h + p[0] + p[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + p[1] + p[3] - (dw * (kw - 1) + 1)) // sw + 1
    cols = np.zeros((n, c * kh * kw, oh * ow), np.float32)
    for i_ in range(oh):
        for j in range(ow):
            patch = xp[:, :, i_ * sh:i_ * sh + dh * (kh - 1) + 1:dh,
                       j * sw:j * sw + dw * (kw - 1) + 1:dw]
            cols[:, :, i_ * ow + j] = patch.reshape(n, -1)
    return {"Y": [cols]}


exp_("unfold", _unfold)


def _crop(i, a):
    x = i["X"]
    off, shp = a["offsets"], a["shape"]
    idx = tuple(slice(o, o + s) for o, s in zip(off, shp))
    return {"Out": [x[idx]]}


exp_("crop", _crop)
exp_("crop_tensor", _crop)

# ---------------------------------------------------------------------------
# interpolation (interpolate_op.h; default align_mode=1 → src = dst*scale
# when align_corners=False)
# ---------------------------------------------------------------------------
def _nearest_interp(i, a):
    x = i["X"]
    n, c, h, w = x.shape
    oh, ow = a["out_h"], a["out_w"]
    if a.get("align_corners", True):
        ri = np.round(np.arange(oh) * (h - 1) / max(oh - 1, 1))
        rj = np.round(np.arange(ow) * (w - 1) / max(ow - 1, 1))
    else:
        ri = np.floor(np.arange(oh) * h / oh)
        rj = np.floor(np.arange(ow) * w / ow)
    return {"Out": [x[:, :, ri.astype(int)][:, :, :, rj.astype(int)]]}


exp_("nearest_interp", _nearest_interp)
grads("nearest_interp", "X")
grads("trilinear_interp", "X")


def _bilinear_interp(i, a):
    x = i["X"].astype(np.float64)
    n, c, h, w = x.shape
    oh, ow = a["out_h"], a["out_w"]
    align = a.get("align_corners", True)
    mode = a.get("align_mode", 1)
    out = np.zeros((n, c, oh, ow))
    for oi in range(oh):
        for oj in range(ow):
            if align:
                fi = oi * (h - 1) / max(oh - 1, 1)
                fj = oj * (w - 1) / max(ow - 1, 1)
            elif mode == 0:
                fi = max((oi + 0.5) * h / oh - 0.5, 0.0)
                fj = max((oj + 0.5) * w / ow - 0.5, 0.0)
            else:
                fi = oi * h / oh
                fj = oj * w / ow
            i0, j0 = int(fi), int(fj)
            i1, j1 = min(i0 + 1, h - 1), min(j0 + 1, w - 1)
            di, dj = fi - i0, fj - j0
            out[:, :, oi, oj] = (
                x[:, :, i0, j0] * (1 - di) * (1 - dj)
                + x[:, :, i1, j0] * di * (1 - dj)
                + x[:, :, i0, j1] * (1 - di) * dj
                + x[:, :, i1, j1] * di * dj)
    return {"Out": [out.astype(np.float32)]}


exp_("bilinear_interp", _bilinear_interp)

# ---------------------------------------------------------------------------
# sequence family — padded+lengths contract (SURVEY §2.1 redesign); the
# math matches sequence_pool_op.h etc. applied per-row up to Lengths[i]
# ---------------------------------------------------------------------------
def _seq_mask3(x, lens):
    t = x.shape[1]
    return (np.arange(t)[None, :] < lens[:, None])


def _sequence_pool(i, a):
    x, lens = i["X"], i["Lengths"]
    m = _seq_mask3(x, lens)[..., None]
    xm = np.where(m, x, 0.0)
    pt = a["pooltype"]
    if pt == "SUM":
        out = xm.sum(1)
    elif pt == "AVERAGE":
        out = xm.sum(1) / lens[:, None]
    elif pt == "SQRT":
        out = xm.sum(1) / np.sqrt(lens[:, None].astype(np.float64))
    elif pt == "MAX":
        out = np.where(m, x, -np.inf).max(1)
    elif pt == "FIRST":
        out = x[:, 0]
    elif pt == "LAST":
        out = x[np.arange(x.shape[0]), lens - 1]
    return {"Out": [out.astype(np.float32)]}


exp_("sequence_pool", _sequence_pool)


def _sequence_softmax(i, a):
    x, lens = i["X"], i["Lengths"]
    m = _seq_mask3(x, lens)
    e = np.where(m, np.exp(x - x.max(1, keepdims=True)), 0.0)
    return {"Out": [(e / e.sum(1, keepdims=True)) * m]}


exp_("sequence_softmax", _sequence_softmax)


def _sequence_reverse(i, a):
    x, lens = i["X"], i["Lengths"]
    out = x.copy()
    for r, ln in enumerate(lens):
        out[r, :ln] = x[r, :ln][::-1]
    return {"Y": [out]}


exp_("sequence_reverse", _sequence_reverse)


def _sequence_pad(i, a):
    x = i["X"]
    pl = a["padded_length"]
    pv = i["PadValue"].reshape(-1)[0]
    b, t = x.shape[0], x.shape[1]
    out = np.full((b, pl) + x.shape[2:], pv, x.dtype)
    out[:, :t] = x
    return {"Out": [out]}


exp_("sequence_pad", _sequence_pad)
grads("sequence_pad", "X")


def _sequence_unpad(i, a):
    x, lens = i["X"], i["Length"]
    m = _seq_mask3(x, lens)[..., None]
    return {"Out": [np.where(m, x, 0.0)]}


exp_("sequence_unpad", _sequence_unpad)
grads("sequence_unpad", "X")


def _sequence_expand_as(i, a):
    # each X row expands to Y's (padded) time width (sequence_expand_as_op)
    reps = i["Y"].shape[1] if i["Y"].ndim > 1 else 1
    return {"Out": [np.repeat(i["X"], reps, axis=0)]}


exp_("sequence_expand_as", _sequence_expand_as)


def _sequence_reshape(i, a):
    x = i["X"]
    nd = a["new_dim"]
    return {"Out": [x.reshape(x.shape[0], -1, nd)]}


exp_("sequence_reshape", _sequence_reshape)
grads("sequence_reshape", "X")


def _sequence_enumerate(i, a):
    x = i["X"]
    win, pad = a["win_size"], a["pad_value"]
    b, t = x.shape
    out = np.full((b, t, win), pad, x.dtype)
    for r in range(b):
        for c in range(t):
            for k in range(win):
                if c + k < t:
                    out[r, c, k] = x[r, c + k]
    return {"Out": [out]}


exp_("sequence_enumerate", _sequence_enumerate)


def _sequence_erase(i, a):
    # padded contract: erased positions compact left, tail -1-padded
    x = i["X"]
    toks = set(a["tokens"])
    out = np.full_like(x, -1)
    for r in range(x.shape[0]):
        keep = [v for v in x[r] if v not in toks]
        out[r, :len(keep)] = keep
    return {"Out": [out]}


exp_("sequence_erase", _sequence_erase)


def _sequence_slice(i, a):
    # padded contract: static input width kept, slice left-aligned,
    # tail zero-padded
    x = i["X"]
    off = i["Offset"].reshape(-1)
    ln = i["Length"].reshape(-1)
    out = np.zeros_like(x)
    for r in range(x.shape[0]):
        out[r, :ln[r]] = x[r, off[r]:off[r] + ln[r]]
    return {"Out": [out]}


exp_("sequence_slice", _sequence_slice)


grads("sequence_slice", "X")
grads("sequence_expand", "X")
grads("sequence_expand_as", "X")
grads("sequence_scatter", "X", "Updates")
grads("im2sequence", "X")


def _cvm(i, a):
    # cvm_op.h: use_cvm=True → passthrough with first two cols
    # log-transformed: show=log(show+1), clk=log(clk+1)-log(show+1)
    x = i["X"].copy()
    if a.get("use_cvm", True):
        out = x.copy()
        out[:, 0] = np.log(x[:, 0] + 1)
        out[:, 1] = np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1)
        return {"Y": [out]}
    return {"Y": [x[:, 2:]]}


exp_("cvm", _cvm)


# ---------------------------------------------------------------------------
# detection (iou_similarity_op.h, box_clip_op.h, box_coder_op.h,
# target_assign_op.h, bipartite_match_op.cc, polygon_box_transform_op.cc,
# roi_align_op.h, roi_pool_op.h, psroi_pool_op.h)
# ---------------------------------------------------------------------------
def _iou(b1, b2, normalized=False):
    off = 0.0 if normalized else 1.0
    a1 = np.maximum(b1[:, None, 0], b2[None, :, 0])
    a2 = np.maximum(b1[:, None, 1], b2[None, :, 1])
    b1x = np.minimum(b1[:, None, 2], b2[None, :, 2])
    b2y = np.minimum(b1[:, None, 3], b2[None, :, 3])
    iw = np.maximum(b1x - a1 + off, 0)
    ih = np.maximum(b2y - a2 + off, 0)
    inter = iw * ih
    ar1 = ((b1[:, 2] - b1[:, 0] + off)
           * (b1[:, 3] - b1[:, 1] + off))[:, None]
    ar2 = ((b2[:, 2] - b2[:, 0] + off)
           * (b2[:, 3] - b2[:, 1] + off))[None, :]
    return inter / (ar1 + ar2 - inter)


exp_("iou_similarity", lambda i, a: {"Out": [
    _iou(i["X"], i["Y"], a.get("box_normalized", True))
    .astype(np.float32)]})


def _box_clip(i, a):
    b = i["Input"].copy()
    h, w = i["ImInfo"][0, 0], i["ImInfo"][0, 1]
    b[:, 0::2] = np.clip(b[:, 0::2], 0, w - 1)
    b[:, 1::2] = np.clip(b[:, 1::2], 0, h - 1)
    return {"Output": [b]}


exp_("box_clip", _box_clip)


def _box_coder_encode(i, a):
    p, t = i["PriorBox"], i["TargetBox"]
    pv = i.get("PriorBoxVar")
    off = 0.0 if a.get("box_normalized", True) else 1.0
    pw = p[:, 2] - p[:, 0] + off
    ph = p[:, 3] - p[:, 1] + off
    px = p[:, 0] + pw / 2
    py = p[:, 1] + ph / 2
    tw = t[:, 2] - t[:, 0] + off
    th = t[:, 3] - t[:, 1] + off
    tx = t[:, 0] + tw / 2
    ty = t[:, 1] + th / 2
    out = np.zeros((t.shape[0], p.shape[0], 4), np.float64)
    out[..., 0] = (tx[:, None] - px[None]) / pw[None]
    out[..., 1] = (ty[:, None] - py[None]) / ph[None]
    out[..., 2] = np.log(tw[:, None] / pw[None])
    out[..., 3] = np.log(th[:, None] / ph[None])
    if pv is not None:
        out /= pv[None]
    return {"OutputBox": [out.astype(np.float32)]}


exp_("box_coder", _box_coder_encode)


def _target_assign(i, a):
    x, mi = i["X"], i["MatchIndices"]
    n, m = mi.shape
    k = x.shape[2]
    out = np.full((n, m, k), a["mismatch_value"], np.float32)
    wt = np.zeros((n, m, 1), np.float32)
    for b in range(n):
        for j in range(m):
            if mi[b, j] >= 0:
                out[b, j] = x[b % x.shape[0], mi[b, j]]
                wt[b, j] = 1.0
    return {"Out": [out], "OutWeight": [wt]}


exp_("target_assign", _target_assign)


def _bipartite_match(i, a):
    d = i["DistMat"].copy()
    n, m = d.shape
    match = np.full(m, -1, np.int32)
    dist = np.zeros(m, np.float32)
    used_r = set()
    used_c = set()
    while len(used_c) < min(n, m):
        best = (-1, -1, -1.0)
        for r in range(n):
            if r in used_r:
                continue
            for c in range(m):
                if c in used_c:
                    continue
                if d[r, c] > best[2]:
                    best = (r, c, d[r, c])
        if best[0] < 0:
            break
        match[best[1]] = best[0]
        dist[best[1]] = best[2]
        used_r.add(best[0])
        used_c.add(best[1])
    return {"ColToRowMatchIndices": [match.reshape(1, -1)],
            "ColToRowMatchDist": [dist.reshape(1, -1)]}


exp_("bipartite_match", _bipartite_match)


def _polygon_box_transform(i, a):
    x = i["Input"]
    n, c, h, w = x.shape
    out = x.copy()
    for id_h in range(h):
        for id_w in range(w):
            for id_c in range(c):
                if id_c % 2 == 0:
                    out[:, id_c, id_h, id_w] = (
                        id_w * 4 - x[:, id_c, id_h, id_w])
                else:
                    out[:, id_c, id_h, id_w] = (
                        id_h * 4 - x[:, id_c, id_h, id_w])
    return {"Output": [out]}


exp_("polygon_box_transform", _polygon_box_transform)


def _roi_align(i, a):
    x, rois = i["X"], i["ROIs"]
    ph, pw = a["pooled_height"], a["pooled_width"]
    scale = a["spatial_scale"]
    sr = a.get("sampling_ratio", -1)
    n, c, h, w = x.shape
    out = np.zeros((rois.shape[0], c, ph, pw), np.float64)
    for r, roi in enumerate(rois):
        x1, y1, x2, y2 = roi * scale
        rw = max(x2 - x1, 1.0)
        rh = max(y2 - y1, 1.0)
        bw, bh = rw / pw, rh / ph
        gw = sr if sr > 0 else int(np.ceil(rw / pw))
        gh = sr if sr > 0 else int(np.ceil(rh / ph))
        for pi in range(ph):
            for pj in range(pw):
                acc = np.zeros(c)
                for iy in range(gh):
                    for ix in range(gw):
                        yy = y1 + pi * bh + (iy + 0.5) * bh / gh
                        xx = x1 + pj * bw + (ix + 0.5) * bw / gw
                        if yy < -1 or yy > h or xx < -1 or xx > w:
                            continue
                        yy = min(max(yy, 0), h - 1)
                        xx = min(max(xx, 0), w - 1)
                        y0, x0 = int(yy), int(xx)
                        y1_, x1_ = min(y0 + 1, h - 1), min(x0 + 1,
                                                           w - 1)
                        ly, lx = yy - y0, xx - x0
                        acc += (x[0, :, y0, x0] * (1 - ly) * (1 - lx)
                                + x[0, :, y0, x1_] * (1 - ly) * lx
                                + x[0, :, y1_, x0] * ly * (1 - lx)
                                + x[0, :, y1_, x1_] * ly * lx)
                out[r, :, pi, pj] = acc / (gh * gw)
    return {"Out": [out.astype(np.float32)]}


exp_("roi_align", _roi_align)


def _roi_pool(i, a):
    x, rois = i["X"], i["ROIs"]
    ph, pw = a["pooled_height"], a["pooled_width"]
    scale = a["spatial_scale"]
    n, c, h, w = x.shape
    out = np.zeros((rois.shape[0], c, ph, pw), np.float32)
    for r, roi in enumerate(rois):
        x1 = int(round(roi[0] * scale))
        y1 = int(round(roi[1] * scale))
        x2 = int(round(roi[2] * scale))
        y2 = int(round(roi[3] * scale))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for pi in range(ph):
            for pj in range(pw):
                hs = y1 + int(np.floor(pi * rh / ph))
                he = y1 + int(np.ceil((pi + 1) * rh / ph))
                ws = x1 + int(np.floor(pj * rw / pw))
                we = x1 + int(np.ceil((pj + 1) * rw / pw))
                hs, he = np.clip([hs, he], 0, h)
                ws, we = np.clip([ws, we], 0, w)
                if he > hs and we > ws:
                    out[r, :, pi, pj] = x[0, :, hs:he, ws:we].max((1, 2))
    return {"Out": [out]}


exp_("roi_pool", _roi_pool)


def _psroi_pool(i, a):
    x, rois = i["X"], i["ROIs"]
    ph, pw = a["pooled_height"], a["pooled_width"]
    oc = a["output_channels"]
    scale = a["spatial_scale"]
    n, c, h, w = x.shape
    out = np.zeros((rois.shape[0], oc, ph, pw), np.float32)
    for r, roi in enumerate(rois):
        # psroi_pool_op.h: start rounded down, end rounded up, +1 shift
        x1 = round(roi[0] * scale)
        y1 = round(roi[1] * scale)
        x2 = round((roi[2] + 1) * scale)
        y2 = round((roi[3] + 1) * scale)
        rw = max(x2 - x1, 0.1)
        rh = max(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph
        for co in range(oc):
            for pi in range(ph):
                for pj in range(pw):
                    hs = int(np.floor(y1 + pi * bh))
                    he = int(np.ceil(y1 + (pi + 1) * bh))
                    ws = int(np.floor(x1 + pj * bw))
                    we = int(np.ceil(x1 + (pj + 1) * bw))
                    hs, he = np.clip([hs, he], 0, h)
                    ws, we = np.clip([ws, we], 0, w)
                    cix = (co * ph + pi) * pw + pj
                    if he > hs and we > ws:
                        out[r, co, pi, pj] = (
                            x[0, cix, hs:he, ws:we].sum()
                            / ((he - hs) * (we - ws)))
    return {"Out": [out]}


exp_("psroi_pool", _psroi_pool)


def _prroi_pool(i, a):
    # precise RoI pooling: the reference's per-cell decomposition
    # (prroi_pool_op.h:32-74 PrRoIPoolingMatCalculation summed over
    # integer cells, :349-365) — deliberately a DIFFERENT decomposition
    # from the lowering's separable triangle-CDF weights, so agreement
    # witnesses the integral itself
    x, rois = i["X"], i["ROIs"]
    ph, pw = a["pooled_height"], a["pooled_width"]
    scale = a["spatial_scale"]
    n, c, h, w = x.shape
    nums = i.get("BatchRoINums", i.get("RoisNum"))
    if nums is not None:
        bid = np.repeat(np.arange(len(nums)), nums.reshape(-1))
    else:
        bid = np.zeros(rois.shape[0], np.int64)

    def data(img, ch, y, xx):
        if 0 <= y < h and 0 <= xx < w:
            return float(x[img, ch, y, xx])
        return 0.0

    def mat(img, ch, sh, sw, eh, ew, y0, x0, y1, x1):
        s = 0.0
        al, be = x0 - sw, y0 - sh
        la, lb = x1 - sw, y1 - sh
        fb = lb - 0.5 * lb * lb - be + 0.5 * be * be
        s += data(img, ch, sh, sw) * (
            (la - 0.5 * la * la - al + 0.5 * al * al) * fb)
        al, la = ew - x1, ew - x0
        s += data(img, ch, sh, ew) * (
            (la - 0.5 * la * la - al + 0.5 * al * al) * fb)
        al, be = x0 - sw, eh - y1
        la, lb = x1 - sw, eh - y0
        fb = lb - 0.5 * lb * lb - be + 0.5 * be * be
        s += data(img, ch, eh, sw) * (
            (la - 0.5 * la * la - al + 0.5 * al * al) * fb)
        al, la = ew - x1, ew - x0
        s += data(img, ch, eh, ew) * (
            (la - 0.5 * la * la - al + 0.5 * al * al) * fb)
        return s

    out = np.zeros((rois.shape[0], c, ph, pw), np.float64)
    for r, roi in enumerate(rois):
        x1r, y1r, x2r, y2r = [float(v) * scale for v in roi[:4]]
        bh = max(y2r - y1r, 0.0) / ph
        bw = max(x2r - x1r, 0.0) / pw
        win = max(bh * bw, 0.0)
        if win <= 0.0:
            continue
        for ch in range(c):
            for pi in range(ph):
                for pj in range(pw):
                    wsh, wsw = y1r + pi * bh, x1r + pj * bw
                    weh, wew = wsh + bh, wsw + bw
                    s = 0.0
                    for hi in range(int(np.floor(wsh)),
                                    int(np.ceil(weh))):
                        for wi in range(int(np.floor(wsw)),
                                        int(np.ceil(wew))):
                            s += mat(int(bid[r]), ch, hi, wi,
                                     hi + 1, wi + 1,
                                     max(wsh, hi), max(wsw, wi),
                                     min(weh, hi + 1.0),
                                     min(wew, wi + 1.0))
                    out[r, ch, pi, pj] = s / win
    return {"Out": [out.astype(np.float32)]}


exp_("prroi_pool", _prroi_pool)
grads("prroi_pool", "X", "ROIs")


def _similarity_focus(i, a):
    # similarity_focus_op.h:76-140: per indexed slice, sort positions
    # of the remaining two dims descending and greedily keep those
    # whose row and column are both unused (stop at min(A, B) picks);
    # kept positions are 1 across the whole focus axis
    x = i["X"]
    axis, indexes = a["axis"], a["indexes"]
    xm = np.moveaxis(x, axis, 1)
    n, c, aa, bb = xm.shape
    out = np.zeros_like(xm)
    for bi in range(n):
        for ind in indexes:
            ch = xm[bi, ind]
            order = np.argsort(-ch, axis=None, kind="stable")
            ru = np.zeros(aa, bool)
            cu = np.zeros(bb, bool)
            picks = 0
            for flat in order:
                r2, c3 = divmod(int(flat), bb)
                if ru[r2] or cu[c3]:
                    continue
                ru[r2] = cu[c3] = True
                out[bi, :, r2, c3] = 1
                picks += 1
                if picks == min(aa, bb):
                    break
    return {"Out": [np.moveaxis(out, 1, axis)]}


exp_("similarity_focus", _similarity_focus)


def _tree_conv(i, a):
    # TBCNN tree conv re-derived from tree2col.cc:23-132 +
    # tree_conv_op.h:30-75: explicit per-root DFS patches with
    # (eta_l, eta_r, eta_t) position weights, then patch @ flat(Filter)
    nodes, edges, filt = i["NodesVector"], i["EdgeSet"], i["Filter"]
    md = a["max_depth"]
    bsz, n, fdim = nodes.shape
    _, _, osz, nf = filt.shape
    out = np.zeros((bsz, n, osz, nf), np.float64)
    w2 = filt.reshape(fdim * 3, osz * nf)  # row (feat i, coeff c)=i*3+c
    for b in range(bsz):
        children = {}
        node_count = 1
        for (u, v) in edges[b]:
            if u == 0 or v == 0:
                break  # construct_tree stops at the first zero pair
            children.setdefault(int(u), []).append(int(v))
            node_count += 1
        for root in range(1, node_count + 1):
            patch = [(root, 1, 1, 0)]
            stack = [(root, 0)]
            while stack:
                nd, depth = stack.pop()
                if depth + 1 < md:
                    ch = children.get(nd, [])
                    for ci, c in enumerate(ch, 1):
                        patch.append((c, ci, len(ch), depth + 1))
                        stack.append((c, depth + 1))
            row = np.zeros(fdim * 3, np.float64)
            for (nd, ci, pl, depth) in patch:
                eta_t = (md - depth) / md
                tempv = 0.5 if pl == 1 else (ci - 1.0) / (pl - 1.0)
                eta_l = (1 - eta_t) * tempv
                eta_r = (1 - eta_t) * (1 - eta_l)
                fvec = nodes[b, nd - 1].astype(np.float64)
                row[0::3] += eta_l * fvec
                row[1::3] += eta_r * fvec
                row[2::3] += eta_t * fvec
            out[b, root - 1] = (row @ w2).reshape(osz, nf)
    return {"Out": [out.astype(np.float32)]}


exp_("tree_conv", _tree_conv)


def _generate_proposals(i, a):
    # full RPN pipeline re-derived from generate_proposals_op.cc:288-430
    # (BoxCoder with variances + log(1000/16) clamp and -1 max corner,
    # ClipTiledBoxes, FilterBoxes origin-scale min_size + center-inside,
    # greedy +1-pixel NMS with adaptive eta, post_nms cap), emitted in
    # the lowering's padded fixed-shape convention
    scores, deltas = i["Scores"], i["BboxDeltas"]
    iminfo = i["ImInfo"]
    anchors = i["Anchors"].reshape(-1, 4).astype(np.float64)
    variances = i["Variances"].reshape(-1, 4).astype(np.float64)
    pre_n = a.get("pre_nms_topN", 256)
    post_n = a.get("post_nms_topN", 64)
    nms_thr = a.get("nms_thresh", 0.7)
    eta = a.get("eta", 1.0)
    min_size = max(a.get("min_size", 0.1), 1.0)
    clipv = np.log(1000.0 / 16.0)
    bsz = scores.shape[0]
    out_b = np.zeros((bsz, post_n, 4), np.float64)
    out_s = np.zeros((bsz, post_n), np.float64)
    nums = np.zeros(bsz, np.int32)

    def iou(b1, b2):
        if b2[0] > b1[2] or b2[2] < b1[0] or b2[1] > b1[3] \
                or b2[3] < b1[1]:
            return 0.0

        def area(b):
            if b[2] < b[0] or b[3] < b[1]:
                return 0.0
            return (b[2] - b[0] + 1.0) * (b[3] - b[1] + 1.0)

        iw = min(b1[2], b2[2]) - max(b1[0], b2[0]) + 1.0
        ih = min(b1[3], b2[3]) - max(b1[1], b2[1]) + 1.0
        inter = max(iw, 0.0) * max(ih, 0.0)
        return inter / (area(b1) + area(b2) - inter)

    for b in range(bsz):
        h, w, scale = [float(x) for x in iminfo[b][:3]]
        s = scores[b].transpose(1, 2, 0).reshape(-1).astype(np.float64)
        d = deltas[b].reshape(-1, 4, deltas.shape[-2], deltas.shape[-1]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4).astype(np.float64)
        order = np.argsort(-s, kind="stable")
        if 0 < pre_n < len(s):
            order = order[:pre_n]
        ts, td, ta, tv = s[order], d[order], anchors[order], \
            variances[order]
        aw = ta[:, 2] - ta[:, 0] + 1
        ah = ta[:, 3] - ta[:, 1] + 1
        acx = ta[:, 0] + aw / 2
        acy = ta[:, 1] + ah / 2
        cx = acx + tv[:, 0] * td[:, 0] * aw
        cy = acy + tv[:, 1] * td[:, 1] * ah
        bw = np.exp(np.minimum(tv[:, 2] * td[:, 2], clipv)) * aw
        bh = np.exp(np.minimum(tv[:, 3] * td[:, 3], clipv)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - 1, cy + bh / 2 - 1], 1)
        boxes[:, 0] = np.clip(boxes[:, 0], 0, w - 1)
        boxes[:, 1] = np.clip(boxes[:, 1], 0, h - 1)
        boxes[:, 2] = np.clip(boxes[:, 2], 0, w - 1)
        boxes[:, 3] = np.clip(boxes[:, 3], 0, h - 1)
        kept_rows = []
        for r in range(len(boxes)):
            ws = boxes[r, 2] - boxes[r, 0] + 1
            hs = boxes[r, 3] - boxes[r, 1] + 1
            ws_o = (boxes[r, 2] - boxes[r, 0]) / scale + 1
            hs_o = (boxes[r, 3] - boxes[r, 1]) / scale + 1
            if ws_o >= min_size and hs_o >= min_size \
                    and boxes[r, 0] + ws / 2 <= w \
                    and boxes[r, 1] + hs / 2 <= h:
                kept_rows.append(r)
        sel = []
        thr = nms_thr
        for r in sorted(kept_rows, key=lambda r: -ts[r]):
            if all(iou(boxes[r], boxes[kr]) <= thr for kr in sel):
                sel.append(r)
                if eta < 1.0 and thr > 0.5:
                    thr *= eta
        sel = sel[:post_n]
        nums[b] = len(sel)
        for j, r in enumerate(sel):
            out_b[b, j] = boxes[r]
            out_s[b, j] = ts[r]
    return {"RpnRois": [out_b.reshape(-1, 4).astype(np.float32)],
            "RpnRoiProbs": [out_s.reshape(-1, 1).astype(np.float32)],
            "RpnRoisNum": [nums]}


exp_("generate_proposals", _generate_proposals)


def _distribute_fpn_proposals(i, a):
    # distribute_fpn_proposals_op.h:55-140 re-derived: pixel-area level
    # routing, per-level compaction in original order, restore slots —
    # emitted in the lowering's padded static-shape convention
    rois = i["FpnRois"].astype(np.float64)
    mn, mx = a["min_level"], a["max_level"]
    rl, rs = a["refer_level"], a["refer_scale"]
    n = rois.shape[0]
    nlv = mx - mn + 1
    levels = []
    for r in rois:
        w, h = r[2] - r[0], r[3] - r[1]
        area = 0.0 if (w < 0 or h < 0) else (w + 1.0) * (h + 1.0)
        t = int(np.floor(np.log2(np.sqrt(area) / rs + 1e-6) + rl))
        levels.append(min(mx, max(t, mn)))
    outs = [np.zeros((n, 4), np.float32) for _ in range(nlv)]
    nums = np.zeros(nlv, np.int32)
    restore = np.zeros((n, 1), np.int32)
    for orig, (r, lv) in enumerate(zip(rois, levels)):
        li = lv - mn
        restore[orig, 0] = li * n + nums[li]
        outs[li][nums[li]] = r
        nums[li] += 1
    return {"MultiFpnRois": outs, "RestoreIndex": [restore],
            "MultiLevelRoIsNum": [nums]}


exp_("distribute_fpn_proposals", _distribute_fpn_proposals)


def _collect_fpn_proposals(i, a):
    # collect_fpn_proposals_op.h:60-150 re-derived (single batch):
    # concat levels, stable-sort by score descending, keep top
    # post_nms_topN; the batch-id re-sort is the identity here
    # (multi-entry slots arrive flattened under their spec entry names)
    rois = np.concatenate([i["cfp_r1"], i["cfp_r2"]])
    scores = np.concatenate([i["cfp_s1"].reshape(-1),
                             i["cfp_s2"].reshape(-1)])
    k = min(a.get("post_nms_topN", len(scores)), len(scores))
    order = np.argsort(-scores, kind="stable")[:k]
    return {"FpnRois": [rois[order].astype(np.float32)],
            "RoisNum": [np.array([k], np.int32)]}


exp_("collect_fpn_proposals", _collect_fpn_proposals)


def _yolov3_loss(i, a):
    # scalar transliteration of yolov3_loss_op.h:253-407: per-cell
    # ignore scan (GetYoloBox + CalcBoxIoU), per-gt best-anchor match
    # over ALL anchors (centred wh-IoU), CalcBoxLocationLoss (sigmoid-CE
    # tx/ty + L1 tw/th, (2-gw*gh)*score scale), CalcLabelLoss with
    # label smoothing, CalcObjnessLoss over the -1/0/score mask
    x = i["X"].astype(np.float64)
    gtbox = i["GTBox"].astype(np.float64)
    gtlabel = i["GTLabel"]
    gtscore = i.get("GTScore")
    anchors = a["anchors"]
    mask = list(a["anchor_mask"])
    C = a["class_num"]
    ignore = a["ignore_thresh"]
    ds = a.get("downsample_ratio", 32)
    smooth = a.get("use_label_smooth", True)
    n, _, h, w = x.shape
    na = len(mask)
    an_num = len(anchors) // 2
    isz = ds * h
    pos, neg = 1.0, 0.0
    if smooth:
        sw = min(1.0 / C, 1.0 / 40)
        pos, neg = 1.0 - sw, sw

    def sce(z, t):
        return max(z, 0.0) - z * t + np.log1p(np.exp(-abs(z)))

    def sig(z):
        return 1.0 / (1.0 + np.exp(-z))

    def iou_box(b1, b2):  # (cx, cy, w, h)
        wov = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2) \
            - max(b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
        hov = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2) \
            - max(b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
        inter = 0.0 if (wov < 0 or hov < 0) else wov * hov
        return inter / max(b1[2] * b1[3] + b2[2] * b2[3] - inter, 1e-10)

    x5 = x.reshape(n, na, 5 + C, h, w)
    nb = gtbox.shape[1]
    loss = np.zeros(n)
    obj = np.zeros((n, na, h, w))
    matchm = np.full((n, nb), -1, np.int32)
    for im in range(n):
        valid = [gtbox[im, t, 2] >= 1e-6 and gtbox[im, t, 3] >= 1e-6
                 for t in range(nb)]
        for j in range(na):
            for k in range(h):
                for ll in range(w):
                    p = ((ll + sig(x5[im, j, 0, k, ll])) / w,
                         (k + sig(x5[im, j, 1, k, ll])) / h,
                         np.exp(min(x5[im, j, 2, k, ll], 20))
                         * anchors[2 * mask[j]] / isz,
                         np.exp(min(x5[im, j, 3, k, ll], 20))
                         * anchors[2 * mask[j] + 1] / isz)
                    best = 0.0
                    for t in range(nb):
                        if valid[t]:
                            best = max(best, iou_box(p, tuple(gtbox[im, t])))
                    if best > ignore:
                        obj[im, j, k, ll] = -1.0
        for t in range(nb):
            if not valid[t]:
                continue
            g = gtbox[im, t]
            gi = min(max(int(g[0] * w), 0), w - 1)
            gj = min(max(int(g[1] * h), 0), h - 1)
            best_iou, best_n = 0.0, 0
            for ai in range(an_num):
                iou = iou_box((0, 0, anchors[2 * ai] / isz,
                               anchors[2 * ai + 1] / isz),
                              (0, 0, g[2], g[3]))
                if iou > best_iou:
                    best_iou, best_n = iou, ai
            mi = mask.index(best_n) if best_n in mask else -1
            matchm[im, t] = mi
            if mi < 0:
                continue
            sc = 1.0 if gtscore is None else float(gtscore[im, t])
            tx, ty = g[0] * w - gi, g[1] * h - gj
            tw = np.log(g[2] * isz / anchors[2 * best_n])
            th = np.log(g[3] * isz / anchors[2 * best_n + 1])
            scl = (2.0 - g[2] * g[3]) * sc
            loss[im] += (sce(x5[im, mi, 0, gj, gi], tx)
                         + sce(x5[im, mi, 1, gj, gi], ty)
                         + abs(x5[im, mi, 2, gj, gi] - tw)
                         + abs(x5[im, mi, 3, gj, gi] - th)) * scl
            obj[im, mi, gj, gi] = sc
            lab = int(gtlabel[im, t])
            for c in range(C):
                loss[im] += sce(x5[im, mi, 5 + c, gj, gi],
                                pos if c == lab else neg) * sc
        for j in range(na):
            for k in range(h):
                for ll in range(w):
                    o = obj[im, j, k, ll]
                    if o > 1e-5:
                        loss[im] += sce(x5[im, j, 4, k, ll], 1.0) * o
                    elif o > -0.5:
                        loss[im] += sce(x5[im, j, 4, k, ll], 0.0)
    return {"Loss": [loss.astype(np.float32)],
            "ObjectnessMask": [obj.astype(np.float32)],
            "GTMatchMask": [matchm]}


exp_("yolov3_loss", _yolov3_loss)


def _generate_mask_labels(i, a):
    # generate_mask_labels_op.cc:199-254 + mask_util.cc
    # Polys2MaskWrtBox:186-211 on pre-binarized image-grid masks:
    # match each fg roi to the same-class gt with max bbox IoU, crop
    # the matched mask to the roi box at `resolution`, class-expand
    # with -1 ignore labels
    rois = i["Rois"]
    labels = i["LabelsInt32"].reshape(-1)
    segms = i["GtSegms"]
    gt_cls = i["GtClasses"].reshape(-1)
    im = i["ImInfo"]
    res = a["resolution"]
    ncls = a["num_classes"]
    g, m, _ = segms.shape
    n = rois.shape[0]
    ih, iw = im[0, 0], im[0, 1]
    # gt boxes from mask extents (normalized), same-class IoU argmax
    tgt = np.full((n, ncls * res * res), -1, np.int32)
    for r in range(n):
        if labels[r] <= 0:
            continue
        best, best_iou = 0, -1.0
        rb = rois[r] / np.array([iw, ih, iw, ih])
        for j in range(g):
            if gt_cls[j] != labels[r]:
                continue
            ys, xs = np.where(segms[j] > 0)
            gb = np.array([xs.min() / m, ys.min() / m,
                           (xs.max() + 1) / m, (ys.max() + 1) / m])
            ix = max(0.0, min(rb[2], gb[2]) - max(rb[0], gb[0]))
            iy = max(0.0, min(rb[3], gb[3]) - max(rb[1], gb[1]))
            inter = ix * iy
            ua = ((rb[2] - rb[0]) * (rb[3] - rb[1])
                  + (gb[2] - gb[0]) * (gb[3] - gb[1]) - inter)
            iou = inter / ua if ua > 0 else 0.0
            if iou > best_iou:
                best_iou, best = iou, j
        bw = max(rois[r, 2] - rois[r, 0], 1.0)
        bh = max(rois[r, 3] - rois[r, 1], 1.0)
        crop = np.zeros((res, res), np.int32)
        for ii in range(res):
            for jj in range(res):
                y = rois[r, 1] + (ii + 0.5) * bh / res
                x = rois[r, 0] + (jj + 0.5) * bw / res
                rr = min(max(int(y / ih * m), 0), m - 1)
                cc = min(max(int(x / iw * m), 0), m - 1)
                crop[ii, jj] = 1 if segms[best, rr, cc] > 0 else 0
        c = labels[r]
        tgt[r, c * res * res:(c + 1) * res * res] = crop.reshape(-1)
    return {"MaskInt32": [tgt]}


exp_("generate_mask_labels", _generate_mask_labels)
grads("prroi_pool", "X")
grads("psroi_pool", "X")

# ---------------------------------------------------------------------------
# fused / misc (fusion ops decompose into the primitives above)
# ---------------------------------------------------------------------------
# BinaryCompound form: binary(X, unary(Y)) for
# functor_list=[elementwise_add, relu] (fused_elemwise_activation_op.h)
exp_("fused_elemwise_activation", lambda i, a: {"Out": [
    i["X"] + np.maximum(i["Y"], 0)]})


def _fused_emb_seq_pool(i, a):
    w, ids = i["W"], i["Ids"]
    emb = w[ids.reshape(ids.shape[0], -1)]
    return {"Out": [emb.sum(1)]}


exp_("fused_embedding_seq_pool", _fused_emb_seq_pool)
exp_("fusion_squared_mat_sub", lambda i, a: {"Out": [
    a.get("scalar", 1.0) * ((i["X"] @ i["Y"]) ** 2
                            - (i["X"] ** 2) @ (i["Y"] ** 2))]})
grads("fusion_squared_mat_sub", "X", "Y")


def _fusion_repeated_fc_relu(i, a):
    h = np.maximum(i["X"] @ i["frfr_w1"] + i["frfr_b1"], 0)
    return {"Out": [np.maximum(h @ i["frfr_w2"] + i["frfr_b2"], 0)]}


exp_("fusion_repeated_fc_relu", _fusion_repeated_fc_relu)
grads("fusion_repeated_fc_relu", "X")


def _fused_fc_eln(i, a):
    y = i["X"] @ i["W"] + i["Y"]
    mu = y.mean(-1, keepdims=True)
    var = y.var(-1, keepdims=True)
    out = (y - mu) / np.sqrt(var + a["epsilon"])
    return {"Out": [out * i["Scale"] + i["Bias1"]]}


exp_("fused_fc_elementwise_layernorm", _fused_fc_eln)
grads("fused_fc_elementwise_layernorm", "X", "W")


def _fusion_transpose_flatten_concat(i, a):
    xs = [i["ftfc_a"], i["ftfc_b"]]
    ts = [np.transpose(x, a["trans_axis"]) for x in xs]
    fl = [t.reshape(int(np.prod(t.shape[:a["flatten_axis"]])), -1)
          for t in ts]
    return {"Out": [np.concatenate(fl, axis=a["concat_axis"])]}


exp_("fusion_transpose_flatten_concat",
     _fusion_transpose_flatten_concat)
grads("fusion_transpose_flatten_concat", "X")
grads("multihead_matmul", "Q", "K", "V")
grads("attention_lstm", "X")
grads("fusion_gru", "X")
grads("fusion_lstm", "X")
grads("fusion_seqconv_eltadd_relu", "X")
grads("fusion_seqpool_concat", "X")
grads("match_matrix_tensor", "X", "Y", "W")
grads("var_conv_2d", "X", "W")
grads("tree_conv", "NodesVector", "Filter")
grads("cudnn_gru", "Input")
grads("unpool", "X")
grads("linear_chain_crf", "Transition")
grads("deformable_conv_v1", "Input")
grads("deformable_psroi_pooling", "Input", "Trans")
grads("conv2d_fusion", "Input", "Filter")
grads("fused_embedding_fc_lstm", "Embeddings")
grads("conv3d", "Filter")
grads("conv3d_transpose", "Filter")
grads("box_coder", "TargetBox")

# ---------------------------------------------------------------------------
# batch B refs: remaining feasible families (conv3d, pooling variants,
# sampling/warping, sequence convs, CRF/CTC, misc losses, mkldnn quant)
# ---------------------------------------------------------------------------
exp_("split", lambda i, a: {"Out": np.split(i["X"], a["num"],
                                            axis=a.get("axis", 0))})
exp_("unstack", lambda i, a: {"Y": [
    np.squeeze(s, a.get("axis", 0))
    for s in np.split(i["X"], i["X"].shape[a.get("axis", 0)],
                      a.get("axis", 0))]})
exp_("lod_reset", lambda i, a: {"Out": [i["X"]]})
exp_("data_norm", lambda i, a: {"Y": [
    (i["X"] - i["BatchSum"] / i["BatchSize"])
    * np.sqrt(i["BatchSize"] / i["BatchSquareSum"])]})
exp_("center_loss", lambda i, a: {"Loss": [
    0.5 * ((i["X"] - i["Centers"][i["Label"].reshape(-1)]) ** 2)
    .sum(1, keepdims=True)]})


def _flash_attention_ref(i, a):
    q, k, v = (x.astype(np.float64) for x in (i["Q"], i["K"], i["V"]))
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
    if a.get("causal"):
        t = q.shape[2]
        s = np.where(np.tril(np.ones((t, t), bool)), s, -1e30)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return {"Out": [np.einsum("bhqk,bhkd->bhqd", p, v)
                    .astype(np.float32)]}


exp_("flash_attention", _flash_attention_ref)


def _max_pool2d_index(i, a):
    x = i["X"]
    kh, kw = a["ksize"]
    sh, sw = a["strides"]
    n, c, h, w = x.shape
    oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), x.dtype)
    idx = np.zeros((n, c, oh, ow), np.int64)
    for pi in range(oh):
        for pj in range(ow):
            win = x[:, :, pi * sh:pi * sh + kh, pj * sw:pj * sw + kw]
            flat = win.reshape(n, c, -1)
            am = flat.argmax(-1)
            out[:, :, pi, pj] = flat.max(-1)
            # mask index is global within the h*w feature map
            r = pi * sh + am // kw
            col = pj * sw + am % kw
            idx[:, :, pi, pj] = r * w + col
    return {"Out": [out], "Mask": [idx]}


exp_("max_pool2d_with_index", _max_pool2d_index)


def _pool3d(i, a):
    x = i["X"]
    kd, kh, kw = a["ksize"]
    sd, sh, sw = a["strides"]
    n, c, d, h, w = x.shape
    od, oh, ow = ((d - kd) // sd + 1, (h - kh) // sh + 1,
                  (w - kw) // sw + 1)
    out = np.zeros((n, c, od, oh, ow), np.float32)
    red = (lambda win: win.max((2, 3, 4))) \
        if a["pooling_type"] == "max" else (lambda win: win.mean((2, 3, 4)))
    for pi in range(od):
        for pj in range(oh):
            for pk in range(ow):
                out[:, :, pi, pj, pk] = red(
                    x[:, :, pi * sd:pi * sd + kd, pj * sh:pj * sh + kh,
                      pk * sw:pk * sw + kw])
    return {"Out": [out]}


exp_("pool3d", _pool3d)


def _unpool(i, a):
    x, ind = i["X"], i["Indices"]
    n, c, h, w = x.shape
    oh = (h - 1) * a["strides"][0] + a["ksize"][0]
    ow = (w - 1) * a["strides"][1] + a["ksize"][1]
    out = np.zeros((n, c, oh, ow), x.dtype)
    for b in range(n):
        for ch in range(c):
            for pi in range(h):
                for pj in range(w):
                    p = ind[b, ch, pi, pj]
                    out[b, ch, p // ow, p % ow] = x[b, ch, pi, pj]
    return {"Out": [out]}


exp_("unpool", _unpool)


def _spp(i, a):
    # spp_op.h:39-50: per level, bins=2^p, ksize=ceil(h/bins),
    # pad=(ksize*bins-h+1)//2, stride=ksize; flatten + concat
    x = i["X"]
    n, c, h, w = x.shape
    outs = []
    for p in range(a["pyramid_height"]):
        bins = 2 ** p
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        fill = -np.inf if a["pooling_type"] == "max" else 0.0
        xp = np.pad(x, [(0, 0), (0, 0), (ph, kh * bins - h - ph),
                        (pw, kw * bins - w - pw)], constant_values=fill)
        lvl = np.zeros((n, c, bins, bins), np.float32)
        for pi in range(bins):
            for pj in range(bins):
                win = xp[:, :, pi * kh:(pi + 1) * kh,
                         pj * kw:(pj + 1) * kw]
                lvl[:, :, pi, pj] = (win.max((2, 3))
                                     if a["pooling_type"] == "max"
                                     else win.mean((2, 3)))
        outs.append(lvl.reshape(n, -1))
    return {"Out": [np.concatenate(outs, 1)]}


exp_("spp", _spp)


def _conv3d_np(x, w, strides, pads, dilations=(1, 1, 1), groups=1):
    n, cin = x.shape[:2]
    cout = w.shape[0]
    kd, kh, kw = w.shape[2:]
    sd, sh, sw = strides
    pd, ph, pw = pads
    xp = np.pad(x, [(0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw)])
    od = (x.shape[2] + 2 * pd - kd) // sd + 1
    oh = (x.shape[3] + 2 * ph - kh) // sh + 1
    ow = (x.shape[4] + 2 * pw - kw) // sw + 1
    out = np.zeros((n, cout, od, oh, ow), np.float64)
    cpg = cin // groups
    opg = cout // groups
    for b in range(n):
        for oc in range(cout):
            g = oc // opg
            xs = xp[b, g * cpg:(g + 1) * cpg]
            for i_ in range(od):
                for j in range(oh):
                    for k_ in range(ow):
                        win = xs[:, i_ * sd:i_ * sd + kd,
                                 j * sh:j * sh + kh,
                                 k_ * sw:k_ * sw + kw]
                        out[b, oc, i_, j, k_] = (win * w[oc]).sum()
    return out.astype(np.float32)


exp_("conv3d", lambda i, a: {"Output": [_conv3d_np(
    i["Input"], i["Filter"], a["strides"], a["paddings"],
    a.get("dilations", [1, 1, 1]), a.get("groups", 1))]})


def _conv3d_transpose_np(i, a):
    x, w = i["Input"], i["Filter"]  # w: [C_in, C_out/g, kd, kh, kw]
    sd, sh, sw = a["strides"]
    pd, ph, pw = a["paddings"]
    n, cin = x.shape[:2]
    cog = w.shape[1]
    kd, kh, kw = w.shape[2:]
    od = (x.shape[2] - 1) * sd + kd
    oh = (x.shape[3] - 1) * sh + kh
    ow = (x.shape[4] - 1) * sw + kw
    out = np.zeros((n, cog, od + 2 * pd, oh + 2 * ph, ow + 2 * pw),
                   np.float64)
    for b in range(n):
        for ic in range(cin):
            for oc in range(cog):
                for i_ in range(x.shape[2]):
                    for j in range(x.shape[3]):
                        for k_ in range(x.shape[4]):
                            out[b, oc, i_ * sd:i_ * sd + kd,
                                j * sh:j * sh + kh,
                                k_ * sw:k_ * sw + kw] += (
                                x[b, ic, i_, j, k_] * w[ic, oc])
    out = out[:, :, pd:pd + od, ph:ph + oh, pw:pw + ow]
    return {"Output": [out.astype(np.float32)]}


exp_("conv3d_transpose", _conv3d_transpose_np)


def _grid_sampler(i, a):
    # grid_sampler_op.h:54-90: x = (g+1)·(W−1)/2 (align-corners),
    # bilinear with zero contribution outside bounds
    x, g = i["X"].astype(np.float64), i["Grid"]
    n, c, h, w = x.shape
    gh, gw = g.shape[1], g.shape[2]
    out = np.zeros((n, c, gh, gw))
    for b in range(n):
        for pi in range(gh):
            for pj in range(gw):
                gx = (g[b, pi, pj, 0] + 1) * 0.5 * (w - 1)
                gy = (g[b, pi, pj, 1] + 1) * 0.5 * (h - 1)
                x0, y0 = int(np.floor(gx)), int(np.floor(gy))
                for dy in (0, 1):
                    for dx in (0, 1):
                        xx, yy = x0 + dx, y0 + dy
                        if 0 <= xx < w and 0 <= yy < h:
                            wt = ((1 - abs(gx - xx))
                                  * (1 - abs(gy - yy)))
                            out[b, :, pi, pj] += wt * x[b, :, yy, xx]
    return {"Output": [out.astype(np.float32)]}


exp_("grid_sampler", _grid_sampler)


def _affine_grid(i, a):
    theta = i["Theta"]  # [n, 2, 3]
    n_, _, h, w = a["output_shape"]
    ys = np.linspace(-1, 1, h)
    xs = np.linspace(-1, 1, w)
    base = np.stack([np.tile(xs, (h, 1)),
                     np.tile(ys[:, None], (1, w)),
                     np.ones((h, w))], axis=-1)  # [h, w, 3]
    out = np.einsum("hwk,njk->nhwj", base, theta)
    return {"Output": [out.astype(np.float32)]}


exp_("affine_grid", _affine_grid)


def _row_conv(i, a):
    # row_conv_op.cc: lookahead conv, out[t] = sum_j w[j]·x[t+j]
    x, w = i["X"], i["Filter"]  # [b, t, d], [fc, d]
    b, t, d = x.shape
    fc = w.shape[0]
    out = np.zeros_like(x)
    for j in range(fc):
        out[:, :t - j] += x[:, j:] * w[j][None, None, :]
    return {"Out": [out]}


exp_("row_conv", _row_conv)


def _sequence_conv(i, a):
    # sequence_conv_op: context window [start, start+len) rows of x
    # concatenated then projected by Filter [len·d, od]
    x, w = i["X"], i["Filter"]  # [b, t, d], [cl*d, od]
    cl = a["contextLength"]
    cs = a.get("contextStart", -((cl - 1) // 2))
    b, t, d = x.shape
    cols = np.zeros((b, t, cl * d), x.dtype)
    for j in range(cl):
        src = cs + j
        lo, hi = max(0, -src), min(t, t - src)
        if lo < hi:
            cols[:, lo:hi, j * d:(j + 1) * d] = x[:, lo + src:hi + src]
    return {"Out": [cols @ w]}


exp_("sequence_conv", _sequence_conv)
exp_("fusion_seqconv_eltadd_relu", lambda i, a: {"Out": [np.maximum(
    _sequence_conv(i, a)["Out"][0] + i["Bias"], 0.0)]})
exp_("fusion_seqpool_concat", lambda i, a: {"Out": [np.concatenate(
    [i["fspc_a"].sum(1), i["fspc_b"].sum(1)], axis=1)]})


def _im2sequence(i, a):
    x = i["X"]
    kh, kw = a["kernels"]
    sh, sw = a["strides"]
    n, c, h, w = x.shape
    oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
    rows = []
    for b in range(n):
        for pi in range(oh):
            for pj in range(ow):
                rows.append(x[b, :, pi * sh:pi * sh + kh,
                              pj * sw:pj * sw + kw].reshape(-1))
    return {"Out": [np.stack(rows)]}


exp_("im2sequence", _im2sequence)


def _match_matrix_tensor(i, a):
    x, y, w = i["X"], i["Y"], i["W"]  # [b,l1,d], [b,l2,d], [d,dim_t,d]
    out = np.einsum("bld,dte,bme->btlm", x, w, y)
    return {"Out": [out.astype(np.float32)]}


exp_("match_matrix_tensor", _match_matrix_tensor)
exp_("var_conv_2d", lambda i, a: {"Out": [_conv2d_np(
    i["X"], i["W"], [a["StrideH"], a["StrideW"]],
    [(a["KernelH"] - 1) // 2, (a["KernelW"] - 1) // 2])]})


def _spectral_norm(i, a):
    w, u, v = (x.astype(np.float64) for x in (i["Weight"], i["U"],
                                              i["V"]))
    eps = a.get("eps", 1e-12)
    for _ in range(a.get("power_iters", 1)):
        v = w.T @ u
        v /= np.sqrt((v * v).sum()) + eps
        u = w @ v
        u /= np.sqrt((u * u).sum()) + eps
    sigma = u @ w @ v
    return {"Out": [(w / sigma).astype(np.float32)]}


exp_("spectral_norm", _spectral_norm)


# ---------------------------------------------------------------------------
# batch D refs: full recurrences, multihead attention, priors, yolo,
# deformable conv
# ---------------------------------------------------------------------------
def _gru_seq(x, w, b, origin=False, h0=None):
    """gru over pre-projected x [b, t, 3d] (gru_unit math per step,
    math/detail/gru kernels: gates [u, r, c])."""
    bsz, t, _ = x.shape
    d = w.shape[0]
    h = np.zeros((bsz, d)) if h0 is None else h0.astype(np.float64)
    hs = np.zeros((bsz, t, d))
    for k in range(t):
        xt = x[:, k].astype(np.float64)
        if b is not None:
            xt = xt + b.reshape(-1)
        gate = xt[:, :2 * d] + h @ w[:, :2 * d]
        u = _sig(gate[:, :d])
        r = _sig(gate[:, d:])
        cand = np.tanh(xt[:, 2 * d:] + (r * h) @ w[:, 2 * d:])
        h = (cand + u * (h - cand)) if origin else (u * (cand - h) + h)
        hs[:, k] = h
    return hs.astype(np.float32)


exp_("gru", lambda i, a: {"Hidden": [_gru_seq(
    i["Input"], i["Weight"].astype(np.float64), i.get("Bias"),
    a.get("origin_mode", False))]})
exp_("fusion_gru", lambda i, a: {"Hidden": [_gru_seq(
    i["X"].astype(np.float64) @ i["WeightX"].astype(np.float64),
    i["WeightH"].astype(np.float64), i.get("Bias"),
    a.get("origin_mode", False))]})


def _lstm_seq(x, w, b=None, proj=None):
    """lstm over pre-projected x [b, t, 4d], gate order [c~, i, f, o]
    (math/detail/lstm_cpu_kernel.h:51-54), no peepholes."""
    bsz, t, _ = x.shape
    d = w.shape[1] // 4
    p = w.shape[0]
    h = np.zeros((bsz, p))
    c = np.zeros((bsz, d))
    hs = np.zeros((bsz, t, p))
    cs = np.zeros((bsz, t, d))
    for k in range(t):
        xt = x[:, k].astype(np.float64)
        if b is not None:
            xt = xt + b.reshape(-1)[:4 * d]
        g = xt + h @ w
        cand = np.tanh(g[:, :d])
        ig = _sig(g[:, d:2 * d])
        f = _sig(g[:, 2 * d:3 * d])
        c = cand * ig + c * f
        o = _sig(g[:, 3 * d:])
        hcell = o * np.tanh(c)
        h = hcell @ proj if proj is not None else hcell
        hs[:, k] = h
        cs[:, k] = c
    return hs.astype(np.float32), cs.astype(np.float32)


def _lstm_ref(i, a):
    hs, cs = _lstm_seq(i["Input"], i["Weight"].astype(np.float64),
                       i.get("Bias"))
    return {"Hidden": [hs], "Cell": [cs]}


exp_("lstm", _lstm_ref)


def _lstmp_ref(i, a):
    hs, _ = _lstm_seq(i["Input"], i["Weight"].astype(np.float64),
                      i.get("Bias"),
                      proj=i["ProjWeight"].astype(np.float64))
    return {"Hidden": [hs]}


exp_("lstmp", _lstmp_ref)


def _fusion_lstm_ref(i, a):
    x = i["X"].astype(np.float64) @ i["WeightX"].astype(np.float64)
    hs, cs = _lstm_seq(x, i["WeightH"].astype(np.float64), i.get("Bias"))
    return {"Hidden": [hs]}


exp_("fusion_lstm", _fusion_lstm_ref)


def _fused_emb_fc_lstm(i, a):
    ids = i["Ids"].reshape(i["Ids"].shape[0], -1)
    x = i["Embeddings"][ids]  # [b, t, 4d] pre-projected embedding rows
    hs, cs = _lstm_seq(x, i["WeightH"].astype(np.float64), i.get("Bias"))
    return {"Hidden": [hs]}


exp_("fused_embedding_fc_lstm", _fused_emb_fc_lstm)


def _prior_box(i, a):
    # prior_box_op.h: centers at (idx+0.5)·step, step = image/feature;
    # min-size square first, then non-unit aspect ratios; clipped and
    # normalized by the image size
    feat, img = i["Input"], i["Image"]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    mins = a["min_sizes"]
    ars = [1.0]
    for r in a.get("aspect_ratios", [1.0]):
        if all(abs(r - e) > 1e-6 for e in ars):
            ars.append(r)
    maxs = a.get("max_sizes", [])
    var = a["variances"]
    clip = a.get("clip", True)
    step_w = a.get("step_w", 0.0) or iw / fw
    step_h = a.get("step_h", 0.0) or ih / fh
    offset = a.get("offset", 0.5)
    npr = len(mins) * len(ars) + len(maxs)
    boxes = np.zeros((fh, fw, npr, 4), np.float32)
    for hi in range(fh):
        cy = (hi + offset) * step_h
        for wi in range(fw):
            cx = (wi + offset) * step_w
            k = 0
            for mi, ms in enumerate(mins):
                for r in ars:
                    bw = ms * np.sqrt(r) / 2
                    bh = ms / np.sqrt(r) / 2
                    boxes[hi, wi, k] = [(cx - bw) / iw, (cy - bh) / ih,
                                        (cx + bw) / iw, (cy + bh) / ih]
                    k += 1
                if mi < len(maxs):
                    sz = np.sqrt(ms * maxs[mi]) / 2
                    boxes[hi, wi, k] = [(cx - sz) / iw, (cy - sz) / ih,
                                        (cx + sz) / iw, (cy + sz) / ih]
                    k += 1
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    variances = np.tile(np.asarray(var, np.float32),
                        (fh, fw, npr, 1)).reshape(fh, fw, npr, 4)
    return {"Boxes": [boxes], "Variances": [variances]}


exp_("prior_box", _prior_box)


def _multihead_matmul(i, a):
    # multihead_matmul_op.cc:108-130: scores = alpha·(Q+bq)(K+bk)^T
    # + BiasQK, softmax, context vs (V+bv)
    q = i["Q"] + i["BiasQ"]
    k = i["K"] + i["BiasK"]
    v = i["V"] + i["BiasV"]
    nh = a["head_number"]
    bt, t, d = q.shape
    dh = d // nh

    def heads(z):
        return z.reshape(bt, t, nh, dh).transpose(0, 2, 1, 3)

    s = np.einsum("bhqd,bhkd->bhqk", heads(q), heads(k)) * a["alpha"]
    s = s + i["BiasQK"]
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bhkd->bhqd", p, heads(v))
    return {"Out": [o.transpose(0, 2, 1, 3).reshape(bt, t, d)
                    .astype(np.float32)]}


exp_("multihead_matmul", _multihead_matmul)


def _yolo_box(i, a):
    # yolo_box_op.h: bx = (j + sigmoid(tx))/W · img_w, bw = anchor_w ·
    # exp(tw) · img_w/downsample·W ... boxes in image pixels, centered
    x = i["X"].astype(np.float64)
    imgs = i["ImgSize"]
    anchors = a["anchors"]
    cn = a["class_num"]
    conf_thr = a["conf_thresh"]
    ds = a["downsample_ratio"]
    n, c, h, w = x.shape
    na = len(anchors) // 2
    attrs_len = 5 + cn
    img_h, img_w = int(imgs[0, 0]), int(imgs[0, 1])
    boxes, scores = [], []
    xr = x.reshape(n, na, attrs_len, h, w)
    for an in range(na):
        aw, ah = anchors[2 * an], anchors[2 * an + 1]
        for hi in range(h):
            for wi in range(w):
                pred = xr[0, an, :, hi, wi]
                conf = _sig(pred[4])
                if conf < conf_thr:
                    boxes.append([0, 0, 0, 0])
                    scores.append([0.0] * cn)
                    continue
                cx = (wi + _sig(pred[0])) / w * img_w
                cy = (hi + _sig(pred[1])) / h * img_h
                bw = np.exp(pred[2]) * aw / (ds * w) * img_w
                bh = np.exp(pred[3]) * ah / (ds * h) * img_h
                x1 = max(cx - bw / 2, 0)
                y1 = max(cy - bh / 2, 0)
                x2 = min(cx + bw / 2, img_w - 1)
                y2 = min(cy + bh / 2, img_h - 1)
                boxes.append([x1, y1, x2, y2])
                scores.append(list(conf * _sig(pred[5:])))
    return {"Boxes": [np.asarray(boxes, np.float32)[None]],
            "Scores": [np.asarray(scores, np.float32)[None]]}


exp_("yolo_box", _yolo_box)


def _deformable_conv_ref(i, a):
    # deformable_conv_op semantics (modulated_deformable_im2col):
    # sample x at p0 + pn + Δp with bilinear weights, modulated by mask
    x, w = i["Input"].astype(np.float64), i["Filter"].astype(np.float64)
    off = i["Offset"].astype(np.float64)
    mask = i["Mask"].astype(np.float64) if "Mask" in i else None
    sh, sw = a["strides"]
    ph, pw = a["paddings"]
    dh, dw = a.get("dilations", [1, 1])
    n, cin, h, wid = x.shape
    cout, _, kh, kw = w.shape
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wid + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    out = np.zeros((n, cout, oh, ow))

    def sample(b, c, y, xx):
        y0, x0 = int(np.floor(y)), int(np.floor(xx))
        v = 0.0
        for yy in (y0, y0 + 1):
            for xc in (x0, x0 + 1):
                if 0 <= yy < h and 0 <= xc < wid:
                    v += ((1 - abs(y - yy)) * (1 - abs(xx - xc))
                          * x[b, c, yy, xc])
        return v

    for b in range(n):
        for oc in range(cout):
            for pi in range(oh):
                for pj in range(ow):
                    acc = 0.0
                    for r in range(kh):
                        for cc in range(kw):
                            kidx = r * kw + cc
                            dy = off[b, 2 * kidx, pi, pj]
                            dx = off[b, 2 * kidx + 1, pi, pj]
                            m = mask[b, kidx, pi, pj] \
                                if mask is not None else 1.0
                            y = pi * sh - ph + r * dh + dy
                            xx = pj * sw - pw + cc * dw + dx
                            for ic in range(cin):
                                acc += (w[oc, ic, r, cc] * m
                                        * sample(b, ic, y, xx))
                    out[b, oc, pi, pj] = acc
    return {"Output": [out.astype(np.float32)]}


exp_("deformable_conv", _deformable_conv_ref)
exp_("deformable_conv_v1", _deformable_conv_ref)


# ---------------------------------------------------------------------------
# batch C refs: CRF/CTC, metric-learning losses, padded select/unique,
# NMS, anchors, recurrent units
# ---------------------------------------------------------------------------
def _where_index_ref(i, a):
    cond = i.get("Condition", i.get("X"))
    idx = np.argwhere(cond != 0).astype(np.int64)
    out = np.full((cond.size, cond.ndim), -1, np.int64)
    out[:idx.shape[0]] = idx
    return {"Out": [out]}


exp_("where", _where_index_ref)
exp_("where_index", _where_index_ref)


def _unique_ref(i, a):
    # documented static-shape contract: SORTED uniques, sentinel-padded
    # (dtype max for ints), Index maps each element to its slot
    x = i["X"].reshape(-1)
    u, inv, cnt = np.unique(x, return_inverse=True, return_counts=True)
    sent = np.iinfo(x.dtype).max if np.issubdtype(x.dtype, np.integer) \
        else np.inf
    out = np.full(x.size, sent, x.dtype)
    out[:u.size] = u
    counts = np.zeros(x.size, np.int64)
    counts[:u.size] = cnt
    return {"Out": [out], "Index": [inv.astype(np.int64)],
            "Count": [counts]}


exp_("unique", lambda i, a: {k: v for k, v in _unique_ref(i, a).items()
                             if k != "Count"})
exp_("unique_with_counts", _unique_ref)


def _sigmoid_focal_loss(i, a):
    # sigmoid_focal_loss_op.h:43-73
    x, lbl = i["X"].astype(np.float64), i["Label"].reshape(-1)
    fg = max(float(i["FgNum"].reshape(-1)[0]), 1.0)
    gamma, alpha = a["gamma"], a["alpha"]
    n, c = x.shape
    d = np.arange(c)[None, :]
    g = lbl[:, None]
    c_pos = (g == d + 1).astype(np.float64)
    c_neg = ((g != -1) & (g != d + 1)).astype(np.float64)
    p = 1.0 / (1.0 + np.exp(-x))
    term_pos = (1 - p) ** gamma * np.log(np.maximum(p, 1e-37))
    term_neg = p ** gamma * (-x * (x >= 0)
                             - np.log1p(np.exp(x - 2 * x * (x >= 0))))
    out = (-c_pos * term_pos * (alpha / fg)
           - c_neg * term_neg * ((1 - alpha) / fg))
    return {"Out": [out.astype(np.float32)]}


exp_("sigmoid_focal_loss", _sigmoid_focal_loss)


def _npair_loss(i, a):
    # layers/nn.py:16592-16649 composition, Beta = 0.25
    anchor, pos = i["Anchor"].astype(np.float64), \
        i["Positive"].astype(np.float64)
    lbl = i["Labels"].reshape(-1)
    n = lbl.shape[0]
    lab = (lbl[:, None] == lbl[None, :]).astype(np.float64)
    lab = lab / lab.sum(1, keepdims=True)
    l2 = (( (anchor ** 2).sum(1).mean() + (pos ** 2).sum(1).mean() )
          * 0.25 * a.get("l2_reg", 0.002))
    sim = anchor @ pos.T
    logp = sim - sim.max(1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(1, keepdims=True))
    ce = -(lab * logp).sum(1, keepdims=True)       # [n, 1]
    cross = (lab * ce).sum(0)                       # reference quirk
    return {"Out": [np.float32(l2 + cross.mean())]}


exp_("npair_loss", _npair_loss)


def _linear_chain_crf(i, a):
    # linear_chain_crf_op.h:160-216: LogLikelihood = logZ − gold score;
    # Transition row 0 = start, row 1 = stop, rows 2.. = transitions
    em = i["Emission"].astype(np.float64)     # [b, t, n] padded batch
    w = i["Transition"].astype(np.float64)    # [n+2, n]
    lbl = i["Label"]
    b, t, n = em.shape
    out = np.zeros((b, 1), np.float64)
    for s in range(b):
        x = em[s]
        # logsumexp alpha recursion
        alpha = w[0] + x[0]
        for k in range(1, t):
            m = alpha.max()
            alpha = x[k] + m + np.log(
                np.exp(alpha - m) @ np.exp(w[2:]))
        m = alpha.max()
        logz = m + np.log(np.exp(alpha - m) @ np.exp(w[1]))
        ls = lbl[s]
        gold = w[0, ls[0]] + x[0, ls[0]] + w[1, ls[t - 1]]
        for k in range(1, t):
            gold += x[k, ls[k]] + w[ls[k - 1] + 2, ls[k]]
        out[s, 0] = logz - gold
    return {"LogLikelihood": [out.astype(np.float32)]}


exp_("linear_chain_crf", _linear_chain_crf)


def _crf_decoding(i, a):
    em = i["Emission"].astype(np.float64)
    w = i["Transition"].astype(np.float64)
    b, t, n = em.shape
    paths = np.zeros((b, t), np.int64)
    for s in range(b):
        x = em[s]
        score = w[0] + x[0]
        back = np.zeros((t, n), np.int64)
        for k in range(1, t):
            cand = score[:, None] + w[2:]
            back[k] = cand.argmax(0)
            score = x[k] + cand.max(0)
        score = score + w[1]
        paths[s, t - 1] = score.argmax()
        for k in range(t - 1, 0, -1):
            paths[s, k - 1] = back[k, paths[s, k]]
    return {"ViterbiPath": [paths]}


exp_("crf_decoding", _crf_decoding)


def _warpctc(i, a):
    # standard CTC forward (alpha) on softmax(logits); loss per sequence
    logits = i["Logits"].astype(np.float64)   # [b, t, c]
    labels = i["Label"]
    blank = a.get("blank", 0)
    b, t, c = logits.shape
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    out = np.zeros((b, 1), np.float64)
    for s in range(b):
        lab = [v for v in labels[s] if v >= 0]
        ext = [blank]
        for v in lab:
            ext += [v, blank]
        m = len(ext)
        al = np.zeros((t, m))
        al[0, 0] = probs[s, 0, blank]
        if m > 1:
            al[0, 1] = probs[s, 0, ext[1]]
        for k in range(1, t):
            for j in range(m):
                v = al[k - 1, j]
                if j > 0:
                    v += al[k - 1, j - 1]
                if j > 1 and ext[j] != blank and ext[j] != ext[j - 2]:
                    v += al[k - 1, j - 2]
                al[k, j] = v * probs[s, k, ext[j]]
        out[s, 0] = -np.log(max(al[t - 1, m - 1]
                                + (al[t - 1, m - 2] if m > 1 else 0.0),
                                1e-300))
    return {"Loss": [out.astype(np.float32)]}


exp_("warpctc", _warpctc)


def _gru_unit(i, a):
    # gru_unit_op.h:55-121 (origin_mode False default):
    # u,r = sigmoid(input[:, :2d] + h_prev @ W[:, :2d]);
    # c = tanh(input[:, 2d:] + (r·h_prev) @ W[:, 2d:]);
    # h = u·(c − h_prev) + h_prev
    x, hp, w = i["Input"], i["HiddenPrev"], i["Weight"]
    d = hp.shape[1]
    gate = x[:, :2 * d] + hp @ w[:, :2 * d]
    if "Bias" in i:
        gate = gate + i["Bias"][0, :2 * d]
    u = _sig(gate[:, :d])
    r = _sig(gate[:, d:])
    cin = x[:, 2 * d:] + (r * hp) @ w[:, 2 * d:]
    if "Bias" in i:
        cin = cin + i["Bias"][0, 2 * d:]
    cand = np.tanh(cin)
    h = u * (cand - hp) + hp
    return {"Hidden": [h.astype(np.float32)]}


exp_("gru_unit", _gru_unit)


def _lstm_unit(i, a):
    # lstm_unit_op.h:63-72: gates ordered i, f(+forget_bias), o, g
    x, cp = i["X"], i["C_prev"]
    d = cp.shape[1]
    fb = a.get("forget_bias", 0.0)
    ig = _sig(x[:, :d])
    f = _sig(x[:, d:2 * d] + fb)
    o = _sig(x[:, 2 * d:3 * d])
    g = np.tanh(x[:, 3 * d:])
    cc = f * cp + ig * g
    return {"C": [cc.astype(np.float32)],
            "H": [(o * np.tanh(cc)).astype(np.float32)]}


exp_("lstm_unit", _lstm_unit)


def _anchor_generator(i, a):
    # anchor_generator_op.h:60-94
    feat = i["Input"]
    h, w = feat.shape[2], feat.shape[3]
    sizes = a["anchor_sizes"]
    ratios = a["aspect_ratios"]
    sw, sh = a["stride"]
    offset = a.get("offset", 0.5)
    var = a["variances"]
    nprior = len(sizes) * len(ratios)
    anchors = np.zeros((h, w, nprior, 4), np.float32)
    # reference: x_ctr = w_idx * stride_w + offset * (stride_w - 1)
    for hi in range(h):
        yc = hi * sh + offset * (sh - 1)
        for wi in range(w):
            xc = wi * sw + offset * (sw - 1)
            idx = 0
            for r in ratios:
                for s in sizes:
                    area = sw * sh
                    base_w = round(np.sqrt(area / r))
                    base_h = round(base_w * r)
                    aw = (s / sw) * base_w
                    ah = (s / sh) * base_h
                    anchors[hi, wi, idx] = [xc - 0.5 * (aw - 1),
                                            yc - 0.5 * (ah - 1),
                                            xc + 0.5 * (aw - 1),
                                            yc + 0.5 * (ah - 1)]
                    idx += 1
    variances = np.tile(np.asarray(var, np.float32),
                        (h, w, nprior, 1)).reshape(h, w, nprior, 4)
    return {"Anchors": [anchors], "Variances": [variances]}


exp_("anchor_generator", _anchor_generator)


def _multiclass_nms_ref(i, a):
    # multiclass_nms_op semantics on the padded [B, keep_top_k, 6]
    # contract (class, score, x1, y1, x2, y2; -1 rows = empty)
    boxes, scores = i["BBoxes"], i["Scores"]  # [B,N,4], [B,C,N]
    st = a.get("score_threshold", 0.0)
    nt = a.get("nms_threshold", 0.3)
    keep_k = a.get("keep_top_k", 16)
    if keep_k <= 0:
        keep_k = 16
    bg = a.get("background_label", 0)
    bsz = boxes.shape[0]
    ncls = scores.shape[1] - (1 if 0 <= bg < scores.shape[1] else 0)
    keep_k = min(keep_k, ncls * boxes.shape[1])  # lowering's static cap
    out = np.full((bsz, keep_k, 6), -1.0, np.float32)
    for b in range(bsz):
        rows = []
        for c in range(scores.shape[1]):
            if c == bg:
                continue
            order = np.argsort(-scores[b, c], kind="stable")
            kept = []
            for idx in order:
                if scores[b, c, idx] <= st:
                    continue
                ok = True
                for j in kept:
                    if _iou(boxes[b, idx:idx + 1],
                            boxes[b, j:j + 1])[0, 0] > nt:
                        ok = False
                        break
                if ok:
                    kept.append(idx)
            for j in kept:
                rows.append([c, scores[b, c, j]] + list(boxes[b, j]))
        rows.sort(key=lambda r: -r[1])
        for k, r in enumerate(rows[:keep_k]):
            out[b, k] = r
    return {"Out": [out]}


exp_("multiclass_nms", _multiclass_nms_ref)
exp_("multiclass_nms2", _multiclass_nms_ref)


def _rdo_pixel_iou(b1, b2):
    # JaccardOverlap normalized=false with the strict-disjoint early
    # return and degenerate-box area 0
    # (retinanet_detection_output_op.cc:133-171)
    if b2[0] > b1[2] or b2[2] < b1[0] or b2[1] > b1[3] or b2[3] < b1[1]:
        return 0.0

    def area(b):
        if b[2] < b[0] or b[3] < b[1]:
            return 0.0
        return (b[2] - b[0] + 1.0) * (b[3] - b[1] + 1.0)

    inter = ((min(b1[2], b2[2]) - max(b1[0], b2[0]) + 1.0)
             * (min(b1[3], b2[3]) - max(b1[1], b2[1]) + 1.0))
    return inter / (area(b1) + area(b2) - inter)


def _retinanet_detection_output_ref(i, a):
    # full pipeline scalar re-derivation of
    # retinanet_detection_output_op.cc:116-452 on the padded
    # [B, final_k, 6] contract (rows [label+1, score, x1, y1, x2, y2])
    import math
    boxes_l = ([i[k] for k in sorted(i) if k.startswith("rdo_box")]
               or [i["BBoxes"]])
    scores_l = ([i[k] for k in sorted(i) if k.startswith("rdo_sc")]
                or [i["Scores"]])
    anchors_l = ([i[k] for k in sorted(i) if k.startswith("rdo_an")]
                 or [i["Anchors"]])
    im_info = i["ImInfo"]
    st = a.get("score_threshold", 0.05)
    ntk = a.get("nms_top_k", 1000)
    ktk = a.get("keep_top_k", 100)
    nt = a.get("nms_threshold", 0.3)
    eta = a.get("nms_eta", 1.0)
    nlv = len(scores_l)
    ncls = scores_l[0].shape[-1]
    bsz = scores_l[0].shape[0]
    k_all = sum(s[0].size if ntk <= -1 else min(ntk, s[0].size)
                for s in scores_l)
    final_k = min(ktk if ktk > 0 else ncls * k_all, ncls * k_all)
    out = np.full((bsz, final_k, 6), -1.0, np.float32)
    for b in range(bsz):
        imh, imw, ims = [float(v) for v in im_info[b][:3]]
        # std::round (half away from zero), not Python's half-to-even
        imh, imw = math.floor(imh / ims + 0.5), math.floor(imw / ims + 0.5)
        preds = {}
        for lv in range(nlv):
            sc = scores_l[lv][b].reshape(-1)
            dl = boxes_l[lv][b].reshape(-1, 4)
            an = anchors_l[lv].reshape(-1, 4)
            thr = st if lv < nlv - 1 else 0.0  # last level keeps all
            idxs = [j for j in range(sc.size) if sc[j] > thr]
            idxs.sort(key=lambda j: -sc[j])    # stable
            if ntk > -1:
                idxs = idxs[:ntk]
            for j in idxs:
                ai, c = j // ncls, j % ncls
                aw = an[ai, 2] - an[ai, 0] + 1.0
                ah = an[ai, 3] - an[ai, 1] + 1.0
                cx = dl[ai, 0] * aw + an[ai, 0] + aw / 2
                cy = dl[ai, 1] * ah + an[ai, 1] + ah / 2
                w = math.exp(dl[ai, 2]) * aw
                h = math.exp(dl[ai, 3]) * ah
                box = [max(min((cx - w / 2) / ims, imw - 1.0), 0.0),
                       max(min((cy - h / 2) / ims, imh - 1.0), 0.0),
                       max(min((cx + w / 2 - 1) / ims, imw - 1.0), 0.0),
                       max(min((cy + h / 2 - 1) / ims, imh - 1.0), 0.0)]
                preds.setdefault(c, []).append(box + [float(sc[j])])
        rows = []
        for c in sorted(preds):                # std::map iteration order
            dets = preds[c]
            order = sorted(range(len(dets)), key=lambda j: -dets[j][4])
            sel, adaptive = [], nt
            for j in order:
                if all(_rdo_pixel_iou(dets[j], dets[k2]) <= adaptive
                       for k2 in sel):
                    sel.append(j)
                    if eta < 1.0 and adaptive > 0.5:
                        adaptive *= eta
            rows.extend([c + 1.0, dets[j][4]] + dets[j][:4] for j in sel)
        rows.sort(key=lambda r: -r[1])         # stable keep_top_k
        for k2, r in enumerate(rows[:final_k]):
            out[b, k2] = r
    return {"Out": [out]}


exp_("retinanet_detection_output", _retinanet_detection_output_ref)


exp_("conv2d_fusion", lambda i, a: {"Output": [np.maximum(
    _conv2d_np(i["Input"], i["Filter"], a["strides"], a["paddings"])
    + i["Bias"].reshape(1, -1, 1, 1), 0.0)]})
exp_("dgc_clip_by_norm", lambda i, a: {"Out": [
    i["X"] * min(1.0, a["max_norm"]
                 / max(float(np.sqrt((i["X"] ** 2).sum())), 1e-10))]})


def _pnpair_ref(i, a):
    score = i["Score"].reshape(-1)
    label = i["Label"].reshape(-1)
    qid = i["QueryID"].reshape(-1)
    pos = neg = neu = 0
    n = score.shape[0]
    for x in range(n):
        for y in range(x + 1, n):
            if qid[x] != qid[y] or label[x] == label[y]:
                continue
            ds = score[x] - score[y]
            dl = label[x] - label[y]
            if ds * dl > 0:
                pos += 1
            elif ds * dl < 0:
                neg += 1
            else:
                neu += 1
    f = lambda v: np.asarray([v], np.float32)  # noqa: E731
    return {"PositivePair": [f(pos)], "NegativePair": [f(neg)],
            "NeutralPair": [f(neu)]}


exp_("positive_negative_pair", _pnpair_ref)


def _filter_by_instag_ref(i, a):
    # padded contract: rows whose tag set misses the filter are zeroed
    # and LossWeight marks the kept rows
    x, tags = i["Ins"], i["Ins_tag"]
    ftags = set(i["Filter_tag"].reshape(-1).tolist())
    keep = np.array([bool(set(np.atleast_1d(t).tolist()) & ftags)
                     for t in tags], np.float32)
    return {"Out": [x * keep.reshape((-1,) + (1,) * (x.ndim - 1))],
            "LossWeight": [keep.reshape(-1, 1)]}


exp_("filter_by_instag", _filter_by_instag_ref)


def _fusion_seqpool_cvm_concat_ref(i, a):
    pooled = [i["fspcc_a"].sum(1), i["fspcc_b"].sum(1)]
    outs = []
    for p in pooled:
        if a.get("use_cvm", True):
            y0 = np.log(p[:, :1] + 1)
            y1 = np.log(p[:, 1:2] + 1) - y0
            outs.append(np.concatenate([y0, y1, p[:, 2:]], 1))
        else:
            outs.append(p[:, 2:])
    return {"Out": [np.concatenate(outs, 1).astype(np.float32)]}


exp_("fusion_seqpool_cvm_concat", _fusion_seqpool_cvm_concat_ref)


def _auc_ref(i, a):
    # auc_op.h: bucket = pred_pos · num_thresholds, histogram stats,
    # trapezoid area over descending thresholds
    pred = i["Predict"][:, -1]
    label = i["Label"].reshape(-1)
    nt = a.get("num_thresholds", 4095)
    pos = i["StatPos"].astype(np.float64).copy()
    neg = i["StatNeg"].astype(np.float64).copy()
    for p, l_ in zip(pred, label):
        b = min(max(int(p * nt), 0), nt)
        if l_ == 1:
            pos[b] += 1
        else:
            neg[b] += 1
    tp = fp = 0.0
    area = 0.0
    for b in range(nt, -1, -1):
        tp_new, fp_new = tp + pos[b], fp + neg[b]
        area += (fp_new - fp) * (tp + tp_new) / 2.0
        tp, fp = tp_new, fp_new
    auc = area / (tp * fp) if tp * fp > 0 else 0.0
    return {"AUC": [np.float64(auc)],
            "StatPosOut": [pos.astype(i["StatPos"].dtype)],
            "StatNegOut": [neg.astype(i["StatNeg"].dtype)]}


exp_("auc", _auc_ref)


def _precision_recall_ref(i, a):
    # precision_recall_op.h:56-156
    idx = i["Indices"].reshape(-1)
    lbl = i["Labels"].reshape(-1)
    cls = a["class_number"]
    ws = i["Weights"].reshape(-1) if "Weights" in i \
        else np.ones(idx.shape[0])
    st = np.zeros((cls, 4))  # TP FP TN FN
    for x, l_, w in zip(idx, lbl, ws):
        if x == l_:
            st[x, 0] += w
            st[:, 2] += w
            st[x, 2] -= w
        else:
            st[l_, 3] += w
            st[x, 1] += w
            st[:, 2] += w
            st[x, 2] -= w
            st[l_, 2] -= w

    def metrics(s):
        def prec(t, f):
            return t / (t + f) if t > 0 or f > 0 else 1.0

        pc = [prec(s[c, 0], s[c, 1]) for c in range(cls)]
        rc = [prec(s[c, 0], s[c, 3]) for c in range(cls)]
        mp, mr = np.mean(pc), np.mean(rc)
        mf = 2 * mp * mr / (mp + mr) if mp > 0 or mr > 0 else 0.0
        up = prec(s[:, 0].sum(), s[:, 1].sum())
        ur = prec(s[:, 0].sum(), s[:, 3].sum())
        uf = 2 * up * ur / (up + ur) if up > 0 or ur > 0 else 0.0
        return np.array([mp, mr, mf, up, ur, uf])

    accum = st + i["StatesInfo"].astype(np.float64) \
        if "StatesInfo" in i else st
    return {"BatchMetrics": [metrics(st)],
            "AccumMetrics": [metrics(accum)],
            "AccumStatesInfo": [accum.astype(np.float32)]}


exp_("precision_recall", _precision_recall_ref)


def _mine_hard_examples_ref(i, a):
    # mine_hard_examples_op.cc:29-38 + :90-122 (max_negative)
    loss = i["ClsLoss"]
    match = i["MatchIndices"]
    dist = i["MatchDist"]
    thr = a.get("neg_dist_threshold", 0.5)
    ratio = a.get("neg_pos_ratio", 3.0)
    b, p = match.shape
    out = np.full((b, p), -1, np.int32)
    for n in range(b):
        elig = [(loss[n, m], m) for m in range(p)
                if match[n, m] == -1 and dist[n, m] < thr]
        n_pos = int((match[n] != -1).sum())
        n_neg = min(int(n_pos * ratio), len(elig))
        elig.sort(key=lambda t: -t[0])
        # :137-140 — the selected indices drain out of a std::set,
        # i.e. ASCENDING prior order
        sel = sorted(m for _, m in elig[:n_neg])
        for k, m in enumerate(sel):
            out[n, k] = m
    return {"NegIndices": [out], "UpdatedMatchIndices": [match]}


exp_("mine_hard_examples", _mine_hard_examples_ref)


def _density_prior_box_ref(i, a):
    feat, img = i["Input"], i["Image"]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw, sh = iw / fw, ih / fh
    step_avg = int((sw + sh) * 0.5)
    offset = a.get("offset", 0.5)
    entries = []
    for size, d in zip(a["fixed_sizes"], a["densities"]):
        shift = step_avg // d
        for r in a["fixed_ratios"]:
            bw, bh = size * np.sqrt(r), size / np.sqrt(r)
            for di in range(d):
                for dj in range(d):
                    entries.append(
                        (bw, bh,
                         -step_avg / 2 + shift / 2 + dj * shift,
                         -step_avg / 2 + shift / 2 + di * shift))
    npr = len(entries)
    boxes = np.zeros((fh, fw, npr, 4), np.float32)
    for hi in range(fh):
        for wi in range(fw):
            cx, cy = (wi + offset) * sw, (hi + offset) * sh
            for k, (bw, bh, ox, oy) in enumerate(entries):
                boxes[hi, wi, k] = [
                    max((cx + ox - bw / 2) / iw, 0.0),
                    max((cy + oy - bh / 2) / ih, 0.0),
                    min((cx + ox + bw / 2) / iw, 1.0),
                    min((cy + oy + bh / 2) / ih, 1.0)]
    var = np.tile(np.asarray(a["variances"], np.float32),
                  (fh, fw, npr, 1)).reshape(fh, fw, npr, 4)
    return {"Boxes": [boxes], "Variances": [var]}


exp_("density_prior_box", _density_prior_box_ref)


def _box_decoder_and_assign_ref(i, a):
    # box_decoder_and_assign_op.h:45-95
    prior = i["PriorBox"]
    pv = i["PriorBoxVar"].reshape(-1)[:4]
    deltas = i["TargetBox"]
    score = i["BoxScore"]
    clip = a.get("box_clip", 2.0)
    n = prior.shape[0]
    c = score.shape[1]
    out = np.zeros((n, c * 4))
    assign = np.zeros((n, 4))
    for r in range(n):
        pw = prior[r, 2] - prior[r, 0] + 1
        ph = prior[r, 3] - prior[r, 1] + 1
        pcx = prior[r, 0] + pw / 2
        pcy = prior[r, 1] + ph / 2
        for j in range(c):
            o = j * 4
            dw = min(pv[2] * deltas[r, o + 2], clip)
            dh = min(pv[3] * deltas[r, o + 3], clip)
            cx = pv[0] * deltas[r, o] * pw + pcx
            cy = pv[1] * deltas[r, o + 1] * ph + pcy
            bw, bh = np.exp(dw) * pw, np.exp(dh) * ph
            out[r, o:o + 4] = [cx - bw / 2, cy - bh / 2,
                               cx + bw / 2 - 1, cy + bh / 2 - 1]
        best, best_s = -1, -1.0
        for j in range(1, c):
            if score[r, j] > best_s:
                best_s, best = score[r, j], j
        assign[r] = out[r, best * 4:best * 4 + 4] if best > 0 \
            else prior[r, :4]
    return {"DecodeBox": [out.astype(np.float32)],
            "OutputAssignBox": [assign.astype(np.float32)]}


exp_("box_decoder_and_assign", _box_decoder_and_assign_ref)


def _rpn_target_assign_ref(i, a):
    # deterministic contract of the redesigned lowering: threshold
    # labels + best-anchor-per-gt positive + delta encoding with the
    # reference's +1 pixel-inclusive widths (rpn_target_assign_op.cc
    # bbox2delta); the reference additionally SAMPLES 256 anchors,
    # which the static-shape redesign replaces with full assignment
    anchors = i["Anchor"]
    gt = i["GtBoxes"]
    pos_t = a.get("rpn_positive_overlap", 0.7)
    neg_t = a.get("rpn_negative_overlap", 0.3)
    ious = _iou(anchors, gt)
    best = ious.max(1)
    arg = ious.argmax(1)
    lab = np.where(best >= pos_t, 1, np.where(best < neg_t, 0, -1))
    lab[ious.argmax(0)] = 1
    m = gt[arg]
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    gw = m[:, 2] - m[:, 0] + 1
    gh = m[:, 3] - m[:, 1] + 1
    deltas = np.stack([
        (m[:, 0] + gw / 2 - anchors[:, 0] - aw / 2) / aw,
        (m[:, 1] + gh / 2 - anchors[:, 1] - ah / 2) / ah,
        np.log(gw / aw), np.log(gh / ah)], 1)
    return {"TargetLabel": [lab.astype(np.int32).reshape(-1, 1)],
            "TargetBBox": [deltas.astype(np.float32)]}


exp_("rpn_target_assign", _rpn_target_assign_ref)
# padded contract: each X row repeats Y_rows/X_rows times
exp_("sequence_expand", lambda i, a: {"Out": [np.repeat(
    i["X"], i["Y"].shape[0] // i["X"].shape[0], axis=0)]})


def _seq_topk_avg_ref(i, a):
    x = i["X"]
    outs = []
    for k in a["topks"]:
        kk = min(k, x.shape[-1])
        v = np.sort(x, axis=-1)[..., ::-1][..., :kk]
        outs.append(v.mean(-1))
    return {"Out": [np.concatenate(outs, -1).astype(np.float32)]}


exp_("sequence_topk_avg_pooling", _seq_topk_avg_ref)


def _attention_lstm_ref(i, a):
    # attention_lstm_op.cc:355-405
    x = i["X"].astype(np.float64)
    c = i["C0"].astype(np.float64)
    h = i.get("H0", np.zeros_like(c)).astype(np.float64)
    aw = i["AttentionWeight"].astype(np.float64)
    lw = i["LSTMWeight"].astype(np.float64)
    lb = i["LSTMBias"].reshape(-1).astype(np.float64)
    b, t, m = x.shape
    d = c.shape[-1]
    atten_x = (x @ aw[:m]).squeeze(-1)
    hs = np.zeros((b, t, d))
    for k in range(t):
        e = np.maximum(atten_x + c @ aw[m:], 0.0)
        ex = np.exp(e - e.max(-1, keepdims=True))
        att = ex / ex.sum(-1, keepdims=True)
        ctxv = np.einsum("bt,btm->bm", att, x)
        g = h @ lw[:d] + ctxv @ lw[d:] + lb
        f, ig, o, cand = np.split(g, 4, axis=-1)
        c = _sig(f) * c + _sig(ig) * np.tanh(cand)
        h = _sig(o) * np.tanh(c)
        hs[:, k] = h
    return {"Hidden": [hs.astype(np.float32)],
            "Cell": [c.astype(np.float32)]}


exp_("attention_lstm", _attention_lstm_ref)


def _cudnn_lstm_ref(i, a):
    # cudnn canonical single-layer LSTM: gates [i, f, g, o],
    # c = f·c + i·tanh(g), h = o·tanh(c); weight blob Wih|Whh|bih|bhh
    x = i["Input"].astype(np.float64)       # [T, B, in]
    h = i["InitH"][0].astype(np.float64)
    c = i["InitC"][0].astype(np.float64)
    w = i["W"].reshape(-1).astype(np.float64)
    hid = a["hidden_size"]
    t, b, insz = x.shape
    o = 0
    wih = w[o:o + 4 * hid * insz].reshape(4 * hid, insz)
    o += 4 * hid * insz
    whh = w[o:o + 4 * hid * hid].reshape(4 * hid, hid)
    o += 4 * hid * hid
    bih = w[o:o + 4 * hid]
    o += 4 * hid
    bhh = w[o:o + 4 * hid]
    ys = np.zeros((t, b, hid))
    for k in range(t):
        g = x[k] @ wih.T + h @ whh.T + bih + bhh
        ig, f, gg, og = np.split(g, 4, axis=-1)
        c = _sig(f) * c + _sig(ig) * np.tanh(gg)
        h = _sig(og) * np.tanh(c)
        ys[k] = h
    return {"Out": [ys.astype(np.float32)],
            "LastH": [h[None].astype(np.float32)],
            "LastC": [c[None].astype(np.float32)]}


exp_("cudnn_lstm", _cudnn_lstm_ref)


def _cudnn_gru_ref(i, a):
    # cudnn canonical GRU: r/z/n gates, n = tanh(xn + r·(h@Whn + bhn)),
    # h = (1−z)·n + z·h
    x = i["Input"].astype(np.float64)
    h = i["InitH"][0].astype(np.float64)
    w = i["W"].reshape(-1).astype(np.float64)
    hid = a["hidden_size"]
    t, b, insz = x.shape
    o = 0
    wih = w[o:o + 3 * hid * insz].reshape(3 * hid, insz)
    o += 3 * hid * insz
    whh = w[o:o + 3 * hid * hid].reshape(3 * hid, hid)
    o += 3 * hid * hid
    bih = w[o:o + 3 * hid]
    o += 3 * hid
    bhh = w[o:o + 3 * hid]
    ys = np.zeros((t, b, hid))
    for k in range(t):
        gx = x[k] @ wih.T + bih
        gh = h @ whh.T + bhh
        xr, xz, xn = np.split(gx, 3, axis=-1)
        hr, hz, hn = np.split(gh, 3, axis=-1)
        r = _sig(xr + hr)
        z = _sig(xz + hz)
        n = np.tanh(xn + r * hn)
        h = (1 - z) * n + z * h
        ys[k] = h
    return {"Out": [ys.astype(np.float32)],
            "LastH": [h[None].astype(np.float32)]}


exp_("cudnn_gru", _cudnn_gru_ref)


def _avg_accumulates_ref(i, a):
    # average_accumulates_op.h:43-110
    p = i["Param"].astype(np.float64)
    s1 = i["InSum1"].astype(np.float64)
    s2 = i["InSum2"].astype(np.float64)
    s3 = i["InSum3"].astype(np.float64)
    na = int(i["InNumAccumulates"].reshape(-1)[0]) + 1
    ona = int(i["InOldNumAccumulates"].reshape(-1)[0]) \
        if "InOldNumAccumulates" in i else 0
    nu = (int(i["InNumUpdates"].reshape(-1)[0]) + 1
          if "InNumUpdates" in i else na)
    # aliased-accumulator semantics: branches read the updated sum1
    o1, o2, o3 = s1 + p, s2.copy(), s3.copy()
    if nu % 16384 == 0:
        o2 = o2 + o1
        o1 = np.zeros_like(o1)
    if na >= a["min_average_window"] and na >= min(
            a["max_average_window"], int(nu * a["average_window"])):
        o3 = o1 + o2
        o1 = np.zeros_like(o1)
        o2 = np.zeros_like(o2)
        ona, na = na, 0
    return {"OutSum1": [o1.astype(np.float32)],
            "OutSum2": [o2.astype(np.float32)],
            "OutSum3": [o3.astype(np.float32)],
            "OutNumAccumulates": [np.asarray([na], np.int64)],
            "OutOldNumAccumulates": [np.asarray([ona], np.int64)],
            "OutNumUpdates": [np.asarray([nu], np.int64)]}


exp_("average_accumulates", _avg_accumulates_ref)


def _max_pool3d_index_ref(i, a):
    x = i["X"]
    kd, kh, kw = a["ksize"]
    sd, sh, sw = a["strides"]
    n, c, d, h, w = x.shape
    od, oh, ow = ((d - kd) // sd + 1, (h - kh) // sh + 1,
                  (w - kw) // sw + 1)
    out = np.zeros((n, c, od, oh, ow), x.dtype)
    idx = np.zeros((n, c, od, oh, ow), np.int64)
    for pi in range(od):
        for pj in range(oh):
            for pk in range(ow):
                win = x[:, :, pi * sd:pi * sd + kd,
                        pj * sh:pj * sh + kh, pk * sw:pk * sw + kw]
                flat = win.reshape(n, c, -1)
                am = flat.argmax(-1)
                out[:, :, pi, pj, pk] = flat.max(-1)
                dd = pi * sd + am // (kh * kw)
                hh = pj * sh + (am % (kh * kw)) // kw
                ww = pk * sw + am % kw
                idx[:, :, pi, pj, pk] = (dd * h + hh) * w + ww
    return {"Out": [out], "Mask": [idx]}


exp_("max_pool3d_with_index", _max_pool3d_index_ref)


def _dgc_ref(i, a):
    # dgc_op.h: U = m·U + g; V += U; threshold at the k-th largest |V|
    u = a["m"] * i["U"] + i["Grad"]
    v = i["V"] + u
    ratio = 1.0 - a["sparsity"][-1]
    k = max(int(v.size * ratio), 1)
    thr = np.sort(np.abs(v).reshape(-1))[::-1][k - 1]
    mask = np.abs(v) >= thr
    enc = np.where(mask, v, 0.0)
    return {"EncodeGrad": [enc.astype(np.float32)],
            "U_out": [np.where(mask, 0.0, u).astype(np.float32)],
            "V_out": [np.where(mask, 0.0, v).astype(np.float32)]}


exp_("dgc", _dgc_ref)


def _trilinear_interp_ref(i, a):
    x = i["X"].astype(np.float64)
    n, c, d, h, w = x.shape
    od, oh, ow = a["out_d"], a["out_h"], a["out_w"]
    align = a.get("align_corners", True)
    mode = a.get("align_mode", 1)

    def src(oi, dim, odim):
        if align:
            return oi * (dim - 1) / max(odim - 1, 1)
        if mode == 0:
            return max((oi + 0.5) * dim / odim - 0.5, 0.0)
        return oi * dim / odim

    out = np.zeros((n, c, od, oh, ow))
    for zi in range(od):
        for yi in range(oh):
            for xi in range(ow):
                fz = src(zi, d, od)
                fy = src(yi, h, oh)
                fx = src(xi, w, ow)
                z0, y0, x0 = int(fz), int(fy), int(fx)
                z1 = min(z0 + 1, d - 1)
                y1 = min(y0 + 1, h - 1)
                x1 = min(x0 + 1, w - 1)
                dz, dy, dx = fz - z0, fy - y0, fx - x0
                acc = 0.0
                for (za, wz) in ((z0, 1 - dz), (z1, dz)):
                    for (ya, wy) in ((y0, 1 - dy), (y1, dy)):
                        for (xa, wx) in ((x0, 1 - dx), (x1, dx)):
                            acc = acc + wz * wy * wx * x[:, :, za, ya, xa]
                out[:, :, zi, yi, xi] = acc
    return {"Out": [out.astype(np.float32)]}


exp_("trilinear_interp", _trilinear_interp_ref)
# padded time-axis concat of equal-batch sequences
exp_("sequence_concat", lambda i, a: {"Out": [np.concatenate(
    [i["sqc_a"], i["sqc_b"]], axis=1)]})


def _sequence_scatter_ref(i, a):
    out = i["X"].astype(np.float64).copy()
    ids, upd = i["Ids"], i["Updates"]
    for r in range(out.shape[0]):
        for k in range(ids.shape[1]):
            out[r, ids[r, k]] += upd[r, k]
    return {"Out": [out.astype(np.float32)]}


exp_("sequence_scatter", _sequence_scatter_ref)
# documented fused global-dice contract: 1 − 2Σxl/(Σx+Σl+1e-5)
exp_("dice_loss", lambda i, a: {"Out": [np.float32(
    1 - 2 * (i["X"] * i["Label"]).sum()
    / ((i["X"].sum() + i["Label"].sum()) + 1e-5))]})
exp_("fake_channel_wise_dequantize_max_abs", lambda i, a: {"Out": [
    i["X"] * i["Scales"].reshape(-1, 1)
    / float((1 << (a["quant_bits"][0] - 1)) - 1)]})


def _fusion_seqexpand_concat_fc_ref(i, a):
    # fusion_seqexpand_concat_fc_op: non-reference inputs broadcast
    # over the reference sequence's time axis, concat, fc, activation
    ref = i["fsecf_a"]
    b, t, d = ref.shape
    other = np.broadcast_to(i["fsecf_b"][:, None, :],
                            (b, t, i["fsecf_b"].shape[-1]))
    cat = np.concatenate([ref, other], axis=-1)
    out = cat @ i["FCWeight"]
    if a.get("fc_activation", "relu") == "relu":
        out = np.maximum(out, 0.0)
    return {"Out": [out.astype(np.float32)]}


exp_("fusion_seqexpand_concat_fc", _fusion_seqexpand_concat_fc_ref)


def _hsigmoid_ref(i, a):
    # matrix_bit_code.h SimpleCode (:109-118): code = label+num_classes,
    # node j = (code >> (j+1)) − 1, bit j = code & (1<<j);
    # loss = Σ softplus(pre) − bit·pre over the path
    x, w = i["X"].astype(np.float64), i["W"].astype(np.float64)
    lbl = i["Label"].reshape(-1)
    ncls = a["num_classes"]
    bias = i["Bias"].reshape(-1).astype(np.float64) if "Bias" in i \
        else None
    out = np.zeros((len(lbl), 1))
    for r, c in enumerate(lbl):
        code = int(c) + ncls
        for bit in range(code.bit_length() - 1):
            node = (code >> (bit + 1)) - 1
            b = (code >> bit) & 1
            pre = x[r] @ w[node % w.shape[0]]
            if bias is not None:
                pre += bias[node % bias.shape[0]]
            out[r, 0] += np.log1p(np.exp(pre)) - b * pre
    return {"Out": [out.astype(np.float32)]}


exp_("hierarchical_sigmoid", _hsigmoid_ref)


def _deformable_psroi_ref(i, a):
    # documented TPU sampling contract (straggler_ops.py): bin (pi, pj)
    # reads channel group pi·pw+pj, origin y1 + pi·bin_h shifted by
    # Trans·trans_std·span, averaged over an (s+0.5)/s bilinear grid
    x, rois, tr = i["Input"], i["ROIs"], i["Trans"]
    ph, pw = a["pooled_height"], a["pooled_width"]
    oc = a["output_dim"]
    scale = a["spatial_scale"]
    std = a["trans_std"]
    samp = a["sample_per_part"]
    n, c, h, w = x.shape
    out = np.zeros((rois.shape[0], oc, ph, pw))

    def bil(feat, y, xx):
        y0, x0 = int(np.floor(y)), int(np.floor(xx))
        v = 0.0
        for yy in (y0, y0 + 1):
            for xc in (x0, x0 + 1):
                if 0 <= yy < h and 0 <= xc < w:
                    v += (1 - abs(y - yy)) * (1 - abs(xx - xc)) \
                        * feat[yy, xc]
        return v

    for r in range(rois.shape[0]):
        x1, y1, x2, y2 = rois[r] * scale
        rw, rh = max(x2 - x1, 0.1), max(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph
        for pi in range(ph):
            for pj in range(pw):
                oy = y1 + pi * bh + tr[r, 1, pi, pj] * std * rh
                ox = x1 + pj * bw + tr[r, 0, pi, pj] * std * rw
                cix_base = pi * pw + pj
                for co in range(oc):
                    cix = co * ph * pw + cix_base
                    acc = 0.0
                    for si in range(samp):
                        for sj in range(samp):
                            acc += bil(x[0, cix],
                                       oy + (si + 0.5) / samp * bh,
                                       ox + (sj + 0.5) / samp * bw)
                    out[r, co, pi, pj] = acc / (samp * samp)
    return {"Output": [out.astype(np.float32)]}


exp_("deformable_psroi_pooling", _deformable_psroi_ref)


def _chunk_eval_ref(i, a):
    # chunk_eval_op.h:41-78 GetSegments with the IOB table
    # (num_tag_types=2, tag 0=B / 1=I, O encoded as type==num_chunk_types)
    nt = a["num_chunk_types"]

    def segments(seq):
        segs = []
        start = ptype = None
        for pos, v in enumerate(int(x) for x in seq):
            tag, typ = v % 2, v // 2
            if typ >= nt:  # O
                if start is not None:
                    segs.append((start, pos, ptype))
                start = None
                ptype = None
                continue
            if tag == 0 or start is None or typ != ptype:
                if start is not None:
                    segs.append((start, pos, ptype))
                start = pos
            ptype = typ
        if start is not None:
            segs.append((start, len(seq), ptype))
        return set(segs)

    inf = i["Inference"].reshape(i["Inference"].shape[0], -1)
    lab = i["Label"].reshape(i["Label"].shape[0], -1)
    exc = set(a.get("excluded_chunk_types", []) or [])
    ic = lc = cc = 0
    for a_, b_ in zip(inf, lab):
        sa = {s for s in segments(a_) if s[2] not in exc}
        sb = {s for s in segments(b_) if s[2] not in exc}
        ic += len(sa)
        lc += len(sb)
        cc += len(sa & sb)
    p = cc / ic if ic else 0.0
    r = cc / lc if lc else 0.0
    f = 2 * p * r / (p + r) if p + r else 0.0
    mk = lambda v, d: np.asarray([v], d)  # noqa: E731
    return {"Precision": [mk(p, np.float32)],
            "Recall": [mk(r, np.float32)],
            "F1-Score": [mk(f, np.float32)],
            "NumInferChunks": [mk(ic, np.int32)],
            "NumLabelChunks": [mk(lc, np.int32)],
            "NumCorrectChunks": [mk(cc, np.int32)]}


exp_("chunk_eval", _chunk_eval_ref)


def _detection_map_ref(i, a):
    # detection_map_op.h:308-475 re-derived: greedy score-ranked
    # matching (strict overlap > threshold, ClipBBox on predictions,
    # one GT consumed per match), then AP via the recall-step identity:
    # integral AP == sum over tp hits of precision_at_hit / npos (each
    # tp advances recall by exactly 1/npos, fps advance it by 0), which
    # is an algebraically different route than the reference's
    # prev_recall loop; 11point takes max precision at recall >= j/10.
    det = i["DetectRes"].reshape(-1, 6)
    lab = i["Label"].reshape(-1, i["Label"].shape[-1])
    thr = a.get("overlap_threshold", 0.5)
    ap_type = a.get("ap_type", "integral")
    eval_diff = a.get("evaluate_difficult", True)
    if lab.shape[-1] == 6:
        gcls, gdiff, gbox = lab[:, 0], lab[:, 1] != 0, lab[:, 2:6]
    else:
        gcls, gbox = lab[:, 0], lab[:, 1:5]
        gdiff = np.zeros(len(lab), bool)

    def iou(b, g):
        ix = max(0.0, min(b[2], g[2]) - max(b[0], g[0]))
        iy = max(0.0, min(b[3], g[3]) - max(b[1], g[1]))
        inter = ix * iy
        ab = (b[2] - b[0]) * (b[3] - b[1])
        ag = (g[2] - g[0]) * (g[3] - g[1])
        return inter / max(ab + ag - inter, 1e-10)

    aps = []
    for cls in sorted(set(gcls.tolist())):
        sel = gcls == cls
        gts, diff = gbox[sel], gdiff[sel]
        npos = len(gts) if eval_diff else int((~diff).sum())
        d = det[det[:, 0] == cls]
        if npos == 0 or len(d) == 0:
            continue
        d = d[np.argsort(-d[:, 1], kind="stable")]
        used = [False] * len(gts)
        flags = []  # +1 tp / 0 fp / None dropped-difficult
        for row in d:
            b = np.clip(row[2:6], 0.0, 1.0)
            ious = [iou(b, g) for g in gts]
            j = int(np.argmax(ious))
            if ious[j] > thr:
                if not eval_diff and diff[j]:
                    continue
                if used[j]:
                    flags.append(0)
                else:
                    used[j] = True
                    flags.append(1)
            else:
                flags.append(0)
        if not flags:
            continue
        tp_run = 0
        ap = 0.0
        if ap_type == "11point":
            precs, recs = [], []
            for k, fl in enumerate(flags):
                tp_run += fl
                precs.append(tp_run / (k + 1))
                recs.append(tp_run / npos)
            for j in range(11):
                t = j / 10.0
                best = max((p for p, r in zip(precs, recs) if r >= t),
                           default=0.0)
                ap += best / 11.0
        else:
            for k, fl in enumerate(flags):
                tp_run += fl
                if fl:
                    ap += (tp_run / (k + 1)) / npos
        aps.append(ap)
    m = float(np.mean(aps)) if aps else 0.0
    return {"MAP": [np.asarray([m], np.float32)]}


exp_("detection_map", _detection_map_ref)


def _hash_ref(i, a):
    # XXH64 re-derived from the public spec, VECTORIZED over rows in
    # np.uint64 wraparound arithmetic — an independent implementation
    # route from the op's scalar python-int version
    # (hash_op.h:60-66: XXH64(row bytes, seed=ihash) % mod_by)
    u = np.uint64
    P1, P2 = u(0x9E3779B185EBCA87), u(0xC2B2AE3D27D4EB4F)
    P3, P4 = u(0x165667B19E3779F9), u(0x85EBCA77C2B2AE63)
    P5 = u(0x27D4EB2F165667C5)

    def rotl(v, r):
        return (v << u(r)) | (v >> u(64 - r))

    def rnd(acc, lane):
        return rotl(acc + lane * P2, u(31)) * P1

    def rows_hash(lanes, seed):
        n_rows, n_lanes = lanes.shape
        nbytes = u(8 * n_lanes)
        k = 0
        if n_lanes >= 4:
            v = [np.full(n_rows, u(seed) + P1 + P2, np.uint64),
                 np.full(n_rows, u(seed) + P2, np.uint64),
                 np.full(n_rows, u(seed), np.uint64),
                 np.full(n_rows, u(seed) - P1, np.uint64)]
            while k + 4 <= n_lanes:
                for j in range(4):
                    v[j] = rnd(v[j], lanes[:, k + j])
                k += 4
            h = rotl(v[0], u(1)) + rotl(v[1], u(7)) + \
                rotl(v[2], u(12)) + rotl(v[3], u(18))
            for vj in v:
                h = (h ^ rnd(u(0), vj)) * P1 + P4
        else:
            h = np.full(n_rows, u(seed) + P5, np.uint64)
        h = h + nbytes
        while k < n_lanes:
            h = rotl(h ^ rnd(u(0), lanes[:, k]), u(27)) * P1 + P4
            k += 1
        h ^= h >> u(33)
        h *= P2
        h ^= h >> u(29)
        h *= P3
        h ^= h >> u(32)
        return h

    x = i["X"]
    nh, mod = a["num_hash"], a["mod_by"]
    lanes = np.ascontiguousarray(
        x.reshape(-1, x.shape[-1]).astype("<i8")).view(np.uint64)
    with np.errstate(over="ignore"):
        cols = [(rows_hash(lanes, s) % u(mod)).astype(np.int64)
                for s in range(nh)]
    out = np.stack(cols, axis=-1).reshape(x.shape[:-1] + (nh, 1))
    return {"Out": [out.astype(np.int32)]}


exp_("hash", _hash_ref)


def _inception_ref(i, a):
    # the documented branch graph over fusion_conv_inception_op.cc's
    # InferShape channel bookkeeping, rebuilt from the conv2d ref
    x = i["Input"]
    f = [i["inc_f0"], i["inc_f1"], i["inc_f2"], i["inc_f3"]]
    bs = [i["inc_b0"], i["inc_b1"], i["inc_b2"], i["inc_b3"]]

    def conv(inp, w, bias, k):
        pad = (k - 1) // 2
        y = _conv2d_np(inp, w, [1, 1], [pad, pad])
        return np.maximum(y + bias.reshape(1, -1, 1, 1), 0.0)

    # 3x3/1 avg pool, pad 1, EXCLUSIVE counting (pad cells not counted)
    n, c, h, w = x.shape
    pooled = np.zeros_like(x)
    for pi in range(h):
        for pj in range(w):
            y0, y1 = max(pi - 1, 0), min(pi + 2, h)
            x0, x1 = max(pj - 1, 0), min(pj + 2, w)
            pooled[:, :, pi, pj] = x[:, :, y0:y1, x0:x1].mean((2, 3))
    c2i, c3i = f[2].shape[1], f[3].shape[1]
    b_a = conv(pooled, f[0], bs[0], f[0].shape[2])
    t = conv(x, f[1], bs[1], f[1].shape[2])
    keep1 = t.shape[1] - 2 * c2i
    r1 = t[:, :keep1]
    u_a = conv(t[:, keep1:keep1 + c2i], f[2], bs[2], f[2].shape[2])
    u_b = conv(t[:, keep1 + c2i:], f[2], bs[2], f[2].shape[2])
    keep2 = u_a.shape[1] - c3i
    b_d = conv(u_b[:, keep2:], f[3], bs[3], f[3].shape[2])
    out = np.concatenate([b_a, r1, u_a[:, :keep2], b_d], axis=1)
    return {"Output": [out.astype(np.float32)]}


exp_("conv2d_inception_fusion", _inception_ref)


def _roi_perspective_ref(i, a):
    # roi_perspective_transform_op.cc:110-175 homography + bilinear
    x = i["X"].astype(np.float64)
    rois = i["ROIs"]
    oh, ow = a["transformed_height"], a["transformed_width"]
    scale = a.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    outs = np.zeros((rois.shape[0], c, oh, ow))
    for r in range(rois.shape[0]):
        qx = rois[r, 0::2].astype(np.float64) * scale
        qy = rois[r, 1::2].astype(np.float64) * scale
        l1 = np.hypot(qx[0] - qx[1], qy[0] - qy[1])
        l2 = np.hypot(qx[1] - qx[2], qy[1] - qy[2])
        l3 = np.hypot(qx[2] - qx[3], qy[2] - qy[3])
        l4 = np.hypot(qx[3] - qx[0], qy[3] - qy[0])
        est_h, est_w = (l2 + l4) / 2, (l1 + l3) / 2
        nh = max(2, oh)
        nw = max(2, min(int(round(est_w * (nh - 1) / est_h)) + 1, ow))
        dx1, dx2 = qx[1] - qx[2], qx[3] - qx[2]
        dx3 = qx[0] - qx[1] + qx[2] - qx[3]
        dy1, dy2 = qy[1] - qy[2], qy[3] - qy[2]
        dy3 = qy[0] - qy[1] + qy[2] - qy[3]
        den = dx1 * dy2 - dx2 * dy1 + 1e-5
        m = np.zeros(9)
        m[6] = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
        m[7] = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
        m[8] = 1.0
        m[3] = (qy[1] - qy[0] + m[6] * (nw - 1) * qy[1]) / (nw - 1)
        m[4] = (qy[3] - qy[0] + m[7] * (nh - 1) * qy[3]) / (nh - 1)
        m[5] = qy[0]
        m[0] = (qx[1] - qx[0] + m[6] * (nw - 1) * qx[1]) / (nw - 1)
        m[1] = (qx[3] - qx[0] + m[7] * (nh - 1) * qx[3]) / (nh - 1)
        m[2] = qx[0]
        for ii in range(oh):
            for jj in range(ow):
                u = m[0] * jj + m[1] * ii + m[2]
                v = m[3] * jj + m[4] * ii + m[5]
                ww = m[6] * jj + m[7] * ii + m[8]
                gx, gy = u / ww, v / ww
                if (jj > nw - 1 or gx < -0.5 or gx > w - 0.5
                        or gy < -0.5 or gy > h - 0.5):
                    continue
                x0 = min(max(int(np.floor(gx)), 0), w - 1)
                y0 = min(max(int(np.floor(gy)), 0), h - 1)
                x1, y1 = min(x0 + 1, w - 1), min(y0 + 1, h - 1)
                wx = min(max(gx - x0, 0.0), 1.0)
                wy = min(max(gy - y0, 0.0), 1.0)
                outs[r, :, ii, jj] = (
                    x[0, :, y0, x0] * (1 - wx) * (1 - wy)
                    + x[0, :, y0, x1] * wx * (1 - wy)
                    + x[0, :, y1, x0] * (1 - wx) * wy
                    + x[0, :, y1, x1] * wx * wy)
    return {"Out": [outs.astype(np.float32)]}


exp_("roi_perspective_transform", _roi_perspective_ref)

# ---------------------------------------------------------------------------
# ops intentionally left without an independent numpy reference —
# recorded so OP_TEST_MATRIX distinguishes "cannot witness" from
# "not yet witnessed"
# ---------------------------------------------------------------------------
grads("box_clip", "Input")          # piecewise-linear clamp
grads("target_assign", "X")         # gather of matched rows
# box_decoder_and_assign: numeric deltas cross the dw/dh upper-clip
# kink; bucketed under discrete assigners below

# why the remaining pass-ops carry no numeric grad check — grouped so
# OP_TEST_MATRIX can state it per op
_NOGRAD_GROUPS = {
    "optimizer state-update rule, not an autodiff surface": [
        "sgd", "momentum", "adam", "adamw", "adamax", "adagrad",
        "adadelta", "decayed_adagrad", "rmsprop", "ftrl", "lamb",
        "lars_momentum", "proximal_gd", "proximal_adagrad", "dpsgd",
        "dgc_momentum", "average_accumulates", "dgc",
        "dgc_clip_by_norm"],
    "integer/bool output": [
        "equal", "not_equal", "less_than", "less_equal",
        "greater_than", "greater_equal", "logical_and", "logical_or",
        "logical_xor", "logical_not", "arg_max", "arg_min",
        "reduce_all", "reduce_any", "one_hot", "one_hot_v2",
        "shard_index", "sequence_mask", "sequence_enumerate",
        "sequence_erase", "is_empty", "isfinite", "has_inf", "has_nan",
        "shape", "size", "where", "where_index", "unique",
        "unique_with_counts", "edit_distance", "ctc_align",
        "crf_decoding", "hash", "elementwise_mod",
        "elementwise_floordiv", "bipartite_match", "filter_by_instag",
        "lod_reset", "increment", "randint"],
    "stochastic op": [
        "uniform_random", "gaussian_random",
        "truncated_gaussian_random", "uniform_random_batch_size_like",
        "gaussian_random_batch_size_like", "random_crop", "sampling_id",
        "nce", "sample_logits"],
    "constant/generator output": [
        "fill", "fill_constant", "fill_any_like", "fill_zeros_like",
        "fill_zeros_like2", "fill_constant_batch_size_like", "eye",
        "diag", "linspace", "range", "assign_value",
        "anchor_generator", "prior_box", "density_prior_box"],
    "STE gradient is intentionally not the numeric derivative": [
        "fake_quantize_abs_max", "fake_channel_wise_quantize_abs_max",
        "fake_quantize_moving_average_abs_max",
        "fake_quantize_dequantize_moving_average_abs_max",
        "fake_quantize_range_abs_max", "fake_dequantize_max_abs",
        "fake_channel_wise_dequantize_max_abs",
        "moving_average_abs_max_scale", "quantize", "dequantize",
        "requantize"],
    "reference defines a custom non-derivative gradient": ["cvm"],
    "discrete assigner/selector (reference registers no grad)": [
        "mine_hard_examples", "rpn_target_assign",
        "retinanet_target_assign", "retinanet_detection_output",
        "multiclass_nms", "multiclass_nms2", "generate_proposals",
        "generate_proposal_labels", "generate_mask_labels",
        "collect_fpn_proposals", "distribute_fpn_proposals",
        "polygon_box_transform", "iou_similarity", "similarity_focus",
        "yolo_box", "roi_perspective_transform", "roi_pool",
        "max_pool3d_with_index", "spp", "pull_box_sparse",
        "box_decoder_and_assign"],
    "metric accumulator": [
        "accuracy", "auc", "precision_recall", "mean_iou",
        "chunk_eval", "detection_map", "positive_negative_pair"],
    "relu kink at 0 flips under numeric deltas; branch convs are "
    "grad-checked via conv2d/conv2d_fusion": [
        "conv2d_inception_fusion"],
    "log(pool+1) needs positivity the numeric perturbation breaks "
    "at the margin; pool+cvm legs grad-checked individually": [
        "fusion_seqpool_cvm_concat"],
}
NOGRAD_REASONS = {}
for _reason, _ops in _NOGRAD_GROUPS.items():
    for _o in _ops:
        NOGRAD_REASONS[_o] = _reason

NOREF_REASONS = {
    "uniform_random": "stochastic output; moment checks only",
    "gaussian_random": "stochastic output; moment checks only",
    "truncated_gaussian_random": "stochastic output",
    "uniform_random_batch_size_like": "stochastic output",
    "gaussian_random_batch_size_like": "stochastic output",
    "randint": "stochastic output",
    "random_crop": "stochastic crop origin",
    "sampling_id": "stochastic sampling",
    "dpsgd": "stochastic DP noise",
    "nce": "stochastic negative sampling",
    "sample_logits": "stochastic candidate sampling",
    "pull_box_sparse": "host-side BoxPS table service; roundtrip "
                       "covered in tests/test_straggler_ops.py",
    "generate_proposal_labels": "stochastic fg/bg subsampling in the "
                                "reference; deterministic redesign "
                                "covered by dedicated tests",
    "retinanet_target_assign": "delegates to the witnessed "
                               "rpn_target_assign contract",
}


exp_("quantize", lambda i, a: {"Output": [np.clip(
    np.round(i["Input"] * a.get("Scale", 1.0)), -128, 127)
    .astype(np.int8)]})
exp_("dequantize", lambda i, a: {"Output": [
    i["Input"].astype(np.float32) / a.get("Scale", 1.0)]})
exp_("requantize", lambda i, a: {"Output": [np.clip(
    np.round(i["Input"] * (a["Scale_out"] / a["Scale_in"])), -128, 127)
    .astype(np.int8)]})
# polygon_box_transform: whole op is marked nondiff (assigner-shaped);
# grid_sampler Grid grad: numeric diff crosses bilinear cell boundaries
grads("top_k", "X")           # gather-of-max: exact as long as no ties
grads("argsort", "X")         # permutation gradient
grads("lod_reset", "X")
grads("spectral_norm", "Weight")
grads("filter_by_instag", "Ins")
grads("sequence_topk_avg_pooling", "X")
grads("fusion_seqexpand_concat_fc", "X")

"""distributions / reader decorators / dataset corpora tests."""
import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.layers.distributions import (Categorical,
                                             MultivariateNormalDiag, Normal,
                                             Uniform)
from paddle_tpu import reader_decorator as rd


def _run(build):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        return [np.asarray(v) for v in
                exe.run(main, fetch_list=list(fetches))]


def test_normal_distribution_math():
    def build():
        n = Normal(0.0, 2.0)
        m = Normal(1.0, 1.0)
        s = n.sample([512, 1], seed=3)
        val = layers.assign(np.asarray([1.0], np.float32))
        return [n.entropy(), n.log_prob(val), n.kl_divergence(m), s]

    ent, lp, kl, s = _run(build)
    sigma = 2.0
    np.testing.assert_allclose(
        ent, 0.5 + 0.5 * math.log(2 * math.pi) + math.log(sigma),
        rtol=1e-5)
    want_lp = -0.5 * (1.0 / sigma**2) - math.log(sigma) \
        - 0.5 * math.log(2 * math.pi)
    np.testing.assert_allclose(lp, want_lp, rtol=1e-5)
    # KL(N(0,2) || N(1,1)) = log(1/2) + (4 + 1)/2 - 1/2
    np.testing.assert_allclose(kl, math.log(0.5) + 2.5 - 0.5, rtol=1e-5)
    assert abs(float(s.mean())) < 0.3 and abs(float(s.std()) - 2.0) < 0.3


def test_uniform_and_categorical():
    def build():
        u = Uniform(-1.0, 3.0)
        c = Categorical(layers.assign(
            np.asarray([[0.0, 0.0, 0.0, 0.0]], np.float32)))
        c2 = Categorical(layers.assign(
            np.asarray([[1.0, 0.0, 0.0, 0.0]], np.float32)))
        return [u.entropy(), u.sample([256, 1], seed=5), c.entropy(),
                c.kl_divergence(c2)]

    ent, s, cent, ckl = _run(build)
    np.testing.assert_allclose(ent, math.log(4.0), rtol=1e-5)
    assert -1.0 <= float(s.min()) and float(s.max()) <= 3.0
    np.testing.assert_allclose(cent, math.log(4.0), rtol=1e-4)
    assert float(ckl) > 0


def test_mvn_diag_entropy():
    def build():
        d = MultivariateNormalDiag(
            layers.assign(np.zeros(3, np.float32)),
            layers.assign(np.ones(3, np.float32) * 2.0))
        return [d.entropy()]

    ent, = _run(build)
    want = 0.5 * 3 * (1 + math.log(2 * math.pi)) + 3 * math.log(2.0)
    np.testing.assert_allclose(ent, want, rtol=1e-5)


# ---------------------------------------------------------------------------

def test_reader_decorators_compose():
    def r1():
        return iter(range(10))

    def r2():
        return iter(range(10, 20))

    assert list(rd.chain(r1, r2)()) == list(range(20))
    assert list(rd.firstn(r1, 3)()) == [0, 1, 2]
    assert list(rd.map_readers(lambda a, b: a + b, r1, r2)()) == \
        [i + j for i, j in zip(range(10), range(10, 20))]
    assert sorted(rd.shuffle(r1, 4)()) == list(range(10))
    assert list(rd.buffered(r1, 2)()) == list(range(10))
    assert list(rd.compose(r1, r2)()) == list(zip(range(10),
                                                 range(10, 20)))
    got = list(rd.xmap_readers(lambda x: x * 2, r1, 3, 4, order=True)())
    assert got == [2 * i for i in range(10)]
    bs = list(rd.batch(r1, 4)())
    assert bs == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    bs = list(rd.batch(r1, 4, drop_last=True)())
    assert len(bs) == 2


def test_compose_not_aligned():
    def r1():
        return iter(range(3))

    def r2():
        return iter(range(5))

    with pytest.raises(rd.ComposeNotAligned):
        list(rd.compose(r1, r2)())


# ---------------------------------------------------------------------------

def test_dataset_shapes():
    from paddle_tpu.datasets import cifar, imdb, mnist, movielens, \
        uci_housing, wmt16

    img, label = next(mnist.train()())
    assert img.shape == (784,) and img.dtype == np.float32
    assert 0 <= label < 10

    img, label = next(cifar.train10()())
    assert img.shape == (3072,) and 0 <= label < 10

    x, y = next(uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)

    ids, label = next(imdb.train()())
    assert isinstance(ids, list) and label in (0, 1)

    sample = next(movielens.train()())
    assert len(sample) == 8

    src, trg_in, trg_next = next(wmt16.train()())
    assert trg_in[0] == wmt16.BOS and trg_next[-1] == wmt16.EOS
    assert len(trg_in) == len(trg_next)


def test_mnist_trains_logistic_regression():
    """The synthetic corpus is learnable (datasets/__init__.py contract)."""
    from paddle_tpu.datasets import mnist

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[784], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        pred = layers.fc(img, size=10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        fluid.optimizer.Adam(0.01).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        reader = fluid.io.batch(mnist.train(), 64, drop_last=True)
        last_acc = 0.0
        for i, batch in enumerate(reader()):
            xs = np.stack([b[0] for b in batch])
            ys = np.asarray([[b[1]] for b in batch], np.int64)
            _, a = exe.run(main, feed={"img": xs, "label": ys},
                           fetch_list=[loss, acc])
            last_acc = float(np.asarray(a).reshape(-1)[0])
            if i >= 40:
                break
    assert last_acc > 0.7, f"synthetic mnist should be learnable, acc={last_acc}"


def test_dataloader_from_dataset(tmp_path):
    """DataLoader.from_dataset iterates Dataset batches as feed dicts
    (reference DatasetLoader, one-process-per-host model)."""
    import paddle_tpu as fluid

    f = tmp_path / "part-0.txt"
    rng = np.random.RandomState(3)
    lines = []
    for _ in range(10):
        feat = " ".join(str(x) for x in rng.rand(4).round(3))
        # MultiSlot format: per slot `<n> <v1> ... <vn>`
        lines.append(f"4 {feat} 1 {rng.randint(0, 2)}\n")
    f.write_text("".join(lines))

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = fluid.layers.data("dlx", shape=[4], dtype="float32")
        y = fluid.layers.data("dly", shape=[1], dtype="int64")
        loss = fluid.layers.mean(fluid.layers.fc(x, size=2))
        ds = fluid.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(4)
        ds.set_use_var([x, y])
        ds.set_filelist([str(f)])
        loader = fluid.io.DataLoader.from_dataset(ds)
        exe = fluid.Executor()
        exe.run(startup)
        n = 0
        for feed in loader:
            assert set(feed) == {"dlx", "dly"}
            assert feed["dlx"].shape[1] == 4
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            assert np.isfinite(lv)
            n += 1
        assert n == 2  # 10 rows, batch 4, drop_last

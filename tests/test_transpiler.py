"""Transpiler tests: program-rewrite structure + runnability.

Reference pattern: unittests/test_dist_transpiler.py asserts the rewritten
op lists; here we also run the collective-transpiled program (its
c_allreduce ops are GSPMD identities single-host) to prove it still lowers.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.transpiler import (DistributeTranspiler, GeoSgdTranspiler,
                                   GradAllReduce, HashName, LocalSGD,
                                   RoundRobin)


def _build(opt="sgd"):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        if opt == "sgd":
            fluid.optimizer.SGD(0.1).minimize(loss)
        else:
            fluid.optimizer.Adam(0.01).minimize(loss)
    return main, startup, loss


def test_grad_allreduce_inserts_collectives():
    main, startup, loss = _build()
    n_params = len(main.global_block().all_parameters())
    t = GradAllReduce(nrings=2)
    t.transpile(startup, main, rank=0,
                endpoints=["127.0.0.1:6170", "127.0.0.1:6171"],
                current_endpoint="127.0.0.1:6170")
    ops = [op.type for op in main.global_block().ops]
    assert ops.count("c_allreduce_sum") == n_params
    assert any(op.type == "c_comm_init_all"
               for op in startup.global_block().ops)
    # each allreduce must come before the opt ops and after a 1/N scale
    i_ar = [i for i, t_ in enumerate(ops) if t_ == "c_allreduce_sum"]
    i_opt = [i for i, t_ in enumerate(ops) if t_ == "sgd"]
    assert max(i_ar) < min(i_opt)
    for i in i_ar:
        assert ops[i - 1] == "scale"
    rings = {op.attrs["ring_id"] for op in main.global_block().ops
             if op.type == "c_allreduce_sum"}
    assert rings == {0, 1}  # multi-ring round robin

    # still runs single-process (collectives are GSPMD identities)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(5):
            lv, = exe.run(main,
                          feed={"x": rng.randn(16, 8).astype(np.float32),
                                "y": rng.randn(16, 1).astype(np.float32)},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0]


def test_local_sgd_inserts_periodic_averaging():
    main, startup, loss = _build()
    t = LocalSGD(k_steps=4)
    t.transpile(startup, main, rank=0,
                endpoints=["a:1", "b:2"], current_endpoint="a:1")
    types = [op.type for op in main.global_block().ops]
    assert "conditional_block" in types
    assert "c_allreduce_sum" not in types  # grads are NOT allreduced
    sub_idx = next(op.attrs["sub_block"]
                   for op in main.global_block().ops
                   if op.type == "conditional_block")
    sub_types = [op.type for op in main.blocks[sub_idx].ops]
    n_params = len(main.global_block().all_parameters())
    assert sub_types.count("c_allreduce_sum") == n_params


def test_distribute_transpiler_programs():
    main, startup, loss = _build(opt="adam")
    t = DistributeTranspiler()
    eps = ["127.0.0.1:6170", "127.0.0.1:6171"]
    t.transpile(trainer_id=0, program=main, pservers=",".join(eps),
                trainers=2, startup_program=startup)

    trainer = t.get_trainer_program()
    ttypes = [op.type for op in trainer.global_block().ops]
    assert "adam" not in ttypes, "optimizer runs on the pserver"
    n_params = len(main.global_block().all_parameters())
    assert ttypes.count("send") == n_params
    assert ttypes.count("recv") == n_params
    assert ttypes.count("send_barrier") == 1
    assert ttypes.count("fetch_barrier") == 1
    # barrier ordering: sends -> send_barrier -> recvs -> fetch_barrier
    assert max(i for i, x in enumerate(ttypes) if x == "send") \
        < ttypes.index("send_barrier") \
        < min(i for i, x in enumerate(ttypes) if x == "recv") \
        < ttypes.index("fetch_barrier")

    all_params = set()
    for ep in eps:
        ps = t.get_pserver_program(ep)
        ls = ps.global_block().ops[-1]
        assert ls.type == "listen_and_serv"
        assert ls.attrs["endpoint"] == ep
        params = ls.attrs["params"]
        all_params.update(params)
        for p in params:
            sub = ps.blocks[ls.attrs["opt_block_of"][p]]
            assert any(op.type == "adam" for op in sub.ops)
        # startup inits exactly this pserver's params (+ their opt state)
        sp = t.get_startup_program(ep)
        inited = {n for op in sp.global_block().ops
                  for n in op.output_names()}
        assert set(params) <= inited
    assert all_params == {p.name for p in
                          main.global_block().all_parameters()}


def test_geo_sgd_trainer_keeps_optimizer():
    main, startup, loss = _build()
    t = GeoSgdTranspiler()
    t.transpile(trainer_id=0, program=main, pservers="127.0.0.1:6172",
                trainers=2, startup_program=startup)
    ttypes = [op.type for op in t.get_trainer_program().global_block().ops]
    assert "sgd" in ttypes, "geo trainers update locally"
    assert "geo_sgd_send" in ttypes


def test_dispatchers():
    class V:
        def __init__(self, name):
            self.name = name

    vs = [V(f"p{i}") for i in range(5)]
    rr = RoundRobin(["a", "b"]).dispatch(vs)
    assert rr == ["a", "b", "a", "b", "a"]
    h1 = HashName(["a", "b"]).dispatch(vs)
    h2 = HashName(["a", "b"]).dispatch(vs)
    assert h1 == h2, "hash placement must be deterministic"

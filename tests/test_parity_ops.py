"""Smoke + numerics tests for the Appendix-A parity op batch.

Each op lowers under jit with plausible inputs; a subset gets exact
numeric checks against hand-computed references.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # registers ops  # noqa: F401
from paddle_tpu.core.lowering import LowerCtx
from paddle_tpu.core.registry import REGISTRY


class _Ctx:
    is_test = False
    mesh = None
    block = None
    attrs = {}

    @property
    def rng(self):
        return jax.random.PRNGKey(0)

    def sub_block(self, idx):
        raise NotImplementedError

    def lower_sub_block(self, block, env):
        raise NotImplementedError


def run(op_type, ins, attrs=None):
    opdef = REGISTRY.get(op_type)
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return opdef.lower(_Ctx(), ins, attrs or {})


rng = np.random.RandomState(0)


def test_where_unique():
    cond = np.array([[0, 1], [1, 0]], np.int32)
    out = run("where", {"Condition": [cond]})["Out"][0]
    rows = np.asarray(out)
    assert {tuple(r) for r in rows[:2].tolist()} == {(0, 1), (1, 0)}
    assert (rows[2:] == -1).all()

    u = run("unique", {"X": [np.array([3, 1, 3, 2], np.int64)]})
    assert set(np.asarray(u["Out"][0]).tolist()) >= {1, 2, 3}
    uc = run("unique_with_counts", {"X": [np.array([3, 1, 3], np.int64)]})
    pairs = set(zip(np.asarray(uc["Out"][0]).tolist(),
                    np.asarray(uc["Count"][0]).tolist()))
    assert {(3, 2), (1, 1)} <= pairs  # fill rows carry count 0


def test_crop_and_pad():
    x = rng.randn(4, 6).astype(np.float32)
    out = run("crop", {"X": [x]}, {"shape": [2, 3], "offsets": [1, 2]})
    np.testing.assert_allclose(np.asarray(out["Out"][0]), x[1:3, 2:5])
    y = rng.randn(2, 3).astype(np.float32)
    out = run("pad_constant_like", {"X": [x], "Y": [y]},
              {"pad_value": 7.0})["Out"][0]
    assert out.shape == x.shape and float(out[3, 5]) == 7.0


def test_ctc_loss_matches_bruteforce():
    """warpctc vs brute-force path enumeration on a tiny case."""
    T, C = 4, 3
    logits = rng.randn(1, T, C).astype(np.float32)
    labels = np.array([[1, 2]], np.int64)
    loss = float(np.asarray(run("warpctc", {"Logits": [logits],
                                            "Label": [labels]},
                                {"blank": 0})["Loss"][0]))
    # brute force: sum over all T-length paths collapsing to [1, 2]
    import itertools
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits[0]), -1))

    def collapse(path):
        out, prev = [], None
        for p in path:
            if p != prev and p != 0:
                out.append(p)
            prev = p
        return out

    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == [1, 2]:
            lp = sum(logp[t, p] for t, p in enumerate(path))
            total = np.logaddexp(total, lp)
    np.testing.assert_allclose(loss, -total, rtol=1e-4)


def test_edit_distance():
    hyps = np.array([[1, 2, 3, -1]], np.int64)
    refs = np.array([[1, 3, 3, -1]], np.int64)
    out = run("edit_distance", {"Hyps": [hyps], "Refs": [refs]},
              {"normalized": False})["Out"][0]
    assert float(np.asarray(out).reshape(())) == 1.0  # one substitution
    out = run("edit_distance", {"Hyps": [hyps], "Refs": [refs]},
              {"normalized": True})["Out"][0]
    np.testing.assert_allclose(float(np.asarray(out).reshape(())),
                               1.0 / 3.0, rtol=1e-6)


def test_crf_decoding_prefers_high_emission():
    em = np.zeros((1, 3, 2), np.float32)
    em[0, :, 1] = 5.0  # tag 1 always best
    trans = np.zeros((4, 2), np.float32)
    path = run("crf_decoding", {"Emission": [em], "Transition": [trans]})
    assert np.asarray(path["ViterbiPath"][0]).reshape(-1).tolist() == \
        [1, 1, 1]


def test_linear_chain_crf_loglikelihood_positive():
    em = rng.randn(2, 4, 3).astype(np.float32)
    trans = rng.randn(5, 3).astype(np.float32)
    label = rng.randint(0, 3, (2, 4)).astype(np.int64)
    ll = run("linear_chain_crf", {"Emission": [em], "Transition": [trans],
                                  "Label": [label]})["LogLikelihood"][0]
    assert np.asarray(ll).shape == (2, 1)
    assert (np.asarray(ll) > 0).all()  # -log p > 0


def test_grid_sampler_identity():
    x = rng.randn(1, 1, 4, 4).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys], -1)[None].astype(np.float32)
    out = run("grid_sampler", {"X": [x], "Grid": [grid]})["Output"][0]
    np.testing.assert_allclose(np.asarray(out), x, atol=1e-5)


def test_roi_align_full_image():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0.0, 0.0, 4.0, 4.0]], np.float32)
    out = run("roi_align", {"X": [x], "ROIs": [rois]},
              {"pooled_height": 2, "pooled_width": 2,
               "spatial_scale": 1.0, "sampling_ratio": 2})["Out"][0]
    assert out.shape == (1, 1, 2, 2)
    # hand-computed: bin (0,0) samples at (0.5,0.5),(0.5,1.5),(1.5,0.5),
    # (1.5,1.5) -> mean 5.0; quadrants increase left-right, top-bottom
    o = np.asarray(out)[0, 0]
    np.testing.assert_allclose(o[0, 0], 5.0, rtol=1e-5)
    assert o[0, 0] < o[0, 1] < o[1, 0] < o[1, 1]


def test_multiclass_nms_suppresses_overlaps():
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 10.1],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out = run("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
              {"score_threshold": 0.1, "nms_threshold": 0.5,
               "keep_top_k": 4, "background_label": 0})
    o = np.asarray(out["Out"][0])[0]
    kept = o[o[:, 1] > 0]
    assert len(kept) == 2  # overlapping pair suppressed to one + far box


def test_bipartite_match_greedy():
    dist = np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
    out = run("bipartite_match", {"DistMat": [dist]})
    idx = np.asarray(out["ColToRowMatchIndices"][0])[0]
    assert idx.tolist() == [0, 1]


def test_cudnn_lstm_shapes():
    T, B, D, H = 5, 2, 3, 4
    x = rng.randn(T, B, D).astype(np.float32)
    h0 = np.zeros((1, B, H), np.float32)
    c0 = np.zeros((1, B, H), np.float32)
    n = 4 * H * D + 4 * H * H + 8 * H
    w = rng.randn(n).astype(np.float32) * 0.1
    out = run("cudnn_lstm", {"Input": [x], "InitH": [h0], "InitC": [c0],
                             "W": [w]},
              {"hidden_size": H, "num_layers": 1})
    assert out["Out"][0].shape == (T, B, H)
    assert np.isfinite(np.asarray(out["Out"][0])).all()


def test_sequence_conv_window():
    x = rng.randn(2, 5, 3).astype(np.float32)
    w = rng.randn(9, 4).astype(np.float32)
    out = run("sequence_conv", {"X": [x], "Filter": [w]},
              {"contextLength": 3, "contextStart": -1})["Out"][0]
    assert out.shape == (2, 5, 4)
    # middle position = full window matmul
    window = np.concatenate([x[0, 1], x[0, 2], x[0, 3]])
    np.testing.assert_allclose(np.asarray(out)[0, 2], window @ w,
                               rtol=1e-4, atol=1e-5)


def test_nce_cost_positive():
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(20, 8).astype(np.float32)
    label = rng.randint(0, 20, (4, 1)).astype(np.int64)
    out = run("nce", {"Input": [x], "Weight": [w], "Label": [label]},
              {"num_neg_samples": 5, "num_total_classes": 20})
    assert (np.asarray(out["Cost"][0]) > 0).all()


def test_spectral_norm_unit_sigma():
    w = rng.randn(6, 4).astype(np.float32)
    u = rng.randn(6).astype(np.float32)
    v = rng.randn(4).astype(np.float32)
    out = run("spectral_norm", {"Weight": [w], "U": [u], "V": [v]},
              {"power_iters": 20})["Out"][0]
    sigma = np.linalg.svd(np.asarray(out), compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, rtol=1e-3)


def test_hash_deterministic():
    x = np.array([[1, 2], [1, 2], [3, 4]], np.int64)
    out = np.asarray(run("hash", {"X": [x]},
                         {"num_hash": 2, "mod_by": 1000})["Out"][0])
    assert (out[0] == out[1]).all() and not (out[0] == out[2]).all()
    assert (out >= 0).all() and (out < 1000).all()


def test_save_load_roundtrip(tmp_path):
    x = rng.randn(3, 4).astype(np.float32)
    path = str(tmp_path / "var")
    run("save", {"X": [x]}, {"file_path": path})
    out = run("load", {}, {"file_path": path, "shape": [3, 4],
                           "dtype": "float32"})["Out"][0]
    np.testing.assert_allclose(np.asarray(out), x)


def test_py_func_roundtrip():
    from paddle_tpu.ops.misc_ops import register_py_func

    fid = register_py_func(lambda a: a * 2 + 1)
    x = rng.randn(2, 2).astype(np.float32)
    out = run("py_func", {"X": [x]},
              {"func_id": fid, "out_shapes": [[2, 2]],
               "out_dtypes": ["float32"]})["Out"][0]
    np.testing.assert_allclose(np.asarray(out), x * 2 + 1, rtol=1e-6)


def test_registry_covers_appendix_batch():
    """Every op in this parity batch must be registered."""
    batch = [
        "where", "unique", "unique_with_counts", "crop", "crop_tensor",
        "pad_constant_like", "fill", "hash", "coalesce_tensor",
        "squared_l2_distance", "l1_norm", "fsp", "random_crop",
        "gaussian_random_batch_size_like", "get_tensor_from_selected_rows",
        "merge_selected_rows", "split_selected_rows", "delete_var",
        "get_places", "save", "save_combine", "load", "load_combine",
        "py_func", "gen_nccl_id", "broadcast", "prefetch", "split_ids",
        "merge_ids", "split_byref", "ref_by_trainer_id", "fake_init",
        "lookup_sparse_table", "distributed_lookup_table",
        "checkpoint_notify", "modified_huber_loss", "sigmoid_focal_loss",
        "teacher_student_sigmoid_loss", "cvm", "positive_negative_pair",
        "warpctc", "ctc_align", "edit_distance", "linear_chain_crf",
        "crf_decoding", "nce", "sample_logits", "chunk_eval", "pool3d",
        "max_pool3d_with_index", "unpool", "spp", "conv3d_transpose",
        "depthwise_conv2d_transpose", "affine_grid", "grid_sampler",
        "trilinear_interp", "sync_batch_norm", "spectral_norm", "row_conv",
        "conv_shift", "similarity_focus", "var_conv_2d", "tree_conv",
        "sequence_concat", "sequence_conv", "sequence_enumerate",
        "sequence_erase", "sequence_expand", "sequence_reshape",
        "sequence_scatter", "sequence_slice", "sequence_topk_avg_pooling",
        "match_matrix_tensor", "filter_by_instag", "lod_reset",
        "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
        "array_to_lod_tensor", "reorder_lod_tensor_by_rank",
        "split_lod_tensor", "merge_lod_tensor", "shrink_rnn_memory",
        "rnn_memory_helper", "im2sequence", "cudnn_lstm", "cudnn_gru",
        "lstmp", "attention_lstm", "multihead_matmul",
        "fused_elemwise_activation", "fused_embedding_seq_pool",
        "fused_fc_elementwise_layernorm", "fusion_gru", "fusion_lstm",
        "fusion_repeated_fc_relu", "fusion_seqconv_eltadd_relu",
        "fusion_seqexpand_concat_fc", "fusion_seqpool_concat",
        "fusion_squared_mat_sub", "fusion_transpose_flatten_concat",
        "fake_quantize_range_abs_max",
        "fake_channel_wise_dequantize_max_abs", "quantize", "dequantize",
        "requantize", "roi_align", "roi_pool", "prroi_pool", "psroi_pool",
        "anchor_generator", "density_prior_box", "bipartite_match",
        "target_assign", "multiclass_nms", "multiclass_nms2",
        "mine_hard_examples", "polygon_box_transform",
        "box_decoder_and_assign", "collect_fpn_proposals",
        "distribute_fpn_proposals", "generate_proposals",
    ]
    missing = [t for t in batch if not REGISTRY.has(t)]
    assert not missing, missing


def test_final_batch_registered_and_runs():
    for t in ["fc", "listen_and_serv", "dgc", "dgc_clip_by_norm",
              "dgc_momentum", "hierarchical_sigmoid", "yolov3_loss",
              "rpn_target_assign", "retinanet_target_assign",
              "retinanet_detection_output", "generate_proposal_labels",
              "generate_mask_labels", "roi_perspective_transform",
              "detection_map"]:
        assert REGISTRY.has(t), t

    out = run("fc", {"Input": [rng.randn(2, 3).astype(np.float32)],
                     "W": [rng.randn(3, 5).astype(np.float32)]})
    assert out["Out"][0].shape == (2, 5)


def test_dgc_sparsifies():
    g = rng.randn(100).astype(np.float32)
    u = np.zeros(100, np.float32)
    v = np.zeros(100, np.float32)
    out = run("dgc", {"U": [u], "V": [v], "Grad": [g]},
              {"m": 0.9, "sparsity": [0.9]})
    enc = np.asarray(out["EncodeGrad"][0])
    nz = (enc != 0).sum()
    assert nz <= 15, nz  # ~10% kept
    # kept + remainder reconstruct the accumulated gradient
    np.testing.assert_allclose(enc + np.asarray(out["V_out"][0]), g,
                               rtol=1e-5)


def test_hierarchical_sigmoid_loss_positive():
    x = rng.randn(4, 8).astype(np.float32)
    num_classes = 8
    w = rng.randn(num_classes - 1, 8).astype(np.float32)
    label = rng.randint(0, num_classes, (4, 1)).astype(np.int64)
    out = run("hierarchical_sigmoid", {"X": [x], "W": [w],
                                       "Label": [label]},
              {"num_classes": num_classes})
    assert (np.asarray(out["Out"][0]) > 0).all()


def test_rpn_target_assign_matches():
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                        [100, 100, 110, 110]], np.float32)
    gt = np.array([[0, 0, 10, 10]], np.float32)
    out = run("rpn_target_assign", {"Anchor": [anchors], "GtBoxes": [gt]},
              {"rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3})
    lab = np.asarray(out["TargetLabel"][0]).reshape(-1)
    assert lab[0] == 1 and lab[1] == 0 and lab[2] == 0


def test_yolov3_loss_finite():
    n, na, c, h, w = 1, 3, 2, 4, 4
    x = rng.randn(n, na * (5 + c), h, w).astype(np.float32)
    gtbox = np.array([[[0.5, 0.5, 0.4, 0.4]]], np.float32)
    gtlabel = np.array([[1]], np.int64)
    out = run("yolov3_loss", {"X": [x], "GTBox": [gtbox],
                              "GTLabel": [gtlabel]},
              {"anchors": [10, 13, 16, 30, 33, 23],
               "anchor_mask": [0, 1, 2], "class_num": c,
               "downsample_ratio": 32})
    assert np.isfinite(np.asarray(out["Loss"][0])).all()


def test_detection_map_perfect_detection():
    # label layout per detection_map_op.h:161-190:
    # (cls, difficult, xmin, ymin, xmax, ymax), normalized coords
    det = np.array([[1.0, 0.9, 0.0, 0.0, 0.5, 0.5]], np.float32)
    lab = np.array([[1.0, 0.0, 0.0, 0.0, 0.5, 0.5]], np.float32)
    for ap_type in ("integral", "11point"):
        out = run("detection_map", {"DetectRes": [det], "Label": [lab]},
                  {"overlap_threshold": 0.5, "ap_type": ap_type})
        np.testing.assert_allclose(float(np.asarray(out["MAP"][0])), 1.0,
                                   rtol=1e-5)


def test_detection_map_difficult_gt_excluded():
    """evaluate_difficult=False: a difficult GT neither counts toward
    npos nor penalizes the detection matching it
    (CalcTrueAndFalsePositive, detection_map_op.h:308-408)."""
    det = np.array([[1.0, 0.9, 0.0, 0.0, 0.5, 0.5],
                    [1.0, 0.8, 0.5, 0.5, 1.0, 1.0]], np.float32)
    lab = np.array([[1.0, 1.0, 0.0, 0.0, 0.5, 0.5],     # difficult
                    [1.0, 0.0, 0.5, 0.5, 1.0, 1.0]], np.float32)
    out = run("detection_map", {"DetectRes": [det], "Label": [lab]},
              {"overlap_threshold": 0.5, "ap_type": "integral",
               "evaluate_difficult": False})
    # only the non-difficult GT counts: one detection matches it
    # perfectly, the difficult-matched one is dropped -> AP = 1.0
    np.testing.assert_allclose(float(np.asarray(out["MAP"][0])), 1.0,
                               rtol=1e-5)


def test_nms_dead_box_does_not_suppress():
    """Regression: a suppressed box must not suppress later boxes."""
    boxes = np.array([[[0, 0, 10, 10], [4, 0, 14, 10],
                       [8, 0, 18, 10]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out = run("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
              {"score_threshold": 0.1, "nms_threshold": 0.3,
               "keep_top_k": 4, "background_label": 0})
    o = np.asarray(out["Out"][0])[0]
    kept = o[o[:, 1] > 0]
    # IoU(A,B) and IoU(B,C) > 0.3 but IoU(A,C) ~ 0.11: keep A and C
    assert len(kept) == 2, kept


def test_fused_elemwise_activation_order():
    x = np.full((2,), -5.0, np.float32)
    y = np.full((2,), 3.0, np.float32)
    out = run("fused_elemwise_activation", {"X": [x], "Y": [y]},
              {"functor_list": ["elementwise_add", "relu"]})["Out"][0]
    np.testing.assert_allclose(np.asarray(out), [-2.0, -2.0])  # add(x, relu(y))
    out = run("fused_elemwise_activation", {"X": [x], "Y": [y]},
              {"functor_list": ["relu", "elementwise_add"]})["Out"][0]
    np.testing.assert_allclose(np.asarray(out), [0.0, 0.0])  # relu(add)


def _chunk_counts(out):
    return (int(np.asarray(out["NumInferChunks"][0])[0]),
            int(np.asarray(out["NumLabelChunks"][0])[0]),
            int(np.asarray(out["NumCorrectChunks"][0])[0]))


def test_chunk_eval_iobes_scheme():
    """IOBES tags (B=t*4, I=t*4+1, E=t*4+2, S=t*4+3, O=num*4): an S
    chunk, a B-I-E chunk, and a split E (chunk_eval_op.h:130-136)."""
    # label: [S0, O, B0, I0, E0]  -> chunks (0,0,0), (2,4,0)
    lab = np.array([[3, 4, 0, 1, 2]], np.int64)
    # inference: [S0, O, B0, E0, S0] -> (0,0,0), (2,3,0), (4,4,0)
    inf = np.array([[3, 4, 0, 2, 3]], np.int64)
    out = run("chunk_eval", {"Inference": [inf], "Label": [lab]},
              {"num_chunk_types": 1, "chunk_scheme": "IOBES"})
    ic, lc, cc = _chunk_counts(out)
    assert (ic, lc, cc) == (3, 2, 1), (ic, lc, cc)


def test_chunk_eval_ioe_scheme():
    """IOE (I=t*2, E=t*2+1): chunks end at E; trailing I without E
    still closes at sequence end (GetSegments tail flush)."""
    # O = num_chunk_types * num_tag_types = 2 here
    # label: [I0, E0, O, I0] -> (0,1,0), (3,3,0)
    lab = np.array([[0, 1, 2, 0]], np.int64)
    # inference: [I0, I0, O, I0]: I-after-I continues (no E seen), the
    # O flushes (0,1,0); (3,3,0) at the tail -> both chunks match
    inf = np.array([[0, 0, 2, 0]], np.int64)
    out = run("chunk_eval", {"Inference": [inf], "Label": [lab]},
              {"num_chunk_types": 1, "chunk_scheme": "IOE"})
    ic, lc, cc = _chunk_counts(out)
    assert (ic, lc, cc) == (2, 2, 2), (ic, lc, cc)


def test_chunk_eval_plain_scheme():
    """plain (tag==type, O=num_chunk_types): runs of equal type."""
    lab = np.array([[0, 0, 1, 2, 2]], np.int64)   # types 0,1 + O=2
    inf = np.array([[0, 1, 1, 2, 0]], np.int64)
    out = run("chunk_eval", {"Inference": [inf], "Label": [lab]},
              {"num_chunk_types": 2, "chunk_scheme": "plain"})
    ic, lc, cc = _chunk_counts(out)
    # label: (0,1,0), (2,2,1); inf: (0,0,0), (1,2,1), (4,4,0)
    assert (ic, lc, cc) == (3, 2, 0), (ic, lc, cc)


def test_chunk_eval_excluded_types():
    """excluded_chunk_types drops that type from every count
    (EvalOneSeq, chunk_eval_op.h:252-261)."""
    lab = np.array([[0, 1, 4, 2, 3]], np.int64)   # (0,1,t0), (3,4,t1)
    inf = np.array([[0, 1, 4, 2, 3]], np.int64)
    out = run("chunk_eval", {"Inference": [inf], "Label": [lab]},
              {"num_chunk_types": 2, "chunk_scheme": "IOB",
               "excluded_chunk_types": [1]})
    ic, lc, cc = _chunk_counts(out)
    assert (ic, lc, cc) == (1, 1, 1), (ic, lc, cc)


def test_chunk_eval_seq_length():
    """SeqLength truncates padded rows (the use_padding path,
    chunk_eval_op.h:180-195): padding tags beyond the length must not
    produce chunks."""
    lab = np.array([[0, 1, 2, 0, 0]], np.int64)   # O = 1*2 = 2
    inf = np.array([[0, 1, 2, 0, 0]], np.int64)
    full = run("chunk_eval", {"Inference": [inf], "Label": [lab]},
               {"num_chunk_types": 1, "chunk_scheme": "IOB"})
    trunc = run("chunk_eval",
                {"Inference": [inf], "Label": [lab],
                 "SeqLength": [np.array([3], np.int64)]},
                {"num_chunk_types": 1, "chunk_scheme": "IOB"})
    assert _chunk_counts(full) == (3, 3, 3)
    assert _chunk_counts(trunc) == (1, 1, 1)

"""Ragged (LoD) feed path: reference-style sequence programs that never
mention lengths must stay correct on ragged batches.

Reference semantics: LoDTensor offsets flow through ops and
sequence_pool reduces each real sequence only (lod_tensor.h:104,
sequence_pool_op.cc). TPU layout: (padded [B, T, ...], lengths [B]) with
the lengths var auto-created by layers.data(lod_level>0), auto-fed from
a LoDTensor by the Executor, and found by sequence layers through
program.lod_link (propagated across length-preserving ops at build
time).
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.data_feeder import DataFeeder


def _ragged_batch():
    rows = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [3]]
    return rows


def test_lod_feed_sequence_pool_sum():
    vocab, emb_d = 16, 8
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        # reference-style: no lengths anywhere in user code
        word = layers.data("word", shape=[1], dtype="int64", lod_level=1)
        emb = layers.embedding(word, size=[vocab, emb_d])
        pooled = layers.sequence_pool(emb, "sum")
        exe = fluid.Executor()
        exe.run(startup)

        rows = _ragged_batch()
        feeder = DataFeeder(feed_list=[word], program=main)
        feed = feeder.feed([(r,) for r in rows])
        assert isinstance(feed["word"], LoDTensor)
        out, = exe.run(main, feed=feed, fetch_list=[pooled])

        wname = main.all_parameters()[0].name
        w = np.asarray(scope.find_var(wname))
        expect = np.stack([w[np.asarray(r)].sum(0) for r in rows])
        np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_lod_feed_max_pool_ignores_padding():
    vocab, emb_d = 16, 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        word = layers.data("word", shape=[1], dtype="int64", lod_level=1)
        emb = layers.embedding(word, size=[vocab, emb_d])
        # scale is length-preserving: the link must survive it
        emb2 = layers.scale(emb, scale=-1.0)
        pooled = layers.sequence_pool(emb2, "max")
        exe = fluid.Executor()
        exe.run(startup)
        rows = _ragged_batch()
        t = LoDTensor.from_ragged(rows, "int64")
        out, = exe.run(main, feed={"word": t}, fetch_list=[pooled])
        wname = main.all_parameters()[0].name
        w = np.asarray(scope.find_var(wname))
        expect = np.stack([(-w[np.asarray(r)]).max(0) for r in rows])
        # padding is zeros; if max pooling saw the padded rows the result
        # would be wrong wherever all real values are negative
        np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_lod_link_roundtrips_serialization():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        word = layers.data("word", shape=[1], dtype="int64", lod_level=1)
        layers.embedding(word, size=[8, 4])
    clone = fluid.Program.from_json(main.to_json())
    assert clone.lod_link.get("word") == "word.lengths"
    # the embedding output is linked too (propagated)
    assert any(k.startswith("embedding") for k in clone.lod_link)


def test_lod_program_accepts_dense_prepadded_feed():
    """A lod_level>0 program fed a plain pre-padded ndarray must run
    maskless (full lengths synthesized), not crash on the unfed
    companion lengths var."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        word = layers.data("word", shape=[1], dtype="int64", lod_level=1)
        emb = layers.embedding(word, size=[16, 4])
        pooled = layers.sequence_pool(emb, "sum")
        exe = fluid.Executor()
        exe.run(startup)
        dense = np.ones((3, 5, 1), np.int64)
        out, = exe.run(main, feed={"word": dense}, fetch_list=[pooled])
        wname = main.all_parameters()[0].name
        w = np.asarray(scope.find_var(wname))
        np.testing.assert_allclose(out, np.tile(w[1] * 5, (3, 1)),
                                   rtol=1e-5)


def test_ragged_feed_without_link_warns():
    import warnings as _w
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("xr", shape=[2], dtype="float32")  # lod_level=0
        y = layers.scale(x, scale=2.0)
        exe = fluid.Executor()
        exe.run(startup)
        t = LoDTensor.from_ragged([[[1.0, 2.0]], [[3.0, 4.0]]], "float32")
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            exe.run(main, feed={"xr": t}, fetch_list=[y])
        assert any("no lengths var" in str(r.message) for r in rec)

"""Router-tier tests: least-loaded dispatch, health-gated failover,
session affinity, preemption-aware membership, zero-downtime hot-swap,
the RouterHTTP front end (shed with Retry-After), two-tier trace
propagation, the /healthz worst-state-wins aggregation table, and the
router loadgen record against its validator + report section.

Unit-level routing tests drive the Router against stub engines (no
model, no warmup) so they pin the dispatch policy itself; the hot-swap,
drain, trace, and loadgen tests run real warmed engines on the same
tiny seq-pad-invariant model tests/test_serving.py uses.
"""
import contextlib
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import trace
from paddle_tpu import layers
from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
from paddle_tpu.serving import (EngineConfig, OverloadedError,
                                QueueFullError, Replica, Router,
                                RouterHTTP, ServingEngine,
                                ServingHTTPServer, serve)
from paddle_tpu.serving.http import _STATE_RANK

FEAT = 6


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("router_model"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, -1, FEAT], dtype="float32",
                        append_batch_size=False)
        s = layers.reduce_sum(x, dim=1)
        h = layers.fc(s, size=16, act="relu")
        pred = layers.fc(h, size=4, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


def _engine(model_dir, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("seq_buckets", (4, 8))
    kw.setdefault("max_wait_us", 1000)
    kw.setdefault("queue_capacity", 64)
    kw.setdefault("default_timeout_ms", 10000)
    return ServingEngine(EngineConfig(model_dir, **kw))


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read() or b"{}"), dict(
                r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _load_tool(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# Stub backends: pin the routing policy without models or warmup
# ---------------------------------------------------------------------------

class _StubEngine:
    """Duck-typed ServingEngine: load/health/predict/output_names plus
    the lifecycle hooks Replica touches."""

    def __init__(self, tag, load=0):
        self.tag = float(tag)
        self.load_value = load
        self.calls = 0
        self.fail = None

    def start(self):
        pass

    def stop(self, drain=True, timeout=30.0):
        pass

    def cache_stats(self):
        return {"misses": 0}

    def load(self):
        return self.load_value

    def health(self):
        return {"state": "ready", "retry_after_s": 0.0}

    def output_names(self):
        return ["y"]

    def predict(self, feed, timeout_ms=None):
        self.calls += 1
        if self.fail is not None:
            raise self.fail
        return [np.full((1, 1), self.tag, np.float32)]


class _StubGenResult:
    def __init__(self, payload):
        self._payload = payload

    def result(self, timeout=None):
        return self._payload


class _StubGenEngine:
    def __init__(self, tag, load=0):
        self.tag = tag
        self.load_value = load
        self.calls = 0

    def start(self):
        pass

    def stop(self, drain=True, timeout=30.0):
        pass

    def load(self):
        return self.load_value

    def health(self):
        return {"state": "ready", "retry_after_s": 0.0}

    def post_warmup_compiles(self):
        return 0

    def submit(self, greq):
        self.calls += 1
        return _StubGenResult({"text": f"from-{self.tag}",
                               "tokens": [1, 2, 3]})


_FEED = {"x": np.zeros((1, 4, FEAT), np.float32)}
_GEN = {"prompt": [1, 2, 3], "max_new_tokens": 4}


@contextlib.contextmanager
def _router(*reps, **kw):
    kw.setdefault("start_probe", False)
    rt = Router(list(reps), **kw)
    try:
        yield rt
    finally:
        rt.close()


# ---------------------------------------------------------------------------
# Dispatch policy
# ---------------------------------------------------------------------------

def test_least_loaded_dispatch():
    stubs = [_StubEngine(tag=i, load=l)
             for i, l in enumerate((5, 0, 3))]
    reps = [Replica(f"r{i}", engine=s) for i, s in enumerate(stubs)]
    with _router(*reps) as rt:
        out = rt.predict(_FEED)
        assert out["y"][0, 0] == 1.0
        assert [s.calls for s in stubs] == [0, 1, 0]
        # load moves, dispatch follows
        stubs[1].load_value = 9
        out = rt.predict(_FEED)
        assert out["y"][0, 0] == 2.0
        assert rt.requests == 2 and rt.redispatches == 0


def test_failover_redispatches_to_healthy_replica():
    bad = _StubEngine(tag=0, load=0)
    bad.fail = QueueFullError("replica queue full")
    good = _StubEngine(tag=7, load=5)
    with _router(Replica("bad", engine=bad),
                 Replica("good", engine=good)) as rt:
        out = rt.predict(_FEED)
        # least-loaded picked the failing replica first, then failed
        # over without surfacing an error to the caller
        assert bad.calls == 1 and good.calls == 1
        assert out["y"][0, 0] == 7.0
        assert rt.redispatches == 1


def test_shed_with_retry_after_when_all_replicas_out():
    s = _StubEngine(tag=0, load=0)
    s.fail = OverloadedError("full", retry_after_s=3.0)
    with _router(Replica("r0", engine=s), redispatch_budget=2) as rt:
        with pytest.raises(OverloadedError) as ei:
            rt.predict(_FEED)
        # the one replica was tried once, then the empty healthy set
        # shed the request with the fleet's max backoff
        assert s.calls == 1
        assert rt.shed == 1
        assert ei.value.retry_after_s >= 1.0


def test_nonretryable_error_propagates_without_failover():
    a = _StubEngine(tag=0, load=0)
    a.fail = ValueError("bad feed")
    b = _StubEngine(tag=1, load=5)
    with _router(Replica("a", engine=a), Replica("b", engine=b)) as rt:
        with pytest.raises(ValueError):
            rt.predict(_FEED)
        assert b.calls == 0 and rt.redispatches == 0
        # the replica is not at fault for a malformed request: its
        # breaker stays closed and it remains routable
        assert [r.name for r in rt.healthy_replicas()] == ["a", "b"]


def test_breaker_opens_after_repeated_failures():
    bad = _StubEngine(tag=0, load=0)
    bad.fail = QueueFullError("full")
    good = _StubEngine(tag=1, load=50)
    with _router(Replica("bad", engine=bad, failure_threshold=2),
                 Replica("good", engine=good)) as rt:
        for _ in range(3):
            rt.predict(_FEED)
        # after 2 strikes the breaker opens: "bad" leaves the healthy
        # set and stops being tried at all despite its lower load
        assert [r.name for r in rt.healthy_replicas()] == ["good"]
        calls_before = bad.calls
        rt.predict(_FEED)
        assert bad.calls == calls_before


def test_half_open_probe_recovers_replica_after_cooldown():
    """A tripped breaker must recover through the half-open probe even
    while read-only paths (healthz, gauge sweeps, healthy_replicas)
    keep checking routability: those checks must not consume the
    HALF_OPEN probe slot, or the replica stays excluded forever."""
    bad = _StubEngine(tag=3, load=0)
    bad.fail = QueueFullError("full")
    rep = Replica("r", engine=bad, failure_threshold=1)
    rep.breaker.cooldown_ms = 60.0
    with _router(rep) as rt:
        with pytest.raises(OverloadedError):
            rt.predict(_FEED)         # one strike trips the breaker
        assert rt.healthy_replicas() == []
        time.sleep(0.08)              # cooldown elapsed -> HALF_OPEN
        for _ in range(5):            # read-only paths, repeatedly
            rt.healthz()
            rt.probe_once()
            assert [r.name for r in rt.healthy_replicas()] == ["r"]
        bad.fail = None
        out = rt.predict(_FEED)       # the real probe closes it
        assert out["y"][0, 0] == 3.0
        from paddle_tpu.resilience.breaker import CLOSED
        assert rep.breaker.state == CLOSED
        assert [r.name for r in rt.healthy_replicas()] == ["r"]


def test_nonretryable_in_half_open_releases_probe_slot():
    bad = _StubEngine(tag=0, load=0)
    bad.fail = QueueFullError("full")
    rep = Replica("r", engine=bad, failure_threshold=1)
    rep.breaker.cooldown_ms = 40.0
    with _router(rep) as rt:
        with pytest.raises(OverloadedError):
            rt.predict(_FEED)         # OPEN
        time.sleep(0.06)              # HALF_OPEN
        bad.fail = ValueError("bad feed")
        with pytest.raises(ValueError):
            rt.predict(_FEED)         # probe claimed, then released
        # the replica is not at fault and must stay routable
        assert [r.name for r in rt.healthy_replicas()] == ["r"]
        bad.fail = None
        assert rt.predict(_FEED)["y"][0, 0] == 0.0


def test_healthz_polls_do_not_inflate_shed_counter():
    with _router(Replica("r", engine=_StubEngine(tag=0))) as rt:
        rt.preempt("r")
        for _ in range(3):
            code, _body, ra = rt.healthz()
            assert code == 503 and ra >= 1.0
        # no client request was shed: only actual sheds may count
        assert rt.shed == 0


def test_session_affinity_pins_and_repins():
    g0, g1 = _StubGenEngine("g0", load=0), _StubGenEngine("g1", load=5)
    with _router(Replica("r0", gen_engine=g0),
                 Replica("r1", gen_engine=g1)) as rt:
        out = rt.generate(_GEN, session="s1")
        assert out["text"] == "from-g0"
        # affinity holds even when the pinned replica gets busier
        g0.load_value = 50
        assert rt.generate(_GEN, session="s1")["text"] == "from-g0"
        # a fresh session follows load, not the old pin
        assert rt.generate(_GEN, session="s2")["text"] == "from-g1"
        # pin breaks with the replica and re-pins on a healthy one
        rt.preempt("r0")
        assert rt.generate(_GEN, session="s1")["text"] == "from-g1"


def test_affinity_map_is_lru_bounded():
    g = _StubGenEngine("g", load=0)
    with _router(Replica("r", gen_engine=g), affinity_max=4) as rt:
        for i in range(10):
            rt.generate(_GEN, session=f"s{i}")
        with rt._lock:
            assert list(rt._affinity) == ["s6", "s7", "s8", "s9"]
        # touching a survivor refreshes it; a new session evicts the
        # least recently used pin, not the refreshed one
        rt.generate(_GEN, session="s6")
        rt.generate(_GEN, session="new")
        with rt._lock:
            assert "s6" in rt._affinity
            assert "s7" not in rt._affinity
            assert len(rt._affinity) == 4


def test_probe_once_gates_unhealthy_replica():
    a, b = _StubEngine(tag=0, load=0), _StubEngine(tag=1, load=5)
    with _router(Replica("a", engine=a), Replica("b", engine=b)) as rt:
        a.health = lambda: {"state": "open", "retry_after_s": 2.0}
        rt.probe_once()
        assert [r.name for r in rt.healthy_replicas()] == ["b"]
        out = rt.predict(_FEED)
        assert out["y"][0, 0] == 1.0 and a.calls == 0
        # recovery: the next sweep re-admits it (backoff expired is
        # simulated by clearing it — probe_once set it from Retry-After)
        a.health = lambda: {"state": "ready", "retry_after_s": 0.0}
        rt.probe_once()
        rep_a = [r for r in rt.replicas() if r.name == "a"][0]
        rep_a.backoff_until = 0.0
        assert len(rt.healthy_replicas()) == 2


# ---------------------------------------------------------------------------
# Preemption-aware membership
# ---------------------------------------------------------------------------

def test_preempt_and_resume_membership():
    a, b = _StubEngine(tag=0, load=0), _StubEngine(tag=1, load=5)
    with _router(Replica("a", engine=a), Replica("b", engine=b)) as rt:
        rt.preempt("a")
        assert [r.name for r in rt.healthy_replicas()] == ["b"]
        out = rt.predict(_FEED)       # no client-visible error
        assert out["y"][0, 0] == 1.0
        rt.resume("a")
        assert len(rt.healthy_replicas()) == 2
        assert rt.predict(_FEED)["y"][0, 0] == 0.0


def test_install_sigterm_chains_previous_handler():
    calls = []

    def prev_handler(signum, frame):
        calls.append(signum)

    old = signal.signal(signal.SIGTERM, prev_handler)
    try:
        with _router(Replica("a", engine=_StubEngine(tag=0))) as rt:
            rt.install_sigterm("a")
            handler = signal.getsignal(signal.SIGTERM)
            assert handler is not prev_handler
            handler(signal.SIGTERM, None)
            # SIGTERM deregistered the replica AND chained through to
            # the previously installed handler (trainer_guard pattern)
            assert calls == [signal.SIGTERM]
            assert rt.replicas()[0].registered is False
        # close() restored the previous handler
        assert signal.getsignal(signal.SIGTERM) is prev_handler
    finally:
        signal.signal(signal.SIGTERM, old)


# ---------------------------------------------------------------------------
# RouterHTTP front end
# ---------------------------------------------------------------------------

def test_router_http_serves_and_sheds(model_dir):
    eng = _engine(model_dir)
    rep = Replica("r0", engine=eng, version="v1")
    rep.start()
    rt = Router([rep], start_probe=False)
    srv = RouterHTTP(rt, port=0)
    try:
        url = srv.url
        code, body, _ = _get(url + "/healthz")
        assert code == 200 and body["state"] == "ok"
        assert body["replicas"]["r0"]["version"] == "v1"

        xb = np.random.RandomState(0).randn(1, 5, FEAT) \
            .astype(np.float32)
        ref = create_paddle_predictor(AnalysisConfig(model_dir))
        want, = ref.run_dict({"x": xb})
        code, body, _ = _post(url + "/v1/predict",
                              {"inputs": {"x": xb.tolist()}})
        assert code == 200, body
        name = eng.output_names()[0]
        np.testing.assert_allclose(np.asarray(body["outputs"][name]),
                                   np.asarray(want), rtol=1e-4,
                                   atol=1e-5)

        code, body, _ = _post(url + "/v1/predict", {"inputs": {}})
        assert code == 400

        # deregister the only replica: the router sheds with a 503 and
        # a Retry-After, both on the route and on /healthz
        rt.preempt("r0")
        code, body, hdrs = _post(url + "/v1/predict",
                                 {"inputs": {"x": xb.tolist()}})
        assert code == 503 and body["retryable"] is True
        assert int(hdrs["Retry-After"]) >= 1
        code, body, hdrs = _get(url + "/healthz")
        assert code == 503 and body["state"] == "open"
        assert int(hdrs["Retry-After"]) >= 1
        assert rt.shed >= 1
    finally:
        srv.close()
        rt.close(stop_replicas=True)


# ---------------------------------------------------------------------------
# Hot swap
# ---------------------------------------------------------------------------

def test_hot_swap_flips_table_and_drains_old(model_dir):
    old_eng = _engine(model_dir)
    rep = Replica("r0", engine=old_eng, version="v1")
    rep.start()
    rt = Router([rep], start_probe=False, drain_timeout_s=10.0)
    standby = Replica("r0v2", engine=_engine(model_dir), version="v2")
    try:
        xb = np.random.RandomState(1).randn(2, 5, FEAT) \
            .astype(np.float32)
        want = rt.predict({"x": xb})
        res = rt.hot_swap("r0", standby)
        assert res["swapped"] and res["drained"]
        assert res["old"] == "r0" and res["new"] == "r0v2"
        assert res["standby_post_warmup_compiles"] == 0
        assert [r.name for r in rt.replicas()] == ["r0v2"]
        # the old replica was drained and fully stopped
        assert not old_eng.ready
        # traffic keeps flowing and the answers don't change
        got = rt.predict({"x": xb})
        name = next(iter(want))
        np.testing.assert_allclose(got[name], want[name], rtol=1e-4,
                                   atol=1e-5)
    finally:
        rt.close(stop_replicas=True)


def test_hot_swap_rejects_duplicate_before_start_allows_same_name():
    a = _StubEngine(tag=0, load=0)
    b = _StubEngine(tag=1, load=9)
    with _router(Replica("r0", engine=a), Replica("r1", engine=b)) as rt:
        # a collision with a live replica is rejected BEFORE the
        # standby is warmed, so no engine is started just to be thrown
        # away
        class _TrackStart(_StubEngine):
            started = False

            def start(self):
                self.started = True

        dup_eng = _TrackStart(tag=2)
        with pytest.raises(ValueError):
            rt.hot_swap("r0", Replica("r1", engine=dup_eng))
        assert dup_eng.started is False
        assert sorted(r.name for r in rt.replicas()) == ["r0", "r1"]
        # swapping under the SAME name (restart with new weights) works
        res = rt.hot_swap("r0", Replica(
            "r0", engine=_StubEngine(tag=5), version="v2"))
        assert res["swapped"] and res["old"] == "r0" \
            and res["new"] == "r0"
        reps = {r.name: r for r in rt.replicas()}
        assert set(reps) == {"r0", "r1"}
        assert reps["r0"].version == "v2"
        assert rt.predict(_FEED)["y"][0, 0] == 5.0


def test_hot_swap_compile_gate_stops_standby_and_keeps_table():
    class _CompilingGen(_StubGenEngine):
        def __init__(self):
            super().__init__("c")
            self.stopped = False

        def post_warmup_compiles(self):
            return 1

        def stop(self, drain=True, timeout=30.0):
            self.stopped = True

    g = _StubGenEngine("g0", load=0)
    comp = _CompilingGen()
    with _router(Replica("g0", gen_engine=g)) as rt:
        with pytest.raises(RuntimeError, match="post-warmup compiles"):
            rt.hot_swap("g0", Replica("g1", gen_engine=comp))
        # the aborted standby was stopped, and the old replica still
        # serves
        assert comp.stopped is True
        assert [r.name for r in rt.replicas()] == ["g0"]
        assert rt.generate(_GEN)["text"] == "from-g0"


# ---------------------------------------------------------------------------
# /healthz worst-state-wins aggregation (replica server)
# ---------------------------------------------------------------------------

class _StubHealth:
    ready = True

    def __init__(self):
        self.h = {"state": "ready", "retry_after_s": 0.0}

    def health(self):
        return self.h


def test_healthz_worst_state_wins_full_table():
    """Every (predict_state, generate_state) pair resolves to the
    higher-ranked state; ok/degraded answer 200, the rest 503; the
    Retry-After header appears only for worst == "open" and carries the
    MAX of the engines' retry_after_s."""
    a, b = _StubHealth(), _StubHealth()
    srv = ServingHTTPServer(engine=a, gen_engine=b, port=0)
    try:
        url = f"http://127.0.0.1:{srv.port}"
        for s1 in _STATE_RANK:
            for s2 in _STATE_RANK:
                a.h = {"state": s1,
                       "retry_after_s": 2.0 if s1 == "open" else 0.0}
                b.h = {"state": s2,
                       "retry_after_s": 5.0 if s2 == "open" else 0.0}
                worst = max((s1, s2), key=lambda s: _STATE_RANK[s])
                code, body, hdrs = _get(url + "/healthz")
                ctx = f"pair ({s1}, {s2})"
                if worst in ("ready", "degraded"):
                    assert code == 200, ctx
                else:
                    assert code == 503, ctx
                expect = "ok" if worst == "ready" else worst
                assert body["state"] == expect, ctx
                if worst == "open":
                    # max of the per-engine retry_after_s values
                    want_ra = 5 if s2 == "open" else 2
                    assert int(hdrs["Retry-After"]) == want_ra, ctx
                else:
                    assert "Retry-After" not in hdrs, ctx
    finally:
        srv.close(drain=False)


# ---------------------------------------------------------------------------
# Drain-before-close (replica server)
# ---------------------------------------------------------------------------

def test_http_close_drains_inflight_request(model_dir):
    from paddle_tpu.resilience import reset_injector
    eng = _engine(model_dir)
    srv = serve(eng, port=0)
    prev_spec = fluid.FLAGS.fault_spec
    fluid.set_flags({"FLAGS_fault_spec": "slow_step:ms=300:site=serving"})
    reset_injector()
    result = {}
    xb = np.random.RandomState(2).randn(1, 5, FEAT).astype(np.float32)

    def worker():
        result["resp"] = _post(srv.url + "/v1/predict",
                               {"inputs": {"x": xb.tolist()}})

    t = threading.Thread(target=worker)
    try:
        t.start()
        time.sleep(0.15)          # request is inside the engine now
        srv.close(drain=True, timeout=5.0)
        t.join(timeout=10.0)
        assert not t.is_alive()
        code, body, _ = result["resp"]
        # the in-flight request completed with a real answer instead of
        # a connection reset
        assert code == 200, body
        # and the listening socket is really gone afterwards
        with pytest.raises(Exception):
            urllib.request.urlopen(srv.url + "/healthz", timeout=2)
    finally:
        fluid.set_flags({"FLAGS_fault_spec": prev_spec})
        reset_injector()
        eng.stop()


# ---------------------------------------------------------------------------
# Two-tier tracing: router span parents the replica's request span
# ---------------------------------------------------------------------------

_TRACE_FLAGS = ("enable_trace", "trace_sample", "trace_tail_slow_ms",
                "trace_ring_capacity")


@contextlib.contextmanager
def _trace_on():
    prev = {k: getattr(fluid.FLAGS, k) for k in _TRACE_FLAGS}
    fluid.set_flags({"FLAGS_enable_trace": True,
                     "FLAGS_trace_sample": 1.0,
                     "FLAGS_trace_tail_slow_ms": 0.0,
                     "FLAGS_trace_ring_capacity": 8192})
    trace.reset()
    try:
        yield
    finally:
        trace.reset()
        fluid.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})


def test_traceparent_crosses_router_to_replica_hop(model_dir):
    """One request through RouterHTTP -> url Replica -> replica server
    produces ONE trace: router http.request (root) -> router.dispatch
    -> replica http.request, and the tree passes the trace_report
    consistency audit."""
    tr_tool = _load_tool("trace_report")
    eng = _engine(model_dir)
    replica_srv = serve(eng, port=0)
    rt = srv = None
    with _trace_on():
        try:
            rep = Replica("r0", url=replica_srv.url)
            rt = Router([rep], start_probe=False)
            srv = RouterHTTP(rt, port=0)
            xb = np.random.RandomState(3).randn(1, 5, FEAT) \
                .astype(np.float32)
            code, body, hdrs = _post(srv.url + "/v1/predict",
                                     {"inputs": {"x": xb.tolist()}})
            assert code == 200, body
            spans = trace.drain_spans()
        finally:
            if srv is not None:
                srv.close()
            if rt is not None:
                rt.close()
            replica_srv.close(drain=False)
            eng.stop()
    roots = [s for s in spans
             if s["name"] == "http.request" and s["parent_id"] is None]
    assert len(roots) == 1
    assert roots[0]["attrs"].get("tier") == "router"
    # every request-path span shares the router root's trace (batch
    # spans live in their own linked trace, by design)
    spans = [s for s in spans
             if s["trace_id"] == roots[0]["trace_id"]]
    disp = [s for s in spans if s["name"] == "router.dispatch"]
    assert len(disp) == 1
    assert disp[0]["parent_id"] == roots[0]["span_id"]
    assert disp[0]["attrs"]["replica"] == "r0"
    rep_http = [s for s in spans
                if s["name"] == "http.request"
                and s["parent_id"] is not None]
    assert len(rep_http) == 1
    # the replica's request span parents under the router's dispatch
    # span: one tree covers both tiers
    assert rep_http[0]["parent_id"] == disp[0]["span_id"]
    rep_report = tr_tool.build_report(spans)
    assert rep_report["consistency"]["violations"] == 0
    assert rep_report["n_requests"] >= 1


# ---------------------------------------------------------------------------
# Loadgen record -> validator -> report section
# ---------------------------------------------------------------------------

def test_router_loadgen_schema_validator_and_report(model_dir, tmp_path,
                                                    capsys):
    loadgen = _load_tool("serving_loadgen")
    v = _load_tool("validate_bench_json")
    metrics_report = _load_tool("metrics_report")
    out = str(tmp_path / "router.jsonl")
    rc = loadgen.main(["--model-dir", model_dir, "--router", "2",
                       "--requests", "24", "--max-batch-size", "2",
                       "--seq-buckets", "4,8", "--service-ms", "5",
                       "--out", out])
    assert rc == 0
    recs = [json.loads(ln) for ln in open(out) if ln.strip()]
    rec = next(r for r in recs if r.get("kind") == "router_loadgen")
    assert rec["replicas"] == 2
    assert rec["wrong_answers"] == 0
    assert rec["scaling"]["rps_1"] > 0 and rec["scaling"]["rps_n"] > 0
    assert v.validate_router_loadgen(rec) == []
    assert v.validate_file(out) == []
    # a corrupted record must fail the zero-wrong-answers gate
    bad = dict(rec, wrong_answers=1)
    assert any("wrong_answers" in e
               for e in v.validate_router_loadgen(bad))
    assert metrics_report.report(out) == 0
    text = capsys.readouterr().out
    assert "-- router " in text
    assert "scaling 1->N" in text

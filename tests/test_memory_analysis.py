"""Static memory planner (paddle_tpu/analysis/memory): golden liveness
fixtures, PTV050-052 budget findings, the FLAGS_memory_gate pre-compile
gate in Executor and ServingEngine.warmup, the level-2 buffer-reuse
rewrite (bit-exact + lower estimated peak), optimizer-sink scheduling,
estimator-vs-measured calibration on the bench builders, and the
memory_plan artifact schema + CLI.

Model and consumers: docs/memory_planning.md.
"""
from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import (ProgramVerificationError,
                                 verify_program)
from paddle_tpu.analysis import memory as memory_mod
from paddle_tpu.analysis.memory import (analyze_program_memory,
                                        apply_state_update_sinks,
                                        memory_gate,
                                        state_update_sinks)
from paddle_tpu.analysis.passes import optimize_program
from paddle_tpu.analysis.passes import reset_memo as reset_opt_memo
from paddle_tpu.analysis.shape_infer import Spec
from paddle_tpu.framework import Operator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_F32_23 = dict(shape=[2, 3], dtype="float32")


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


def _raw_program(var_specs, op_specs):
    prog = fluid.Program()
    blk = prog.global_block()
    for name, kw in var_specs:
        blk.create_var(name=name, **kw)
    for op_type, ins, outs, attrs in op_specs:
        blk.ops.append(Operator(blk, op_type, ins, outs, attrs))
    return prog


def _flags(**kv):
    """Set flags, return the previous values for the finally-restore."""
    prev = {k: getattr(fluid.FLAGS, k[len("FLAGS_"):]) for k in kv}
    fluid.set_flags(kv)
    return prev


def _tiny_builds():
    sys.path.insert(0, REPO)
    os.environ.setdefault("BENCH_FLASH", "0")
    import bench
    return bench._CPU_TINY_BUILDS


# ---------------------------------------------------------------------------
# golden liveness fixtures
# ---------------------------------------------------------------------------

def test_golden_intervals_on_a_chain():
    """relu chain x -> a -> b -> out: transients live [def, last read],
    feeds/fetches pin for the whole program, and the peak lands on the
    op where both temporaries are resident."""
    prog = _raw_program(
        [("x", dict(is_data=True, **_F32_23)), ("a", dict(**_F32_23)),
         ("b", dict(**_F32_23)), ("out", dict(**_F32_23))],
        [("relu", {"X": ["x"]}, {"Out": ["a"]}, {}),
         ("relu", {"X": ["a"]}, {"Out": ["b"]}, {}),
         ("relu", {"X": ["b"]}, {"Out": ["out"]}, {})])
    plan = analyze_program_memory(prog, feed_names=["x"],
                                  fetch_names=["out"])
    a, b = plan.intervals["a"], plan.intervals["b"]
    assert (a.def_idx, a.last_use) == (0, 1)
    assert (b.def_idx, b.last_use) == (1, 2)
    assert a.nbytes == b.nbytes == 2 * 3 * 4
    assert plan.intervals["x"].pinned and plan.intervals["out"].pinned
    assert plan.pinned_bytes == 2 * 24
    # timeline: op0 = pinned+a, op1 = pinned+a+b (peak), op2 = pinned+b
    assert plan.timeline == [72, 96, 72]
    assert plan.peak_bytes == 96 and plan.peak_op == "relu:0/1"
    assert not plan.dynamic and plan.unsized_vars == 0
    # b is defined by the op that last reads a -> in-place reuse pair
    assert plan.reuse_bytes_available == 24


def test_spec_nbytes_units_and_dynamic_lower_bound():
    assert Spec((2, 3), "float32").nbytes() == (24, False)
    assert Spec((4,), "int64").nbytes() == (32, False)
    # dynamic dims size at dyn_defaults each and set the marker
    assert Spec((-1, 4), "float32").nbytes() == (16, True)
    assert Spec((-1, 4), "float32").nbytes(dyn_defaults=8) == (128, True)


def test_dynamic_dims_resolve_from_feed_shapes():
    prog = _raw_program(
        [("x", dict(is_data=True, shape=[-1, 4], dtype="float32")),
         ("y", dict(shape=[-1, 4], dtype="float32"))],
        [("relu", {"X": ["x"]}, {"Out": ["y"]}, {})])
    # without concrete shapes the plan is a marked lower bound ...
    plan = analyze_program_memory(prog, feed_names=["x"],
                                  fetch_names=["y"])
    assert plan.dynamic
    # ... with the gate's feed-shape seed it is exact
    plan2 = analyze_program_memory(
        prog, fetch_names=["y"],
        feed_shapes={"x": ((8, 4), "float32")})
    assert not plan2.dynamic
    assert plan2.intervals["x"].nbytes == 8 * 4 * 4


def test_sub_block_read_extends_liveness():
    """A var read only inside a control-flow op's sub-block stays live
    up to that op's index (same rule as PTV012/PTV013 and DCE)."""
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="x", is_data=True, **_F32_23)
    blk.create_var(name="t", **_F32_23)
    blk.create_var(name="cond", shape=[1], dtype="bool")
    blk.create_var(name="cb_out", **_F32_23)
    blk.ops.append(Operator(blk, "relu", {"X": ["x"]}, {"Out": ["t"]}, {}))
    sub = prog._create_block()
    sub.create_var(name="cb_out", **_F32_23)
    sub.ops.append(Operator(sub, "scale", {"X": ["t"]},
                            {"Out": ["cb_out"]}, {"scale": 2.0}))
    prog._rollback()
    blk.ops.append(Operator(
        blk, "conditional_block", {"Cond": ["cond"], "Input": ["x"]},
        {"Out": ["cb_out"]},
        {"sub_block": sub.idx, "input_vars": ["x"],
         "output_vars": ["cb_out"]}))
    plan = analyze_program_memory(prog, feed_names=["x", "cond"],
                                  fetch_names=["cb_out"])
    # t is read by the sub-block only: live through the ctrl-flow op
    assert plan.intervals["t"].last_use == 1
    # satellite regression: the sub-block read also keeps PTV013 quiet
    prog2 = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("b", dict(**_F32_23)),
         ("xs", dict(**_F32_23)), ("cond", dict(shape=[1],
                                                dtype="bool")),
         ("cb", dict(**_F32_23))],
        [("reshape2", {"X": ["a"]}, {"Out": ["b"], "XShape": ["xs"]},
          {"shape": [2, 3]})])
    sub2 = prog2._create_block()
    sub2.create_var(name="cb", **_F32_23)
    sub2.ops.append(Operator(sub2, "scale", {"X": ["xs"]},
                             {"Out": ["cb"]}, {"scale": 1.0}))
    prog2._rollback()
    prog2.global_block().ops.append(Operator(
        prog2.global_block(), "conditional_block",
        {"Cond": ["cond"], "Input": ["b"]}, {"Out": ["cb"]},
        {"sub_block": sub2.idx, "input_vars": ["b"],
         "output_vars": ["cb"]}))
    res = verify_program(prog2, fetch_names=["cb"], check_shapes=False)
    assert not [d for d in res.findings
                if d.rule == "PTV013" and d.var == "xs"]


# ---------------------------------------------------------------------------
# budget findings
# ---------------------------------------------------------------------------

def _mib_chain():
    """Four 1-MiB relu stages: peak ~3 MiB over 2 MiB pinned; a and c
    are strictly disjoint same-spec temporaries."""
    spec = dict(shape=[512, 512], dtype="float32")
    return _raw_program(
        [("x", dict(is_data=True, **spec)), ("a", dict(**spec)),
         ("b", dict(**spec)), ("c", dict(**spec)),
         ("out", dict(**spec))],
        [("relu", {"X": ["x"]}, {"Out": ["a"]}, {}),
         ("relu", {"X": ["a"]}, {"Out": ["b"]}, {}),
         ("relu", {"X": ["b"]}, {"Out": ["c"]}, {}),
         ("relu", {"X": ["c"]}, {"Out": ["out"]}, {})])


def test_ptv050_peak_over_budget():
    plan = analyze_program_memory(_mib_chain(), feed_names=["x"],
                                  fetch_names=["out"],
                                  budget_bytes=2 << 20)
    res = plan.findings()
    hits = [d for d in res.findings if d.rule == "PTV050"]
    assert hits and hits[0].severity == "error"
    assert "exceeds" in hits[0].message \
        and "FLAGS_memory_budget_bytes" in hits[0].message
    assert res.errors()


def test_ptv051_single_tensor_over_budget():
    plan = analyze_program_memory(_mib_chain(), feed_names=["x"],
                                  fetch_names=["out"],
                                  budget_bytes=512 << 10)
    hits = [d for d in plan.findings().findings if d.rule == "PTV051"]
    assert hits and all(d.severity == "error" for d in hits)
    assert any(d.var == "a" for d in hits)
    assert "no buffer plan can fit it" in hits[0].message


def test_ptv052_reuse_advisory_without_budget():
    """>=1 MiB and >=5% of peak reusable fires the advisory even with
    no budget configured."""
    plan = analyze_program_memory(_mib_chain(), feed_names=["x"],
                                  fetch_names=["out"])
    assert plan.reuse_bytes_available >= 1 << 20
    hits = [d for d in plan.findings().findings if d.rule == "PTV052"]
    assert hits and hits[0].severity == "warn"
    assert "FLAGS_buffer_reuse" in hits[0].message
    # under budget, no PTV050/051
    assert {d.rule for d in plan.findings().findings} == {"PTV052"}


# ---------------------------------------------------------------------------
# the pre-compile gate
# ---------------------------------------------------------------------------

def test_executor_gate_rejects_over_budget_before_compile():
    memory_mod.reset_memo()
    prev = _flags(FLAGS_memory_budget_bytes=4096,
                  FLAGS_memory_gate="error")
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[256], dtype="float32")
            y = layers.relu(layers.scale(x, scale=2.0))
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            with pytest.raises(ProgramVerificationError) as ei:
                exe.run(main, feed={"x": np.ones((64, 256), np.float32)},
                        fetch_list=[y.name])
        msg = str(ei.value)
        assert "PTV050" in msg and "budget" in msg
        # rejected BEFORE the executable-cache key: zero compiles
        stats = exe.cache_stats()
        assert stats["misses"] == 0 and stats["size"] == 0, stats
    finally:
        _flags(**prev)
        memory_mod.reset_memo()


def test_gate_warn_mode_warns_once_then_memoizes():
    memory_mod.reset_memo()
    prev = _flags(FLAGS_memory_budget_bytes=4096,
                  FLAGS_memory_gate="warn")
    try:
        prog = _mib_chain()
        shapes = {"x": ((512, 512), "float32")}
        with pytest.warns(UserWarning, match="PTV050"):
            plan = memory_gate(prog, feed_shapes=shapes,
                               fetch_names=["out"], where="test")
        assert plan is not None and plan.peak_bytes > 4096
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            memory_gate(prog, feed_shapes=shapes, fetch_names=["out"],
                        where="test")
        assert not [w for w in rec if "PTV" in str(w.message)], \
            [str(w.message) for w in rec]
    finally:
        _flags(**prev)
        memory_mod.reset_memo()


def test_gate_off_mode_and_bad_value():
    memory_mod.reset_memo()
    prev = _flags(FLAGS_memory_gate="off")
    try:
        assert memory_gate(_mib_chain(), fetch_names=["out"]) is None
        fluid.set_flags({"FLAGS_memory_gate": "everything"})
        with pytest.raises(ValueError, match="memory_gate"):
            memory_gate(_mib_chain(), fetch_names=["out"])
    finally:
        _flags(**prev)
        memory_mod.reset_memo()


def test_serving_warmup_gate_rejects_over_budget(tmp_path):
    """An over-budget model is rejected during warmup as the max over
    ladder cells — with zero ladder-cell compiles spent."""
    from paddle_tpu.serving import EngineConfig, ServingEngine

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[64], dtype="float32")
        out = layers.fc(x, size=64, act="relu")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        mdir = str(tmp_path / "model")
        fluid.io.save_inference_model(mdir, ["x"], [out], exe,
                                      main_program=main)
    memory_mod.reset_memo()
    prev = _flags(FLAGS_memory_budget_bytes=1024,
                  FLAGS_memory_gate="error")
    try:
        engine = ServingEngine(EngineConfig(model_dir=mdir,
                                            max_batch_size=4,
                                            warmup=True))
        with pytest.raises(ProgramVerificationError, match="PTV050"):
            engine.start()
        assert engine.cache_stats()["misses"] == 0
    finally:
        _flags(**prev)
        memory_mod.reset_memo()


# ---------------------------------------------------------------------------
# optimizer-sink scheduling
# ---------------------------------------------------------------------------

def _sgd_fixture(reader_of=None):
    specs = [("x", dict(is_data=True, **_F32_23)),
             ("w", dict(persistable=True, **_F32_23)),
             ("g", dict(**_F32_23)), ("t", dict(**_F32_23)),
             ("lr", dict(persistable=True, shape=[1],
                         dtype="float32"))]
    ops = [("relu", {"X": ["x"]}, {"Out": ["g"]}, {}),
           ("relu", {"X": [reader_of or "x"]}, {"Out": ["t"]}, {}),
           ("sgd", {"Param": ["w"], "Grad": ["g"],
                    "LearningRate": ["lr"]}, {"ParamOut": ["w"]}, {})]
    return _raw_program(specs, ops)


def test_state_update_sinks_past_independent_ops():
    prog = _sgd_fixture()
    moves = state_update_sinks(prog)
    # the sgd can run right after its gradient producer
    assert moves == {2: 1}
    assert apply_state_update_sinks(prog) == 1
    assert [op.type for op in prog.global_block().ops] == \
        ["relu", "sgd", "relu"]
    # sunk schedule still verifies clean
    res = verify_program(prog, feed_names=["x"], fetch_names=["t"],
                         check_shapes=False)
    assert not res.errors()


def test_state_update_sinks_respects_readers_of_the_param():
    # op1 reads w -> sinking the sgd above it would reorder a RAW
    prog = _sgd_fixture(reader_of="w")
    assert state_update_sinks(prog) == {}


def test_state_update_sink_shortens_gradient_lifetime():
    prog = _sgd_fixture()
    before = analyze_program_memory(prog, feed_names=["x"],
                                    fetch_names=["t"])
    assert before.intervals["g"].last_use == 2
    apply_state_update_sinks(prog)
    after = analyze_program_memory(prog, feed_names=["x"],
                                   fetch_names=["t"])
    assert after.intervals["g"].last_use == 1


# ---------------------------------------------------------------------------
# buffer reuse: bit-exact + lower estimated peak on a bench builder
# ---------------------------------------------------------------------------

def _builder_losses(build, level, steps=2, reuse=True):
    prev = _flags(FLAGS_graph_opt_level=level,
                  FLAGS_buffer_reuse=reuse)
    reset_opt_memo()
    try:
        exe, prog, scope, feed, loss, _cfg = build()
        out = []
        with fluid.scope_guard(scope):
            for _ in range(steps):
                lv, = exe.run(prog, feed=feed, fetch_list=[loss])
                out.append(np.asarray(lv))
        exe.close()
        return out
    finally:
        _flags(**prev)
        reset_opt_memo()


def test_reuse_pass_bit_exact_and_lowers_estimated_peak():
    """Acceptance: on the bert builder the level-2 pipeline with buffer
    reuse is bit-exact vs level 0, and the pass itself reports a lower
    estimated peak. (The reuse-off arm and the other builders ride in
    the slow parity sweep below.)"""
    build = _tiny_builds()["bert"]
    l0 = _builder_losses(build, 0)
    l2_on = _builder_losses(build, 2, reuse=True)
    for a, b in zip(l0, l2_on):
        assert np.array_equal(a, b), (l0, l2_on)

    exe, prog, scope, feed, loss, _cfg = build()
    exe.close()
    prev = _flags(FLAGS_buffer_reuse=True)
    try:
        _, report = optimize_program(prog, feed_names=list(feed),
                                     fetch_names=[loss.name], level=2)
    finally:
        _flags(**prev)
    assert not report.get("rejected"), report
    entry = next(p for p in report["passes"]
                 if p["name"] == "buffer_reuse")
    assert entry["reused_vars"] > 0 and entry["sunk_updates"] > 0
    assert entry["est_peak_bytes"] < entry["est_peak_before"], entry


def test_reuse_pass_disabled_by_flag():
    prog = _mib_chain()
    prev = _flags(FLAGS_buffer_reuse=False)
    try:
        opt, report = optimize_program(prog, feed_names=["x"],
                                       fetch_names=["out"], level=2)
    finally:
        _flags(**prev)
    entry = next(p for p in report["passes"]
                 if p["name"] == "buffer_reuse")
    assert entry.get("disabled") and entry["reused_vars"] == 0


# ---------------------------------------------------------------------------
# estimator calibration vs the compiled executable
# ---------------------------------------------------------------------------

def _calibrate(model, lo, hi):
    import jax.numpy as jnp
    build = _tiny_builds()[model]
    exe, prog, scope, feed, loss, _cfg = build()
    with fluid.scope_guard(scope):
        step_fn, state, feed_arrays = exe._resolve_step(
            prog, feed, [loss.name], scope, None)
        compiled = step_fn.fn.lower(state, feed_arrays,
                                    jnp.uint32(0)).compile()
        try:
            ma = compiled.memory_analysis()
        except Exception as e:  # pragma: no cover - backend-dependent
            pytest.skip(f"no memory_analysis on this backend: {e}")
    exe.close()
    measured = ma.temp_size_in_bytes + ma.argument_size_in_bytes
    plan = analyze_program_memory(
        prog, feed_names=sorted(feed), fetch_names=[loss.name],
        feed_shapes={k: (tuple(v.shape), str(v.dtype))
                     for k, v in feed.items()})
    assert not plan.dynamic
    ratio = plan.peak_bytes / max(measured, 1)
    assert lo <= ratio <= hi, (model, plan.peak_bytes, measured, ratio)


def test_estimated_peak_calibrates_against_xla_on_bert():
    """Acceptance: the static estimate tracks what XLA actually
    allocates (temp + argument buffers) for the compiled train step."""
    _calibrate("bert", 0.5, 2.0)


@pytest.mark.slow
@pytest.mark.parametrize("model", ["resnet50", "gpt", "transformer",
                                   "deeplab"])
def test_estimated_peak_calibrates_on_all_builders(model):
    _calibrate(model, 1 / 3, 3.0)


@pytest.mark.slow
def test_self_check_memory_full_sweep_exits_zero():
    """--self-check-memory sweeps the planner over every bench builder
    and the whole op matrix (minutes; the fast sampled smoke rides in
    --self-check, covered by test_analysis.py)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"),
         "--self-check-memory"],
        capture_output=True, text=True, timeout=580,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "builders" in r.stdout and "self-check ok" in r.stdout


@pytest.mark.slow
@pytest.mark.parametrize("model", ["bert", "resnet50", "gpt",
                                   "transformer", "deeplab"])
def test_reuse_parity_on_all_builders(model):
    build = _tiny_builds()[model]
    base = _builder_losses(build, 0)
    for reuse in (True, False):
        got = _builder_losses(build, 2, reuse=reuse)
        for a, b in zip(base, got):
            assert np.array_equal(a, b), (model, reuse, base, got)


# ---------------------------------------------------------------------------
# artifact schema + CLI
# ---------------------------------------------------------------------------

def test_memory_plan_record_schema():
    validate = _tools("validate_bench_json").validate_memory_plan
    plan = analyze_program_memory(_mib_chain(), feed_names=["x"],
                                  fetch_names=["out"],
                                  budget_bytes=2 << 20)
    good = plan.to_record(model="mib_chain")
    assert validate(good, where="t") == []
    assert good["est_peak_bytes"] >= good["pinned_bytes"]
    assert any(f["rule"] == "PTV050" for f in good["findings"])
    # invariants the validator must hold
    assert validate({"kind": "memory_plan"}, where="t")  # all missing
    shrunk = dict(good, est_peak_bytes=good["pinned_bytes"] - 1)
    assert any("pinned" in e for e in validate(shrunk, where="t"))
    assert validate(dict(good, ops=True), where="t")  # bool is not int


def test_program_lint_memory_cli_end_to_end(tmp_path):
    """--memory emits a kind="memory_plan" record that the artifact
    validator accepts and metrics_report renders."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        out = layers.softmax(layers.fc(x, size=4))
    exe = fluid.Executor()
    model_dir = str(tmp_path / "model")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [out], exe,
                                      main_program=main)
    log = str(tmp_path / "lint.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"),
         model_dir, "--memory", "--jsonl", "--out", log],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    recs = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    kinds = [rec["kind"] for rec in recs]
    assert kinds == ["program_lint", "memory_plan"]
    mem = recs[1]
    assert mem["est_peak_bytes"] >= mem["pinned_bytes"] > 0
    assert mem["ops"] > 0 and mem["top_residents"]
    # --budget drives PTV050 and the exit code
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"),
         model_dir, "--memory", "--budget", "64", "--jsonl"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r2.returncode == 1, r2.stdout + r2.stderr
    mem2 = [json.loads(ln) for ln in r2.stdout.splitlines()
            if ln.strip()][1]
    assert any(f["rule"] == "PTV050" for f in mem2["findings"])
    # schema + rendering
    assert _tools("validate_bench_json").validate_file(log) == []
    buf = io.StringIO()
    rc = _tools("metrics_report").report(log, out=buf)
    text = buf.getvalue()
    assert rc == 0 and "-- memory" in text and "est_peak=" in text

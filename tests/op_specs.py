"""Per-op test specifications for the registry-wide OpTest sweep.

Reference analogue: the ~557 one-file-per-op tests under
python/paddle/fluid/tests/unittests/ driven by op_test.py. Here one spec
entry per op type drives tests/test_op_sweep.py, which checks:

- the op lowers and executes through the full Program-IR -> Executor ->
  XLA path, matching a direct invocation of its registered lowering
  (`exact`), with finite outputs;
- an optional independent numpy reference (`expect`);
- analytic-vs-numeric gradients for the slots in `grad`
  (get_numeric_gradient discipline, reference op_test.py:47).

Ops that cannot run as a single op (host/RPC loops, control flow needing
sub-blocks, mesh collectives) are in SKIPS with a reason; most have
dedicated tests elsewhere (tests/test_parallel.py, test_ops.py, ...).
The committed OP_TEST_MATRIX.json records the whole registry's status.
"""
from __future__ import annotations

import zlib

import numpy as np

rng = np.random.RandomState(1234)

SPECS = {}
SKIPS = {}


def spec(op, ins=None, attrs=None, grad=(), exact=True, expect=None,
         atol=1e-5, grad_tol=8e-3, is_test=False, finite=True):
    assert op not in SPECS, op
    SPECS[op] = dict(ins=ins or {}, attrs=attrs or {}, grad=tuple(grad),
                     exact=exact, expect=expect, atol=atol,
                     grad_tol=grad_tol, is_test=is_test, finite=finite)
    # Reseed from the op name so the NEXT spec's random draws depend
    # only on its predecessor's name, never on how many values earlier
    # specs consumed — editing one spec's shapes must not perturb every
    # later op's inputs (which turns unrelated kink-adjacent draws into
    # phantom grad-check failures).
    rng.seed(zlib.crc32(op.encode()) & 0x7FFFFFFF)


def skip(op, reason):
    assert op not in SKIPS, op
    SKIPS[op] = reason


def f32(*shape, lo=-1.0, hi=1.0):
    return (rng.uniform(lo, hi, shape)).astype(np.float32)


def pos(*shape, lo=0.1, hi=1.5):
    return rng.uniform(lo, hi, shape).astype(np.float32)


def ints(*shape, lo=0, hi=4):
    return rng.randint(lo, hi, shape).astype(np.int32)


# ---------------------------------------------------------------------------
# unary elementwise: X -> Out. Values chosen away from kinks/domain edges.
# ---------------------------------------------------------------------------
_X = np.array([[0.31, -0.77, 1.42], [0.58, -1.23, 0.09]], np.float32)
_XPOS = np.array([[0.31, 0.77, 1.42], [0.58, 1.23, 0.49]], np.float32)
_XUNIT = np.array([[0.31, -0.77, 0.42], [0.58, -0.23, 0.09]], np.float32)

for _op in ["exp", "tanh", "sigmoid", "sin", "cos", "atan", "erf",
            "softplus", "softsign", "gelu", "logsigmoid", "stanh",
            "square", "swish", "hard_sigmoid", "hard_swish", "elu",
            "selu", "soft_relu", "tanh_shrink"]:
    spec(_op, ins={"X": _X}, grad=["X"])
for _op in ["log", "sqrt", "rsqrt", "reciprocal"]:
    spec(_op, ins={"X": _XPOS}, grad=["X"])
for _op in ["asin", "acos"]:
    spec(_op, ins={"X": _XUNIT}, grad=["X"])
for _op in ["abs", "relu", "relu6", "leaky_relu", "brelu", "hard_shrink",
            "softshrink", "thresholded_relu"]:
    spec(_op, ins={"X": _X}, grad=["X"])
for _op in ["ceil", "floor", "round", "sign"]:
    spec(_op, ins={"X": _X})
spec("pow", ins={"X": _XPOS}, attrs={"factor": 2.0}, grad=["X"])
spec("scale", ins={"X": _X}, attrs={"scale": 2.5, "bias": 0.5},
     grad=["X"], expect=lambda i, a: {"Out": [i["X"] * 2.5 + 0.5]})
spec("clip", ins={"X": _X}, attrs={"min": -0.5, "max": 0.5}, grad=["X"],
     expect=lambda i, a: {"Out": [np.clip(i["X"], -0.5, 0.5)]})
spec("prelu", ins={"X": _X, "Alpha": np.array([0.2], np.float32)},
     attrs={"mode": "all"}, grad=["X"])

# ---------------------------------------------------------------------------
# binary elementwise + comparisons + logical
# ---------------------------------------------------------------------------
_Y = np.array([[0.91, 0.27, -0.62], [1.11, 0.53, -0.88]], np.float32)
for _op, _g in [("elementwise_add", True), ("elementwise_sub", True),
                ("elementwise_mul", True), ("elementwise_max", True),
                ("elementwise_min", True)]:
    spec(_op, ins={"X": _X, "Y": _Y}, grad=["X", "Y"] if _g else ())
spec("elementwise_div", ins={"X": _X, "Y": _Y + 2.0}, grad=["X", "Y"])
spec("elementwise_pow", ins={"X": _XPOS, "Y": _Y}, grad=["X"])
spec("elementwise_mod", ins={"X": ints(2, 3, lo=1, hi=9),
                             "Y": ints(2, 3, lo=2, hi=5)})
spec("elementwise_floordiv", ins={"X": ints(2, 3, lo=1, hi=9),
                                  "Y": ints(2, 3, lo=2, hi=5)})
spec("minus", ins={"X": _X, "Y": _Y}, grad=["X", "Y"],
     expect=lambda i, a: {"Out": [i["X"] - i["Y"]]})
for _op in ["equal", "not_equal", "less_than", "less_equal",
            "greater_than", "greater_equal"]:
    spec(_op, ins={"X": ints(2, 3), "Y": ints(2, 3)})
_B1 = rng.rand(2, 3) > 0.5
_B2 = rng.rand(2, 3) > 0.5
for _op in ["logical_and", "logical_or", "logical_xor"]:
    spec(_op, ins={"X": _B1, "Y": _B2})
spec("logical_not", ins={"X": _B1})

# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
for _op, _arr, _g in [("reduce_sum", _X, True), ("reduce_mean", _X, True),
                      ("reduce_max", _X, True), ("reduce_min", _X, True),
                      ("reduce_prod", _XPOS, True)]:
    spec(_op, ins={"X": _arr}, attrs={"dim": [1], "keep_dim": False},
         grad=["X"] if _g else ())
spec("reduce_all", ins={"X": _B1}, attrs={"dim": [0], "keep_dim": False})
spec("reduce_any", ins={"X": _B1}, attrs={"dim": [0], "keep_dim": False})
spec("sum", ins={"X": [("sum_a", _X), ("sum_b", _Y)]}, grad=["X"],
     expect=lambda i, a: {"Out": [i["sum_a"] + i["sum_b"]]})
spec("mean", ins={"X": _X}, grad=["X"],
     expect=lambda i, a: {"Out": [np.mean(i["X"])]})
spec("cumsum", ins={"X": _X}, attrs={"axis": 1}, grad=["X"],
     expect=lambda i, a: {"Out": [np.cumsum(i["X"], axis=1)]})
spec("l1_norm", ins={"X": _X}, grad=["X"],
     expect=lambda i, a: {"Out": [np.abs(i["X"]).sum()]})
spec("squared_l2_norm", ins={"X": _X}, grad=["X"],
     expect=lambda i, a: {"Out": [(i["X"] ** 2).sum()]})
spec("frobenius_norm" if False else "norm", ins={"X": _X},
     attrs={"axis": 1, "epsilon": 1e-10}, grad=["X"])
spec("l2_normalize", ins={"X": _X}, attrs={"axis": 1}, grad=["X"])
spec("clip_by_norm", ins={"X": _X}, attrs={"max_norm": 1.0}, grad=["X"])

# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------
spec("mul", ins={"X": f32(2, 3), "Y": f32(3, 4)}, grad=["X", "Y"],
     expect=lambda i, a: {"Out": [i["X"] @ i["Y"]]})
spec("matmul", ins={"X": f32(2, 3), "Y": f32(3, 4)}, grad=["X", "Y"],
     expect=lambda i, a: {"Out": [i["X"] @ i["Y"]]})
spec("matmul_v2", ins={"X": f32(2, 3), "Y": f32(3, 4)}, grad=["X", "Y"])
spec("fc", ins={"Input": f32(2, 3), "W": f32(3, 4), "Bias": f32(4)},
     grad=["Input", "W"])
spec("bilinear_tensor_product",
     ins={"X": f32(2, 3), "Y": f32(2, 4), "Weight": f32(5, 3, 4),
          "Bias": f32(1, 5)}, grad=["X", "Y"])
spec("cos_sim", ins={"X": f32(2, 4), "Y": f32(2, 4)}, grad=["X", "Y"])
spec("conv_shift", ins={"X": f32(2, 5), "Y": f32(2, 3)}, grad=["X", "Y"])
spec("fsp", ins={"X": f32(1, 2, 4, 4), "Y": f32(1, 3, 4, 4)},
     grad=["X", "Y"])

# ---------------------------------------------------------------------------
# shape / tensor manipulation
# ---------------------------------------------------------------------------
spec("reshape", ins={"X": _X}, attrs={"shape": [3, 2]}, grad=["X"])
spec("reshape2", ins={"X": _X}, attrs={"shape": [3, 2]}, grad=["X"])
spec("flatten", ins={"X": f32(2, 3, 4)}, attrs={"axis": 1})
spec("flatten2", ins={"X": f32(2, 3, 4)}, attrs={"axis": 1})
spec("squeeze", ins={"X": f32(2, 1, 3)}, attrs={"axes": [1]})
spec("squeeze2", ins={"X": f32(2, 1, 3)}, attrs={"axes": [1]})
spec("unsqueeze", ins={"X": _X}, attrs={"axes": [1]})
spec("unsqueeze2", ins={"X": _X}, attrs={"axes": [1]})
spec("stack", ins={"X": [("stk_a", _X), ("stk_b", _Y)]},
     attrs={"axis": 0}, grad=["X"])
spec("unstack", ins={"X": f32(2, 3)}, attrs={"axis": 0, "num": 2})
spec("concat", ins={"X": [("cc_a", _X), ("cc_b", _Y)]},
     attrs={"axis": 1}, grad=["X"],
     expect=lambda i, a: {"Out": [np.concatenate(
         [i["cc_a"], i["cc_b"]], axis=1)]})
spec("split", ins={"X": f32(2, 6)}, attrs={"num": 2, "axis": 1},
     grad=["X"])
spec("transpose", ins={"X": _X}, attrs={"axis": [1, 0]}, grad=["X"])
spec("transpose2", ins={"X": _X}, attrs={"axis": [1, 0]}, grad=["X"])
spec("slice", ins={"Input": f32(3, 4)},
     attrs={"axes": [0, 1], "starts": [1, 0], "ends": [3, 2]},
     grad=["Input"])
spec("strided_slice", ins={"Input": f32(3, 6)},
     attrs={"axes": [1], "starts": [0], "ends": [6], "strides": [2]},
     grad=["Input"])
spec("expand", ins={"X": f32(1, 3)}, attrs={"expand_times": [2, 1]},
     grad=["X"])
spec("expand_as", ins={"X": f32(1, 3), "target_tensor": f32(2, 3)})
spec("pad", ins={"X": _X}, attrs={"paddings": [1, 1, 0, 2],
                                  "pad_value": 0.0}, grad=["X"])
spec("pad2d", ins={"X": f32(1, 2, 3, 3)},
     attrs={"paddings": [1, 1, 1, 1], "mode": "constant"}, grad=["X"])
spec("pad_constant_like", ins={"X": f32(3, 4), "Y": f32(2, 3)},
     grad=["Y"])
spec("reverse", ins={"X": _X}, attrs={"axis": [1]}, grad=["X"])
spec("gather", ins={"X": f32(4, 3), "Index": ints(2, lo=0, hi=4)},
     grad=["X"])
spec("gather_nd", ins={"X": f32(3, 4),
                       "Index": np.array([[0, 1], [2, 3]], np.int32)},
     grad=["X"])
spec("scatter", ins={"X": f32(4, 3), "Ids": np.array([1, 3], np.int32),
                     "Updates": f32(2, 3)}, attrs={"overwrite": True})
spec("scatter_nd_add",
     ins={"X": f32(4, 3), "Index": np.array([[1], [3]], np.int32),
          "Updates": f32(2, 3)}, grad=["X", "Updates"])
spec("cast", ins={"X": _X}, attrs={"out_dtype": "float32"}, grad=["X"])
spec("assign", ins={"X": _X}, grad=["X"])
spec("shape", ins={"Input": f32(2, 5)})
spec("size", ins={"Input": f32(2, 5)})
spec("diag", ins={"Diagonal": f32(3)})
spec("eye", attrs={"num_rows": 3, "num_columns": 3, "dtype": "float32"})
spec("linspace", ins={"Start": np.array([0.0], np.float32),
                      "Stop": np.array([1.0], np.float32)},
     attrs={"num": 5})   # count must be static under XLA
spec("range", ins={"Start": np.array([0.0], np.float32),
                   "End": np.array([5.0], np.float32),
                   "Step": np.array([1.0], np.float32)},
     attrs={"static_len": 5})  # length must be static under XLA
spec("fill_constant", attrs={"shape": [2, 3], "value": 1.5,
                             "dtype": "float32"},
     expect=lambda i, a: {"Out": [np.full((2, 3), 1.5, np.float32)]})
spec("fill_any_like", ins={"X": _X}, attrs={"value": 2.0})
spec("fill_zeros_like", ins={"X": _X},
     expect=lambda i, a: {"Out": [np.zeros_like(i["X"])]})
spec("fill", attrs={"shape": [2, 2], "value": [3.0, 3.0, 3.0, 3.0],
                    "dtype": "float32"})
spec("fill_constant_batch_size_like", ins={"Input": f32(4, 3)},
     attrs={"shape": [-1, 2], "value": 0.5, "dtype": "float32"})
spec("increment", ins={"X": np.array([1.0], np.float32)},
     attrs={"step": 2.0},
     expect=lambda i, a: {"Out": [np.array([3.0], np.float32)]})
spec("one_hot", ins={"X": np.array([[1], [3]], np.int32)},
     attrs={"depth": 4})
spec("one_hot_v2", ins={"X": np.array([1, 3], np.int32)},
     attrs={"depth": 4})
spec("shard_index", ins={"X": np.array([[1], [5]], np.int64)},
     attrs={"index_num": 8, "nshards": 2, "shard_id": 0,
            "ignore_value": -1})
spec("where", ins={"Condition": _B1})
spec("unique", ins={"X": np.array([3, 1, 3, 2], np.int32)})
spec("unique_with_counts", ins={"X": np.array([3, 1, 3, 2], np.int32)})
spec("top_k", ins={"X": f32(2, 5)}, attrs={"k": 2})
spec("arg_max", ins={"X": f32(2, 5)}, attrs={"axis": 1})
spec("arg_min", ins={"X": f32(2, 5)}, attrs={"axis": 1})
spec("argsort", ins={"X": f32(2, 5)}, attrs={"axis": 1})
spec("is_empty", ins={"X": f32(2)})
spec("isfinite", ins={"X": _X})
spec("has_inf", ins={"X": _X})
spec("has_nan", ins={"X": _X})
spec("multiplex", ins={"X": [("mpx_a", f32(2, 3)), ("mpx_b", f32(2, 3))],
                       "Ids": np.array([[1], [0]], np.int32)})
spec("assign_value", attrs={"shape": [2, 2],
                            "values": [1.0, 2.0, 3.0, 4.0],
                            "dtype": "float32"})
spec("lod_reset", ins={"X": f32(4, 2),
                       "Y": np.array([0, 2, 4], np.int32)})
spec("sequence_mask", ins={"X": np.array([1, 3], np.int64)},
     attrs={"maxlen": 4})
spec("space_to_depth", ins={"X": f32(1, 2, 4, 4)}, attrs={"blocksize": 2},
     grad=["X"])
spec("pixel_shuffle", ins={"X": f32(1, 4, 2, 2)},
     attrs={"upscale_factor": 2}, grad=["X"])
spec("shuffle_channel", ins={"X": f32(1, 4, 2, 2)}, attrs={"group": 2},
     grad=["X"])

# ---------------------------------------------------------------------------
# embedding / lookup
# ---------------------------------------------------------------------------
spec("lookup_table", ins={"W": f32(6, 3),
                          "Ids": np.array([[1], [4]], np.int64)},
     grad=["W"])
spec("lookup_table_v2", ins={"W": f32(6, 3),
                             "Ids": np.array([1, 4], np.int64)},
     grad=["W"])

# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
_PROB = np.array([[0.2, 0.5, 0.3], [0.6, 0.1, 0.3]], np.float32)
_LBL = np.array([[1], [0]], np.int64)
spec("cross_entropy", ins={"X": _PROB, "Label": _LBL}, grad=["X"])
spec("cross_entropy2", ins={"X": _PROB, "Label": _LBL}, grad=["X"])
spec("bpr_loss", ins={"X": _PROB, "Label": _LBL}, grad=["X"])
spec("softmax_with_cross_entropy", ins={"Logits": f32(2, 4),
                                        "Label": _LBL}, grad=["Logits"])
spec("sigmoid_cross_entropy_with_logits",
     ins={"X": f32(2, 3), "Label": rng.rand(2, 3).astype(np.float32)},
     grad=["X"])
spec("hinge_loss", ins={"Logits": np.array([[0.3], [-0.4]], np.float32),
                        "Labels": np.array([[1.0], [0.0]], np.float32)},
     grad=["Logits"])  # values keep 1 -/+ x away from the hinge kink
spec("huber_loss", ins={"X": f32(2, 1), "Y": f32(2, 1)},
     attrs={"delta": 1.0}, grad=["X"])
spec("kldiv_loss", ins={"X": np.log(_PROB), "Target": _PROB},
     attrs={"reduction": "mean"}, grad=["X"])
spec("log_loss", ins={"Predicted": _PROB[:, :1] * 0.8 + 0.1,
                      "Labels": np.array([[1.0], [0.0]], np.float32)},
     attrs={"epsilon": 1e-4}, grad=["Predicted"])
spec("mse_loss", ins={"X": f32(2, 3), "Y": f32(2, 3)}, grad=["X"])
spec("rank_loss", ins={"Label": np.array([[1.0], [0.0]], np.float32),
                       "Left": f32(2, 1), "Right": f32(2, 1)},
     grad=["Left", "Right"])
spec("margin_rank_loss", ins={"Label": np.array([[1.0], [-1.0]],
                                                np.float32),
                              "X1": f32(2, 1), "X2": f32(2, 1)},
     attrs={"margin": 0.1}, grad=["X1", "X2"])
spec("smooth_l1_loss", ins={"X": f32(2, 3), "Y": f32(2, 3)}, grad=["X"])
spec("modified_huber_loss",
     ins={"X": f32(2, 1), "Y": np.array([[1.0], [0.0]], np.float32)},
     grad=["X"])
spec("squared_l2_distance", ins={"X": f32(2, 3), "Y": f32(2, 3)},
     grad=["X"])
spec("dice_loss", ins={"X": _PROB[:, :1],
                       "Label": np.array([[1], [0]], np.int64)})
spec("npair_loss", ins={"Anchor": f32(2, 4), "Positive": f32(2, 4),
                        "Labels": np.array([0, 1], np.int64)},
     attrs={"l2_reg": 0.002}, grad=["Anchor", "Positive"])
spec("center_loss",
     ins={"X": f32(2, 4), "Label": np.array([[0], [1]], np.int64),
          "Centers": f32(3, 4),
          "CenterUpdateRate": np.array([0.1], np.float32)},
     attrs={"cluster_num": 3, "need_update": True}, grad=["X"])
spec("teacher_student_sigmoid_loss",
     ins={"X": f32(2, 1), "Label": np.array([[1.0], [0.0]], np.float32)},
     grad=["X"])
spec("sigmoid_focal_loss",
     ins={"X": f32(2, 3), "Label": np.array([[1], [0]], np.int32),
          "FgNum": np.array([1], np.int32)},
     attrs={"gamma": 2.0, "alpha": 0.25}, grad=["X"])
spec("label_smooth", ins={"X": _PROB}, attrs={"epsilon": 0.1},
     grad=["X"])
spec("log_softmax", ins={"X": f32(2, 4)}, grad=["X"])
spec("softmax", ins={"X": f32(2, 4)}, grad=["X"])

# ---------------------------------------------------------------------------
# optimizer update ops (output check only; inplace semantics)
# ---------------------------------------------------------------------------
_P, _G = f32(3, 2), f32(3, 2)
_LR = np.array([0.1], np.float32)
spec("sgd", ins={"Param": _P, "Grad": _G, "LearningRate": _LR},
     expect=lambda i, a: {"ParamOut": [i["Param"] - 0.1 * i["Grad"]]})
spec("momentum", ins={"Param": _P, "Grad": _G, "Velocity": f32(3, 2),
                      "LearningRate": _LR}, attrs={"mu": 0.9})
spec("adam", ins={"Param": _P, "Grad": _G, "Moment1": f32(3, 2),
                  "Moment2": pos(3, 2), "LearningRate": _LR,
                  "Beta1Pow": np.array([0.9], np.float32),
                  "Beta2Pow": np.array([0.999], np.float32)},
     attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
spec("adamw", ins={"Param": _P, "Grad": _G, "Moment1": f32(3, 2),
                   "Moment2": pos(3, 2), "LearningRate": _LR,
                   "Beta1Pow": np.array([0.9], np.float32),
                   "Beta2Pow": np.array([0.999], np.float32)},
     attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
            "coeff": 0.01})
spec("adamax", ins={"Param": _P, "Grad": _G, "Moment": f32(3, 2),
                    "InfNorm": pos(3, 2), "LearningRate": _LR,
                    "Beta1Pow": np.array([0.9], np.float32)},
     attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
spec("adagrad", ins={"Param": _P, "Grad": _G, "Moment": pos(3, 2),
                     "LearningRate": _LR}, attrs={"epsilon": 1e-6})
spec("adadelta", ins={"Param": _P, "Grad": _G,
                      "AvgSquaredGrad": pos(3, 2),
                      "AvgSquaredUpdate": pos(3, 2)},
     attrs={"rho": 0.95, "epsilon": 1e-6})
spec("decayed_adagrad", ins={"Param": _P, "Grad": _G,
                             "Moment": pos(3, 2), "LearningRate": _LR},
     attrs={"decay": 0.95, "epsilon": 1e-6})
spec("rmsprop", ins={"Param": _P, "Grad": _G, "MeanSquare": pos(3, 2),
                     "Moment": f32(3, 2), "LearningRate": _LR,
                     "MeanGrad": f32(3, 2)},
     attrs={"decay": 0.9, "epsilon": 1e-6, "momentum": 0.9})
spec("ftrl", ins={"Param": _P, "Grad": _G, "SquaredAccumulator": pos(3, 2),
                  "LinearAccumulator": f32(3, 2), "LearningRate": _LR},
     attrs={"l1": 0.01, "l2": 0.01, "lr_power": -0.5})
spec("lamb", ins={"Param": _P, "Grad": _G, "Moment1": f32(3, 2),
                  "Moment2": pos(3, 2), "LearningRate": _LR,
                  "Beta1Pow": np.array([0.9], np.float32),
                  "Beta2Pow": np.array([0.999], np.float32)},
     attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6,
            "weight_decay": 0.01})
spec("lars_momentum", ins={"Param": _P, "Grad": _G,
                           "Velocity": f32(3, 2), "LearningRate": _LR},
     attrs={"mu": 0.9, "lars_coeff": 0.001, "lars_weight_decay": 0.0005})
spec("proximal_gd", ins={"Param": _P, "Grad": _G, "LearningRate": _LR},
     attrs={"l1": 0.01, "l2": 0.01})
spec("proximal_adagrad", ins={"Param": _P, "Grad": _G,
                              "Moment": pos(3, 2), "LearningRate": _LR},
     attrs={"l1": 0.01, "l2": 0.01, "epsilon": 1e-6})
spec("dpsgd", ins={"Param": _P, "Grad": _G, "LearningRate": _LR},
     attrs={"batch_size": 2.0, "sigma": 0.0, "clip": 10.0}, exact=False)
spec("dgc_momentum", ins={"Param": _P, "Grad": _G, "Velocity": f32(3, 2),
                          "LearningRate": _LR,
                          "current_step": np.array([0.0], np.float32)},
     attrs={"mu": 0.9, "rampup_begin_step": 100.0})

# ---------------------------------------------------------------------------
# random / init ops (distribution checks only)
# ---------------------------------------------------------------------------
spec("uniform_random", attrs={"shape": [4, 3], "min": -1.0, "max": 1.0,
                              "dtype": "float32"}, exact=False)
spec("gaussian_random", attrs={"shape": [4, 3], "mean": 0.0, "std": 1.0,
                               "dtype": "float32"}, exact=False)
spec("truncated_gaussian_random",
     attrs={"shape": [4, 3], "mean": 0.0, "std": 1.0,
            "dtype": "float32"}, exact=False)
spec("uniform_random_batch_size_like", ins={"Input": f32(4, 3)},
     attrs={"shape": [-1, 2], "min": -1.0, "max": 1.0,
            "dtype": "float32"}, exact=False)
spec("gaussian_random_batch_size_like", ins={"Input": f32(4, 3)},
     attrs={"shape": [-1, 2], "mean": 0.0, "std": 1.0,
            "dtype": "float32"}, exact=False)
spec("randint", attrs={"shape": [4], "low": 0, "high": 5}, exact=False)
spec("sampling_id", ins={"X": _PROB}, exact=False)
spec("random_crop", ins={"X": f32(1, 3, 5, 5), "Seed": np.array([7],
                                                                np.int64)},
     attrs={"shape": [3, 3, 3]}, exact=False)
spec("dropout", ins={"X": f32(2, 3)},
     attrs={"dropout_prob": 0.5, "is_test": True}, is_test=True,
     expect=lambda i, a: {"Out": [i["X"] * 0.5]})

# ---------------------------------------------------------------------------
# skips: ops that cannot run as an isolated single op
# ---------------------------------------------------------------------------
for _op in ["feed", "fetch"]:
    skip(_op, "executor-internal feed/fetch plumbing; exercised by every "
              "exe.run test")
for _op in ["while", "conditional_block", "recurrent",
            "recompute_segment"]:
    skip(_op, "needs a sub-block program; covered in tests/test_ops.py / "
              "test_rnn.py / test_parallel.py")
for _op in ["select_input", "merge_lod_tensor", "split_lod_tensor",
            "array_to_lod_tensor", "lod_tensor_to_array",
            "write_to_array", "read_from_array", "tensor_array_to_tensor",
            "lod_array_length", "lod_rank_table", "max_sequence_len",
            "shrink_rnn_memory", "rnn_memory_helper",
            "reorder_lod_tensor_by_rank", "beam_search",
            "beam_search_decode", "beam_reorder", "gather_tree"]:
    skip(_op, "LoDTensorArray / decode-loop op; covered via "
              "layers.control_flow and rnn decode tests")
for _op in ["listen_and_serv", "send", "recv", "prefetch",
            "fetch_barrier", "send_barrier", "gen_nccl_id",
            "c_gen_nccl_id", "c_comm_init", "c_comm_init_all",
            "checkpoint_notify", "geo_sgd_send", "ref_by_trainer_id",
            "distributed_lookup_table", "lookup_sparse_table",
            "split_ids", "merge_ids", "split_byref",
            "fl_listen_and_serv" if False else "delete_var"]:
    skip(_op, "host-side PS/RPC runtime op; covered in "
              "tests/test_distributed.py")
for _op in ["c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
            "c_allreduce_prod", "c_allgather", "c_reducescatter",
            "c_broadcast", "c_sync_calc_stream", "c_sync_comm_stream",
            "allreduce", "broadcast", "shard_hint", "ring_attention",
            "ulysses_attention", "c_alltoall", "moe_ffn",  # op bodies exercised in
            # tests/test_parallel.py (c_alltoall, seq-parallel op) and
            # tests/test_kernels.py (sharded fns)
            "sync_batch_norm"]:
    skip(_op, "mesh collective; covered in tests/test_parallel.py on the "
              "8-device CPU mesh")
for _op in ["save", "save_combine", "load", "load_combine"]:
    skip(_op, "host IO op; covered by tests/test_models.py save/load and "
              "test_jit_and_extras.py")
skip("paged_attention", "stateful decode op over externally-allocated "
     "KV block pools + block table; token-exact parity vs the slab "
     "path is covered in tests/test_generation.py and the allocator in "
     "tests/test_kv_blocks.py")
skip("print", "host-side debug print (io_callback); side-effect only")
skip("py_func", "wraps arbitrary user Python; covered in "
                "test_jit_and_extras.py")
skip("get_places", "host device-enumeration helper")
skip("fake_init", "PS-mode placeholder init; no computation")
skip("grad::generic", "internal vjp grad dispatcher; exercised by every "
                      "check_grad in this sweep")
skip("fused_elementwise", "emitted only by the level-2 fusion pass; "
                          "bit-exact replay covered by "
                          "tests/test_graph_passes.py parity sweeps")
skip("split_selected_rows", "SelectedRows compat view; covered in "
                            "test_parity_ops.py")
skip("merge_selected_rows", "SelectedRows compat view; covered in "
                            "test_parity_ops.py")
skip("get_tensor_from_selected_rows", "SelectedRows compat view")
skip("coalesce_tensor", "aliasing buffer fusion helper; XLA owns buffer "
                        "layout on TPU (no-op lowering)")

# ===========================================================================
# batch 2: conv/pool/norm, interp, sequence, RNN, detection, quant, metrics
# ===========================================================================

# --- conv / pool -----------------------------------------------------------
_IMG = f32(1, 2, 5, 5)
spec("conv2d", ins={"Input": _IMG, "Filter": f32(3, 2, 3, 3)},
     attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
            "groups": 1}, grad=["Input", "Filter"])
spec("depthwise_conv2d", ins={"Input": _IMG, "Filter": f32(2, 1, 3, 3)},
     attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
            "groups": 2}, grad=["Input", "Filter"])
spec("conv2d_transpose", ins={"Input": f32(1, 2, 3, 3),
                              "Filter": f32(2, 3, 3, 3)},
     attrs={"strides": [2, 2], "paddings": [0, 0], "dilations": [1, 1],
            "groups": 1}, grad=["Input", "Filter"])
spec("depthwise_conv2d_transpose",
     ins={"Input": f32(1, 2, 3, 3), "Filter": f32(2, 1, 3, 3)},
     attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
            "groups": 2}, grad=["Input"])
spec("conv3d", ins={"Input": f32(1, 2, 4, 4, 4),
                    "Filter": f32(3, 2, 3, 3, 3)},
     attrs={"strides": [1, 1, 1], "paddings": [1, 1, 1],
            "dilations": [1, 1, 1], "groups": 1}, grad=["Input"])
spec("conv3d_transpose", ins={"Input": f32(1, 2, 3, 3, 3),
                              "Filter": f32(2, 3, 3, 3, 3)},
     attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
            "dilations": [1, 1, 1], "groups": 1}, grad=["Input"])
spec("pool2d", ins={"X": f32(1, 2, 4, 4)},
     attrs={"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
            "paddings": [0, 0]}, grad=["X"])
spec("pool3d", ins={"X": f32(1, 2, 4, 4, 4)},
     attrs={"pooling_type": "max", "ksize": [2, 2, 2],
            "strides": [2, 2, 2], "paddings": [0, 0, 0]}, grad=["X"])
# well-separated values: numeric-grad deltas must not flip a window max
_POOLX = (np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4) * 0.137
          )[:, :, ::-1]
spec("max_pool2d_with_index", ins={"X": _POOLX.copy()},
     attrs={"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]},
     grad=["X"])
spec("max_pool3d_with_index", ins={"X": f32(1, 2, 4, 4, 4)},
     attrs={"ksize": [2, 2, 2], "strides": [2, 2, 2],
            "paddings": [0, 0, 0]})
spec("unpool", ins={"X": f32(1, 2, 2, 2),
                    "Indices": np.array(
                        [[[[0, 3], [8, 11]], [[0, 3], [8, 11]]]],
                        np.int32)},
     attrs={"unpooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
            "paddings": [0, 0]})
spec("spp", ins={"X": f32(1, 2, 4, 4)},
     attrs={"pyramid_height": 2, "pooling_type": "max"})
spec("unfold", ins={"X": f32(1, 2, 4, 4)},
     attrs={"kernel_sizes": [2, 2], "strides": [1, 1],
            "paddings": [0, 0, 0, 0], "dilations": [1, 1]}, grad=["X"])
spec("maxout", ins={"X": f32(1, 4, 3, 3)}, attrs={"groups": 2},
     grad=["X"])

# --- norms -----------------------------------------------------------------
_BN = dict(ins={"X": f32(2, 3, 4, 4), "Scale": pos(3), "Bias": f32(3),
                "Mean": f32(3), "Variance": pos(3)},
           attrs={"is_test": True, "epsilon": 1e-5, "momentum": 0.9})
spec("batch_norm", is_test=True, **_BN)
spec("layer_norm", ins={"X": f32(2, 6), "Scale": pos(6), "Bias": f32(6)},
     attrs={"begin_norm_axis": 1, "epsilon": 1e-5},
     grad=["X", "Scale", "Bias"])
spec("instance_norm", ins={"X": f32(2, 3, 4, 4), "Scale": pos(3),
                           "Bias": f32(3)},
     attrs={"epsilon": 1e-5}, grad=["X"])
spec("group_norm", ins={"X": f32(2, 4, 3, 3), "Scale": pos(4),
                        "Bias": f32(4)},
     attrs={"groups": 2, "epsilon": 1e-5}, grad=["X"])
spec("data_norm", ins={"X": f32(2, 3), "BatchSize": pos(3, lo=4, hi=8),
                       "BatchSum": f32(3), "BatchSquareSum": pos(3,
                                                                lo=4,
                                                                hi=8)})
spec("lrn", ins={"X": f32(1, 4, 3, 3)},
     attrs={"n": 4, "k": 1.0, "alpha": 1e-4, "beta": 0.75}, grad=["X"])
spec("spectral_norm", ins={"Weight": f32(6, 4), "U": f32(6),
                           "V": f32(4)},
     attrs={"power_iters": 5, "eps": 1e-12})
spec("affine_channel", ins={"X": f32(1, 3, 2, 2), "Scale": pos(3),
                            "Bias": f32(3)}, grad=["X"])
spec("add_position_encoding", ins={"X": f32(2, 4, 6)},
     attrs={"alpha": 1.0, "beta": 1.0}, grad=["X"])
spec("temporal_shift", ins={"X": f32(4, 4, 2, 2)},
     attrs={"seg_num": 2, "shift_ratio": 0.25}, grad=["X"])

# --- interpolation / warping ----------------------------------------------
spec("bilinear_interp", ins={"X": f32(1, 2, 3, 3)},
     attrs={"out_h": 6, "out_w": 6, "align_corners": False},
     grad=["X"])
spec("nearest_interp", ins={"X": f32(1, 2, 3, 3)},
     attrs={"out_h": 6, "out_w": 6, "align_corners": False})
spec("trilinear_interp", ins={"X": f32(1, 2, 3, 3, 3)},
     attrs={"out_d": 6, "out_h": 6, "out_w": 6,
            "align_corners": False})
spec("affine_grid", ins={"Theta": f32(1, 2, 3)},
     attrs={"output_shape": [1, 1, 4, 4]}, grad=["Theta"])
spec("grid_sampler", ins={"X": f32(1, 2, 4, 4),
                          "Grid": f32(1, 3, 3, 2, lo=-0.9, hi=0.9)},
     grad=["X"])
spec("crop", ins={"X": f32(4, 6)}, attrs={"shape": [2, 3],
                                          "offsets": [1, 2]},
     grad=["X"])
spec("crop_tensor", ins={"X": f32(4, 6)},
     attrs={"shape": [2, 3], "offsets": [1, 2]}, grad=["X"])
spec("square_error_cost", ins={"X": f32(2, 3), "Y": f32(2, 3)},
     grad=["X", "Y"],
     expect=lambda i, a: {"Out": [(i["X__in"] - i["Y__in"]) ** 2]
                          } if False else {
         "Out": [(i["X__in"] - i["Y__in"]) ** 2]})

# --- sequence (padded + lengths design) ------------------------------------
_SEQ = f32(2, 4, 3)
_LENS = np.array([3, 4], np.int64)
spec("sequence_pool", ins={"X": _SEQ, "Lengths": _LENS},
     attrs={"pooltype": "SUM"}, grad=["X"])
spec("sequence_softmax", ins={"X": f32(2, 4), "Lengths": _LENS},
     grad=["X"])
spec("sequence_reverse", ins={"X": _SEQ, "Lengths": _LENS}, grad=["X"])
spec("sequence_pad", ins={"X": _SEQ,
                          "PadValue": np.zeros((1,), np.float32)},
     attrs={"padded_length": 5})
spec("sequence_unpad", ins={"X": _SEQ, "Length": _LENS})
spec("sequence_expand", ins={"X": f32(2, 3), "Y": f32(4, 3)},
     attrs={"ref_level": 0})
spec("sequence_expand_as", ins={"X": f32(2, 3), "Y": f32(2, 3)})
spec("sequence_concat", ins={"X": [("sqc_a", _SEQ), ("sqc_b",
                                                     f32(2, 4, 3))]},
     grad=["X"])  # entries must be distinct buffers for the numeric pass
spec("sequence_conv", ins={"X": _SEQ, "Filter": f32(9, 4)},
     attrs={"contextLength": 3, "contextStart": -1},
     grad=["X", "Filter"])
spec("sequence_enumerate",
     ins={"X": np.array([[1, 2, 3, 4]], np.int64)},
     attrs={"win_size": 2, "pad_value": 0})
spec("sequence_erase", ins={"X": np.array([[1, 2, 0, 3]], np.int64)},
     attrs={"tokens": [0]})
spec("sequence_reshape", ins={"X": f32(2, 4, 6)}, attrs={"new_dim": 8})
spec("sequence_scatter",
     ins={"X": f32(2, 6), "Ids": np.array([[1, 3], [0, 2]], np.int64),
          "Updates": f32(2, 2)})
spec("sequence_slice", ins={"X": _SEQ,
                            "Offset": np.array([[0], [1]], np.int64),
                            "Length": np.array([[2], [2]], np.int64)})
spec("sequence_topk_avg_pooling",
     ins={"X": f32(1, 1, 4, 4), "ROW": f32(1, 4, 1),
          "COLUMN": f32(1, 4, 1)},
     attrs={"topks": [1, 2], "channel_num": 1})
spec("im2sequence", ins={"X": f32(1, 2, 4, 4)},
     attrs={"kernels": [2, 2], "strides": [2, 2],
            "paddings": [0, 0, 0, 0]})
spec("row_conv", ins={"X": f32(2, 5, 3), "Filter": f32(2, 3)},
     grad=["X", "Filter"])
spec("match_matrix_tensor", ins={"X": f32(1, 3, 4), "Y": f32(1, 5, 4),
                                 "W": f32(4, 2, 4)},
     attrs={"dim_t": 2})
spec("var_conv_2d", ins={"X": f32(1, 2, 4, 4), "W": f32(3, 2, 3, 3)},
     attrs={"OutputChannel": 3, "InputChannel": 2, "KernelH": 3,
            "KernelW": 3, "StrideH": 1, "StrideW": 1})
# batch 0: branching tree (1->2,3; 2->4,5) exercises sibling
# index/count weights at depth 2; batch 1: chain whose post-(0,0) edge
# must be IGNORED (construct_tree break semantics)
spec("tree_conv", ins={"NodesVector": f32(2, 6, 3),
                       "EdgeSet": np.array(
                           [[[1, 2], [1, 3], [2, 4], [2, 5], [0, 0]],
                            [[1, 2], [2, 3], [3, 4], [0, 0], [5, 6]]],
                           np.int32),
                       "Filter": f32(3, 3, 2, 2)},
     attrs={"max_depth": 3}, grad=["NodesVector", "Filter"])
spec("filter_by_instag",
     ins={"Ins": f32(3, 2), "Ins_tag": np.array([1, 2, 1], np.int64),
          "Filter_tag": np.array([1], np.int64)},
     attrs={"is_lod": False})
# rectangular A!=B plus two indexes: exercises the greedy
# row/column-retirement order and the cross-index mask union
spec("similarity_focus", ins={"X": f32(2, 3, 4, 5)},
     attrs={"axis": 1, "indexes": [0, 2]})
# no grad check: the reference injects the CVM input as the show/click
# column gradients (cvm_op.h CvmGradComputeKernel) — intentionally NOT
# the numeric derivative of the forward's log transform
spec("cvm", ins={"X": pos(2, 4), "CVM": f32(2, 2)},
     attrs={"use_cvm": True})
# rows of 5 int64 lanes = 40 bytes: exercises BOTH the 32-byte stripe
# accumulator and the 8-byte tail path of XXH64
spec("hash", ins={"X": np.array([[1, 2, 3, 4, 5],
                                 [3, 4, 5, 6, 7]], np.int64)},
     attrs={"num_hash": 2, "mod_by": 1000})

# --- RNN family ------------------------------------------------------------
spec("gru", ins={"Input": f32(2, 4, 9), "Weight": f32(3, 9),
                 "Bias": f32(1, 9)},
     attrs={"activation": "tanh", "gate_activation": "sigmoid"},
     grad=["Input"])
spec("gru_unit", ins={"Input": f32(2, 9), "HiddenPrev": f32(2, 3),
                      "Weight": f32(3, 9), "Bias": f32(1, 9)},
     grad=["Input"])
spec("lstm", ins={"Input": f32(2, 4, 12), "Weight": f32(3, 12),
                  "Bias": f32(1, 12)},
     attrs={"use_peepholes": False}, grad=["Input"])
spec("lstm_unit", ins={"X": f32(2, 12), "C_prev": f32(2, 3)},
     grad=["X"])
spec("lstmp", ins={"Input": f32(2, 4, 12), "Weight": f32(2, 12),
                   "ProjWeight": f32(3, 2), "Bias": f32(1, 12)},
     grad=["Input"])
spec("cudnn_lstm",
     ins={"Input": f32(5, 2, 3), "InitH": np.zeros((1, 2, 4), np.float32),
          "InitC": np.zeros((1, 2, 4), np.float32),
          "W": f32(4 * 4 * 3 + 4 * 4 * 4 + 8 * 4) * 0.1},
     attrs={"hidden_size": 4, "num_layers": 1}, grad=["Input"])
spec("cudnn_gru",
     ins={"Input": f32(5, 2, 3), "InitH": np.zeros((1, 2, 4), np.float32),
          "W": f32(3 * 4 * 3 + 3 * 4 * 4 + 6 * 4) * 0.1},
     attrs={"hidden_size": 4, "num_layers": 1})
spec("attention_lstm",
     ins={"X": f32(2, 4, 6), "C0": f32(2, 3),
          "AttentionWeight": f32(9, 1),
          "LSTMWeight": f32(9, 12), "LSTMBias": f32(1, 12)})
spec("multihead_matmul",
     ins={"Q": f32(2, 4, 6), "K": f32(2, 4, 6), "V": f32(2, 4, 6),
          "BiasQ": f32(6), "BiasK": f32(6), "BiasV": f32(6),
          "BiasQK": f32(2, 2, 4, 4)},
     attrs={"head_number": 2, "alpha": 0.4})
spec("fused_elemwise_activation",
     ins={"X": f32(2, 3), "Y": f32(2, 3)},
     attrs={"functor_list": ["elementwise_add", "relu"]}, grad=["X"])
spec("fused_embedding_seq_pool",
     ins={"W": f32(6, 3), "Ids": np.array([[[1], [4]], [[2], [0]]],
                                          np.int64)},
     attrs={"combiner": "sum"}, grad=["W"])
spec("fused_fc_elementwise_layernorm",
     ins={"X": f32(2, 3), "W": f32(3, 4), "Y": f32(2, 4),
          "Scale": pos(4), "Bias1": f32(4)},
     attrs={"epsilon": 1e-5})
spec("fusion_gru", ins={"X": f32(2, 4, 3), "WeightX": f32(3, 9),
                        "WeightH": f32(3, 9), "Bias": f32(1, 9)},
     attrs={"activation": "tanh", "gate_activation": "sigmoid"})
spec("fusion_lstm", ins={"X": f32(2, 4, 3), "WeightX": f32(3, 12),
                         "WeightH": f32(3, 12), "Bias": f32(1, 12)})
spec("fusion_repeated_fc_relu",
     ins={"X": f32(2, 3), "W": [("frfr_w1", f32(3, 4)),
                                ("frfr_w2", f32(4, 2))],
          "Bias": [("frfr_b1", f32(4)), ("frfr_b2", f32(2))]})
spec("fusion_seqconv_eltadd_relu",
     ins={"X": f32(2, 5, 3), "Filter": f32(9, 4), "Bias": f32(4)},
     attrs={"contextLength": 3, "contextStart": -1})
spec("fusion_seqexpand_concat_fc",
     ins={"X": [("fsecf_a", f32(2, 4, 3)), ("fsecf_b", f32(2, 3))],
          "FCWeight": f32(6, 5)},
     attrs={"fc_activation": "relu"})
spec("fusion_seqpool_concat",
     ins={"X": [("fspc_a", f32(2, 4, 3)), ("fspc_b", f32(2, 4, 3))]},
     attrs={"pooltype": "SUM"})
spec("fusion_squared_mat_sub", ins={"X": f32(2, 3), "Y": f32(3, 4)},
     attrs={"scalar": 1.0})
spec("fusion_transpose_flatten_concat",
     ins={"X": [("ftfc_a", f32(2, 3, 4)), ("ftfc_b", f32(2, 3, 4))]},
     attrs={"trans_axis": [0, 2, 1], "flatten_axis": 1,
            "concat_axis": 1})

# --- CTC / CRF / metrics ---------------------------------------------------
spec("warpctc", ins={"Logits": f32(1, 4, 3),
                     "Label": np.array([[1, 2]], np.int64)},
     attrs={"blank": 0}, grad=["Logits"])
spec("ctc_align", ins={"Input": np.array([[1, 1, 0, 2]], np.int32)},
     attrs={"blank": 0})
spec("edit_distance", ins={"Hyps": np.array([[1, 2, 3, -1]], np.int64),
                           "Refs": np.array([[1, 3, 3, -1]], np.int64)},
     attrs={"normalized": False})
spec("linear_chain_crf",
     ins={"Emission": f32(2, 4, 3), "Transition": f32(5, 3),
          "Label": ints(2, 4, lo=0, hi=3).astype(np.int64)},
     grad=["Emission"])
spec("crf_decoding", ins={"Emission": f32(1, 3, 2),
                          "Transition": np.zeros((4, 2), np.float32)})
spec("accuracy", ins={"Out": _PROB,
                      "Indices": np.array([[1], [0]], np.int64),
                      "Label": _LBL})
spec("mean_iou", ins={"Predictions": ints(2, 3, lo=0, hi=3),
                      "Labels": ints(2, 3, lo=0, hi=3)},
     attrs={"num_classes": 3})
spec("auc", ins={"Predict": _PROB[:, :2],
                 "Label": np.array([[1], [0]], np.int64),
                 "StatPos": np.zeros(201, np.int64),
                 "StatNeg": np.zeros(201, np.int64)},
     attrs={"num_thresholds": 200})
spec("precision_recall",
     ins={"MaxProbs": _PROB[:, :1],
          "Indices": np.array([[1], [0]], np.int64),
          "Labels": np.array([[1], [0]], np.int64),
          "StatesInfo": np.zeros((3, 4), np.int64)},
     attrs={"class_number": 3})
# imperfect IOB inputs: split spans (B where the label has I), merged
# spans (I where the label has B), I-after-O chunk starts, type
# changes mid-chunk, chunks ending at the sequence boundary
# (tags for num_chunk_types=2: B0=0 I0=1 B1=2 I1=3 O=4)
spec("chunk_eval",
     ins={"Inference": np.array([[0, 1, 4, 0, 3, 2, 4, 1],
                                 [2, 3, 3, 0, 4, 4, 0, 1]], np.int64),
          "Label": np.array([[0, 1, 1, 4, 2, 3, 4, 1],
                             [2, 3, 0, 1, 4, 4, 0, 0]], np.int64)},
     attrs={"num_chunk_types": 2, "chunk_scheme": "IOB"})
spec("positive_negative_pair",
     ins={"Score": f32(4, 1), "Label": np.array([[1.], [0.], [1.], [0.]],
                                                np.float32),
          "QueryID": np.array([[1], [1], [1], [1]], np.int64)})
spec("nce", ins={"Input": f32(4, 8), "Weight": f32(20, 8),
                 "Label": ints(4, 1, lo=0, hi=20).astype(np.int64)},
     attrs={"num_neg_samples": 5, "num_total_classes": 20}, exact=False)
spec("sample_logits", ins={"Logits": f32(2, 10),
                           "Labels": ints(2, 1, lo=0,
                                          hi=10).astype(np.int64)},
     attrs={"num_samples": 4}, exact=False)
spec("hierarchical_sigmoid",
     ins={"X": f32(4, 8), "W": f32(7, 8),
          "Label": ints(4, 1, lo=0, hi=8).astype(np.int64)},
     attrs={"num_classes": 8}, grad=["X", "W"])

# --- quantization ----------------------------------------------------------
# no grad checks on fake-quant ops: the registered STE gradient is
# intentionally NOT the numeric derivative of the staircase
spec("fake_quantize_abs_max", ins={"X": _X}, attrs={"bit_length": 8})
spec("fake_channel_wise_quantize_abs_max", ins={"X": f32(3, 4)},
     attrs={"bit_length": 8})
spec("fake_quantize_moving_average_abs_max",
     ins={"X": _X, "InScale": np.array([1.0], np.float32)},
     attrs={"bit_length": 8, "moving_rate": 0.9}, is_test=True)
spec("fake_quantize_dequantize_moving_average_abs_max",
     ins={"X": _X, "InScale": np.array([1.0], np.float32)},
     attrs={"bit_length": 8, "moving_rate": 0.9}, is_test=True)
spec("fake_quantize_range_abs_max",
     ins={"X": _X, "InScale": np.array([1.0], np.float32),
          "Iter": np.array([0], np.int64)},
     attrs={"bit_length": 8, "window_size": 10}, is_test=True)
spec("fake_dequantize_max_abs",
     ins={"X": ints(2, 3, lo=-10, hi=10).astype(np.float32),
          "Scale": np.array([2.0], np.float32)},
     attrs={"max_range": 127.0})
spec("fake_channel_wise_dequantize_max_abs",
     ins={"X": f32(3, 4), "Scales": np.array([2.0, 1.5, 3.0],
                                             np.float32)},
     attrs={"quant_bits": [8]})
spec("moving_average_abs_max_scale",
     ins={"X": _X}, attrs={"moving_rate": 0.9}, is_test=True)
spec("quantize", ins={"Input": _X, "Scale": np.array([2.0], np.float32)})
spec("dequantize", ins={"Input": ints(2, 3, lo=-10, hi=10).astype(
    np.float32), "Scale": np.array([2.0], np.float32)})
spec("requantize", ins={"Input": ints(2, 3, lo=-10, hi=10).astype(
    np.float32)}, attrs={"Scale_in": 2.0, "Scale_out": 4.0})
# attr names are capitalized in the reference (requantize_op.cc:36-37)
spec("dgc", ins={"U": np.zeros(20, np.float32),
                 "V": np.zeros(20, np.float32), "Grad": f32(20)},
     attrs={"m": 0.9, "sparsity": [0.8]})
spec("dgc_clip_by_norm", ins={"X": f32(4),
                              "current_step": np.array([0.0],
                                                       np.float32)},
     attrs={"max_norm": 1.0, "rampup_begin_step": 0.0})
spec("average_accumulates",
     ins={"Param": _P, "InSum1": np.zeros((3, 2), np.float32),
          "InSum2": np.zeros((3, 2), np.float32),
          "InSum3": np.zeros((3, 2), np.float32),
          "InNumAccumulates": np.array([0], np.int64),
          "InOldNumAccumulates": np.array([0], np.int64),
          "InNumUpdates": np.array([0], np.int64)},
     attrs={"average_window": 10, "max_average_window": 20,
            "min_average_window": 5})

# --- detection -------------------------------------------------------------
_BOXES1 = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                    [5, 5, 15, 15]], np.float32)
spec("iou_similarity", ins={"X": _BOXES1, "Y": _BOXES1[:2]})
spec("box_clip", ins={"Input": _BOXES1,
                      "ImInfo": np.array([[12.0, 12.0, 1.0]],
                                         np.float32)})
spec("box_coder",
     ins={"PriorBox": _BOXES1, "PriorBoxVar": pos(3, 4),
          # distinct buffer: the numeric-grad pass perturbs in place
          "TargetBox": _BOXES1 + np.float32(0.5)},
     attrs={"code_type": "encode_center_size"})
spec("box_decoder_and_assign",
     ins={"PriorBox": _BOXES1, "PriorBoxVar": pos(3, 4),
          "TargetBox": f32(3, 8), "BoxScore": pos(3, 2)},
     attrs={"box_clip": 4.135})
spec("prior_box", ins={"Input": f32(1, 2, 3, 3),
                       "Image": f32(1, 3, 12, 12)},
     attrs={"min_sizes": [2.0], "aspect_ratios": [1.0],
            "variances": [0.1, 0.1, 0.2, 0.2]})
spec("density_prior_box", ins={"Input": f32(1, 2, 3, 3),
                               "Image": f32(1, 3, 12, 12)},
     attrs={"fixed_sizes": [2.0], "fixed_ratios": [1.0],
            "densities": [1], "variances": [0.1, 0.1, 0.2, 0.2]})
spec("anchor_generator", ins={"Input": f32(1, 2, 3, 3)},
     attrs={"anchor_sizes": [16.0], "aspect_ratios": [1.0],
            "stride": [4.0, 4.0], "variances": [0.1, 0.1, 0.2, 0.2]})
spec("yolo_box", ins={"X": f32(1, 3 * 7, 4, 4),
                      "ImgSize": np.array([[128, 128]], np.int32)},
     attrs={"anchors": [10, 13, 16, 30, 33, 23], "class_num": 2,
            "conf_thresh": 0.01, "downsample_ratio": 32})
# three gts: a big box (best-anchor inside the mask -> positive), a
# small box whose best anchor (0) is OUTSIDE the mask -> match -1 with
# only the ignore scan applying, and an all-zero invalid box; GTScore
# exercises the mixup-score weighting; anchor_mask=[1,2] subsets the
# anchor list
spec("yolov3_loss",
     ins={"X": f32(1, 2 * 7, 4, 4),
          "GTBox": np.array([[[0.52, 0.47, 0.4, 0.42],
                              [0.25, 0.75, 0.05, 0.06],
                              [0.0, 0.0, 0.0, 0.0]]], np.float32),
          "GTLabel": np.array([[1, 0, 0]], np.int64),
          "GTScore": np.array([[0.8, 0.6, 1.0]], np.float32)},
     attrs={"anchors": [10, 13, 16, 30, 33, 23],
            "anchor_mask": [1, 2], "class_num": 2,
            "ignore_thresh": 0.5, "downsample_ratio": 32},
     grad=["X"], grad_tol=5e-2)
spec("bipartite_match", ins={"DistMat": np.array([[0.9, 0.1],
                                                  [0.2, 0.8]],
                                                 np.float32)})
spec("target_assign",
     ins={"X": f32(1, 2, 3),
          "MatchIndices": np.array([[0, -1, 1]], np.int32)},
     attrs={"mismatch_value": 0.0})
spec("mine_hard_examples",
     ins={"ClsLoss": pos(1, 3), "MatchIndices": np.array([[0, -1, -1]],
                                                         np.int32),
          "MatchDist": pos(1, 3, lo=0.1, hi=0.9)},
     attrs={"neg_pos_ratio": 2.0, "mining_type": "max_negative"})
spec("polygon_box_transform", ins={"Input": f32(1, 8, 2, 2)})
spec("multiclass_nms",
     ins={"BBoxes": _BOXES1[None], "Scores": pos(1, 2, 3)},
     attrs={"score_threshold": 0.1, "nms_threshold": 0.5,
            "keep_top_k": 4, "background_label": 0})
spec("multiclass_nms2",
     ins={"BBoxes": _BOXES1[None], "Scores": pos(1, 2, 3)},
     attrs={"score_threshold": 0.1, "nms_threshold": 0.5,
            "keep_top_k": 4, "background_label": 0})
spec("collect_fpn_proposals",
     ins={"MultiLevelRois": [("cfp_r1", _BOXES1), ("cfp_r2", _BOXES1)],
          "MultiLevelScores": [("cfp_s1", pos(3)), ("cfp_s2", pos(3))]},
     attrs={"post_nms_topN": 4})
# mixed-scale rois spread across 3 levels, incl. a degenerate box
# (x2<x1 -> area 0 -> clamped to min_level)
spec("distribute_fpn_proposals",
     ins={"FpnRois": np.array(
         [[0, 0, 7, 7], [0, 0, 31, 31], [2, 2, 60, 50],
          [5, 3, 1, 9], [1, 1, 16, 14], [0, 0, 63, 63]], np.float32)},
     attrs={"min_level": 2, "max_level": 4, "refer_level": 3,
            "refer_scale": 16})
# well-formed anchor grid (x1<x2), two images with different sizes and
# scales: exercises variance-scaled decoding, the origin-scale
# min_size filter, center-inside-image rejection, and adaptive-eta NMS
_gp_anchors = np.zeros((3, 3, 2, 4), np.float32)
for _yy in range(3):
    for _xx in range(3):
        for _ai, _sz in enumerate((3.0, 6.0)):
            _gp_anchors[_yy, _xx, _ai] = [8 * _xx + 4 - _sz,
                                          8 * _yy + 4 - _sz,
                                          8 * _xx + 4 + _sz,
                                          8 * _yy + 4 + _sz]
spec("generate_proposals",
     ins={"Scores": pos(2, 2, 3, 3), "BboxDeltas": f32(2, 8, 3, 3),
          "ImInfo": np.array([[24.0, 24.0, 2.0],
                              [20.0, 28.0, 1.0]], np.float32),
          "Anchors": _gp_anchors,
          "Variances": pos(3, 3, 2, 4)},
     attrs={"pre_nms_topN": 12, "post_nms_topN": 6, "nms_thresh": 0.6,
            "min_size": 2.0, "eta": 0.9})
spec("generate_proposal_labels",
     ins={"RpnRois": _BOXES1, "GtClasses": np.array([1], np.int32),
          "IsCrowd": np.array([0], np.int32),
          "GtBoxes": np.array([[0, 0, 10, 10]], np.float32),
          "ImInfo": np.array([[32.0, 32.0, 1.0]], np.float32)},
     attrs={"fg_thresh": 0.5, "class_nums": 3})
spec("generate_mask_labels",
     ins={"ImInfo": np.array([[16.0, 16.0, 1.0]], np.float32),
          "GtClasses": np.array([1, 1], np.int32),
          "IsCrowd": np.array([0, 0], np.int32),
          "GtSegms": (np.arange(128).reshape(2, 8, 8) % 2
                      ).astype(np.float32),
          "Rois": np.array([[0, 0, 7, 15]], np.float32),
          "LabelsInt32": np.array([[1]], np.int32)},
     attrs={"resolution": 8, "num_classes": 2})
spec("rpn_target_assign",
     ins={"Anchor": _BOXES1,
          "GtBoxes": np.array([[0, 0, 10, 10]], np.float32)},
     attrs={"rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3})
spec("retinanet_target_assign",
     ins={"Anchor": _BOXES1,
          "GtBoxes": np.array([[0, 0, 10, 10]], np.float32),
          "GtLabels": np.array([[1]], np.int32),
          "IsCrowd": np.array([0], np.int32),
          "ImInfo": np.array([[32.0, 32.0, 1.0]], np.float32)},
     attrs={"positive_overlap": 0.5, "negative_overlap": 0.4})
# two FPN levels, batch of two images with different im_scale, score
# ties (stable-sort order), nms_top_k below the per-level candidate
# count, and adaptive-eta NMS — the full reference pipeline
_RDO_SC0 = pos(2, 4, 3, lo=0.0, hi=1.0)
_RDO_SC0[0, 1, 2] = _RDO_SC0[0, 2, 0] = 0.6   # tie within level 0
_RDO_SC0[1, 0, 1] = 0.01                      # below threshold
_RDO_SC1 = pos(2, 2, 3, lo=0.0, hi=1.0)
_RDO_SC1[0, 0, 1] = 0.6                       # cross-level tie
spec("retinanet_detection_output",
     ins={"BBoxes": [("rdo_box0", f32(2, 4, 4, lo=-0.6, hi=0.6)),
                     ("rdo_box1", f32(2, 2, 4, lo=-0.6, hi=0.6))],
          "Scores": [("rdo_sc0", _RDO_SC0), ("rdo_sc1", _RDO_SC1)],
          "Anchors": [("rdo_an0",
                       np.array([[0, 0, 9, 9], [5, 5, 14, 14],
                                 [20, 20, 29, 29], [0, 20, 9, 29]],
                                np.float32)),
                      ("rdo_an1",
                       np.array([[0, 0, 19, 19], [10, 10, 29, 29]],
                                np.float32))],
          "ImInfo": np.array([[64.0, 64.0, 1.0], [65.0, 65.0, 2.0]],
                             np.float32)},
     # threshold 0.6 > 0.5 so the adaptive-eta decay gate actually
     # fires; image 2's 65/2 = 32.5 frame pins half-away-from-zero
     # rounding (std::round, not banker's)
     attrs={"score_threshold": 0.05, "nms_threshold": 0.6,
            "nms_top_k": 5, "keep_top_k": 6, "nms_eta": 0.9})
spec("roi_align", ins={"X": f32(1, 2, 6, 6),
                       "ROIs": np.array([[0, 0, 4, 4]], np.float32)},
     attrs={"pooled_height": 2, "pooled_width": 2,
            "spatial_scale": 1.0}, grad=["X"])
spec("roi_pool", ins={"X": f32(1, 2, 6, 6),
                      "ROIs": np.array([[0, 0, 4, 4]], np.float32)},
     attrs={"pooled_height": 2, "pooled_width": 2,
            "spatial_scale": 1.0})
# fractional, off-grid roi corners exercise the partial-cell integral
# terms; two images + BatchRoINums exercise the roi->image mapping
spec("prroi_pool", ins={"X": f32(2, 2, 6, 6),
                        "ROIs": np.array([[0.6, 0.4, 4.3, 3.7],
                                          [1.2, 0.7, 5.6, 4.4]],
                                         np.float32),
                        "BatchRoINums": np.array([1, 1], np.int64)},
     attrs={"pooled_height": 2, "pooled_width": 2,
            "spatial_scale": 0.8})
spec("psroi_pool", ins={"X": f32(1, 8, 6, 6),
                        "ROIs": np.array([[0, 0, 4, 4]], np.float32)},
     attrs={"pooled_height": 2, "pooled_width": 2, "output_channels": 2,
            "spatial_scale": 1.0})
spec("roi_perspective_transform",
     ins={"X": f32(1, 2, 8, 8),
          "ROIs": np.array([[1, 1, 6, 1, 6, 6, 1, 6]], np.float32)},
     attrs={"transformed_height": 4, "transformed_width": 4,
            "spatial_scale": 1.0})
# imperfect detections: duplicates on one GT, a near-miss below the
# IoU threshold, a difficult GT, ranked scores crossing class lines —
# the cases where the AP interpolation actually matters
spec("detection_map",
     ins={"DetectRes": np.array(
         [[1.0, 0.90, 0.00, 0.00, 0.40, 0.38],   # tp on gt1
          [1.0, 0.80, 0.02, 0.02, 0.42, 0.40],   # duplicate on gt1 -> fp
          [1.0, 0.70, 0.50, 0.55, 0.90, 0.95],   # tp on gt2
          [1.0, 0.60, 0.10, 0.50, 0.30, 0.70],   # near-miss -> fp
          [2.0, 0.85, 0.21, 0.20, 0.70, 0.71],   # matches difficult gt
          [2.0, 0.75, 0.00, 0.61, 0.30, 0.89]],  # tp on gt4
         np.float32),
          "Label": np.array(
         [[1.0, 0.0, 0.00, 0.00, 0.40, 0.40],
          [1.0, 0.0, 0.50, 0.50, 0.90, 0.90],
          [2.0, 1.0, 0.20, 0.20, 0.70, 0.70],    # difficult
          [2.0, 0.0, 0.00, 0.60, 0.30, 0.90]],
         np.float32)},
     attrs={"overlap_threshold": 0.5, "ap_type": "integral",
            "evaluate_difficult": False})
spec("flash_attention",
     ins={"Q": f32(1, 2, 4, 8), "K": f32(1, 2, 4, 8),
          "V": f32(1, 2, 4, 8)},
     attrs={"causal": False, "block_q": 128, "block_k": 128},
     grad=["Q", "K", "V"], is_test=True)
spec("where_index", ins={"Condition": _B1})

# ===========================================================================
# batch 3: straggler ops (straggler_ops.py)
# ===========================================================================
spec("deformable_conv",
     ins={"Input": f32(1, 2, 5, 5), "Filter": f32(3, 2, 3, 3),
          "Offset": f32(1, 18, 5, 5, lo=-0.5, hi=0.5),
          "Mask": pos(1, 9, 5, 5, lo=0.5, hi=1.0)},
     attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
            "groups": 1, "deformable_groups": 1},
     grad=["Input", "Filter"], grad_tol=3e-2)
spec("deformable_conv_v1",
     ins={"Input": f32(1, 2, 5, 5), "Filter": f32(3, 2, 3, 3),
          "Offset": f32(1, 18, 5, 5, lo=-0.5, hi=0.5)},
     attrs={"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
            "groups": 1, "deformable_groups": 1})
spec("deformable_psroi_pooling",
     ins={"Input": f32(1, 8, 6, 6),
          "ROIs": np.array([[0, 0, 4, 4]], np.float32),
          "Trans": f32(1, 2, 2, 2, lo=-0.1, hi=0.1)},
     attrs={"pooled_height": 2, "pooled_width": 2, "output_dim": 2,
            "spatial_scale": 1.0, "trans_std": 0.1,
            "sample_per_part": 2})
# positive input/filter/bias keep every relu pre-activation strictly
# positive: central differences disagree with the analytic subgradient
# on draws that land within delta of the kink
spec("conv2d_fusion",
     ins={"Input": f32(1, 2, 4, 4, lo=0.1, hi=1.0),
          "Filter": f32(3, 2, 3, 3, lo=0.05, hi=1.0),
          "Bias": f32(3, lo=0.5, hi=1.5)},
     attrs={"strides": [1, 1], "paddings": [1, 1], "activation": "relu"})
spec("conv2d_inception_fusion",
     ins={"Input": f32(1, 4, 5, 5),
          "Filter": [("inc_f0", f32(2, 4, 1, 1)),
                     ("inc_f1", f32(7, 4, 1, 1)),
                     ("inc_f2", f32(5, 2, 3, 3)),
                     ("inc_f3", f32(4, 3, 3, 3))],
          "Bias": [("inc_b0", f32(2)), ("inc_b1", f32(7)),
                   ("inc_b2", f32(5)), ("inc_b3", f32(4))]},
     attrs={"activation": "relu"})
spec("fused_embedding_fc_lstm",
     ins={"Ids": np.array([[[1], [3], [0]]], np.int64),
          "Embeddings": f32(6, 16), "WeightH": f32(4, 16),
          "Bias": f32(1, 16)})
spec("fusion_seqpool_cvm_concat",
     # positive values: the CVM transform takes log(show/click + 1)
     ins={"X": [("fspcc_a", pos(2, 3, 4)), ("fspcc_b", pos(2, 3, 4))],
          "CVM": f32(2, 2)},
     attrs={"pooltype": "SUM", "use_cvm": True})
spec("pull_box_sparse",
     ins={"Ids": np.array([[1], [5]], np.int64)},
     attrs={"size": 4, "table_id": 7}, exact=False)
spec("fill_zeros_like2", ins={"X": _X}, attrs={"dtype": "float32"},
     expect=lambda i, a: {"Out": [np.zeros_like(i["X"])]})

skip("push_box_sparse", "host-side table update paired with "
                        "pull_box_sparse; covered in "
                        "tests/test_straggler_ops.py")
skip("fl_listen_and_serv", "host-side federated PS loop; routed to "
                           "distributed/ps_server.py by the Executor "
                           "like listen_and_serv")
skip("distributed_notify", "host RPC side effect; covered in "
                           "tests/test_straggler_ops.py")
skip("conditional_block_infer", "needs a sub-block program; delegates "
                                "to the conditional_block lowering")
skip("read", "host reader infeed; covered in "
             "tests/test_straggler_ops.py")
skip("create_custom_reader", "host reader binding; covered in "
                             "tests/test_straggler_ops.py")

# ===========================================================================
# independent numpy references + extra grad slots (op_expects.py) —
# merged last so every entry targets an existing spec
# ===========================================================================
from op_expects import EXPECTS, EXTRA_GRADS  # noqa: E402

for _op, _fn in EXPECTS.items():
    assert _op in SPECS, f"expect for unspec'd op {_op}"
    if SPECS[_op]["expect"] is None:
        SPECS[_op]["expect"] = _fn
for _op, _slots in EXTRA_GRADS.items():
    assert _op in SPECS, f"extra grads for unspec'd op {_op}"
    SPECS[_op]["grad"] = tuple(
        dict.fromkeys(list(SPECS[_op]["grad"]) + list(_slots)))

"""Resilience-subsystem tests: fault-spec parsing + deterministic
injection, the retry taxonomy, the circuit-breaker state machine,
executor/reader hook sites, TrainerGuard NaN rollback + preemption
checkpoint/resume (bit-identical), serving graceful degradation over
/healthz, atomic checkpoint writes under a mid-save kill, multiprocess
reader worker-death detection, flight-recorder install idempotency, and
the chaos loadgen acceptance harness.

The preempt/resume acceptance test drives a REAL SIGTERM through the
fault injector (preempt_at) into TrainerGuard's chained handler and
asserts the resumed run's losses and final parameters are bit-identical
to an uninterrupted run that skipped the same NaN batch.
"""
import contextlib
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, monitor
from paddle_tpu.models import gpt
from paddle_tpu.reader_decorator import ReaderWorkerDied, \
    multiprocess_reader
from paddle_tpu.resilience import (CLOSED, HALF_OPEN, OPEN,
                                   CircuitBreaker, FaultSpecError,
                                   NanStepError, PreemptedError,
                                   RetryExhausted, RetryPolicy,
                                   TrainerGuard, TransientFault,
                                   is_transient, parse_fault_spec,
                                   reset_injector)
from paddle_tpu.resilience.faults import FaultInjector
from paddle_tpu.serving import (EngineConfig, GenerationEngine,
                                GenerationRequest, OverloadedError,
                                ServingEngine, serve)

FEAT = 5


@pytest.fixture(autouse=True)
def _fault_hygiene():
    """No test may leak an armed fault spec into the rest of the
    suite."""
    yield
    fluid.set_flags({"FLAGS_fault_spec": "", "FLAGS_fault_seed": 0})
    reset_injector()


@contextlib.contextmanager
def _flags(**kv):
    from paddle_tpu.core.flags import FLAGS
    old = {k: getattr(FLAGS, k) for k in kv}
    fluid.set_flags({f"FLAGS_{k}": v for k, v in kv.items()})
    try:
        yield
    finally:
        fluid.set_flags({f"FLAGS_{k}": v for k, v in old.items()})


@contextlib.contextmanager
def _stats():
    """Monitor on + clean slate (STAT_* are no-ops when the monitor is
    off, so every stats assertion needs this)."""
    with _flags(enable_monitor=True):
        monitor.STAT_RESET()
        try:
            yield
        finally:
            monitor.STAT_RESET()


def _arm(spec, seed=0):
    fluid.set_flags({"FLAGS_fault_spec": spec, "FLAGS_fault_seed": seed})
    reset_injector()


def _disarm():
    fluid.set_flags({"FLAGS_fault_spec": ""})
    reset_injector()


# ---------------------------------------------------------------------------
# fault spec parsing + deterministic decisions
# ---------------------------------------------------------------------------

def test_parse_fault_spec_roundtrip_and_errors():
    specs = parse_fault_spec("step_nan:p=0.01,slow_step:ms=500,"
                             "transient_fail:p=0.02,preempt_at:step=40")
    assert [s.kind for s in specs] == ["step_nan", "slow_step",
                                      "transient_fail", "preempt_at"]
    assert specs[0].p == 0.01 and specs[1].ms == 500.0
    assert specs[3].step == 40
    s = parse_fault_spec("transient_fail:at=3:site=executor")[0]
    assert s.at == 3 and s.site == "executor"
    assert parse_fault_spec("") == []

    for bad in ("bogus_kind:p=0.1",          # unknown kind
                "transient_fail",             # needs p= or at=
                "slow_step:p=0.5",            # needs ms=
                "preempt_at:p=0.5",           # needs step=
                "step_nan:p=1.5",             # p out of range
                "step_nan:at=0",              # at is 1-based
                "transient_fail:p=0.1:site=gpu",  # unknown site
                "transient_fail:frobnicate"):     # malformed param
        with pytest.raises(FaultSpecError):
            parse_fault_spec(bad)


def _fire_pattern(inj, n=60, site="serving"):
    out = []
    for _ in range(n):
        try:
            inj.pre_step(site)
            out.append(False)
        except TransientFault:
            out.append(True)
    return out


def test_fault_decisions_deterministic_per_seed():
    a = _fire_pattern(FaultInjector("transient_fail:p=0.3", seed=123))
    b = _fire_pattern(FaultInjector("transient_fail:p=0.3", seed=123))
    assert a == b
    assert any(a) and not all(a)
    c = _fire_pattern(FaultInjector("transient_fail:p=0.3", seed=124))
    assert c != a
    # at=N fires exactly once, on the Nth invocation
    d = _fire_pattern(FaultInjector("transient_fail:at=4", seed=0), n=10)
    assert d == [False] * 3 + [True] + [False] * 6
    # site restriction: a serving-only fault never fires at the executor
    e = FaultInjector("transient_fail:p=1.0:site=serving", seed=0)
    for _ in range(5):
        e.pre_step("executor")
    with pytest.raises(TransientFault):
        e.pre_step("serving")


# ---------------------------------------------------------------------------
# retry taxonomy + policy
# ---------------------------------------------------------------------------

def test_is_transient_taxonomy():
    assert is_transient(TransientFault("x"))
    assert is_transient(RetryExhausted("x"))
    assert is_transient(OSError("tunnel reset"))
    assert is_transient(TimeoutError("stuck"))
    for poison in (ValueError("bad shape"), TypeError("bad type"),
                   KeyError("missing feed"), AssertionError("no"),
                   FloatingPointError("nan"), NotImplementedError("op")):
        assert not is_transient(poison)
    # unknown RuntimeErrors default to NOT retryable
    assert not is_transient(RuntimeError("who knows"))


def test_retry_policy_poison_fails_fast():
    calls = []

    def poison():
        calls.append(1)
        raise ValueError("malformed")

    policy = RetryPolicy(max_attempts=5, sleep=lambda s: None)
    with pytest.raises(ValueError):
        policy.call(poison)
    assert len(calls) == 1


def test_retry_policy_transient_then_success():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientFault("glitch")
        return "ok"

    slept = []
    policy = RetryPolicy(max_attempts=5, base_delay_ms=4.0,
                         sleep=slept.append)
    assert policy.call(flaky) == "ok"
    assert len(calls) == 3 and len(slept) == 2
    # jittered exponential: attempt-2 backoff in [half, full] of 2*base
    assert 0.002 <= slept[1] <= 0.008


def test_retry_policy_exhaustion_and_deadline():
    def always():
        raise TransientFault("still down")

    policy = RetryPolicy(max_attempts=3, sleep=lambda s: None)
    with pytest.raises(RetryExhausted) as ei:
        policy.call(always)
    assert isinstance(ei.value.__cause__, TransientFault)

    # a deadline shorter than the next backoff gives up without sleeping
    slept = []
    tight = RetryPolicy(max_attempts=10, base_delay_ms=500.0,
                        deadline_ms=1.0, sleep=slept.append)
    with pytest.raises(RetryExhausted):
        tight.call(always)
    assert slept == []


# ---------------------------------------------------------------------------
# circuit breaker state machine (fake clock)
# ---------------------------------------------------------------------------

def test_breaker_state_cycle_fake_clock():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=2, cooldown_ms=1000.0,
                       clock=lambda: t[0])
    assert b.state == CLOSED and b.allow()
    b.record_failure()
    assert b.state == CLOSED          # below threshold
    b.record_success()
    b.record_failure()
    assert b.state == CLOSED          # success reset the streak
    b.record_failure()
    b.record_failure()
    assert b.state == OPEN
    assert not b.allow()
    assert b.retry_after_s() == pytest.approx(1.0)

    t[0] = 1.1                        # cooldown elapsed -> HALF_OPEN
    assert b.state == HALF_OPEN
    assert b.allow()                  # one probe admitted
    assert not b.allow()              # second concurrent probe shed
    b.record_failure()                # probe failed -> OPEN, fresh clock
    assert b.state == OPEN
    assert b.retry_after_s() == pytest.approx(1.0)

    t[0] = 2.3
    assert b.allow()                  # half-open probe again
    b.record_success()
    assert b.state == CLOSED and b.allow()

    # threshold=0 disables the breaker entirely
    off = CircuitBreaker(failure_threshold=0)
    for _ in range(10):
        off.record_failure()
    assert off.allow() and off.state == CLOSED


# ---------------------------------------------------------------------------
# executor + reader hook sites
# ---------------------------------------------------------------------------

def _scale_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        out = layers.scale(x, scale=2.0)
    return main, startup, out


def test_executor_transient_fault_retried_invisibly():
    main, startup, out = _scale_program()
    scope = fluid.Scope()
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    with fluid.scope_guard(scope), _stats():
        exe = fluid.Executor()
        exe.run(startup)
        _arm("transient_fail:at=1:site=executor")
        res = exe.run(main, feed={"x": arr}, fetch_list=[out])
        np.testing.assert_allclose(res[0], arr * 2)
        snap = monitor.get_stats_snapshot()
        assert snap["counters"].get("resilience.fault_transient") == 1
        assert snap["counters"].get("resilience.retries", 0) >= 1


def test_executor_step_nan_corrupts_fetches_then_clean_rerun():
    main, startup, out = _scale_program()
    scope = fluid.Scope()
    arr = np.ones((2, 3), np.float32)
    with fluid.scope_guard(scope), _stats():
        exe = fluid.Executor()
        exe.run(startup)
        _arm("step_nan:at=1:site=executor")
        res = exe.run(main, feed={"x": arr}, fetch_list=[out])
        assert np.isnan(res[0]).any()
        snap = monitor.get_stats_snapshot()
        assert snap["counters"].get("resilience.fault_nan") == 1
        _disarm()
        # device state was never touched: the rerun is clean
        res2 = exe.run(main, feed={"x": arr}, fetch_list=[out])
        np.testing.assert_allclose(res2[0], arr * 2)


def test_reader_fault_site_and_worker_error_propagation():
    loader = fluid.io.DataLoader.from_generator(capacity=2)
    loader.set_batch_generator(
        lambda: iter([{"a": 1}, {"a": 2}, {"a": 3}]))
    _arm("transient_fail:at=2:site=reader")
    it = iter(loader)
    assert next(it) == {"a": 1}
    with pytest.raises(TransientFault):
        next(it)
    _disarm()

    # a prefetch-worker exception surfaces on the training thread
    def bad():
        yield {"a": 1}
        raise OSError("decode died")

    loader2 = fluid.io.DataLoader.from_generator(capacity=2)
    loader2.set_batch_generator(bad)
    it2 = iter(loader2)
    assert next(it2) == {"a": 1}
    with pytest.raises(OSError, match="decode died"):
        next(it2)


# ---------------------------------------------------------------------------
# TrainerGuard: NaN rollback, watchdog, preempt/resume
# ---------------------------------------------------------------------------

def _build_sgd():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), \
            fluid.unique_name.guard("tg_"):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _persist_names(program, scope):
    return [v.name for v in program.list_vars()
            if v.persistable and not v.is_data and scope.has(v.name)]


def _clean_batch(seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(4, 3).astype(np.float32),
            "y": rng.randn(4, 1).astype(np.float32)}


def _nan_batch():
    b = _clean_batch(1)
    b["x"] = b["x"].copy()
    b["x"][0, 0] = np.nan
    return b


def test_trainer_guard_nan_skip_rolls_back():
    main, startup, loss = _build_sgd()
    scope = fluid.Scope()
    with fluid.scope_guard(scope), _stats():
        exe = fluid.Executor()
        exe.run(startup)
        guard = TrainerGuard(exe, main, scope=scope, fetch_list=[loss],
                             install_sigterm=False)
        try:
            out = guard.step(_clean_batch())
            assert out is not None and np.isfinite(out[0]).all()
            names = _persist_names(main, scope)
            before = {n: scope.get_numpy(n).copy() for n in names}
            assert guard.step(_nan_batch()) is None   # skipped
            for n in names:   # SGD applied NaN, rollback undid it
                np.testing.assert_array_equal(scope.get_numpy(n),
                                              before[n])
            assert guard.global_step == 2 and guard.nan_skips == 1
            out2 = guard.step(_clean_batch(2))
            assert out2 is not None and np.isfinite(out2[0]).all()
            snap = monitor.get_stats_snapshot()
            assert snap["counters"].get(
                "resilience.nan_steps_skipped") == 1
            assert snap["counters"].get("resilience.rollbacks") == 1
        finally:
            guard.close()


def test_trainer_guard_max_nan_skips_raises():
    main, startup, loss = _build_sgd()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        guard = TrainerGuard(exe, main, scope=scope, fetch_list=[loss],
                             max_nan_skips=2, install_sigterm=False)
        try:
            assert guard.step(_nan_batch()) is None
            assert guard.step(_nan_batch()) is None
            with pytest.raises(NanStepError):
                guard.step(_nan_batch())
        finally:
            guard.close()


def test_trainer_guard_watchdog_dumps_flight_recorder(tmp_path):
    main, startup, loss = _build_sgd()
    scope = fluid.Scope()
    fr = str(tmp_path / "fr.jsonl")
    with fluid.scope_guard(scope), _stats(), \
            _flags(flight_recorder_path=fr):
        exe = fluid.Executor()
        exe.run(startup)   # compile before the slow_step is armed
        exe.run(main, feed=_clean_batch(), fetch_list=[loss])
        guard = TrainerGuard(exe, main, scope=scope, fetch_list=[loss],
                             watchdog_timeout_s=0.15,
                             install_sigterm=False)
        try:
            _arm("slow_step:ms=700:site=executor")
            guard.step(_clean_batch())
            _disarm()
        finally:
            guard.close()
        snap = monitor.get_stats_snapshot()
        assert snap["counters"].get(
            "resilience.watchdog_fires", 0) >= 1
        assert os.path.exists(fr)
        head = json.loads(open(fr).readline())
        assert head["kind"] == "flight_dump"
        assert head["reason"] == "watchdog_stuck_step"


def test_trainer_guard_preempt_checkpoint_resume_bit_identical(tmp_path):
    """Acceptance: a training run with an injected NaN step AND an
    injected SIGTERM preemption resumes from its checkpoint to
    bit-identical losses and final parameters vs an uninterrupted run
    that skipped the same batch."""
    NB, NAN_AT, PREEMPT_STEP = 8, 2, 4
    rng = np.random.RandomState(7)
    batches = []
    for i in range(NB):
        b = {"x": rng.randn(4, 3).astype(np.float32),
             "y": rng.randn(4, 1).astype(np.float32)}
        if i == NAN_AT:
            b["x"][0, 0] = np.nan
        batches.append(b)

    def fresh():
        main, startup, loss = _build_sgd()
        scope = fluid.Scope()
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(startup)
        return main, loss, scope, exe

    # pin identical initial weights across all three program instances
    # (unique_name.guard in _build_sgd makes the names line up)
    main0, loss0, scope0, exe0 = fresh()
    names = _persist_names(main0, scope0)
    init = {n: scope0.get_numpy(n).copy() for n in names}

    def seed_params(scope):
        for n, a in init.items():
            scope.set(n, a.copy())

    def run(guard, stream):
        losses = []
        for b in stream:
            out = guard.step(b)
            losses.append(None if out is None else out[0].copy())
        return losses

    # --- reference: uninterrupted, skips the NaN batch ---------------
    mainA, lossA, scopeA, exeA = fresh()
    seed_params(scopeA)
    guardA = TrainerGuard(exeA, mainA, scope=scopeA,
                          fetch_list=[lossA], install_sigterm=False)
    try:
        lossesA = run(guardA, batches)
    finally:
        guardA.close()
    assert lossesA[NAN_AT] is None
    assert all(v is not None for i, v in enumerate(lossesA)
               if i != NAN_AT)

    # --- interrupted: injected SIGTERM via preempt_at ----------------
    ck = str(tmp_path / "ck")
    mainB, lossB, scopeB, exeB = fresh()
    seed_params(scopeB)
    guardB = TrainerGuard(exeB, mainB, scope=scopeB,
                          fetch_list=[lossB], checkpoint_dir=ck,
                          snapshot_every=1)
    _arm(f"preempt_at:step={PREEMPT_STEP}:site=executor")
    consumed = None
    try:
        with pytest.raises(PreemptedError) as ei:
            run(guardB, batches)
        consumed = ei.value.global_step
        assert ei.value.checkpoint_dir == ck
    finally:
        guardB.close()
        _disarm()
    # the executor's per-program counter is 0-based: step=4 fires
    # during the 5th batch, which completes before the checkpoint
    assert consumed == PREEMPT_STEP + 1
    assert TrainerGuard.has_checkpoint(ck)

    # --- resumed: fresh process state, restore, finish the stream ----
    mainC, lossC, scopeC, exeC = fresh()
    guardC = TrainerGuard(exeC, mainC, scope=scopeC,
                          fetch_list=[lossC], checkpoint_dir=ck,
                          install_sigterm=False)
    try:
        skip = guardC.resume(ck)
        assert skip == consumed
        lossesC = run(guardC, batches[skip:])
    finally:
        guardC.close()

    # bit-identical: losses after the preemption point and the final
    # parameters match the uninterrupted run exactly
    assert len(lossesC) == NB - consumed
    for got, want in zip(lossesC, lossesA[consumed:]):
        np.testing.assert_array_equal(got, want)
    for n in names:
        np.testing.assert_array_equal(scopeC.get_numpy(n),
                                      scopeA.get_numpy(n))


# ---------------------------------------------------------------------------
# atomic checkpoint writes (satellite: kill-mid-save)
# ---------------------------------------------------------------------------

def test_atomic_write_helpers_replace_not_append(tmp_path):
    from paddle_tpu.io import atomic_np_save, atomic_write_text
    p = str(tmp_path / "a.npy")
    atomic_np_save(p, np.arange(3))
    atomic_np_save(p, np.arange(4))
    assert np.load(p).shape == (4,)          # no .npy suffix doubling
    t = str(tmp_path / "s.json")
    atomic_write_text(t, "one")
    atomic_write_text(t, "two")
    assert open(t).read() == "two"
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


_KILL_MID_SAVE = """
import os, sys
import numpy as np
sys.path.insert(0, sys.argv[1])
import paddle_tpu as fluid
from paddle_tpu import layers

d = sys.argv[2]
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[-1, 2], dtype="float32",
                    append_batch_size=False)
    layers.fc(x, size=2)
scope = fluid.Scope()
with fluid.scope_guard(scope):
    names = [v for v in main.list_vars()
             if v.persistable and not v.is_data]
    for v in names:
        scope.set(v.name, np.full([abs(s) for s in v.shape], 1.0,
                                  np.float32))
    fluid.io.save_persistables(None, d, main, filename="params.npz")
    for v in names:
        scope.set(v.name, np.full([abs(s) for s in v.shape], 2.0,
                                  np.float32))
    # die mid-save of v2: after the tmp file is written but before it
    # is fsynced/renamed over the v1 checkpoint
    os.fsync = lambda fd: os._exit(9)
    fluid.io.save_persistables(None, d, main, filename="params.npz")
os._exit(1)  # unreachable: the patched fsync must have killed us
"""


def test_kill_mid_save_leaves_previous_checkpoint_intact(tmp_path):
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    script = tmp_path / "kill_mid_save.py"
    script.write_text(textwrap.dedent(_KILL_MID_SAVE))
    d = str(tmp_path / "ckpt")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, str(script), repo, d],
                       env=env, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == 9, (p.stdout, p.stderr)
    blob = np.load(os.path.join(d, "params.npz"))
    assert blob.files
    for k in blob.files:   # v1 everywhere: the torn v2 never landed
        np.testing.assert_array_equal(blob[k],
                                      np.full(blob[k].shape, 1.0,
                                              np.float32))


# ---------------------------------------------------------------------------
# multiprocess reader worker death (satellite: SIGKILL a worker)
# ---------------------------------------------------------------------------

def _pid_then_hang_reader():
    """Module-level so the spawn context can pickle it by name."""
    yield os.getpid()
    time.sleep(300)
    yield -1


def test_multiprocess_reader_detects_sigkilled_worker():
    with _stats():
        gen = multiprocess_reader([_pid_then_hang_reader],
                                  queue_size=4, get_timeout_s=0.3)
        it = gen()
        pid = next(it)
        assert isinstance(pid, int) and pid != os.getpid()
        os.kill(pid, signal.SIGKILL)
        with pytest.raises(ReaderWorkerDied, match="exit code"):
            next(it)
        snap = monitor.get_stats_snapshot()
        assert snap["counters"].get("reader.worker_deaths") == 1


def test_multiprocess_reader_clean_end_of_stream():
    got = list(multiprocess_reader([_range_reader], queue_size=8,
                                   get_timeout_s=0.5)())
    assert sorted(got) == [0, 1, 2, 3]


def _range_reader():
    for i in range(4):
        yield i


# ---------------------------------------------------------------------------
# flight-recorder install idempotency (satellite: SIGTERM chaining)
# ---------------------------------------------------------------------------

def test_flight_recorder_double_install_single_dump(tmp_path,
                                                    monkeypatch):
    dumps = []
    monkeypatch.setattr(monitor, "dump_flight_recorder",
                        lambda path=None, reason="explicit":
                        dumps.append(reason) or str(path))
    prev_exc, prev_term = [], []

    def prev_hook(tp, val, tb):
        prev_exc.append(tp)

    def prev_handler(signum, frame):
        prev_term.append(signum)

    old_hook = sys.excepthook
    old_term = signal.getsignal(signal.SIGTERM)
    sys.excepthook = prev_hook
    signal.signal(signal.SIGTERM, prev_handler)
    try:
        # bench and monitor both install: second must REPLACE, not chain
        monitor.install_flight_recorder(str(tmp_path / "fr.jsonl"))
        monitor.install_flight_recorder(str(tmp_path / "fr.jsonl"))

        sys.excepthook(RuntimeError, RuntimeError("boom"), None)
        assert dumps.count("unhandled RuntimeError") == 1
        assert prev_exc == [RuntimeError]   # previous hook still ran

        signal.raise_signal(signal.SIGTERM)
        sigs = [r for r in dumps if r.startswith("signal")]
        assert sigs == [f"signal {int(signal.SIGTERM)}"]
        assert prev_term == [int(signal.SIGTERM)]  # chained handler ran
    finally:
        sys.excepthook = old_hook
        signal.signal(signal.SIGTERM, old_term)


# ---------------------------------------------------------------------------
# serving graceful degradation
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("resilience_model"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data("x", shape=[-1, -1, FEAT], dtype="float32",
                        append_batch_size=False)
        s = layers.reduce_sum(x, dim=1)
        pred = layers.fc(s, size=3, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


def _http(url, payload=None):
    try:
        if payload is None:
            req = urllib.request.Request(url)
        else:
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read() or b"{}"), \
                dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _x(batch=1, seq=4):
    return np.random.RandomState(0).randn(
        batch, seq, FEAT).astype(np.float32)


def test_serving_breaker_cycle_and_healthz(model_dir):
    """Acceptance: CLOSED -> OPEN -> HALF_OPEN -> CLOSED, observable
    through resilience.* stats and /healthz, driven by real threaded
    serving traffic."""
    stats_ctx = _stats()
    stats_ctx.__enter__()
    with _flags(serving_breaker_threshold=2,
                serving_breaker_cooldown_ms=400.0,
                retry_max_attempts=1):
        eng = ServingEngine(EngineConfig(
            model_dir, max_batch_size=2, seq_buckets=(4,),
            max_wait_us=1000, queue_capacity=16,
            default_timeout_ms=10000))
        srv = serve(eng, port=0)
    try:
        code, body, _ = _http(srv.url + "/healthz")
        assert code == 200 and body["state"] == "ok"
        out = eng.predict({"x": _x()})
        assert np.isfinite(out[0]).all()

        _arm("transient_fail:p=1.0:site=serving")
        for _ in range(2):           # threshold=2 consecutive failures
            with pytest.raises(RuntimeError):
                eng.predict({"x": _x()})
        assert eng.breaker.state == OPEN

        # shedding: direct submit AND the HTTP route answer 503 +
        # Retry-After while OPEN
        with pytest.raises(OverloadedError):
            eng.predict({"x": _x()})
        code, body, hdrs = _http(srv.url + "/v1/predict",
                                 {"inputs": {"x": _x().tolist()}})
        assert code == 503 and body["retryable"] is True
        assert int(hdrs["Retry-After"]) >= 1
        code, body, hdrs = _http(srv.url + "/healthz")
        assert code == 503 and body["state"] == "open"
        assert int(hdrs["Retry-After"]) >= 1
        snap = monitor.get_stats_snapshot()
        assert snap["counters"].get("resilience.breaker_opens") == 1
        assert snap["counters"].get("resilience.breaker_shed", 0) >= 2
        assert snap["counters"].get("resilience.fault_transient",
                                    0) >= 2
        assert snap["gauges"].get("resilience.breaker_state") == 2.0

        _disarm()
        time.sleep(0.45)             # cooldown -> HALF_OPEN (lazily)
        code, body, _ = _http(srv.url + "/healthz")
        assert code == 200 and body["state"] == "degraded"
        assert eng.breaker.state == HALF_OPEN

        out = eng.predict({"x": _x()})   # successful half-open probe
        assert np.isfinite(out[0]).all()
        assert eng.breaker.state == CLOSED
        code, body, _ = _http(srv.url + "/healthz")
        assert code == 200 and body["state"] == "ok"
        snap = monitor.get_stats_snapshot()
        assert snap["gauges"].get("resilience.breaker_state") == 0.0
    finally:
        srv.close()
        eng.stop()
        stats_ctx.__exit__(None, None, None)


def test_serving_nan_guard_retries_corrupted_batch(model_dir):
    """A step_nan corruption at the serving site is cured by the
    engine-level re-run: the client still gets a clean answer."""
    with _stats():
        eng = ServingEngine(EngineConfig(
            model_dir, max_batch_size=2, seq_buckets=(4,),
            max_wait_us=1000, queue_capacity=16,
            default_timeout_ms=10000))
        eng.start()
        try:
            want = eng.predict({"x": _x()})
            _arm("step_nan:at=1:site=serving")
            got = eng.predict({"x": _x()})
            _disarm()
            np.testing.assert_allclose(got[0], want[0],
                                       rtol=1e-5, atol=1e-6)
            snap = monitor.get_stats_snapshot()
            assert snap["counters"].get(
                "resilience.nan_batches_retried") == 1
            assert snap["counters"].get("resilience.fault_nan") == 1
        finally:
            eng.stop()


def test_healthz_warming_until_async_start_completes(model_dir):
    # slow the warmup compiles so the warming window is observable
    _arm("slow_step:ms=400:site=executor")
    eng = ServingEngine(EngineConfig(
        model_dir, max_batch_size=2, seq_buckets=(4,),
        max_wait_us=1000, queue_capacity=16,
        default_timeout_ms=10000))
    srv = serve(eng, port=0, async_start=True)
    try:
        code, body, _ = _http(srv.url + "/healthz")
        assert code == 503 and body["state"] == "warming"
        assert body["engines"]["predict"]["state"] == "warming"
        deadline = time.time() + 60
        while time.time() < deadline:
            code, body, _ = _http(srv.url + "/healthz")
            if code == 200:
                break
            assert code == 503 and body["state"] == "warming"
            time.sleep(0.05)
        assert code == 200 and body["state"] == "ok"
        _disarm()
        code, body, _ = _http(srv.url + "/v1/predict",
                              {"inputs": {"x": _x().tolist()}})
        assert code == 200 and "outputs" in body
    finally:
        _disarm()
        srv.close()
        eng.stop()


# ---------------------------------------------------------------------------
# generation: a failed decode step fails its requests, not the worker
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gen_setup():
    cfg = gpt.gpt_small(vocab_size=8, d_model=16, n_heads=2,
                        n_layers=1, d_ff=32, max_seq_len=8,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        gpt.build_train(cfg, batch=2, seq_len=8, lr=1e-2)
        exe = fluid.Executor()
        exe.run(startup)
    return cfg, scope


def test_generation_step_failure_fails_requests_not_worker(gen_setup):
    cfg, scope = gen_setup
    with _stats():
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=8)
        eng.start()
        try:
            assert eng.health()["state"] == "ready"
            _arm("transient_fail:p=1.0:site=generation")
            r = eng.submit(GenerationRequest([1, 2], 3))
            # the paged-KV engine hits the injected fault on the
            # request's first step (prefill); the legacy path on decode
            with pytest.raises(RuntimeError,
                               match="(decode|prefill) step"):
                r.result(timeout=60.0)
            _disarm()
            # the worker survived: a clean request still completes
            out = eng.generate([1, 2], 3)
            assert len(out["tokens"]) == 3
            snap = monitor.get_stats_snapshot()
            assert snap["counters"].get(
                "resilience.gen_step_failures", 0) >= 1
        finally:
            eng.stop()
        assert eng.health()["state"] == "stopped"


# ---------------------------------------------------------------------------
# chaos loadgen acceptance harness
# ---------------------------------------------------------------------------

def _load_tool(name):
    tools = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                         "..", "tools"))
    sys.path.insert(0, tools)
    try:
        return __import__(name)
    finally:
        sys.path.remove(tools)


def test_chaos_loadgen_zero_wrong_answers_and_schema(tmp_path):
    """Acceptance: --chaos with injected transient+NaN faults completes
    with zero incorrect responses, zero worker deaths, and a bounded
    p99 inflation, all recorded in schema-valid JSONL."""
    lg = _load_tool("serving_loadgen")
    out = str(tmp_path / "chaos.jsonl")
    rc = lg.main(["--chaos", "--requests", "24", "--concurrency", "3",
                  "--fault-spec", "transient_fail:p=0.05,step_nan:p=0.01",
                  "--out", out])
    assert rc == 0

    vb = _load_tool("validate_bench_json")
    assert vb.validate_file(out) == []
    rec = [json.loads(ln) for ln in open(out)][-1]
    assert rec["kind"] == "chaos_loadgen"
    assert rec["wrong_answers"] == 0
    assert rec["worker_deaths"] == 0
    assert rec["p99_inflation"] is None or \
        rec["p99_inflation"] <= rec["p99_bound"]

    # the schema enforces the zero-incorrect-responses contract
    assert vb.validate_chaos_loadgen(dict(rec, wrong_answers=1), "x")
    assert vb.validate_chaos_loadgen(dict(rec, worker_deaths=2), "x")
    assert vb.validate_chaos_loadgen(
        dict(rec, p99_inflation=(rec["p99_bound"] or 50.0) + 1), "x")

"""End-to-end parameter-server training on localhost.

Reference pattern: test_dist_base.py — run pservers + 2 trainers against a
single-process baseline and assert loss equivalence (:22-27). Threads
stand in for the reference's subprocesses (one jax runtime per process is
the TPU-side constraint); the RPC/barrier choreography is identical.
"""
import socket
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.distributed import HeartBeatMonitor, PServerRuntime
from paddle_tpu.distributed.rpc import RPCClient
from paddle_tpu.transpiler import DistributeTranspiler


def _free_endpoint():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return f"127.0.0.1:{s.getsockname()[1]}"


def _build(seed=21):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.2).minimize(loss)
    main.random_seed = startup.random_seed = seed
    return main, startup, loss


def test_ps_sync_training_matches_single_process():
    RPCClient.reset_all()
    rng = np.random.RandomState(5)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randn(16, 1).astype(np.float32)
    n_steps = 3

    # ---- single-process baseline -------------------------------------
    main, startup, loss = _build()
    param_names = [p.name for p in main.global_block().all_parameters()]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(n_steps):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        # keyed by position: unique_name numbering differs per build
        baseline = [np.asarray(scope.get(p)) for p in param_names]

    # ---- PS mode: 2 pservers, 2 trainers ------------------------------
    main, startup, loss = _build()
    eps = [_free_endpoint(), _free_endpoint()]
    transpilers = []
    for tid in range(2):
        t = DistributeTranspiler()
        t.transpile(trainer_id=tid, program=main, pservers=",".join(eps),
                    trainers=2, startup_program=startup)
        transpilers.append(t)

    servers = []
    for ep in eps:
        ps_prog = transpilers[0].get_pserver_program(ep)
        ps_startup = transpilers[0].get_startup_program(ep)
        rt = PServerRuntime(ps_prog, ps_startup, scope=fluid.Scope())
        rt.start()
        servers.append(rt)

    errors = []

    def trainer(tid):
        try:
            sl = slice(0, 8) if tid == 0 else slice(8, 16)
            scope = fluid.Scope()
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            prog = transpilers[tid].get_trainer_program()
            for _ in range(n_steps):
                exe.run(prog, feed={"x": xs[sl], "y": ys[sl]},
                        fetch_list=[loss], scope=scope)
            c = RPCClient.instance(tid)
            for ep in eps:
                c.send_complete(ep)
        except Exception as e:  # surfaced below
            errors.append((tid, e))

    threads = [threading.Thread(target=trainer, args=(tid,))
               for tid in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors

    for rt in servers:
        rt.wait_all_completed(timeout=30)

    # gather params from the owning pservers
    got = {}
    for rt in servers:
        for p in rt.params:
            got[p] = np.asarray(rt.scope.get(p))
    for rt in servers:
        rt.stop()
    RPCClient.reset_all()

    ps_param_names = [p.name for p in main.global_block().all_parameters()]
    assert set(got) == set(ps_param_names)
    for i, p in enumerate(ps_param_names):
        np.testing.assert_allclose(
            got[p], baseline[i], rtol=1e-4, atol=1e-5,
            err_msg=f"param {p} diverged between PS and single-process")


def test_ps_async_mode_trains():
    RPCClient.reset_all()
    main, startup, loss = _build(seed=33)
    ep = _free_endpoint()
    t = DistributeTranspiler()
    t.config.sync_mode = False
    t.transpile(trainer_id=0, program=main, pservers=ep, trainers=1,
                startup_program=startup, sync_mode=False)

    rt = PServerRuntime(t.get_pserver_program(ep),
                        t.get_startup_program(ep), scope=fluid.Scope())
    rt.start()

    rng = np.random.RandomState(7)
    xs = rng.randn(8, 8).astype(np.float32)
    ys = rng.randn(8, 1).astype(np.float32)
    scope = fluid.Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    prog = t.get_trainer_program()
    losses = []
    for _ in range(6):
        lv, = exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss],
                      scope=scope)
        losses.append(float(np.asarray(lv)))
    RPCClient.instance(0).send_complete(ep)
    rt.wait_all_completed(timeout=30)
    rt.stop()
    RPCClient.reset_all()
    assert losses[-1] < losses[0], losses


def test_heartbeat_monitor_detects_lost_worker():
    m = HeartBeatMonitor(n_workers=2, timeout=0.05)
    m.update(0, "PING")
    m.update(1, "PING")
    assert m.lost_workers() == []
    import time
    time.sleep(0.1)
    m.update(1, "COMPLETED")
    assert m.lost_workers() == [0], "worker 0 silent past timeout"


def test_fleet_collective_api():
    from paddle_tpu.incubate.fleet.base.role_maker import (Role,
                                                           UserDefinedRoleMaker)
    from paddle_tpu.incubate.fleet.collective import (Collective,
                                                      DistributedStrategy)

    f = Collective()
    f.init(UserDefinedRoleMaker(current_id=0, role=Role.WORKER,
                                worker_num=2,
                                worker_endpoints=["e0", "e1"]))
    assert f.is_worker() and f.worker_num() == 2

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        opt = f.distributed_optimizer(fluid.optimizer.SGD(0.1),
                                      DistributedStrategy())
        opt.minimize(loss, startup_program=startup)
    types = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in types


def test_fleet_ps_api_roles():
    import os

    from paddle_tpu.incubate.fleet.base.role_maker import PaddleCloudRoleMaker
    from paddle_tpu.incubate.fleet.parameter_server import \
        ParameterServerFleet

    env = {"TRAINING_ROLE": "PSERVER",
           "PADDLE_PSERVERS_IP_PORT_LIST": "127.0.0.1:7000,127.0.0.1:7001",
           "PADDLE_CURRENT_ENDPOINT": "127.0.0.1:7001",
           "PADDLE_TRAINERS_NUM": "2"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        f = ParameterServerFleet()
        f.init(PaddleCloudRoleMaker())
        assert f.is_server()
        assert f.server_index() == 1
        assert f.server_num() == 2 and f.worker_num() == 2
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_geo_sgd_end_to_end():
    """Trainer steps locally; every push_nums steps deltas merge on the
    pserver and the trainer re-syncs (GeoSgdCommunicator semantics)."""
    from paddle_tpu.ops.distributed_ops import _GeoState
    from paddle_tpu.transpiler import GeoSgdTranspiler

    RPCClient.reset_all()
    _GeoState.reset()
    main, startup, loss = _build(seed=44)
    ep = _free_endpoint()
    t = GeoSgdTranspiler()
    t.config.geo_sgd_need_push_nums = 2
    t.transpile(trainer_id=0, program=main, pservers=ep, trainers=1,
                startup_program=startup)

    rt = PServerRuntime(t.get_pserver_program(ep),
                        t.get_startup_program(ep), scope=fluid.Scope())
    rt.start()

    rng = np.random.RandomState(9)
    xs = rng.randn(8, 8).astype(np.float32)
    ys = rng.randn(8, 1).astype(np.float32)
    scope = fluid.Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    prog = t.get_trainer_program()
    for _ in range(5):  # pushes at local steps 2 and 4
        exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss],
                scope=scope)

    p0 = rt.params[0]
    init_ps, _ = None, None
    # after pushes the server copy must have moved away from its init
    exe2 = fluid.Executor()
    init_scope = fluid.Scope()
    exe2.run(t.get_startup_program(ep), scope=init_scope)
    moved = not np.allclose(np.asarray(rt.scope.get(p0)),
                            np.asarray(init_scope.get(p0)))
    RPCClient.instance(0).send_complete(ep)
    rt.wait_all_completed(timeout=30)
    rt.stop()
    RPCClient.reset_all()
    _GeoState.reset()
    assert moved, "geo deltas never reached the pserver"


def test_ps_with_lr_scheduler_matches_single_process():
    """Regression: lr-scheduler ops must ship to the pserver
    (reference _get_lr_ops) — a decayed lr must keep working in PS mode."""
    RPCClient.reset_all()

    def build(seed=55):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            pred = layers.fc(x, size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            lr = layers.exponential_decay(learning_rate=0.2,
                                          decay_steps=2,
                                          decay_rate=0.5,
                                          staircase=True)
            fluid.optimizer.SGD(lr).minimize(loss)
        main.random_seed = startup.random_seed = seed
        return main, startup, loss

    rng = np.random.RandomState(11)
    xs = rng.randn(8, 8).astype(np.float32)
    ys = rng.randn(8, 1).astype(np.float32)
    n_steps = 4

    main, startup, loss = build()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(n_steps):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        base = [np.asarray(scope.get(p.name))
                for p in main.global_block().all_parameters()]

    main, startup, loss = build()
    ep = _free_endpoint()
    t = DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, pservers=ep, trainers=1,
                startup_program=startup)
    rt = PServerRuntime(t.get_pserver_program(ep),
                        t.get_startup_program(ep), scope=fluid.Scope())
    rt.start()

    scope = fluid.Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    prog = t.get_trainer_program()
    # lr-scheduler ops must NOT remain in the trainer (they moved to the
    # pserver); the schedule's step counter increments there, not here
    assert not any(op.type == "increment"
                   for op in prog.global_block().ops)
    for _ in range(n_steps):
        exe.run(prog, feed={"x": xs, "y": ys}, fetch_list=[loss],
                scope=scope)
    RPCClient.instance(0).send_complete(ep)
    rt.wait_all_completed(timeout=30)
    got = [np.asarray(rt.scope.get(p.name))
           for p in main.global_block().all_parameters()]
    rt.stop()
    RPCClient.reset_all()
    for g, b in zip(got, base):
        np.testing.assert_allclose(g, b, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# host-sharded sparse embedding tables (SURVEY §7.10)
# ---------------------------------------------------------------------------


def test_sparse_table_pull_push_roundtrip():
    from paddle_tpu.distributed.sparse_table import (SparseTableClient,
                                                     SparseTableServer)
    servers = [SparseTableServer().start() for _ in range(2)]
    try:
        eps = [s.endpoint for s in servers]
        client = SparseTableClient("emb", eps, dim=4, lr=0.5, seed=1)
        ids = np.array([0, 1, 5, 102], np.int64)
        rows1 = client.pull(ids)
        assert rows1.shape == (4, 4)
        # pull is stable (lazy init happens once)
        np.testing.assert_allclose(client.pull(ids), rows1)
        # rows land on their owning shard only (id % 2)
        assert len(servers[0].tables["emb"]) == 2  # ids 0, 102
        assert len(servers[1].tables["emb"]) == 2  # ids 1, 5
        g = np.ones((4, 4), np.float32)
        client.push(ids, g)
        np.testing.assert_allclose(client.pull(ids), rows1 - 0.5,
                                   rtol=1e-6)
    finally:
        for s in servers:
            s.stop()
        RPCClient.reset_all()


def test_distributed_lookup_table_ps_mode_trains():
    """distributed_lookup_table with endpoints pulls rows host-side and
    pushes row grads back through backward — the vocab never exists on
    device (SURVEY §7.10)."""
    from paddle_tpu.distributed.sparse_table import SparseTableServer

    servers = [SparseTableServer().start() for _ in range(2)]
    try:
        eps = [s.endpoint for s in servers]
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup):
            blk = main.global_block()
            blk.create_var(name="ids", shape=[6], dtype="int64",
                           is_data=True)
            # the anchor parameter keeps the op on backward's path so
            # its grad (= the sparse PUSH) actually runs
            blk.create_parameter("emb_anchor", shape=[1],
                                 dtype="float32")
            blk.create_var(name="emb_rows", stop_gradient=False)
            blk.append_op("distributed_lookup_table",
                          inputs={"Ids": ["ids"], "W": ["emb_anchor"]},
                          outputs={"Outputs": ["emb_rows"]},
                          attrs={"endpoints": eps, "emb_dim": 3,
                                 "table_name": "emb", "sparse_lr": 0.5})
            blk.create_var(name="loss", stop_gradient=False)
            blk.append_op("mean", inputs={"X": ["emb_rows"]},
                          outputs={"Out": ["loss"]})
            from paddle_tpu.backward import append_backward
            append_backward(blk.var("loss"))
        exe = fluid.Executor()
        ids = np.array([1, 2, 3, 4, 5, 6], np.int64)
        with fluid.scope_guard(scope):
            exe.run(startup)
            scope.set("emb_anchor", np.zeros(1, np.float32))
            l1, = exe.run(main, feed={"ids": ids}, fetch_list=["loss"])
            l2, = exe.run(main, feed={"ids": ids}, fetch_list=["loss"])
        # each step pushes d(mean)/d(rows) = 1/18 with lr 0.5: the mean
        # of the pulled rows decreases deterministically
        np.testing.assert_allclose(float(l2), float(l1) - 0.5 / 18,
                                   rtol=1e-4, atol=1e-6)
    finally:
        for s in servers:
            s.stop()
        RPCClient.reset_all()


def test_init_parallel_env_and_global_mesh():
    """Multi-host bootstrap glue: single-process init is a no-op, and
    global_mesh builds meshes over the job's devices with one inferred
    axis (SURVEY.md §2.8 comm-backend mapping)."""
    import jax

    from paddle_tpu.distributed import env as dist_env

    dist_env.init_parallel_env()  # world size 1: must not require env
    assert dist_env.parallel_env_rank() == 0

    mesh = dist_env.global_mesh({"dp": -1, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh2 = dist_env.global_mesh({"sp": 8})
    assert mesh2.shape == {"sp": 8}
    try:
        dist_env.global_mesh({"dp": 3, "tp": 2})
        assert False, "expected size mismatch error"
    except ValueError as e:
        assert "devices" in str(e)

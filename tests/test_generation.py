"""Continuous-batching generation tests: sampling helper, SlotManager,
multi-slot decode parity against serial kv_generate (including
join-mid-flight admission), graph-opt-level invariance, the /v1/generate
HTTP route, and the generation loadgen JSONL schema + report rendering.

The trained model is the tests/test_models.py cyclic-successor task
(token t is followed by (t + 1) % vocab), so greedy continuations are
known exactly and any numerical or scheduling divergence between the
serial and continuous-batching decode paths shows up as a wrong token,
not a tolerance failure.
"""
import io
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import gpt, sampling
from paddle_tpu.serving import (DeadlineExceededError, GenerationEngine,
                                GenerationRequest, QueueFullError,
                                SlotManager, serve)

VOCAB, SEQ = 16, 12


@pytest.fixture(scope="module")
def trained():
    """Tiny GPT trained on the cyclic-successor task; returns
    (cfg, scope, exe).  Greedy continuation of [a, b, c] is
    [(c+1) % VOCAB, (c+2) % VOCAB, ...]."""
    cfg = gpt.gpt_small(vocab_size=VOCAB, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=SEQ,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch=8, seq_len=SEQ,
                                               lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        base = np.arange(SEQ) % VOCAB
        toks = np.stack([(base + i) % VOCAB for i in range(8)]) \
            .astype(np.int64)
        for _ in range(40):
            exe.run(main, feed={"tokens": toks}, fetch_list=[loss])
    return cfg, scope, exe


def _serial_decode(cfg):
    """Fresh batch=1 decode program with UNPREFIXED state names (no
    collision with a gen.-prefixed engine sharing the scope)."""
    dec_main, dec_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(dec_main, dec_start):
        step = gpt.build_decode_step(cfg, batch=1, max_seq=SEQ)
    return dec_main, step


def _kv(exe, scope, dec_main, step, prompt, max_new, **kw):
    return gpt.kv_generate(exe, scope, dec_main, step.token_var,
                           step.logits_var, step.cache_names,
                           prompt=prompt, max_new_tokens=max_new, **kw)


# ---------------------------------------------------------------------------
# sampling helper (models/sampling.py)
# ---------------------------------------------------------------------------

def test_sample_token_greedy_is_argmax():
    logits = np.array([0.1, 2.0, -1.0, 1.9], np.float32)
    assert sampling.sample_token(logits) == 1
    assert sampling.sample_token(logits, temperature=0.0, top_k=2) == 1


def test_sample_token_top_k_masks_tail():
    # with top_k=2 only ids {1, 3} are eligible; at any temperature the
    # sampled id must come from that set
    logits = np.array([0.0, 5.0, 1.0, 4.0], np.float32)
    rng = np.random.RandomState(0)
    got = {sampling.sample_token(logits, temperature=1.0, top_k=2,
                                 rng=rng) for _ in range(64)}
    assert got <= {1, 3} and 1 in got


def test_sample_token_temperature_deterministic_per_seed():
    logits = np.random.RandomState(3).randn(VOCAB).astype(np.float32)
    a = [sampling.sample_token(logits, temperature=0.8,
                               rng=np.random.RandomState(7))
         for _ in range(5)]
    b = [sampling.sample_token(logits, temperature=0.8,
                               rng=np.random.RandomState(7))
         for _ in range(5)]
    assert a == b
    # temperature -> 0 concentrates on the argmax
    assert sampling.sample_token(logits, temperature=1e-4,
                                 rng=np.random.RandomState(0)) == \
        int(np.argmax(logits))


def test_sample_token_validation():
    with pytest.raises(ValueError):
        sampling.sample_token(np.zeros((2, 3), np.float32))
    with pytest.raises(ValueError):
        sampling.sample_token(np.zeros(4, np.float32), temperature=1.0)


# ---------------------------------------------------------------------------
# SlotManager / GenerationRequest
# ---------------------------------------------------------------------------

def test_slot_manager_lowest_first_and_release():
    m = SlotManager(3)
    assert [m.acquire() for _ in range(3)] == [0, 1, 2]
    assert m.acquire() is None and m.free_count() == 0
    m.release(1)
    assert m.active_count() == 2 and m.acquire() == 1
    m.release(2)
    m.release(0)
    assert m.acquire() == 0    # lowest free slot wins again
    with pytest.raises(ValueError):
        m.release(2)           # double release
    with pytest.raises(ValueError):
        m.release(99)
    with pytest.raises(ValueError):
        SlotManager(0)


def test_generation_request_validation():
    with pytest.raises(ValueError):
        GenerationRequest([], 4)
    with pytest.raises(ValueError):
        GenerationRequest([1], 0)
    r = GenerationRequest(np.array([1, 2], np.int64), 3, eos_id=7)
    assert r.prompt == [1, 2] and r.eos_id == 7


# ---------------------------------------------------------------------------
# kv_generate: graph-opt-level invariance (satellite 3)
# ---------------------------------------------------------------------------

def test_kv_generate_bit_exact_across_graph_opt_levels(trained):
    """The optimization pipeline (DCE/fold/CSE/fusion) must not change
    a single sampled token: decode at FLAGS_graph_opt_level 0 and 2
    from identical state must agree bit-exactly."""
    cfg, scope, _ = trained
    dec_main, step = _serial_decode(cfg)
    prev = fluid.FLAGS.graph_opt_level
    outs = {}
    try:
        for lvl in (0, 2):
            fluid.set_flags({"FLAGS_graph_opt_level": lvl})
            exe = fluid.Executor()   # fresh executable cache per level
            outs[lvl] = _kv(exe, scope, dec_main, step,
                            prompt=[0, 1, 2], max_new=7)
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})
    assert outs[0] == outs[2], outs
    assert outs[0] == [(3 + i) % VOCAB for i in range(7)]


# ---------------------------------------------------------------------------
# GenerationEngine vs serial kv_generate (tentpole parity)
# ---------------------------------------------------------------------------

def test_engine_matches_serial_kv_generate(trained):
    """3 mixed-length requests over 2 slots (forces eviction + re-
    admission) must produce EXACTLY the serial kv_generate tokens, with
    zero post-warmup compiles."""
    cfg, scope, exe = trained
    prompts = [([0, 1, 2], 5), ([5, 6], 5), ([1, 2, 3, 4], 4)]
    dec_main, step = _serial_decode(cfg)
    want = [_kv(exe, scope, dec_main, step, p, n) for p, n in prompts]

    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=2, max_seq=SEQ)
    eng.start()
    try:
        resps = [eng.submit(GenerationRequest(p, n)) for p, n in prompts]
        got = [r.result(timeout=30.0)["tokens"] for r in resps]
        assert got == want, (got, want)
        assert eng.post_warmup_compiles() == 0, eng.cache_stats()
    finally:
        eng.stop()
    assert not eng.ready


def test_engine_join_mid_flight_matches_serial(trained):
    """A request admitted from another request's stream callback (i.e.
    joining the batch while decode is mid-flight) must neither perturb
    the running slot nor be perturbed by it."""
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    want_a = _kv(exe, scope, dec_main, step, [0, 1, 2], 6)
    want_b = _kv(exe, scope, dec_main, step, [7, 8], 4)

    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=2, max_seq=SEQ)
    eng.start()
    try:
        later = []

        def cb(tok):
            if not later:   # first generated token of A -> admit B
                later.append(eng.submit(GenerationRequest([7, 8], 4)))

        resp_a = eng.submit(GenerationRequest([0, 1, 2], 6,
                                              stream_cb=cb))
        got_a = resp_a.result(timeout=30.0)["tokens"]
        got_b = later[0].result(timeout=30.0)["tokens"]
        assert got_a == want_a and got_b == want_b
        assert eng.post_warmup_compiles() == 0, eng.cache_stats()
    finally:
        eng.stop()


def test_engine_eos_and_result_metadata(trained):
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    full = _kv(exe, scope, dec_main, step, [0, 1], 6)
    eos = full[2]   # stop after the 3rd generated token
    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=2, max_seq=SEQ)
    eng.start()
    try:
        out = eng.generate([0, 1], 6, eos_id=eos)
        assert out["tokens"] == full[:3]
        assert out["finish_reason"] == "eos"
        assert out["ttft_ms"] > 0 and out["e2e_ms"] >= out["ttft_ms"]
        out2 = eng.generate([0, 1], 4)
        assert out2["finish_reason"] == "length"
        assert len(out2["tokens"]) == 4
    finally:
        eng.stop()


def test_engine_backpressure_and_capacity_validation(trained):
    cfg, scope, _ = trained
    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=1, max_seq=SEQ, queue_capacity=1)
    # not started: submissions queue up, nothing drains
    eng.submit(GenerationRequest([1], 2))
    with pytest.raises(QueueFullError):
        eng.submit(GenerationRequest([2], 2))
    # prompt + max_new - 1 must fit in the KV cache
    with pytest.raises(ValueError):
        eng.submit(GenerationRequest(list(range(8)), SEQ))


def test_engine_deadline_fails_queued_request(trained):
    cfg, scope, _ = trained
    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=1, max_seq=SEQ)
    eng.start()
    try:
        # saturate the single slot with a long request, then queue one
        # with a deadline far shorter than the occupant's runtime
        slow = eng.submit(GenerationRequest([0, 1], 8))
        fast = eng.submit(GenerationRequest([3], 2, timeout_ms=0.01))
        with pytest.raises(DeadlineExceededError):
            fast.result(timeout=30.0)
        assert len(slow.result(timeout=30.0)["tokens"]) == 8
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Paged KV cache: parity, prefix hits, planner visibility (tentpole)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt_level", [0, 2])
def test_paged_engine_matches_slab_and_serial(trained, opt_level):
    """Paged engine (tight pool -> eviction + re-admission pressure)
    over mixed-length prompts must produce EXACTLY the serial slab
    kv_generate tokens at graph-opt level 0 and 2, with both of its
    executables compiled in warmup and none after."""
    cfg, scope, exe = trained
    prompts = [([0, 1, 2], 5), ([5, 6], 5), ([1, 2, 3, 4], 4),
               ([7], 6), ([3, 4, 5, 6, 7], 3)]
    dec_main, step = _serial_decode(cfg)
    want = [_kv(exe, scope, dec_main, step, p, n) for p, n in prompts]

    prev = fluid.FLAGS.graph_opt_level
    fluid.set_flags({"FLAGS_graph_opt_level": opt_level})
    try:
        # 2 slots x 3 blocks/slot (block_size=4, SEQ=12) but only 7
        # allocatable blocks shared with the prefix cache: finished
        # requests' blocks must be evicted and reused for admission
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ,
                               block_size=4, kv_pool_blocks=8)
        assert eng.paged and eng.block_size == 4
        eng.start()
        try:
            resps = [eng.submit(GenerationRequest(p, n))
                     for p, n in prompts]
            got = [r.result(timeout=60.0)["tokens"] for r in resps]
            assert got == want, (got, want)
            assert eng.post_warmup_compiles() == 0, eng.cache_stats()
        finally:
            eng.stop()
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev})


def test_paged_prefix_cache_hit_reuses_blocks(trained):
    """Two requests sharing a whole-block prefix: the second must
    report cached_tokens == the shared full blocks, still match the
    serial reference exactly, and TTFT bookkeeping must count one hit
    and one miss."""
    from paddle_tpu import monitor
    cfg, scope, exe = trained
    prefix = [0, 1, 2, 3, 4, 5, 6, 7]      # two full 4-token blocks
    p_a, p_b = prefix + [8], prefix + [9]
    dec_main, step = _serial_decode(cfg)
    want_a = _kv(exe, scope, dec_main, step, p_a, 3)
    want_b = _kv(exe, scope, dec_main, step, p_b, 3)

    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    try:
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ, block_size=4)
        eng.start()
        try:
            out_a = eng.generate(p_a, 3)
            out_b = eng.generate(p_b, 3)
            assert out_a["tokens"] == want_a
            assert out_b["tokens"] == want_b
            assert out_a["cached_tokens"] == 0
            assert out_b["cached_tokens"] == len(prefix)
            assert eng.post_warmup_compiles() == 0
            stats = eng.kv_block_stats()
            assert stats["paged"] and stats["prefix_entries"] >= 2
            c = monitor.get_stats_snapshot()["counters"]
            assert c["serving.gen_prefix_hits"] == 1
            assert c["serving.gen_prefix_misses"] == 1
        finally:
            eng.stop()
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})


def test_paged_pool_decouples_planner_kv_from_slots(trained):
    """The static memory planner must price the paged program's KV at
    num_blocks x block_bytes (pool persistables, pinned) while the slab
    program pins max_slots x max_seq — the planner-visibility
    acceptance of the paged subsystem."""
    from paddle_tpu.analysis import analyze_program_memory
    cfg, _, _ = trained
    block_size, num_blocks, slots = 4, 5, 4

    paged_main, paged_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(paged_main, paged_start):
        gpt.build_paged_decode_step(cfg, batch=slots, max_seq=SEQ,
                                    block_size=block_size,
                                    num_blocks=num_blocks)
    slab_main, slab_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(slab_main, slab_start):
        gpt.build_decode_step(cfg, batch=slots, max_seq=SEQ)

    kv_paged = analyze_program_memory(paged_main).kv_summary()
    kv_slab = analyze_program_memory(slab_main).kv_summary()
    assert kv_paged["layout"] == "paged"
    assert kv_slab["layout"] == "slab"
    elem = 2 * cfg.n_layers * cfg.d_model * 4        # K+V, fp32
    assert kv_paged["kv_bytes"] == num_blocks * block_size * elem
    assert kv_slab["kv_bytes"] == slots * SEQ * elem
    # the tight pool above is smaller than the slab bound — the whole
    # point: pool size is budget-derived, not slots x max_seq
    assert kv_paged["kv_bytes"] < kv_slab["kv_bytes"]


# ---------------------------------------------------------------------------
# HTTP front end: /v1/generate
# ---------------------------------------------------------------------------

def _post(url, obj):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        try:
            return e.code, json.loads(body)
        except ValueError:
            return e.code, body


def test_http_generate_route(trained):
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    want = _kv(exe, scope, dec_main, step, [0, 1, 2], 5)

    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=2, max_seq=SEQ)
    srv = serve(gen_engine=eng, port=0)   # starts the engine too
    try:
        url = srv.url
        with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
            assert r.status == 200
        code, body = _post(url + "/v1/generate",
                           {"prompt": [0, 1, 2], "max_new_tokens": 5})
        assert code == 200, body
        assert body["tokens"] == want
        assert body["finish_reason"] == "length"
        code, _ = _post(url + "/v1/generate", {"prompt": []})
        assert code == 400
        # no encoder engine behind this server
        code, _ = _post(url + "/v1/predict", {"inputs": {}})
        assert code == 404
    finally:
        srv.close()
        eng.stop()


# ---------------------------------------------------------------------------
# Loadgen schema + metrics report (satellite 6)
# ---------------------------------------------------------------------------

def _load_tool(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_generation_loadgen_schema_and_speedup(tmp_path, capsys):
    loadgen = _load_tool("serving_loadgen")
    v = _load_tool("validate_bench_json")
    out = str(tmp_path / "gen.jsonl")
    rc = loadgen.main(["--generate", "--slots", "4", "--requests", "12",
                       "--max-new-tokens", "6", "--compare-serial",
                       "--check-compiles", "--out", out])
    capsys.readouterr()
    assert rc == 0, "--check-compiles saw a post-warmup compile"
    assert v.validate_file(out) == []
    recs = [json.loads(ln) for ln in open(out) if ln.strip()]
    assert [r["mode"] for r in recs] == ["closed", "serial_baseline"]
    cont, ser = recs
    assert cont["requests"] == 12 and cont["errors"] == 0
    assert cont["tokens"] == 12 * 6
    assert cont["cache"]["post_warmup_compiles"] == 0
    for q in ("p50", "p95", "p99"):
        assert isinstance(cont["ttft_ms"][q], float)
        assert isinstance(cont["latency_ms"][q], float)
    # the acceptance headline: continuous batching beats serial decode
    assert cont["tokens_per_s"] > ser["tokens_per_s"], (cont, ser)

    bad = dict(cont)
    bad["ttft_ms"] = {"p50": 1.0}
    assert any("ttft_ms.p95" in e
               for e in v.validate_generation_loadgen(bad))
    bad2 = dict(cont, tokens_per_s="fast")
    assert any("tokens_per_s" in e
               for e in v.validate_generation_loadgen(bad2))


def test_metrics_report_renders_generation_section(trained, tmp_path):
    metrics_report = _load_tool("metrics_report")
    from paddle_tpu import monitor
    cfg, scope, _ = trained
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    log = str(tmp_path / "gen_stats.jsonl")
    try:
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ)
        eng.start()
        try:
            eng.generate([0, 1, 2], 4)
            eng.generate([5, 6], 3)
        finally:
            eng.stop()
        snap = monitor.get_stats_snapshot()
        c = snap["counters"]
        assert c["serving.gen_requests"] == 2
        assert c["serving.gen_tokens"] == 7
        assert c["serving.gen_steps"] >= 1
        assert "serving.gen_ttft_ms" in snap["histograms"]
        assert "serving.gen_e2e_ms" in snap["histograms"]
        monitor.snapshot_to_jsonl(log)
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})
    with open(log, "a") as f:
        f.write(json.dumps({
            "kind": "generation_loadgen", "mode": "closed",
            "requests": 2, "errors": 0, "duration_s": 0.1,
            "throughput_rps": 20.0, "tokens": 7, "tokens_per_s": 70.0,
            "latency_ms": {"p50": 2.0, "p95": 3.0, "p99": 3.0},
            "ttft_ms": {"p50": 1.0, "p95": 1.5, "p99": 1.5},
            "inter_token_ms": {"p50": 0.5, "p95": 0.7, "p99": 0.7},
            "config": {}, "cache": {"post_warmup_compiles": 0}}) + "\n")
    buf = io.StringIO()
    rc = metrics_report.report(log, out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "-- generation (continuous batching)" in out
    assert "genload[closed]" in out
    assert "post-warmup compiles 0" in out
    assert "ttft" in out and "inter-token" in out

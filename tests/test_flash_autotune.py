"""Flash-attention tile autotuner + end-to-end block plumbing.

Covers the lowering-time tile resolution order (explicit op attr >
autotune cache > FLAGS_flash_attention_block_{q,k}), numerics parity
across tiles, the persistent JSON cache round trip (including the
tools/attn_micro.py --emit-cache writer), the monitor counters/gauges,
and bench.py's partial-results contract. See docs/attention_tuning.md.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import layers, monitor
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                   reference_attention)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


@pytest.fixture
def _restore_flash_flags():
    prev = {
        "FLAGS_enable_monitor": FLAGS.enable_monitor,
        "FLAGS_flash_attention_block_q": FLAGS.flash_attention_block_q,
        "FLAGS_flash_attention_block_k": FLAGS.flash_attention_block_k,
        "FLAGS_flash_autotune": FLAGS.flash_autotune,
        "FLAGS_flash_autotune_cache": FLAGS.flash_autotune_cache,
    }
    yield
    fluid.set_flags(prev)
    autotune.reset_memo()
    monitor.STAT_RESET()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blk", [8, 16, None])
def test_flash_parity_across_blocks(blk, causal, _restore_flash_flags):
    """Tiled kernel == exact attention whatever tile is requested:
    sub-128 asks are clamped up by _pick_block, None delegates to the
    flag/autotune default — numerics must not depend on the tile."""
    fluid.set_flags({"FLAGS_flash_autotune": "off"})
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 256, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 256, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 256, 8), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=blk, block_k=blk)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flags_govern_unset_blocks_and_explicit_attr_wins(
        _restore_flash_flags):
    """Regression for the unpinned tile path: with block attrs unset the
    FLAGS defaults choose the tile; an explicit block_q/block_k beats
    the flag. Asserted via the trace-time flash.block_{q,k} gauges."""
    fluid.set_flags({"FLAGS_enable_monitor": True,
                     "FLAGS_flash_autotune": "off"})
    monitor.STAT_RESET()
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 256, 8), jnp.float32)

    fluid.set_flags({"FLAGS_flash_attention_block_q": 128,
                     "FLAGS_flash_attention_block_k": 128})
    flash_attention(q, q, q)
    g = monitor.get_stats_snapshot()["gauges"]
    assert g["flash.block_q"] == 128 and g["flash.block_k"] == 128

    fluid.set_flags({"FLAGS_flash_attention_block_q": 256,
                     "FLAGS_flash_attention_block_k": 256})
    flash_attention(q, q, q)
    g = monitor.get_stats_snapshot()["gauges"]
    assert g["flash.block_q"] == 256 and g["flash.block_k"] == 256

    # explicit attr wins over the flag
    flash_attention(q, q, q, block_q=128, block_k=128)
    g = monitor.get_stats_snapshot()["gauges"]
    assert g["flash.block_q"] == 128 and g["flash.block_k"] == 128


def test_layer_omits_block_attrs_when_unset():
    """layers.flash_attention must NOT bake a tile into the program when
    the caller leaves blocks unset (the old min(128, t) pin) — absent
    attrs are what lets the flags/autotuner govern per process."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[2, 4, 256, 8], dtype="float32",
                        append_batch_size=False)
        layers.flash_attention(q, q, q, causal=False)
        layers.flash_attention(q, q, q, causal=False, block_q=128,
                               block_k=128)
    ops = [op for op in main.global_block().ops
           if op.type == "flash_attention"]
    assert len(ops) == 2
    assert "block_q" not in ops[0].attrs and "block_k" not in ops[0].attrs
    assert ops[1].attrs["block_q"] == 128 and ops[1].attrs["block_k"] == 128


def test_model_configs_carry_no_pinned_tile():
    """The transformer/nmt model builders must not hard-pin a flash tile
    unless the config asks for one (flash_block_q/k)."""
    from paddle_tpu.models import transformer

    cfg = transformer.bert_base(use_flash=True)
    assert transformer._flash_block_attrs(cfg) == {}
    cfg = transformer.bert_base(use_flash=True, flash_block_q=512,
                                flash_block_k=512)
    assert transformer._flash_block_attrs(cfg) == {"block_q": 512,
                                                   "block_k": 512}
    cfg = transformer.bert_base(use_flash=False)
    assert transformer._flash_block_attrs(cfg) == {"block_q": 0,
                                                   "block_k": 0}
    # use_flash="auto" stays on the composed path until the measured
    # end-to-end crossover (ops/attention.py:FLASH_AUTO_MIN_SEQ): flash
    # lost 37% tok/s at seq 512 and is within noise at 2048, so only
    # 4096+ flips it
    from paddle_tpu.ops.attention import FLASH_AUTO_MIN_SEQ
    assert FLASH_AUTO_MIN_SEQ == 4096
    assert not transformer.bert_base(use_flash="auto",
                                     max_seq_len=512).use_flash
    assert not transformer.bert_base(use_flash="auto",
                                     max_seq_len=2048).use_flash
    assert transformer.bert_base(use_flash="auto",
                                 max_seq_len=4096).use_flash


def test_autotune_cache_roundtrip_and_counters(tmp_path,
                                               _restore_flash_flags):
    path = str(tmp_path / "flash_autotune.json")
    fluid.set_flags({"FLAGS_enable_monitor": True,
                     "FLAGS_flash_autotune": "cached",
                     "FLAGS_flash_autotune_cache": path})
    autotune.reset_memo()
    monitor.STAT_RESET()

    # miss: no file yet -> flag default governs (resolve returns None)
    assert autotune.resolve(256, 8, "float32", False) is None
    c = monitor.get_stats_snapshot()["counters"]
    assert c.get("flash.autotune_cache_miss") == 1

    key = autotune.cache_key(256, 8, "float32", False)
    autotune.store({key: {"block_q": 256, "block_k": 128}}, path,
                   source="test")
    assert autotune.resolve(256, 8, "float32", False) == (256, 128)
    # second resolve answers from the process memo
    assert autotune.resolve(256, 8, "float32", False) == (256, 128)
    c = monitor.get_stats_snapshot()["counters"]
    assert c.get("flash.autotune_cache_hit") == 2

    # the stored file is versioned + merge-safe
    doc = json.load(open(path))
    assert doc["version"] == autotune.CACHE_VERSION
    assert doc["entries"][key]["source"] == "test"
    autotune.store({"other": {"block_q": 512, "block_k": 512}}, path)
    assert set(autotune.load_cache(path)) == {key, "other"}

    # corrupt file: resolve degrades to a miss, never raises
    with open(path, "w") as f:
        f.write("not json{")
    autotune.reset_memo()
    assert autotune.load_cache(path) == {}
    assert autotune.resolve(256, 8, "float32", False) is None

    # off mode skips even the lookup
    fluid.set_flags({"FLAGS_flash_autotune": "off"})
    autotune.reset_memo()
    monitor.STAT_RESET()
    assert autotune.resolve(256, 8, "float32", False) is None
    c = monitor.get_stats_snapshot()["counters"]
    assert "flash.autotune_cache_miss" not in c

    fluid.set_flags({"FLAGS_flash_autotune": "bogus"})
    with pytest.raises(ValueError):
        autotune.resolve(256, 8, "float32", False)


def test_cached_tile_drives_kernel(tmp_path, _restore_flash_flags):
    """A persistent-cache entry actually changes the lowered tile when
    the op leaves blocks unset (gauge evidence), and kernel numerics
    stay exact."""
    path = str(tmp_path / "flash_autotune.json")
    fluid.set_flags({"FLAGS_enable_monitor": True,
                     "FLAGS_flash_autotune": "cached",
                     "FLAGS_flash_autotune_cache": path,
                     "FLAGS_flash_attention_block_q": 256,
                     "FLAGS_flash_attention_block_k": 256})
    autotune.store({autotune.cache_key(256, 8, "float32", False):
                    {"block_q": 128, "block_k": 128}}, path)
    autotune.reset_memo()
    monitor.STAT_RESET()
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 256, 8), jnp.float32)
    out = flash_attention(q, q, q)
    g = monitor.get_stats_snapshot()["gauges"]
    assert g["flash.block_q"] == 128 and g["flash.block_k"] == 128
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference_attention(q, q, q)),
                               rtol=1e-4, atol=1e-4)


def test_attn_micro_emit_cache_roundtrip(tmp_path, _restore_flash_flags):
    """tools/attn_micro.py --emit-cache writes a cache a fresh cached-mode
    process resolves from (the one-microbench-tunes-every-process flow)."""
    path = str(tmp_path / "emitted.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "attn_micro.py"),
         "--seqs", "128", "--bh", "2", "--d", "8", "--blocks", "128",
         "--iters", "1", "--emit-cache", path],
        env=env, capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    entries = autotune.load_cache(path)
    key = autotune.cache_key(128, 8, "bfloat16", False)
    assert entries[key]["block_q"] == 128
    assert entries[key]["source"] == "attn_micro"

    fluid.set_flags({"FLAGS_flash_autotune": "cached",
                     "FLAGS_flash_autotune_cache": path})
    autotune.reset_memo()
    assert autotune.resolve(128, 8, "bfloat16", False) == (128, 128)


def test_bench_partial_lines_and_flash_block_env(monkeypatch):
    import bench

    lines, summary = bench._partial_lines(
        ["bert", "resnet50", "gpt"], {"bert"}, "killed: signal 15")
    assert [ln["metric"] for ln in lines] == [
        "resnet50_imagenet_images_per_sec_per_chip",
        "gpt_small_pretrain_tokens_per_sec_per_chip"]
    assert all(ln["error"] == "killed: signal 15" and ln["value"] == 0.0
               for ln in lines)
    assert summary["kind"] == "bench_partial_summary"
    assert summary["completed"] == ["bert"]
    json.dumps([summary, *lines])  # the artifact must stay parseable

    monkeypatch.delenv("BENCH_FLASH_BLOCK", raising=False)
    assert bench._bench_flash_blocks() == {}
    monkeypatch.setenv("BENCH_FLASH_BLOCK", "512")
    assert bench._bench_flash_blocks() == {"flash_block_q": 512,
                                           "flash_block_k": 512}
    monkeypatch.setenv("BENCH_FLASH_BLOCK", "512,256")
    assert bench._bench_flash_blocks() == {"flash_block_q": 512,
                                           "flash_block_k": 256}

"""Regression tests for the round-1 advisor findings (ADVICE.md):

1. roi_perspective_transform sampled image 0 for every roi (batch > 1)
2. generate_mask_labels indexed gt masks by class label, not matched
   instance
3. warpctc ignored per-sequence logit lengths (padded timesteps emitted)
4. multiclass_nms counted valid rows by score > 0, inconsistent with the
   padding threshold
5. unique padded with x[0], indistinguishable from real data

Each test fails against the pre-fix lowering.
"""
import numpy as np

import paddle_tpu  # registers ops  # noqa: F401

from test_parity_ops import run


def test_roi_perspective_transform_uses_roi_image():
    # image 0 all zeros, image 1 all ones; the only roi lives on image 1
    x = np.stack([np.zeros((1, 8, 8), np.float32),
                  np.ones((1, 8, 8), np.float32)])
    quad = np.array([[1.0, 1.0, 6.0, 1.0, 6.0, 6.0, 1.0, 6.0]], np.float32)
    out = run("roi_perspective_transform",
              {"X": [x], "ROIs": [quad],
               "RoisNum": [np.array([0, 1], np.int32)]},
              {"transformed_height": 4, "transformed_width": 4,
               "spatial_scale": 1.0})["Out"][0]
    assert np.allclose(np.asarray(out), 1.0), \
        "roi on image 1 must sample image 1"


def test_generate_mask_labels_matches_instance_not_class():
    # two gt instances of the SAME class: instance 0 fills the left half,
    # instance 1 the right half. A roi over the left region must get
    # instance 0's mask (class-indexed lookup would return segms[1]).
    m = 8
    seg0 = np.zeros((m, m), np.float32)
    seg0[:, : m // 2] = 1.0
    seg1 = np.zeros((m, m), np.float32)
    seg1[:, m // 2:] = 1.0
    segms = np.stack([seg0, seg1])
    rois = np.array([[0.0, 0.0, 7.0, 15.0]], np.float32)  # left strip
    out = run("generate_mask_labels",
              {"Rois": [rois],
               "LabelsInt32": [np.array([[1]], np.int32)],
               "GtClasses": [np.array([1, 1], np.int32)],
               "GtSegms": [segms],
               "ImInfo": [np.array([[16.0, 16.0, 1.0]], np.float32)]},
              {"resolution": m, "num_classes": 2})
    # class-expanded targets [R, num_classes*res^2]: class-1 slice holds
    # the roi-cropped mask, class-0 slice stays -1 (ignore)
    tgt = np.asarray(out["MaskInt32"][0]).reshape(2, m, m)
    assert np.all(tgt[0] == -1), "non-matched class slice must be ignore"
    # the roi covers exactly instance 0's region (left strip), so its
    # crop of seg0 is all ones; instance-1's mask would crop to zeros
    assert np.all(tgt[1] == 1), \
        "roi over the left instance must take instance 0's mask"


def test_warpctc_respects_logit_lengths():
    rng = np.random.RandomState(7)
    t, c = 6, 4
    logits_full = rng.randn(1, t, c).astype(np.float32)
    labels = np.array([[1, 2, -1]], np.int32)
    # exact-length reference: only the first 4 timesteps exist
    ref = float(np.asarray(run(
        "warpctc", {"Logits": [logits_full[:, :4]], "Label": [labels]},
        {"blank": 0})["Loss"][0])[0])
    padded = float(np.asarray(run(
        "warpctc", {"Logits": [logits_full], "Label": [labels],
                    "LogitsLength": [np.array([4], np.int64)]},
        {"blank": 0})["Loss"][0])[0])
    assert abs(ref - padded) < 1e-4, \
        f"padded timesteps changed the loss: {ref} vs {padded}"


def test_multiclass_nms_counts_negative_score_detections():
    # logits-style scores below zero but above the threshold must be
    # counted as valid detections
    boxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    scores = np.array([[[-0.2, -0.3],    # class 0 = background
                        [-0.2, -0.3]]], np.float32)  # class 1
    out = run("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
              {"score_threshold": -0.5, "nms_threshold": 0.3,
               "nms_top_k": 2, "keep_top_k": 2, "background_label": 0})
    nums = np.asarray(out["NmsRoisNum"][0])
    assert nums[0] == 2, f"expected 2 valid detections, got {nums[0]}"
    rows = np.asarray(out["Out"][0])[0]
    assert (rows[:2, 0] == 1).all()          # class 1 rows are valid
    assert np.allclose(sorted(rows[:2, 1]), [-0.3, -0.2], atol=1e-6)


def test_unique_padding_is_distinguishable():
    x = np.array([3, 1, 3, 2], np.int64)
    out = run("unique", {"X": [x]})
    u = np.asarray(out["Out"][0])
    inv = np.asarray(out["Index"][0])
    n_real = inv.max() + 1
    assert n_real == 3
    assert set(u[:n_real].tolist()) == {1, 2, 3}
    # pad slots hold the dtype-max sentinel, never a real value
    # (u.dtype, not the feed dtype: jax may truncate int64 -> int32)
    assert (u[n_real:] == np.iinfo(u.dtype).max).all()

    uc = run("unique_with_counts", {"X": [x]})
    cnt = np.asarray(uc["Count"][0])
    assert cnt[n_real:].sum() == 0 and cnt[:n_real].sum() == 4

    # bool input must not crash (iinfo is undefined for bool)
    ub = run("unique", {"X": [np.array([True, False, True])]})
    assert set(np.asarray(ub["Out"][0])[:2].tolist()) == {False, True}


def test_roi_batch_index_from_lod_offsets():
    # RoisLod offsets [0, 0, 1] == RoisNum [0, 1]: roi is on image 1
    x = np.stack([np.zeros((1, 8, 8), np.float32),
                  np.ones((1, 8, 8), np.float32)])
    quad = np.array([[1.0, 1.0, 6.0, 1.0, 6.0, 6.0, 1.0, 6.0]], np.float32)
    out = run("roi_perspective_transform",
              {"X": [x], "ROIs": [quad],
               "RoisLod": [np.array([0, 0, 1], np.int32)]},
              {"transformed_height": 4, "transformed_width": 4,
               "spatial_scale": 1.0})["Out"][0]
    assert np.allclose(np.asarray(out), 1.0)


def test_generate_mask_labels_partitions_gts_by_image():
    # identical left-half masks in two images; roi belongs to image 1 so
    # it must match gt 1 even though gt 0 has identical box + class
    m = 8
    seg = np.zeros((m, m), np.float32)
    seg[:, : m // 2] = 1.0
    seg_marked = seg.copy()
    seg_marked[0, 0] = 0.0  # distinguishable corner pixel
    segms = np.stack([seg, seg_marked])
    rois = np.array([[0.0, 0.0, 7.0, 15.0]], np.float32)
    out = run("generate_mask_labels",
              {"Rois": [rois],
               "LabelsInt32": [np.array([[1]], np.int32)],
               "GtClasses": [np.array([1, 1], np.int32)],
               "GtSegms": [segms],
               "RoisNum": [np.array([0, 1], np.int32)],
               "GtNum": [np.array([1, 1], np.int32)],
               "ImInfo": [np.array([[16.0, 16.0, 1.0],
                                    [16.0, 16.0, 1.0]], np.float32)]},
              {"resolution": m, "num_classes": 2})
    tgt = np.asarray(out["MaskInt32"][0]).reshape(2, m, m)
    assert np.all(tgt[0] == -1), "non-matched class slice must be ignore"
    # the roi covers instance 1's region; its crop is all ones except
    # the samples hitting the marked corner cell (the gt grid is 2x2
    # image pixels per cell; target cols 0-1 of row 0 both sample it) —
    # instance 0's crop would be all ones, so the zeros prove image
    # partitioning
    expect = np.ones((m, m), np.int32)
    expect[0, 0] = expect[0, 1] = 0
    assert np.array_equal(tgt[1], expect), \
        "roi on image 1 must match image 1's gt instance"


def test_conditional_block_skipped_output_is_loud():
    """A conditional_block output with no prior value must surface as a
    NaN sentinel + warning when the branch is skipped — not silent
    zeros (VERDICT r2: IfElse silent-wrong-numerics hazard)."""
    import warnings
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        x = layers.data("cbx", shape=[2], dtype="float32",
                        append_batch_size=False)
        cond_var = blk.create_var(name="cb_cond", dtype="bool", shape=[1])
        blk.create_var(name="cb_cond_full", dtype="bool")
        blk.append_op("less_than", inputs={"X": [x.name], "Y": [x.name]},
                      outputs={"Out": ["cb_cond_full"]})
        blk.append_op("reduce_all", inputs={"X": ["cb_cond_full"]},
                      outputs={"Out": [cond_var.name]},
                      attrs={"dim": [0], "keep_dim": False})
        sub = main._create_block()
        with fluid.program_guard(main):
            sub_out = sub.create_var(name="cb_out", dtype="float32",
                                     stop_gradient=False)
            sub.append_op("scale", inputs={"X": [x.name]},
                          outputs={"Out": ["cb_out"]},
                          attrs={"scale": 2.0})
        main._rollback()
        blk.create_var(name="cb_out", dtype="float32")
        blk.append_op("conditional_block",
                      inputs={"Cond": [cond_var.name], "Input": [x.name]},
                      outputs={"Out": ["cb_out"]},
                      attrs={"sub_block": sub.idx,
                             "input_vars": [x.name],
                             "output_vars": ["cb_out"]})
    exe = fluid.Executor()
    from paddle_tpu.ops import controlflow as cf
    cf._WARNED_UNSET.discard("cb_out")  # warning is once-per-var
    with fluid.scope_guard(scope):
        exe.run(startup)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out, = exe.run(main, feed={"cbx": np.ones(2, np.float32)},
                           fetch_list=["cb_out"])
        assert any("no value" in str(x.message) for x in w)
    # x < x is always false -> branch skipped -> loud NaN, not zeros
    assert np.isnan(out).all()


def test_amp_backward_dots_stay_bf16():
    """Round-3 MFU fix: preferred_element_type=f32 on the matmul
    lowerings forced an f32 primal, so jax's dot transpose emitted every
    BACKWARD dot as f32 x f32 (2/3 of training FLOPs off the bf16 MXU
    path). The AMP-rewritten program must lower with zero f32 dots."""
    import re

    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.contrib import mixed_precision as mp

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("ampx", shape=[16], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        y = layers.matmul(h, h, transpose_y=True)
        loss = layers.mean(y)
        mp.decorate(fluid.optimizer.SGD(learning_rate=0.1)).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"ampx": np.ones((8, 16), np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss])
        step_fn = list(exe._cache.values())[-1]
        state = {n: jnp.asarray(scope.find_var(n))
                 for n in step_fn.state_in_names}
        fa = exe._prepare_feed(main.current_block(), feed, None)
        txt = jax.jit(step_fn.fn).lower(state, fa, jnp.uint32(0)).as_text()
    dots = re.findall(r"stablehlo\.dot_general[^\n]*->\s*tensor<([0-9x]*)"
                      r"(\w+)>", txt)
    assert dots, "expected dot_generals in the lowered step"
    f32_dots = [s for s, dt in dots if dt == "f32"]
    assert not f32_dots, f"f32 dots leaked into the AMP step: {f32_dots}"

"""Flag registry + env bootstrap + wired knobs.

Reference: platform/flags.cc (central DEFINE_* registry),
python/paddle/fluid/__init__.py:165 read_env_flags, core.globals get/set.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core import flags as flags_mod
from paddle_tpu.core.flags import FLAGS


def test_get_set_flags_api():
    assert fluid.get_flags("FLAGS_check_nan_inf") == {
        "FLAGS_check_nan_inf": False}
    fluid.set_flags({"FLAGS_executor_cache_capacity": 8})
    assert FLAGS.executor_cache_capacity == 8
    fluid.set_flags({"FLAGS_executor_cache_capacity": 64})
    with pytest.raises(ValueError):
        fluid.get_flags("FLAGS_no_such_flag")
    with pytest.raises(AttributeError):
        FLAGS.no_such_flag


def test_env_bootstrap(monkeypatch):
    monkeypatch.setenv("FLAGS_reader_queue_depth", "7")
    monkeypatch.setenv("FLAGS_pallas_interpret", "true")
    flags_mod.reload_from_env()
    assert FLAGS.reader_queue_depth == 7
    assert FLAGS.pallas_interpret is True
    monkeypatch.delenv("FLAGS_reader_queue_depth")
    monkeypatch.delenv("FLAGS_pallas_interpret")
    FLAGS.reader_queue_depth = 2
    FLAGS.pallas_interpret = False


def test_compat_noop_flags_accepted():
    # reference scripts set these; they must be storable without effect
    fluid.set_flags({"FLAGS_eager_delete_tensor_gb": 1.5,
                     "FLAGS_fraction_of_gpu_memory_to_use": 0.5})
    info = {f["name"]: f for f in flags_mod.flag_info()}
    assert info["eager_delete_tensor_gb"]["noop"] is True
    assert info["eager_delete_tensor_gb"]["value"] == 1.5


def test_check_nan_inf_raises_with_op_name():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 2], dtype="float32",
                        append_batch_size=False)
        y = layers.log(x)  # log(-1) = nan
    exe = fluid.Executor()
    scope = fluid.Scope()
    bad = np.array([[1.0, -1.0], [2.0, 3.0]], np.float32)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(Exception) as ei:
                exe.run(main, feed={"x": bad}, fetch_list=[y])
        assert "Inf/Nan" in str(ei.value)
        # a clean input passes with the flag still on
        with fluid.scope_guard(scope):
            out, = exe.run(main, feed={"x": np.abs(bad)}, fetch_list=[y])
        assert np.isfinite(out).all()
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_set_flags_invalidates_cached_executables():
    # a trace-time flag flipped AFTER the first run must not be silently
    # ignored by the executable cache
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], dtype="float32",
                        append_batch_size=False)
        y = layers.log(x)
    exe = fluid.Executor()
    scope = fluid.Scope()
    bad = np.array([1.0, -1.0], np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": bad}, fetch_list=[y])  # cached, no guard
        fluid.set_flags({"FLAGS_check_nan_inf": True})
        try:
            with pytest.raises(Exception, match="Inf/Nan"):
                exe.run(main, feed={"x": bad}, fetch_list=[y])
        finally:
            fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_reader_queue_depth_flag_used_when_capacity_unset():
    fluid.set_flags({"FLAGS_reader_queue_depth": 5})
    try:
        loader = fluid.DataLoader.from_generator(feed_list=[])
        assert loader.capacity is None  # resolved at iteration time

        def rd():
            yield {"a": np.zeros(1)}

        loader.set_batch_generator(rd)
        assert len(list(loader())) == 1  # smoke: queue built from flag
    finally:
        fluid.set_flags({"FLAGS_reader_queue_depth": 2})


def test_executor_cache_evicts_lru():
    fluid.set_flags({"FLAGS_executor_cache_capacity": 2})
    try:
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            for i in range(4):  # 4 distinct programs -> 4 cache keys
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    x = layers.data("x", shape=[2], dtype="float32",
                                    append_batch_size=False)
                    y = layers.scale(x, scale=float(i + 1))
                exe.run(startup)
                out, = exe.run(main, feed={"x": np.ones(2, np.float32)},
                               fetch_list=[y])
                assert out[0] == i + 1
        assert len(exe._cache) <= 2
    finally:
        fluid.set_flags({"FLAGS_executor_cache_capacity": 64})


def test_reference_flag_inventory_accepted():
    """App. C parity: every flags.cc name a reference program might set
    is accepted (live knob or documented no-op)."""
    import paddle_tpu as fluid
    names = ["allocator_strategy", "check_nan_inf", "fast_check_nan_inf",
             "cudnn_deterministic", "cudnn_exhaustive_search",
             "fraction_of_gpu_memory_to_use", "eager_delete_tensor_gb",
             "inner_op_parallelism", "paddle_num_threads", "use_mkldnn",
             "rpc_deadline", "communicator_send_queue_size",
             "selected_gpus", "init_p2p", "use_pinned_memory",
             "benchmark", "tracer_profile_fname"]
    names += ["sync_nccl_allreduce", "eager_delete_scope",
              "fuse_parameter_groups_size", "fuse_parameter_memory_size",
              "reader_queue_speed_test_mode", "max_body_size",
              "rpc_get_thread_num", "local_exe_sub_scope_limit"]
    vals = fluid.get_flags([f"FLAGS_{n}" for n in names])
    assert len(vals) == len(names)
    # reference type fidelity: double flag stays float
    assert isinstance(vals["FLAGS_local_exe_sub_scope_limit"], float)
    try:
        fluid.set_flags({"FLAGS_cudnn_deterministic": True})
        assert fluid.get_flags(["FLAGS_cudnn_deterministic"])[
            "FLAGS_cudnn_deterministic"] is True
    finally:
        fluid.set_flags({"FLAGS_cudnn_deterministic": False})

"""Speculative decoding tests: NgramDrafter suffix matching,
accept_draft accept/reject boundaries, and end-to-end engine parity —
the spec-decode engine must produce EXACTLY the serial kv_generate
tokens (greedy and sampled) at graph-opt level 0 and 2 with zero
post-warmup compiles.

The trained model is the usual cyclic-successor task (token t is
followed by (t + 1) % VOCAB) at max_seq_len 32, long enough for
generations to wrap the vocab-16 cycle: once the context repeats, the
n-gram drafter locks on and the verify path actually runs, so parity
here exercises real accepted drafts, not just the n_valid=1 fallback.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.models import gpt, sampling
from paddle_tpu.serving import (GenerationEngine, GenerationRequest,
                                NgramDrafter)

VOCAB, SEQ = 16, 32


@pytest.fixture(scope="module")
def trained():
    """Tiny GPT trained on the cyclic-successor task; returns
    (cfg, scope, exe). max_seq_len is 32 so generations can run past
    one full cycle of the vocab and give the drafter repeats to find."""
    cfg = gpt.gpt_small(vocab_size=VOCAB, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=SEQ,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch=8, seq_len=12,
                                               lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        base = np.arange(12) % VOCAB
        toks = np.stack([(base + i) % VOCAB for i in range(8)]) \
            .astype(np.int64)
        for _ in range(40):
            exe.run(main, feed={"tokens": toks}, fetch_list=[loss])
    return cfg, scope, exe


def _serial_decode(cfg):
    dec_main, dec_start = fluid.Program(), fluid.Program()
    with fluid.program_guard(dec_main, dec_start):
        step = gpt.build_decode_step(cfg, batch=1, max_seq=SEQ)
    return dec_main, step


def _kv(exe, scope, dec_main, step, prompt, max_new, **kw):
    return gpt.kv_generate(exe, scope, dec_main, step.token_var,
                           step.logits_var, step.cache_names,
                           prompt=prompt, max_new_tokens=max_new, **kw)


# ---------------------------------------------------------------------------
# NgramDrafter (serving/spec_decode.py)
# ---------------------------------------------------------------------------

def test_drafter_proposes_what_followed_the_suffix():
    d = NgramDrafter(max_ngram=3, k=4)
    # suffix [7, 8] occurred earlier, followed by 9, 1, 2, 7
    assert d.draft([7, 8, 9, 1, 2, 7, 8]) == [9, 1, 2, 7]
    assert d.draft([7, 8, 9, 1, 2, 7, 8], k=2) == [9, 1]


def test_drafter_caps_at_k():
    d = NgramDrafter(max_ngram=2, k=2)
    assert d.draft([5, 6, 1, 2, 3, 4, 5, 6]) == [1, 2]
    # per-call k overrides the constructor cap
    assert d.draft([5, 6, 1, 2, 3, 4, 5, 6], k=3) == [1, 2, 3]


def test_drafter_most_recent_occurrence_wins():
    # suffix [1, 2] appears twice; the later occurrence (followed by 9)
    # must win over the earlier one (followed by 5)
    d = NgramDrafter(max_ngram=2, k=1)
    assert d.draft([1, 2, 5, 0, 1, 2, 9, 0, 1, 2]) == [9]


def test_drafter_prefers_longer_ngram():
    # the 1-gram suffix [2] occurs at index 0 (followed by 7) but the
    # 2-gram suffix [3, 2] also matches (followed by 8): longer wins
    d = NgramDrafter(max_ngram=3, k=1)
    assert d.draft([2, 7, 3, 2, 8, 0, 3, 2]) == [8]


def test_drafter_no_match_returns_empty():
    d = NgramDrafter(max_ngram=3, k=4)
    assert d.draft([1, 2, 3, 4, 5]) == []       # unique suffix
    assert d.draft([]) == []
    assert d.draft([1]) == []                   # too short
    assert NgramDrafter(max_ngram=0).draft([1, 2, 1, 2]) == []


def test_drafter_period_one_repeat():
    # an immediately-repeated token is itself an n-gram hit: the match
    # at index 0 is followed by the second 9
    d = NgramDrafter(max_ngram=1, k=2)
    assert d.draft([9, 9]) == [9]


# ---------------------------------------------------------------------------
# accept_draft (models/sampling.py)
# ---------------------------------------------------------------------------

def _rows(*argmaxes, vocab=8):
    """Logit rows whose greedy token is the given id per row."""
    out = np.zeros((len(argmaxes), vocab), np.float32)
    for j, t in enumerate(argmaxes):
        out[j, t] = 5.0
    return out


def test_accept_draft_full_accept_emits_bonus():
    emitted, n_acc = sampling.accept_draft(_rows(1, 2, 3, 4), [1, 2, 3])
    assert emitted == [1, 2, 3, 4] and n_acc == 3   # k accepted + bonus


def test_accept_draft_full_reject_is_single_step():
    emitted, n_acc = sampling.accept_draft(_rows(7, 2, 3), [1, 2])
    assert emitted == [7] and n_acc == 0  # the draw IS the correction


def test_accept_draft_stops_at_first_mismatch():
    emitted, n_acc = sampling.accept_draft(_rows(1, 6, 3), [1, 2])
    assert emitted == [1, 6] and n_acc == 1


def test_accept_draft_empty_draft_degenerates_to_sample():
    emitted, n_acc = sampling.accept_draft(_rows(5), [])
    assert emitted == [sampling.sample_token(_rows(5)[0])] == [5]
    assert n_acc == 0


def test_accept_draft_shape_validation():
    with pytest.raises(ValueError):
        sampling.accept_draft(_rows(1, 2), [1, 2])   # needs k+1 rows
    with pytest.raises(ValueError):
        sampling.accept_draft(_rows(1)[0], [])       # 1-D logits


def test_accept_draft_sampled_path_matches_serial_rng_order():
    """One rng draw per EMITTED token in serial order: replaying the
    same rows through sample_token with an identically-seeded rng must
    reproduce accept_draft's emissions exactly."""
    rows = np.random.RandomState(11).randn(4, VOCAB).astype(np.float32)
    draft = [3, 1, 4]
    emitted, n_acc = sampling.accept_draft(
        rows, draft, temperature=0.9, top_k=5,
        rng=np.random.RandomState(42))
    ref_rng = np.random.RandomState(42)
    want = []
    for j in range(len(emitted)):
        want.append(sampling.sample_token(rows[j], temperature=0.9,
                                          top_k=5, rng=ref_rng))
    assert emitted == want
    # n_accepted is the length of the agreeing prefix
    agree = 0
    while agree < min(len(emitted), len(draft)) \
            and emitted[agree] == draft[agree]:
        agree += 1
    assert n_acc == agree


# ---------------------------------------------------------------------------
# engine parity: spec decode vs serial kv_generate
# ---------------------------------------------------------------------------

# mixed lengths; max_new large enough that contexts wrap the vocab-16
# cycle and the drafter actually fires
PROMPTS = [([0, 1, 2], 24), ([5, 6], 20), ([1, 2, 3, 4], 22),
           ([7], 18), ([3, 4, 5], 16)]


@pytest.mark.parametrize("opt_level", [0, 2])
def test_spec_engine_matches_serial_greedy(trained, opt_level):
    """Greedy spec-decode engine under eviction pressure (tight pool)
    must be token-for-token identical to serial kv_generate, with all
    three executables compiled in warmup and none after, and with the
    spec counters showing real drafting happened."""
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    want = [_kv(exe, scope, dec_main, step, p, n) for p, n in PROMPTS]

    prev_opt = fluid.FLAGS.graph_opt_level
    prev_mon = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_graph_opt_level": opt_level,
                     "FLAGS_enable_monitor": True})
    monitor.reset_stats()
    try:
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ, block_size=4,
                               spec_decode=True, spec_k=4)
        assert eng.paged and eng.spec_decode and eng.spec_k == 4
        eng.start()
        try:
            resps = [eng.submit(GenerationRequest(p, n))
                     for p, n in PROMPTS]
            got = [r.result(timeout=120.0)["tokens"] for r in resps]
            assert got == want, (got, want)
            assert eng.post_warmup_compiles() == 0, eng.cache_stats()
        finally:
            eng.stop()
        c = monitor.get_stats_snapshot()["counters"]
        assert c.get("serving.gen_spec_steps", 0) > 0
        proposed = c.get("serving.gen_spec_draft_proposed", 0)
        accepted = c.get("serving.gen_spec_draft_accepted", 0)
        assert proposed > 0 and 0 < accepted <= proposed
    finally:
        fluid.set_flags({"FLAGS_graph_opt_level": prev_opt,
                         "FLAGS_enable_monitor": prev_mon})


def test_spec_engine_matches_serial_sampled(trained):
    """temperature > 0: accept_draft's one-draw-per-emitted-token rng
    discipline keeps sampled outputs bit-exact against serial decode
    with the same seed."""
    cfg, scope, exe = trained
    cases = [([0, 1, 2], 24, 0.9, 7), ([5, 6], 20, 1.3, 11),
             ([1, 2, 3, 4], 22, 0.7, 3)]
    dec_main, step = _serial_decode(cfg)
    want = [_kv(exe, scope, dec_main, step, p, n,
                temperature=t, seed=s) for p, n, t, s in cases]

    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=2, max_seq=SEQ, block_size=4,
                           spec_decode=True, spec_k=4)
    eng.start()
    try:
        resps = [eng.submit(GenerationRequest(p, n, temperature=t,
                                              seed=s))
                 for p, n, t, s in cases]
        got = [r.result(timeout=120.0)["tokens"] for r in resps]
        assert got == want, (got, want)
        assert eng.post_warmup_compiles() == 0, eng.cache_stats()
    finally:
        eng.stop()


def test_spec_per_request_opt_out_and_flag_default(trained):
    """GenerationRequest.spec_decode=False forces plain decode on a
    spec engine (still correct); FLAGS_gen_spec_decode drives the
    engine default when the ctor arg is omitted."""
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    prompt, n = [0, 1, 2], 20
    want = _kv(exe, scope, dec_main, step, prompt, n)

    prev = fluid.FLAGS.gen_spec_decode
    fluid.set_flags({"FLAGS_gen_spec_decode": True})
    try:
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ, block_size=4)
        assert eng.spec_decode  # picked up the flag default
        eng.start()
        try:
            opted_out = eng.submit(
                GenerationRequest(prompt, n, spec_decode=False))
            opted_in = eng.submit(GenerationRequest(prompt, n))
            assert opted_out.result(timeout=120.0)["tokens"] == want
            assert opted_in.result(timeout=120.0)["tokens"] == want
        finally:
            eng.stop()
    finally:
        fluid.set_flags({"FLAGS_gen_spec_decode": prev})


def test_spec_requires_paged_engine(trained):
    """Slab-layout engines have no verify substrate: spec_decode must
    quietly resolve to off rather than break."""
    cfg, scope, exe = trained
    eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                           max_slots=2, max_seq=SEQ,
                           spec_decode=True, paged=False)
    assert not eng.spec_decode


# ---------------------------------------------------------------------------
# acceptance-aware adaptive spec_k (serving/spec_decode.update_spec_k)
# ---------------------------------------------------------------------------

def test_update_spec_k_pure_function():
    from paddle_tpu.serving import update_spec_k
    # first sample seeds the EWMA directly; low acceptance shrinks
    k, ewma, moved = update_spec_k(4, None, 0.0, k_max=4)
    assert (k, moved) == (3, -1) and ewma == 0.0
    # floor at 1 draft — never moves below
    k2, _, moved2 = update_spec_k(1, 0.0, 0.0, k_max=4)
    assert (k2, moved2) == (1, 0)
    # high acceptance grows back, capped at k_max
    k3, ewma3, moved3 = update_spec_k(3, 0.9, 1.0, k_max=4)
    assert (k3, moved3) == (4, 1) and ewma3 > 0.8
    k4, _, moved4 = update_spec_k(4, 0.95, 1.0, k_max=4)
    assert (k4, moved4) == (4, 0)
    # mid-band holds steady; EWMA blends alpha*rate + (1-alpha)*prev
    k5, ewma5, moved5 = update_spec_k(3, 0.5, 0.6, k_max=4, alpha=0.5)
    assert (k5, moved5) == (3, 0) and abs(ewma5 - 0.55) < 1e-9
    # out-of-range rates are clamped, not propagated
    _, ewma6, _ = update_spec_k(2, None, 7.5, k_max=4)
    assert ewma6 == 1.0


class _BadDrafter:
    """Adversarial drafter: always proposes the wrong successor, so
    every draft is rejected and the adaptive budget must collapse."""

    def draft(self, ctx, k=None):
        k = int(k or 1)
        return [(int(ctx[-1]) + 3) % VOCAB] * k


def test_adaptive_spec_k_shrinks_under_bad_drafter(trained):
    """With a drafter that is always wrong, the per-slot budget must
    walk down to 1 (gen_spec_k_shrinks counter moves, effective-k gauge
    ends at 1) while verify keeps the output EXACTLY serial."""
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    prompt, n = [0, 1, 2], 24
    want = _kv(exe, scope, dec_main, step, prompt, n)

    prev_mon = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    try:
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ, block_size=4,
                               spec_decode=True, spec_k=4,
                               spec_adaptive=True)
        assert eng.spec_adaptive
        eng._drafter = _BadDrafter()
        eng.start()
        try:
            got = eng.generate(prompt, n)["tokens"]
            assert got == want, (got, want)
            assert eng.post_warmup_compiles() == 0
        finally:
            eng.stop()
        snap = monitor.get_stats_snapshot()
        c = snap["counters"]
        assert c.get("serving.gen_spec_k_shrinks", 0) >= 3  # 4 -> 1
        assert not c.get("serving.gen_spec_k_grows")
        assert snap["gauges"].get("serving.gen_spec_k_effective") == 1
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev_mon})


def test_adaptive_spec_k_off_keeps_static_budget(trained):
    """spec_adaptive=False: the same bad drafter never moves the
    budget (no shrink counters), and parity still holds."""
    cfg, scope, exe = trained
    dec_main, step = _serial_decode(cfg)
    prompt, n = [5, 6], 20
    want = _kv(exe, scope, dec_main, step, prompt, n)

    prev_mon = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    try:
        eng = GenerationEngine(cfg, scope, exe=fluid.Executor(),
                               max_slots=2, max_seq=SEQ, block_size=4,
                               spec_decode=True, spec_k=4,
                               spec_adaptive=False)
        assert not eng.spec_adaptive
        eng._drafter = _BadDrafter()
        eng.start()
        try:
            assert eng.generate(prompt, n)["tokens"] == want
        finally:
            eng.stop()
        c = monitor.get_stats_snapshot()["counters"]
        assert not c.get("serving.gen_spec_k_shrinks")
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev_mon})

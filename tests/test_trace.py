"""Per-request distributed tracing tests (paddle_tpu/trace.py).

Covers the span primitives (context propagation within and across
threads, W3C traceparent parsing), the head+tail sampling rules
(errored and slow requests are ALWAYS kept, the ring is bounded), the
request-completion choke point (`complete_request` finishes the trace
exactly once at the outermost owner), the exporters, the end-to-end
GenerationEngine span tree (queue -> prefill -> decode with a nested
fetch, critical path consistent with measured e2e, zero post-warmup
compiles), HTTP trace continuation, and the tools/trace_report.py +
validate_bench_json.py trace_report surfaces.
"""
import contextlib
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

import paddle_tpu as fluid
from paddle_tpu import trace
from paddle_tpu.models import gpt
from paddle_tpu.serving import GenerationEngine, GenerationRequest, serve

VOCAB, SEQ = 16, 12

_TRACE_FLAGS = ("enable_trace", "trace_sample", "trace_tail_slow_ms",
                "trace_ring_capacity", "enable_monitor")


def _load_tool(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@contextlib.contextmanager
def _trace_on(sample=1.0, tail_slow_ms=0.0, ring=8192, monitor=False):
    from paddle_tpu import monitor as mon
    prev = {k: getattr(fluid.FLAGS, k) for k in _TRACE_FLAGS}
    fluid.set_flags({"FLAGS_enable_trace": True,
                     "FLAGS_trace_sample": sample,
                     "FLAGS_trace_tail_slow_ms": tail_slow_ms,
                     "FLAGS_trace_ring_capacity": ring,
                     "FLAGS_enable_monitor": monitor})
    trace.reset()
    if monitor:
        mon.reset_stats()
    try:
        yield
    finally:
        trace.reset()
        if monitor:
            mon.reset_stats()
        fluid.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------

def test_disabled_tracing_is_inert():
    prev = fluid.FLAGS.enable_trace
    fluid.set_flags({"FLAGS_enable_trace": False})
    try:
        assert trace.start_span("op") is None
        assert trace.current_span() is None
        assert trace.current_trace_id() is None
        assert not trace.finish_trace(None)
        trace.complete_request(None)           # must not raise
        trace.end_span(None)
        with trace.use_span(None) as s:
            assert s is None
        with trace.span("op") as s:
            assert s is None
        assert trace.record_span("op", 0.0, 1.0, None) is None
    finally:
        fluid.set_flags({"FLAGS_enable_trace": prev})


def test_traceparent_parse_format_roundtrip():
    with _trace_on():
        root = trace.start_span("op")
        hdr = trace.format_traceparent(root)
        assert hdr == f"00-{root.trace_id}-{root.span_id}-01"
        assert trace.parse_traceparent(hdr) == (root.trace_id,
                                                root.span_id)
        trace.finish_trace(root)
    # malformed headers must be ignored, not propagated
    tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
    assert trace.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)
    # case-insensitive per spec
    assert trace.parse_traceparent(
        f"00-{tid.upper()}-{sid}-01") == (tid, sid)
    for bad in (None, "", "garbage",
                f"00-{tid}-{sid}",             # too few fields
                f"00-{tid}-{sid}-01-extra",    # too many fields
                f"ff-{tid}-{sid}-01",          # forbidden version
                f"00-{tid[:-2]}-{sid}-01",     # short trace id
                f"00-{tid}-{sid[:-1]}-01",     # short span id
                f"00-{'z' * 32}-{sid}-01",     # non-hex
                f"00-{'0' * 32}-{sid}-01",     # all-zero trace id
                f"00-{tid}-{'0' * 16}-01"):    # all-zero span id
        assert trace.parse_traceparent(bad) is None, bad


def test_span_tree_context_and_events():
    with _trace_on():
        root = trace.start_span("root", attrs={"k": 1})
        assert root.parent_id is None and trace.is_root(root)
        with trace.use_span(root):
            assert trace.current_span() is root
            assert trace.current_trace_id() == root.trace_id
            with trace.span("child", attrs={"j": 2}) as c:
                assert c.trace_id == root.trace_id
                assert c.parent_id == root.span_id
                c.add_event("tick", n=3)
                with trace.span("grandchild") as g:
                    assert g.parent_id == c.span_id
        assert c.dur_ms is not None and c.status == "ok"
        assert c.events[0]["name"] == "tick" and c.events[0]["n"] == 3
        # error inside span() marks status and re-raises
        with pytest.raises(ValueError):
            with trace.use_span(root):
                with trace.span("boom"):
                    raise ValueError("nope")
        assert trace.finish_trace(root)        # sample=1.0 -> head keep
        spans = trace.drain_spans()
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"root", "child", "grandchild", "boom"}
        assert by_name["boom"]["status"] == "error"
        assert by_name["root"]["attrs"]["keep"] == "head"
        assert all(s["dur_ms"] is not None for s in spans)


def test_thread_handoff_propagation():
    """Contextvars do not cross threads; the hand-off contract is to
    pass the Span object and re-enter it with use_span()."""
    with _trace_on():
        root = trace.start_span("root")
        seen = {}

        def worker():
            # fresh thread: no ambient span leaks in
            seen["ambient"] = trace.current_span()
            with trace.use_span(root):
                child = trace.start_span("worker_op")
                trace.end_span(child)
                seen["child"] = child

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert seen["ambient"] is None
        assert seen["child"].trace_id == root.trace_id
        assert seen["child"].parent_id == root.span_id
        trace.finish_trace(root)


def test_record_span_retroactive():
    with _trace_on():
        root = trace.start_span("root")
        sp = trace.record_span("sub", 100.0, 100.25, root,
                               attrs={"bytes": 8})
        assert sp.parent_id == root.span_id and sp.t_start == 100.0
        assert abs(sp.dur_ms - 250.0) < 1e-6
        assert sp.attrs["bytes"] == 8
        trace.finish_trace(root)


# ---------------------------------------------------------------------------
# Head + tail sampling
# ---------------------------------------------------------------------------

def test_tail_keep_rules_fixed_threshold():
    with _trace_on(sample=0.0, tail_slow_ms=5.0):
        # fast + ok + head coin lost -> dropped
        r = trace.start_span("req")
        assert not trace.finish_trace(r, e2e_ms=1.0)
        # slower than FLAGS_trace_tail_slow_ms -> kept
        r = trace.start_span("req")
        assert trace.finish_trace(r, e2e_ms=50.0)
        assert r.attrs["keep"] == "slow"
        # errored -> always kept, regardless of latency
        r = trace.start_span("req")
        assert trace.finish_trace(r, error="boom", e2e_ms=0.1)
        assert r.attrs["keep"] == "error" and r.status == "error"
        assert r.attrs["error"] == "boom"
        kept = trace.drain_spans()
        assert [s["attrs"]["keep"] for s in kept] == ["slow", "error"]
    with _trace_on(sample=1.0, tail_slow_ms=5.0):
        r = trace.start_span("req")
        assert trace.finish_trace(r, e2e_ms=0.1)
        assert r.attrs["keep"] == "head"


def test_tail_rolling_p95_threshold():
    """With FLAGS_trace_tail_slow_ms=0 the slow rule self-calibrates to
    a rolling p95 — undefined until enough requests have finished."""
    with _trace_on(sample=0.0, tail_slow_ms=0.0):
        assert trace.slow_threshold_ms() is None
        for _ in range(30):
            r = trace.start_span("req")
            assert not trace.finish_trace(r, e2e_ms=10.0)
        thresh = trace.slow_threshold_ms()
        assert thresh is not None and abs(thresh - 10.0) < 1e-6
        r = trace.start_span("req")
        assert trace.finish_trace(r, e2e_ms=100.0)   # 10x the p95
        assert r.attrs["keep"] == "slow"
        # record_latency=False traces don't drag the window (the
        # batch-span exemption)
        r = trace.start_span("batch")
        assert not trace.finish_trace(r, e2e_ms=0.01,
                                      record_latency=False)
        assert abs(trace.slow_threshold_ms() - 10.0) < 1e-6


def test_ring_capacity_bound_and_drain():
    with _trace_on(sample=1.0, ring=6):
        ids = []
        for _ in range(10):
            r = trace.start_span("req")
            ids.append(r.trace_id)
            trace.finish_trace(r)
        ring = trace.ring_spans()
        assert len(ring) == 6
        # oldest evicted first
        assert [s["trace_id"] for s in ring] == ids[4:]
        assert trace.drain_spans() == ring
        assert trace.ring_spans() == []


def test_complete_request_root_vs_child():
    """complete_request runs the tail decision exactly once, at the
    outermost owner: child spans are just ended, the root finishes the
    trace."""
    with _trace_on(sample=1.0):
        root = trace.start_span("outer")
        child = trace.start_span("gen.request", parent=root)
        trace.complete_request(child)          # not root -> end only
        assert child.dur_ms is not None
        assert trace.is_root(root)             # trace still in flight
        assert trace.ring_spans() == []
        trace.complete_request(root, e2e_ms=3.0)
        assert not trace.is_root(root)
        spans = trace.drain_spans()
        assert {s["name"] for s in spans} == {"outer", "gen.request"}
        assert spans[0]["attrs"]["e2e_ms"] == 3.0


def test_trace_stats_counters():
    with _trace_on(sample=0.0, tail_slow_ms=5.0, monitor=True):
        from paddle_tpu import monitor
        r = trace.start_span("req")
        trace.start_span("child", parent=r)
        trace.finish_trace(r, e2e_ms=50.0)     # slow -> both spans kept
        r = trace.start_span("req")
        trace.finish_trace(r, e2e_ms=0.1)      # dropped
        c = monitor.get_stats_snapshot()["counters"]
        assert c["trace.spans_started"] == 3
        assert c["trace.spans_kept"] == 2
        assert c["trace.spans_dropped"] == 1
        g = monitor.get_stats_snapshot()["gauges"]
        assert g["trace.ring_spans"] == 2.0


def test_exporters_jsonl_and_chrome(tmp_path):
    with _trace_on():
        root = trace.start_span("req")
        with trace.use_span(root):
            with trace.span("work"):
                pass
        trace.finish_trace(root)
        jl = str(tmp_path / "spans.jsonl")
        n = trace.export_jsonl(jl, trace.ring_spans())
        assert n == 2
        recs = [json.loads(x) for x in open(jl)]
        assert all(r["kind"] == "span" for r in recs)
        ct = str(tmp_path / "trace.json")
        n = trace.export_chrome_tracing(ct, include_phases=False)
        assert n == 2
        doc = json.load(open(ct))
        ev = doc["traceEvents"][0]
        assert ev["ph"] == "X"
        assert ev["args"]["trace_id"] == root.trace_id


# ---------------------------------------------------------------------------
# trace_report + validate_bench_json surfaces
# ---------------------------------------------------------------------------

def _sp(trace_id, span_id, parent_id, name, t0, dur_ms, status="ok",
        attrs=None):
    return {"kind": "span", "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "name": name, "t_start": t0,
            "dur_ms": dur_ms, "status": status, "attrs": attrs or {},
            "events": [], "links": [], "tid": 1}


def test_trace_report_build_and_consistency():
    trp = _load_tool("trace_report")
    t1, t2 = "a" * 32, "b" * 32
    spans = [
        _sp(t1, "r1", None, "request", 100.0, 10.0,
            attrs={"e2e_ms": 10.0, "keep": "head"}),
        _sp(t1, "q1", "r1", "queue", 100.0, 2.0),
        _sp(t1, "p1", "r1", "prefill", 100.002, 3.0),
        _sp(t1, "d1", "r1", "decode", 100.005, 5.0),
        _sp(t1, "f1", "d1", "fetch", 100.005, 1.0),
        # second trace: a child LONGER than its parent -> inconsistency
        _sp(t2, "r2", None, "request", 200.0, 5.0,
            attrs={"e2e_ms": 5.0, "keep": "slow"}),
        _sp(t2, "q2", "r2", "queue", 200.0, 50.0),
    ]
    by_id, children = trp.build_index(spans)
    roots = trp.trace_roots(spans, by_id)
    assert {r["span_id"] for r in roots} == {"r1", "r2"}
    row = trp.analyze_request(spans[0], children)
    assert row["e2e_ms"] == 10.0
    assert abs(row["critical_path_ms"] - 10.0) < 1e-6
    assert row["queue_ms"] == 2.0 and row["fetch_ms"] == 1.0
    assert row["n_spans"] == 5
    checked, violations = trp.check_consistency(spans, children)
    assert checked == 5 and len(violations) == 1
    assert "queue" in violations[0] and "request" in violations[0]

    report = trp.build_report(spans, top=5, source="unit")
    assert report["kind"] == "trace_report"
    assert report["n_traces"] == 2 and report["n_requests"] == 2
    assert report["keep"] == {"head": 1, "slow": 1}
    assert abs(report["breakdown_ms"]["queue"]["mean_ms"] - 26.0) < 1e-6
    assert report["consistency"]["violations"] == 1
    # slowest sorted by e2e descending
    assert [r["trace_id"] for r in report["slowest"]] == [t1, t2]
    text = trp.render(report)
    assert "critical" in text and "queue" in text

    v = _load_tool("validate_bench_json")
    assert v.validate_trace_report(report) == []
    bad = json.loads(json.dumps(report))
    bad["n_spans"] = -1
    del bad["breakdown_ms"]["decode"]
    bad["consistency"]["checked"] = "x"
    errs = v.validate_trace_report(bad)
    assert any("n_spans" in e for e in errs)
    assert any("breakdown_ms.decode" in e for e in errs)
    assert any("consistency.checked" in e for e in errs)


# ---------------------------------------------------------------------------
# End to end: GenerationEngine span tree + HTTP continuation
# ---------------------------------------------------------------------------

def _fresh_engine(max_slots=2):
    cfg = gpt.gpt_small(vocab_size=VOCAB, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=SEQ,
                        dropout=0.0, use_flash=False)
    eng = GenerationEngine(cfg, fluid.Scope(), exe=fluid.Executor(),
                           max_slots=max_slots, max_seq=SEQ)
    eng.init_scope()
    return eng


def test_engine_end_to_end_span_tree():
    """The acceptance shape: a traced request produces a complete
    queue -> prefill -> decode(+fetch) tree whose critical path agrees
    with the measured e2e, with zero post-warmup compiles. Reuses the
    same engine to check the error tail rule: a rejected request is
    kept even at sample=0 (one engine build — this is a 1-core box)."""
    trp = _load_tool("trace_report")
    from paddle_tpu.serving import QueueFullError
    with _trace_on(sample=1.0):
        eng = _fresh_engine()
        eng.start()
        try:
            t0 = time.perf_counter()
            root = trace.start_span("request")
            with trace.use_span(root):
                resp = eng.submit(GenerationRequest([0, 1, 2], 5))
            out = resp.result(timeout=60.0)
            e2e_ms = (time.perf_counter() - t0) * 1e3
            trace.finish_trace(root, e2e_ms=e2e_ms)
            assert out["finish_reason"] == "length"
            assert eng.post_warmup_compiles() == 0, eng.cache_stats()
            spans = trace.drain_spans()
            # rejected request at sample=0: errors are ALWAYS kept
            fluid.set_flags({"FLAGS_trace_sample": 0.0,
                             "FLAGS_trace_tail_slow_ms": 1e9})
            eng.queue_capacity = 0
            with pytest.raises(QueueFullError):
                eng.submit(GenerationRequest([0, 1], 2))
        finally:
            eng.stop()
        err_spans = trace.drain_spans()
        assert err_spans, "errored request was not kept"
        err_root = next(s for s in err_spans
                        if s["name"] == "gen.request")
        assert err_root["status"] == "error"
        assert err_root["attrs"]["keep"] == "error"
        assert "QueueFullError" in err_root["attrs"]["error"]
        by_id, children = trp.build_index(spans)
        roots = [r for r in trp.trace_roots(spans, by_id)
                 if r["name"] in trp.REQUEST_ROOTS]
        assert len(roots) == 1
        rd = roots[0]
        names = {s["name"] for s in trp._walk(rd, children)}
        assert {"gen.request", "queue", "prefill",
                "decode", "fetch"} <= names
        row = trp.analyze_request(rd, children)
        crit = row["critical_path_ms"]
        # queue + prefill + decode must account for the request (the
        # fetch child is nested inside decode, not double-counted)
        assert abs(e2e_ms - crit) <= 0.10 * e2e_ms + 5.0, (e2e_ms, row)
        checked, violations = trp.check_consistency(spans, children)
        assert checked > 0 and violations == [], violations
        # gen.request carries the engine's own e2e/token metadata
        gen = next(s for s in spans if s["name"] == "gen.request")
        assert gen["parent_id"] == rd["span_id"]
        assert gen["attrs"]["tokens"] == 5
        assert gen["attrs"]["finish_reason"] == "length"


def _post(url, obj, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, dict(r.headers), json.loads(r.read().decode())


def test_http_traceparent_continuation_and_request_id():
    with _trace_on(sample=1.0):
        eng = _fresh_engine()
        srv = serve(gen_engine=eng, port=0)   # starts the engine too
        try:
            url = srv.url + "/v1/generate"
            body = {"prompt": [0, 1, 2], "max_new_tokens": 3}
            # caller sends a valid traceparent -> the server continues
            # that trace and echoes it back
            tid, sid = "c" * 32, "d" * 16
            code, hdrs, _ = _post(url, body, headers={
                "traceparent": f"00-{tid}-{sid}-01"})
            assert code == 200
            assert hdrs["X-Request-Id"] == tid
            got = trace.parse_traceparent(hdrs["traceparent"])
            assert got is not None and got[0] == tid
            # no (or malformed) traceparent -> a fresh trace id
            code, hdrs2, _ = _post(url, body,
                                   headers={"traceparent": "garbage"})
            assert code == 200
            rid = hdrs2["X-Request-Id"]
            assert rid != tid and len(rid) == 32
            int(rid, 16)
        finally:
            srv.close()
            eng.stop()
        # the handler finishes the trace just after writing the reply;
        # give that thread a beat before inspecting the ring
        deadline = time.time() + 5.0
        spans = trace.ring_spans()
        while time.time() < deadline and len(
                {s["trace_id"] for s in spans}) < 2:
            time.sleep(0.02)
            spans = trace.ring_spans()
        mine = [s for s in spans if s["trace_id"] == tid]
        assert mine, "continued trace never reached the ring"
        http_root = next(s for s in mine if s["name"] == "http.request")
        assert http_root["parent_id"] == sid       # remote parent
        assert http_root["attrs"]["http.status"] == 200
        names = {s["name"] for s in mine}
        assert {"gen.request", "queue", "prefill", "decode"} <= names


def test_loadgen_trace_mode_end_to_end(tmp_path, capsys):
    """`serving_loadgen --generate --trace`: exit 0, a span dump on
    disk, a trace audit record with zero violations, and a
    trace_report over the dump that validates against the schema."""
    loadgen = _load_tool("serving_loadgen")
    trp = _load_tool("trace_report")
    v = _load_tool("validate_bench_json")
    out = str(tmp_path / "gen.jsonl")
    spans_out = str(tmp_path / "gen.spans.jsonl")
    with _trace_on():   # loadgen arms the flags itself; restore after
        rc = loadgen.main(["--generate", "--slots", "2",
                           "--requests", "6", "--max-new-tokens", "4",
                           "--check-compiles", "--trace",
                           "--trace-out", spans_out, "--out", out])
    capsys.readouterr()
    assert rc == 0
    rec = next(json.loads(ln) for ln in open(out) if ln.strip())
    tr = rec["trace"]
    assert tr["requests"] == 6
    assert tr["incomplete"] == 0
    assert tr["crit_path_violations"] == 0
    assert tr["consistency_violations"] == 0
    assert tr["spans"] > 0 and os.path.exists(spans_out)
    spans = trp.load_spans([spans_out])
    assert len(spans) == tr["spans"]
    report = trp.build_report(spans, source=spans_out)
    assert report["n_requests"] == 6
    assert v.validate_trace_report(report) == []
    rep_out = str(tmp_path / "report.jsonl")
    assert trp.main([spans_out, "--out", rep_out, "--strict"]) == 0
    capsys.readouterr()
    assert v.validate_file(rep_out) == []

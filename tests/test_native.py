"""Native C++ runtime layer: data feed, buffer pool, profiler, dataset API.

Mirrors the reference's colocated C++ tests (native/src/native_test.cc runs
the pure-C++ suite via `make test`) plus the Python-visible Dataset path
(reference: test_dataset.py over DatasetFactory/InMemoryDataset)."""
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.native import AVAILABLE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_mnist_like(tmp_path, n_files=2, rows=40, dim=8):
    """MultiSlot format: `<n> v... <n> v...` per line (feature, label)."""
    rng = np.random.RandomState(0)
    files = []
    for fi in range(n_files):
        p = tmp_path / f"part-{fi}.txt"
        with open(p, "w") as f:
            for _ in range(rows):
                x = rng.randn(dim)
                y = rng.randint(0, 10)
                f.write(f"{dim} " + " ".join(f"{v:.4f}" for v in x) +
                        f" 1 {y}\n")
        files.append(str(p))
    return files


def test_cpp_unit_suite():
    """The C++ asserts (queue/pool/feed/profiler) run via make test."""
    r = subprocess.run(["make", "-s", "test"],
                       cwd=os.path.join(REPO, "native"),
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL NATIVE TESTS OK" in r.stdout


@pytest.mark.skipif(not AVAILABLE, reason="native lib unavailable")
def test_native_feed_batches(tmp_path):
    from paddle_tpu.native import NativeDataFeed
    files = _write_mnist_like(tmp_path, n_files=3, rows=50, dim=8)
    feed = NativeDataFeed([("x", "float32", 8), ("y", "int64", 1)],
                          batch_size=16, drop_last=False)
    feed.set_filelist(files)
    feed.start(3)
    total, batches = 0, 0
    for b in feed:
        assert b["x"].shape[1] == 8
        assert b["y"].shape == (b["x"].shape[0], 1)
        assert (b["y"] >= 0).all() and (b["y"] < 10).all()
        total += b["x"].shape[0]
        batches += 1
    assert total == 150
    assert feed.samples_parsed == 150
    assert feed.parse_errors == 0


def test_dataset_train_from_dataset(tmp_path):
    """End-to-end: Dataset files → native feed → Executor training loop
    (reference pattern: test_dataset.py + train_from_dataset)."""
    files = _write_mnist_like(tmp_path, n_files=2, rows=32, dim=8)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        fc = layers.fc(x, size=10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(fc, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    dataset.set_batch_size(16)
    dataset.set_thread(2)
    dataset.set_use_var([x, y])
    dataset.set_filelist(files)
    dataset.local_shuffle()

    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = exe.train_from_dataset(main, dataset, fetch_list=[loss])
    assert out and np.isfinite(np.asarray(out[0])).all()


def test_native_profiler_trace(tmp_path):
    from paddle_tpu import native
    if not AVAILABLE:
        pytest.skip("native lib unavailable")
    native.profiler_reset()
    native.profiler_enable()
    with native.profiler_scope("phase_a"):
        with native.profiler_scope("phase_b"):
            pass
    native.profiler_disable()
    path = str(tmp_path / "trace.json")
    n = native.profiler_dump(path)
    assert n == 4
    import json
    with open(path) as f:
        trace = json.load(f)
    assert len(trace["traceEvents"]) == 4


def test_c_abi_trainer_trains():
    """Pure-C training entry (native/src/trainer.cc + trainer_test.cc):
    the reference's train/demo/demo_trainer.cc analogue — load a saved
    program from C, run 40 training steps through the C ABI, loss must
    drop. Skipped when no C++ toolchain/libpython is present."""
    import shutil
    if shutil.which("g++") is None or \
            shutil.which("python3-config") is None:
        pytest.skip("no C++ toolchain / python3-dev")
    r = subprocess.run(["make", "-s", "trainer-test"],
                       cwd=os.path.join(REPO, "native"),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trainer_test OK" in r.stdout


def test_native_trainer_python_surface(tmp_path):
    """save_trainer_model/load_trainer round-trip from Python (the same
    artifact layout the C ABI consumes)."""
    from paddle_tpu.native_trainer import load_trainer, save_trainer_model

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[4, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    save_trainer_model(str(tmp_path), main, startup, loss.name)
    tr = load_trainer(str(tmp_path))
    rng2 = np.random.RandomState(0)
    feed = {"x": rng2.randn(4, 3).astype(np.float32),
            "y": rng2.randn(4, 1).astype(np.float32)}
    first = tr.run_step(feed)
    for _ in range(30):
        last = tr.run_step(feed)
    assert last < first
    tr.save(str(tmp_path / "out"))
    assert (tmp_path / "out" / "main_program.json").exists()


def test_c_abi_predictor_predicts():
    """Pure-C inference entry (native/src/predictor.cc +
    predictor_test.cc): the reference inference/capi analogue — save an
    inference model, load + run it from C, read raw outputs back.
    Skipped when no C++ toolchain/libpython is present."""
    import shutil
    if shutil.which("g++") is None or \
            shutil.which("python3-config") is None:
        pytest.skip("no C++ toolchain / python3-dev")
    r = subprocess.run(["make", "-s", "predictor-test"],
                       cwd=os.path.join(REPO, "native"),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "predictor_test OK" in r.stdout


def test_native_predictor_python_surface(tmp_path):
    """NativePredictor drives the same artifact fluid C API consumes."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.native_predictor import load_predictor

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    d = str(tmp_path / "pred_model")
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data("x", shape=[8], dtype="float32")
        z = layers.fc(x, size=2)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [z], exe,
                                      main_program=main)
    p = load_predictor(d)
    xv = np.ones((3, 8), np.float32)
    n = p.run_raw([("x", xv.tobytes(), "float32", (3, 8))])
    assert n == 1
    dtype, shape, nbytes = p.output_meta(0)
    assert dtype == "float32" and shape == [3, 2]
    out = np.frombuffer(p.output_bytes(0), np.float32).reshape(shape)
    assert np.isfinite(out).all()

"""Test config: run on a virtual 8-device CPU mesh.

Mirrors the reference's localhost multi-process trick (test_dist_base.py:877
NCCL_P2P_DISABLE=1) — here XLA fakes 8 host devices so sharding/collective
paths compile and run without TPU hardware (SURVEY.md §7 hard part (h)).
Must run before jax is imported anywhere.
"""
import os

# Hard-set: the host environment pins JAX_PLATFORMS to the TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

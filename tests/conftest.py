"""Test config: run on a virtual 8-device CPU mesh.

Mirrors the reference's localhost multi-process trick (test_dist_base.py:877
NCCL_P2P_DISABLE=1) — XLA fakes 8 host devices so sharding/collective paths
compile and run without TPU hardware (SURVEY.md §7 hard part (h)).

Hermeticity: the host image registers a TPU-tunnel PJRT backend from a
sitecustomize at interpreter start and pins JAX_PLATFORMS to it; its init
can block on TPU-tunnel state. Setting os.environ["JAX_PLATFORMS"] here is
too late (jax is already imported), but jax.config.update still works — and
no XLA client exists yet, so XLA_FLAGS set now is honoured by the CPU
client. This keeps tests fully independent of the TPU tunnel.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8 and jax.devices()[0].platform == "cpu", \
    "tests require the 8-device virtual CPU mesh"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: exhaustive sweeps excluded from the tier-1 run "
        "(-m 'not slow')")

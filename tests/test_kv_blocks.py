"""Paged KV-cache bookkeeping tests: BlockPool free-list/refcount
semantics, PrefixCache chain hashing + LRU eviction, and the
block-aware admission errors of GenerationEngine.submit.

Pure host-side unit tests — no programs are built or compiled here
(the paged decode executables are covered end-to-end by
tests/test_generation.py); the engine admission test constructs the
engine without start(), so no warmup runs either.
"""
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import gpt
from paddle_tpu.serving import GenerationEngine, GenerationRequest
from paddle_tpu.serving.kv_blocks import (SCRATCH_BLOCK, BlockPool,
                                          PrefixCache, blocks_for_tokens)


# ---------------------------------------------------------------------------
# blocks_for_tokens
# ---------------------------------------------------------------------------

def test_blocks_for_tokens_ceil():
    assert blocks_for_tokens(0, 16) == 0
    assert blocks_for_tokens(-3, 16) == 0
    assert blocks_for_tokens(1, 16) == 1
    assert blocks_for_tokens(16, 16) == 1
    assert blocks_for_tokens(17, 16) == 2
    assert blocks_for_tokens(32, 16) == 2


# ---------------------------------------------------------------------------
# BlockPool
# ---------------------------------------------------------------------------

def test_block_pool_alloc_order_and_scratch():
    pool = BlockPool(num_blocks=4, block_size=8)
    assert pool.capacity() == 3 and pool.free_count() == 3
    # lowest id first, and the scratch block is never handed out
    assert [pool.alloc() for _ in range(3)] == [1, 2, 3]
    assert SCRATCH_BLOCK not in (1, 2, 3)
    assert pool.alloc() is None          # exhausted, not an exception
    assert pool.used_count() == 3


def test_block_pool_refcount_release():
    pool = BlockPool(num_blocks=4, block_size=8)
    a = pool.alloc()
    assert pool.refcount(a) == 1
    pool.incref(a)                        # shared: two holders
    pool.decref(a)
    assert pool.refcount(a) == 1 and pool.free_count() == 2
    pool.decref(a)                        # last holder gone -> freed
    assert pool.refcount(a) == 0 and pool.free_count() == 3
    assert pool.alloc() == a              # lowest free id again


def test_block_pool_validation():
    with pytest.raises(ValueError):
        BlockPool(num_blocks=1, block_size=8)     # no usable block
    with pytest.raises(ValueError):
        BlockPool(num_blocks=4, block_size=0)
    pool = BlockPool(num_blocks=4, block_size=8)
    with pytest.raises(ValueError):
        pool.incref(SCRATCH_BLOCK)
    with pytest.raises(ValueError):
        pool.decref(2)                            # never allocated


# ---------------------------------------------------------------------------
# PrefixCache
# ---------------------------------------------------------------------------

def test_chunk_hashes_chain_semantics():
    bs = 4
    h_ab = PrefixCache.chunk_hashes([1, 2, 3, 4, 5, 6, 7, 8], bs)
    assert len(h_ab) == 2
    # same first block -> same first hash; the chain makes the second
    # hash cover the whole prefix, not just its own tokens
    h_ac = PrefixCache.chunk_hashes([1, 2, 3, 4, 9, 9, 9, 9], bs)
    assert h_ac[0] == h_ab[0] and h_ac[1] != h_ab[1]
    # same second block under a DIFFERENT first block must not collide
    h_db = PrefixCache.chunk_hashes([0, 0, 0, 0, 5, 6, 7, 8], bs)
    assert h_db[1] != h_ab[1]
    # partial tail blocks are not hashable
    assert len(PrefixCache.chunk_hashes([1, 2, 3, 4, 5], bs)) == 1
    assert PrefixCache.chunk_hashes([1, 2], bs) == []


def test_prefix_cache_lookup_insert_and_cap():
    bs = 4
    pool = BlockPool(num_blocks=8, block_size=bs)
    cache = PrefixCache(pool)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    hashes = PrefixCache.chunk_hashes(prompt, bs)
    b1, b2 = pool.alloc(), pool.alloc()
    assert cache.insert(hashes[0], b1)
    assert cache.insert(hashes[1], b2)
    assert not cache.insert(hashes[0], b2)   # first writer wins
    assert pool.refcount(b1) == 2            # slot ref + cache ref

    n, ids = cache.lookup(prompt, max_tokens=len(prompt) - 1)
    assert n == 8 and ids == [b1, b2]
    assert pool.refcount(b1) == 3            # lookup increfs for caller
    # max_tokens caps the match at full blocks below the limit: a
    # 5-token prompt may only reuse tokens 0..3 (position 4 must stay
    # writable for the adopting slot's first decode step)
    n, ids = cache.lookup([1, 2, 3, 4, 5], max_tokens=4)
    assert n == 4 and ids == [b1]
    # a diverging prompt matches only up to the divergence
    n, ids = cache.lookup([1, 2, 3, 4, 9, 9, 9, 9, 0], max_tokens=8)
    assert n == 4 and ids == [b1]


def test_prefix_cache_evict_lru_skips_live_blocks():
    bs = 2
    pool = BlockPool(num_blocks=6, block_size=bs)
    cache = PrefixCache(pool)
    h = PrefixCache.chunk_hashes([1, 2, 3, 4, 5, 6], bs)
    blocks = [pool.alloc() for _ in range(3)]
    for hj, bj in zip(h, blocks):
        cache.insert(hj, bj)
    # slots drop their refs on blocks 0 and 2; block 1 stays live
    pool.decref(blocks[0])
    pool.decref(blocks[2])
    assert cache.evictable_count() == 2
    assert cache.evict_lru() == blocks[0]    # oldest evictable first
    assert cache.evict_lru() == blocks[2]    # blocks[1] is protected
    assert cache.evict_lru() is None
    assert len(cache) == 1 and pool.free_count() == 4


# ---------------------------------------------------------------------------
# block-aware admission errors (satellite: GenerationEngine.submit)
# ---------------------------------------------------------------------------

def test_submit_error_names_blocks_needed_vs_available():
    cfg = gpt.gpt_small(vocab_size=16, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=16,
                        dropout=0.0, use_flash=False)
    eng = GenerationEngine(cfg, fluid.Scope(), exe=fluid.Executor(),
                           max_slots=2, max_seq=16, block_size=4)
    assert eng.paged
    # prompt + max_new - 1 = 20 tokens -> 5 blocks > the 4-block table
    with pytest.raises(ValueError) as ei:
        eng.submit(GenerationRequest(list(range(10)), 11))
    msg = str(ei.value)
    assert "5 KV blocks" in msg and "block table holds at most 4" in msg

    # a pool smaller than a request's worst case: the error must name
    # the pool's allocatable capacity, not the table bound
    small = GenerationEngine(cfg, fluid.Scope(), exe=fluid.Executor(),
                             max_slots=2, max_seq=16, block_size=4,
                             kv_pool_blocks=4)   # 3 allocatable
    with pytest.raises(ValueError) as ei:
        small.submit(GenerationRequest(list(range(10)), 7))  # 4 blocks
    msg = str(ei.value)
    assert "4 KV blocks" in msg and "only 3 allocatable blocks" in msg
    assert "free now" in msg

"""Model-level tests (reference pattern: tests/book/ end-to-end tutorials)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.models import lenet, resnet, transformer


def test_mnist_cnn_trains():
    """book/02.recognize_digits (test_recognize_digits.py:65) on synthetic
    digits: loss must drop and fitting a fixed batch must approach zero."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        loss, predict = lenet.convolutional_neural_network(img, label)
        # lr 1e-3: the prob-space cross_entropy (softmax act + CE, the
        # reference book formulation) diverges at 1e-2
        fluid.optimizer.Adam(learning_rate=0.001).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    imgs = rng.randn(16, 1, 28, 28).astype(np.float32)
    lbls = rng.randint(0, 10, (16, 1)).astype(np.int64)
    first = None
    for i in range(50):
        lv, = exe.run(main, feed={"img": imgs, "label": lbls},
                      fetch_list=[loss])
        if first is None:
            first = float(np.asarray(lv))
    last = float(np.asarray(lv))
    assert last < first * 0.2, (first, last)


def test_transformer_trains():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cfg = transformer.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            dropout=0.0)
        loss, feeds = transformer.build_train(cfg, batch=4, seq_len=8,
                                              lr=1e-2)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 64, (4, 8)).astype(np.int64)
    for i in range(40):
        lv, = exe.run(main, feed={"tokens": toks, "labels": toks},
                      fetch_list=[loss])
    assert float(np.asarray(lv)) < 0.5


def test_resnet18_step():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, acc, feeds = resnet.build_train(
            img_shape=(3, 32, 32), class_dim=10, depth=18, lr=0.01)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    img = rng.randn(4, 3, 32, 32).astype(np.float32)
    lbl = rng.randint(0, 10, (4, 1)).astype(np.int64)
    l0 = None
    for _ in range(5):
        lv, = exe.run(main, feed={"image": img, "label": lbl},
                      fetch_list=[loss])
        if l0 is None:
            l0 = float(np.asarray(lv))
    assert np.isfinite(np.asarray(lv)).all()
    assert float(np.asarray(lv)) < l0


def test_clone_for_test_disables_dropout():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.dropout(x, 0.5,
                           dropout_implementation="upscale_in_train")
        out = layers.mean(h)
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.ones((4, 8), np.float32)
    o_test, = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    # upscale_in_train at test time = identity
    np.testing.assert_allclose(float(np.asarray(o_test)), 1.0, rtol=1e-6)


def test_save_load_inference_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, size=2)
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    ref, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    fluid.io.save_inference_model(str(tmp_path), ["x"], [y], exe,
                                  main_program=main)
    prog2, feed_names, fetches = fluid.io.load_inference_model(
        str(tmp_path), exe)
    out, = exe.run(prog2, feed={"x": xv}, fetch_list=fetches)
    np.testing.assert_allclose(ref, out, rtol=1e-6)
    # training state must not leak into the export
    import os
    files = os.listdir(tmp_path)
    assert not any("beta" in f or "moment" in f for f in files), files

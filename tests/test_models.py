"""Model-level tests (reference pattern: tests/book/ end-to-end tutorials)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.models import lenet, resnet, transformer


def test_mnist_cnn_trains():
    """book/02.recognize_digits (test_recognize_digits.py:65) on synthetic
    digits: loss must drop and fitting a fixed batch must approach zero."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        loss, predict = lenet.convolutional_neural_network(img, label)
        # lr 1e-3: the prob-space cross_entropy (softmax act + CE, the
        # reference book formulation) diverges at 1e-2
        fluid.optimizer.Adam(learning_rate=0.001).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    imgs = rng.randn(16, 1, 28, 28).astype(np.float32)
    lbls = rng.randint(0, 10, (16, 1)).astype(np.int64)
    first = None
    for i in range(50):
        lv, = exe.run(main, feed={"img": imgs, "label": lbls},
                      fetch_list=[loss])
        if first is None:
            first = float(np.asarray(lv))
    last = float(np.asarray(lv))
    assert last < first * 0.2, (first, last)


def test_transformer_trains():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cfg = transformer.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            dropout=0.0)
        loss, feeds = transformer.build_train(cfg, batch=4, seq_len=8,
                                              lr=1e-2)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 64, (4, 8)).astype(np.int64)
    for i in range(40):
        lv, = exe.run(main, feed={"tokens": toks, "labels": toks},
                      fetch_list=[loss])
    assert float(np.asarray(lv)) < 0.5


def test_resnet18_step():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, acc, feeds = resnet.build_train(
            img_shape=(3, 32, 32), class_dim=10, depth=18, lr=0.01)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    img = rng.randn(4, 3, 32, 32).astype(np.float32)
    lbl = rng.randint(0, 10, (4, 1)).astype(np.int64)
    l0 = None
    for _ in range(5):
        lv, = exe.run(main, feed={"image": img, "label": lbl},
                      fetch_list=[loss])
        if l0 is None:
            l0 = float(np.asarray(lv))
    assert np.isfinite(np.asarray(lv)).all()
    assert float(np.asarray(lv)) < l0


def test_clone_for_test_disables_dropout():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.dropout(x, 0.5,
                           dropout_implementation="upscale_in_train")
        out = layers.mean(h)
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.ones((4, 8), np.float32)
    o_test, = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    # upscale_in_train at test time = identity
    np.testing.assert_allclose(float(np.asarray(o_test)), 1.0, rtol=1e-6)


def test_save_load_inference_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.fc(x, size=2)
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    ref, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    fluid.io.save_inference_model(str(tmp_path), ["x"], [y], exe,
                                  main_program=main)
    prog2, feed_names, fetches = fluid.io.load_inference_model(
        str(tmp_path), exe)
    out, = exe.run(prog2, feed={"x": xv}, fetch_list=fetches)
    np.testing.assert_allclose(ref, out, rtol=1e-6)
    # training state must not leak into the export
    import os
    files = os.listdir(tmp_path)
    assert not any("beta" in f or "moment" in f for f in files), files


# ---------------------------------------------------------------------------
# Book-parity models (SURVEY.md §4: tests/book/)
# ---------------------------------------------------------------------------

def test_word2vec_trains():
    """book/04: N-gram next-word prediction loss must drop."""
    from paddle_tpu.models import word2vec

    dict_size = 200
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, feed_names = word2vec.build_train(dict_size, lr=0.05)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        # deterministic "corpus": next word = sum of context mod dict
        ctx = rng.randint(0, dict_size, (256, 4)).astype(np.int64)
        nxt = (ctx.sum(axis=1) % dict_size).astype(np.int64)
        losses = []
        for i in range(12):
            sl = slice((i % 4) * 64, (i % 4 + 1) * 64)
            feed = {n: ctx[sl, j:j + 1]
                    for j, n in enumerate(feed_names[:4])}
            feed["nextw"] = nxt[sl, None]
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0], losses


def test_recommender_trains():
    """book/05: tower model on the movielens-shaped corpus."""
    from paddle_tpu.datasets import movielens
    from paddle_tpu.models import recommender

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, scaled, feeds = recommender.build_train(lr=0.05)

    def batch(n=64, seed=0):
        rs = np.random.RandomState(seed)
        samples = [s for _, s in zip(range(n), movielens.train()())]
        f = {
            "user_id": np.asarray([[s[0]] for s in samples], np.int64),
            "gender_id": np.asarray([[s[1]] for s in samples], np.int64),
            "age_id": np.asarray([[s[2]] for s in samples], np.int64),
            "job_id": np.asarray([[s[3]] for s in samples], np.int64),
            "movie_id": np.asarray([[s[4]] for s in samples], np.int64),
            "category_id": np.asarray(
                [(s[5] + [0] * 4)[:4] for s in samples], np.int64),
            "movie_title": np.asarray(
                [(s[6] + [0] * 8)[:8] for s in samples], np.int64),
            "score": np.asarray([[s[7]] for s in samples], np.float32),
        }
        return f

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        feed = batch()
        losses = []
        for _ in range(15):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_seq2seq_translation_trains():
    """book/08: attention seq2seq on the wmt16-shaped corpus."""
    from paddle_tpu.datasets import wmt16
    from paddle_tpu.models import seq2seq

    src_len = trg_len = 12
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, feeds = seq2seq.build_train(
            src_vocab=200, trg_vocab=200, src_len=src_len,
            trg_len=trg_len, hidden=32, emb_dim=32, lr=0.02)

    def pad(ids, ln):
        out = np.zeros((len(ids), ln), np.int64)
        for i, row in enumerate(ids):
            out[i, :min(ln, len(row))] = row[:ln]
        return out

    samples = [s for _, s in zip(range(64),
                                 wmt16.train(200, 200)())]
    feed = {"src_ids": pad([s[0] for s in samples], src_len),
            "trg_in": pad([s[1] for s in samples], trg_len),
            "trg_next": pad([s[2] for s in samples], trg_len)}
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for _ in range(10):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0] * 0.9, losses


def test_se_resnext_step():
    """SE-ResNeXt (tiny config): one train step runs and is finite."""
    from paddle_tpu.models import se_resnext

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        loss, acc = se_resnext.build_train(
            img_shape=(3, 32, 32), class_dim=10,
            layers_per_stage=(1, 1), cardinality=4, base_ch=32, lr=0.01)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        lv, = exe.run(main,
                      feed={"image": rng.randn(4, 3, 32, 32).astype(
                          np.float32),
                          "label": rng.randint(0, 10, (4, 1)).astype(
                              np.int64)},
                      fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()


def test_sentiment_lstm_ragged_trains():
    """Book test understand_sentiment (reference
    tests/book/test_understand_sentiment.py): embedding -> lstm ->
    pooled features -> classifier, driven end to end through the ragged
    LoD feed path — variable-length reviews, no lengths anywhere in the
    model code (program.lod_link threads them through embedding, fc,
    and the lstm to the pools)."""
    from paddle_tpu.data_feeder import DataFeeder

    vocab, emb_d, hid = 64, 16, 16
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        words = layers.data("sent_words", shape=[1], dtype="int64",
                            lod_level=1)
        label = layers.data("sent_label", shape=[1], dtype="int64")
        emb = layers.embedding(words, size=[vocab, emb_d])
        proj = layers.fc(emb, size=hid * 4, num_flatten_dims=2)
        h, c = layers.dynamic_lstm(proj, size=hid * 4)
        feat = layers.concat([layers.sequence_pool(h, "max"),
                              layers.sequence_last_step(h)], axis=1)
        logits = layers.fc(feat, size=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)

        exe = fluid.Executor()
        exe.run(startup)

        rng = np.random.RandomState(7)
        # class-separable toy reviews with ragged lengths 3..11: class 1
        # uses the top half of the vocab
        def batch(n=16):
            rows = []
            for _ in range(n):
                y = rng.randint(0, 2)
                ln = rng.randint(3, 12)
                lo, hi = (vocab // 2, vocab) if y else (0, vocab // 2)
                rows.append((rng.randint(lo, hi, (ln, 1)), [y]))
            return rows

        feeder = DataFeeder(feed_list=[words, label], program=main)
        losses = []
        data = batch(32)
        for _ in range(30):
            lv, = exe.run(main, feed=feeder.feed(data),
                          fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_gpt_causal_lm_trains_and_generates():
    """Decoder-only causal LM: next-token training converges on a
    deterministic sequence, and greedy generation continues it."""
    from paddle_tpu.models import gpt

    vocab, seq = 16, 12
    cfg = gpt.gpt_small(vocab_size=vocab, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=seq,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch=8, seq_len=seq,
                                               lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        # the learnable pattern: token t follows (t + 1) % vocab
        base = np.arange(seq) % vocab
        toks = np.stack([(base + i) % vocab for i in range(8)]) \
            .astype(np.int64)
        losses = []
        for _ in range(60):
            lv, = exe.run(main, feed={"tokens": toks}, fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

        # generation over a for_test clone: parameters shared by
        # construction, dropout off
        infer = main.clone(for_test=True)
        out = gpt.greedy_generate(exe, infer, tokens, logits,
                                  prompt=[0, 1, 2, 3],
                                  max_new_tokens=4, seq_len=seq)
        assert out == [4, 5, 6, 7], out


def test_gpt_kv_cache_decode_matches_full_reforward():
    """Incremental (KV-cache) decoding must generate exactly what the
    O(T^2) full-re-forward path generates from the same trained
    weights."""
    from paddle_tpu.models import gpt

    vocab, seq = 16, 12
    cfg = gpt.gpt_small(vocab_size=vocab, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=seq,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch=4, seq_len=seq,
                                               lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        base = np.arange(seq) % vocab
        toks = np.stack([(base + i) % vocab for i in range(4)]) \
            .astype(np.int64)
        for _ in range(40):
            exe.run(main, feed={"tokens": toks}, fetch_list=[loss])

        infer = main.clone(for_test=True)
        want = gpt.greedy_generate(exe, infer, tokens, logits,
                                   prompt=[0, 1, 2],
                                   max_new_tokens=5, seq_len=seq)

        # decode-step program in a fresh program but the SAME scope:
        # weights shared by name; kv_generate creates the caches (its
        # startup must NOT run — it would re-init the trained weights)
        dec_main, dec_start = fluid.Program(), fluid.Program()
        with fluid.program_guard(dec_main, dec_start):
            tok_var, dec_logits, cache_names = gpt.build_decode_step(
                cfg, batch=1, max_seq=seq)
    got = gpt.kv_generate(exe, scope, dec_main, tok_var, dec_logits,
                          cache_names, prompt=[0, 1, 2],
                          max_new_tokens=5)
    assert got == want, (got, want)


def test_gpt_beam_generate():
    """Beam search over the trained cyclic model: beam=3 must find the
    same (deterministic) continuation greedy does, with a higher-
    is-better score ordering."""
    from paddle_tpu.models import gpt

    vocab, seq = 16, 12
    cfg = gpt.gpt_small(vocab_size=vocab, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq_len=seq,
                        dropout=0.0, use_flash=False)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch=4, seq_len=seq,
                                               lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        base = np.arange(seq) % vocab
        toks = np.stack([(base + i) % vocab for i in range(4)]) \
            .astype(np.int64)
        for _ in range(40):
            exe.run(main, feed={"tokens": toks}, fetch_list=[loss])
        infer = main.clone(for_test=True)
        out = gpt.beam_generate(exe, infer, tokens, logits,
                                prompt=[0, 1, 2], max_new_tokens=4,
                                seq_len=seq, beam_size=3)
        assert out == [3, 4, 5, 6], out


def test_nmt_transformer_trains():
    """Encoder-decoder NMT (BASELINE config 3): loss must drop on a
    learnable copy task (trg = src shifted through BOS)."""
    from paddle_tpu.models import nmt

    vocab = 32
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        cfg = nmt.TransformerConfig(vocab_size=vocab, d_model=32,
                                    n_heads=4, n_layers=2, d_ff=64,
                                    dropout=0.0, use_flash=False)
        loss, feeds = nmt.build_train(cfg, batch=4, src_len=8, trg_len=8,
                                      lr=5e-3, label_smooth_eps=0.0)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        src = rng.randint(2, vocab, (4, 8)).astype(np.int64)
        # BOS=1 prefix; target = copy of source
        trg = np.concatenate([np.ones((4, 1), np.int64), src], axis=1)
        first = last = None
        for _ in range(40):
            lv, = exe.run(main,
                          feed={"src_tokens": src, "trg_tokens": trg},
                          fetch_list=[loss])
            if first is None:
                first = float(np.asarray(lv))
            last = float(np.asarray(lv))
    assert last < first * 0.5, (first, last)


def test_nmt_label_smoothing_loss_floor():
    """With smoothing eps, perfect predictions cannot reach zero loss —
    the smoothed CE floor is eps-dependent; just check the graph builds
    and produces a loss strictly above the hard-label run's floor."""
    from paddle_tpu.models import nmt

    vocab = 32
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        cfg = nmt.TransformerConfig(vocab_size=vocab, d_model=32,
                                    n_heads=4, n_layers=1, d_ff=64,
                                    dropout=0.0, use_flash=False)
        loss, feeds = nmt.build_train(cfg, batch=2, src_len=6, trg_len=6,
                                      lr=5e-3, label_smooth_eps=0.1)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(1)
        src = rng.randint(2, vocab, (2, 6)).astype(np.int64)
        trg = np.concatenate([np.ones((2, 1), np.int64), src], axis=1)
        for _ in range(60):
            lv, = exe.run(main,
                          feed={"src_tokens": src, "trg_tokens": trg},
                          fetch_list=[loss])
    # smoothed CE floor: -(1-eps)ln(1-eps+eps/V) - eps*(V-1)/V*ln(eps/V)
    # ~= 0.38 for eps=.1, V=32; hard-label training would go to ~0
    assert 0.2 < float(np.asarray(lv)) < 2.0


def test_deeplab_trains():
    """DeepLabv3+ (BASELINE config 5): per-pixel CE drops on a fixed
    tiny batch; checks the dilated backbone + ASPP + decoder wiring."""
    from paddle_tpu.models import deeplab

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss, feeds = deeplab.build_train(img_hw=33, batch=2,
                                          n_classes=5, lr=0.01)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        img = rng.randn(2, 3, 33, 33).astype(np.float32)
        # learnable labels: one constant class per image (per-PIXEL random
        # labels are unlearnable through the OS16 bottleneck — the finest
        # decoder resolution is /4)
        lab = np.zeros((2, 33, 33), np.int64)
        lab[1, :, :] = 1
        first = last = None
        for _ in range(15):
            lv, = exe.run(main, feed={"image": img, "label": lab},
                          fetch_list=[loss])
            if first is None:
                first = float(np.asarray(lv))
            last = float(np.asarray(lv))
    assert last < first * 0.8, (first, last)


def test_label_semantic_roles_crf_trains():
    """book/07.label_semantic_roles at toy scale: embeddings ->
    bidirectional LSTM -> CRF loss, decoded with crf_decoding and
    scored with chunk_eval (reference:
    python/paddle/fluid/tests/book/test_label_semantic_roles.py)."""
    from paddle_tpu.framework import ParamAttr

    vocab, n_tags, B, T, hid = 24, 4, 8, 10, 16
    rng = np.random.RandomState(0)
    words = rng.randint(0, vocab, (B, T)).astype(np.int64)
    tags = (words % n_tags).astype(np.int64)  # learnable tag rule

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        w = layers.data("words", shape=[B, T], dtype="int64",
                        append_batch_size=False)
        lab = layers.data("tags", shape=[B, T], dtype="int64",
                          append_batch_size=False)
        emb = layers.embedding(w, size=[vocab, hid])
        proj = layers.fc(emb, size=4 * hid, num_flatten_dims=2)
        fwd, _ = layers.dynamic_lstm(proj, size=4 * hid)
        rev, _ = layers.dynamic_lstm(proj, size=4 * hid, is_reverse=True)
        feat = layers.concat([fwd, rev], axis=2)
        scores = layers.fc(feat, size=n_tags, num_flatten_dims=2)
        crf_attr = ParamAttr(name="crf_w")
        ll = layers.linear_chain_crf(scores, lab, param_attr=crf_attr)
        loss = layers.mean(ll)
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
        decoded = layers.crf_decoding(scores, param_attr=crf_attr)

        exe = fluid.Executor()
        exe.run(startup)
        first = last = None
        for _ in range(30):
            lv, = exe.run(main, feed={"words": words, "tags": tags},
                          fetch_list=[loss])
            if first is None:
                first = float(np.asarray(lv))
            last = float(np.asarray(lv))
        assert last < first * 0.5, (first, last)

        infer = main.clone(for_test=True)
        path, = exe.run(infer, feed={"words": words, "tags": tags},
                        fetch_list=[decoded])
        acc = float((np.asarray(path).reshape(B, T) == tags).mean())
        assert acc > 0.9, acc
